"""Megascale scenario-lab bench: event-batch engine runs at 10^5–10^6
hosts → BENCH_mega.json.

Drives `dragonfly2_tpu.megascale.run_megascale` for one or more
(scenario, hosts) cells and writes the BENCH_rXX-format artifact with
per-run reports plus a summary: pieces/s, per-region time-to-complete
percentiles, origin-traffic fraction, quarantine/failover event counts,
engine step-phase p50s, and peak RSS.

    python bench_megascale.py --quick                 # 10k-host smoke
    python bench_megascale.py --full --artifact BENCH_mega.json
        # the acceptance pair: 100k-host planet (regions + diurnal Zipf
        # + flash crowds) and 100k-host soak (every fault family at once)
    python bench_megascale.py --scenario soak --hosts 1000000 \
        --rounds 30 --artifact BENCH_mega_1m.json     # slow-tier scale
    python bench_megascale.py --fleet --hosts 1000000 --rounds 30
        # the sharded-control-plane scaling pair: the fleet builtin at
        # K=1 and K=4 scheduler replicas (summary cells fleet_<hosts>_r1
        # / fleet_<hosts>_r4 with aggregate pieces/s + handoff counts)

Everything outside each run's `timing` block is deterministic in
(scenario, hosts, seed) — same contract as BENCH_scenarios.json.
"""

from __future__ import annotations

import argparse
import json
import os
import sys

os.environ.setdefault("JAX_PLATFORMS", "cpu")


def summarize(runs: list[dict]) -> dict:
    out = {}
    for r in runs:
        key = f"{r['scenario']}_{r['hosts']}"
        if r.get("fleet"):
            # sharded-control-plane rounds: one cell per replica count so
            # benchwatch compares K=1 and K=4 each against their own lineage
            key = f"{key}_r{r['fleet']['replicas']}"
        total = (r.get("origin_bytes") or 0) + (r.get("p2p_bytes") or 0)
        out[key] = {
            "pieces_per_sec": r["timing"]["pieces_per_sec"],
            "wall_s": r["timing"]["wall_s"],
            "setup_s": r["timing"]["setup_s"],
            "peak_rss_mb": r["timing"]["peak_rss_mb"],
            "completed": r["stats"]["completed"],
            "pieces": r["stats"]["pieces"],
            "origin_traffic_fraction": r.get("origin_traffic_fraction"),
            "origin_gib": round(total and (r["origin_bytes"] / (1 << 30)), 2),
            "ttc_ms_p50_by_region": {
                name: v["ttc_ms_p50"] for name, v in r["regions"].items()
            },
            "fault_families": r["fault_families"],
            "quarantine": r["quarantine"],
            "failover": r["failover"],
            # timeline-measured scheduler-kill recovery (megascale/soak):
            # dip + simulated-minutes-to-recover per kill, not an
            # end-of-run assertion
            "kill_recovery": _kill_recovery_summary(r.get("recovery", [])),
            # decision provenance: applied selections + shadow-arm
            # divergence/regret (None when no inactive arm ran). The
            # disagreement/rank-corr cells have no monotonic "better"
            # and are excluded from benchwatch's regression directions.
            "decisions": (r.get("decisions") or {}).get("decisions"),
            "decision_top1_disagreement": (
                (r.get("decisions") or {}).get("top1_disagreement")
            ),
            "decision_regret_fail_rate": (
                (r.get("decisions") or {}).get("regret_fail_rate")
            ),
            # SLO verdict plane (telemetry/slo.py): alert counts and
            # worst-case budget burn are lower-is-better benchwatch
            # cells; the verdict state is a category, direction-exempt.
            "slo_pages_fired": (r.get("slo") or {}).get("pages_fired"),
            "slo_tickets_fired": (r.get("slo") or {}).get("tickets_fired"),
            "slo_alerts_fired": (r.get("slo") or {}).get("alerts_fired"),
            "slo_budget_burn": (r.get("slo") or {}).get("budget_burn"),
            "slo_verdict_state": (
                (r.get("slo") or {}).get("verdict_code_final")
            ),
            # tail plane (telemetry/tailtrace.py): worst-region TTC p99
            # is the lower-is-better benchwatch cell; the decomposition
            # ratio (consistency audit, perfect = 1.0) and the dominant
            # failover share are direction-exempt context.
            "tail_ttc_p99_ms": _tail_worst_p99(r.get("tail")),
            "tail_decomp_ratio": _tail_worst_ratio(r.get("tail")),
            "tail_failover_phase_share": _tail_failover_share(r.get("tail")),
        }
        if r.get("fleet"):
            # fleet plane (megascale/fleet.py): aggregate pieces/s —
            # pieces over the busiest shard's scheduler-compute seconds,
            # the fleet's control-plane capacity — is the 1-vs-K scaling
            # cell (higher-is-better in benchwatch); handoff counts
            # track ring churn under the fault schedule and are
            # direction-exempt context.
            out[key]["aggregate_pieces_per_sec"] = (
                r["timing"]["fleet"]["aggregate_pieces_per_sec"]
            )
            out[key]["fleet_handoffs"] = r["fleet"]["handoffs_total"]
    return out


def _tail_worst_p99(tail: dict | None) -> float | None:
    p99s = [
        (reg.get("ttc_ms") or {}).get("p99")
        for reg in (tail or {}).get("regions", {}).values()
    ]
    p99s = [p for p in p99s if p is not None]
    return max(p99s) if p99s else None


def _tail_worst_ratio(tail: dict | None) -> float | None:
    ratios = [
        reg.get("decomp_ratio")
        for reg in (tail or {}).get("regions", {}).values()
        if reg.get("decomp_ratio") is not None
    ]
    # "worst" = farthest from the perfect 1.0
    return max(ratios, key=lambda x: abs(x - 1.0)) if ratios else None


def _tail_failover_share(tail: dict | None) -> float | None:
    shares = [
        (reg.get("phase_share") or {}).get("failover", 0.0)
        for reg in (tail or {}).get("regions", {}).values()
    ]
    return max(shares) if shares else None


def _kill_recovery_summary(recovery: list[dict]) -> dict:
    recovered = [e for e in recovery if e.get("recovered")]
    minutes = [e["recovery_sim_minutes"] for e in recovered
               if e.get("recovery_sim_minutes") is not None]
    return {
        "kills": len(recovery),
        "recovered": len(recovered),
        "max_recovery_sim_minutes": max(minutes) if minutes else None,
        "min_dip_ratio": min(
            (e["dip_ratio"] for e in recovery if e.get("dip_ratio") is not None),
            default=None,
        ),
    }


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--scenario", default="planet",
                    help="megascale builtin (planet|soak) or any scenario builtin")
    ap.add_argument("--hosts", type=int, default=100_000)
    ap.add_argument("--tasks", type=int, default=128)
    ap.add_argument("--seed", type=int, default=11)
    ap.add_argument("--rounds", type=int, default=None,
                    help="engine rounds (default: one compressed day + drain)")
    ap.add_argument("--arrivals", type=int, default=None,
                    help="arrival wave size per round (default ~1.5x hosts/day)")
    ap.add_argument("--algorithm", default="default")
    ap.add_argument("--retire", type=int, default=24,
                    help="retire completed downloads after this many rounds")
    ap.add_argument("--max-peers-per-task", type=int, default=None,
                    help="per-task peer cap (default: auto from arrivals, "
                         "clamped at 8192 — a hot swarm past the cap spills "
                         "its overflow to origin)")
    ap.add_argument("--quick", action="store_true",
                    help="10k-host smoke configuration")
    ap.add_argument("--full", action="store_true",
                    help="the acceptance pair: 100k planet + 100k soak")
    ap.add_argument("--fleet", action="store_true",
                    help="the scaling pair: fleet builtin at 1 and 4 "
                         "scheduler replicas")
    ap.add_argument("--replicas", type=int, default=None,
                    help="scheduler replicas for a single fleet cell "
                         "(default: no fleet, one scheduler)")
    ap.add_argument("--artifact", default=None,
                    help="write BENCH_mega.json-format artifact here")
    args = ap.parse_args()

    from dragonfly2_tpu.megascale.soak import run_megascale

    cells: list[tuple[str, int, int | None]] = []
    if args.full:
        cells = [("planet", args.hosts, None), ("soak", args.hosts, None)]
    elif args.fleet:
        cells = [("fleet", args.hosts, 1), ("fleet", args.hosts, 4)]
    else:
        if args.quick:
            args.hosts = 10_000
        cells = [(args.scenario, args.hosts, args.replicas)]

    runs = []
    for scenario, hosts, replicas in cells:
        report = run_megascale(
            scenario=scenario, num_hosts=hosts, num_tasks=args.tasks,
            seed=args.seed, rounds=args.rounds,
            arrivals_per_round=args.arrivals, algorithm=args.algorithm,
            retire_after_rounds=args.retire, fleet_replicas=replicas,
            max_peers_per_task=args.max_peers_per_task,
        )
        runs.append(report)
        line = {
            "scenario": scenario, "hosts": hosts,
            "pieces_per_sec": report["timing"]["pieces_per_sec"],
            "wall_s": report["timing"]["wall_s"],
            "origin_traffic_fraction": report["origin_traffic_fraction"],
        }
        if replicas is not None:
            line["replicas"] = replicas
            line["aggregate_pieces_per_sec"] = (
                report["timing"]["fleet"]["aggregate_pieces_per_sec"]
            )
        print(json.dumps(line))

    summary = summarize(runs)
    print("bench_megascale_summary " + json.dumps(summary))
    if args.artifact:
        # the shared schema writer (tools/bench_schema.py): one artifact
        # contract + platform block across every bench driver
        from tools.bench_schema import write_artifact

        write_artifact(
            args.artifact, ["python", "bench_megascale.py"] + sys.argv[1:],
            summary, runs=runs,
        )
    return 0


if __name__ == "__main__":
    sys.exit(main())
