"""Consistent-hash ring for task -> scheduler affinity.

Capability parity with pkg/balancer/consistent_hashing.go:40-57 + the
dynconfig-fed resolver (pkg/resolver/): every request for a given task id
must land on the same scheduler so its in-memory DAG/state is authoritative.
Implemented as a sorted ring of virtual-node hashes.
"""

from __future__ import annotations

import bisect
import hashlib


def _hash(key: str) -> int:
    return int.from_bytes(hashlib.sha256(key.encode("utf-8")).digest()[:8], "big")


class HashRing:
    def __init__(self, nodes: list[str] | None = None, replicas: int = 64):
        self._replicas = replicas
        self._ring: list[int] = []
        self._members: dict[int, str] = {}
        self._nodes: set[str] = set()
        for node in nodes or []:
            self.add(node)

    def add(self, node: str) -> None:
        if node in self._nodes:
            return
        self._nodes.add(node)
        for i in range(self._replicas):
            h = _hash(f"{node}#{i}")
            idx = bisect.bisect(self._ring, h)
            self._ring.insert(idx, h)
            self._members[h] = node

    def remove(self, node: str) -> None:
        if node not in self._nodes:
            return
        self._nodes.remove(node)
        for i in range(self._replicas):
            h = _hash(f"{node}#{i}")
            idx = bisect.bisect_left(self._ring, h)
            if idx < len(self._ring) and self._ring[idx] == h:
                self._ring.pop(idx)
                self._members.pop(h, None)

    def pick(self, key: str) -> str | None:
        """Pick the node owning `key` (e.g. a task id)."""
        if not self._ring:
            return None
        h = _hash(key)
        idx = bisect.bisect(self._ring, h) % len(self._ring)
        return self._members[self._ring[idx]]

    def nodes(self) -> set[str]:
        return set(self._nodes)

    def __len__(self) -> int:
        return len(self._nodes)
