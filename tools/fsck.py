"""Offline integrity scan of a daemon's task storage (dfstore fsck).

Walks every task directory under a daemon data dir (the layout
`client/storage.py` writes: `<dir>/<task_id>/{data, metadata.json,
pieces.jsonl}`), re-hashes each recorded piece's bytes against its
committed md5, and — for completed tasks — the whole file against the
recorded task sha256. Exit status is the contract: 0 = every digest
matched, 1 = at least one mismatch/hole, 2 = nothing scannable.

This is the OFFLINE leg of the trust-boundary integrity chain: the
scheduler attests digests in-band (children verify before commit) and the
upload server verifies on serve; fsck catches rot that happened while a
daemon was down, before the task is ever advertised again.

Usage:
    python -m tools.fsck <data_dir> [--json]
"""

from __future__ import annotations

import argparse
import dataclasses
import json
import pathlib
import sys

from dragonfly2_tpu.client.storage import TaskStorage
from dragonfly2_tpu.utils.digest import md5_from_bytes


@dataclasses.dataclass
class Finding:
    task_id: str
    kind: str       # piece_digest | task_digest | short_data | unreadable
    detail: str
    piece: int = -1


def _scan_task(ts: TaskStorage) -> list[Finding]:
    findings: list[Finding] = []
    task_id = ts.meta.task_id
    try:
        # seek+read one piece at a time: a store can hold multi-GiB tasks
        # and fsck must not allocate a whole data file per task
        with open(ts.data_path, "rb") as f:
            for number in sorted(ts.meta.pieces):
                piece = ts.meta.pieces[number]
                f.seek(piece.offset)
                chunk = f.read(piece.length)
                if len(chunk) != piece.length:
                    findings.append(Finding(
                        task_id, "short_data",
                        f"piece {number}: data file holds {len(chunk)} of "
                        f"{piece.length} bytes", number,
                    ))
                    continue
                if piece.digest and md5_from_bytes(chunk) != piece.digest:
                    findings.append(Finding(
                        task_id, "piece_digest",
                        f"piece {number}: md5 mismatch vs recorded digest",
                        number,
                    ))
    except OSError as e:
        return [Finding(task_id, "unreadable", f"data file: {e}")]
    if ts.meta.done and ts.meta.digest and ts.meta.content_length >= 0:
        actual = ts.compute_digest()
        if actual != ts.meta.digest:
            findings.append(Finding(
                task_id, "task_digest",
                f"whole-task sha256 {actual} != recorded {ts.meta.digest}",
            ))
    return findings


def scan(data_dir: str | pathlib.Path) -> tuple[int, list[Finding]]:
    """(tasks_scanned, findings) over every task directory in `data_dir`."""
    base = pathlib.Path(data_dir)
    scanned = 0
    findings: list[Finding] = []
    for task_dir in sorted(p for p in base.iterdir() if p.is_dir()):
        if not (task_dir / "metadata.json").exists():
            continue
        ts = TaskStorage.load(base, task_dir)
        if ts is None:
            findings.append(Finding(task_dir.name, "unreadable",
                                    "metadata failed to load"))
            continue
        scanned += 1
        findings.extend(_scan_task(ts))
    return scanned, findings


def main(argv: list[str] | None = None) -> int:
    parser = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    parser.add_argument("data_dir", help="daemon storage directory")
    parser.add_argument("--json", action="store_true", dest="as_json",
                        help="machine-readable findings on stdout")
    args = parser.parse_args(argv)
    if not pathlib.Path(args.data_dir).is_dir():
        print(f"fsck: {args.data_dir}: not a directory", file=sys.stderr)
        return 2
    scanned, findings = scan(args.data_dir)
    if args.as_json:
        print(json.dumps({
            "tasks_scanned": scanned,
            "findings": [dataclasses.asdict(f) for f in findings],
        }, indent=2))
    else:
        for f in findings:
            print(f"BAD  {f.task_id} [{f.kind}] {f.detail}")
        print(f"fsck: {scanned} task(s) scanned, {len(findings)} finding(s)")
    if scanned == 0:
        print(f"fsck: no tasks under {args.data_dir}", file=sys.stderr)
        return 2
    return 1 if findings else 0


if __name__ == "__main__":
    sys.exit(main())
