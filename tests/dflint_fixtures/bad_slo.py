"""dflint red fixture: DET002 (wall-clock read on an SLO replay
evaluation path) + DET003 (set-ordered iteration over firing alerts) —
in a file the test configures as a decision module, the way
telemetry/slo.py is in the real DET domain."""

import time


class BadSLOEngine:
    def __init__(self):
        self.firing = set()

    def step(self, good, bad):
        # stamping the evaluation off the wall clock makes the alert
        # timeline depend on machine load, not the replay
        t = time.time()  # <- DET002
        return {"t": t, "good": good, "bad": bad}

    def causes(self):
        out = []
        for name in self.firing:  # <- DET003 (alert order differs per process)
            out.append({"slo": name})
        return out
