"""Concurrency hammer — the race-detector analog for the scheduler
service (SURVEY §5 race safety; the reference leans on Go's -race in CI).

Many threads drive the full message surface of ONE SchedulerService under
its RPC-edge lock (exactly how rpc/server.py dispatches: every mutation
under service.mu) while another thread runs tick() + run_gc() in a loop.
Afterwards the service must be INTERNALLY CONSISTENT — no exception ever
escaped, every live peer's state is a legal FSM value, the SoA free lists
agree with the id maps, and host-side dicts hold no entries for reclaimed
peers. Any forgotten lock or dict/array divergence shows up as a torn
invariant within a few thousand operations."""

import threading

import numpy as np

from dragonfly2_tpu.cluster import messages as msg
from dragonfly2_tpu.cluster.scheduler import SchedulerService
from dragonfly2_tpu.config.config import Config
from dragonfly2_tpu.state.fsm import PeerState
from tools.dflint.lockorder import assert_clean, guard_attributes, instrument_locks


def _host(i: int) -> msg.HostInfo:
    return msg.HostInfo(host_id=f"ch-{i}", hostname=f"ch-{i}", ip=f"10.3.0.{i % 250}")


def _harnessed(svc: SchedulerService):
    """Activate the runtime lock-order harness (tools/dflint/lockorder)
    on one service: track the service lock, the piece-buffer lock and
    the quarantine board's lock for acquisition-order cycles, and guard
    the attributes whose static contract (dflint LOCK001 / under[mu])
    says they are only written under a specific lock."""
    graph = instrument_locks(svc, {
        "mu": "scheduler.mu",
        "_piece_buf_mu": "scheduler.piece_buf_mu",
    })
    instrument_locks(svc.quarantine, {"_mu": "quarantine.mu"}, graph)
    guard_attributes(svc, {
        # mu-guarded serving sideband + seed round-robin (LOCK001 set).
        # NOT guarded: seed_triggers — the storm only ever .append()s it
        # (a method call the __setattr__ guard cannot see); its one
        # REBIND is rpc/server.py's drain swap, outside this in-proc
        # storm, so a guard entry here would be inert coverage theater.
        "_serving_full_sync": "mu",
        "_seed_rr": "mu",
        # the buffer reference itself may only be swapped under its lock
        "_piece_buf": "_piece_buf_mu",
    }, graph)
    return graph


def test_concurrent_message_storm_keeps_service_consistent():
    cfg = Config()
    cfg.scheduler.max_hosts = 256
    cfg.scheduler.max_tasks = 128
    svc = SchedulerService(config=cfg)
    lock_graph = _harnessed(svc)
    svc.announce_host(msg.HostInfo(host_id="seed", hostname="seed", ip="10.3.1.1",
                                   host_type="super"))
    errors: list[BaseException] = []
    stop = threading.Event()
    n_workers, ops_per_worker = 8, 400

    def worker(wid: int) -> None:
        rng = np.random.default_rng(wid)
        my_peers: list[tuple[str, str]] = []
        try:
            for op in range(ops_per_worker):
                with svc.mu:
                    roll = rng.random()
                    if roll < 0.35 or not my_peers:
                        pid = f"p-{wid}-{op}"
                        task = f"t-{int(rng.integers(0, 24))}"
                        svc.register_peer(msg.RegisterPeerRequest(
                            peer_id=pid, task_id=task,
                            host=_host(int(rng.integers(0, 40))),
                            url=f"https://o.example/{task}",
                            content_length=16 << 20,
                        ))
                        my_peers.append((pid, task))
                    elif roll < 0.6:
                        pid, _ = my_peers[int(rng.integers(len(my_peers)))]
                        svc.handle(msg.DownloadPieceFinishedRequest(
                            peer_id=pid, piece_number=int(rng.integers(0, 8)),
                            length=1 << 20, cost_ns=int(rng.integers(1, 9)) * 1_000_000,
                        ))
                    elif roll < 0.75:
                        pid, _ = my_peers[int(rng.integers(len(my_peers)))]
                        # may be protocol-illegal for the current state —
                        # must answer ScheduleFailure, never corrupt/raise
                        svc.handle(msg.DownloadPeerFinishedRequest(peer_id=pid))
                    elif roll < 0.85:
                        pid, _ = my_peers[int(rng.integers(len(my_peers)))]
                        svc.handle(msg.DownloadPeerBackToSourceStartedRequest(peer_id=pid))
                        svc.handle(msg.DownloadPeerBackToSourceFinishedRequest(
                            peer_id=pid, piece_count=4,
                        ))
                    else:
                        pid, _ = my_peers.pop(int(rng.integers(len(my_peers))))
                        svc.leave_peer(pid)
        except BaseException as e:  # noqa: BLE001 - recorded for the assert
            errors.append(e)

    def ticker() -> None:
        try:
            while not stop.is_set():
                with svc.mu:
                    svc.tick()
                svc.run_gc(force=True)
        except BaseException as e:  # noqa: BLE001
            errors.append(e)

    threads = [threading.Thread(target=worker, args=(w,)) for w in range(n_workers)]
    t_tick = threading.Thread(target=ticker)
    t_tick.start()
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=120)
        assert not t.is_alive(), "worker wedged — scheduler starved or deadlocked"
    stop.set()
    t_tick.join(timeout=30)
    assert not t_tick.is_alive(), "ticker wedged"

    assert not errors, errors[:3]

    # ---- internal consistency under the lock ----
    with svc.mu:
        st = svc.state
        legal = {int(s) for s in PeerState}
        alive_idx = np.nonzero(st.peer_alive)[0]
        for idx in alive_idx:
            pid = st._peer_id[idx]
            assert pid is not None, f"alive slot {idx} has no id"
            assert st.peer_index(pid) == idx, "id map diverged from SoA"
            assert int(st.peer_state[idx]) in legal
            assert pid in svc._peer_meta, f"alive peer {pid} lost its meta"
        # no host-side entries for reclaimed peers
        for pid in svc._peer_meta:
            assert st.peer_index(pid) is not None, f"meta for dead peer {pid}"
        for pid in svc._pending:
            assert st.peer_index(pid) is not None, f"pending dead peer {pid}"
        # free-list accounting matches the alive mask
        counts = st.counts()
        assert counts["peers"] == len(alive_idx)
        # upload accounting can never be negative
        assert (st.host_upload_used[: st.max_hosts] >= 0).all()

    # ---- runtime lock-order harness verdict ----
    # the storm exercised every lock pair (mu -> piece_buf_mu,
    # mu -> quarantine.mu) across 9+ threads: the cross-thread
    # acquisition graph must be acyclic (deadlock potential) and every
    # guarded attribute write must have held its owning lock — the
    # dynamic check of the static under[mu]/LOCK001 contracts
    assert_clean(lock_graph)
    assert ("scheduler.mu", "scheduler.piece_buf_mu") in lock_graph.edges, (
        "storm never exercised the mu -> piece_buf_mu nesting the "
        "harness exists to watch — did the report path change?"
    )
