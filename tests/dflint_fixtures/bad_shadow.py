"""dflint red fixture: an IN-TICK shadow-scoring D2H trips JIT003.

The counterfactual shadow arm's packed selections may come back to the
host ONLY at the end-of-tick drain valve (`_drain_shadow`, allowlisted in
tools/dflint/passes/jit_hygiene.D2H_ALLOWLIST). This fixture's `tick`
reads the shadow result back BETWEEN chunks — exactly the sync that
would re-serialize the pipelined tick — and must fail JIT003; the
`_drain_shadow` read is allowlisted by the test's config and stays
silent.
"""

import numpy as np


def tick(chunks, shadow_entry):
    results = []
    for buf, bsz in chunks:
        shadow_packed = shadow_entry(buf.copy(), bsz)
        # <- JIT003: in-tick shadow D2H (not the allowlisted drain valve)
        results.append(np.asarray(shadow_packed))
    return results


def _drain_shadow(inflight):
    out = []
    for _s, _e, packed in inflight:
        out.append(np.asarray(packed))  # allowlisted end-of-tick drain
    return out
