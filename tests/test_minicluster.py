"""Mini-cluster e2e: scheduler + daemons over real localhost sockets.

The reference's kind-cluster e2e tier (SURVEY.md §4) in-process: a file
server with a request counter stands in for the origin, a
SchedulerRPCServer serves the batched evaluator, and Daemons play dfget.
Asserts the actual P2P property: the first peer back-sources, later peers
pull pieces from it (origin GET count does not grow), and bytes match
end to end.
"""

import asyncio
import hashlib
import http.server
import threading

import pytest

from dragonfly2_tpu.client.daemon import Daemon
from dragonfly2_tpu.cluster.scheduler import SchedulerService
from dragonfly2_tpu.cluster.probes import ProbeStore
from dragonfly2_tpu.config.config import Config
from dragonfly2_tpu.records.storage import TraceStorage
from dragonfly2_tpu.rpc.server import SchedulerRPCServer


class _CountingFileServer:
    """Origin server: GET/HEAD for one blob, counting data requests."""

    def __init__(self, payload: bytes):
        self.payload = payload
        self.get_count = 0
        outer = self

        class Handler(http.server.BaseHTTPRequestHandler):
            protocol_version = "HTTP/1.1"

            def log_message(self, *args):
                pass

            def do_HEAD(self):
                self.send_response(200)
                self.send_header("Content-Length", str(len(outer.payload)))
                self.end_headers()

            def do_GET(self):
                outer.get_count += 1
                data = outer.payload
                range_header = self.headers.get("Range")
                status = 200
                if range_header and range_header.startswith("bytes="):
                    spec = range_header[len("bytes=") :].split("-")
                    start = int(spec[0]) if spec[0] else 0
                    end = int(spec[1]) if len(spec) > 1 and spec[1] else len(data) - 1
                    data = data[start : end + 1]
                    status = 206
                self.send_response(status)
                self.send_header("Content-Length", str(len(data)))
                self.end_headers()
                self.wfile.write(data)

        self._server = http.server.ThreadingHTTPServer(("127.0.0.1", 0), Handler)
        self.port = self._server.server_address[1]
        threading.Thread(target=self._server.serve_forever, daemon=True).start()

    def url(self) -> str:
        return f"http://127.0.0.1:{self.port}/blob.bin"

    def stop(self):
        self._server.shutdown()
        self._server.server_close()


@pytest.fixture
def origin():
    server = _CountingFileServer(bytes(i % 256 for i in range(300_000)))
    yield server
    server.stop()


def _scheduler_service(tmp_path) -> SchedulerService:
    cfg = Config()
    cfg.scheduler.max_hosts = 64
    cfg.scheduler.max_tasks = 64
    return SchedulerService(
        config=cfg,
        storage=TraceStorage(tmp_path / "traces"),
        probes=ProbeStore(max_pairs=4096, max_hosts=64),
    )


def test_p2p_distribution(tmp_path, origin):
    async def run():
        service = _scheduler_service(tmp_path)
        server = SchedulerRPCServer(service, tick_interval=0.01)
        host, port = await server.start()

        sha = hashlib.sha256(origin.payload).hexdigest()
        daemons = []
        try:
            # Peer 1: nothing in the mesh yet -> back-to-source.
            d1 = Daemon(tmp_path / "d1", [(host, port)], hostname="host-1")
            await d1.start()
            daemons.append(d1)
            ts1 = await d1.download(origin.url(), piece_length=32 * 1024)
            with open(ts1.data_path, "rb") as f:
                assert hashlib.sha256(f.read()).hexdigest() == sha
            source_gets = origin.get_count
            assert source_gets > 0

            # Peers 2..3: scheduler must hand them peer 1 (then each other)
            # as parents; origin must see no further data requests.
            for i in (2, 3):
                d = Daemon(tmp_path / f"d{i}", [(host, port)], hostname=f"host-{i}")
                await d.start()
                daemons.append(d)
                ts = await d.download(
                    origin.url(), piece_length=32 * 1024, back_source_allowed=False
                )
                with open(ts.data_path, "rb") as f:
                    assert hashlib.sha256(f.read()).hexdigest() == sha
            assert origin.get_count == source_gets, "P2P peers hit the origin"

            # Scheduler recorded the downloads as training traces.
            assert service.storage.list_downloads(), "no Download trace rows"
            counts = service.counts()
            assert counts["hosts"] == 3 and counts["tasks"] == 1
        finally:
            for d in daemons:
                await d.stop()
            await server.stop()

    asyncio.run(run())


def test_tiny_and_small_size_scopes_end_to_end(tmp_path):
    """TINY (<=128 B) and SMALL (<= one piece) files through the REAL
    daemon + scheduler path (the conductor's size-scope handling,
    peertask_conductor.go + handleRegisterPeerRequest fast paths): exact
    bytes, single-piece metadata, and P2P reuse for a second peer."""

    async def run():
        for payload, piece_length, label in (
            (b"tiny!" * 20, 4 << 20, "tiny"),        # 100 B -> TINY
            (bytes(range(256)) * 12, 4096, "small"),  # 3 KiB <= 4 KiB piece
        ):
            origin = _CountingFileServer(payload)
            service = _scheduler_service(tmp_path / label)
            server = SchedulerRPCServer(service, tick_interval=0.01)
            host, port = await server.start()
            sha = hashlib.sha256(payload).hexdigest()
            daemons = []
            try:
                d1 = Daemon(tmp_path / f"{label}-1", [(host, port)], hostname=f"{label}-1")
                await d1.start()
                daemons.append(d1)
                ts1 = await d1.download(origin.url(), piece_length=piece_length)
                with open(ts1.data_path, "rb") as f:
                    assert hashlib.sha256(f.read()).hexdigest() == sha, label
                assert len(ts1.meta.pieces) == 1, (label, ts1.meta.pieces)
                gets = origin.get_count

                d2 = Daemon(tmp_path / f"{label}-2", [(host, port)], hostname=f"{label}-2")
                await d2.start()
                daemons.append(d2)
                ts2 = await d2.download(
                    origin.url(), piece_length=piece_length, back_source_allowed=False
                )
                with open(ts2.data_path, "rb") as f:
                    assert hashlib.sha256(f.read()).hexdigest() == sha, label
                assert origin.get_count == gets, f"{label}: second peer hit origin"
            finally:
                for d in daemons:
                    await d.stop()
                await server.stop()
                origin.stop()

    asyncio.run(run())


def test_child_recovers_when_parent_vanishes(tmp_path, origin):
    """Failure recovery through the conductor's full retry chain
    (peertask_conductor.go error path): the scheduled parent crashed
    without LeavePeer, so the child's piece fetches fail at the socket,
    the failed parent is blocklisted via piece-result reporting, and the
    scheduler's retry loop escalates the child to back-to-source — bytes
    still exact, origin hit again."""

    async def run():
        service = _scheduler_service(tmp_path)
        server = SchedulerRPCServer(service, tick_interval=0.01)
        host, port = await server.start()
        sha = hashlib.sha256(origin.payload).hexdigest()
        try:
            d1 = Daemon(tmp_path / "d1", [(host, port)], hostname="host-1")
            await d1.start()
            await d1.download(origin.url(), piece_length=64 * 1024)
            # crash, not leave: the scheduler still believes the peer is a
            # viable SUCCEEDED parent
            await d1.stop(leave=False)

            gets_before = origin.get_count
            d2 = Daemon(tmp_path / "d2", [(host, port)], hostname="host-2")
            await d2.start()
            try:
                ts2 = await d2.download(origin.url(), piece_length=64 * 1024)
                with open(ts2.data_path, "rb") as f:
                    assert hashlib.sha256(f.read()).hexdigest() == sha
                assert origin.get_count > gets_before, (
                    "child never fell back to the origin"
                )
            finally:
                await d2.stop()
        finally:
            await server.stop()

    asyncio.run(run())


def test_child_rejects_corrupt_parent_piece(tmp_path, origin):
    """Digest enforcement end-to-end (pieceManager digest check): the
    parent's on-disk data is corrupted AFTER download (bit rot), so it
    serves wrong bytes under the original piece digest; the child's
    write_piece verification rejects them and the download still
    completes exactly via recovery."""

    async def run():
        service = _scheduler_service(tmp_path)
        server = SchedulerRPCServer(service, tick_interval=0.01)
        host, port = await server.start()
        sha = hashlib.sha256(origin.payload).hexdigest()
        daemons = []
        try:
            d1 = Daemon(tmp_path / "d1", [(host, port)], hostname="host-1")
            await d1.start()
            daemons.append(d1)
            ts1 = await d1.download(origin.url(), piece_length=64 * 1024)
            # flip bytes inside piece 1 on disk; metadata digests keep the
            # ORIGINAL values, so the upload server now serves provably
            # corrupt bytes
            with open(ts1.data_path, "r+b") as f:
                f.seek(64 * 1024 + 100)
                f.write(b"\xff\x00\xff\x00garbage")

            gets_before = origin.get_count
            d2 = Daemon(tmp_path / "d2", [(host, port)], hostname="host-2")
            await d2.start()
            daemons.append(d2)
            ts2 = await d2.download(origin.url(), piece_length=64 * 1024)
            with open(ts2.data_path, "rb") as f:
                assert hashlib.sha256(f.read()).hexdigest() == sha, (
                    "corrupt parent bytes reached the child's store"
                )
            # the rejection actually happened: the child REPORTED the
            # failed piece (parent-host failure accounting moved) and had
            # to re-fetch from the origin — with an honest parent the
            # sibling P2P test proves the origin sees zero extra GETs
            assert origin.get_count > gets_before, (
                "digest rejection never forced an origin re-fetch"
            )
            assert int(service.state.host_upload_failed.sum()) >= 1, (
                "piece failure was never reported to the scheduler"
            )
        finally:
            for d in daemons:
                await d.stop()
            await server.stop()

    asyncio.run(run())


def test_daemon_survives_scheduler_restart(tmp_path, origin):
    """The daemon's pooled announce connection dies when its scheduler
    restarts; the pool must evict the dead connection, redial, and
    RE-ANNOUNCE on the new connection (announced-ness is per connection,
    not per address) so the next download just works — the resilience the
    reference gets from gRPC channel reconnects. Without the eviction the
    daemon was permanently broken after any scheduler restart."""

    async def run():
        service = _scheduler_service(tmp_path)
        server = SchedulerRPCServer(service, tick_interval=0.01)
        host, port = await server.start()
        sha = hashlib.sha256(origin.payload).hexdigest()
        d = Daemon(tmp_path / "d", [(host, port)], hostname="restart-peer")
        server2 = None
        try:
            await d.start()
            ts1 = await d.download(origin.url(), piece_length=64 * 1024)
            with open(ts1.data_path, "rb") as f:
                assert hashlib.sha256(f.read()).hexdigest() == sha

            await server.stop()  # scheduler crashes/restarts, same port
            server2 = SchedulerRPCServer(
                _scheduler_service(tmp_path / "s2"), host=host, port=port,
                tick_interval=0.01,
            )
            await server2.start()

            payload2 = bytes(reversed(origin.payload))
            origin2 = _CountingFileServer(payload2)
            try:
                ts2 = await asyncio.wait_for(
                    d.download(origin2.url(), piece_length=64 * 1024), 40
                )
                with open(ts2.data_path, "rb") as f:
                    got = hashlib.sha256(f.read()).hexdigest()
                assert got == hashlib.sha256(payload2).hexdigest()
                # the fresh scheduler really was re-announced + re-registered
                assert server2.service.counts()["hosts"] >= 1
            finally:
                origin2.stop()
        finally:
            await d.stop()
            if server2 is not None:
                await server2.stop()

    asyncio.run(run())


def test_probe_cycle_over_rpc(tmp_path, origin):
    async def run():
        service = _scheduler_service(tmp_path)
        server = SchedulerRPCServer(service, tick_interval=0.01)
        host, port = await server.start()
        daemons = []
        try:
            for i in range(3):
                d = Daemon(tmp_path / f"pd{i}", [(host, port)], hostname=f"probe-{i}")
                await d.start()
                daemons.append(d)
                conn = await d.pool.for_task(d.host_id)
                await d._ensure_announced(conn)
            # each daemon runs one probe cycle against the others
            probed = 0
            for d in daemons:
                probed += await d.sync_probes_once(count=2)
            assert probed > 0
            # the probe store now holds RTTs the evaluator can gather
            avg = service.probes.average_rtt(
                service.state.host_index(daemons[0].host_id),
                service.state.host_index(daemons[1].host_id),
            )
            assert avg is None or avg > 0  # pair order depends on sampling
            total_pairs = service.probes._next
            assert total_pairs > 0
        finally:
            for d in daemons:
                await d.stop(leave=False)
            await server.stop()

    asyncio.run(run())


def test_empty_task_fast_path(tmp_path):
    async def run():
        service = _scheduler_service(tmp_path)
        server = SchedulerRPCServer(service, tick_interval=0.01)
        host, port = await server.start()
        empty = tmp_path / "empty.bin"
        empty.write_bytes(b"")
        try:
            d = Daemon(tmp_path / "de", [(host, port)], hostname="host-e")
            await d.start()
            ts = await d.download(f"file://{empty}")
            assert ts.meta.done and ts.meta.content_length == 0
            await d.stop()
        finally:
            await server.stop()

    asyncio.run(run())


def test_seed_peer_trigger(tmp_path, origin):
    """A first-seen task triggers a seed download (seed_peer.go:101
    TriggerTask / ObtainSeeds): a peer that may NOT back-source still gets
    the file, because the scheduler told the seed host to fetch it."""

    async def run():
        service = _scheduler_service(tmp_path)
        server = SchedulerRPCServer(service, tick_interval=0.01)
        host, port = await server.start()
        sha = hashlib.sha256(origin.payload).hexdigest()
        try:
            seed = Daemon(
                tmp_path / "seed", [(host, port)], hostname="seed-1", host_type="super"
            )
            await seed.start()
            assert seed.is_seed
            # scheduler learns the seed host from its announce (async)
            for _ in range(100):
                if service._seed_hosts:
                    break
                await asyncio.sleep(0.05)
            assert service._seed_hosts == [seed.host_id]

            normal = Daemon(tmp_path / "n1", [(host, port)], hostname="normal-1")
            await normal.start()
            ts = await normal.download(
                origin.url(),
                piece_length=32 * 1024,
                back_source_allowed=False,
                schedule_timeout=30.0,
            )
            with open(ts.data_path, "rb") as f:
                assert hashlib.sha256(f.read()).hexdigest() == sha
            # the bytes came through the seed: origin was hit by the seed's
            # back-source, and the seed holds the completed task locally
            assert origin.get_count > 0
            seed_ts = seed.storage.find_completed_task(ts.meta.task_id)
            assert seed_ts is not None and seed_ts.meta.done
            await normal.stop()
            await seed.stop()
        finally:
            await server.stop()

    asyncio.run(run())


def test_preheat_via_manager_rest(tmp_path, origin):
    """Full preheat path (SURVEY.md §3.4): POST /api/v1/jobs on the manager
    -> JobManager fan-out by hash ring -> scheduler seed trigger -> seed
    daemon back-sources (ObtainSeeds) -> later peers download P2P without
    touching the origin again."""
    import json
    import urllib.request

    from dragonfly2_tpu.cluster import messages as msg
    from dragonfly2_tpu.cluster.jobs import JobManager
    from dragonfly2_tpu.manager.rest import ManagerREST
    from dragonfly2_tpu.manager.service import ManagerService

    async def run():
        service = _scheduler_service(tmp_path)
        server = SchedulerRPCServer(service, tick_interval=0.01)
        host, port = await server.start()
        seed = Daemon(
            tmp_path / "seed", [(host, port)], hostname="seed-1", host_type="super"
        )
        await seed.start()

        jm = JobManager(
            {"s1": service},
            [msg.HostInfo(
                host_id=seed.host_id, hostname="seed-1", ip=seed.ip,
                host_type="super",
            )],
        )
        manager = ManagerService(jobs=jm)
        rest = ManagerREST(manager)
        mhost, mport = rest.start()

        peer = None
        try:
            req = urllib.request.Request(
                f"http://{mhost}:{mport}/api/v1/jobs",
                data=json.dumps(
                    {"type": "preheat", "args": {"urls": [origin.url()],
                     "piece_length": 32 * 1024}}
                ).encode(),
                headers={"Content-Type": "application/json"},
            )
            body = await asyncio.to_thread(
                lambda: json.loads(urllib.request.urlopen(req, timeout=10).read())
            )
            assert body.get("state") in ("SUCCESS", "PENDING"), body

            # the seed daemon consumes the trigger and back-sources
            for _ in range(100):
                if origin.get_count > 0 and not service.seed_triggers:
                    break
                await asyncio.sleep(0.1)
            assert origin.get_count > 0, "seed never back-sourced"
            await asyncio.sleep(0.3)  # let the seed report completion
            warm_gets = origin.get_count

            # a normal peer now gets the bytes purely over P2P
            peer = Daemon(tmp_path / "p1", [(host, port)], hostname="peer-1")
            await peer.start()
            ts = await peer.download(
                origin.url(), piece_length=32 * 1024, back_source_allowed=False
            )
            with open(ts.data_path, "rb") as f:
                assert hashlib.sha256(f.read()).hexdigest() == hashlib.sha256(
                    origin.payload
                ).hexdigest()
            assert origin.get_count == warm_gets, "peer hit the origin"
        finally:
            if peer is not None:
                await peer.stop()
            await seed.stop()
            await server.stop()
            rest.stop()

    asyncio.run(run())


def test_two_schedulers_task_affinity(tmp_path, origin):
    """Two live schedulers: every peer's RPCs for one task land on the
    SAME scheduler (consistent-hash affinity, pkg/balancer) — that is the
    only reason peer 2 can discover peer 1 as a parent — while different
    tasks spread across the scheduler set."""
    async def run():
        services = [_scheduler_service(tmp_path / f"s{i}") for i in (0, 1)]
        servers = [SchedulerRPCServer(s, tick_interval=0.01) for s in services]
        addrs = [await s.start() for s in servers]

        sha = hashlib.sha256(origin.payload).hexdigest()
        daemons = []
        try:
            d1 = Daemon(tmp_path / "d1", addrs, hostname="aff-1")
            d2 = Daemon(tmp_path / "d2", addrs, hostname="aff-2")
            await d1.start(); await d2.start()
            daemons = [d1, d2]

            # several distinct tasks via per-task tags (distinct task ids)
            tags = [f"t{i}" for i in range(6)]
            for tag in tags:
                ts1 = await d1.download(origin.url(), piece_length=64 * 1024, tag=tag)
                with open(ts1.data_path, "rb") as f:
                    assert hashlib.sha256(f.read()).hexdigest() == sha
                gets = origin.get_count
                # peer 2 must find peer 1 through the scheduler that owns
                # this task — no back-source allowed
                ts2 = await d2.download(
                    origin.url(), piece_length=64 * 1024, tag=tag,
                    back_source_allowed=False,
                )
                with open(ts2.data_path, "rb") as f:
                    assert hashlib.sha256(f.read()).hexdigest() == sha
                assert origin.get_count == gets, f"tag {tag}: p2p peer hit origin"

            # each task lives on EXACTLY the scheduler its id hashes to —
            # computed from the same ring the daemons use, so the check is
            # deterministic for any ephemeral ports
            from dragonfly2_tpu.utils import idgen

            expected = [0, 0]
            keys = [f"{h}:{p}" for h, p in addrs]
            for tag in tags:
                task_id = idgen.task_id_v1(origin.url(), tag=tag)
                picked = d1.pool._ring.pick(task_id)
                expected[keys.index(picked)] += 1
            counts = [svc.counts()["tasks"] for svc in services]
            assert counts == expected, (counts, expected)
            assert sum(counts) == len(tags), counts
        finally:
            for d in daemons:
                await d.stop()
            for s in servers:
                await s.stop()

    asyncio.run(run())


def test_adaptive_tick_latency(tmp_path, origin):
    """A lone request must be scheduled at kernel latency, not tick-interval
    latency (VERDICT r1 item 8): with a deliberately huge tick_interval
    (2 s), a peer's download that needs a real scheduling round must finish
    far inside one interval, because the empty->nonempty pending transition
    wakes the tick immediately (rpc/server.py _tick_wake).

    Phase 1 warms the evaluator's XLA compile and seeds two parents through
    a fast-tick server; phase 2 points a third daemon at a 2 s-tick server
    sharing the same cluster state and times just its schedule+download."""
    import time as _time

    async def run():
        service = _scheduler_service(tmp_path)
        warm = SchedulerRPCServer(service, tick_interval=0.01)
        host, port = await warm.start()
        daemons = []
        try:
            d1 = Daemon(tmp_path / "d1", [(host, port)], hostname="host-1")
            await d1.start()
            daemons.append(d1)
            await d1.download(origin.url(), piece_length=32 * 1024)
            d2 = Daemon(tmp_path / "d2", [(host, port)], hostname="host-2")
            await d2.start()
            daemons.append(d2)
            # real scheduling round -> compiles the evaluator for this shape
            await d2.download(
                origin.url(), piece_length=32 * 1024, back_source_allowed=False
            )
            await warm.stop()

            slow = SchedulerRPCServer(service, tick_interval=2.0)
            shost, sport = await slow.start()
            try:
                t0 = _time.monotonic()
                d3 = Daemon(tmp_path / "d3", [(shost, sport)], hostname="host-3")
                await d3.start()
                daemons.append(d3)
                await d3.download(
                    origin.url(), piece_length=32 * 1024, back_source_allowed=False
                )
                elapsed = _time.monotonic() - t0
                # Without the wake this waits out the 2 s tick; with it the
                # whole register+schedule+download runs in millis.
                assert elapsed < 1.0, f"adaptive tick not firing: {elapsed:.2f}s"
            finally:
                await slow.stop()
        finally:
            for d in daemons:
                await d.stop()

    asyncio.run(run())


def test_download_traces_carry_live_host_stats(tmp_path, origin):
    """The training CSV's host feature columns must be real /proc samples,
    not zeros (VERDICT r1 item 3): after a download, the written Download
    record's host carries non-zero cpu/memory stats."""

    async def run():
        service = _scheduler_service(tmp_path)
        server = SchedulerRPCServer(service, tick_interval=0.01)
        host, port = await server.start()
        try:
            d1 = Daemon(tmp_path / "d1", [(host, port)], hostname="host-1")
            await d1.start()
            await d1.download(origin.url(), piece_length=32 * 1024)
            # DownloadPeerFinished arrives async after download() returns
            records = []
            for _ in range(100):
                service.storage.flush()
                records = service.storage.list_downloads()
                if records:
                    break
                await asyncio.sleep(0.05)
            assert records, "no Download trace rows"
            rec = records[-1]
            assert rec.host.cpu.logical_count > 0
            assert rec.host.memory.total > 0
            assert rec.host.memory.used_percent > 0.0
            assert rec.host.disk.total > 0
            # and the numeric feature vector is non-zero in the host-stat
            # columns (records/features.py HOST_NUMERIC_FEATURES tail)
            from dragonfly2_tpu.records.features import host_numeric_features

            feats = host_numeric_features(rec.host)
            assert feats[10] > 0.0  # memory used_percent column
            await d1.stop()
        finally:
            await server.stop()

    asyncio.run(run())
