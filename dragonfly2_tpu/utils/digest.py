"""Digest helpers — parity with pkg/digest (sha256/md5 of strings, readers).

Reference: /root/reference/pkg/digest/digest.go. IDs across the system are
sha256 over ``:``-joined parts (digest.SHA256FromStrings).
"""

from __future__ import annotations

import hashlib
from typing import BinaryIO, Iterable

SEPARATOR = ":"


def sha256_from_strings(*parts: str) -> str:
    h = hashlib.sha256()
    h.update(SEPARATOR.join(parts).encode("utf-8"))
    return h.hexdigest()


def sha256_from_bytes(data: bytes) -> str:
    return hashlib.sha256(data).hexdigest()


def md5_from_bytes(data: bytes) -> str:
    return hashlib.md5(data).hexdigest()


def sha256_from_reader(reader: BinaryIO, chunk_size: int = 1 << 20) -> str:
    h = hashlib.sha256()
    while True:
        chunk = reader.read(chunk_size)
        if not chunk:
            break
        h.update(chunk)
    return h.hexdigest()


def sha256_from_chunks(chunks: Iterable[bytes]) -> str:
    h = hashlib.sha256()
    for chunk in chunks:
        h.update(chunk)
    return h.hexdigest()


def stable_hash64(s: str) -> int:
    """Stable 63-bit integer hash of a string (feature encoding for kernels).

    Used to turn categorical identity fields (IDC, location elements, host
    ids) into integer codes the batched evaluator can compare on device.
    Python's builtin hash() is salted per-process; this one is stable.
    """
    d = hashlib.blake2b(s.encode("utf-8"), digest_size=8).digest()
    return int.from_bytes(d, "big") & 0x7FFF_FFFF_FFFF_FFFF
