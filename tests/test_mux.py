"""Single-port protocol mux + health checking (pkg/rpc mux.go +
pkg/rpc/health parity): one TCP port answers HTTP /healthz and /metrics
AND serves the full scheduler wire protocol, sniffed per connection."""

import asyncio
import urllib.request

from dragonfly2_tpu.cluster import messages as msg
from dragonfly2_tpu.cluster.scheduler import SchedulerService
from dragonfly2_tpu.rpc import wire
from dragonfly2_tpu.rpc.mux import (
    HealthCheckRequest,
    HealthCheckResponse,
    MuxServer,
    SERVING,
)
from dragonfly2_tpu.rpc.server import SchedulerRPCServer
from dragonfly2_tpu.telemetry import default_registry


def _host(i):
    return msg.HostInfo(
        host_id=f"mux-host-{i}", hostname=f"mux-{i}", ip="127.0.0.1",
        host_type="normal", port=9000 + i, download_port=9000 + i,
    )


def test_mux_http_and_wire_on_one_port(tmp_path):
    async def run():
        service = SchedulerService()
        rpc = SchedulerRPCServer(service, tick_interval=0.01)
        # bind the real rpc server too (it owns the tick loop), but talk
        # through the mux port only
        await rpc.start()
        mux_srv = MuxServer(
            rpc._serve_conn, metrics_registry=default_registry(),
            health_check=lambda: True,
        )
        host, port = await mux_srv.start()

        # -- HTTP side
        def http_get(path):
            with urllib.request.urlopen(f"http://{host}:{port}{path}", timeout=5) as r:
                return r.status, r.read()

        loop = asyncio.get_running_loop()
        status, body = await loop.run_in_executor(None, http_get, "/healthz")
        assert (status, body) == (200, b"ok")
        status, body = await loop.run_in_executor(None, http_get, "/metrics")
        assert status == 200 and b"dragonfly_scheduler" in body

        # -- wire side on the SAME port
        reader, writer = await asyncio.open_connection(host, port)
        wire.write_frame(writer, HealthCheckRequest())
        await writer.drain()
        response = await asyncio.wait_for(wire.read_frame(reader), 10)
        assert isinstance(response, HealthCheckResponse) and response.status == SERVING

        wire.write_frame(writer, msg.AnnounceHostRequest(host=_host(1)))
        await writer.drain()
        await asyncio.sleep(0.1)
        assert service.counts()["hosts"] == 1
        writer.close()

        await mux_srv.stop()
        await rpc.stop()

    asyncio.run(run())


def test_mux_unhealthy_and_unknown_path():
    async def run():
        async def never(reader, writer):
            writer.close()

        mux_srv = MuxServer(never, health_check=lambda: False)
        host, port = await mux_srv.start()

        def http_get(path):
            import urllib.error

            try:
                with urllib.request.urlopen(f"http://{host}:{port}{path}", timeout=5) as r:
                    return r.status
            except urllib.error.HTTPError as e:
                return e.code

        loop = asyncio.get_running_loop()
        assert await loop.run_in_executor(None, http_get, "/healthz") == 503
        assert await loop.run_in_executor(None, http_get, "/nope") == 404
        await mux_srv.stop()

    asyncio.run(run())


def test_health_request_on_all_rpc_servers(tmp_path):
    """Every service's wire endpoint answers the health Check."""
    from dragonfly2_tpu.manager.models import Database
    from dragonfly2_tpu.manager.rpc import ManagerRPCServer
    from dragonfly2_tpu.manager.service import ManagerService
    from dragonfly2_tpu.rpc.inference import InferenceRPCServer

    async def check(host, port):
        reader, writer = await asyncio.open_connection(host, port)
        wire.write_frame(writer, HealthCheckRequest(service="any"))
        await writer.drain()
        response = await asyncio.wait_for(wire.read_frame(reader), 10)
        writer.close()
        assert isinstance(response, HealthCheckResponse) and response.status == SERVING

    async def run():
        sched = SchedulerRPCServer(SchedulerService(), tick_interval=0.01)
        await check(*await sched.start())
        await sched.stop()

        manager = ManagerRPCServer(ManagerService(db=Database(":memory:")))
        await check(*await manager.start())
        await manager.stop()

        infer = InferenceRPCServer({})
        await check(*await infer.start())
        await infer.stop()

    asyncio.run(run())


def test_mux_relays_frames_larger_than_backpressure_window():
    """A frame bigger than the relay's high-water slack must still pass:
    read_frame buffers the WHOLE frame before consuming, so a bound below
    MAX_FRAME would deadlock producer against consumer."""
    import dataclasses

    @dataclasses.dataclass
    class BigBlob:
        data: bytes

    wire.register_messages(BigBlob)

    async def echo(reader, writer):
        request = await wire.read_frame(reader)
        if request is not None:
            wire.write_frame(writer, request)
            await writer.drain()
        writer.close()

    async def run():
        mux_srv = MuxServer(echo)
        host, port = await mux_srv.start()
        reader, writer = await asyncio.open_connection(host, port)
        blob = bytes(range(256)) * (24 * 1024)  # 6 MiB, > old 4 MiB bound
        wire.write_frame(writer, BigBlob(data=blob))
        await writer.drain()
        response = await asyncio.wait_for(wire.read_frame(reader), 30)
        assert response.data == blob
        writer.close()
        await mux_srv.stop()

    asyncio.run(run())


def test_wire_health_honors_health_check():
    """A draining server must answer NOT_SERVING on the wire health
    protocol, matching its HTTP /healthz."""
    from dragonfly2_tpu.rpc.mux import NOT_SERVING

    async def run():
        sched = SchedulerRPCServer(
            SchedulerService(), tick_interval=0.01, health_check=lambda: False
        )
        host, port = await sched.start()
        reader, writer = await asyncio.open_connection(host, port)
        wire.write_frame(writer, HealthCheckRequest())
        await writer.drain()
        response = await asyncio.wait_for(wire.read_frame(reader), 10)
        assert response.status == NOT_SERVING
        writer.close()
        await sched.stop()

    asyncio.run(run())


def test_mux_rejects_oversized_frames():
    """A length prefix above the mux frame ceiling closes the connection
    instead of buffering it (or deadlocking the relay)."""
    from dragonfly2_tpu.rpc.mux import MUX_MAX_FRAME

    async def echo(reader, writer):
        request = await wire.read_frame(reader)
        if request is not None:
            wire.write_frame(writer, request)
            await writer.drain()
        writer.close()

    async def run():
        mux_srv = MuxServer(echo)
        host, port = await mux_srv.start()
        reader, writer = await asyncio.open_connection(host, port)
        writer.write((MUX_MAX_FRAME + 1).to_bytes(4, "big") + b"x" * 64)
        await writer.drain()
        got = await asyncio.wait_for(reader.read(), 10)
        assert got == b""  # server closed without a response
        writer.close()
        await mux_srv.stop()

    asyncio.run(run())
