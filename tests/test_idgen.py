"""ID generation semantics (reference: pkg/idgen/*_test.go patterns)."""

from dragonfly2_tpu.utils import digest, idgen


def test_task_id_v1_deterministic():
    a = idgen.task_id_v1("https://example.com/a.bin", tag="t", application="app")
    b = idgen.task_id_v1("https://example.com/a.bin", tag="t", application="app")
    assert a == b
    assert len(a) == 64


def test_task_id_v1_fields_matter():
    base = idgen.task_id_v1("https://example.com/a.bin")
    assert idgen.task_id_v1("https://example.com/a.bin", tag="x") != base
    assert idgen.task_id_v1("https://example.com/a.bin", application="y") != base
    assert idgen.task_id_v1("https://example.com/a.bin", digest="sha256:00") != base


def test_task_id_v1_filtered_query_params():
    with_token = idgen.task_id_v1("https://e.com/a?x=1&token=abc", filtered_query_params="token")
    other_token = idgen.task_id_v1("https://e.com/a?x=1&token=zzz", filtered_query_params="token")
    assert with_token == other_token
    assert with_token != idgen.task_id_v1("https://e.com/a?x=2&token=abc", filtered_query_params="token")


def test_filtered_urls_sort_query_keys():
    """Go's url.Values.Encode() sorts keys — param order must not change
    the task identity once any filter applies."""
    a = idgen.task_id_v1("https://e.com/a?b=2&a=1", filtered_query_params="x")
    b = idgen.task_id_v1("https://e.com/a?a=1&b=2", filtered_query_params="x")
    assert a == b


def test_parent_task_id_ignores_range():
    ranged = idgen.task_id_v1("https://e.com/a", byte_range="0-99")
    parent = idgen.parent_task_id_v1("https://e.com/a", byte_range="0-99")
    plain = idgen.task_id_v1("https://e.com/a")
    assert ranged != plain
    assert parent == plain


def test_task_id_v2_always_includes_fields():
    # v2 hashes empty fields too, so it differs from a bare sha256 of the url.
    v2 = idgen.task_id_v2("https://e.com/a")
    assert v2 == digest.sha256_from_strings("https://e.com/a", "", "", "", "0")


def test_host_and_peer_ids():
    assert idgen.host_id_v1("node-1", 8002) == "node-1-8002"
    h = idgen.host_id_v2("10.0.0.1", "node-1")
    assert h == digest.sha256_from_strings("10.0.0.1", "node-1")
    assert idgen.peer_id_v2() != idgen.peer_id_v2()
    assert idgen.seed_peer_id_v1("10.0.0.1").endswith("_Seed")


def test_stable_hash64_stability():
    assert digest.stable_hash64("idc-a") == digest.stable_hash64("idc-a")
    assert digest.stable_hash64("idc-a") != digest.stable_hash64("idc-b")
    assert digest.stable_hash64("x") >= 0
