"""Exponential-backoff retry loop.

Capability parity with pkg/retry/retry.go `Run(ctx, initBackoff,
maxBackoff, maxAttempts, f)`: f returns (result, cancel, err); cancel=True
aborts the loop immediately (non-retryable), otherwise failures back off
exponentially up to maxBackoff for maxAttempts tries.

Two hardenings over the plain loop:

- **Full jitter** (the AWS-architecture backoff result): each sleep is
  uniform in [0, min(cap, init * 2^attempt)] rather than the deterministic
  ladder, so a fleet of daemons retrying the same restarted scheduler
  spreads its redials instead of stampeding in lockstep.
- **A `retryable` predicate**: errors that can never succeed on retry —
  a malformed request (`InvalidArgument`), a bad credential
  (`Unauthenticated`) — abort immediately instead of burning every
  attempt against a deterministic failure. The default predicate encodes
  exactly that for DFErrors and retries everything else; `Cancel` keeps
  its original contract as the explicit in-band abort.
"""

from __future__ import annotations

import random
import time
from typing import Any, Callable, TypeVar

from dragonfly2_tpu.utils import dferrors

T = TypeVar("T")


class Cancel(Exception):
    """Raise inside the retried callable to abort without further attempts."""

    def __init__(self, cause: Exception | None = None):
        super().__init__(str(cause) if cause else "cancelled")
        self.cause = cause


# DFError codes for which a retry is wasted by construction: the same
# request will fail the same way until the CALLER changes something.
_NON_RETRYABLE_CODES = frozenset({
    dferrors.Code.INVALID_ARGUMENT,
    dferrors.Code.UNAUTHENTICATED,
    dferrors.Code.PERMISSION_DENIED,
})


def default_retryable(error: Exception) -> bool:
    """Retry unless the error is a DFError whose code marks it as a
    caller bug/credential problem rather than a transient fault."""
    if isinstance(error, dferrors.DFError):
        return error.code not in _NON_RETRYABLE_CODES
    return True


def run(
    fn: Callable[[], T],
    init_backoff: float = 0.2,
    max_backoff: float = 5.0,
    max_attempts: int = 3,
    sleep: Callable[[float], Any] = time.sleep,
    retryable: Callable[[Exception], bool] | None = default_retryable,
    rng: random.Random | None = None,
) -> T:
    """Call fn until it succeeds, sleeping a full-jittered exponential
    backoff between failures. Raises the last error after max_attempts,
    the Cancel cause immediately, or the first error `retryable` rejects.
    `retryable=None` retries every Exception (the pre-predicate behavior);
    `rng` pins the jitter for deterministic tests."""
    uniform = (rng or random).uniform
    cap = init_backoff
    last: Exception | None = None
    for attempt in range(max_attempts):
        try:
            return fn()
        except Cancel as c:
            raise (c.cause or c)
        except Exception as e:  # noqa: BLE001 - the predicate decides
            if retryable is not None and not retryable(e):
                raise
            last = e
            if attempt + 1 < max_attempts:
                sleep(uniform(0.0, min(cap, max_backoff)))
                cap *= 2
    assert last is not None
    raise last
