from dragonfly2_tpu.cluster.messages import (
    RegisterPeerRequest,
    DownloadPieceFinishedRequest,
    DownloadPieceFailedRequest,
    DownloadPeerFinishedRequest,
    DownloadPeerFailedRequest,
    DownloadPeerBackToSourceStartedRequest,
    RescheduleRequest,
    NormalTaskResponse,
    NeedBackToSourceResponse,
    ScheduleFailure,
    SizeScope,
)
from dragonfly2_tpu.cluster.probes import ProbeStore
from dragonfly2_tpu.cluster.scheduler import SchedulerService

__all__ = [
    "RegisterPeerRequest",
    "DownloadPieceFinishedRequest",
    "DownloadPieceFailedRequest",
    "DownloadPeerFinishedRequest",
    "DownloadPeerFailedRequest",
    "DownloadPeerBackToSourceStartedRequest",
    "RescheduleRequest",
    "NormalTaskResponse",
    "NeedBackToSourceResponse",
    "ScheduleFailure",
    "SizeScope",
    "ProbeStore",
    "SchedulerService",
]
