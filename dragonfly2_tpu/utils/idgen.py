"""ID generation — parity with pkg/idgen (task/peer/host/model IDs).

Reference: /root/reference/pkg/idgen/{task_id.go,peer_id.go,host_id.go}.
Task IDs are sha256 over filtered-url + meta fields; host ID v2 is
sha256(ip, hostname); peer ID v2 is a UUID.
"""

from __future__ import annotations

import os
import uuid
from urllib.parse import parse_qsl, urlencode, urlsplit, urlunsplit

from dragonfly2_tpu.utils.digest import sha256_from_strings

FILTERED_QUERY_PARAMS_SEPARATOR = "&"


def filter_query_params(url: str, filtered: list[str] | None) -> str:
    """Drop the named query params from the url (pkg/net/url semantics).

    Go's url.Values.Encode() emits keys in sorted order (values within a
    key keep insertion order), so the surviving params are sorted by key
    to keep task-id parity with the reference.
    """
    if not filtered:
        return url
    parts = urlsplit(url)
    kept = [(k, v) for k, v in parse_qsl(parts.query, keep_blank_values=True) if k not in set(filtered)]
    kept.sort(key=lambda kv: kv[0])
    return urlunsplit(parts._replace(query=urlencode(kept)))


def task_id_v1(
    url: str,
    digest: str = "",
    tag: str = "",
    application: str = "",
    byte_range: str = "",
    filtered_query_params: str = "",
    ignore_range: bool = False,
) -> str:
    """v1 task id (pkg/idgen/task_id.go:38-84): sha256 of the filtered url
    plus any non-empty meta fields, in digest/range/tag/application order."""
    filters = (
        filtered_query_params.split(FILTERED_QUERY_PARAMS_SEPARATOR)
        if filtered_query_params.strip()
        else None
    )
    try:
        u = filter_query_params(url, filters)
    except ValueError:
        u = ""
    data = [u]
    if digest:
        data.append(digest)
    if not ignore_range and byte_range:
        data.append(byte_range)
    if tag:
        data.append(tag)
    if application:
        data.append(application)
    return sha256_from_strings(*data)


def parent_task_id_v1(url: str, **kwargs) -> str:
    kwargs["ignore_range"] = True
    return task_id_v1(url, **kwargs)


def task_id_v2(
    url: str,
    digest: str = "",
    tag: str = "",
    application: str = "",
    piece_length: int = 0,
    filtered_query_params: list[str] | None = None,
) -> str:
    """v2 task id (task_id.go:96-104): sha256(url, digest, tag, application,
    str(piece_length)) — all fields always included."""
    try:
        u = filter_query_params(url, filtered_query_params)
    except ValueError:
        u = ""
    return sha256_from_strings(u, digest, tag, application, str(piece_length))


def peer_id_v1(ip: str) -> str:
    return f"{ip}-{os.getpid()}-{uuid.uuid4()}"


def seed_peer_id_v1(ip: str) -> str:
    return f"{peer_id_v1(ip)}_Seed"


def peer_id_v2() -> str:
    return str(uuid.uuid4())


def host_id_v1(hostname: str, port: int) -> str:
    return f"{hostname}-{port}"


def host_id_v2(ip: str, hostname: str) -> str:
    return sha256_from_strings(ip, hostname)


def model_id(name: str, host_id: str) -> str:
    """Model id (pkg/idgen/model_id.go): sha256(host_id, name)."""
    return sha256_from_strings(host_id, name)
