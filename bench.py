"""Headline benchmark: scheduler parent-selection p50 latency.

North star (BASELINE.md / BASELINE.json): p50 < 1 ms for batched parent
selection at the 1k-concurrent-tasks x 64-candidates shape on a cluster
with 10k+ peers — the workload the reference serves one-peer-at-a-time in
Go behind mutexes (scheduler/scheduling/scheduling.go), here ONE
jit-compiled device call (dragonfly2_tpu/ops/evaluator.py).

Prints exactly one JSON line:
  {"metric": ..., "value": ..., "unit": ..., "vs_baseline": ...}
vs_baseline = baseline_ms / measured_ms (>1 means faster than the 1 ms
target; the reference publishes no numbers of its own, BASELINE.md).

Robustness: the tunneled dev TPU shows multi-minute slow windows where
every dispatch costs ~70 ms (see .claude/skills/verify/SKILL.md); each
trial is paired with a trivial-dispatch control and the p50 is taken over
trials whose control stayed sane.
"""

import json
import statistics
import sys
import time

import numpy as np

BASELINE_MS = 1.0
BATCH_TASKS = 1024
BATCH_CANDIDATES = 64
NUM_HOSTS = 10_000
TRIALS = 200
CONTROL_THRESHOLD_MS = 5.0


def main() -> int:
    import jax

    from dragonfly2_tpu.ops import evaluator as ev
    from dragonfly2_tpu.records import synth
    from dragonfly2_tpu.records.features import downloads_to_eval_batch

    # Build a 10k-host cluster and replay its traces as scoring requests.
    cluster = synth.make_cluster(NUM_HOSTS, seed=0)
    records = synth.gen_download_records(
        cluster, BATCH_TASKS, num_tasks=256, max_parents=20
    )
    feats = downloads_to_eval_batch(records, BATCH_TASKS, BATCH_CANDIDATES)
    rng = np.random.default_rng(0)
    # randomize states/rtt so every branch is live
    feats.peer_state = rng.integers(5, 8, feats.peer_state.shape).astype(np.int8)
    feats.has_rtt = rng.random(feats.has_rtt.shape) < 0.7
    feats.avg_rtt_ns = (rng.random(feats.avg_rtt_ns.shape) * 5e7).astype(np.float32)

    d = jax.device_put(feats.as_dict())
    control_in = jax.device_put(np.ones((8, 128), np.float32))
    control = jax.jit(lambda x: x + 1)

    def call():
        return ev.schedule_candidate_parents(d, algorithm="nt", limit=4)

    # warmup / compile
    jax.block_until_ready(call())
    jax.block_until_ready(control(control_in))

    samples = []
    for _ in range(TRIALS):
        t0 = time.perf_counter()
        jax.block_until_ready(control(control_in))
        control_ms = (time.perf_counter() - t0) * 1e3
        t0 = time.perf_counter()
        jax.block_until_ready(call())
        kernel_ms = (time.perf_counter() - t0) * 1e3
        if control_ms < CONTROL_THRESHOLD_MS:
            samples.append(kernel_ms)
    if not samples:  # every window was bad; report unfiltered
        for _ in range(50):
            t0 = time.perf_counter()
            jax.block_until_ready(call())
            samples.append((time.perf_counter() - t0) * 1e3)

    p50 = statistics.median(samples)
    print(
        json.dumps(
            {
                "metric": "scheduler_parent_selection_p50_ms_1024x64",
                "value": round(p50, 4),
                "unit": "ms",
                "vs_baseline": round(BASELINE_MS / p50, 2),
            }
        )
    )
    return 0


if __name__ == "__main__":
    sys.exit(main())
