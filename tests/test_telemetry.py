"""Telemetry: metrics exposition + span tracing (SURVEY.md §5)."""

import json
import time
import urllib.request

from dragonfly2_tpu.telemetry import metrics as m
from dragonfly2_tpu.telemetry import tracing


def test_counter_gauge_histogram_expose():
    reg = m.Registry()
    c = reg.counter(
        "dragonfly_scheduler_announce_peer_total", "announce totals", ("priority",)
    )
    c.labels("LEVEL0").inc()
    c.labels("LEVEL0").inc(2)
    g = reg.gauge("dragonfly_scheduler_concurrent_schedule", "gauge")
    g.set(7)
    g.inc()
    h = reg.histogram(
        "dragonfly_scheduler_download_duration_seconds", buckets=(0.1, 1.0, 10.0)
    )
    h.observe(0.05)
    h.observe(0.5)
    h.observe(100.0)

    text = reg.expose()
    assert 'dragonfly_scheduler_announce_peer_total{priority="LEVEL0"} 3.0' in text
    assert "dragonfly_scheduler_concurrent_schedule 8.0" in text
    assert 'le="+Inf"} 3' in text
    assert "download_duration_seconds_count 3" in text
    assert "# TYPE dragonfly_scheduler_download_duration_seconds histogram" in text
    assert c.value("LEVEL0") == 3.0


def test_registry_dedup_and_timer():
    reg = m.Registry()
    a = reg.counter("x_total")
    b = reg.counter("x_total")
    assert a is b
    h = reg.histogram("t_seconds", buckets=(10.0,))
    with m.Timer(h.labels()):
        pass
    assert "t_seconds_count 1" in reg.expose()


def test_registry_rejects_type_and_label_conflicts():
    import pytest

    reg = m.Registry()
    reg.counter("x_total")
    with pytest.raises(ValueError):
        reg.gauge("x_total")
    with pytest.raises(ValueError):
        reg.counter("x_total", labels=("a",))


def test_labeled_gauge_dec_and_label_escaping():
    reg = m.Registry()
    g = reg.gauge("concurrent", labels=("host",))
    g.labels("h1").inc(3)
    g.labels("h1").dec()
    assert g.value("h1") == 2.0
    c = reg.counter("nl", labels=("v",))
    c.labels("line1\nline2").inc()
    text = reg.expose()
    assert 'nl{v="line1\\nline2"} 1.0' in text


def test_metrics_http_server():
    reg = m.Registry()
    reg.counter("served_total").inc()
    server = m.serve_metrics(reg, port=0)
    try:
        port = server.server_address[1]
        body = urllib.request.urlopen(f"http://127.0.0.1:{port}/metrics").read().decode()
        assert "served_total 1.0" in body
    finally:
        server.shutdown()


def test_tracing_nesting_and_export(tmp_path):
    tracer = tracing.Tracer("scheduler")
    spans = tracer.export_to_memory()
    path = tmp_path / "spans.jsonl"
    file_exporter = tracer.export_to_file(path)

    with tracer.span("announce_peer", peer_id="p1") as outer:
        with tracer.span("schedule_tick") as inner:
            inner.add_event("batched", size=32)
        assert tracing.current_span() is outer
    assert tracing.current_span() is None

    assert [s.name for s in spans] == ["schedule_tick", "announce_peer"]
    child, parent = spans
    assert child.trace_id == parent.trace_id
    assert child.parent_id == parent.span_id
    assert parent.attributes["peer_id"] == "p1"
    assert parent.duration_ms() is not None
    lines = [json.loads(l) for l in path.read_text().splitlines()]
    assert len(lines) == 2 and lines[1]["name"] == "announce_peer"
    file_exporter.close()


def test_tracing_error_status():
    tracer = tracing.Tracer()
    spans = tracer.export_to_memory()
    try:
        with tracer.span("failing"):
            raise RuntimeError("boom")
    except RuntimeError:
        pass
    assert spans[0].status == "ERROR"
    assert spans[0].events[0]["type"] == "RuntimeError"


def test_service_series_families_registered():
    """Metrics parity sweep (VERDICT r1 item 5): every service's series
    families from the reference metrics packages exist with their label
    sets after the series factories run (scheduler/metrics/metrics.go:
    44-454, client/daemon/metrics, manager/metrics, trainer/metrics)."""
    from dragonfly2_tpu.telemetry.metrics import Registry
    from dragonfly2_tpu.telemetry.series import (
        daemon_series,
        manager_series,
        register_version,
        scheduler_series,
        trainer_series,
    )

    reg = Registry()
    scheduler_series(reg)
    daemon_series(reg)
    manager_series(reg)
    trainer_series(reg)
    for svc in ("scheduler", "dfdaemon", "manager", "trainer"):
        register_version(reg, svc)
    # touch one labeled child per family so exposition shows the labels
    sched = scheduler_series(reg)
    sched.traffic.labels("p2p", "STANDARD", "t", "a", "normal").inc(42)
    sched.register_peer.labels("0", "STANDARD", "", "").inc()
    sched.download_peer_duration.labels("NORMAL").observe(123.0)
    daemon = daemon_series(reg)
    daemon.proxy_request.labels("GET").inc()
    text = reg.expose()
    for family in (
        "dragonfly_scheduler_register_peer_total",
        "dragonfly_scheduler_download_peer_finished_total",
        "dragonfly_scheduler_download_piece_finished_total",
        "dragonfly_scheduler_traffic",
        "dragonfly_scheduler_host_traffic",
        "dragonfly_scheduler_download_peer_duration_milliseconds",
        "dragonfly_scheduler_concurrent_schedule_total",
        "dragonfly_scheduler_announce_host_total",
        "dragonfly_scheduler_sync_probes_total",
        "dragonfly_dfdaemon_proxy_request_total",
        "dragonfly_dfdaemon_peer_task_total",
        "dragonfly_dfdaemon_piece_task_total",
        "dragonfly_dfdaemon_seed_peer_download_total",
        "dragonfly_dfdaemon_peer_task_cache_hit_total",
        "dragonfly_manager_search_scheduler_cluster_total",
        "dragonfly_manager_request_total",
        "dragonfly_trainer_training_total",
        "dragonfly_scheduler_version",
        "dragonfly_dfdaemon_version",
        "dragonfly_manager_version",
        "dragonfly_trainer_version",
    ):
        assert f"# TYPE {family}" in text, family
    assert 'traffic{type="p2p",task_type="STANDARD",task_tag="t",task_app="a",host_type="normal"} 42' in text
    assert 'git_version=' in text


def test_scheduler_metrics_populated_by_live_traffic(tmp_path):
    """Drive a real download through the RPC edge and scrape /metrics over
    HTTP (MuxServer): per-RPC totals, traffic bytes, and duration
    histogram must be populated — not just registered."""
    import asyncio
    import urllib.request as _rq

    from test_minicluster import _CountingFileServer, _scheduler_service
    from dragonfly2_tpu.client.daemon import Daemon
    from dragonfly2_tpu.rpc.mux import MuxServer
    from dragonfly2_tpu.rpc.server import SchedulerRPCServer
    from dragonfly2_tpu.telemetry import default_registry

    origin = _CountingFileServer(bytes(i % 256 for i in range(150_000)))

    async def run():
        service = _scheduler_service(tmp_path)
        server = SchedulerRPCServer(service, tick_interval=0.01)
        # the tick loop lives in the rpc server; without start() every
        # mux-connected peer silently waited out the 10 s schedule
        # timeout and back-sourced (shared service => ticks serve peers
        # connected through either listener)
        await server.start()
        mux_srv = MuxServer(server._serve_conn, metrics_registry=default_registry())
        host, port = await mux_srv.start()
        try:
            d1 = Daemon(tmp_path / "d1", [(host, port)], hostname="mh-1")
            await d1.start()
            await d1.download(origin.url(), piece_length=32 * 1024)
            # download() resolves when the bytes land; the daemon's final
            # DownloadPeer*Finished report rides the announce stream right
            # after, so the duration series can trail the return by a beat
            text = ""
            for _ in range(50):
                text = await asyncio.to_thread(
                    lambda: _rq.urlopen(f"http://{host}:{port}/metrics").read().decode()
                )
                if "dragonfly_scheduler_download_peer_duration_milliseconds_count" in text:
                    break
                await asyncio.sleep(0.1)
            assert "dragonfly_scheduler_register_peer_total{" in text
            assert "dragonfly_scheduler_traffic{" in text
            assert 'type="back_to_source"' in text
            assert "dragonfly_scheduler_host_traffic{" in text
            assert "dragonfly_scheduler_download_peer_duration_milliseconds_count" in text
            assert "dragonfly_dfdaemon_peer_task_total" in text
            # pipelined tick: the old device_call phase is split into the
            # async dispatch and the blocking D2H read
            assert 'dragonfly_scheduler_tick_phase_seconds_count{phase="dispatch"}' in text
            assert 'dragonfly_scheduler_tick_phase_seconds_count{phase="d2h_wait"}' in text
            await d1.stop()
        finally:
            await mux_srv.stop()
            await server.stop()
            origin.stop()

    asyncio.run(run())


def test_spans_emitted_at_live_service_boundaries(tmp_path):
    """A real download emits boundary spans (dfdaemon.peer_task around
    the conductor lifecycle, scheduler.tick around the device call) —
    the tracing row's claim, proven on live traffic instead of
    hand-created spans."""
    import asyncio

    from test_minicluster import _CountingFileServer, _scheduler_service
    from dragonfly2_tpu.client.daemon import Daemon
    from dragonfly2_tpu.rpc.server import SchedulerRPCServer
    from dragonfly2_tpu.telemetry.tracing import default_tracer

    captured = []
    exporter = captured.append  # bind ONCE so removal-by-identity works
    tracer = default_tracer()
    tracer.add_exporter(exporter)
    origin = _CountingFileServer(bytes(i % 256 for i in range(120_000)))

    async def run():
        service = _scheduler_service(tmp_path)
        server = SchedulerRPCServer(service, tick_interval=0.01)
        host, port = await server.start()
        try:
            d1 = Daemon(tmp_path / "d1", [(host, port)], hostname="tr-1")
            await d1.start()
            await d1.download(origin.url(), piece_length=32 * 1024)
            await d1.stop()
        finally:
            await server.stop()
            origin.stop()

    try:
        asyncio.run(run())
        names = {s.name for s in captured}
        assert "dfdaemon.peer_task" in names, names
        assert "scheduler.tick" in names, names
        task_span = next(s for s in captured if s.name == "dfdaemon.peer_task")
        assert task_span.attributes["pieces"] >= 1
        assert task_span.end_ns > task_span.start_ns
    finally:
        # default_tracer() is process-global: leave no exporter behind
        tracer.remove_exporter(exporter)


def test_otlp_exporter_ships_ingestible_batches(tmp_path):
    """Spans exported through OTLPExporter must arrive at a collector
    fixture as a valid OTLP/JSON ExportTraceServiceRequest (resourceSpans
    -> scopeSpans -> spans with ids/times/status), preserving parent links
    and error status (VERDICT r1 item 9)."""
    import http.server
    import json as _json
    import threading

    from dragonfly2_tpu.telemetry.tracing import OTLPExporter, Tracer

    received = []

    class Collector(http.server.BaseHTTPRequestHandler):
        def log_message(self, *a):
            pass

        def do_POST(self):
            assert self.path == "/v1/traces"
            length = int(self.headers.get("Content-Length") or 0)
            received.append(_json.loads(self.rfile.read(length)))
            self.send_response(200)
            self.send_header("Content-Length", "0")
            self.end_headers()

    srv = http.server.ThreadingHTTPServer(("127.0.0.1", 0), Collector)
    threading.Thread(target=srv.serve_forever, daemon=True).start()
    try:
        tracer = Tracer(service="test-svc")
        exporter = OTLPExporter(
            f"http://127.0.0.1:{srv.server_address[1]}", service="test-svc",
            batch_size=100,
        )
        tracer.add_exporter(exporter.export)
        with tracer.span("parent", task_id="t-1", pieces=7):
            with tracer.span("child"):
                pass
        try:
            with tracer.span("boom"):
                raise RuntimeError("nope")
        except RuntimeError:
            pass
        exporter.flush()
        assert len(received) == 1
        body = received[0]
        rs = body["resourceSpans"][0]
        res_attrs = {a["key"]: a["value"] for a in rs["resource"]["attributes"]}
        assert res_attrs["service.name"] == {"stringValue": "test-svc"}
        spans = {s["name"]: s for s in rs["scopeSpans"][0]["spans"]}
        assert set(spans) == {"parent", "child", "boom"}
        child, parent = spans["child"], spans["parent"]
        assert child["traceId"] == parent["traceId"]
        assert child["parentSpanId"] == parent["spanId"]
        assert int(parent["endTimeUnixNano"]) >= int(parent["startTimeUnixNano"])
        attrs = {a["key"]: a["value"] for a in parent["attributes"]}
        assert attrs["task_id"] == {"stringValue": "t-1"}
        assert attrs["pieces"] == {"intValue": "7"}
        assert spans["boom"]["status"]["code"] == 2
        assert spans["boom"]["events"][0]["name"] == "exception"
    finally:
        srv.shutdown()
        srv.server_close()


def test_otlp_flush_drains_worker_queued_batches():
    """ISSUE 14 satellite: flush() must post batches already handed to
    the daemon worker's queue, not only the partial buffer — with the
    worker prevented from running (the crash/teardown race), a full
    queued batch previously vanished on flush."""
    from dragonfly2_tpu.telemetry.tracing import OTLPExporter, Span

    posted = []
    exporter = OTLPExporter("http://127.0.0.1:1", batch_size=2)
    exporter._post = posted.append  # no network; record batches
    exporter._ensure_worker = lambda: None  # worker never runs

    def span(i):
        return Span(name=f"s{i}", trace_id="t", span_id=f"i{i}",
                    parent_id=None, start_ns=1, end_ns=2)

    for i in range(5):  # two full batches queued + one partial buffered
        exporter.export(span(i))
    assert exporter._queue.qsize() == 2 and len(exporter._buf) == 1
    exporter.flush()
    flat = [s.name for batch in posted for s in batch]
    assert flat == ["s0", "s1", "s2", "s3", "s4"], flat
    assert exporter._queue.qsize() == 0 and exporter._buf == []


def test_otlp_close_is_bounded_and_stops_the_worker():
    """close(): flush everything, stop the worker via sentinel, join
    bounded, and drop (never crash on) post-close exports."""
    import threading as _threading

    from dragonfly2_tpu.telemetry.tracing import OTLPExporter, Span

    posted = []
    exporter = OTLPExporter("http://127.0.0.1:1", batch_size=1)
    exporter._post = posted.append

    s = Span(name="one", trace_id="t", span_id="i", parent_id=None,
             start_ns=1, end_ns=2)
    exporter.export(s)  # full batch -> worker starts and posts it
    deadline = time.time() + 5
    while not posted and time.time() < deadline:
        time.sleep(0.01)
    worker = exporter._worker
    assert worker is not None and worker.is_alive()
    exporter.close(timeout=5)
    assert not worker.is_alive(), "close() left the otlp worker running"
    assert exporter._worker is None
    n = len(posted)
    exporter.export(s)  # post-close exports drop silently
    exporter.flush()
    assert len(posted) == n
    exporter.close()  # idempotent
    assert not any(
        t.name == "otlp-exporter" and t.is_alive()
        for t in _threading.enumerate()
    )


def test_otlp_flush_preserves_close_sentinel():
    """A concurrent flush() racing close() must hand the None shutdown
    sentinel back to the queue instead of swallowing it — a stolen
    sentinel left the worker blocked in get() forever and close()
    burning its full join timeout."""
    from dragonfly2_tpu.telemetry.tracing import OTLPExporter, Span

    posted = []
    exporter = OTLPExporter("http://127.0.0.1:1", batch_size=8)
    exporter._post = posted.append
    s = Span(name="one", trace_id="t", span_id="i", parent_id=None,
             start_ns=1, end_ns=2)
    exporter.export(s)
    exporter._queue.put_nowait(None)  # close()'s sentinel, worker not yet at it
    exporter.flush()
    # partial buffer posted, sentinel back on the queue for the worker
    assert [sp.name for b in posted for sp in b] == ["one"]
    assert exporter._queue.qsize() == 1
    assert exporter._queue.get_nowait() is None


def test_file_exporter_holds_one_handle_with_locked_writes(tmp_path, monkeypatch):
    """export_to_file keeps ONE held handle (the old closure reopened
    the file per span), writes byte-identical JSONL, and closes
    explicitly — post-close spans drop instead of raising."""
    import builtins
    import json as _json

    from dragonfly2_tpu.telemetry import tracing

    path = tmp_path / "spans.jsonl"
    tracer = tracing.Tracer("scheduler")
    opens = []
    real_open = builtins.open

    def counting_open(file, *a, **kw):
        if str(file) == str(path):
            opens.append(file)
        return real_open(file, *a, **kw)

    monkeypatch.setattr(builtins, "open", counting_open)
    exporter = tracer.export_to_file(path)
    try:
        for i in range(8):
            with tracer.span(f"span-{i}"):
                pass
        assert len(opens) == 1, f"{len(opens)} opens for 8 spans"
        lines = [_json.loads(l) for l in path.read_text().splitlines()]
        assert [l["name"] for l in lines] == [f"span-{i}" for i in range(8)]
        # byte-identical JSONL: same serializer the per-open version used
        assert path.read_text().splitlines()[0] == _json.dumps(lines[0])
    finally:
        monkeypatch.undo()
        exporter.close()
        tracer.remove_exporter(exporter)
    with tracer.span("after-close"):
        pass  # dropped silently, no ValueError from a closed file
    assert len(path.read_text().splitlines()) == 8
