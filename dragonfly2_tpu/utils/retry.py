"""Exponential-backoff retry loop.

Capability parity with pkg/retry/retry.go `Run(ctx, initBackoff,
maxBackoff, maxAttempts, f)`: f returns (result, cancel, err); cancel=True
aborts the loop immediately (non-retryable), otherwise failures back off
exponentially up to maxBackoff for maxAttempts tries.
"""

from __future__ import annotations

import time
from typing import Any, Callable, TypeVar

T = TypeVar("T")


class Cancel(Exception):
    """Raise inside the retried callable to abort without further attempts."""

    def __init__(self, cause: Exception | None = None):
        super().__init__(str(cause) if cause else "cancelled")
        self.cause = cause


def run(
    fn: Callable[[], T],
    init_backoff: float = 0.2,
    max_backoff: float = 5.0,
    max_attempts: int = 3,
    sleep: Callable[[float], Any] = time.sleep,
) -> T:
    """Call fn until it succeeds, backing off exponentially between
    failures. Raises the last error after max_attempts, or the Cancel cause
    immediately."""
    delay = init_backoff
    last: Exception | None = None
    for attempt in range(max_attempts):
        try:
            return fn()
        except Cancel as c:
            raise (c.cause or c)
        except Exception as e:  # noqa: BLE001 - retry treats any error as retryable
            last = e
            if attempt + 1 < max_attempts:
                sleep(min(delay, max_backoff))
                delay *= 2
    assert last is not None
    raise last
