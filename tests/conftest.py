"""Test harness: force an 8-device virtual CPU mesh before jax loads.

Mirrors the reference's approach of unit-testing "multi-node" logic without
a cluster (SURVEY.md §4): sharding/collective code paths run on
xla_force_host_platform_device_count=8 CPU devices; numeric kernels run on
the CPU backend with fixed seeds. No TPU needed in CI.
"""

import os

# Env vars alone are not enough: in this image jax is pre-imported at
# interpreter startup (a .pth hook) with JAX_PLATFORMS already resolved, so
# the config must be updated through jax.config before first backend use.
os.environ["JAX_PLATFORMS"] = "cpu"
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (_flags + " --xla_force_host_platform_device_count=8").strip()

import jax

try:
    jax.config.update("jax_platforms", "cpu")
    jax.config.update("jax_num_cpu_devices", 8)
except RuntimeError:
    # Backend already initialized (a plugin touched jax before conftest) —
    # the env vars above were then read at init and did the same job.
    pass
except AttributeError:
    # Older jax without the jax_num_cpu_devices option: the XLA_FLAGS
    # host-platform device-count flag above is the only mechanism.
    pass

import numpy as np
import pytest


def pytest_configure(config):
    config.addinivalue_line("markers", "slow: long-running end-to-end tests")
    # the packed-transport jits donate their one-shot staging buffer;
    # backends without donation support (CPU CI) warn once per compiled
    # shape — expected no-op, not a finding (ops/evaluator.py)
    config.addinivalue_line(
        "filterwarnings", "ignore:Some donated buffers were not usable"
    )
    # chaos tests are tier-1 on purpose (NOT slow): failure-domain
    # resilience must not rot behind an opt-in marker
    config.addinivalue_line(
        "markers", "chaos: fault-injection resilience tests (tier-1)"
    )
    # like chaos: trust-boundary integrity tests (corrupt parents, digest
    # chains, guarded activation) stay tier-1, never opt-in
    config.addinivalue_line(
        "markers", "corruption: trust-boundary integrity tests (tier-1)"
    )
    # megascale scenario lab: the tier-1 soak smoke (>=50k hosts, a few
    # engine steps, time-budgeted well under the tier-1 wall); the full
    # 24h-trace soak and the >=100k-host runs live behind `slow` and
    # bench_megascale.py --artifact
    config.addinivalue_line(
        "markers", "soak: megascale soak smoke (tier-1, time-budgeted)"
    )
    # real-process planet: the tier-1 procworld smoke (2 schedulers + 3
    # daemons + manager over real sockets, one SIGKILL + one rolling
    # restart, time-budgeted); the full compressed day + divergence
    # report lives in tools/dfproc.py
    config.addinivalue_line(
        "markers", "procworld: real-process planet harness (tier-1, "
        "time-budgeted)"
    )


@pytest.fixture
def rng():
    return np.random.default_rng(0)


# ----------------------------------------------------- resource leak guard

# Thread names whole subsystems own for the process lifetime: runtime pools
# (jax/XLA, orbax async machinery, grpc pollers, asyncio's default executor)
# plus the few intentionally-immortal daemons in this tree. Anything else
# alive after the last test is a leak the suite must fail on — resilience
# tests juggle servers and sockets, and a silently leaked listener turns
# every later run flaky.
_THREAD_ALLOWLIST_PREFIXES = (
    "MainThread", "pytest", "asyncio_", "ThreadPoolExecutor", "jax_",
    "orbax", "ocdbt", "ts_", "grpc", "eval-warmup", "Dummy",
    "watchdog", "QueueFeederThread",
    # orbax/tensorstore checkpoint pools (0.7.x thread names): process-
    # lifetime runtime pools like the jax_/grpc entries above
    "base_pytree_ch", "metadata_store", "process_metadata_ch",
)


def _listening_socket_inodes() -> set[str]:
    """Inodes of LISTEN-state TCP sockets owned by this process — derived
    from /proc so no extra dependency; empty off-Linux (guard no-ops)."""
    import os
    import re

    listen_inodes = set()
    for table in ("/proc/net/tcp", "/proc/net/tcp6"):
        try:
            with open(table) as f:
                for line in f.readlines()[1:]:
                    parts = line.split()
                    if len(parts) > 9 and parts[3] == "0A":  # TCP_LISTEN
                        listen_inodes.add(parts[9])
        except OSError:
            return set()
    owned = set()
    try:
        for fd in os.listdir("/proc/self/fd"):
            try:
                target = os.readlink(f"/proc/self/fd/{fd}")
            except OSError:
                continue
            m = re.match(r"socket:\[(\d+)\]", target)
            if m and m.group(1) in listen_inodes:
                owned.add(m.group(1))
    except OSError:
        return set()
    return owned


@pytest.fixture(scope="session", autouse=True)
def resource_leak_guard():
    """Fail the suite when tests leak non-daemon threads or listening
    sockets past their teardown (CI satellite of the failure-domain PR:
    resilience tests must not regress into resource leaks)."""
    import gc
    import threading
    import time

    baseline_sockets = _listening_socket_inodes()
    yield
    gc.collect()
    # grace for executors/handlers that are mid-teardown at session end
    deadline = time.monotonic() + 2.0
    while time.monotonic() < deadline:
        leaked_threads = [
            t for t in threading.enumerate()
            if t.is_alive() and not t.daemon
            and not t.name.startswith(_THREAD_ALLOWLIST_PREFIXES)
        ]
        leaked_sockets = _listening_socket_inodes() - baseline_sockets
        if not leaked_threads and not leaked_sockets:
            return
        time.sleep(0.1)
    problems = []
    if leaked_threads:
        problems.append(
            "leaked non-daemon threads: "
            + ", ".join(sorted(t.name for t in leaked_threads))
        )
    if leaked_sockets:
        problems.append(f"leaked listening sockets (inodes): {sorted(leaked_sockets)}")
    pytest.fail("resource leak after test-session teardown: " + "; ".join(problems))


@pytest.fixture(scope="session", autouse=True)
def serving_retrace_tripwire():
    """dfshape's runtime half (tools/dflint/retracer.py): every compile
    signature the serving jits route during the whole session must land
    inside the statically-proven ``_EVAL_BUCKETS`` set — a compile the
    static shape pass did not predict fails the suite. The donation
    guards ride along in mark mode: a donated staging buffer passed
    twice raises UseAfterDonateError at the offending call, and donated
    buffers are frozen so a later write crashes loudly."""
    import pathlib

    from tools.dflint import retracer

    root = pathlib.Path(__file__).resolve().parents[1]
    tripwire = retracer.RetraceTripwire(root=root)
    guards = retracer.install_donation_guards()
    yield
    retracer.uninstall_donation_guards(guards)
    violations = tripwire.violations()
    if violations:
        pytest.fail(
            "retrace tripwire: serving jit compiled outside the "
            "statically-proven signature set:\n" + "\n".join(violations)
        )


@pytest.fixture(scope="session", autouse=True)
def ml_refresh_worker_guard():
    """The background embedding-refresh worker (registry/serving.py
    MLEvaluator) is a daemon thread, so the non-daemon sweep above cannot
    see it — this guard fails the suite if any `ml-embed-refresh` worker
    outlives its evaluator. A collected evaluator's weakref finalizer
    signals its worker to exit, so after a gc pass every worker whose
    owner is gone must drain within the grace window; survivors mean a
    strong reference leaked into the worker (exactly the daemon-thread
    leak this fixture exists to catch)."""
    import gc
    import threading
    import time

    yield
    gc.collect()
    deadline = time.monotonic() + 2.0
    while time.monotonic() < deadline:
        workers = [
            t for t in threading.enumerate()
            if t.is_alive() and t.name.startswith("ml-embed-refresh")
        ]
        if not workers:
            return
        time.sleep(0.1)
    pytest.fail(
        "ml-embed-refresh worker(s) outlived their evaluator: "
        + ", ".join(sorted(t.name for t in workers))
    )
