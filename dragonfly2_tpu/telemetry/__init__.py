from dragonfly2_tpu.telemetry.metrics import (  # noqa: F401
    Counter,
    Gauge,
    Histogram,
    MonitorServer,
    Registry,
    default_registry,
    serve_metrics,
)
from dragonfly2_tpu.telemetry.tracing import (  # noqa: F401
    Span,
    Tracer,
    current_context,
    default_tracer,
)
from dragonfly2_tpu.telemetry.flight import (  # noqa: F401
    PhaseRecorder,
    instrument_jit,
)
from dragonfly2_tpu.telemetry.costcard import (  # noqa: F401
    CostCard,
    CostCardLedger,
)
from dragonfly2_tpu.telemetry.timeline import (  # noqa: F401
    QuantileSketch,
    TimelineRecorder,
    recovery_time,
)
from dragonfly2_tpu.telemetry.slo import (  # noqa: F401
    BurnRateRule,
    SLOEngine,
    SLOSpec,
    health_verdict,
)
