"""SHAPE001/SHAPE002/DON001 — dfshape: static shape/dtype/donation
verification of the jit compile-signature set.

The serving pipeline's perf contract is that the compiled-signature set
of the device entry points is CLOSED: every batch that reaches a jitted
scheduling kernel is padded to one of the three fixed ``_EVAL_BUCKETS``
(cluster/scheduler.py), every serving-graph array is ``pad_pow2``-padded
(ops/segment.py), and a chunk's batch dim comes out of ``_bucket_rows``/
``_chunk_stride`` — so warmup() compiles everything the process will
ever execute and a tick can never eat a 35 s XLA compile. The runtime
compile-shape-stability test (tests/test_serving_pipeline.py) and the
retrace tripwire (tools/dflint/retracer.py) check this dynamically; this
pass proves it statically at every call site, so a NEW call site that
can feed a runtime-dependent shape fails tier-1 before it ever runs.

The pass runs a small abstract interpreter over each function body. Int
expressions live in a four-point lattice:

- ``CONST``   — literal ints, ``CONSTANTS.*`` / ``*.config.*`` reads,
  module-level UPPERCASE constants: fixed per process.
- ``BUCKET``  — provably a member of the closed bucket set: produced by
  ``_bucket_rows``/``_chunk_stride``, iterated out of ``_EVAL_BUCKETS``,
  or returned by ``pad_pow2``.
- ``RUNTIME`` — provably runtime-varying: ``len(...)``, arithmetic on a
  RUNTIME value, loop indices over runtime ranges. Feeding one of these
  into a compile-signature position is a finding.
- ``UNKNOWN`` — everything else (function parameters, attribute reads).
  UNKNOWN stays silent: the proof is compositional — a forwarding layer
  (e.g. MLEvaluator.schedule_from_packed passing ``b`` through) is
  checked at the call sites where the value ORIGINATES, which is where
  scheduler.py computes it from the bucket machinery.

Rules:

- ``SHAPE001`` — a runtime-dependent value (RUNTIME) or a runtime-length
  slice reaches a shape-bearing argument of a registered serving jit
  entry (``SERVING_JIT_REGISTRY``). Each distinct value is a fresh
  compiled signature; the bucket set is no longer closed.
- ``SHAPE002`` — a RUNTIME value flows into a ``static_argnames``
  parameter of a jit call (same-file jit defs contribute their static
  sets; registry entries their static keyword names). Static args are
  part of the compile key, so a runtime-length ``limit=len(parents)``
  recompiles per distinct length.
- ``DON001`` — a read of a donated buffer after the donating call.
  ``donate_argnums`` positions are collected from same-file jit
  decorators and the cross-file ``DONATING_CALLABLES`` registry, then a
  fixpoint over the in-module call graph marks functions that forward a
  parameter into a donated position as donating that parameter — so the
  PR-4 argument "verified no caller reuses buf" is machine-checked at
  every layer, not just at the jit boundary. Donations created by a
  ``return``-statement call don't leak into unreachable code; rebinding
  (``params, opt = run_epoch(params, opt, ...)``) kills the donation.

Like every dflint pass this is a lint for a discipline, not a proof
system: coverage is source-order within a function, and UNKNOWN gives
the benefit of the doubt. The retrace tripwire + donation guard
(tools/dflint/retracer.py) are the runtime backstop for whatever this
approximation lets through.
"""

from __future__ import annotations

import ast
import dataclasses

from tools.dflint.core import FileContext, Finding, attr_chain
from tools.dflint.passes.collective import _functions_with_symbols, _walk_own
from tools.dflint.passes.jit_hygiene import _collect_jit_functions

CONST = "const"
BUCKET = "bucket"
RUNTIME = "runtime"
UNKNOWN = "unknown"

# producers whose return value is provably inside the closed bucket set
BUCKET_PRODUCERS = frozenset({"_bucket_rows", "_chunk_stride", "pad_pow2"})
# the bucket-set constants themselves (iteration / subscript yields BUCKET)
BUCKET_CONSTANTS = frozenset({"_EVAL_BUCKETS", "EVAL_BUCKETS"})
# array producers whose output shape is fixed by their bucket argument
PADDED_PRODUCERS = frozenset({"_pad_rows", "pack_eval_batch"})
# callables whose int result is runtime-varying by construction
RUNTIME_PRODUCERS = frozenset({"len", "sum", "count_nonzero"})

# Registered serving jit entries, keyed by callee LEAF name (cross-file
# call sites resolve by leaf, same as the rest of dflint). Specs:
#   b_arg        positional index of the batch-bucket static dim
#   static_args  positional indexes that are compile-key statics
#   static_kw    keyword names that are compile-key statics
#   donate       positional indexes donated to the device program
# THIS REGISTRY IS THE DESIGN DOCUMENT for the serving signature set:
# the retrace tripwire (retracer.py) derives its runtime-allowed set
# from the same bucket constants these entries are proven against.
SERVING_JIT_REGISTRY: dict[str, dict] = {
    # ops/evaluator.schedule_from_packed(buf, b, k, c, l, n, ...)
    # and registry/serving.MLEvaluator.schedule_from_packed(buf, b, ...)
    "schedule_from_packed": {
        "b_arg": 1,
        "static_args": (1, 2, 3, 4, 5),
        "static_kw": ("limit", "algorithm", "b", "k", "c", "l", "n"),
        "donate": (0,),
    },
    # registry/serving._ml_schedule_from_packed(model, params, host_emb,
    # buf, b, k, c, l, n, limit, ...)
    "_ml_schedule_from_packed": {
        "b_arg": 4,
        "static_args": (4, 5, 6, 7, 8, 9),
        "static_kw": ("limit", "algorithm"),
        "donate": (3,),
    },
    # ops/tick.fused_tick_chunk(inbuf, cols, b, k, c, l, n, algorithm,
    # limit, emit_led, emit_packed): the device-resident fused tick —
    # the staging buffer is donated, every scalar after cols is a
    # compile-key static, and b is the closed-bucket batch dim.
    "fused_tick_chunk": {
        "b_arg": 2,
        "static_args": (2, 3, 4, 5, 6, 7, 8, 9, 10),
        "static_kw": (
            "b", "k", "c", "l", "n", "algorithm", "limit", "emit_led",
            "emit_packed",
        ),
        "donate": (0,),
    },
    # ops/tick._scatter_rows(col, idx, rows, nb): the mirror's donated
    # incremental row scatter — the resident column is donated (callers
    # rebind the attribute to the result) and nb is the bucket-padded
    # update batch size.
    "_scatter_rows": {
        "b_arg": 3,
        "static_args": (3,),
        "static_kw": ("nb",),
        "donate": (0,),
    },
}

# cross-file donating callables: leaf name -> donated positional indexes
# (non-self). Same-file jit defs contribute their decorators' literal
# donate_argnums on top of this seed set.
DONATING_CALLABLES: dict[str, tuple[int, ...]] = {
    leaf: spec["donate"] for leaf, spec in SERVING_JIT_REGISTRY.items()
}


@dataclasses.dataclass
class _Donation:
    name: str
    after_line: int  # reads strictly after this line are suspect
    callee: str
    kills: list[int] = dataclasses.field(default_factory=list)
    # line ranges of sibling if/else branches: a read there is on a
    # mutually-exclusive path and never follows this donation
    exclusions: list[tuple[int, int]] = dataclasses.field(default_factory=list)


class ShapeDonationPass:
    name = "shape-donation"
    rules = ("SHAPE001", "SHAPE002", "DON001")

    def __init__(
        self,
        registry: dict[str, dict] | None = None,
        donating: dict[str, tuple[int, ...]] | None = None,
    ):
        self.registry = SERVING_JIT_REGISTRY if registry is None else registry
        self.donating_seed = (
            DONATING_CALLABLES if donating is None else donating
        )

    # ------------------------------------------------------------- run

    def run(self, ctx: FileContext) -> list[Finding]:
        findings: list[Finding] = []
        jit_funcs = _collect_jit_functions(ctx.tree)
        jit_statics = {f.name: static for f, static in jit_funcs}
        donating = dict(self.donating_seed)
        donating.update(_collect_donating_defs(ctx.tree))
        scopes = list(_functions_with_symbols(ctx.tree))
        functions = {symbol: func for func, symbol, _anc in scopes}
        donating.update(_donation_fixpoint(functions, donating))
        module_consts = _module_constants(ctx.tree)
        # one _Env per actual scope, chained to the enclosing function's
        # env (closure reads fall back outward; a nested helper's locals
        # can never pollute — or launder — the outer classification)
        envs: dict[int, _Env] = {}
        for func, symbol, ancestors in scopes:
            parent = envs.get(id(ancestors[0])) if ancestors else None
            env = _Env(func, module_consts, parent=parent)
            envs[id(func)] = env
            findings.extend(self._check_shapes(ctx, func, symbol, env, jit_statics))
            findings.extend(self._check_donations(ctx, func, symbol, donating))
        return findings

    # ------------------------------------------------------- SHAPE001/2

    def _check_shapes(self, ctx, func, symbol, env, jit_statics) -> list[Finding]:
        findings = []
        for node in _walk_own(func):  # nested defs scan as their own scope
            if not isinstance(node, ast.Call):
                continue
            chain = attr_chain(node.func)
            if chain is None:
                continue
            leaf = chain.rsplit(".", 1)[-1]
            spec = self.registry.get(leaf)
            if spec is not None:
                findings.extend(
                    self._check_registry_call(ctx, func, symbol, env, node, leaf, spec)
                )
            if leaf in jit_statics:
                findings.extend(self._check_static_kwargs(
                    ctx, func, symbol, env, node, leaf, jit_statics[leaf]
                ))
        return findings

    def _check_registry_call(self, ctx, func, symbol, env, node, leaf, spec):
        findings = []
        b_arg = spec.get("b_arg")
        for i, arg in enumerate(node.args):
            if i == b_arg and env.classify(arg) == RUNTIME:
                findings.append(ctx.make_finding(
                    "SHAPE001", arg,
                    (
                        f"runtime-dependent batch dim feeds jitted "
                        f"'{leaf}' — every distinct value is a fresh "
                        f"compile signature; route it through "
                        f"_bucket_rows/_chunk_stride so the compiled set "
                        f"stays closed over _EVAL_BUCKETS"
                    ),
                    symbol=symbol, def_line=func.lineno,
                ))
            elif i == b_arg:
                continue
            elif _is_runtime_slice(arg, env):
                findings.append(ctx.make_finding(
                    "SHAPE001", arg,
                    (
                        f"runtime-length slice passed into jitted "
                        f"'{leaf}' — the sliced length becomes a fresh "
                        f"compile signature; pad to a bucket "
                        f"(_pad_rows/pad_pow2) first"
                    ),
                    symbol=symbol, def_line=func.lineno,
                ))
            elif i in spec.get("static_args", ()) and env.classify(arg) == RUNTIME:
                findings.append(ctx.make_finding(
                    "SHAPE002", arg,
                    (
                        f"runtime-dependent value in static position "
                        f"{i} of jitted '{leaf}' — static args are part "
                        f"of the compile key; each distinct value "
                        f"recompiles"
                    ),
                    symbol=symbol, def_line=func.lineno,
                ))
        for kw in node.keywords:
            if kw.arg in spec.get("static_kw", ()) and \
                    env.classify(kw.value) == RUNTIME:
                findings.append(ctx.make_finding(
                    "SHAPE002", kw.value,
                    (
                        f"runtime-dependent value for static arg "
                        f"'{kw.arg}' of jitted '{leaf}' — each distinct "
                        f"value is a fresh compile"
                    ),
                    symbol=symbol, def_line=func.lineno,
                ))
        return findings

    def _check_static_kwargs(self, ctx, func, symbol, env, node, leaf, statics):
        findings = []
        for kw in node.keywords:
            if kw.arg in statics and env.classify(kw.value) == RUNTIME:
                findings.append(ctx.make_finding(
                    "SHAPE002", kw.value,
                    (
                        f"runtime-dependent value for static_argnames "
                        f"param '{kw.arg}' of jitted '{leaf}' — each "
                        f"distinct value recompiles the program"
                    ),
                    symbol=symbol, def_line=func.lineno,
                ))
        return findings

    # ----------------------------------------------------------- DON001

    def _check_donations(self, ctx, func, symbol, donating) -> list[Finding]:
        branch_ranges = _if_branch_ranges(func)
        stmt_of = _innermost_stmt_map(func)
        loop_ranges = [
            (node.lineno, getattr(node, "end_lineno", node.lineno))
            for node in _walk_own(func)
            if isinstance(node, (ast.For, ast.AsyncFor, ast.While))
        ]
        rebind_lines: dict[str, list[int]] = {}
        for stmt in _walk_statements(func):
            for name in _assigned_names(stmt):
                rebind_lines.setdefault(name, []).append(
                    getattr(stmt, "lineno", 0)
                )
        findings = []
        donations: list[_Donation] = []
        for node in _walk_own(func):
            if not isinstance(node, ast.Call):
                continue
            chain = attr_chain(node.func)
            if chain is None:
                continue
            leaf = chain.rsplit(".", 1)[-1]
            positions = donating.get(leaf)
            stmt = stmt_of.get(id(node))
            if positions is None or stmt is None or isinstance(stmt, ast.Return):
                # a return-statement donation has no reachable
                # same-function code after it on that path
                continue
            targets = _assigned_names(stmt)
            end = getattr(node, "end_lineno", node.lineno)
            exclusions = [
                sibling for here, sibling in branch_ranges
                if here[0] <= node.lineno <= here[1]
            ]
            for pos in positions:
                if pos < len(node.args) and isinstance(node.args[pos], ast.Name):
                    name = node.args[pos].id
                    if name in targets:
                        continue  # rebound by this very statement
                    donations.append(
                        _Donation(name, end, leaf, exclusions=exclusions)
                    )
                    # loop-carried reuse: a donating call inside a loop
                    # whose buffer is bound OUTSIDE the loop re-donates
                    # the dead buffer on the second iteration — the
                    # exact pattern the runtime DonationGuard trips on
                    for lo, hi in loop_ranges:
                        if not (lo <= node.lineno <= hi):
                            continue
                        if any(lo <= r <= hi for r in rebind_lines.get(name, ())):
                            continue  # packed fresh inside this loop
                        findings.append(ctx.make_finding(
                            "DON001", node,
                            (
                                f"'{name}' is donated to '{leaf}' inside "
                                f"a loop but bound outside it — the "
                                f"second iteration re-donates a dead "
                                f"buffer; pack a fresh buffer per "
                                f"iteration"
                            ),
                            symbol=symbol, def_line=func.lineno,
                        ))
                        break
        if not donations:
            return findings
        # kills: any later rebinding of the name ends the donation window
        for stmt in _walk_statements(func):
            names = _assigned_names(stmt)
            line = getattr(stmt, "lineno", 0)
            for don in donations:
                if don.name in names and line > don.after_line:
                    don.kills.append(line)
        reported: set[tuple[str, int]] = set()
        for node in _walk_own(func):
            if not (isinstance(node, ast.Name) and isinstance(node.ctx, ast.Load)):
                continue
            for don in donations:
                if node.id != don.name or node.lineno <= don.after_line:
                    continue
                if any(k <= node.lineno for k in don.kills):
                    continue
                if any(lo <= node.lineno <= hi for lo, hi in don.exclusions):
                    continue  # mutually-exclusive if/else sibling branch
                key = (don.name, node.lineno)
                if key in reported:
                    continue
                reported.add(key)
                findings.append(ctx.make_finding(
                    "DON001", node,
                    (
                        f"read of '{don.name}' after it was donated to "
                        f"'{don.callee}' (donate_argnums) — the buffer "
                        f"may be deallocated or reused by XLA; pack a "
                        f"fresh buffer or read before the donating call"
                    ),
                    symbol=symbol, def_line=func.lineno,
                ))
        return findings


# --------------------------------------------------------------- lattice


class _Env:
    """Per-SCOPE abstract environment: Name -> lattice point, built
    lazily from the scope's own assignments and loop targets (nested
    function bodies are pruned — they get their own env) with closure
    fallback to the enclosing scope's env and a recursion guard
    (self-referential assigns degrade to UNKNOWN)."""

    def __init__(self, func, module_consts: dict[str, str],
                 parent: "_Env | None" = None):
        self.module_consts = module_consts
        self.parent = parent
        # name -> [(line, value expr)] in source order: classification
        # is flow-sensitive (the binding LIVE at the reference line), so
        # a rebinding after the call site cannot retroactively change —
        # or launder — what the call saw
        self.assigns: dict[str, list[tuple[int, ast.AST]]] = {}
        self.loop_buckets: set[str] = set()
        self.loop_runtime: set[str] = set()
        self.params = {
            a.arg for a in (
                func.args.posonlyargs + func.args.args + func.args.kwonlyargs
            )
        }
        for node in _walk_own(func):
            if isinstance(node, ast.Assign) and len(node.targets) == 1 \
                    and isinstance(node.targets[0], ast.Name):
                self.assigns.setdefault(node.targets[0].id, []).append(
                    (node.lineno, node.value)
                )
            elif isinstance(node, (ast.For, ast.AsyncFor)) \
                    and isinstance(node.target, ast.Name):
                src = node.iter
                src_chain = attr_chain(src)
                src_leaf = src_chain.rsplit(".", 1)[-1] if src_chain else None
                if src_leaf in BUCKET_CONSTANTS:
                    self.loop_buckets.add(node.target.id)
                elif _iterates_runtime_range(src):
                    self.loop_runtime.add(node.target.id)
        self._memo: dict[int, str] = {}
        self._stack: set[str] = set()

    def classify(self, node: ast.AST) -> str:
        key = id(node)
        if key in self._memo:
            return self._memo[key]
        out = self._classify(node)
        self._memo[key] = out
        return out

    def _classify(self, node: ast.AST) -> str:  # noqa: C901 - one lattice
        if isinstance(node, ast.Constant):
            return CONST
        if isinstance(node, ast.Name):
            return self._classify_name(node.id, getattr(node, "lineno", 0))
        if isinstance(node, ast.Call):
            chain = attr_chain(node.func)
            leaf = chain.rsplit(".", 1)[-1] if chain else None
            if leaf in BUCKET_PRODUCERS:
                return BUCKET
            if leaf in RUNTIME_PRODUCERS:
                return RUNTIME
            if leaf in ("int", "abs", "min", "max"):
                points = [self.classify(a) for a in node.args]
                if RUNTIME in points:
                    return RUNTIME
                if points and all(p in (CONST, BUCKET) for p in points):
                    return BUCKET if BUCKET in points else CONST
            return UNKNOWN
        if isinstance(node, ast.Attribute):
            chain = attr_chain(node)
            if chain is not None:
                parts = chain.split(".")
                if "config" in parts or parts[0] == "CONSTANTS":
                    return CONST
                if parts[-1].isupper():
                    return CONST
            return UNKNOWN
        if isinstance(node, ast.Subscript):
            src_chain = attr_chain(node.value)
            src_leaf = src_chain.rsplit(".", 1)[-1] if src_chain else None
            if src_leaf in BUCKET_CONSTANTS:
                return BUCKET
            return UNKNOWN
        if isinstance(node, ast.BinOp):
            left, right = self.classify(node.left), self.classify(node.right)
            if RUNTIME in (left, right):
                return RUNTIME
            if left == CONST and right == CONST:
                return CONST
            return UNKNOWN
        if isinstance(node, ast.UnaryOp):
            return self.classify(node.operand)
        if isinstance(node, ast.IfExp):
            points = {self.classify(node.body), self.classify(node.orelse)}
            if RUNTIME in points:
                return RUNTIME
            if points == {BUCKET}:
                return BUCKET
            if points <= {CONST, BUCKET}:
                return BUCKET if BUCKET in points else CONST
            return UNKNOWN
        return UNKNOWN

    def _classify_name(self, name: str, at_line: int) -> str:
        if name in self.loop_buckets:
            return BUCKET
        if name in self.loop_runtime:
            return RUNTIME
        if name in self._stack:
            return UNKNOWN
        binding = self._binding_at(name, at_line)
        if binding is not None:
            self._stack.add(name)
            try:
                return self.classify(binding)
            finally:
                self._stack.discard(name)
        if name in self.params:
            return UNKNOWN  # own param shadows any enclosing binding
        if self.parent is not None:
            return self.parent._classify_name(name, at_line)  # closure read
        if name in BUCKET_CONSTANTS:
            return BUCKET
        if name in self.module_consts:
            return self.module_consts[name]
        if name.isupper():
            return CONST
        return UNKNOWN

    def _binding_at(self, name: str, at_line: int) -> ast.AST | None:
        """The assignment LIVE at a reference line: the latest binding
        at-or-before the line; a reference before any binding falls back
        to the earliest one (loop back-edge reads)."""
        bindings = self.assigns.get(name)
        if not bindings:
            return None
        live = None
        for line, value in bindings:  # collected in source order
            if line <= at_line:
                live = value
        return live if live is not None else bindings[0][1]


def _iterates_runtime_range(src: ast.AST) -> bool:
    """True for ``range(len(...))``-shaped iteration sources."""
    if not isinstance(src, ast.Call):
        return False
    chain = attr_chain(src.func)
    if chain != "range":
        return False
    for arg in src.args:
        for inner in ast.walk(arg):
            if isinstance(inner, ast.Call):
                inner_chain = attr_chain(inner.func)
                if inner_chain and inner_chain.rsplit(".", 1)[-1] in RUNTIME_PRODUCERS:
                    return True
    return False


def _is_runtime_slice(arg: ast.AST, env: _Env) -> bool:
    """``x[a:b]`` where a bound is RUNTIME — a runtime-length array."""
    if not (isinstance(arg, ast.Subscript) and isinstance(arg.slice, ast.Slice)):
        return False
    for bound in (arg.slice.lower, arg.slice.upper):
        if bound is not None and env.classify(bound) == RUNTIME:
            return True
    return False


# -------------------------------------------------------------- helpers


def _module_constants(tree) -> dict[str, str]:
    out: dict[str, str] = {}
    for node in tree.body:
        if isinstance(node, ast.Assign) and len(node.targets) == 1 \
                and isinstance(node.targets[0], ast.Name):
            name = node.targets[0].id
            if name in BUCKET_CONSTANTS:
                out[name] = BUCKET
            elif name.isupper() and isinstance(node.value, ast.Constant):
                out[name] = CONST
    return out


def _collect_donating_defs(tree) -> dict[str, tuple[int, ...]]:
    """leaf name -> donate_argnums for same-file jit defs carrying a
    literal ``donate_argnums`` in their decorator."""
    out: dict[str, tuple[int, ...]] = {}
    for node in ast.walk(tree):
        if not isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        for dec in node.decorator_list:
            if not isinstance(dec, ast.Call):
                continue
            for kw in dec.keywords:
                if kw.arg != "donate_argnums":
                    continue
                nums = _literal_int_tuple(kw.value)
                if nums:
                    out[node.name] = nums
    return out


def _literal_int_tuple(node: ast.AST) -> tuple[int, ...]:
    if isinstance(node, ast.Constant) and isinstance(node.value, int):
        return (node.value,)
    if isinstance(node, (ast.Tuple, ast.List)):
        nums = []
        for elt in node.elts:
            if isinstance(elt, ast.Constant) and isinstance(elt.value, int):
                nums.append(elt.value)
            else:
                return ()
        return tuple(nums)
    return ()


def _donation_fixpoint(
    functions: dict[str, ast.AST], donating: dict[str, tuple[int, ...]]
) -> dict[str, tuple[int, ...]]:
    """Functions that forward a parameter into a donated position of a
    known donating callee donate that parameter themselves — iterated to
    fixpoint so chains of forwarding layers are all covered. Parameter
    indexes are non-self (call sites never pass self)."""
    known = dict(donating)
    for _ in range(len(functions) + 1):
        changed = False
        for qualname, func in functions.items():
            leaf = qualname.rsplit(".", 1)[-1]
            params = [
                a.arg for a in (
                    func.args.posonlyargs + func.args.args + func.args.kwonlyargs
                )
                if a.arg != "self"
            ]
            current = set(known.get(leaf, ()))
            for node in ast.walk(func):
                if not isinstance(node, ast.Call):
                    continue
                chain = attr_chain(node.func)
                if chain is None:
                    continue
                callee = chain.rsplit(".", 1)[-1]
                for pos in known.get(callee, ()):
                    if pos < len(node.args) and isinstance(node.args[pos], ast.Name):
                        name = node.args[pos].id
                        if name in params:
                            current.add(params.index(name))
            if current != set(known.get(leaf, ())):
                known[leaf] = tuple(sorted(current))
                changed = True
        if not changed:
            break
    return known


def _innermost_stmt_map(func) -> dict[int, ast.stmt]:
    """id(expr node) -> the innermost statement containing it (nested
    function bodies pruned — they map within their own scope)."""
    out: dict[int, ast.stmt] = {}

    def visit(stmt: ast.stmt) -> None:
        if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
            return
        for child in ast.iter_child_nodes(stmt):
            if isinstance(child, ast.stmt):
                visit(child)
            else:
                for node in ast.walk(child):
                    if isinstance(node, ast.stmt):
                        continue  # claimed by its own statement visit
                    out.setdefault(id(node), stmt)

    for child in ast.iter_child_nodes(func):
        if isinstance(child, ast.stmt):
            visit(child)
    return out


def _if_branch_ranges(func) -> list[tuple[tuple[int, int], tuple[int, int]]]:
    """For every if/else in `func` (own scope): ((body line range),
    (orelse range)) and the mirror pair — used to exempt reads on the
    mutually-exclusive sibling branch of a donating call."""
    pairs = []
    for node in _walk_own(func):
        if not isinstance(node, ast.If) or not node.orelse:
            continue
        body = _stmt_range(node.body)
        orelse = _stmt_range(node.orelse)
        pairs.append((body, orelse))
        pairs.append((orelse, body))
    return pairs


def _stmt_range(stmts: list[ast.stmt]) -> tuple[int, int]:
    first = stmts[0].lineno
    last = max(getattr(s, "end_lineno", s.lineno) for s in stmts)
    return first, last


def _walk_statements(func):
    for node in _walk_own(func):
        if isinstance(node, ast.stmt):
            yield node


def _assigned_names(stmt: ast.AST) -> set[str]:
    names: set[str] = set()
    if isinstance(stmt, ast.Assign):
        targets = stmt.targets
    elif isinstance(stmt, (ast.AugAssign, ast.AnnAssign)):
        targets = [stmt.target]
    elif isinstance(stmt, (ast.For, ast.AsyncFor)):
        targets = [stmt.target]
    elif isinstance(stmt, ast.Delete):
        targets = stmt.targets
    else:
        return names
    for target in targets:
        for node in ast.walk(target):
            if isinstance(node, ast.Name):
                names.add(node.id)
    return names
