"""Telemetry: metrics exposition + span tracing (SURVEY.md §5)."""

import json
import urllib.request

from dragonfly2_tpu.telemetry import metrics as m
from dragonfly2_tpu.telemetry import tracing


def test_counter_gauge_histogram_expose():
    reg = m.Registry()
    c = reg.counter(
        "dragonfly_scheduler_announce_peer_total", "announce totals", ("priority",)
    )
    c.labels("LEVEL0").inc()
    c.labels("LEVEL0").inc(2)
    g = reg.gauge("dragonfly_scheduler_concurrent_schedule", "gauge")
    g.set(7)
    g.inc()
    h = reg.histogram(
        "dragonfly_scheduler_download_duration_seconds", buckets=(0.1, 1.0, 10.0)
    )
    h.observe(0.05)
    h.observe(0.5)
    h.observe(100.0)

    text = reg.expose()
    assert 'dragonfly_scheduler_announce_peer_total{priority="LEVEL0"} 3.0' in text
    assert "dragonfly_scheduler_concurrent_schedule 8.0" in text
    assert 'le="+Inf"} 3' in text
    assert "download_duration_seconds_count 3" in text
    assert "# TYPE dragonfly_scheduler_download_duration_seconds histogram" in text
    assert c.value("LEVEL0") == 3.0


def test_registry_dedup_and_timer():
    reg = m.Registry()
    a = reg.counter("x_total")
    b = reg.counter("x_total")
    assert a is b
    h = reg.histogram("t_seconds", buckets=(10.0,))
    with m.Timer(h.labels()):
        pass
    assert "t_seconds_count 1" in reg.expose()


def test_registry_rejects_type_and_label_conflicts():
    import pytest

    reg = m.Registry()
    reg.counter("x_total")
    with pytest.raises(ValueError):
        reg.gauge("x_total")
    with pytest.raises(ValueError):
        reg.counter("x_total", labels=("a",))


def test_labeled_gauge_dec_and_label_escaping():
    reg = m.Registry()
    g = reg.gauge("concurrent", labels=("host",))
    g.labels("h1").inc(3)
    g.labels("h1").dec()
    assert g.value("h1") == 2.0
    c = reg.counter("nl", labels=("v",))
    c.labels("line1\nline2").inc()
    text = reg.expose()
    assert 'nl{v="line1\\nline2"} 1.0' in text


def test_metrics_http_server():
    reg = m.Registry()
    reg.counter("served_total").inc()
    server = m.serve_metrics(reg, port=0)
    try:
        port = server.server_address[1]
        body = urllib.request.urlopen(f"http://127.0.0.1:{port}/metrics").read().decode()
        assert "served_total 1.0" in body
    finally:
        server.shutdown()


def test_tracing_nesting_and_export(tmp_path):
    tracer = tracing.Tracer("scheduler")
    spans = tracer.export_to_memory()
    path = tmp_path / "spans.jsonl"
    tracer.export_to_file(path)

    with tracer.span("announce_peer", peer_id="p1") as outer:
        with tracer.span("schedule_tick") as inner:
            inner.add_event("batched", size=32)
        assert tracing.current_span() is outer
    assert tracing.current_span() is None

    assert [s.name for s in spans] == ["schedule_tick", "announce_peer"]
    child, parent = spans
    assert child.trace_id == parent.trace_id
    assert child.parent_id == parent.span_id
    assert parent.attributes["peer_id"] == "p1"
    assert parent.duration_ms() is not None
    lines = [json.loads(l) for l in path.read_text().splitlines()]
    assert len(lines) == 2 and lines[1]["name"] == "announce_peer"


def test_tracing_error_status():
    tracer = tracing.Tracer()
    spans = tracer.export_to_memory()
    try:
        with tracer.span("failing"):
            raise RuntimeError("boom")
    except RuntimeError:
        pass
    assert spans[0].status == "ERROR"
    assert spans[0].events[0]["type"] == "RuntimeError"
