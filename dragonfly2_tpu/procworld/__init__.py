"""procworld — the real-process planet harness (ISSUE 18).

One supervised multi-process deployment of the actual services
(schedulers, dfdaemons, manager) over real sockets, with process-level
chaos the simulator cannot express (SIGKILL mid-download, SIGSTOP
partitions, rolling restarts of real processes), reduced to the SAME
timeline/SLO artifact the megascale simulator emits — so dfslo replays
it unchanged and the divergence report compares sim and real
like-for-like.
"""

from dragonfly2_tpu.procworld.divergence import (
    DEFAULT_BANDS,
    compute_divergence,
    publish_divergence,
)
from dragonfly2_tpu.procworld.origin import OriginServer
from dragonfly2_tpu.procworld.planet import real_facts, run_procday
from dragonfly2_tpu.procworld.sample import (
    RoundObservation,
    announce_page_rounds,
    build_sample,
    quantile,
    synthesize_timeline,
)
from dragonfly2_tpu.procworld.supervisor import (
    ManagedProc,
    ProcessPlanet,
    spawn_cmd,
    stop_proc,
    wait_for,
)

__all__ = [
    "DEFAULT_BANDS",
    "ManagedProc",
    "OriginServer",
    "ProcessPlanet",
    "RoundObservation",
    "announce_page_rounds",
    "build_sample",
    "compute_divergence",
    "publish_divergence",
    "quantile",
    "real_facts",
    "run_procday",
    "spawn_cmd",
    "stop_proc",
    "synthesize_timeline",
    "wait_for",
]
