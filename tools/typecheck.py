#!/usr/bin/env python
"""Run the checked-in mypy config over the strict core subset.

Usage: ``python tools/typecheck.py [--strict-subset] [extra mypy args]``

The container this repo builds in does not ship mypy (and the build
constraint forbids installing packages), so the runner GATES instead of
failing: without mypy it prints the subset it would check and exits 0
with a SKIPPED marker. On a rig with mypy (``pip install mypy`` on a dev
box), it runs ``mypy --config-file mypy.ini`` and propagates the exit
code — tests/test_static_analysis.py invokes it and skips on the
SKIPPED marker, so a mypy-equipped CI automatically tightens the gate.
"""

from __future__ import annotations

import configparser
import importlib.util
import subprocess
import sys
from pathlib import Path

ROOT = Path(__file__).resolve().parents[1]
CONFIG = ROOT / "mypy.ini"

SKIP_MARKER = "TYPECHECK SKIPPED: mypy not installed in this rig"


def subset() -> list[str]:
    parser = configparser.ConfigParser()
    parser.read(CONFIG)
    files = parser.get("mypy", "files", fallback="")
    return [part.strip() for part in files.split(",") if part.strip()]


def main(argv: list[str]) -> int:
    if "--strict-subset" in argv:
        print("\n".join(subset()))
        return 0
    if importlib.util.find_spec("mypy") is None:
        print(SKIP_MARKER)
        print("would check: " + ", ".join(subset()))
        return 0
    cmd = [
        sys.executable, "-m", "mypy",
        "--config-file", str(CONFIG),
        *[a for a in argv if a != "--strict-subset"],
    ]
    return subprocess.call(cmd, cwd=ROOT)


if __name__ == "__main__":
    sys.exit(main(sys.argv[1:]))
