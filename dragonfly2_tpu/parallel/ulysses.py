"""Ulysses (all-to-all) sequence parallelism — the second long-context
strategy, alongside ring attention (parallel/ring.py).

The reference has no sequence models (SURVEY.md §5); this is new TPU-first
capability. Where ring attention keeps queries resident and rotates KV
shards hop-by-hop around the ICI ring (sp all-reduce-ish traffic, best
when L is huge and heads are few), Ulysses re-shards with two
`lax.all_to_all`s: heads scatter across the `sp` axis while the sequence
gathers, every device runs *exact* full-sequence attention over H/sp
heads, then the inverse all_to_all restores the sequence sharding. Two
collective hops total, best when H >= sp and the per-device full sequence
fits HBM — and the local attend is free to use the fused pallas kernel
(ops/flash.py).

Layouts match ring.py: q/k/v [B, H, L, D] with L sharded over `sp` inside
shard_map, kv_mask [B, L] key validity. dense_attention is the parity
oracle; both strategies are numerically interchangeable with it.
"""

from __future__ import annotations

import functools
from typing import Callable

import jax

from dragonfly2_tpu.utils.jaxcompat import shard_map
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from dragonfly2_tpu.parallel.mesh import DP_AXIS, SP_AXIS
from dragonfly2_tpu.parallel.ring import dense_attention


def ulysses_attention(
    q,
    k,
    v,
    kv_mask,
    axis_name: str = SP_AXIS,
    inner: Callable = dense_attention,
    causal: bool = False,
) -> jax.Array:
    """Inside shard_map: [B, H, L/sp, D] shards -> exact attention.

    all_to_all #1: scatter heads (axis 1), gather sequence (axis 2) ->
    each device holds [B, H/sp, L, D]. Local `inner` attends the full
    sequence for its head group. all_to_all #2 inverts the exchange.
    Requires H % sp == 0."""
    sp = jax.lax.psum(1, axis_name)
    heads = q.shape[1]
    if heads % sp:
        raise ValueError(f"num_heads={heads} must be divisible by sp={sp}")

    def scatter_heads(t):  # [B, H, Ls, D] -> [B, H/sp, L, D]
        return jax.lax.all_to_all(t, axis_name, split_axis=1, concat_axis=2, tiled=True)

    def gather_heads(t):  # [B, H/sp, L, D] -> [B, H, Ls, D]
        return jax.lax.all_to_all(t, axis_name, split_axis=2, concat_axis=1, tiled=True)

    qg, kg, vg = scatter_heads(q), scatter_heads(k), scatter_heads(v)
    # every device needs the full-sequence key mask for its head group
    mask_full = jax.lax.all_gather(kv_mask, axis_name, axis=1, tiled=True)
    out = inner(qg, kg, vg, mask_full, causal=causal)
    return gather_heads(out)


def sharded_ulysses_attention(
    mesh, q, k, v, kv_mask, inner: Callable = dense_attention, causal: bool = False
) -> jax.Array:
    """shard_map wrapper: batch over `dp`, sequence over `sp` — the same
    global-shapes-in/out contract as ring.sharded_ring_attention, so the
    two strategies are drop-in swaps for each other."""
    qkv_spec = P(DP_AXIS, None, SP_AXIS, None)
    mask_spec = P(DP_AXIS, SP_AXIS)
    fn = shard_map(
        functools.partial(
            ulysses_attention, axis_name=SP_AXIS, inner=inner, causal=causal
        ),
        mesh=mesh,
        in_specs=(qkv_spec, qkv_spec, qkv_spec, mask_spec),
        out_specs=qkv_spec,
        check_vma=False,
    )
    return fn(q, k, v, kv_mask)
