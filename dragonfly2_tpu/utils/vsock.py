"""AF_VSOCK transport helpers — VM-guest addressing for the cluster edge.

Capability parity with pkg/rpc/vsock.go (`VsockDialer` parsing
`vsock://<cid>:<port>` targets + `IsVsock`) and pkg/dfnet's VSOCK network
type: a guest VM reaches the host daemon over a vsock instead of TCP.
Helpers return plain sockets / asyncio streams so every existing wire
server and client can ride them — including TLS: both ends accept an
`ssl_context`, so `--tls-dir` clusters keep mutual auth on the vsock
listener too (a plaintext side door would negate the mTLS boundary).
AF_VSOCK needs kernel support, so `available()` gates tests and callers
degrade with a clear error rather than an AttributeError on platforms
without it.
"""

from __future__ import annotations

import asyncio
import socket
import urllib.parse

VSOCK_SCHEME = "vsock"

# socket.VMADDR_CID_* only exist where the platform defines AF_VSOCK
VMADDR_CID_ANY = getattr(socket, "VMADDR_CID_ANY", -1)
VMADDR_CID_LOCAL = getattr(socket, "VMADDR_CID_LOCAL", 1)

# TLS-over-vsock has no DNS name; contexts are built with
# check_hostname=False (utils/certs.py client_context), and asyncio just
# needs a non-empty server_hostname to satisfy the SSL plumbing.
_TLS_PSEUDO_HOSTNAME = "vsock"


def available() -> bool:
    return hasattr(socket, "AF_VSOCK")


def is_vsock(target: str) -> bool:
    """pkg/rpc/vsock.go IsVsock: does the target use the vsock scheme?"""
    return target.startswith(f"{VSOCK_SCHEME}://")


def parse_target(target: str) -> tuple[int, int]:
    """`vsock://<cid>:<port>` -> (cid, port) (VsockDialer's parse).

    Parsed by hand rather than urlsplit().port: AF_VSOCK ports are 32-bit,
    and urllib enforces the TCP 0-65535 range."""
    u = urllib.parse.urlsplit(target)
    if u.scheme != VSOCK_SCHEME or not u.netloc:
        raise ValueError(f"vsock target must be vsock://<cid>:<port>, got {target!r}")
    cid_s, sep, port_s = u.netloc.partition(":")
    if not sep or not cid_s.isdigit() or not port_s.isdigit():
        raise ValueError(f"vsock target must be vsock://<cid>:<port>, got {target!r}")
    return int(cid_s), int(port_s)


def listen_socket(port: int, cid: int = VMADDR_CID_ANY) -> socket.socket:
    """Bound+listening AF_VSOCK socket, ready for asyncio.start_server(sock=...)."""
    if not available():
        raise RuntimeError("AF_VSOCK is not supported on this platform")
    sock = socket.socket(socket.AF_VSOCK, socket.SOCK_STREAM)  # type: ignore[attr-defined]
    try:
        sock.bind((cid, port))
        sock.listen()
        sock.setblocking(False)
    except OSError:
        sock.close()
        raise
    return sock


async def start_server(handler, port: int, cid: int = VMADDR_CID_ANY, ssl_context=None):
    """asyncio server speaking the wire protocol over a vsock listener;
    `handler` is any `async (reader, writer)` (e.g. a ConnTracker-wrapped
    SchedulerRPCServer._serve_conn). `ssl_context` applies the same mTLS
    the TCP listener enforces."""
    return await asyncio.start_server(
        handler, sock=listen_socket(port, cid), ssl=ssl_context
    )


async def open_connection(target: str, ssl_context=None):
    """Dial a `vsock://<cid>:<port>` target -> (reader, writer)
    (VsockDialer + grpc.WithContextDialer equivalent). With `ssl_context`
    the stream is wrapped in TLS after connect, so mutual-auth clusters
    keep their boundary over vsock too."""
    cid, port = parse_target(target)
    if not available():
        raise RuntimeError("AF_VSOCK is not supported on this platform")
    sock = socket.socket(socket.AF_VSOCK, socket.SOCK_STREAM)  # type: ignore[attr-defined]
    sock.setblocking(False)
    loop = asyncio.get_running_loop()
    try:
        await loop.sock_connect(sock, (cid, port))
    except BaseException:
        # sock_connect failure (scheduler down, CancelledError) must not
        # leak one fd per retry of the pool's reconnect loop
        sock.close()
        raise
    kwargs = {}
    if ssl_context is not None:
        kwargs = {"ssl": ssl_context, "server_hostname": _TLS_PSEUDO_HOSTNAME}
    try:
        return await asyncio.open_connection(sock=sock, **kwargs)
    except BaseException:
        sock.close()
        raise
