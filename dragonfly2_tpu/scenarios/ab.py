"""Scenario-matrix A/B harness: {default, ml, random[, nt]} × scenarios.

Round 5's headline (`ml_vs_default = 1.001`) was measured on a
homogeneous cluster where no evaluator has anything to exploit. This
harness runs each evaluator across a grid of structured scenarios
(scenarios/spec.builtin_scenarios) with PAIRED seeds — every arm of one
(scenario, seed) cell sees the identical host population, task set,
arrival order, and injected fault schedule — and reports per-scenario
`ml_vs_default` cost ratios with small-sample confidence intervals. The
output answers *where* the learned evaluator wins, loses, or needs
retraining, instead of one break-even number.

The ml arm's model is trained ONCE, on traces a scenario-driven replay
produced (schedule → Download/NetworkTopology CSV → announcer → trainer
→ registry → served MLEvaluator — the full loop), then evaluated across
every scenario: exactly the generalization question a production
scheduler faces.

Determinism: arms never touch the wall clock for decisions — fault
schedules are counter-hashed (scenarios/engine), the scheduler rng is
seeded, and interval GC is left un-driven (TTL sweeps key off
time.time(), which would make results depend on machine load). Re-running
`run_matrix` with the same config bit-reproduces everything outside the
`timing` sub-objects; `deterministic_view` strips those for comparison.

`bench_scenarios.py` is the CLI; `BENCH_scenarios.json` the artifact.
"""

from __future__ import annotations

import dataclasses
import math
import statistics
import tempfile
import time

import numpy as np

from dragonfly2_tpu.scenarios.spec import ScenarioSpec, builtin_scenarios

# two-sided 95% Student-t critical values by degrees of freedom
_T95 = {1: 12.706, 2: 4.303, 3: 3.182, 4: 2.776, 5: 2.571, 6: 2.447,
        7: 2.365, 8: 2.306, 9: 2.262, 10: 2.228}


@dataclasses.dataclass
class MatrixConfig:
    hosts: int = 600
    tasks: int = 24
    target_pieces: int = 8000
    downloads_per_round: int = 32
    seeds: tuple = (11, 12, 13)
    evaluators: tuple = ("default", "ml", "random")
    probe_every: int = 20
    max_rounds: int = 20_000
    # ml arm: one model trained on scenario-heterogeneous traces
    train_scenario: str = "bandwidth_skew"
    train_pieces: int = 12_000
    train_seed: int = 1009
    trainer_epochs: int = 3
    trainer_batch: int = 512
    hidden_dim: int = 32
    refresh_every: int = 10  # rounds between serving-graph embed refreshes


class _RandomScores:
    """Anchor arm: uniform-random candidate scores through the plugin
    path — seeded, so the anchor is as reproducible as the rest."""

    def __init__(self, seed: int):
        self.rng = np.random.default_rng(seed)

    def evaluate(self, fd: dict) -> np.ndarray:
        return self.rng.random(fd["valid"].shape).astype(np.float32)


def _scheduler_config(cfg: MatrixConfig, algorithm: str):
    from dragonfly2_tpu.config.config import Config

    config = Config()
    config.evaluator.algorithm = algorithm
    config.scheduler.max_hosts = max(1024, 1 << (cfg.hosts - 1).bit_length())
    config.scheduler.max_tasks = max(256, 2 * cfg.tasks)
    # hotspot scenarios concentrate a large share of downloads on one
    # task; the per-task DAG must hold the deep swarm
    config.scheduler.max_peers_per_task = 1024
    return config


def _run_arm(
    spec: ScenarioSpec, evaluator: str, seed: int, cfg: MatrixConfig, server,
) -> dict:
    """One (scenario, evaluator, seed) cell: fresh service + simulator,
    replay to the piece target, report costs + injected-event counts +
    the flight-recorder phase breakdown."""
    from dragonfly2_tpu.cluster.probes import ProbeStore, warm_from_link_model
    from dragonfly2_tpu.cluster.scheduler import SchedulerService
    from dragonfly2_tpu.cluster.simulator import ClusterSimulator
    from dragonfly2_tpu.registry import MLEvaluator

    algorithm = {"ml": "ml", "nt": "nt"}.get(evaluator, "default")
    config = _scheduler_config(cfg, algorithm)
    # EVERY arm gets a probe store, not just nt: run_probe_round consumes
    # draws from the simulator's shared seeded rng only when a store is
    # attached, so a probe-less arm would diverge in download arrival
    # order from its paired siblings after the first probe round — and
    # the per-seed ratios would no longer compare identical replays.
    # Only the nt algorithm ever READS the store (scheduler tick gates on
    # algorithm == "nt"), so the other arms' scheduling is unchanged.
    probes = ProbeStore(max_pairs=1 << 15, max_hosts=config.scheduler.max_hosts)
    ml = MLEvaluator(server) if evaluator == "ml" else None
    svc = SchedulerService(config=config, probes=probes, ml_evaluator=ml, seed=seed)
    if evaluator == "random":
        svc.plugin_evaluator = _RandomScores(seed)
    sim = ClusterSimulator(
        svc, num_hosts=cfg.hosts, num_tasks=cfg.tasks, seed=seed, scenario=spec
    )
    if evaluator == "nt":
        # cold-store warmup so the nt arm measures the algorithm, not
        # probe-coverage ramp; deterministic (counter-hashed jitter, no
        # rng draws), so it cannot skew the pairing above
        slotted = [
            (h, svc.state.host_index(h.id))
            for h in sim.cluster.hosts
            if svc.state.host_index(h.id) is not None
        ]
        warm_from_link_model(probes, slotted, sim.engine.rtt_ns)

    refresh_s = 0.0
    if ml is not None:
        def _refresh() -> None:
            nonlocal refresh_s
            t = time.perf_counter()
            # wait=True: the matrix is a DETERMINISM-pinned artifact —
            # every arm of a (scenario, seed) cell must see embeddings
            # commit at the same round on every run, which the background
            # worker's timing cannot guarantee. The async path is
            # exercised by bench_loop and the refresh/serve race test.
            ml.refresh_embeddings(svc.serving_graph_arrays(), wait=True)
            refresh_s += time.perf_counter() - t

        _refresh()  # edge-less warm refresh: ml serves from round 1

    t0 = time.perf_counter()
    rounds = 0
    while sim.stats.pieces < cfg.target_pieces and rounds < cfg.max_rounds:
        sim.run_round(cfg.downloads_per_round)
        rounds += 1
        if rounds % cfg.probe_every == 0:
            sim.run_probe_round(sources=8)
        if ml is not None and rounds % cfg.refresh_every == 0:
            _refresh()
    wall = time.perf_counter() - t0

    st = sim.stats
    return {
        "pieces": st.pieces,
        "completed": st.completed,
        "back_to_source": st.back_to_source,
        "back_to_source_starved": st.back_to_source_starved,
        "back_to_source_with_parents": st.back_to_source_with_parents,
        "mean_piece_cost_ms": round(
            st.piece_cost_ns_total / max(st.pieces, 1) / 1e6, 4
        ),
        "injected": {
            "piece_failures": st.injected_piece_failures,
            "stalls": st.injected_stalls,
            "crashes": st.injected_crashes,
            "host_leaves": st.injected_host_leaves,
            "scheduler_crashes": st.injected_scheduler_crashes,
            "crash_reannounced_peers": st.crash_reannounced_peers,
            "partition_drops": st.injected_partition_drops,
        },
        "retry_waves": st.retry_waves,
        "rounds": rounds,
        "schedule_digest": sim.engine.schedule_digest(),
        # decision provenance counters (telemetry/decisions.py) —
        # deterministic (counts only), so they ride the pinned view;
        # the ml arm's shadow is the rule blend, so shadow_compared > 0
        # there once a snapshot serves
        "decisions": (
            svc.decisions.counters() if svc.decisions is not None else None
        ),
        # everything wall-clock-dependent lives under `timing` so the
        # determinism check can strip it in one pass
        "timing": {
            "wall_s": round(wall, 2),
            "pieces_per_sec": round(st.pieces / max(wall, 1e-9), 1),
            "phases_p50_ms": svc.recorder.phase_p50s(),
            **({"embed_refresh_s": round(refresh_s, 2)} if refresh_s else {}),
        },
    }


def train_model(cfg: MatrixConfig, workdir: str, scenarios: dict[str, ScenarioSpec]):
    """Train + serve the GNN ranker from traces a scenario replay wrote:
    schedule → CSV traces (+ topology snapshot from scenario-modeled
    probes) → announcer upload → trainer → registry → ModelServer.
    Returns (server, info)."""
    import jax

    from dragonfly2_tpu.cluster.announcer import Announcer
    from dragonfly2_tpu.cluster.probes import ProbeStore
    from dragonfly2_tpu.cluster.scheduler import SchedulerService
    from dragonfly2_tpu.cluster.simulator import ClusterSimulator
    from dragonfly2_tpu.cluster.trainer_service import GNN_MODEL_NAME, TrainerService
    from dragonfly2_tpu.config.config import TrainerConfig
    from dragonfly2_tpu.models import GraphSAGERanker
    from dragonfly2_tpu.records.storage import HostTraceStorage, TraceStorage
    from dragonfly2_tpu.registry import ModelRegistry, ModelServer
    from dragonfly2_tpu.registry.registry import MODEL_TYPE_GNN

    spec = scenarios.get(cfg.train_scenario) or builtin_scenarios()[cfg.train_scenario]
    config = _scheduler_config(cfg, "default")
    storage = TraceStorage(f"{workdir}/traces")
    probes = ProbeStore(max_pairs=1 << 15, max_hosts=config.scheduler.max_hosts)
    svc = SchedulerService(config=config, storage=storage, probes=probes,
                           seed=cfg.train_seed)
    sim = ClusterSimulator(
        svc, num_hosts=cfg.hosts, num_tasks=cfg.tasks,
        seed=cfg.train_seed, scenario=spec,
    )
    t0 = time.perf_counter()
    rounds = 0
    while sim.stats.pieces < cfg.train_pieces and rounds < cfg.max_rounds:
        sim.run_round(cfg.downloads_per_round)
        rounds += 1
        if rounds % cfg.probe_every == 0:
            sim.run_probe_round(sources=8)
    svc.snapshot_topology(now_ns=1)

    registry = ModelRegistry(f"{workdir}/registry")
    tcfg = TrainerConfig(
        epochs=cfg.trainer_epochs, batch_size=cfg.trainer_batch,
        hidden_dim=cfg.hidden_dim,
    )
    trainer = TrainerService(
        HostTraceStorage(f"{workdir}/trainer-data"), registry, tcfg
    )
    announcer = Announcer("ab-sched", storage, trainer, interval_seconds=0)
    if not announcer.maybe_announce():
        raise RuntimeError("scenario trace announce+train failed")
    active = registry.active_version(registry.model_id(GNN_MODEL_NAME, "ab-sched"))
    if active is None:
        raise RuntimeError("no active GNN version after scenario training")

    feat_dim = svc.state.host_numeric.shape[1]
    model = GraphSAGERanker(hidden_dim=cfg.hidden_dim)
    template = model.init(
        jax.random.key(0),
        {
            "node_feats": np.zeros((4, feat_dim), np.float32),
            "edge_src": np.zeros(2, np.int32),
            "edge_dst": np.zeros(2, np.int32),
            "edge_feats": np.zeros((2, 2), np.float32),
        },
        np.zeros(1, np.int32), np.zeros((1, 2), np.int32),
        np.zeros((1, 2, 2), np.float32),
    )
    server = ModelServer(registry, GNN_MODEL_NAME, "ab-sched", MODEL_TYPE_GNN, template)
    if not server.refresh():
        raise RuntimeError("model server refresh failed")
    info = {
        "train_scenario": cfg.train_scenario,
        "train_pieces": sim.stats.pieces,
        "precision": round(active.evaluation.precision, 4),
        "recall": round(active.evaluation.recall, 4),
        "f1": round(active.evaluation.f1_score, 4),
        "hidden_dim": cfg.hidden_dim,
        "timing": {"train_wall_s": round(time.perf_counter() - t0, 2)},
    }
    return server, info


def _ratio_stats(numerator: list[float], denominator: list[float]) -> dict:
    """Paired per-seed ratios + mean + 95% t-CI. `resolvable` = the CI
    excludes 1.0 — the gap is statistically distinguishable from a tie at
    this replicate count (in either direction; a real measurement, not a
    guaranteed win)."""
    ratios = [n / max(d, 1e-12) for n, d in zip(numerator, denominator)]
    mean = statistics.fmean(ratios)
    out = {"per_seed": [round(r, 4) for r in ratios], "mean": round(mean, 4)}
    if len(ratios) >= 2:
        sd = statistics.stdev(ratios)
        half = _T95.get(len(ratios) - 1, 1.96) * sd / math.sqrt(len(ratios))
        lo, hi = mean - half, mean + half
        out["ci95"] = [round(lo, 4), round(hi, 4)]
        out["resolvable"] = bool(lo > 1.0 or hi < 1.0)
    else:
        out["ci95"] = [round(mean, 4), round(mean, 4)]
        out["resolvable"] = False
    return out


def run_matrix(
    scenarios: dict[str, ScenarioSpec] | None = None,
    cfg: MatrixConfig | None = None,
    workdir: str | None = None,
    log=None,
) -> dict:
    """Run the scenario × evaluator × seed grid; returns the artifact
    dict (see module docstring). `log` (optional callable) receives one
    progress line per completed arm."""
    cfg = cfg or MatrixConfig()
    scenarios = scenarios or builtin_scenarios()
    workdir = workdir or tempfile.mkdtemp(prefix="bench-scenarios-")
    log = log or (lambda _line: None)

    server, model_info = None, None
    if "ml" in cfg.evaluators:
        server, model_info = train_model(cfg, workdir, scenarios)
        log(f"trained ml model on {cfg.train_scenario}: "
            f"precision={model_info['precision']} recall={model_info['recall']}")

    out_scenarios: dict[str, dict] = {}
    for name, spec in scenarios.items():
        arms: dict[str, dict] = {}
        for evaluator in cfg.evaluators:
            per_seed = {}
            for seed in cfg.seeds:
                result = _run_arm(spec, evaluator, seed, cfg, server)
                per_seed[str(seed)] = result
                log(f"{name}/{evaluator}/seed={seed}: "
                    f"cost={result['mean_piece_cost_ms']}ms "
                    f"pieces={result['pieces']} "
                    f"wall={result['timing']['wall_s']}s")
            arms[evaluator] = {"seeds": per_seed}

        def _costs(evaluator: str) -> list[float]:
            return [
                arms[evaluator]["seeds"][str(s)]["mean_piece_cost_ms"]
                for s in cfg.seeds
            ]

        summary: dict = {
            "spec": spec.to_dict(),
            "arms": arms,
            "mean_piece_cost_ms": {
                ev: round(statistics.fmean(_costs(ev)), 4) for ev in cfg.evaluators
            },
        }
        # ratio > 1 means the left evaluator picks CHEAPER parents than
        # the right one (cost of right / cost of left), matching
        # bench_loop's ml_vs_default orientation
        if "ml" in cfg.evaluators and "default" in cfg.evaluators:
            summary["ml_vs_default"] = _ratio_stats(_costs("default"), _costs("ml"))
        if "default" in cfg.evaluators and "random" in cfg.evaluators:
            summary["default_vs_random"] = _ratio_stats(_costs("random"), _costs("default"))
        if "nt" in cfg.evaluators and "default" in cfg.evaluators:
            summary["nt_vs_default"] = _ratio_stats(_costs("default"), _costs("nt"))
        out_scenarios[name] = summary

    return {
        "config": dataclasses.asdict(cfg),
        "model": model_info,
        "scenarios": out_scenarios,
    }


def deterministic_view(result: dict):
    """The artifact minus every wall-clock-dependent field: recursively
    drops `timing` sub-objects. Two runs of the same (config, scenarios)
    must compare equal under this view — the determinism contract
    tests/test_scenarios.py pins."""
    if isinstance(result, dict):
        return {
            k: deterministic_view(v) for k, v in result.items() if k != "timing"
        }
    if isinstance(result, list):
        return [deterministic_view(v) for v in result]
    return result
