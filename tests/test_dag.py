"""DAG engine tests (reference: pkg/graph/dag/dag_test.go behaviors) plus
differential host-vs-device checks for the batched kernels."""

import numpy as np
import pytest

from dragonfly2_tpu.graph import TaskDAG, DAGError, batch_can_add_edge, batch_reachable


def test_add_edge_and_degrees():
    g = TaskDAG(64)
    for v in (0, 1, 2):
        g.add_vertex(v)
    g.add_edge(0, 1)
    g.add_edge(1, 2)
    assert g.has_edge(0, 1) and g.has_edge(1, 2)
    assert g.in_degree[1] == 1 and g.in_degree[2] == 1 and g.in_degree[0] == 0
    assert g.out_degree[0] == 1 and g.out_degree[2] == 0
    assert g.vertex_count() == 3 and g.edge_count() == 2


def test_cycle_rejected():
    g = TaskDAG(64)
    for v in (0, 1, 2):
        g.add_vertex(v)
    g.add_edge(0, 1)
    g.add_edge(1, 2)
    assert not g.can_add_edge(2, 0)  # 0 reaches 2, closing the loop
    with pytest.raises(DAGError):
        g.add_edge(2, 0)
    assert not g.can_add_edge(0, 0)  # self loop
    assert not g.can_add_edge(0, 1)  # duplicate
    assert not g.can_add_edge(0, 5)  # absent vertex


def test_delete_vertex_clears_incident_edges():
    g = TaskDAG(64)
    for v in (0, 1, 2):
        g.add_vertex(v)
    g.add_edge(0, 1)
    g.add_edge(1, 2)
    g.delete_vertex(1)
    assert g.vertex_count() == 2 and g.edge_count() == 0
    assert g.in_degree[2] == 0 and g.out_degree[0] == 0
    # 2 -> 0 is now legal: the old path is gone
    assert g.can_add_edge(2, 0)


def test_delete_in_out_edges():
    g = TaskDAG(64)
    for v in range(4):
        g.add_vertex(v)
    g.add_edge(0, 2)
    g.add_edge(1, 2)
    g.add_edge(2, 3)
    g.delete_in_edges(2)
    assert g.in_degree[2] == 0 and g.out_degree[0] == 0 and g.out_degree[1] == 0
    assert g.has_edge(2, 3)
    g.delete_out_edges(2)
    assert g.edge_count() == 0


def test_random_vertices(rng):
    g = TaskDAG(64)
    for v in range(10):
        g.add_vertex(v)
    got = g.random_vertices(5, rng)
    assert len(got) == 5 and len(set(got.tolist())) == 5
    assert all(g.present[v] for v in got)
    assert len(g.random_vertices(50, rng)) == 10  # capped at live count


def _random_dag(p, n_edges, rng):
    g = TaskDAG(p)
    for v in range(p):
        g.add_vertex(v)
    adj = np.zeros((p, p), bool)
    added = 0
    while added < n_edges:
        u, v = int(rng.integers(p)), int(rng.integers(p))
        if g.can_add_edge(u, v):
            g.add_edge(u, v)
            adj[u, v] = True
            added += 1
    return g, adj


def test_batch_reachable_matches_host(rng):
    p = 64
    g, adj = _random_dag(p, 120, rng)
    src = rng.integers(0, p, (1, 32)).astype(np.int32)
    dst = rng.integers(0, p, (1, 32)).astype(np.int32)
    got = np.asarray(batch_reachable(adj[None], src, dst))
    for q in range(32):
        assert got[0, q] == g.reachable(int(src[0, q]), int(dst[0, q])), q


def test_batch_can_add_edge_matches_host(rng):
    p = 64
    graphs = [_random_dag(p, 100, rng) for _ in range(3)]
    adj = np.stack([a for _, a in graphs])
    present = np.ones((3, p), bool)
    child = rng.integers(0, p, (3,)).astype(np.int32)
    parent = rng.integers(0, p, (3, 16)).astype(np.int32)
    got = np.asarray(batch_can_add_edge(adj, present, parent, child))
    for b, (g, _) in enumerate(graphs):
        for k in range(16):
            assert got[b, k] == g.can_add_edge(int(parent[b, k]), int(child[b])), (b, k)


def test_batch_can_add_edge_respects_present_mask(rng):
    p = 64
    g, adj = _random_dag(p, 50, rng)
    present = np.ones((1, p), bool)
    present[0, 5] = False
    parent = np.array([[5, 6]], np.int32)
    child = np.array([7], np.int32)
    got = np.asarray(batch_can_add_edge(adj[None], present, parent, child))
    assert not got[0, 0]  # absent parent


def test_can_add_edges_matches_scalar(monkeypatch):
    """Batched cycle check == per-candidate can_add_edge, across self-loop,
    duplicate-edge, absent-vertex, cycle, and legal cases — with and
    without the native library."""
    import numpy as np

    from dragonfly2_tpu.graph.dag import TaskDAG

    dag = TaskDAG(64)
    a, b, c, d, e, f, g, h = range(8)
    for v in (a, b, c, d, e, f, g, h):
        dag.add_vertex(v)
    dag.add_edge(a, b)
    dag.add_edge(b, c)
    dag.add_edge(c, d)
    dag.add_edge(e, f)
    dag.delete_vertex(h)

    child = c
    parents = np.array([a, b, c, d, e, f, g, h, 63], np.int64)
    want = np.array([dag.can_add_edge(int(p), child) for p in parents])
    got = dag.can_add_edges(parents, child)
    assert (got == want).all(), (got, want)
    # pure-python fallback agrees (monkeypatch restores the env var)
    monkeypatch.setenv("DF_NATIVE", "0")
    got_py = dag.can_add_edges(parents, child)
    assert (got_py == want).all()
    monkeypatch.undo()
    # an unassigned child slot (-1) is never legal and never reaches native
    assert not dag.can_add_edges(parents, -1).any()


def test_add_edges_from_equals_sequential_add_edge():
    """Batched in-edge insertion (one legality pass, the scheduler's
    _apply_selection path) must accept exactly what sequential add_edge
    would and leave an identical graph — including duplicate parents in
    one batch, pre-existing edges, cycles, and absent vertices."""
    rng = np.random.default_rng(3)
    for trial in range(20):
        a, b = TaskDAG(64), TaskDAG(64)
        alive = rng.choice(16, size=10, replace=False)
        for v in alive:
            a.add_vertex(int(v)); b.add_vertex(int(v))
        # random pre-existing edges
        for _ in range(12):
            u, v = rng.choice(alive, 2, replace=False)
            try:
                a.add_edge(int(u), int(v)); b.add_edge(int(u), int(v))
            except DAGError:
                pass
        child = int(rng.choice(alive))
        parents = rng.integers(-1, 20, size=6).astype(np.int64)
        parents[rng.integers(6)] = parents[rng.integers(6)]  # force dupes
        want = []
        for p in parents:
            try:
                a.add_edge(int(p), child)
                want.append(True)
            except (DAGError, IndexError):
                want.append(False)
        got = b.add_edges_from(parents, child)
        assert list(got) == want, (trial, parents, child)
        assert np.array_equal(a.adj, b.adj), trial
        assert np.array_equal(a.in_degree, b.in_degree), trial
        assert np.array_equal(a.out_degree, b.out_degree), trial


def test_can_add_edges_pairs_matches_scalar(monkeypatch):
    """Pairs-batched cycle check (ONE native call for every pending peer
    of a task — the tick's per-task batching) == per-pair can_add_edge,
    across self-loop, duplicate, absent-vertex, cycle, unassigned (-1)
    and out-of-range ids, with and without the native library."""
    import numpy as np

    from dragonfly2_tpu.graph.dag import TaskDAG

    dag = TaskDAG(64)
    for v in range(8):
        dag.add_vertex(v)
    dag.add_edge(0, 1)
    dag.add_edge(1, 2)
    dag.add_edge(2, 3)
    dag.add_edge(4, 5)
    dag.delete_vertex(7)

    rng = np.random.default_rng(0)
    parents = rng.integers(-1, 10, 64).astype(np.int64)
    children = rng.integers(-1, 10, 64).astype(np.int64)
    parents[:5] = [0, 3, 2, 7, 63]
    children[:5] = [1, 0, 2, 1, 1]  # duplicate, cycle, self-loop, absent, oob
    want = np.array([
        dag.can_add_edge(int(p), int(c)) if 0 <= c < 64 and 0 <= p < 64 else False
        for p, c in zip(parents, children)
    ])
    got = dag.can_add_edges_pairs(parents, children)
    assert (got == want).all(), np.nonzero(got != want)
    monkeypatch.setenv("DF_NATIVE", "0")
    got_py = dag.can_add_edges_pairs(parents, children)
    assert (got_py == want).all()
    monkeypatch.undo()
    assert dag.can_add_edges_pairs(np.zeros(0, np.int64), np.zeros(0, np.int64)).shape == (0,)
