"""Long-context attention benchmark: Pallas flash kernel vs dense XLA.

The reference has no sequence models at all (SURVEY.md §5); long-context
support is new TPU-native territory: ops/flash.py (fused fwd AND fused
bwd kernels, O(L) memory), parallel/ring.py (sp-sharded ring attention),
and parallel/ulysses.py (all-to-all head parallelism). This script
measures the single-chip kernel against the dense reference at growing
sequence lengths on the real chip — dense attention materializes the
[L, L] score matrix, so it falls off a memory cliff where flash keeps
scaling, and since round 3 the fused backward holds the same O(L)
contract for training.

Timing method: N data-dependent steps inside ONE jit (each step feeds
eps*output back into the inputs, eps traced so XLA cannot fold the
chain), timed end-to-end with a D2H fetch forcing completion, divided by
N. A single dispatch over the axon tunnel can carry ~100 ms of transport
latency in degraded windows — per-dispatch timing measures the tunnel,
not the kernel.

Prints one JSON line per (length, impl): ms/step over the best chain,
plus a summary line with the flash-vs-dense speedup at the largest
length both complete, and fwd+bwd lines with MFU vs the chip's 197
TFLOP/s bf16 peak.
"""

from __future__ import annotations

import json
import time

import numpy as np

BATCH, HEADS, DIM = 4, 8, 128
LENGTHS = (2048, 4096, 8192, 16384, 32768)
CHAIN = 8
TRIALS = 3


def _bench_chain(jfn, *args) -> float:
    """min wall-ms per chained step; np.asarray forces completion."""
    np.asarray(jfn(*args))  # compile + warm
    best = float("inf")
    for _ in range(TRIALS):
        t0 = time.perf_counter()
        np.asarray(jfn(*args))
        best = min(best, time.perf_counter() - t0)
    return best / CHAIN * 1e3


def main() -> int:
    import jax
    import jax.numpy as jnp

    from dragonfly2_tpu.ops.flash import flash_attention
    from dragonfly2_tpu.parallel.ring import dense_attention

    rng = np.random.default_rng(0)
    results = {}
    for length in LENGTHS:
        shape = (BATCH, HEADS, length, DIM)
        q = jnp.asarray(rng.standard_normal(shape), jnp.bfloat16)
        k = jnp.asarray(rng.standard_normal(shape), jnp.bfloat16)
        v = jnp.asarray(rng.standard_normal(shape), jnp.bfloat16)
        mask = jnp.ones((BATCH, length), bool)
        for name in ("flash", "dense"):
            if name == "flash":
                step = lambda q_, k_, v_: flash_attention(q_, k_, v_)  # no-mask fast path
            else:
                step = lambda q_, k_, v_: dense_attention(q_, k_, v_, mask)

            @jax.jit
            def chain(q_, k_, v_, eps, step=step):
                for _ in range(CHAIN):
                    o = step(q_, k_, v_)
                    q_ = q_ + eps * o.astype(q_.dtype)
                return q_[0, 0, :8, :4].astype(jnp.float32)

            try:
                ms = _bench_chain(chain, q, k, v, jnp.bfloat16(0.0))
            except Exception as e:  # noqa: BLE001 - dense OOMs eventually
                print(json.dumps({
                    "metric": f"attention_{name}_ms", "length": length,
                    "value": None, "error": type(e).__name__,
                }))
                continue
            results[(name, length)] = ms
            tflops = 4 * BATCH * HEADS * length * length * DIM / (ms / 1e3) / 1e12
            print(json.dumps({
                "metric": f"attention_{name}_ms", "length": length,
                "value": round(ms, 3), "unit": "ms", "tflops": round(tflops, 1),
                "mfu_pct_vs_197tf": round(100 * tflops / 197.0, 1),
            }))

    common = [l for l in LENGTHS if ("flash", l) in results and ("dense", l) in results]
    if common:
        l = common[-1]
        print(json.dumps({
            "metric": "attention_flash_speedup_vs_dense",
            "length": l,
            "value": round(results[("dense", l)] / results[("flash", l)], 2),
            "unit": "x",
        }))

    # Forward+backward through the fused flash bwd — the cost a TRAINING
    # step actually pays. Standard accounting: fwd+bwd = 3 * 4*B*H*L^2*D.
    # Full fwd shape all the way to 32k: the fused dQ and dK/dV kernels
    # keep the footprint constant in L (round-2's dense-recompute bwd
    # could not fit these shapes). All three grads feed the chain so no
    # kernel is dead-code-eliminated.
    for length in (8192, 16384, 32768):
        shape = (BATCH, HEADS, length, DIM)
        q = jnp.asarray(rng.standard_normal(shape), jnp.bfloat16)
        k = jnp.asarray(rng.standard_normal(shape), jnp.bfloat16)
        v = jnp.asarray(rng.standard_normal(shape), jnp.bfloat16)

        grad_fn = jax.grad(
            lambda a, b, c: flash_attention(a, b, c).astype(jnp.float32).sum(),
            argnums=(0, 1, 2),
        )

        @jax.jit
        def chain_g(q_, k_, v_, eps):
            for _ in range(CHAIN):
                dq, dk, dv = grad_fn(q_, k_, v_)
                q_ = q_ + eps * dq.astype(q_.dtype)
                k_ = k_ + eps * dk.astype(k_.dtype)
                v_ = v_ + eps * dv.astype(v_.dtype)
            return (q_[0, 0, :8, :4] + k_[0, 0, :8, :4] + v_[0, 0, :8, :4]).astype(jnp.float32)

        try:
            ms = _bench_chain(chain_g, q, k, v, jnp.bfloat16(0.0))
        except Exception as e:  # noqa: BLE001
            print(json.dumps({
                "metric": "attention_flash_fwdbwd_ms", "length": length,
                "value": None, "error": type(e).__name__,
            }))
            continue
        tflops = 3 * 4 * BATCH * HEADS * length * length * DIM / (ms / 1e3) / 1e12
        print(json.dumps({
            "metric": "attention_flash_fwdbwd_ms", "length": length,
            "value": round(ms, 3), "unit": "ms", "tflops": round(tflops, 1),
            "mfu_pct_vs_197tf": round(100 * tflops / 197.0, 1),
        }))
    return 0


if __name__ == "__main__":
    import sys

    sys.exit(main())
