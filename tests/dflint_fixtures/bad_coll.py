"""dflint red fixture: collective-hygiene violations in a meshed body.

CollectivePass: COLL001 x2 (axis not in MESH_AXES; axis inconsistent
with the enclosing shard_map's partition specs), COLL002 x2 (host syncs
in a shard_map body: .item() and np.asarray). JitHygienePass over the
same file: JIT001 x2 + JIT002 — the satellite pin that the jit pass now
sees inside shard_map-wrapped bodies.
"""

import jax
import numpy as np
from jax.sharding import PartitionSpec as P

from dragonfly2_tpu.utils.jaxcompat import shard_map


def rogue_axis(x):
    return jax.lax.psum(x, "rows")  # <- COLL001 (axis not registered)


def mesh_body(x):
    y = jax.lax.ppermute(x, "tp", [(0, 1)])  # <- COLL001 (specs say dp)
    peak = y.max().item()  # <- JIT001 (host sync in traced body)
    if x.sum() > 0:  # <- JIT002 (python branch on a shard)
        y = y + peak
    return np.asarray(y)  # <- COLL002 (+ JIT001: host materialization)


def wrapper(mesh, x):
    fn = shard_map(mesh_body, mesh=mesh, in_specs=(P("dp"),), out_specs=P("dp"))
    return fn(x)
