"""Tier-1 gate for dflint (tools/dflint) + per-pass fixture goldens +
pinning regressions for the bugs the passes surfaced.

The gate is the contract: dflint over the whole package returns ZERO
unwaived findings, every waiver carries a reason, and the run stays
under a hard time budget so tier-1 wall does not regress. The fixture
tests make each pass's red/green behavior non-negotiable: a crafted
known-bad snippet must trip exactly its rule (stable finding IDs), and
the known-good idioms must stay silent — so a future pass edit cannot
silently go blind OR noisy."""

import threading
import time
from pathlib import Path

import pytest

from tools.dflint.core import run_dflint
from tools.dflint.passes.collective import CollectivePass
from tools.dflint.passes.determinism import DeterminismPass
from tools.dflint.passes.flush_valve import FlushValvePass
from tools.dflint.passes.jit_hygiene import JitHygienePass
from tools.dflint.passes.lock_discipline import LockDisciplinePass
from tools.dflint.passes.shape import ShapeDonationPass
from tools.dflint.passes.wire import WirePass

ROOT = Path(__file__).resolve().parents[1]
FIXTURES = Path(__file__).parent / "dflint_fixtures"

# hard wall for the full-package lint inside tier-1: generous vs the
# ~1 s measured, tight vs the suite budget
LINT_TIME_BUDGET_S = 30.0


def _lint(passes, *names):
    report, contexts = run_dflint(
        ROOT, files=[FIXTURES / n for n in names], passes=passes
    )
    return report, contexts


# ------------------------------------------------------------ tier-1 gate


def test_dflint_package_gate_zero_unwaived_findings():
    """THE gate: the tree is clean under its own lint. Prints every
    unwaived finding on failure so the culprit is one read away."""
    report, contexts = run_dflint(ROOT)
    assert report.files_scanned > 100, "package walk found too few files"
    unwaived = report.unwaived()
    assert not unwaived, "dflint findings:\n" + "\n".join(
        f.render() for f in unwaived
    )
    # every waiver must argue its case: a reason-less waiver is a muzzle
    assert report.reasonless_waivers(contexts) == []
    # and stay live: a waiver whose rule no longer fires must be deleted
    assert report.stale_waivers(contexts) == []
    # waivers exist and carry substantive reasons (not one-word shrugs)
    for finding in report.waived():
        assert len(finding.waive_reason) >= 20, (
            f"waiver at {finding.location} has a throwaway reason: "
            f"{finding.waive_reason!r}"
        )
    assert report.duration_s < LINT_TIME_BUDGET_S, (
        f"lint took {report.duration_s:.1f}s — over the tier-1 budget"
    )


def test_waiver_without_reason_does_not_suppress(tmp_path):
    bad = tmp_path / "nolock.py"
    bad.write_text(
        "import threading\n"
        "class C:\n"
        "    def __init__(self):\n"
        "        self._mu = threading.Lock()\n"
        "        self.x = 0\n"
        "    def a(self):\n"
        "        with self._mu:\n"
        "            self.x += 1\n"
        "    def b(self):\n"
        "        self.x += 1  # dflint: waive[LOCK001]\n"
    )
    report, contexts = run_dflint(ROOT, files=[bad],
                                  passes=[LockDisciplinePass()])
    assert len(report.unwaived()) == 1, "reason-less waiver must not suppress"
    assert report.reasonless_waivers(contexts), (
        "the gate must also surface the reason-less waiver itself"
    )


# ------------------------------------------------------- fixture goldens


def test_lock_discipline_fixtures():
    report, _ = _lint([LockDisciplinePass()], "bad_lock.py", "good_lock.py")
    ids = [f.finding_id for f in report.findings]
    assert ids == [
        "LOCK001@tests/dflint_fixtures/bad_lock.py:Board.racy_bump"
    ], ids
    # the never-guarded attribute and every green idiom stayed silent
    assert not any("good_lock" in f.path for f in report.findings)
    assert not any("unshared" in f.message for f in report.findings)


def test_flush_valve_fixtures():
    report, _ = _lint([FlushValvePass()], "bad_flush.py", "good_flush.py")
    ids = sorted(f.finding_id for f in report.findings)
    assert ids == [
        "FLUSH001@tests/dflint_fixtures/bad_flush.py:SchedulerService.stale_read",
        "FLUSH002@tests/dflint_fixtures/bad_flush.py:SchedulerService.peek_buffer",
    ], ids


def test_jit_hygiene_fixtures():
    jit_pass = JitHygienePass(
        hot_functions={("bad_jit.py", "hot_tick"), ("good_jit.py", "host_caller")},
        allowlist={},
    )
    report, _ = _lint([jit_pass], "bad_jit.py", "good_jit.py")
    by_rule = {rule: len(fs) for rule, fs in report.by_rule().items()}
    assert by_rule == {"JIT001": 2, "JIT002": 1, "JIT003": 2, "JIT004": 1}, (
        by_rule, [f.render() for f in report.findings]
    )
    assert not any("good_jit" in f.path for f in report.findings), [
        f.render() for f in report.findings if "good_jit" in f.path
    ]
    # allowlisting the hot D2H sync alone leaves EXACTLY the hot-path
    # cost_analysis finding: a cost-card capture on the tick path is a
    # full XLA recompile and needs its own argued allowlist entry
    # (telemetry/costcard.py capture discipline)
    allowed = JitHygienePass(
        hot_functions={("bad_jit.py", "hot_tick")},
        allowlist={("bad_jit.py", "hot_tick", "asarray"): "fixture"},
    )
    report2, _ = _lint([allowed], "bad_jit.py")
    jit003 = report2.by_rule().get("JIT003", [])
    assert len(jit003) == 1 and "cost_analysis" in jit003[0].message, [
        f.render() for f in jit003
    ]
    # allowlisting both silences JIT003 entirely and nothing else
    allowed_both = JitHygienePass(
        hot_functions={("bad_jit.py", "hot_tick")},
        allowlist={
            ("bad_jit.py", "hot_tick", "asarray"): "fixture",
            ("bad_jit.py", "hot_tick", "cost_analysis"): "fixture",
        },
    )
    report3, _ = _lint([allowed_both], "bad_jit.py")
    assert "JIT003" not in report3.by_rule()


def test_shadow_scoring_drain_discipline_fixture():
    """An in-tick shadow-scoring D2H trips JIT003; the same read at the
    allowlisted `_drain_shadow` end-of-tick valve is silent — the
    capture discipline the decision ledger's counterfactual arm lives
    under (telemetry/decisions.py). Also pins that the REAL repo
    allowlist carries the argued `_drain_shadow` entry, so the
    production drain point cannot silently fall off the design
    document."""
    from tools.dflint.passes.jit_hygiene import D2H_ALLOWLIST

    shadow_pass = JitHygienePass(
        hot_functions={
            ("bad_shadow.py", "tick"),
            ("bad_shadow.py", "_drain_shadow"),
        },
        allowlist={
            ("bad_shadow.py", "_drain_shadow", "asarray"):
                "fixture: the designed end-of-tick shadow drain valve",
        },
    )
    report, _ = _lint([shadow_pass], "bad_shadow.py")
    jit003 = report.by_rule().get("JIT003", [])
    assert len(jit003) == 1, [f.render() for f in report.findings]
    assert jit003[0].symbol == "tick", jit003[0].render()
    # allowlisting the in-tick read too silences the fixture entirely
    allowed = JitHygienePass(
        hot_functions={
            ("bad_shadow.py", "tick"),
            ("bad_shadow.py", "_drain_shadow"),
        },
        allowlist={
            ("bad_shadow.py", "_drain_shadow", "asarray"): "fixture",
            ("bad_shadow.py", "tick", "asarray"): "fixture",
        },
    )
    report2, _ = _lint([allowed], "bad_shadow.py")
    assert "JIT003" not in report2.by_rule()
    # the production drain point is on the real allowlist, argued
    key = ("cluster/scheduler.py", "_drain_shadow", "asarray")
    assert key in D2H_ALLOWLIST and len(D2H_ALLOWLIST[key]) >= 20


def test_determinism_fixtures():
    det = DeterminismPass(
        decision_suffixes=("bad_det.py", "good_det.py"),
        set_iter_suffixes=("bad_det.py", "good_det.py"),
    )
    report, _ = _lint([det], "bad_det.py", "good_det.py")
    by_rule = {rule: len(fs) for rule, fs in report.by_rule().items()}
    assert by_rule == {"DET001": 2, "DET002": 1, "DET003": 1}, (
        by_rule, [f.render() for f in report.findings]
    )
    assert not any("good_det" in f.path for f in report.findings), [
        f.render() for f in report.findings if "good_det" in f.path
    ]


def test_slo_determinism_fixtures_and_domain():
    """ISSUE 14 satellite: telemetry/slo.py is a DET domain (the replay
    evaluation path may never read the wall clock — paired-seed alert
    timelines depend on it; perf_counter stays exempt), pinned by a
    red/green fixture pair shaped like the SLO engine."""
    from tools.dflint.passes.determinism import DEFAULT_DECISION_SUFFIXES

    assert any(
        s.endswith("telemetry/slo.py") for s in DEFAULT_DECISION_SUFFIXES
    ), DEFAULT_DECISION_SUFFIXES
    det = DeterminismPass(
        decision_suffixes=("bad_slo.py", "good_slo.py"),
        set_iter_suffixes=("bad_slo.py", "good_slo.py"),
    )
    report, _ = _lint([det], "bad_slo.py", "good_slo.py")
    by_rule = {rule: len(fs) for rule, fs in report.by_rule().items()}
    assert by_rule == {"DET002": 1, "DET003": 1}, (
        by_rule, [f.render() for f in report.findings]
    )
    # the green twin (caller-stamped clock, perf_counter measuring,
    # sorted alert iteration) stays silent
    assert not any("good_slo" in f.path for f in report.findings), [
        f.render() for f in report.findings if "good_slo" in f.path
    ]
    # and the real module is clean under the default domain set
    real = run_dflint(
        ROOT,
        files=[ROOT / "dragonfly2_tpu" / "telemetry" / "slo.py"],
        passes=[DeterminismPass()],
    )[0]
    assert real.unwaived() == [], [f.render() for f in real.unwaived()]


def test_tail_determinism_fixtures_and_domain():
    """ISSUE 16 satellite: telemetry/tailtrace.py is a DET domain
    (paired-seed megascale runs pin its digest bit for bit, so the
    ledger may never read the wall clock, draw from a process rng, or
    iterate a set into output), pinned by a red/green fixture pair
    shaped like the tail ledger."""
    from tools.dflint.passes.determinism import DEFAULT_DECISION_SUFFIXES

    assert any(
        s.endswith("telemetry/tailtrace.py") for s in DEFAULT_DECISION_SUFFIXES
    ), DEFAULT_DECISION_SUFFIXES
    det = DeterminismPass(
        decision_suffixes=("bad_tail.py", "good_tail.py"),
        set_iter_suffixes=("bad_tail.py", "good_tail.py"),
    )
    report, _ = _lint([det], "bad_tail.py", "good_tail.py")
    by_rule = {rule: len(fs) for rule, fs in report.by_rule().items()}
    assert by_rule == {"DET001": 1, "DET002": 1, "DET003": 1}, (
        by_rule, [f.render() for f in report.findings]
    )
    # the green twin (counter-hashed sampler, caller-stamped clock,
    # sorted tracer iteration) stays silent
    assert not any("good_tail" in f.path for f in report.findings), [
        f.render() for f in report.findings if "good_tail" in f.path
    ]
    # and the real module is clean under the default domain set
    real = run_dflint(
        ROOT,
        files=[ROOT / "dragonfly2_tpu" / "telemetry" / "tailtrace.py"],
        passes=[DeterminismPass()],
    )[0]
    assert real.unwaived() == [], [f.render() for f in real.unwaived()]


def test_fleet_determinism_fixtures_and_domain():
    """ISSUE 17 satellite: megascale/fleet.py is a DET domain (the K=1
    equivalence oracle and paired-seed fleet soaks pin the handoff
    stream bit for bit, so ring-rebalance sweeps may never iterate a
    set into output, pick victims from a process rng, or put replica
    down windows on the wall clock), pinned by a red/green fixture pair
    shaped like the fleet's rebalance path."""
    from tools.dflint.passes.determinism import DEFAULT_DECISION_SUFFIXES

    assert any(
        s.endswith("megascale/fleet.py") for s in DEFAULT_DECISION_SUFFIXES
    ), DEFAULT_DECISION_SUFFIXES
    det = DeterminismPass(
        decision_suffixes=("bad_fleet.py", "good_fleet.py"),
        set_iter_suffixes=("bad_fleet.py", "good_fleet.py"),
    )
    report, _ = _lint([det], "bad_fleet.py", "good_fleet.py")
    by_rule = {rule: len(fs) for rule, fs in report.by_rule().items()}
    assert by_rule == {"DET001": 1, "DET002": 1, "DET003": 1}, (
        by_rule, [f.render() for f in report.findings]
    )
    # the green twin (round-robin victim, round-counter down window,
    # sorted rebalance sweep) stays silent
    assert not any("good_fleet" in f.path for f in report.findings), [
        f.render() for f in report.findings if "good_fleet" in f.path
    ]
    # and the real module is clean under the default domain set
    real = run_dflint(
        ROOT,
        files=[ROOT / "dragonfly2_tpu" / "megascale" / "fleet.py"],
        passes=[DeterminismPass()],
    )[0]
    assert real.unwaived() == [], [f.render() for f in real.unwaived()]


def test_proc_determinism_fixtures_and_domain():
    """ISSUE 18 satellite: the procworld replay path (sample synthesis
    and the divergence verdict) is a DET domain — dfslo re-judges
    BENCH_proc.json offline, so both must be pure functions of the
    recorded observations (no wall clocks, no process rng, no
    set-ordered output) — pinned by a red/green fixture pair shaped
    like the synthesizer. The supervisor stays out of scope: it runs
    real processes on the real clock by design."""
    from tools.dflint.passes.determinism import DEFAULT_DECISION_SUFFIXES

    for suffix in ("procworld/sample.py", "procworld/divergence.py"):
        assert any(
            s.endswith(suffix) for s in DEFAULT_DECISION_SUFFIXES
        ), (suffix, DEFAULT_DECISION_SUFFIXES)
    assert not any(
        s.endswith("procworld/supervisor.py") for s in DEFAULT_DECISION_SUFFIXES
    ), DEFAULT_DECISION_SUFFIXES
    det = DeterminismPass(
        decision_suffixes=("bad_proc.py", "good_proc.py"),
        set_iter_suffixes=("bad_proc.py", "good_proc.py"),
    )
    report, _ = _lint([det], "bad_proc.py", "good_proc.py")
    by_rule = {rule: len(fs) for rule, fs in report.by_rule().items()}
    assert by_rule == {"DET001": 1, "DET002": 1, "DET003": 1}, (
        by_rule, [f.render() for f in report.findings]
    )
    # the green twin (constant argued bands, model-clock round stamps,
    # sorted region sweep, perf_counter measurement) stays silent
    assert not any("good_proc" in f.path for f in report.findings), [
        f.render() for f in report.findings if "good_proc" in f.path
    ]
    # and the real modules are clean under the default domain set
    real = run_dflint(
        ROOT,
        files=[ROOT / "dragonfly2_tpu" / "procworld" / "sample.py",
               ROOT / "dragonfly2_tpu" / "procworld" / "divergence.py"],
        passes=[DeterminismPass()],
    )[0]
    assert real.unwaived() == [], [f.render() for f in real.unwaived()]


def test_shape_donation_fixtures():
    report, _ = _lint(
        [ShapeDonationPass()],
        "bad_shape.py", "good_shape.py", "bad_donate.py", "good_donate.py",
    )
    by_rule = {rule: len(fs) for rule, fs in report.by_rule().items()}
    assert by_rule == {"SHAPE001": 2, "SHAPE002": 1, "DON001": 3}, (
        by_rule, [f.render() for f in report.findings]
    )
    assert not any("good_" in f.path for f in report.findings), [
        f.render() for f in report.findings if "good_" in f.path
    ]
    assert sorted(
        f.finding_id for f in report.findings if f.rule == "DON001"
    ) == [
        "DON001@tests/dflint_fixtures/bad_donate.py:caller_via_fixpoint",
        "DON001@tests/dflint_fixtures/bad_donate.py:loop_carried_reuse",
        "DON001@tests/dflint_fixtures/bad_donate.py:reuse_after_donate",
    ]


def test_fused_tick_fixtures():
    """ISSUE 19 satellite: the fused-tick entries in the dfshape design
    document are live, pinned red/green. bad_tick.py must trip exactly
    one of each registered defect — a runtime batch dim into
    `fused_tick_chunk` (SHAPE001), a runtime `limit` static (SHAPE002),
    a read of the donated staging buffer after the fused call (DON001) —
    and a mid-pipeline fused read-back in the hot `_dispatch_fused`
    trips JIT003 while the allowlisted `_drain_fused` drain stays
    silent. good_tick.py carries the production idioms (bucketed batch
    dims, fresh staging per donation, the mirror's attribute-rebind
    scatter) and must stay silent under both passes."""
    from tools.dflint.passes.jit_hygiene import D2H_ALLOWLIST
    from tools.dflint.passes.shape import SERVING_JIT_REGISTRY

    report, _ = _lint([ShapeDonationPass()], "bad_tick.py", "good_tick.py")
    by_rule = {rule: len(fs) for rule, fs in report.by_rule().items()}
    assert by_rule == {"SHAPE001": 1, "SHAPE002": 1, "DON001": 1}, (
        by_rule, [f.render() for f in report.findings]
    )
    assert not any("good_tick" in f.path for f in report.findings), [
        f.render() for f in report.findings if "good_tick" in f.path
    ]
    assert sorted(f.finding_id for f in report.findings) == [
        "DON001@tests/dflint_fixtures/bad_tick.py:staging_reuse",
        "SHAPE001@tests/dflint_fixtures/bad_tick.py:unbucketed_fused_batch",
        "SHAPE002@tests/dflint_fixtures/bad_tick.py:runtime_fused_limit",
    ]
    # the fused drain discipline: one allowlisted D2H point per tick
    jit_pass = JitHygienePass(
        hot_functions={
            ("bad_tick.py", "_dispatch_fused"),
            ("bad_tick.py", "_drain_fused"),
        },
        allowlist={
            ("bad_tick.py", "_drain_fused", "asarray"):
                "fixture: the single end-of-chunk fused drain valve",
        },
    )
    report2, _ = _lint([jit_pass], "bad_tick.py", "good_tick.py")
    jit003 = report2.by_rule().get("JIT003", [])
    assert len(jit003) == 1 and jit003[0].symbol == "_dispatch_fused", [
        f.render() for f in report2.findings
    ]
    assert not any("good_tick" in f.path for f in report2.findings)
    # the registry rows the fixtures exercise exist and donate the
    # staging buffer / resident column respectively
    assert SERVING_JIT_REGISTRY["fused_tick_chunk"]["donate"] == (0,)
    assert SERVING_JIT_REGISTRY["fused_tick_chunk"]["b_arg"] == 2
    assert SERVING_JIT_REGISTRY["_scatter_rows"]["donate"] == (0,)
    # the production fused drain point is on the real allowlist, argued
    key = ("cluster/scheduler.py", "_drain_fused", "asarray")
    assert key in D2H_ALLOWLIST and len(D2H_ALLOWLIST[key]) >= 20


def test_wire_contract_fixtures():
    """dfwire red/green goldens (ISSUE 15): every WIRE001-004 shape
    fires exactly once per crafted defect in bad_wire.py — unregistered
    send, consumer-less send, dead registered type, producer-less
    dispatch arm (WIRE001 x4); set/multi-tuple/union/dict-of-dataclass
    hints outside the codec lattice (WIRE002 x4); a serve loop dropping
    the deadline budget and the trace (WIRE003 x2); a declared-but-
    unarmed v1 type, an unreachable arm, an untranslated scheduling
    response (WIRE004 x3) — and the good twin's closed loop stays
    silent. Fixtures are linted separately: the pass is whole-program
    (finalize hook), so the red file's producers must not feed the
    green file's closure."""
    bad_pass = WirePass(
        dispatch_sites=frozenset({("bad_wire.py", "_dispatch"),
                                  ("bad_wire.py", "_dispatch_v1")}),
        external_producers={}, external_consumers={},
        translated_responses=("NormalT", "FailT"),
        dialect_suffix="bad_wire.py",
    )
    report, _ = _lint([bad_pass], "bad_wire.py")
    by_rule = {rule: len(fs) for rule, fs in report.by_rule().items()}
    assert by_rule == {"WIRE001": 4, "WIRE002": 4, "WIRE003": 2,
                       "WIRE004": 3}, (
        by_rule, [f.render() for f in report.findings]
    )
    # finding ids are stable (file+symbol) for the CI annotator
    assert "WIRE001@tests/dflint_fixtures/bad_wire.py:OrphanMsg" in {
        f.finding_id for f in report.findings
    }
    good_pass = WirePass(
        dispatch_sites=frozenset({("good_wire.py", "_dispatch"),
                                  ("good_wire.py", "_dispatch_v1")}),
        external_producers={}, external_consumers={},
        translated_responses=("NormalT", "FailT"),
        dialect_suffix="good_wire.py",
    )
    report2, _ = _lint([good_pass], "good_wire.py")
    assert report2.findings == [], [f.render() for f in report2.findings]


def test_wire_pass_registries_argue_their_case():
    """The pass's external producer/consumer registries follow the
    D2H_ALLOWLIST discipline: every entry carries a substantive reason,
    and every entry names a REAL registered message (a stale entry for
    a deleted type would silently exempt the next name collision)."""
    import json

    from tools.dflint.passes.wire import (
        EXTERNAL_CONSUMERS, EXTERNAL_PRODUCERS, V1_TRANSLATED_RESPONSES,
    )

    snapshot = json.loads(
        (ROOT / "tools" / "dfwire_schema.json").read_text()
    )
    for name, reason in {**EXTERNAL_PRODUCERS, **EXTERNAL_CONSUMERS}.items():
        assert len(reason) >= 20, (name, reason)
        assert name in snapshot["messages"], (
            f"registry entry {name!r} is not in the wire schema — stale"
        )
    for name in V1_TRANSLATED_RESPONSES:
        assert name in snapshot["messages"], name


def test_collective_fixtures():
    report, _ = _lint([CollectivePass()], "bad_coll.py", "good_coll.py")
    by_rule = {rule: len(fs) for rule, fs in report.by_rule().items()}
    assert by_rule == {"COLL001": 2, "COLL002": 2}, (
        by_rule, [f.render() for f in report.findings]
    )
    assert not any("good_coll" in f.path for f in report.findings)
    # satellite pin: the jit-hygiene pass sees inside shard_map bodies
    report2, _ = _lint([JitHygienePass()], "bad_coll.py", "good_coll.py")
    by_rule2 = {rule: len(fs) for rule, fs in report2.by_rule().items()}
    assert by_rule2 == {"JIT001": 2, "JIT002": 1}, (
        by_rule2, [f.render() for f in report2.findings]
    )
    assert not any("good_coll" in f.path for f in report2.findings)


def test_waiver_audit_flags_stale_waivers(tmp_path):
    """A waiver whose rule still fires is live; one aimed at a silent
    line is stale — the audit (and only the audit) fails on it."""
    src = tmp_path / "mixed.py"
    src.write_text(
        "import threading\n"
        "class C:\n"
        "    def __init__(self):\n"
        "        self._mu = threading.Lock()\n"
        "        self.x = 0\n"
        "        self.y = 0\n"
        "    def a(self):\n"
        "        with self._mu:\n"
        "            self.x += 1\n"
        "            self.y += 1\n"
        "    def live(self):\n"
        "        self.x += 1  # dflint: waive[LOCK001] -- single writer thread by design\n"
        "    def stale(self):\n"
        "        with self._mu:\n"
        "            self.y += 1  # dflint: waive[LOCK001] -- guarded; rule does not fire\n"
    )
    from tools.dflint.passes.lock_discipline import LockDisciplinePass

    report, contexts = run_dflint(ROOT, files=[src],
                                  passes=[LockDisciplinePass()])
    assert report.unwaived() == []
    stale = report.stale_waivers(contexts)
    assert len(stale) == 1 and "waive[LOCK001] is stale" in stale[0], stale
    assert str(src) in stale[0]


def test_cli_json_output_and_audit_exit_codes(tmp_path, capsys):
    """--json emits the machine-readable document with stable finding
    ids; --audit-waivers turns stale waivers into exit 1."""
    import json as jsonlib

    from tools.dflint.__main__ import main

    rc = main([
        "--root", str(ROOT), "--json",
        "tests/dflint_fixtures/bad_lock.py",
    ])
    doc = jsonlib.loads(capsys.readouterr().out)
    assert rc == 1 and doc["ok"] is False
    assert doc["findings"][0]["id"] == (
        "LOCK001@tests/dflint_fixtures/bad_lock.py:Board.racy_bump"
    )
    assert doc["stale_waivers"] == [] and doc["reasonless_waivers"] == []

    stale_file = tmp_path / "stale.py"
    stale_file.write_text(
        "X = 1  # dflint: waive[LOCK001] -- nothing fires here anymore\n"
    )
    rc = main(["--root", str(ROOT), "--audit-waivers", str(stale_file)])
    out = capsys.readouterr().out
    assert rc == 1 and "STALE WAIVER" in out
    # without the audit flag the same tree is clean (stale != unwaived)
    rc = main(["--root", str(ROOT), str(stale_file)])
    capsys.readouterr()
    assert rc == 0


def test_lint_all_entry_point_is_green():
    """Satellite: the single gate CI and tier-1 share — dflint (seven
    passes) with the waiver audit, the typecheck runner, benchwatch,
    and the dfwire breaking gate — passes on this tree. The breaking
    stage runs in a fresh interpreter, so the throwaway message types
    other tests register in THIS process cannot leak into the schema
    extraction."""
    from tools.lint_all import main

    assert main([]) == 0


def test_fixture_findings_carry_stable_ids_and_locations():
    report, _ = _lint([LockDisciplinePass()], "bad_lock.py")
    (finding,) = report.findings
    assert finding.rule == "LOCK001"
    assert finding.location.endswith("bad_lock.py:19")
    # the id survives line churn (file+symbol, no line number)
    assert ":" not in finding.finding_id.rsplit(":", 1)[-1]


# ---------------------------------------- pinning regressions (fixes)


def test_stat_peer_reflects_buffered_piece_reports():
    """Pin the FLUSH001 fix in rpc/server._stat_peer: a StatPeer racing
    the tick must see piece reports that were acknowledged but still
    sitting in the scheduler's report buffer."""
    from dragonfly2_tpu.cluster import messages as msg
    from dragonfly2_tpu.cluster.scheduler import SchedulerService
    from dragonfly2_tpu.config.config import Config
    from dragonfly2_tpu.rpc.server import SchedulerRPCServer

    cfg = Config()
    cfg.scheduler.max_hosts = 16
    cfg.scheduler.max_tasks = 8
    svc = SchedulerService(config=cfg)
    server = SchedulerRPCServer(svc)
    svc.register_peer(msg.RegisterPeerRequest(
        peer_id="p1", task_id="t1",
        host=msg.HostInfo(host_id="h1", hostname="h1", ip="10.0.0.1"),
        url="https://o.example/t1", content_length=16 << 20,
    ))
    for piece in range(3):
        svc.piece_finished(msg.DownloadPieceFinishedRequest(
            peer_id="p1", piece_number=piece, length=1 << 20,
            cost_ns=1_000_000,
        ))
    # NO tick ran: the reports are buffered, the columns are stale —
    # the stat path must flush before reading
    stat = server._stat_peer("p1")
    assert stat.found
    assert stat.detail["finished_pieces"] == 3


def test_bare_driver_handlers_are_thread_safe_without_external_lock():
    """Pin the scheduler entry-point locking (LOCK001 set): in-proc
    drivers (simulator, bench_loop) call handlers and tick() BARE —
    before the fix, two bare threads could race the seed-trigger queue,
    the dirty frontier and the pending map. The harness's guarded
    attributes fail the test if any mu-guarded write happens unlocked."""
    import numpy as np

    from dragonfly2_tpu.cluster import messages as msg
    from dragonfly2_tpu.cluster.scheduler import SchedulerService
    from dragonfly2_tpu.config.config import Config
    from tools.dflint.lockorder import (
        assert_clean, guard_attributes, instrument_locks,
    )

    cfg = Config()
    cfg.scheduler.max_hosts = 64
    cfg.scheduler.max_tasks = 16
    svc = SchedulerService(config=cfg)
    graph = instrument_locks(svc, {
        "mu": "scheduler.mu", "_piece_buf_mu": "scheduler.piece_buf_mu",
    })
    guard_attributes(svc, {
        "_serving_full_sync": "mu", "_seed_rr": "mu",
        "_piece_buf": "_piece_buf_mu",
    }, graph)
    svc.announce_host(msg.HostInfo(
        host_id="seed", hostname="seed", ip="10.9.0.1", host_type="super",
    ))
    errors: list[BaseException] = []
    stop = threading.Event()

    def driver(wid: int) -> None:
        rng = np.random.default_rng(wid)
        try:
            for op in range(150):
                pid = f"b-{wid}-{op}"
                task = f"t-{int(rng.integers(0, 6))}"
                # NOTE: no `with svc.mu:` — the entry points lock
                svc.register_peer(msg.RegisterPeerRequest(
                    peer_id=pid, task_id=task,
                    host=msg.HostInfo(host_id=f"bh-{wid}", hostname=f"bh-{wid}",
                                      ip=f"10.9.1.{wid}"),
                    url=f"https://o.example/{task}", content_length=8 << 20,
                ))
                svc.piece_finished(msg.DownloadPieceFinishedRequest(
                    peer_id=pid, piece_number=int(rng.integers(0, 4)),
                    length=1 << 20, cost_ns=2_000_000,
                ))
        except BaseException as e:  # noqa: BLE001 - recorded for the assert
            errors.append(e)

    def ticker() -> None:
        try:
            while not stop.is_set():
                svc.tick()  # bare, like bench_loop
        except BaseException as e:  # noqa: BLE001
            errors.append(e)

    t_tick = threading.Thread(target=ticker)
    workers = [threading.Thread(target=driver, args=(w,)) for w in range(4)]
    t_tick.start()
    for t in workers:
        t.start()
    for t in workers:
        t.join(timeout=60)
        assert not t.is_alive()
    stop.set()
    t_tick.join(timeout=30)
    assert not t_tick.is_alive()
    assert not errors, errors[:3]
    assert_clean(graph)


def test_dynconfig_refresh_now_resets_under_lock():
    """Pin the DynConfig.refresh_now LOCK001 fix via the runtime guard:
    _last_refresh writes must hold _lock on every path."""
    from dragonfly2_tpu.config.config import Config, DynConfig
    from tools.dflint.lockorder import (
        assert_clean, guard_attributes, instrument_locks,
    )

    dyn = DynConfig(Config(), resolver=lambda: {"scheduler.retry_limit": 7},
                    refresh_interval=0.0)
    graph = instrument_locks(dyn, {"_lock": "dynconfig.lock"})
    guard_attributes(dyn, {"_last_refresh": "_lock"}, graph)
    dyn.refresh_now()
    assert dyn.get("scheduler.retry_limit") == 7
    assert_clean(graph)


def test_storage_reload_does_not_clobber_live_registrations(tmp_path):
    """Pin the StorageManager.reload LOCK001 fix: a reload scanning disk
    while registrations land must never replace a live TaskStorage
    (downloads hold references into it)."""
    from dragonfly2_tpu.client.storage import StorageManager, TaskMetadata

    mgr = StorageManager(tmp_path / "store")
    # persist one task so reload has something to scan
    seeded = mgr.register_task(TaskMetadata(
        task_id="t-disk", peer_id="pd", piece_length=1 << 20,
        content_length=1 << 20, total_pieces=1,
    ))
    seeded._flush_meta()

    errors: list[BaseException] = []
    live: dict[str, object] = {}

    def registrar() -> None:
        try:
            for i in range(200):
                ts = mgr.register_task(TaskMetadata(
                    task_id=f"t-live-{i % 5}", peer_id=f"pl-{i}",
                    piece_length=1 << 20, content_length=1 << 20,
                    total_pieces=1,
                ))
                prev = live.setdefault(ts.meta.task_id, ts)
                assert prev is ts, "registration returned a replaced object"
        except BaseException as e:  # noqa: BLE001
            errors.append(e)

    def reloader() -> None:
        try:
            for _ in range(50):
                mgr.reload()
        except BaseException as e:  # noqa: BLE001
            errors.append(e)

    threads = [threading.Thread(target=registrar),
               threading.Thread(target=reloader)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=60)
        assert not t.is_alive()
    assert not errors, errors[:3]
    for task_id, ts in live.items():
        assert mgr.get(task_id) is ts, (
            f"reload clobbered live task {task_id}"
        )


def test_typecheck_runner_gates_or_passes():
    """Satellite: the checked-in strict-subset type check. On rigs
    without mypy (this container: no new deps allowed) the runner must
    gate with an explicit SKIPPED marker and exit 0 — never fail-closed
    on a missing tool, never silently pretend it ran. On a mypy-equipped
    rig the exit code is the verdict."""
    import subprocess
    import sys

    from tools.typecheck import SKIP_MARKER, subset

    assert subset() == [
        "dragonfly2_tpu/state", "dragonfly2_tpu/graph", "dragonfly2_tpu/ops",
        "dragonfly2_tpu/telemetry/flight.py",
        "dragonfly2_tpu/telemetry/slo.py",
        "dragonfly2_tpu/telemetry/tailtrace.py",
        "dragonfly2_tpu/cluster/quarantine.py",
        "dragonfly2_tpu/scenarios/spec.py",
        "dragonfly2_tpu/rpc/wire.py",
        "dragonfly2_tpu/rpc/client.py",
    ]
    proc = subprocess.run(
        [sys.executable, "tools/typecheck.py"],
        cwd=ROOT, capture_output=True, text=True, timeout=600,
    )
    if SKIP_MARKER in proc.stdout:
        pytest.skip("mypy not installed in this rig (runner gated cleanly)")
    assert proc.returncode == 0, proc.stdout + proc.stderr


def test_lint_gate_runs_fast_enough_for_tier1():
    """Dedicated wall-time pin (separate from the gate so a slow lint
    and a dirty tree fail distinguishably)."""
    t0 = time.perf_counter()
    run_dflint(ROOT)
    assert time.perf_counter() - t0 < LINT_TIME_BUDGET_S
