"""Manager control plane: DB, auth/RBAC, searcher, service, REST, RPC."""

from __future__ import annotations

import asyncio
import json
import threading
import time
import urllib.request

import pytest

from dragonfly2_tpu.manager import auth
from dragonfly2_tpu.manager import rpc as mrpc
from dragonfly2_tpu.manager import searcher as msearcher
from dragonfly2_tpu.manager.models import Database, DuplicateRecord, RecordNotFound
from dragonfly2_tpu.manager.rest import ManagerREST
from dragonfly2_tpu.manager.service import ManagerService


# ------------------------------------------------------------------ database


def test_database_crud_roundtrip():
    db = Database()
    rec = db.create("applications", {"name": "app-1", "url": "http://x", "priority": {"value": 3}})
    assert rec["id"] == 1 and rec["priority"] == {"value": 3}
    assert db.get("applications", 1)["name"] == "app-1"
    db.update("applications", 1, {"bio": "hello"})
    assert db.get("applications", 1)["bio"] == "hello"
    assert db.count("applications") == 1
    db.delete("applications", 1)
    with pytest.raises(RecordNotFound):
        db.get("applications", 1)


def test_database_unique_key_enforced():
    db = Database()
    db.create("schedulers", {"host_name": "h", "ip": "1.2.3.4", "scheduler_cluster_id": 1})
    with pytest.raises(DuplicateRecord):
        db.create("schedulers", {"host_name": "h", "ip": "1.2.3.4", "scheduler_cluster_id": 1})
    # different cluster is fine (uk is composite, manager/models/scheduler.go)
    db.create("schedulers", {"host_name": "h", "ip": "1.2.3.4", "scheduler_cluster_id": 2})


def test_database_list_where_and_pagination():
    db = Database()
    for i in range(7):
        db.create("jobs", {"type": "preheat", "state": "PENDING" if i % 2 else "SUCCESS"})
    assert len(db.list("jobs", {"state": "PENDING"})) == 3
    assert len(db.list("jobs", page=2, per_page=5)) == 2


# ---------------------------------------------------------------------- auth


def test_password_hash_and_verify():
    enc = auth.hash_password("s3cret")
    assert auth.verify_password("s3cret", enc)
    assert not auth.verify_password("wrong", enc)


def test_token_issue_verify_expiry_refresh():
    ta = auth.TokenAuthority(ttl=100)
    token = ta.issue(7, "alice")
    claims = ta.verify(token)
    assert claims["id"] == 7 and claims["name"] == "alice"
    assert ta.verify(token + "x") is None
    assert ta.verify(token, now=time.time() + 200) is None
    assert ta.verify(ta.refresh(token)) is not None


def test_rbac_root_all_guest_read():
    db = Database()
    enforcer = auth.Enforcer(db)
    enforcer.init_policies()
    enforcer.add_role_for_user("admin", auth.ROOT_ROLE)
    enforcer.add_role_for_user("bob", auth.GUEST_ROLE)
    assert enforcer.enforce("admin", "clusters", "*")
    assert enforcer.enforce("bob", "clusters", "read")
    assert not enforcer.enforce("bob", "clusters", "*")
    assert not enforcer.enforce("nobody", "clusters", "read")
    enforcer.delete_role_for_user("bob", auth.GUEST_ROLE)
    assert not enforcer.enforce("bob", "clusters", "read")


def test_personal_access_token_verification():
    db = Database()
    now = time.time()
    db.create(
        "personal_access_tokens",
        {"name": "t", "token": "tok123", "state": "active", "expired_at": now + 60},
    )
    assert auth.verify_personal_access_token(db, "tok123") is not None
    assert auth.verify_personal_access_token(db, "nope") is None
    assert auth.verify_personal_access_token(db, "tok123", now=now + 120) is None


# ------------------------------------------------------------------ searcher


def test_searcher_weights_match_reference():
    # cidr(0.3) + hostname(0.3) + idc(0.25) + location(0.14) + default(0.01)
    scopes = msearcher.Scopes(
        idc="idc-a", location="area|zone|rack", cidrs=["10.0.0.0/8"], hostnames=["worker-.*"]
    )
    score = msearcher.evaluate(
        "10.1.2.3", "worker-7", {"idc": "idc-a", "location": "area|zone|rack"}, scopes, True
    )
    assert score == pytest.approx(1.0)
    # two of three leading location elements match -> 2/5 of 0.14
    partial = msearcher.multi_element_affinity_score("area|zone|other", "area|zone|rack")
    assert partial == pytest.approx(2 / 5)
    assert msearcher.idc_affinity_score("b", "a|b|c") == 1.0
    assert msearcher.cidr_affinity_score("192.168.1.1", ["10.0.0.0/8"]) == 0.0


def test_searcher_ranks_and_filters_clusters():
    s = msearcher.Searcher()
    near = {
        "name": "near",
        "scopes": {"idc": "idc-a"},
        "is_default": False,
        "schedulers": [{"host_name": "s1"}],
    }
    far = {
        "name": "far",
        "scopes": {"idc": "idc-z"},
        "is_default": True,
        "schedulers": [{"host_name": "s2"}],
    }
    empty = {"name": "empty", "scopes": {}, "is_default": True, "schedulers": []}
    ranked = s.find_scheduler_clusters([far, near, empty], "1.1.1.1", "h", {"idc": "idc-a"})
    assert [c["name"] for c in ranked] == ["near", "far"]
    with pytest.raises(ValueError):
        s.find_scheduler_clusters([empty], "1.1.1.1", "h", {})


# ------------------------------------------------------------------- service


def make_service(**kw) -> ManagerService:
    return ManagerService(Database(), **kw)


def test_service_root_user_and_signin():
    svc = make_service()
    token = svc.sign_in("root", "dragonfly")
    claims = svc.tokens.verify(token)
    assert claims["name"] == "root"
    assert svc.enforcer.enforce("root", "users", "*")
    with pytest.raises(PermissionError):
        svc.sign_in("root", "wrong")


def test_service_signup_gets_guest_role():
    svc = make_service()
    user = svc.sign_up("alice", "pw")
    assert "encrypted_password" not in user
    assert svc.enforcer.roles_for_user("alice") == [auth.GUEST_ROLE]


def test_service_cluster_composite():
    svc = make_service()
    cluster = svc.create_cluster({"name": "c1", "scopes": {"idc": "a"}})
    assert svc.db.count("scheduler_clusters") == 1
    assert svc.db.count("seed_peer_clusters") == 1
    svc.delete_cluster(cluster["id"])
    assert svc.db.count("scheduler_clusters") == 0
    assert svc.db.count("clusters") == 0


def test_service_keepalive_flips_state():
    svc = make_service()
    svc.create_cluster({"name": "c1"})
    rec = svc.register_scheduler(
        {"host_name": "sched-1", "ip": "10.0.0.1", "port": 8002, "scheduler_cluster_id": 1}
    )
    assert rec["state"] == "inactive"
    svc.keepalive("scheduler", "sched-1", "10.0.0.1", 1)
    assert svc.db.get("schedulers", rec["id"])["state"] == "active"
    # silent instance flips back on sweep
    svc.db.update("schedulers", rec["id"], {"keepalive_at": time.time() - 120})
    assert svc.expire_keepalives(timeout=60) == 1
    assert svc.db.get("schedulers", rec["id"])["state"] == "inactive"
    with pytest.raises(RecordNotFound):
        svc.keepalive("scheduler", "ghost", "0.0.0.0", 1)


def test_service_list_schedulers_ranked():
    svc = make_service()
    svc.create_cluster({"name": "a", "scopes": {"idc": "idc-a"}})
    svc.create_cluster({"name": "b", "scopes": {"idc": "idc-b"}})
    for i, cid in ((1, 1), (2, 2)):
        svc.register_scheduler(
            {"host_name": f"s{i}", "ip": f"10.0.0.{i}", "port": 8002, "scheduler_cluster_id": cid}
        )
        svc.keepalive("scheduler", f"s{i}", f"10.0.0.{i}", cid)
    ranked = svc.list_schedulers("1.1.1.1", "host", {"idc": "idc-b"})
    assert [s["host_name"] for s in ranked] == ["s2", "s1"]


def test_service_dynconfig_payload():
    svc = make_service()
    svc.create_cluster({"name": "c1", "scheduler_cluster_config": {"x": 1}})
    svc.register_seed_peer(
        {"host_name": "seed", "ip": "10.0.0.9", "port": 8002, "seed_peer_cluster_id": 1}
    )
    payload = svc.scheduler_dynconfig(1)
    assert payload["scheduler_cluster_config"] == {"x": 1}
    assert payload["seed_peers"][0]["host_name"] == "seed"


def test_service_model_lifecycle(tmp_path):
    from dragonfly2_tpu.registry.registry import ModelEvaluation, ModelRegistry

    registry = ModelRegistry(tmp_path)
    svc = make_service(registry=registry)
    params = {"w": [1.0, 2.0]}
    rec1 = svc.create_model("ranker", "gnn", "host-1", params, ModelEvaluation(recall=0.9))
    rec2 = svc.create_model("ranker", "gnn", "host-1", params, ModelEvaluation(recall=0.95))
    assert rec2["version"] == 2
    svc.activate_model(rec2["model_id"], 2)
    states = {r["version"]: r["state"] for r in svc.db.list("models")}
    assert states == {1: "inactive", 2: "active"}
    assert registry.active_version(rec2["model_id"]).version == 2


# ---------------------------------------------------------------------- REST


@pytest.fixture()
def rest_server():
    svc = make_service()
    server = ManagerREST(svc)
    server.start()
    yield server
    server.stop()


def _http(server: ManagerREST, method: str, path: str, body=None, token=None):
    url = f"http://{server.host}:{server.port}{path}"
    data = json.dumps(body).encode() if body is not None else None
    req = urllib.request.Request(url, data=data, method=method)
    req.add_header("Content-Type", "application/json")
    if token:
        req.add_header("Authorization", f"Bearer {token}")
    try:
        with urllib.request.urlopen(req) as resp:
            return resp.status, json.loads(resp.read())
    except urllib.error.HTTPError as e:
        return e.code, json.loads(e.read())


def test_rest_signin_and_crud(rest_server):
    status, out = _http(rest_server, "POST", "/api/v1/users/signin", {"name": "root", "password": "dragonfly"})
    assert status == 200
    token = out["token"]
    status, cluster = _http(
        rest_server, "POST", "/api/v1/clusters", {"name": "c1", "is_default": True}, token
    )
    assert status == 200 and cluster["name"] == "c1"
    status, clusters = _http(rest_server, "GET", "/api/v1/clusters", None, token)
    assert status == 200 and len(clusters) == 1
    status, _ = _http(rest_server, "DELETE", f"/api/v1/clusters/{cluster['id']}", None, token)
    assert status == 200


def test_rest_requires_auth_and_rbac(rest_server):
    status, _ = _http(rest_server, "GET", "/api/v1/clusters")
    assert status == 401
    # guest can read but not write
    _http(rest_server, "POST", "/api/v1/users/signup", {"name": "bob", "password": "pw"})
    status, out = _http(rest_server, "POST", "/api/v1/users/signin", {"name": "bob", "password": "pw"})
    guest_token = out["token"]
    status, _ = _http(rest_server, "GET", "/api/v1/clusters", None, guest_token)
    assert status == 200
    status, _ = _http(rest_server, "POST", "/api/v1/clusters", {"name": "x"}, guest_token)
    assert status == 401


def test_rest_auth_matrix_every_crud_group(rest_server):
    """Table-driven auth x RBAC over EVERY CRUD route group (router.go's
    19 handler groups behind jwt+casbin): unauthenticated reads 401,
    guest reads 200, guest writes 401, root writes reach the handler
    (any status except 401/403 — body validation may still reject).
    Enumerated from the live CRUD_TABLES so a newly added group is
    covered automatically."""
    from dragonfly2_tpu.manager.rest import CRUD_TABLES

    _, out = _http(rest_server, "POST", "/api/v1/users/signin",
                   {"name": "root", "password": "dragonfly"})
    root = out["token"]
    _http(rest_server, "POST", "/api/v1/users/signup",
          {"name": "matrix-guest", "password": "pw"})
    _, out = _http(rest_server, "POST", "/api/v1/users/signin",
                   {"name": "matrix-guest", "password": "pw"})
    guest = out["token"]

    from dragonfly2_tpu.manager.rest import _OPEN_ROUTES

    open_gets = {g for (m, g, sub) in _OPEN_ROUTES if m in ("GET", "*") and sub is None}
    for group in CRUD_TABLES:
        path = f"/api/v1/{group}"
        status, _ = _http(rest_server, "GET", path)
        if group in open_gets:
            # reference parity: router.go leaves GET /configs (and /jobs)
            # unauthenticated — pin THAT, not a blanket 401
            assert status == 200, f"{group}: open GET -> {status}"
        else:
            assert status == 401, f"{group}: unauthenticated GET -> {status}"
        status, _ = _http(rest_server, "GET", path, None, guest)
        assert status == 200, f"{group}: guest GET -> {status}"
        status, _ = _http(rest_server, "POST", path, {"name": f"x-{group}"}, guest)
        assert status == 401, f"{group}: guest POST -> {status}"
        status, _ = _http(rest_server, "POST", path, {"name": f"x-{group}"}, root)
        assert status not in (401, 403), f"{group}: root POST blocked ({status})"
        status, _ = _http(rest_server, "GET", path, None, "garbage-token")
        if group in open_gets:
            assert status == 200, f"{group}: open GET w/ bad token -> {status}"
        else:
            assert status == 401, f"{group}: garbage token GET -> {status}"


def test_rest_duplicate_is_409_and_missing_404(rest_server):
    _, out = _http(rest_server, "POST", "/api/v1/users/signin", {"name": "root", "password": "dragonfly"})
    token = out["token"]
    body = {"name": "app"}
    assert _http(rest_server, "POST", "/api/v1/applications", body, token)[0] == 200
    assert _http(rest_server, "POST", "/api/v1/applications", body, token)[0] == 409
    assert _http(rest_server, "GET", "/api/v1/applications/999", None, token)[0] == 404


def test_rest_list_pagination_and_query_filters(rest_server):
    """GET lists honor ?page/?per_page and treat remaining query params
    as query-by-example filters (GORM listing parity) — the old fixed
    per_page=100 silently truncated every list and every count derived
    from one."""
    _, out = _http(rest_server, "POST", "/api/v1/users/signin",
                   {"name": "root", "password": "dragonfly"})
    token = out["token"]
    for i in range(130):
        status, _ = _http(rest_server, "POST", "/api/v1/applications",
                          {"name": f"app-{i:03d}", "tier": "a" if i % 2 else "b"}, token)
        assert status == 200
    status, rows = _http(rest_server, "GET", "/api/v1/applications?per_page=1000",
                         None, token)
    assert status == 200 and len(rows) == 130
    status, rows = _http(rest_server, "GET", "/api/v1/applications", None, token)
    assert len(rows) == 100  # documented default page size
    status, page2 = _http(rest_server, "GET",
                          "/api/v1/applications?page=2&per_page=100", None, token)
    assert len(page2) == 30
    status, odd = _http(rest_server, "GET", "/api/v1/applications?tier=a&per_page=1000",
                        None, token)
    assert len(odd) == 65 and all(r["tier"] == "a" for r in odd)
    status, _ = _http(rest_server, "GET", "/api/v1/applications?per_page=bogus",
                      None, token)
    assert status == 400
    # a negative per_page must not become SQLite's LIMIT -1 (= unlimited)
    status, rows = _http(rest_server, "GET", "/api/v1/applications?per_page=-1",
                         None, token)
    assert status == 200 and len(rows) == 1
    # numeric-looking string filters match integer-typed JSON fields
    # (SQLite would otherwise compare 1 = '1' as false and return [])
    _http(rest_server, "POST", "/api/v1/applications",
          {"name": "int-field-app", "priority": 7}, token)
    status, pri = _http(rest_server, "GET", "/api/v1/applications?priority=7",
                        None, token)
    assert status == 200 and [r["name"] for r in pri] == ["int-field-app"]


def test_rest_pat_flow_and_oapi(rest_server):
    _, out = _http(rest_server, "POST", "/api/v1/users/signin", {"name": "root", "password": "dragonfly"})
    token = out["token"]
    status, pat = _http(
        rest_server, "POST", "/api/v1/personal-access-tokens", {"name": "ci"}, token
    )
    assert status == 200 and pat["state"] == "active"
    # oapi jobs with the PAT
    status, job = _http(rest_server, "POST", "/oapi/v1/jobs", {"type": "noop"}, pat["token"])
    assert status == 200 and job["state"] == "PENDING"
    status, _ = _http(rest_server, "GET", "/oapi/v1/clusters", None, "bad-token")
    assert status == 401


def test_rest_roles_endpoints(rest_server):
    _, out = _http(rest_server, "POST", "/api/v1/users/signin", {"name": "root", "password": "dragonfly"})
    token = out["token"]
    status, roles = _http(rest_server, "GET", "/api/v1/roles", None, token)
    assert status == 200 and set(roles) >= {"root", "guest"}
    status, perms = _http(rest_server, "GET", "/api/v1/roles/guest", None, token)
    assert status == 200 and {"object": "clusters", "action": "read"} in perms
    # grant bob root via the user-role route
    _http(rest_server, "POST", "/api/v1/users/signup", {"name": "bob", "password": "pw"})
    users = _http(rest_server, "GET", "/api/v1/users", None, token)[1]
    bob_id = next(u["id"] for u in users if u["name"] == "bob")
    assert _http(rest_server, "PUT", f"/api/v1/users/{bob_id}/roles/root", None, token)[0] == 200
    status, bob_roles = _http(rest_server, "GET", f"/api/v1/users/{bob_id}/roles", None, token)
    assert "root" in bob_roles


# ----------------------------------------------------------------------- RPC


def _run_async(coro):
    return asyncio.new_event_loop().run_until_complete(coro)


def test_manager_rpc_roundtrip(tmp_path):
    from dragonfly2_tpu.registry.registry import ModelRegistry
    from dragonfly2_tpu.training.checkpoint import params_to_bytes

    async def scenario():
        svc = ManagerService(Database(), registry=ModelRegistry(tmp_path))
        svc.create_cluster({"name": "c1", "scopes": {"idc": "idc-a"}})
        server = mrpc.ManagerRPCServer(svc)
        host, port = await server.start()
        client = await mrpc.ManagerClient(host, port).connect()
        try:
            reg = await client.call(
                mrpc.RegisterInstanceRequest(
                    source_type="scheduler", host_name="s1", ip="10.0.0.1", port=8002, cluster_id=1
                )
            )
            assert reg.id == 1
            await client.call(
                mrpc.KeepAliveRequest(
                    source_type="scheduler", host_name="s1", ip="10.0.0.1", cluster_id=1
                )
            )
            got = await client.call(
                mrpc.GetSchedulersRequest(ip="1.1.1.1", hostname="h", idc="idc-a")
            )
            assert [s.host_name for s in got.schedulers] == ["s1"]
            import numpy as np

            blob = params_to_bytes({"dense": {"kernel": np.ones((2, 2), np.float32)}})
            created = await client.call(
                mrpc.CreateModelRequest(
                    name="ranker",
                    type="gnn",
                    scheduler_host_id="s1-host",
                    params_blob=blob,
                    evaluation={"recall": 0.8},
                )
            )
            assert created.version == 1
            dyn = await client.call(mrpc.GetDynconfigRequest(scheduler_cluster_id=1))
            assert "scheduler_cluster_config" in dyn.data
            # error path: keepalive for unknown instance -> RuntimeError
            with pytest.raises(RuntimeError):
                await client.call(
                    mrpc.KeepAliveRequest(
                        source_type="scheduler", host_name="ghost", ip="0.0.0.0", cluster_id=1
                    )
                )
        finally:
            await client.close()
            await server.stop()

    _run_async(scenario())


def test_manager_rpc_stop_with_connected_client():
    """3.12's wait_closed() waits on in-flight handlers; a manager with a
    connected keepalive client must still stop promptly (the handlers are
    cancelled via ConnTracker before wait_closed)."""

    async def scenario():
        svc = ManagerService(Database())
        server = mrpc.ManagerRPCServer(svc)
        host, port = await server.start()
        client = await mrpc.ManagerClient(host, port).connect()
        # idle, long-lived connection held open across stop()
        await asyncio.wait_for(server.stop(), timeout=5.0)
        await client.close()

    _run_async(scenario())


# --------------------------------------------------------------- oauth2


class _StubIdP:
    """Fake provider: consent page is never rendered (the test follows the
    redirect by hand), /token validates the code+client creds, /userinfo
    validates the bearer token (manager/auth/oauth flow)."""

    CODE = "authcode-42"
    TOKEN = "idp-token-77"

    def __init__(self):
        import http.server
        import threading

        outer = self
        self.token_requests = []

        class Handler(http.server.BaseHTTPRequestHandler):
            def log_message(self, *a):
                pass

            def _json(self, obj, status=200):
                body = json.dumps(obj).encode()
                self.send_response(status)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def do_POST(self):
                if self.path != "/token":
                    self.send_error(404)
                    return
                length = int(self.headers.get("Content-Length") or 0)
                form = urllib.parse.parse_qs(self.rfile.read(length).decode())
                outer.token_requests.append(form)
                if (
                    form.get("code") == [outer.CODE]
                    and form.get("client_id") == ["cid"]
                    and form.get("client_secret") == ["csecret"]
                ):
                    self._json({"access_token": outer.TOKEN, "token_type": "bearer"})
                else:
                    self._json({"error": "bad_verification_code"}, 200)

            def do_GET(self):
                if self.path != "/userinfo":
                    self.send_error(404)
                    return
                if self.headers.get("Authorization") != f"Bearer {outer.TOKEN}":
                    self.send_error(401)
                    return
                self._json(
                    {"login": "octo-dev", "email": "octo@example.com",
                     "avatar_url": "http://a/x.png"}
                )

        import http.server as _h

        self._srv = _h.ThreadingHTTPServer(("127.0.0.1", 0), Handler)
        self.port = self._srv.server_address[1]
        threading.Thread(target=self._srv.serve_forever, daemon=True).start()

    def stop(self):
        self._srv.shutdown()
        self._srv.server_close()


def test_oauth_signin_full_flow():
    """VERDICT r1 item 6: the full authorization-code exchange against a
    stub provider — signin redirect carries state, the callback exchanges
    the code, creates the user on first signin, and issues the normal JWT
    that then authenticates real API calls."""
    idp = _StubIdP()
    try:
        svc = ManagerService(Database())
        base = f"http://127.0.0.1:{idp.port}"
        svc.db.create(
            "oauth",
            {
                "name": "github",
                "client_id": "cid",
                "client_secret": "csecret",
                "redirect_url": "http://manager/callback",
                "auth_url": f"{base}/authorize",
                "token_url": f"{base}/token",
                "userinfo_url": f"{base}/userinfo",
            },
        )
        rest = ManagerREST(svc)
        host, port = rest.start()
        try:
            import urllib.request

            # 1. signin -> 302 to the provider with client_id + state
            try:
                urllib.request.build_opener(_NoRedirect).open(
                    f"http://{host}:{port}/api/v1/users/signin/github"
                )
                raise AssertionError("expected a 302 redirect")
            except urllib.error.HTTPError as e:
                assert e.code == 302
                loc = e.headers["Location"]
            assert loc.startswith(f"{base}/authorize?")
            q = urllib.parse.parse_qs(urllib.parse.urlsplit(loc).query)
            assert q["client_id"] == ["cid"]
            state = q["state"][0]

            # 2. provider "redirects back" with a code; callback issues JWT
            with urllib.request.urlopen(
                f"http://{host}:{port}/api/v1/users/signin/github/callback"
                f"?code={_StubIdP.CODE}&state={state}"
            ) as r:
                token = json.loads(r.read())["token"]
            assert token
            claims = svc.tokens.verify(token)
            assert claims and claims["name"] == "octo-dev"
            user = svc.db.find_one("users", {"name": "octo-dev"})
            assert user is not None and user["email"] == "octo@example.com"

            # 3. a replayed/forged state is rejected
            import pytest as _pytest

            with _pytest.raises(urllib.error.HTTPError) as exc:
                urllib.request.urlopen(
                    f"http://{host}:{port}/api/v1/users/signin/github/callback"
                    f"?code={_StubIdP.CODE}&state={state}"
                )
            assert exc.value.code == 401
        finally:
            rest.stop()
    finally:
        idp.stop()


class _NoRedirect(urllib.request.HTTPRedirectHandler):
    def redirect_request(self, *a, **k):
        return None


def test_swagger_doc_lists_all_groups():
    """GET /swagger.json serves a machine-readable OpenAPI spec covering
    every route group (api/manager/docs.go parity, VERDICT r1 item 9)."""
    from dragonfly2_tpu.manager.rest import CRUD_TABLES

    svc = ManagerService(Database())
    rest = ManagerREST(svc)
    host, port = rest.start()
    try:
        with urllib.request.urlopen(f"http://{host}:{port}/swagger.json") as r:
            spec = json.loads(r.read())
        assert spec["openapi"].startswith("3.")
        tags = {
            tag for methods in spec["paths"].values()
            for opdef in methods.values() for tag in opdef["tags"]
        }
        for group in list(CRUD_TABLES) + [
            "users", "roles", "permissions", "jobs", "personal-access-tokens",
        ]:
            assert group in tags, group
        # the oauth signin routes are present with their path params
        assert "/api/v1/users/signin/{name}/callback" in spec["paths"]
    finally:
        rest.stop()


def test_console_served_and_drives_api():
    """GET / serves the embedded console (manager.go:61-63 parity) and the
    API calls the page makes (signin -> list clusters) work end-to-end."""
    svc = ManagerService(Database())
    svc.create_cluster({"name": "c1"})
    rest = ManagerREST(svc)
    host, port = rest.start()
    try:
        with urllib.request.urlopen(f"http://{host}:{port}/") as r:
            assert r.headers["Content-Type"].startswith("text/html")
            html = r.read().decode()
        assert "Dragonfly2-TPU Manager" in html and "users/signin" in html
        # the exact flow the console runs: signin, then a bearer-listed group
        req = urllib.request.Request(
            f"http://{host}:{port}/api/v1/users/signin",
            data=json.dumps({"name": "root", "password": "dragonfly"}).encode(),
            headers={"Content-Type": "application/json"},
        )
        with urllib.request.urlopen(req) as r:
            token = json.loads(r.read())["token"]
        req = urllib.request.Request(
            f"http://{host}:{port}/api/v1/clusters",
            headers={"Authorization": f"Bearer {token}"},
        )
        with urllib.request.urlopen(req) as r:
            clusters = json.loads(r.read())
        assert [c["name"] for c in clusters] == ["c1"]
        # the overview tab + model-activation affordances ship in the page
        assert "overview" in html and "scheduler health" in html
        assert "activate" in html and "PATCH" in html
    finally:
        rest.stop()


def test_oauth_display_name_cannot_shadow_local_users(monkeypatch):
    """An IdP display name of 'root' must NOT sign in as (or create) the
    bootstrap root account: linking keys on the provider's stable subject
    id, and colliding display names get a provider-scoped username."""
    svc = ManagerService(Database())
    svc.db.create("oauth", {"name": "github", "client_id": "c", "client_secret": "s"})
    provider = svc._oauth_provider("github")
    monkeypatch.setattr(provider, "check_state", lambda s: True)
    monkeypatch.setattr(provider, "exchange", lambda code: "tok")
    monkeypatch.setattr(
        provider, "get_user",
        lambda tok: {"subject": "9001", "name": "root", "email": "", "avatar": ""},
    )
    token = svc.oauth_signin_callback("github", "code", state="x")
    claims = svc.tokens.verify(token)
    assert claims["name"] == "root@github:9001"  # never the local root
    root = svc.db.find_one("users", {"name": "root"})
    assert root is not None and "oauth_subject" not in root
    # second signin reuses the SAME linked account (stable subject)
    token2 = svc.oauth_signin_callback("github", "code", state="x")
    assert svc.tokens.verify(token2)["name"] == "root@github:9001"
    assert svc.db.count("users") == 2  # root + the one oauth user


def test_oauth_callback_requires_state():
    svc = ManagerService(Database())
    svc.db.create("oauth", {"name": "github", "client_id": "c", "client_secret": "s"})
    with pytest.raises(PermissionError, match="state"):
        svc.oauth_signin_callback("github", "code", state="")


def test_get_job_refreshes_preheat_state_live():
    """GET /jobs/:id recomputes a preheat's state from the schedulers'
    live task FSMs (machinery group polling semantics): PENDING at create,
    SUCCESS once every task succeeded, persisted back into the record."""
    from dragonfly2_tpu.cluster import messages as cmsg
    from dragonfly2_tpu.cluster.jobs import JobManager
    from dragonfly2_tpu.cluster.scheduler import SchedulerService

    sched = SchedulerService()
    seed = cmsg.HostInfo(host_id="seed-0", hostname="seed-0", ip="10.1.0.0",
                         host_type="super")
    sched.announce_host(seed)
    jm = JobManager({"s1": sched}, [seed])
    svc = ManagerService(Database(), jobs=jm)
    record = svc.create_job({"type": "preheat", "args": {"url": "https://e.com/blob"}})
    assert record["state"] == "PENDING"
    # GET while the seed has not downloaded anything: still PENDING
    assert svc.get_job(record["id"])["state"] == "PENDING"
    # drive the task to SUCCEEDED the way a finished seed download would
    task_id = record["result"]["task_ids"][0]
    sched.register_peer(cmsg.RegisterPeerRequest(
        peer_id="p-1", task_id=task_id, host=seed, url="https://e.com/blob",
        content_length=10 << 20,
    ))
    sched.back_to_source_started(cmsg.DownloadPeerBackToSourceStartedRequest(peer_id="p-1"))
    sched.back_to_source_finished(
        cmsg.DownloadPeerBackToSourceFinishedRequest(peer_id="p-1", piece_count=3)
    )
    refreshed = svc.get_job(record["id"])
    assert refreshed["state"] == "SUCCESS"
    # persisted: a raw DB read shows the updated state too
    assert svc.db.get("jobs", record["id"])["state"] == "SUCCESS"
