"""Masked top-k selection.

The TPU-native replacement for the reference's sort-by-score parent
selection (evaluator_base.go:59-68 sort.Slice + scheduling.go candidate
truncation): invalid candidates are pushed below every real score so
selection never picks them, and validity flows back out as a mask.

`lax.top_k` lowers to a full cross-lane sort on TPU (~0.33 ms at the
1024x64 serving shape — the single biggest term in the scheduler's p50
budget). For the small candidate widths the scheduler actually uses
(K <= 128), an exact rank-by-pairwise-comparison select is ~9x faster:
rank[i] = #{j : score_j > score_i, or equal score with lower index},
which is a strict total order, so ranks are a permutation and a one-hot
matmul scatters the top-k elements into place with no sort at all —
pure VPU compares + an MXU-shaped einsum, fully fusable by XLA.

The mask sentinel is float32 min rather than -inf: the one-hot einsum
multiplies every element by 0-or-1 weights, and IEEE -inf * 0 is NaN
(the TPU MXU happens to flush it, the CPU backend does not). Validity is
derived from the per-row eligible COUNT, never from sentinel compares,
so real scores only need to stay above float32 min — every evaluator
blend is within a few orders of magnitude of 1.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

NEG_INF = jnp.float32(-jnp.inf)
_FINITE_MIN = jnp.float32(jnp.finfo(jnp.float32).min)
# Real scores are clamped to this floor BEFORE masking, and the mask
# sentinel sits strictly below it: an externally supplied -inf/NaN score
# (plugin / ml path) must still rank above every masked-out candidate, or
# the rank order would select blocklisted entries into "valid" slots
# (validity is derived from the eligible COUNT, not score compares).
_SCORE_FLOOR = jnp.float32(-1e37)

# Above this candidate width the (B, K, K) comparison tensor stops being
# cheap and lax.top_k's sort wins; every scheduler path sits well below.
_RANK_SELECT_MAX_WIDTH = 128


def _masked_top_k_rank(
    scores: jax.Array, mask: jax.Array, k: int
) -> tuple[jax.Array, jax.Array, jax.Array]:
    """Exact top-k via pairwise ranking (no sort). Matches lax.top_k's
    value order and lowest-index tie-break for non-NaN input."""
    n = scores.shape[-1]
    sane = jnp.maximum(jnp.nan_to_num(scores, nan=_SCORE_FLOOR, neginf=_SCORE_FLOOR), _SCORE_FLOOR)
    masked = jnp.where(mask, sane, _FINITE_MIN)
    idx = jnp.arange(n, dtype=jnp.int32)
    s_i = masked[..., :, None]  # element i        (..., K, 1)
    s_j = masked[..., None, :]  # vs element j     (..., 1, K)
    # j outranks i when it scores higher, or ties with a lower index.
    outranks = (s_j > s_i) | ((s_j == s_i) & (idx[None, :] < idx[:, None]))
    rank = outranks.sum(axis=-1).astype(jnp.int32)  # (..., K), a permutation
    pos = jnp.arange(k, dtype=jnp.int32)
    onehot = (rank[..., None] == pos).astype(jnp.float32)  # (..., K, k)
    values = jnp.einsum("...k,...kp->...p", masked, onehot)
    indices = jnp.einsum(
        "...k,...kp->...p", idx.astype(jnp.float32) + jnp.zeros_like(masked), onehot
    ).astype(jnp.int32)
    valid = pos < mask.sum(axis=-1, dtype=jnp.int32)[..., None]  # (..., k)
    return jnp.where(valid, values, NEG_INF), indices, valid


def masked_top_k(
    scores: jax.Array, mask: jax.Array, k: int
) -> tuple[jax.Array, jax.Array, jax.Array]:
    """Top-k along the last axis honoring a validity mask.

    Returns (values, indices, valid): `valid[i, j]` is False for slots that
    had fewer than j+1 valid candidates (their value is -inf). Ties break
    toward lower index (same contract as lax.top_k).
    """
    scores = scores.astype(jnp.float32)
    if scores.shape[-1] <= _RANK_SELECT_MAX_WIDTH:
        return _masked_top_k_rank(scores, mask, k)
    # Wide fallback keeps the SAME hostile-score contract as the rank
    # path: sanitize NaN/-inf up to the score floor so eligible-but-awful
    # candidates still outrank masked ones, and derive validity from the
    # eligible COUNT, never from sentinel compares.
    sane = jnp.maximum(
        jnp.nan_to_num(scores, nan=_SCORE_FLOOR, neginf=_SCORE_FLOOR), _SCORE_FLOOR
    )
    masked = jnp.where(mask, sane, _FINITE_MIN)
    values, indices = jax.lax.top_k(masked, k)
    pos = jnp.arange(k, dtype=jnp.int32)
    valid = pos < mask.sum(axis=-1, dtype=jnp.int32)[..., None]
    return jnp.where(valid, values, NEG_INF), indices, valid
