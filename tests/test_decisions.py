"""Decision provenance ledger + counterfactual shadow scoring (ISSUE 13):
per-decision candidate provenance, outcome joins, shadow divergence/
regret, the dfwhy explainer, and the ledger→trainer exporter."""

import json

import numpy as np
import pytest

from dragonfly2_tpu.cluster import messages as msg
from dragonfly2_tpu.cluster.scheduler import _EVAL_BUCKETS, SchedulerService
from dragonfly2_tpu.config.config import Config
from dragonfly2_tpu.telemetry import metrics as m
from dragonfly2_tpu.telemetry.decisions import (
    ARM_CODES,
    OUTCOME_COMPLETED,
    OUTCOME_FAILED,
    DecisionLedger,
)

# ------------------------------------------------------------- helpers


def _host(i, seed=False, idc="idc-a"):
    return msg.HostInfo(
        host_id=f"dc-h{i}", hostname=f"dc-n{i}", ip=f"10.21.{i // 250}.{i % 250}",
        host_type="super" if seed else "normal", idc=idc,
        location="na|zone|rack", concurrent_upload_limit=1000,
    )


def _register(svc, peer_id, h, task_id="dc-task", **kw):
    return svc.register_peer(
        msg.RegisterPeerRequest(
            peer_id=peer_id, task_id=task_id, host=h,
            url="https://e.com/blob", content_length=4 * (4 << 20),
            total_piece_count=4, **kw,
        )
    )


def _seeded_service(reg=None, algorithm="default", ml=None):
    cfg = Config()
    cfg.evaluator.algorithm = algorithm
    svc = SchedulerService(
        config=cfg, metrics_registry=reg or m.Registry(), ml_evaluator=ml
    )
    _register(svc, "dc-seed", _host(0, seed=True))
    svc.peer_finished(
        msg.DownloadPeerFinishedRequest(peer_id="dc-seed", piece_count=4)
    )
    svc.tick()
    return svc


def _served_ml(tmp_path, feat_dim, hidden=16):
    import jax

    from dragonfly2_tpu.models.graphsage import GraphSAGERanker
    from dragonfly2_tpu.registry import (
        MLEvaluator,
        ModelEvaluation,
        ModelRegistry,
        ModelServer,
    )
    from dragonfly2_tpu.registry.registry import MODEL_TYPE_GNN

    model = GraphSAGERanker(hidden_dim=hidden)
    graph = {
        "node_feats": np.zeros((8, feat_dim), np.float32),
        "edge_src": np.zeros(2, np.int32),
        "edge_dst": np.zeros(2, np.int32),
        "edge_feats": np.zeros((2, 2), np.float32),
    }
    params = model.init(
        jax.random.key(0), graph, np.zeros(1, np.int32),
        np.zeros((1, 2), np.int32), np.zeros((1, 2, 2), np.float32),
    )
    reg = ModelRegistry(tmp_path)
    server = ModelServer(reg, "ranker", "h", MODEL_TYPE_GNN,
                         template_params=params)
    mv = reg.create_model_version(
        "ranker", MODEL_TYPE_GNN, "h", params, ModelEvaluation(),
        metadata={"hidden_dim": hidden},
    )
    reg.activate(mv.model_id, mv.version)
    assert server.refresh()
    return MLEvaluator(server)


# ---------------------------------------------------------- core ledger


def test_ledger_records_applied_selections_and_joins_outcomes():
    reg = m.Registry()
    svc = _seeded_service(reg)
    for i in range(4):
        _register(svc, f"dc-c{i}", _host(i + 1))
        responses = svc.tick()
        assert isinstance(responses[-1], msg.NormalTaskResponse)
    led = svc.decisions
    assert led is not None
    assert led.counters()["decisions"] == 4
    dump = led.dump()
    row = next(r for r in dump["rows"] if r["peer"] == "dc-c3")
    # the recorded chosen parent is the response's first kept parent
    assert row["chosen_parent"] is not None
    assert row["arm"] == "default"
    assert row["candidates"], "candidate set missing"
    ranked = [c for c in row["candidates"] if "rank" in c]
    assert ranked, "no ranked candidates recorded"
    chosen = next(c for c in row["candidates"] if c["pos"] == row["chosen_pos"])
    assert chosen["accepted"] is True
    # every candidate carries the compact feature row
    for c in row["candidates"]:
        assert set(c["features"]) == set(dump["features"])
    # outcome join: completed with a measured TTC + bytes
    assert row["outcome"]["state"] == "pending"
    svc.peer_finished(msg.DownloadPeerFinishedRequest(
        peer_id="dc-c3", piece_count=4, content_length=1234,
    ))
    row2 = next(r for r in led.dump()["rows"] if r["peer"] == "dc-c3")
    assert row2["outcome"]["state"] == "completed"
    assert row2["outcome"]["bytes"] == 1234
    assert row2["outcome"]["ttc_ms"] is not None
    assert led.counters()["joined"] == 1
    # metric families exported under the scheduler decision namespace
    text = reg.expose()
    assert 'dragonfly_scheduler_decision_total{arm="default"} 4' in text
    assert 'dragonfly_scheduler_decision_outcome_total{outcome="completed"} 1' in text
    assert "dragonfly_scheduler_decision_ledger_occupancy" in text
    assert "dragonfly_scheduler_decision_join_latency_seconds" in text


def test_ledger_outcome_variants_and_marks():
    svc = _seeded_service()
    for i, pid in enumerate(("dc-f", "dc-b", "dc-x")):
        _register(svc, pid, _host(i + 1))
        svc.tick()
    led = svc.decisions
    # corruption attribution marks the CHILD's decision
    parent = next(
        r["chosen_parent"] for r in led.dump()["rows"] if r["peer"] == "dc-x"
    )
    svc.piece_failed(msg.DownloadPieceFailedRequest(
        peer_id="dc-x", parent_peer_id=parent, reason="corruption",
    ))
    svc.peer_failed(msg.DownloadPeerFailedRequest(peer_id="dc-f"))
    svc.back_to_source_started(
        msg.DownloadPeerBackToSourceStartedRequest(peer_id="dc-b")
    )
    rows = {r["peer"]: r for r in led.dump()["rows"]}
    assert rows["dc-f"]["outcome"]["state"] == "failed"
    assert rows["dc-b"]["outcome"]["state"] == "back_to_source"
    assert rows["dc-x"]["outcome"]["corruption"] is True
    # failover mark: a known peer re-announcing with kept pieces
    _register(svc, "dc-x", _host(3), finished_pieces=[0, 1])
    assert {r["peer"]: r for r in led.dump()["rows"]}["dc-x"]["outcome"][
        "failover"
    ] is True


def test_ledger_ring_bound_and_eviction():
    led = DecisionLedger(capacity=8, k=4, limit=2, registry=m.Registry())
    one = lambda v: np.asarray([v])  # noqa: E731
    for i in range(20):
        led.record_batch(
            1, ARM_CODES["default"], one(i), one(i),
            np.asarray([[0, 1, 2, 3]]), np.asarray([[0, 1, 2, 3]]),
            one(4), np.zeros((1, 4, 8), np.float32),
            np.asarray([[0, 1]]), np.asarray([[1.0, 0.5]], np.float32),
            np.asarray([[True, False]]), one(0),
            [f"p{i}"], ["t"], [f"par{i}"],
        )
    assert led.counters()["decisions"] == 20
    assert int((led.seq > 0).sum()) == 8
    dump = led.dump()
    assert [r["peer"] for r in dump["rows"]] == [f"p{i}" for i in range(12, 20)]
    # evicted peers' join mappings are gone; live ones join fine
    assert led.join_outcome("p3", OUTCOME_COMPLETED) is False
    assert led.join_outcome("p19", OUTCOME_COMPLETED) is True


def test_divergence_and_regret_math():
    led = DecisionLedger(capacity=64, k=4, limit=3, registry=m.Registry())
    n = 4
    slots, seqs = led.record_batch(
        7, ARM_CODES["ml"],
        np.arange(n), np.arange(n),
        np.tile(np.arange(4), (n, 1)),
        # candidate HOSTS: candidate pos j lives on host j (all rows)
        np.tile(np.arange(4), (n, 1)),
        np.full(n, 4), np.zeros((n, 4, 8), np.float32),
        # active ranking: every row picks pos 0 then 1 then 2
        np.tile(np.asarray([0, 1, 2]), (n, 1)),
        np.tile(np.asarray([3.0, 2.0, 1.0], np.float32), (n, 1)),
        np.ones((n, 3), bool), np.zeros(n, np.int64),
        [f"pr{i}" for i in range(n)], ["t"] * n, ["x"] * n,
    )
    # shadow: rows 0,1 agree on top-1; rows 2,3 pick pos 1 first
    shadow_pos = np.asarray([
        [0, 1, 2],      # identical -> rho 1.0
        [0, 2, 1],      # same top-1, tail swapped
        [1, 0, 2],      # top-1 disagrees
        [1, 2, 0],      # top-1 disagrees
    ])
    entry = led.record_shadow(
        slots, seqs, shadow_pos, np.zeros((n, 3), np.float32),
        ARM_CODES["default"], 7,
    )
    assert entry["compared"] == 4
    assert entry["top1_disagreement"] == 0.5
    # rho per row: [1.0, corr([0,1,2],[0,2,1])=0.5, 0.5, corr([0,1,2],[2,0,1])=-0.5]
    assert entry["rank_corr"] == pytest.approx((1.0 + 0.5 + 0.5 - 0.5) / 4)
    # outcomes: host 0 (active pick) always fails; host 1 (shadow pick
    # on the disagreements) completes — regret must surface positive
    # fail-rate delta for the active (ml) arm
    led.join_outcome("pr0", OUTCOME_FAILED)
    led.join_outcome("pr1", OUTCOME_FAILED)
    led.join_outcome("pr2", OUTCOME_FAILED)
    # a separate decision whose CHOSEN host is 1, completing:
    s2, _ = led.record_batch(
        8, ARM_CODES["ml"], np.asarray([9]), np.asarray([9]),
        np.asarray([[0, 1, 2, 3]]), np.asarray([[1, 1, 1, 1]]),
        np.asarray([4]), np.zeros((1, 4, 8), np.float32),
        np.asarray([[0, -1, -1]]), np.asarray([[1.0, np.nan, np.nan]], np.float32),
        np.asarray([[True, False, False]]), np.asarray([0]),
        ["pr9"], ["t"], ["y"],
    )
    assert s2.size == 1
    led.join_outcome("pr9", OUTCOME_COMPLETED)
    regret = led.regret()
    assert regret["n_joined"] == 4
    assert regret["n_disagreements"] == 2
    arm = regret["by_arm"]["ml"]
    # active picks host 0 (fail rate 1.0), shadow host 1 (fail rate 0.0)
    assert arm["regret_fail_rate"] == pytest.approx(1.0)
    # host 0 has NO completed download, so no TTC mean exists for it —
    # the TTC basis must abstain rather than treat fast failures as
    # fast downloads (review finding: failed rows' TTC inverted regret)
    assert arm["regret_ttc_ms"] is None
    assert led.divergence_summary()["top1_disagreement"] == 0.5


def test_shadow_join_rejects_overwritten_slots():
    """A tick recording more decisions than the ring capacity must not
    cross-match shadow data onto recycled slots: record_shadow skips
    rows whose (slot, seq) no longer agree."""
    led = DecisionLedger(capacity=8, k=4, limit=2, registry=m.Registry())
    args = lambda n, names: (  # noqa: E731
        np.arange(n), np.arange(n),
        np.tile(np.arange(4), (n, 1)), np.tile(np.arange(4), (n, 1)),
        np.full(n, 4), np.zeros((n, 4, 8), np.float32),
        np.tile(np.asarray([0, 1]), (n, 1)),
        np.ones((n, 2), np.float32), np.ones((n, 2), bool),
        np.zeros(n, np.int64), names, ["t"] * n, ["x"] * n,
    )
    slots1, seqs1 = led.record_batch(1, 0, *args(6, [f"a{i}" for i in range(6)]))
    # second chunk of the SAME tick wraps the 8-slot ring over chunk 1
    led.record_batch(1, 0, *args(6, [f"b{i}" for i in range(6)]))
    entry = led.record_shadow(
        slots1, seqs1, np.tile(np.asarray([1, 0]), (6, 1)),
        np.zeros((6, 2), np.float32), 2, 1,
    )
    # only the chunk-1 rows NOT overwritten by chunk 2 compared
    assert entry["compared"] == 2
    # and no b-row silently acquired chunk-1 shadow data
    for r in led.dump()["rows"]:
        if r["peer"] and r["peer"].startswith("b"):
            assert r["shadow_arm"] is None, r
    # ONE batch larger than the whole ring: only the newest `capacity`
    # rows survive, dropped rows return slot -1, and no dropped peer's
    # mapping can cross-join an outcome onto a survivor's columns
    led2 = DecisionLedger(capacity=8, k=4, limit=2, registry=m.Registry())
    slots, seqs = led2.record_batch(
        1, 0, *args(12, [f"c{i}" for i in range(12)])
    )
    assert slots.shape == (12,) and (slots[:4] == -1).all()
    assert (slots[4:] >= 0).all() and len(set(slots[4:].tolist())) == 8
    assert led2.join_outcome("c0", OUTCOME_COMPLETED) is False  # dropped
    assert led2.join_outcome("c11", OUTCOME_COMPLETED) is True
    assert [r["peer"] for r in led2.dump()["rows"]] == [
        f"c{i}" for i in range(4, 12)
    ]


def test_ledger_deterministic_digest_stability():
    def build():
        led = DecisionLedger(capacity=16, k=4, limit=2, registry=m.Registry())
        slots, seqs = led.record_batch(
            3, ARM_CODES["default"], np.asarray([1, 2]), np.asarray([1, 2]),
            np.asarray([[0, 1, 2, 3]] * 2), np.asarray([[4, 5, 6, 7]] * 2),
            np.asarray([4, 3]), np.ones((2, 4, 8), np.float32),
            np.asarray([[0, 1]] * 2), np.asarray([[1.0, 0.5]] * 2, np.float32),
            np.asarray([[True, True]] * 2), np.asarray([0, 0]),
            ["a", "b"], ["t", "t"], ["x", "y"],
        )
        led.record_shadow(
            slots, seqs, np.asarray([[1, 0]] * 2),
            np.asarray([[2.0, 1.0]] * 2, np.float32), ARM_CODES["ml"], 3,
        )
        return led

    l1, l2 = build(), build()
    assert l1.deterministic_digest() == l2.deterministic_digest()
    # wall-clock columns differ between the two builds but are excluded
    l2.join_outcome("a", OUTCOME_COMPLETED, bytes_=10)
    assert l1.deterministic_digest() != l2.deterministic_digest()


# ------------------------------------------------------- shadow scoring


def test_shadow_rule_active_ml_counterfactual(tmp_path):
    """Rule arm serving, committed ml snapshot shadow-scoring: every
    applied decision gets a shadow ranking, per-tick divergence lands in
    the ring, and the serving jits route ONLY the proven bucket set
    (zero new compile signatures — the retrace-tripwire contract)."""
    from tools.dflint.retracer import SERVING_B_ARGS, observed_batch_buckets

    from dragonfly2_tpu.telemetry.flight import jit_wrappers

    feat_dim = SchedulerService(
        metrics_registry=m.Registry()
    ).state.host_numeric.shape[1]
    ml = _served_ml(tmp_path, feat_dim)
    try:
        svc = _seeded_service(algorithm="default", ml=ml)
        ml.refresh_embeddings(svc.serving_graph_arrays(), wait=True)
        assert ml.serving_snapshot() is not None
        svc.warmup()  # warms the ml SHADOW entry too -> shadow-ready
        for i in range(5):
            _register(svc, f"dc-s{i}", _host(i + 1))
            svc.tick()
        led = svc.decisions
        c = led.counters()
        assert c["decisions"] == 5 and c["shadow_compared"] == 5
        assert led.divergence_ring, "no per-tick divergence entries"
        row = led.dump()["rows"][-1]
        assert row["arm"] == "default" and row["shadow_arm"] == "ml"
        assert row["shadow_agrees_top1"] is not None
        shadow_ranked = [c_ for c_ in row["candidates"] if "shadow_rank" in c_]
        assert shadow_ranked, "shadow ranking missing from the dump"
        # the counterfactual must not claim the ml version SERVED: the
        # rule blend served every tick, so the refresh/serve audit trail
        # stays on its rule-served sentinel (review finding)
        assert ml.last_used_versions is None
        # last_n=0 means NO rows, not all of them
        assert led.dump(last_n=0)["rows"] == []
        # compile-signature discipline: both serving entries observed
        # only statically-proven buckets
        for name, b_arg in SERVING_B_ARGS.items():
            w = jit_wrappers().get(name)
            if w is None:
                continue
            observed = observed_batch_buckets(w, b_arg) - {None}
            assert observed <= set(_EVAL_BUCKETS), (name, observed)
    finally:
        ml.close()


def test_shadow_ml_active_rule_counterfactual(tmp_path):
    feat_dim = SchedulerService(
        metrics_registry=m.Registry()
    ).state.host_numeric.shape[1]
    ml = _served_ml(tmp_path, feat_dim)
    try:
        svc = _seeded_service(algorithm="ml", ml=ml)
        ml.refresh_embeddings(svc.serving_graph_arrays(), wait=True)
        for i in range(4):
            _register(svc, f"dc-m{i}", _host(i + 1))
            svc.tick()
        led = svc.decisions
        assert led.counters()["shadow_compared"] == 4
        row = led.dump()["rows"][-1]
        assert row["arm"] == "ml" and row["shadow_arm"] == "default"
        # the shadow_score phase is recorded and excluded from the
        # control/device aggregates
        last_tick = svc.recorder.ring[-1]
        assert last_tick.get("shadow_score", 0.0) > 0.0
        assert "shadow_score" in svc.recorder.phase_p50s()
    finally:
        ml.close()


def test_shadow_disabled_paths():
    # config off: ledger records, no shadow
    cfg = Config()
    cfg.scheduler.shadow_scoring = False
    svc = SchedulerService(config=cfg, metrics_registry=m.Registry())
    _register(svc, "dc-seed", _host(0, seed=True))
    svc.peer_finished(
        msg.DownloadPeerFinishedRequest(peer_id="dc-seed", piece_count=4)
    )
    svc.tick()
    _register(svc, "dc-nsh", _host(1))
    svc.tick()
    assert svc.decisions.counters()["shadow_compared"] == 0
    # ledger off entirely: tick still works, no ledger attached
    cfg2 = Config()
    cfg2.scheduler.decision_ledger = False
    svc2 = SchedulerService(config=cfg2, metrics_registry=m.Registry())
    assert svc2.decisions is None
    _register(svc2, "dc-seed2", _host(0, seed=True))
    svc2.peer_finished(
        msg.DownloadPeerFinishedRequest(peer_id="dc-seed2", piece_count=4)
    )
    svc2.tick()
    _register(svc2, "dc-off", _host(1))
    assert any(
        isinstance(r, msg.NormalTaskResponse) for r in svc2.tick()
    )


def test_shadow_every_thins_the_counterfactual_cadence(tmp_path):
    """shadow_every=N shadows every Nth tick, keyed on the deterministic
    tick counter — the 1/N-cost sampling knob for CPU-device rigs."""
    feat_dim = SchedulerService(
        metrics_registry=m.Registry()
    ).state.host_numeric.shape[1]
    ml = _served_ml(tmp_path, feat_dim)
    try:
        cfg = Config()
        cfg.scheduler.shadow_every = 2
        svc = SchedulerService(
            config=cfg, metrics_registry=m.Registry(), ml_evaluator=ml
        )
        _register(svc, "dc-seed", _host(0, seed=True))
        svc.peer_finished(
            msg.DownloadPeerFinishedRequest(peer_id="dc-seed", piece_count=4)
        )
        svc.tick()
        ml.refresh_embeddings(svc.serving_graph_arrays(), wait=True)
        svc.warmup()
        for i in range(6):
            _register(svc, f"dc-e{i}", _host(i + 1))
            svc.tick()
        c = svc.decisions.counters()
        assert c["decisions"] == 6
        assert 0 < c["shadow_compared"] < 6
    finally:
        ml.close()


def test_late_snapshot_commit_warms_shadow_off_the_tick(tmp_path):
    """A snapshot committing AFTER cold start must not compile the ml
    shadow program inside a serving tick: shadow stays off, a one-shot
    background warm runs, and shadow engages once it lands (review
    finding: the mid-tick XLA compile stall)."""
    feat_dim = SchedulerService(
        metrics_registry=m.Registry()
    ).state.host_numeric.shape[1]
    ml = _served_ml(tmp_path, feat_dim)
    try:
        svc = _seeded_service(algorithm="default", ml=ml)
        # warmup BEFORE any snapshot: the ml shadow entry is not warm
        svc.warmup()
        assert not svc._shadow_ml_ready
        ml.refresh_embeddings(svc.serving_graph_arrays(), wait=True)
        _register(svc, "dc-l0", _host(1))
        svc.tick()  # shadow unavailable -> skipped; background warm spawns
        assert svc.decisions.counters()["shadow_compared"] == 0
        t = svc._shadow_warm_thread
        assert t is not None
        t.join(timeout=30)
        assert svc._shadow_ml_ready
        _register(svc, "dc-l1", _host(2))
        svc.tick()
        assert svc.decisions.counters()["shadow_compared"] == 1
    finally:
        ml.close()


def test_oracle_path_records_equivalent_provenance():
    """vectorized_control=False (the decision-equivalence oracle) must
    record the same provenance shape the production path does."""
    cfg = Config()
    cfg.scheduler.vectorized_control = False
    svc = SchedulerService(config=cfg, metrics_registry=m.Registry())
    _register(svc, "dc-seed", _host(0, seed=True))
    svc.peer_finished(
        msg.DownloadPeerFinishedRequest(peer_id="dc-seed", piece_count=4)
    )
    svc.tick()
    for i in range(3):
        _register(svc, f"dc-o{i}", _host(i + 1))
        svc.tick()
    led = svc.decisions
    assert led.counters()["decisions"] == 3
    row = led.dump()["rows"][-1]
    assert row["chosen_parent"] is not None
    assert any("rank" in c for c in row["candidates"])


# ------------------------------------------- dfwhy + trainer exporter


def _scenario_lab_dump(tmp_path):
    """A small scenario-lab replay's ledger dump written to disk — the
    artifact dfwhy and the trainer exporter consume."""
    from dragonfly2_tpu.cluster.simulator import ClusterSimulator
    from dragonfly2_tpu.scenarios.spec import builtin_scenarios

    spec = builtin_scenarios()["bandwidth_skew"]
    svc = SchedulerService(metrics_registry=m.Registry())
    sim = ClusterSimulator(svc, num_hosts=48, num_tasks=4, seed=5, scenario=spec)
    rounds = 0
    while svc.decisions.counters()["joined"] < 8 and rounds < 400:
        sim.run_round(8)
        rounds += 1
    dump = svc.decisions.dump(last_n=256)
    path = tmp_path / "decisions.json"
    path.write_text(json.dumps(dump))
    return svc, dump, path


def test_dfwhy_reconstructs_candidate_explanation(tmp_path, capsys):
    from tools import dfwhy

    _svc, dump, path = _scenario_lab_dump(tmp_path)
    target = next(
        r for r in reversed(dump["rows"]) if r["chosen_parent"] is not None
    )
    rc = dfwhy.main([str(path), "--peer", target["peer"], "--last"])
    out = capsys.readouterr().out
    assert rc == 0
    assert f"peer={target['peer']}" in out
    assert target["chosen_parent"] in out
    assert "cand[" in out and "score=" in out
    assert "outcome=" in out
    # every candidate in the record appears in the explanation
    assert out.count("cand[") == len(target["candidates"])
    # --parent narrows to decisions involving that parent
    rc2 = dfwhy.main([
        str(path), "--peer", target["peer"], "--parent",
        target["chosen_parent"],
    ])
    assert rc2 == 0
    # unknown peer exits 1; a rows-free file exits 2
    assert dfwhy.main([str(path), "--peer", "nope"]) == 1
    empty = tmp_path / "empty.json"
    empty.write_text("{}")
    assert dfwhy.main([str(empty), "--peer", "x"]) == 2


def test_ledger_to_trainer_exporter(tmp_path):
    from dragonfly2_tpu.training.data import (
        decision_rank_batches,
        decision_rows,
        decisions_to_rank_arrays,
    )

    _svc, dump, _path = _scenario_lab_dump(tmp_path)
    rows = decision_rows(dump)
    assert rows, "exporter found no rows in the ledger dump"
    arrays = decisions_to_rank_arrays(rows)
    n, p = arrays["parent_idx"].shape
    assert n > 0, "no joined completed decisions to export"
    assert arrays["child_idx"].shape == (n,)
    assert arrays["pair_feats"].shape == (n, p, 2)
    # logged-bandit labeling: exactly one labeled action per decision
    assert (arrays["mask"].sum(axis=1) == 1).all()
    labeled = arrays["throughput"][arrays["mask"]]
    assert np.isfinite(labeled).all() and (labeled > 0).all()
    # the label basis is the replay-safe reported-piece-cost column, not
    # wall TTC (a replay's wall interval measures the host, not the
    # parent): completed rows carry it in the dump
    completed = [r for r in rows if r["outcome"]["state"] == "completed"]
    assert completed and all(
        r["outcome"]["cost_ms"] and r["outcome"]["cost_ms"] > 0
        for r in completed
    )
    batches = list(
        decision_rank_batches(rows, batch_size=4, rng=np.random.default_rng(0))
    )
    assert batches
    assert batches[0].pair_feats.shape == (4, p, 2)
    # the flight dump embeds the same rows — exporter reads it too
    from dragonfly2_tpu.telemetry import flight

    rows2 = decision_rows(flight.dump(max_bytes=None))
    assert {r["seq"] for r in rows2} >= {r["seq"] for r in rows[-8:]}
