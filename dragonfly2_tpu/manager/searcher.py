"""Searcher: score scheduler clusters for a joining daemon.

Capability parity with manager/searcher/searcher.go:94-276 — the exact
affinity blend: 0.3·CIDR + 0.3·hostname-regex + 0.25·IDC + 0.14·location +
0.01·cluster-type, with the same semantics: CIDR containment via parsed
networks, hostname tested against each regex in scopes, IDC matches any
`|`-separated source element, location scored as matching leading elements
/ 5 (maxElementLen), default cluster scores the type point. Clusters with
no active schedulers are filtered out first (FilterSchedulerClusters).
Plugin override supported via utils.plugins (the reference loads a .so
searcher plugin, manager/searcher/plugin.go).
"""

from __future__ import annotations

import ipaddress
import re
from dataclasses import dataclass, field

CIDR_AFFINITY_WEIGHT = 0.3
HOSTNAME_AFFINITY_WEIGHT = 0.3
IDC_AFFINITY_WEIGHT = 0.25
LOCATION_AFFINITY_WEIGHT = 0.14
CLUSTER_TYPE_WEIGHT = 0.01

MAX_ELEMENT_LEN = 5  # searcher.go maxElementLen
AFFINITY_SEPARATOR = "|"  # pkg/types AffinitySeparator

CONDITION_IDC = "idc"
CONDITION_LOCATION = "location"


@dataclass
class Scopes:
    """Scheduler-cluster scopes (searcher.go:79-84)."""

    idc: str = ""
    location: str = ""
    cidrs: list[str] = field(default_factory=list)
    hostnames: list[str] = field(default_factory=list)


def cidr_affinity_score(ip: str, cidrs: list[str]) -> float:
    try:
        addr = ipaddress.ip_address(ip)
    except ValueError:
        return 0.0
    for cidr in cidrs:
        try:
            if addr in ipaddress.ip_network(cidr, strict=False):
                return 1.0
        except ValueError:
            continue
    return 0.0


def hostname_affinity_score(hostname: str, patterns: list[str]) -> float:
    if not hostname or not patterns:
        return 0.0
    for pattern in patterns:
        try:
            if re.search(pattern, hostname):
                return 1.0
        except re.error:
            continue
    return 0.0


def idc_affinity_score(dst: str, src: str) -> float:
    if not dst or not src:
        return 0.0
    if dst.casefold() == src.casefold():
        return 1.0
    return float(
        any(dst.casefold() == el.casefold() for el in src.split(AFFINITY_SEPARATOR))
    )


def multi_element_affinity_score(dst: str, src: str) -> float:
    """Matching leading `|`-elements / 5 (searcher.go:243-271)."""
    if not dst or not src:
        return 0.0
    if dst.casefold() == src.casefold():
        return 1.0
    dst_elements = dst.split(AFFINITY_SEPARATOR)
    src_elements = src.split(AFFINITY_SEPARATOR)
    n = min(len(dst_elements), len(src_elements), MAX_ELEMENT_LEN)
    score = 0
    for i in range(n):
        if dst_elements[i].casefold() != src_elements[i].casefold():
            break
        score += 1
    return score / MAX_ELEMENT_LEN


def evaluate(ip: str, hostname: str, conditions: dict, scopes: Scopes, is_default: bool) -> float:
    return (
        CIDR_AFFINITY_WEIGHT * cidr_affinity_score(ip, scopes.cidrs)
        + HOSTNAME_AFFINITY_WEIGHT * hostname_affinity_score(hostname, scopes.hostnames)
        + IDC_AFFINITY_WEIGHT * idc_affinity_score(conditions.get(CONDITION_IDC, ""), scopes.idc)
        + LOCATION_AFFINITY_WEIGHT
        * multi_element_affinity_score(conditions.get(CONDITION_LOCATION, ""), scopes.location)
        + CLUSTER_TYPE_WEIGHT * (1.0 if is_default else 0.0)
    )


class Searcher:
    def find_scheduler_clusters(
        self,
        scheduler_clusters: list[dict],
        ip: str,
        hostname: str,
        conditions: dict | None = None,
    ) -> list[dict]:
        """Rank cluster records (Database rows: `scopes` dict, `is_default`
        bool, `schedulers` list of active scheduler rows) best-first.
        Raises ValueError when nothing is eligible, matching the
        reference's error returns (searcher.go:105-117)."""
        if not scheduler_clusters:
            raise ValueError("empty scheduler clusters")
        conditions = conditions or {}
        eligible = [c for c in scheduler_clusters if c.get("schedulers")]
        if not eligible:
            raise ValueError(f"conditions {conditions!r} does not match any scheduler cluster")
        return sorted(
            eligible,
            key=lambda c: evaluate(
                ip, hostname, conditions, _scopes_of(c), bool(c.get("is_default"))
            ),
            reverse=True,
        )


def _scopes_of(cluster: dict) -> Scopes:
    raw = cluster.get("scopes") or {}
    return Scopes(
        idc=raw.get("idc", ""),
        location=raw.get("location", ""),
        cidrs=list(raw.get("cidrs") or []),
        hostnames=list(raw.get("hostnames") or []),
    )


def new_searcher(plugin_dir: str | None = None, name: str = "default") -> Searcher:
    """Plugin-overridable constructor (searcher.go New: try plugin, fall
    back to the default)."""
    if plugin_dir:
        from dragonfly2_tpu.utils import plugins

        try:
            return plugins.load(plugin_dir, "searcher", name)
        except FileNotFoundError:
            pass
    return Searcher()
