"""dflint green fixture: disciplined meshed code. All silent.

Registered axes bound via functools.partial / parameter default, a
``psum(1, axis)`` axis-size idiom (static, branchable), and collectives
consistent with the wrapper's partition specs — the parallel/ idioms.
"""

import functools

import jax
from jax.sharding import PartitionSpec as P

from dragonfly2_tpu.parallel.mesh import DP_AXIS, TP_AXIS
from dragonfly2_tpu.utils.jaxcompat import shard_map


def tp_body(x, w, axis_name: str = TP_AXIS):
    n = jax.lax.psum(1, axis_name)  # axis size: static under trace
    partial_out = x @ w
    if n > 1:  # branching on the static axis size is legal
        partial_out = partial_out / n
    return jax.lax.psum(partial_out, axis_name)


def wrapper(mesh, x, w):
    fn = shard_map(
        functools.partial(tp_body, axis_name=TP_AXIS),
        mesh=mesh,
        in_specs=(P(DP_AXIS), P(None, TP_AXIS)),
        out_specs=P(DP_AXIS),
    )
    return fn(x, w)
