"""Trainer service: ingest per-host datasets, train both models, publish to
the registry — the reference's Train RPC with the TODO bodies filled in.

Parity: trainer/service/service_v1.go:59-162 (per-host dataset files from
chunked streams, cleanup on error, training kicked on stream end) +
trainer/training/training.go:60-98 (trainGNN ∥ trainMLP — empty stubs in
the reference, real `training/train.py` runs here) + the CreateModel
upload the reference never wires (manager_server_v1.go:802-952 →
registry.create_model_version + evaluation metrics).
"""

from __future__ import annotations

import contextlib
import dataclasses
import logging
import pathlib

from dragonfly2_tpu.config.config import TrainerConfig
from dragonfly2_tpu.records.features import (
    downloads_to_ranking_dataset,
    topology_to_pairs,
)
from dragonfly2_tpu.records.storage import HostTraceStorage
from dragonfly2_tpu.registry.registry import (
    MODEL_TYPE_ATTENTION,
    MODEL_TYPE_GNN,
    MODEL_TYPE_MLP,
    ModelEvaluation,
    ModelRegistry,
    ModelVersion,
)
from dragonfly2_tpu.training.train import (
    TrainResult,
    train_attention,
    train_gnn,
    train_mlp,
)

logger = logging.getLogger(__name__)


def _ranker_evaluation(result: "TrainResult") -> "ModelEvaluation":
    """Registry evaluation fields for the parent-ranker families (GNN and
    attention share the top-1 selection metrics)."""
    return ModelEvaluation(
        recall=result.eval_metrics.get("recall", 0.0),
        precision=result.eval_metrics.get("precision", 0.0),
        f1_score=result.eval_metrics.get("f1", 0.0),
    )

GNN_MODEL_NAME = "parent-ranker"
MLP_MODEL_NAME = "rtt-regressor"
ATTENTION_MODEL_NAME = "parent-ranker-attention"


@dataclasses.dataclass
class TrainOutcome:
    host_id: str
    gnn: ModelVersion | None
    mlp: ModelVersion | None
    gnn_result: TrainResult | None
    mlp_result: TrainResult | None
    attention: ModelVersion | None = None
    attention_result: TrainResult | None = None


class TrainerService:
    """In-proc trainer; the gRPC edge adapts chunk streams onto these calls."""

    def __init__(
        self,
        storage: HostTraceStorage,
        registry: ModelRegistry,
        config: TrainerConfig | None = None,
        mesh=None,
        auto_activate: bool = True,
    ):
        self.storage = storage
        self.registry = registry
        self.config = config or TrainerConfig()
        self.mesh = mesh
        # The reference leaves activation to an operator (manager/service/
        # model.go:109); auto_activate closes the loop unattended.
        self.auto_activate = auto_activate

    # ------------------------------------------------- TrainerSink protocol

    def train_mlp_chunk(self, host_id: str, data: bytes) -> None:
        self.storage.append_download_bytes(host_id, data)

    def train_gnn_chunk(self, host_id: str, data: bytes) -> None:
        self.storage.append_network_topology_bytes(host_id, data)

    def train_abort(self, host_id: str) -> None:
        """Stream error: clear ONLY the failing host's partial files
        (service_v1.go:117-131); other schedulers' uploads survive."""
        self.storage.clear_host(host_id)

    def train_finish(self, host_id: str) -> TrainOutcome:
        """Stream end: train GNN ∥ MLP (∥ attention when enabled), publish
        versions, clear datasets (training.go:60-98's errgroup, realized)."""
        outcome = TrainOutcome(host_id, None, None, None, None)
        try:
            downloads = self.storage.list_downloads()
            topologies = self.storage.list_network_topologies()
            if downloads:
                ds, graph = downloads_to_ranking_dataset(downloads)
                with self._checkpoint(GNN_MODEL_NAME) as ck:
                    result = train_gnn(
                        ds, graph, self.config, mesh=self.mesh, checkpointer=ck
                    )
                outcome.gnn_result = result
                outcome.gnn = self._publish(
                    GNN_MODEL_NAME, MODEL_TYPE_GNN, host_id, result,
                    _ranker_evaluation(result),
                    extra={"num_downloads": len(downloads), "num_hosts": len(graph.host_ids)},
                )
                if self.config.train_attention:
                    with self._checkpoint(ATTENTION_MODEL_NAME) as ck:
                        result = train_attention(
                            ds, self.config, mesh=self.mesh, checkpointer=ck
                        )
                    outcome.attention_result = result
                    outcome.attention = self._publish(
                        ATTENTION_MODEL_NAME, MODEL_TYPE_ATTENTION, host_id, result,
                        _ranker_evaluation(result),
                        extra={"num_downloads": len(downloads)},
                    )
            if topologies:
                x, y = topology_to_pairs(topologies)
                if x.shape[0] >= 8:
                    with self._checkpoint(MLP_MODEL_NAME) as ck:
                        result = train_mlp(
                            x, y, self.config, mesh=self.mesh, checkpointer=ck
                        )
                    outcome.mlp_result = result
                    outcome.mlp = self._publish(
                        MLP_MODEL_NAME, MODEL_TYPE_MLP, host_id, result,
                        ModelEvaluation(
                            mse=result.eval_metrics.get("mse", 0.0),
                            mae=result.eval_metrics.get("mae", 0.0),
                        ),
                        extra={"num_pairs": int(x.shape[0])},
                    )
        finally:
            self.storage.clear_downloads()
            self.storage.clear_network_topologies()
        return outcome

    @contextlib.contextmanager
    def _checkpoint(self, model_name: str):
        """Per-model train-state checkpointer when checkpoint_dir is set:
        a trainer killed mid-run resumes at the next epoch on restart.
        Cleared on successful completion — otherwise the NEXT train_finish
        would "resume" past its final epoch, run zero steps on the fresh
        traces, and publish the stale params. Closed either way (orbax
        managers hold background threads; a long-lived service would leak
        them per upload cycle)."""
        if not self.config.checkpoint_dir:
            yield None
            return
        from dragonfly2_tpu.training.checkpoint import TrainCheckpointer

        ck = TrainCheckpointer(pathlib.Path(self.config.checkpoint_dir) / model_name)
        try:
            yield ck
            ck.clear()  # success: next run starts fresh
        finally:
            ck.close()

    def _publish(self, name, model_type, host_id, result: TrainResult,
                 evaluation: ModelEvaluation, extra: dict) -> ModelVersion:
        mv = self.registry.create_model_version(
            name=name,
            model_type=model_type,
            scheduler_host_id=host_id,
            params=result.params,
            evaluation=evaluation,
            metadata={
                "steps": result.steps,
                "final_loss": result.losses[-1] if result.losses else None,
                "samples_per_sec": result.samples_per_sec,
                "hidden_dim": self.config.hidden_dim,
                # structural bound on single-pick recall — judge recall
                # against this, not 1.0 (models/metrics.py)
                "recall_ceiling": result.eval_metrics.get("recall_ceiling", 0.0),
                **extra,
            },
        )
        if self.auto_activate:
            self.registry.activate(mv.model_id, mv.version)
        logger.info("published %s v%d (%s)", mv.model_id, mv.version, name)
        return mv
