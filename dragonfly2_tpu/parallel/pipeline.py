"""Pipeline parallelism: GPipe-style microbatch schedule over the `pp` axis.

No analogue in the reference (SURVEY.md §2.6); TPU-native depth scaling:
each device owns ONE stage's params (the stage pytree is sharded on its
leading dim), activations hop stage-to-stage with `lax.ppermute` around
the ICI ring, and M microbatches fill the pipe so steady-state keeps all
pp devices busy (bubble = (pp-1)/(M+pp-1)).

Homogeneous stages (same fn/shape per stage) — the layer-stack case, e.g.
the AttentionRanker's SelfAttentionBlocks. The last stage's outputs are
broadcast back to every device with a psum so the wrapper returns
replicated global-shape outputs.
"""

from __future__ import annotations

import functools
from typing import Callable

import jax

from dragonfly2_tpu.utils.jaxcompat import shard_map
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from dragonfly2_tpu.parallel.mesh import PP_AXIS


def pipeline_apply(
    stage_fn: Callable,
    stage_params,
    x,
    axis_name: str = PP_AXIS,
):
    """Inside shard_map: run M microbatches through pp chained stages.

    stage_params: pytree whose leaves have a leading local dim of 1 (this
    device's stage, from a [pp, ...]-sharded tree); stage_fn(params, a)
    must preserve a's shape. x: [M, ...microbatch...] replicated on every
    device. Returns [M, ...] outputs, replicated."""
    pp = jax.lax.psum(1, axis_name)
    idx = jax.lax.axis_index(axis_name)
    my_params = jax.tree_util.tree_map(lambda a: a[0], stage_params)
    num_micro = x.shape[0]
    perm = [(i, (i + 1) % pp) for i in range(pp)]

    def tick(carry, t):
        # lax.scan (not fori_loop): the schedule must be reverse-mode
        # differentiable so the pp TRAINING path can backprop through the
        # whole pipeline (while_loop has no transpose rule; scan does).
        outputs, state = carry
        feed = jax.lax.dynamic_index_in_dim(
            x, jnp.clip(t, 0, num_micro - 1), 0, keepdims=False
        )
        inp = jnp.where(idx == 0, feed, state)
        y = stage_fn(my_params, inp)
        out_t = t - (pp - 1)
        collected = jax.lax.dynamic_update_index_in_dim(
            outputs, y, jnp.clip(out_t, 0, num_micro - 1), 0
        )
        take = (idx == pp - 1) & (out_t >= 0) & (out_t < num_micro)
        outputs = jnp.where(take, collected, outputs)
        state = jax.lax.ppermute(y, axis_name, perm)
        return (outputs, state), None

    outputs0 = jnp.zeros_like(x)
    state0 = jnp.zeros_like(x[0])
    (outputs, _), _ = jax.lax.scan(
        tick, (outputs0, state0), jnp.arange(num_micro + pp - 1)
    )
    # only the last stage holds real outputs; broadcast to all devices
    outputs = jnp.where(idx == pp - 1, outputs, jnp.zeros_like(outputs))
    return jax.lax.psum(outputs, axis_name)


def sharded_pipeline_apply(mesh, stage_fn, stage_params, x):
    """shard_map wrapper: stage_params leaves are [pp, ...] (stage i's
    params at index i), x is [M, ...] microbatched input; both global.
    Returns [M, ...] outputs equal to applying the stages sequentially."""
    fn = shard_map(
        functools.partial(pipeline_apply, stage_fn, axis_name=PP_AXIS),
        mesh=mesh,
        in_specs=(
            jax.tree_util.tree_map(lambda _: P(PP_AXIS), stage_params),
            P(),
        ),
        out_specs=P(),
        check_vma=False,
    )
    return fn(stage_params, x)
