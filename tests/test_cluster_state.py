"""Struct-of-arrays cluster state tests (reference behaviors:
scheduler/resource managers + FSMs)."""

import numpy as np
import pytest

from dragonfly2_tpu.state import ClusterState, PeerEvent, PeerState, TaskEvent, TaskState
from dragonfly2_tpu.state.fsm import HostType, InvalidTransition, peer_transition


def make_state():
    return ClusterState(max_hosts=16, max_tasks=8, max_peers=32, piece_cost_capacity=8)


def test_host_lifecycle_and_freelist_reuse():
    s = make_state()
    a = s.upsert_host("h1", id_hash=111, host_type=HostType.SUPER, upload_limit=10)
    b = s.upsert_host("h2", id_hash=222)
    assert a != b
    assert s.host_index("h1") == a
    assert s.host_type[a] == int(HostType.SUPER)
    # upsert same id updates in place
    assert s.upsert_host("h1", id_hash=111, upload_limit=99) == a
    assert s.host_upload_limit[a] == 99
    s.remove_host("h1")
    assert s.host_index("h1") is None
    assert not s.host_alive[a]
    c = s.upsert_host("h3", id_hash=333)
    assert c == a  # slot reused


def test_slot_reuse_does_not_leak_columns():
    s = make_state()
    loc = np.array([11, 22, 33, 0, 0], np.int64)
    num = np.full(s.host_numeric.shape[1], 7.0, np.float32)
    a = s.upsert_host("old", id_hash=1, location=loc, numeric=num)
    s.host_upload_used[a] = 49
    s.remove_host("old")
    b = s.upsert_host("new", id_hash=2)  # no location/numeric kwargs
    assert b == a
    assert s.host_location[b].sum() == 0
    assert s.host_numeric[b].sum() == 0
    assert s.host_upload_used[b] == 0


def test_capacity_error():
    s = ClusterState(max_hosts=2, max_tasks=2, max_peers=2)
    s.upsert_host("a", id_hash=1)
    s.upsert_host("b", id_hash=2)
    with pytest.raises(Exception):
        s.upsert_host("c", id_hash=3)


def test_peer_fsm_paths():
    s = make_state()
    h = s.upsert_host("h", id_hash=1)
    t = s.upsert_task("t", total_pieces=10)
    p = s.add_peer("p", t, h)
    assert s.peer_state[p] == int(PeerState.PENDING)
    s.peer_event(p, PeerEvent.REGISTER_NORMAL)
    s.peer_event(p, PeerEvent.DOWNLOAD)
    assert s.peer_state[p] == int(PeerState.RUNNING)
    s.peer_event(p, PeerEvent.DOWNLOAD_SUCCEEDED)
    assert s.peer_state[p] == int(PeerState.SUCCEEDED)
    with pytest.raises(InvalidTransition):
        s.peer_event(p, PeerEvent.DOWNLOAD)  # Succeeded -> Running illegal
    s.peer_event(p, PeerEvent.LEAVE)
    assert s.peer_state[p] == int(PeerState.LEAVE)


def test_peer_transition_table_matches_reference():
    # back-to-source path (peer.go:85-109)
    st = peer_transition(PeerState.RECEIVED_NORMAL, PeerEvent.DOWNLOAD_BACK_TO_SOURCE)
    assert st == PeerState.BACK_TO_SOURCE
    assert peer_transition(st, PeerEvent.DOWNLOAD_SUCCEEDED) == PeerState.SUCCEEDED
    # Succeeded can fail (e.g. validation failure)
    assert peer_transition(PeerState.SUCCEEDED, PeerEvent.DOWNLOAD_FAILED) == PeerState.FAILED


def test_task_fsm():
    s = make_state()
    t = s.upsert_task("t")
    s.task_event(t, TaskEvent.DOWNLOAD)
    assert s.task_state[t] == int(TaskState.RUNNING)
    s.task_event(t, TaskEvent.DOWNLOAD_SUCCEEDED)
    # succeeded task can re-enter running (task.go transitions)
    s.task_event(t, TaskEvent.DOWNLOAD)
    assert s.task_state[t] == int(TaskState.RUNNING)


def test_record_piece_ring_and_bitset():
    s = make_state()
    h = s.upsert_host("h", id_hash=1)
    t = s.upsert_task("t", total_pieces=100)
    p = s.add_peer("p", t, h)
    for i in range(5):
        s.record_piece(p, i, 10.0 * (i + 1))
    assert s.peer_finished_count[p] == 5
    # duplicate piece number doesn't double count
    s.record_piece(p, 0, 60.0)
    assert s.peer_finished_count[p] == 5
    costs = s.peer_piece_costs_ordered(p)
    assert costs.tolist() == [10.0, 20.0, 30.0, 40.0, 50.0, 60.0]
    # overflow the 8-slot ring: oldest drops
    for i in range(5, 9):
        s.record_piece(p, i, 100.0 + i)
    costs = s.peer_piece_costs_ordered(p)
    assert len(costs) == 8
    assert costs[-1] == 108.0 and costs[0] == 30.0


def test_gc_peers():
    s = make_state()
    h = s.upsert_host("h", id_hash=1)
    t = s.upsert_task("t")
    s.add_peer("old", t, h)
    s.add_peer("new", t, h)
    s.peer_updated_at[s.peer_index("old")] -= 1000
    reaped = s.gc_peers(ttl_seconds=500)
    assert reaped == 1
    assert s.peer_index("old") is None and s.peer_index("new") is not None


def test_gather_candidates_feeds_evaluator():
    from dragonfly2_tpu.ops import evaluator as ev

    s = make_state()
    hosts = [s.upsert_host(f"h{i}", id_hash=100 + i, upload_limit=10) for i in range(4)]
    t = s.upsert_task("t", total_pieces=50)
    child = s.add_peer("child", t, hosts[0])
    parents = [s.add_peer(f"p{i}", t, hosts[i + 1]) for i in range(3)]
    for i, p in enumerate(parents):
        s.peer_event(p, PeerEvent.REGISTER_NORMAL)
        s.peer_event(p, PeerEvent.DOWNLOAD)
        s.peer_event(p, PeerEvent.DOWNLOAD_SUCCEEDED)
        for piece in range(i + 2):
            s.record_piece(p, piece, 50.0)

    cand = np.array([parents + [0]], np.int32)
    valid = np.array([[True, True, True, False]])
    feats = s.gather_candidates(np.array([child]), cand, valid)
    assert feats.valid.tolist() == [[True, True, True, False]]
    assert feats.finished_pieces[0, :3].tolist() == [2, 3, 4]
    assert feats.total_piece_count[0] == 50

    out = ev.schedule_candidate_parents(feats.as_dict(), limit=2)
    sel_valid = np.asarray(out["selected_valid"])
    assert sel_valid[0].sum() == 2
