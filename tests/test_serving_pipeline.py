"""Asynchronous serving pipeline (ISSUE 4): double-buffered tick
dispatch, compile-shape stability under chunking, off-critical-path
embedding refresh, and the incremental dirty-frontier embed."""

import gc
import threading
import time

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from dragonfly2_tpu.cluster import messages as msg
from dragonfly2_tpu.cluster.scheduler import (
    _EVAL_BUCKETS,
    SchedulerService,
    _chunk_stride,
)
from dragonfly2_tpu.cluster.simulator import ClusterSimulator
from dragonfly2_tpu.config.config import Config
from dragonfly2_tpu.models.graphsage import GraphSAGERanker
from dragonfly2_tpu.ops import evaluator as ev
from dragonfly2_tpu.ops.segment import gather_coo_subgraph
from dragonfly2_tpu.registry import (
    MLEvaluator,
    ModelEvaluation,
    ModelRegistry,
    ModelServer,
)
from dragonfly2_tpu.registry.registry import MODEL_TYPE_GNN
from dragonfly2_tpu.scenarios.spec import builtin_scenarios
from dragonfly2_tpu.telemetry import metrics as m
from dragonfly2_tpu.telemetry.flight import jit_wrappers

# ------------------------------------------------------------ tick helpers


def _host(i: int, seed: bool = False) -> msg.HostInfo:
    return msg.HostInfo(
        host_id=f"sp-h{i}", hostname=f"sp-n{i}", ip=f"10.11.{i // 250}.{i % 250}",
        host_type="super" if seed else "normal", idc="idc-a",
        location="na|zone|rack",
        # one seed must be able to parent a whole bucket's worth of
        # children, or saturated-uploader filtering drains selections
        concurrent_upload_limit=100_000,
    )


def _register(svc, peer_id, host, task_id):
    return svc.register_peer(
        msg.RegisterPeerRequest(
            peer_id=peer_id, task_id=task_id, host=host,
            url="https://e.com/blob", content_length=4 * (4 << 20),
            total_piece_count=4,
        )
    )


def _pipeline_service(num_tasks: int = 16, num_hosts: int = 64,
                      fused: bool = True):
    """Service with one finished seed parent per task, so every child the
    tick schedules has a rooted candidate. `fused=False` selects the
    legacy packed pipeline (the decision-equivalence oracle path)."""
    cfg = Config()
    cfg.scheduler.fused_tick = fused
    svc = SchedulerService(config=cfg, metrics_registry=m.Registry())
    hosts = [_host(i) for i in range(num_hosts)]
    for i in range(num_tasks):
        seed_host = _host(1000 + i, seed=True)
        _register(svc, f"sp-seed-{i}", seed_host, f"sp-task-{i}")
        svc.peer_finished(
            msg.DownloadPeerFinishedRequest(peer_id=f"sp-seed-{i}", piece_count=4)
        )
    svc.tick()  # drain the pre_schedule-only seed tick
    return svc, hosts


def test_chunk_stride_buckets_and_pipelining():
    """The stride rule: single chunk only when the batch fits the smallest
    bucket; otherwise the smallest bucket that keeps <= 4 chunks — total
    padded rows never exceed the single-big-bucket split, and every chunk
    pads to one of the three fixed buckets."""
    for b in range(1, 5000, 37):
        stride = _chunk_stride(b)
        assert stride in _EVAL_BUCKETS
        n_chunks = -(-b // stride)
        if b > _EVAL_BUCKETS[0]:
            assert n_chunks >= 2 or stride == _EVAL_BUCKETS[-1]
        if stride != _EVAL_BUCKETS[-1]:
            assert n_chunks <= 4
    assert _chunk_stride(_EVAL_BUCKETS[0]) == _EVAL_BUCKETS[0]


def test_tick_compile_shapes_stable_across_buckets():
    """Satellite: ticks across all three _EVAL_BUCKETS sizes, twice each,
    add at most one compile per (bucket, algorithm) — and none at all
    beyond what warmup() already compiled. Pins the at-most-three-
    compiled-shapes contract the pipelined chunking must not break."""
    svc, hosts = _pipeline_service()
    wrapper = jit_wrappers()["scheduler.evaluator.schedule_from_packed"]
    before_warmup = wrapper.stats()["signatures"]
    svc.warmup()
    after_warmup = wrapper.stats()["signatures"]
    # one compiled shape per bucket at most (0 when an earlier test in
    # this process already warmed the same shapes)
    assert after_warmup - before_warmup <= len(_EVAL_BUCKETS)

    reg_counter = [0]

    def _top_up(target: int) -> None:
        while len(svc._pending) < target:
            i = reg_counter[0]
            reg_counter[0] += 1
            _register(
                svc, f"sp-child-{i}", hosts[i % len(hosts)],
                f"sp-task-{i % 16}",
            )

    # one tick per bucket regime, twice: 64 -> single 64-chunk;
    # 300 -> 256 + 64 chunks; 1025 -> 1024 + 64 chunks
    for _ in range(2):
        for target in (64, 300, 1025):
            _top_up(target)
            svc.tick()
    assert wrapper.stats()["signatures"] == after_warmup, (
        "tick chunking reached a (B, K) shape warmup never compiled"
    )

    # dfshape acceptance: the STATICALLY-derived signature set (retracer
    # parses _EVAL_BUCKETS out of scheduler.py by AST) exactly matches
    # the runtime-observed compile set of the serving jit — warmup plus
    # ticks across every bucket regime compiled all proven buckets and
    # nothing else
    from pathlib import Path

    from tools.dflint import retracer

    root = Path(__file__).resolve().parents[1]
    name = "scheduler.evaluator.schedule_from_packed"
    derived = retracer.derive_static_signature_sets(root)[name]
    observed = retracer.observed_batch_buckets(
        wrapper, retracer.SERVING_B_ARGS[name]
    )
    assert observed == set(derived), (observed, derived)


def test_ml_serving_jit_signature_set_matches_static(tmp_path):
    """The ml packed entry honors the same proven bucket set: warming
    every bucket through MLEvaluator.schedule_from_packed lands exactly
    _EVAL_BUCKETS as the wrapper's observed batch dims."""
    from pathlib import Path

    from tools.dflint import retracer

    reg, server, evaluator, graph, params = _served_evaluator(tmp_path)
    try:
        evaluator.refresh_embeddings(dict(graph), wait=True)
        assert evaluator.serving_snapshot() is not None
        for bsz in _EVAL_BUCKETS:
            buf, dims = _packed_buf(b=bsz)
            out = np.asarray(evaluator.schedule_from_packed(buf, *dims))
            assert out.shape == (bsz, out.shape[1], 2)
    finally:
        evaluator.close()
    root = Path(__file__).resolve().parents[1]
    name = "scheduler.ml.schedule_from_packed"
    wrapper = jit_wrappers()[name]
    derived = retracer.derive_static_signature_sets(root)[name]
    observed = retracer.observed_batch_buckets(
        wrapper, retracer.SERVING_B_ARGS[name]
    )
    # every proven bucket observed (this test warmed all three), and
    # nothing outside the proven set (the session tripwire's invariant)
    assert observed == set(derived), (observed, derived)


def test_pipelined_tick_overlaps_dispatch_and_apply():
    """A multi-chunk tick records the split phases AND nonzero overlap:
    host work (pack of chunk i+1, apply of chunk i) ran while a device
    call was in flight. Pinned on the LEGACY packed pipeline
    (fused_tick=False) — it stays reachable as the decision-equivalence
    oracle; the fused default's phase split + overlap is pinned by
    tests/test_fused_tick.py::test_fused_tick_records_split_phases."""
    svc, hosts = _pipeline_service(fused=False)
    for i in range(200):  # > _EVAL_BUCKETS[0] -> at least two chunks
        _register(svc, f"sp-ov-{i}", hosts[i % len(hosts)], f"sp-task-{i % 16}")
    responses = svc.tick()
    phases = list(svc.recorder.ring)[-1]
    for name in ("pack", "dispatch", "d2h_wait", "apply_selection"):
        assert name in phases, phases
    # device_call is back as an explicit AGGREGATE (= dispatch + d2h_wait)
    # next to control_dispatch (the summed control-plane phases), so the
    # control-vs-device comparison reads straight off the recorder (PR 8)
    assert phases["device_call"] == pytest.approx(
        phases["dispatch"] + phases["d2h_wait"], rel=1e-6, abs=1e-6
    )
    assert phases["control_dispatch"] == pytest.approx(
        phases.get("report_ingest", 0.0) + phases.get("pre_schedule", 0.0)
        + phases.get("candidate_fill", 0.0) + phases.get("feature_gather", 0.0)
        + phases.get("pack", 0.0) + phases.get("apply_selection", 0.0),
        rel=1e-6, abs=1e-6,
    )
    assert phases.get("overlap", 0.0) > 0.0, phases
    # the pipeline reordered the work, not the results: every scheduled
    # child got rooted (seed) parents
    assert responses
    assert all(
        isinstance(r, msg.NormalTaskResponse) and r.candidate_parents
        for r in responses
    )


# --------------------------------------------------- incremental embedding


def _ranker_params(model: GraphSAGERanker, graph: dict):
    return model.init(
        jax.random.key(0),
        graph["node_feats"], graph["edge_src"], graph["edge_dst"],
        graph["edge_feats"],
        method="embed",
    )


def _embed(model, params, graph):
    return np.asarray(model.apply(
        params,
        graph["node_feats"], graph["edge_src"], graph["edge_dst"],
        graph["edge_feats"],
        method="embed",
    ))


def test_new_host_join_stays_incremental():
    """A brand-new host joining mid-serving must NOT force a full
    embedding resync — its slot rides the dirty frontier (and a grown
    table is separately caught by the refresh's shape guard). Only slot
    RECYCLING and host departure carry invisible neighbor changes; in a
    growing cluster a join-means-full-sync rule would silently defeat
    the incremental path on every refresh interval containing a join."""
    svc = SchedulerService(metrics_registry=m.Registry())
    for i in range(8):
        svc.announce_host(_host(i))
    assert svc.serving_graph_arrays()["full_sync"]  # first read
    new_slot = svc.announce_host(_host(99))
    g = svc.serving_graph_arrays()
    assert not g["full_sync"], "first-time join must stay incremental"
    assert new_slot in g["dirty_slots"]
    svc.leave_host(_host(3).host_id)
    assert svc.serving_graph_arrays()["full_sync"]  # departure: full


@pytest.mark.parametrize("scenario_name", ["bandwidth_skew", "hotspot"])
def test_embed_subset_matches_full_on_dirty_frontier(scenario_name):
    """Acceptance: `embed_subset` over a gathered dirty frontier matches
    the full `embed` output on every dirty-reachable slot to fp32
    tolerance, leaves every other slot bit-identical, and the frontier
    covers every row the graph change actually moved — across two
    scenario-lab topologies (both churn-free: a host leave would
    legitimately force a full sync)."""
    spec = builtin_scenarios()[scenario_name]
    svc = SchedulerService(metrics_registry=m.Registry())
    sim = ClusterSimulator(svc, num_hosts=48, num_tasks=6, seed=3, scenario=spec)
    for _ in range(10):
        sim.run_round(new_downloads=6)
    g1 = svc.serving_graph_arrays()
    assert g1["full_sync"]  # first read is always a full sync
    for _ in range(4):
        sim.run_round(new_downloads=4)
    g2 = svc.serving_graph_arrays()
    assert not g2["full_sync"]
    dirty = g2["dirty_slots"]
    assert dirty.size > 0
    assert g2["node_feats"].shape == g1["node_feats"].shape

    model = GraphSAGERanker(hidden_dim=32, compute_dtype=jnp.float32)
    params = _ranker_params(model, g1)
    table_old = _embed(model, params, g1)
    full_new = _embed(model, params, g2)

    n = g2["node_feats"].shape[0]
    sub = gather_coo_subgraph(
        g2["edge_src"], g2["edge_dst"], dirty,
        num_nodes=n, hops=model.num_layers, max_frac=1.0,
    )
    assert sub is not None
    edge_feats = np.where(
        sub["edge_pad"][:, None], 0.0, g2["edge_feats"][sub["edge_index"]]
    ).astype(np.float32)
    updated = np.asarray(model.apply(
        params,
        g2["node_feats"][sub["nodes"]],
        sub["edge_src"], sub["edge_dst"], edge_feats,
        jnp.asarray(table_old), sub["target_local"], sub["target_global"],
        method="embed_subset",
    ))
    targets = sub["target_global"]
    targets = targets[targets < n]
    # (a) recomputed rows match the full recompute (fp32: summation order
    # inside segment_sum is the only difference)
    np.testing.assert_allclose(
        updated[targets], full_new[targets], rtol=1e-4, atol=1e-5
    )
    # (b) rows outside the frontier are untouched, bit for bit
    outside = np.ones(n, bool)
    outside[targets] = False
    np.testing.assert_array_equal(updated[outside], table_old[outside])
    # (c) the frontier is COMPLETE: every row the new edges actually
    # moved is inside it — nothing outside changed between the reads
    moved = ~np.isclose(full_new, table_old, rtol=1e-4, atol=1e-6).all(-1)
    assert not moved[outside].any(), (
        f"rows {np.nonzero(moved & outside)[0]} changed outside the frontier"
    )


def test_gather_coo_subgraph_fallback_and_empty():
    src = np.array([0, 1, 2], np.int64)
    dst = np.array([1, 2, 3], np.int64)
    assert gather_coo_subgraph(src, dst, np.array([], np.int64), 8) is None
    # a frontier larger than max_frac of the graph declines the gather
    assert gather_coo_subgraph(
        src, dst, np.array([0]), num_nodes=8, hops=2, max_frac=0.1
    ) is None
    sub = gather_coo_subgraph(
        src, dst, np.array([1]), num_nodes=8, hops=1, max_frac=1.0
    )
    assert sub is not None
    n_real = (sub["target_global"] < 8).sum()
    # directed semantics: node 1 dirty -> its dependents are itself and
    # node 0 (edge 0->1 means 0 AGGREGATES 1); node 2 reads nothing
    # from 1 and must stay outside the target set
    assert set(sub["target_global"][:n_real].tolist()) == {0, 1}


# ------------------------------------------- background refresh / serving


def _served_evaluator(tmp_path, n_nodes=64, hidden=16, n_feats=12, edges=256,
                      seed=0):
    rng = np.random.default_rng(seed)
    graph = {
        "node_feats": rng.normal(size=(n_nodes, n_feats)).astype(np.float32),
        "edge_src": rng.integers(0, n_nodes - 1, edges).astype(np.int32),
        "edge_dst": rng.integers(0, n_nodes - 1, edges).astype(np.int32),
        "edge_feats": rng.normal(size=(edges, 2)).astype(np.float32),
    }
    model = GraphSAGERanker(hidden_dim=hidden)
    child = np.zeros(4, np.int32)
    cands = np.arange(4 * 4, dtype=np.int32).reshape(4, 4) % n_nodes
    pair = np.zeros((4, 4, 2), np.float32)
    params = model.init(jax.random.key(0), graph, child, cands, pair)
    reg = ModelRegistry(tmp_path)
    server = ModelServer(reg, "ranker", "h", MODEL_TYPE_GNN, template_params=params)
    mv = reg.create_model_version(
        "ranker", MODEL_TYPE_GNN, "h", params, ModelEvaluation(),
        metadata={"hidden_dim": hidden},  # the trainer always records this
    )
    reg.activate(mv.model_id, mv.version)
    assert server.refresh()
    return reg, server, MLEvaluator(server), graph, params


def _packed_buf(b=64, k=8, n_hosts=64, seed=0):
    from dragonfly2_tpu.records.features import CandidateFeatures
    from dragonfly2_tpu.state.fsm import PeerState

    rng = np.random.default_rng(seed)
    feats = CandidateFeatures.zeros(b, k)
    feats.valid[:] = True
    feats.peer_state[:] = int(PeerState.SUCCEEDED)
    feats.upload_limit[:] = 10
    feats.parent_host_id[:] = np.arange(1, b * k + 1).reshape(b, k)
    feats.child_host_id[:] = 0
    fd = feats.as_dict()
    child = rng.integers(0, n_hosts, b).astype(np.int32)
    cands = rng.integers(0, n_hosts, (b, k)).astype(np.int32)
    buf = ev.pack_eval_batch(fd, child_host_slot=child, cand_host_slot=cands)
    c = fd["piece_costs"].shape[-1]
    l = fd["parent_location"].shape[-1]
    n = fd["numeric"].shape[-1]
    return buf, (b, k, c, l, n)


def test_async_refresh_commits_off_thread_and_worker_dies_with_evaluator(tmp_path):
    _, server, evaluator, graph, _ = _served_evaluator(tmp_path)
    assert evaluator._committed is None
    evaluator.refresh_embeddings(dict(graph))  # wait=False: enqueue only
    deadline = time.monotonic() + 60
    while evaluator._committed is None and time.monotonic() < deadline:
        time.sleep(0.01)
    snap = evaluator._committed
    assert snap is not None, "background refresh never committed"
    assert snap.emb_version == 1 and snap.params_version == server.version
    assert evaluator.committed_versions[-1] == (server.version, 1)
    worker = evaluator._worker
    assert worker is not None and worker.is_alive()
    assert worker.name.startswith("ml-embed-refresh")

    # close() joins the worker; the committed snapshot keeps serving
    evaluator.close()
    assert not worker.is_alive()
    assert evaluator._committed is not None
    # a closed evaluator must not resurrect a worker on a late enqueue,
    # but must not silently strand the request either: it computes
    # inline (the consumed dirty frontier would otherwise be lost)
    evaluator.refresh_embeddings(dict(graph))
    assert evaluator._worker is None
    assert evaluator._request is None, "post-close refresh stranded"
    assert evaluator._committed.emb_version == 2

    # GC path: dropping the last reference signals the worker to exit
    # even though nobody called close() (the conftest session guard
    # enforces this globally; this pins the finalizer mechanism)
    _, _, ev2, graph2, _ = _served_evaluator(tmp_path / "gc", seed=1)
    ev2.refresh_embeddings(dict(graph2))
    worker2 = ev2._worker
    assert worker2 is not None
    del ev2
    gc.collect()
    worker2.join(timeout=5)
    assert not worker2.is_alive(), "worker outlived its GC'd evaluator"


def test_refresh_serve_race_consistent_versions_and_bounded_ticks(tmp_path):
    """Satellite: hammer refresh_embeddings from a thread (with a params
    activation flip mid-run) while schedule_from_packed serves in a loop.
    Every tick must serve from a (params_version, emb_version) pair that
    was committed as a unit, and no tick may block for anything close to
    a full-graph refresh."""
    # graph heavy enough that a full refresh costs visibly more than any
    # scheduling call — the bound below must separate the two regimes
    # even under CI scheduler noise
    n_nodes, edges = 4096, 32768
    reg, server, evaluator, graph, params = _served_evaluator(
        tmp_path, n_nodes=n_nodes, hidden=128, edges=edges
    )
    # runtime lock-order harness (tools/dflint/lockorder): the hammer /
    # worker / serving triangle is exactly where a req_mu<->compute_mu
    # inversion or an unlocked mailbox/snapshot write would hide
    from tools.dflint.lockorder import (
        assert_clean, guard_attributes, instrument_locks,
    )

    lock_graph = instrument_locks(evaluator, {
        "_req_mu": "serving.req_mu",
        "_compute_mu": "serving.compute_mu",
    })
    guard_attributes(evaluator, {
        "_request": "_req_mu",     # mailbox writes: merge/take under req_mu
        "_committed": "_compute_mu",  # snapshot commit: only on the drain
        "_worker": "_req_mu",      # spawn/clear under req_mu (LOCK001 fix)
    }, lock_graph)
    rng = np.random.default_rng(7)
    evaluator.refresh_embeddings(dict(graph), wait=True)  # commit + warm jit
    # serial full-refresh cost = the stall each tick USED to pay
    t_full = []
    for _ in range(2):
        t0 = time.perf_counter()
        evaluator.refresh_embeddings(dict(graph, full_sync=True), wait=True)
        t_full.append(time.perf_counter() - t0)
    # noise floor 0.15: the hammer's mid-run params flip now also runs the
    # activation gate on the worker, whose first canary scoring pass pays
    # a one-time jit compile that (on CPU) shares the XLA intra-op pool
    # with serving — a fast machine's min(t_full) can undercut the real
    # contention a tick may briefly see
    refresh_bound = max(min(t_full), 0.15)

    buf, dims = _packed_buf(n_hosts=n_nodes)
    # .copy(): the donation guard (tools/dflint/retracer.py) enforces the
    # one-shot contract on donated staging buffers session-wide — every
    # call gets its own buffer, exactly like the tick packs fresh
    np.asarray(evaluator.schedule_from_packed(buf.copy(), *dims))  # warm the ml jit
    # blocking accumulated so far is the DELIBERATE synchronous phase
    # (incl. the embed jit compile); the hammer below must add ~nothing
    blocking_before_hammer = evaluator.refresh_blocking_s

    stop = threading.Event()

    def hammer():
        i = 0
        while not stop.is_set():
            i += 1
            g = dict(graph)
            g["dirty_slots"] = rng.integers(0, n_nodes, 8).astype(np.int32)
            g["full_sync"] = (i % 7 == 0)  # mix full recomputes in
            evaluator.refresh_embeddings(g)  # async
            time.sleep(0.001)

    thread = threading.Thread(target=hammer, name="race-hammer")
    thread.start()
    try:
        used_pairs = []
        tick_s = []
        flipped_at = 25
        for i in range(50):
            if i == flipped_at:
                mv = reg.create_model_version(
                    "ranker", MODEL_TYPE_GNN, "h", params, ModelEvaluation(),
                    metadata={"hidden_dim": 128},
                )
                reg.activate(mv.model_id, mv.version)
                assert server.refresh()
            t0 = time.perf_counter()
            out = np.asarray(evaluator.schedule_from_packed(buf.copy(), *dims))
            tick_s.append(time.perf_counter() - t0)
            assert out.shape[-1] == 2
            used_pairs.append(evaluator.last_used_versions)
        # the params flip propagates through a WORKER refresh commit whose
        # first gate pass pays a one-time canary-scoring compile; on a slow
        # CPU that compile can outlast the fixed 25 post-flip ticks, so keep
        # ticking (bounded) until a commit with the new version lands —
        # the race assertions below still cover every tick taken
        deadline = time.perf_counter() + 20.0
        while (
            not any(p and p[0] == server.version for p in used_pairs)
            and time.perf_counter() < deadline
        ):
            g = dict(graph)
            g["dirty_slots"] = rng.integers(0, n_nodes, 8).astype(np.int32)
            g["full_sync"] = False
            evaluator.refresh_embeddings(g)  # async nudge
            time.sleep(0.05)
            t0 = time.perf_counter()
            out = np.asarray(evaluator.schedule_from_packed(buf.copy(), *dims))
            tick_s.append(time.perf_counter() - t0)
            used_pairs.append(evaluator.last_used_versions)
    finally:
        stop.set()
        thread.join(timeout=10)
    evaluator.close()

    committed = set(evaluator.committed_versions)
    assert all(pair in committed for pair in used_pairs), (
        "a tick served from a (params_version, emb_version) pair that was "
        "never committed together"
    )
    # lock-order verdict over the whole hammer run: no acquisition-order
    # cycles between the mailbox and compute locks, and every _request/
    # _committed/_worker write held its owning lock
    assert_clean(lock_graph)
    # Ticks never inherited a refresh (4.98 s of r05's 7.01 s ml wall was
    # exactly that inheritance). On CPU the background refresh shares the
    # XLA intra-op pool with serving, so a tick CAN wait out the tail of
    # an in-flight embed program — the bound is therefore "well under a
    # refresh" in the median and "never a full synchronous refresh cycle"
    # at the max, not zero contention.
    import statistics

    assert statistics.median(tick_s) < 0.25 * refresh_bound, (
        f"median tick {statistics.median(tick_s):.3f}s vs full-refresh "
        f"bound {refresh_bound:.3f}s — serving is inheriting refresh work"
    )
    assert max(tick_s) < 2 * refresh_bound, (
        f"tick blocked {max(tick_s):.3f}s >= 2x full-refresh bound "
        f"{refresh_bound:.3f}s"
    )
    # the params flip eventually reached serving through a refresh commit
    assert any(p and p[0] == server.version for p in used_pairs), (
        "no tick ever served the activated params version"
    )
    # refreshes actually ran both paths under the hammer
    assert evaluator.refresh_count > 2
    # the async hammer (hundreds of refresh calls) stalled callers for
    # ~enqueue cost only — the off-critical-path contract
    assert evaluator.refresh_blocking_s - blocking_before_hammer < 0.5


def test_mlevaluator_incremental_path_via_scheduler_frontier(tmp_path):
    """End-to-end: scheduler dirty frontier -> MLEvaluator refresh takes
    the incremental embed_subset path (params unchanged, no structural
    sync) and falls back to full on a params flip."""
    svc = SchedulerService(metrics_registry=m.Registry())
    sim = ClusterSimulator(svc, num_hosts=32, num_tasks=4, seed=5)
    for _ in range(8):
        sim.run_round(new_downloads=6)
    g1 = svc.serving_graph_arrays()
    reg, server, evaluator, _, params = _served_evaluator(
        tmp_path, n_nodes=g1["node_feats"].shape[0],
        n_feats=g1["node_feats"].shape[1],
    )
    evaluator.INCREMENTAL_MAX_FRAC = 1.0  # tiny graph: always worth it
    evaluator.refresh_embeddings(g1, wait=True)
    assert (evaluator.refresh_count, evaluator.incremental_refresh_count) == (1, 0)
    for _ in range(4):
        sim.run_round(new_downloads=4)
    g2 = svc.serving_graph_arrays()
    if g2["node_feats"].shape != g1["node_feats"].shape:
        pytest.skip("padded node bucket grew; incremental legitimately skipped")
    evaluator.refresh_embeddings(g2, wait=True)
    assert evaluator.incremental_refresh_count == 1
    assert evaluator.committed_versions[-1][1] == 2  # emb_version bumped
    # params flip forces the next refresh full even with a tiny frontier
    mv = reg.create_model_version(
        "ranker", MODEL_TYPE_GNN, "h", params, ModelEvaluation(),
        metadata={"hidden_dim": 16},
    )
    reg.activate(mv.model_id, mv.version)
    assert server.refresh()
    for _ in range(2):
        sim.run_round(new_downloads=4)
    g3 = svc.serving_graph_arrays()
    evaluator.refresh_embeddings(g3, wait=True)
    assert evaluator.incremental_refresh_count == 1  # still 1: went full
    assert evaluator._committed.params_version == server.version
    evaluator.close()
