"""Over-the-wire ModelInfer (rpc/inference.py) against live ModelServers.

The reference's pkg/rpc/inference client can only talk to an external
Triton sidecar; here the same KServe-v2-shaped surface (ServerLive /
ModelReady / ModelMetadata / ModelInfer) is served natively and must
return bit-identical scores to in-process serving."""

import asyncio

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from dragonfly2_tpu.models.attention import AttentionRanker
from dragonfly2_tpu.models.mlp import ProbeRTTRegressor
from dragonfly2_tpu.registry import ModelEvaluation, ModelRegistry, ModelServer
from dragonfly2_tpu.registry.registry import MODEL_TYPE_ATTENTION, MODEL_TYPE_MLP
from dragonfly2_tpu.rpc.inference import InferenceClient, InferenceRPCServer
from dragonfly2_tpu.utils import dferrors


@pytest.fixture()
def rig(tmp_path):
    reg = ModelRegistry(tmp_path)

    mlp = ProbeRTTRegressor(hidden_dim=8)
    x = jnp.ones((2, 8))
    mlp_params = mlp.init(jax.random.key(0), x)
    mlp_server = ModelServer(
        reg, "rtt", "sched-h", MODEL_TYPE_MLP, template_params=mlp_params, model=mlp
    )

    n, p, f = 3, 5, 12
    rng = np.random.default_rng(1)
    child = rng.normal(size=(n, f)).astype(np.float32)
    parents = rng.normal(size=(n, p, f)).astype(np.float32)
    pair = rng.normal(size=(n, p, 2)).astype(np.float32)
    mask = np.ones((n, p), bool)
    att = AttentionRanker(hidden_dim=32)
    att_params = att.init(jax.random.key(1), child, parents, pair, mask)
    att_server = ModelServer(
        reg, "set-ranker", "sched-h", MODEL_TYPE_ATTENTION,
        template_params=att_params, model=att,
    )

    servers = {"rtt": mlp_server, "set-ranker": att_server}
    return reg, servers, {
        "mlp": (mlp_params, np.asarray(x, np.float32)),
        "att": (att_params, (child, parents, pair, mask)),
    }


def test_infer_rpc_end_to_end(rig):
    reg, servers, data = rig

    async def run():
        # ttl=0: the test flips activation and expects the very next
        # request to observe it
        server = InferenceRPCServer(servers, refresh_ttl_s=0.0)
        host, port = await server.start()
        client = await InferenceClient(host, port).connect()
        try:
            assert await client.server_live()
            # nothing active yet
            assert not await client.model_ready("rtt")
            with pytest.raises(dferrors.Unavailable, match="no active version"):
                await client.model_infer("rtt", {"features": data["mlp"][1]})

            # publish + activate both models
            mlp_params, x = data["mlp"]
            mv = reg.create_model_version(
                "rtt", MODEL_TYPE_MLP, "sched-h", mlp_params, ModelEvaluation()
            )
            reg.activate(mv.model_id, mv.version)
            att_params, (child, parents, pair, mask) = data["att"]
            av = reg.create_model_version(
                "set-ranker", MODEL_TYPE_ATTENTION, "sched-h", att_params,
                ModelEvaluation(),
            )
            reg.activate(av.model_id, av.version)

            assert await client.model_ready("rtt")
            meta = await client.model_metadata("rtt")
            assert meta.platform == "jax-mlp" and meta.versions == ["1"]
            assert meta.inputs == ["features"] and meta.outputs == ["rtt"]

            # scores over the wire == scores in-process
            out = await client.model_infer("rtt", {"features": x})
            direct = np.asarray(servers["rtt"].infer_mlp(x))
            np.testing.assert_array_equal(out["rtt"], direct)

            out = await client.model_infer(
                "set-ranker",
                {"child_feats": child, "parent_feats": parents,
                 "pair_feats": pair, "mask": mask},
            )
            direct = np.asarray(
                servers["set-ranker"].score_set(child, parents, pair, mask)
            )
            np.testing.assert_array_equal(out["scores"], direct)
            assert out["scores"].shape == (3, 5)

            # error surfaces, connection stays usable afterwards
            with pytest.raises(dferrors.Unavailable, match="missing"):
                await client.model_infer("set-ranker", {"child_feats": child})
            with pytest.raises(dferrors.Unavailable, match="no model"):
                await client.model_infer("nope", {"features": x})
            assert await client.server_live()
        finally:
            await client.close()
            await server.stop()

    asyncio.run(run())


def test_infer_tensor_roundtrip():
    from dragonfly2_tpu.rpc.inference import InferTensor
    from dragonfly2_tpu.rpc import wire

    for arr in (
        np.arange(12, dtype=np.float32).reshape(3, 4),
        np.array([[True, False], [False, True]]),
        np.arange(6, dtype=np.int32).reshape(2, 3),
    ):
        t = InferTensor.from_numpy("t", arr)
        decoded = wire.decode(wire.encode(t)[4:])
        np.testing.assert_array_equal(decoded.to_numpy(), arr)
        assert decoded.to_numpy().dtype == arr.dtype


def test_infer_rpc_stop_with_connected_client(rig):
    """A persistent InferenceClient connection must not hang stop()
    (handlers are cancelled before wait_closed; ADVICE round 1)."""
    _, servers, _ = rig

    async def run():
        server = InferenceRPCServer(servers, refresh_ttl_s=0.0)
        host, port = await server.start()
        client = await InferenceClient(host, port).connect()
        assert await client.server_live()
        await asyncio.wait_for(server.stop(), timeout=5.0)
        await client.close()

    asyncio.new_event_loop().run_until_complete(run())
