"""OAuth2 sign-in providers — the authorization-code flow.

Capability parity with manager/auth/oauth/{oauth,github,google}.go: a
provider wraps client id/secret + the three endpoint URLs; `signin`
redirects the browser to the provider's consent page, the callback
exchanges the code for a token and fetches the user profile, and the
manager then issues its normal JWT for that (created-on-first-signin)
user. Endpoint URLs are constructor arguments with github/google
defaults, so tests (and self-hosted IdPs) can point a provider at any
token/userinfo server — the reference hard-wires golang.org/x/oauth2's
endpoint tables instead.

State parameter: generated per signin and validated at the callback with
a TTL (the reference generates but never checks it, oauth/github.go:50-56;
checking is strictly safer and costs one dict).
"""

from __future__ import annotations

import json
import secrets
import threading
import time
import urllib.parse
import urllib.request

GITHUB_AUTH_URL = "https://github.com/login/oauth/authorize"
GITHUB_TOKEN_URL = "https://github.com/login/oauth/access_token"
GITHUB_USERINFO_URL = "https://api.github.com/user"
GOOGLE_AUTH_URL = "https://accounts.google.com/o/oauth2/auth"
GOOGLE_TOKEN_URL = "https://oauth2.googleapis.com/token"
GOOGLE_USERINFO_URL = "https://www.googleapis.com/oauth2/v2/userinfo"

_STATE_TTL_S = 120.0  # oauth.go timeout = 2 minutes


class OAuthError(Exception):
    pass


class OAuthProvider:
    """One configured provider speaking the authorization-code flow."""

    def __init__(
        self,
        name: str,
        client_id: str,
        client_secret: str,
        redirect_url: str = "",
        auth_url: str = "",
        token_url: str = "",
        userinfo_url: str = "",
        scopes: list[str] | None = None,
        timeout: float = 120.0,
    ):
        if name == "github":
            auth_url = auth_url or GITHUB_AUTH_URL
            token_url = token_url or GITHUB_TOKEN_URL
            userinfo_url = userinfo_url or GITHUB_USERINFO_URL
            scopes = scopes if scopes is not None else ["user", "public_repo"]
        elif name == "google":
            auth_url = auth_url or GOOGLE_AUTH_URL
            token_url = token_url or GOOGLE_TOKEN_URL
            userinfo_url = userinfo_url or GOOGLE_USERINFO_URL
            scopes = scopes if scopes is not None else [
                "https://www.googleapis.com/auth/userinfo.email",
                "https://www.googleapis.com/auth/userinfo.profile",
            ]
        elif not (auth_url and token_url and userinfo_url):
            raise OAuthError(
                f"unknown oauth provider {name!r} needs explicit auth/token/userinfo urls"
            )
        self.name = name
        self.client_id = client_id
        self.client_secret = client_secret
        self.redirect_url = redirect_url
        self.auth_url = auth_url
        self.token_url = token_url
        self.userinfo_url = userinfo_url
        self.scopes = scopes or []
        self.timeout = timeout
        self._states: dict[str, float] = {}
        self._lock = threading.Lock()

    # ------------------------------------------------------------- signin

    def auth_code_url(self) -> str:
        """Consent-page URL with a fresh state (AuthCodeURL)."""
        state = secrets.token_urlsafe(16)
        now = time.monotonic()
        with self._lock:
            self._states[state] = now + _STATE_TTL_S
            for s, exp in list(self._states.items()):
                if exp < now:
                    del self._states[s]
        query = {
            "client_id": self.client_id,
            "response_type": "code",
            "state": state,
        }
        if self.redirect_url:
            query["redirect_uri"] = self.redirect_url
        if self.scopes:
            query["scope"] = " ".join(self.scopes)
        return f"{self.auth_url}?{urllib.parse.urlencode(query)}"

    def check_state(self, state: str) -> bool:
        with self._lock:
            exp = self._states.pop(state, None)
        return exp is not None and exp >= time.monotonic()

    # ----------------------------------------------------------- exchange

    def exchange(self, code: str) -> str:
        """Authorization code -> access token (Exchange)."""
        body = urllib.parse.urlencode(
            {
                "client_id": self.client_id,
                "client_secret": self.client_secret,
                "code": code,
                "grant_type": "authorization_code",
                **({"redirect_uri": self.redirect_url} if self.redirect_url else {}),
            }
        ).encode()
        req = urllib.request.Request(
            self.token_url,
            data=body,
            headers={
                "Accept": "application/json",
                "Content-Type": "application/x-www-form-urlencoded",
            },
        )
        try:
            with urllib.request.urlopen(req, timeout=self.timeout) as resp:
                payload = json.loads(resp.read())
        except (urllib.error.URLError, ValueError) as e:
            raise OAuthError(f"token exchange against {self.token_url} failed: {e}") from e
        token = payload.get("access_token")
        if not token:
            raise OAuthError(f"provider returned no access_token: {payload}")
        return token

    def get_user(self, token: str) -> dict:
        """Access token -> {name, email, avatar} (GetUser)."""
        req = urllib.request.Request(
            self.userinfo_url,
            headers={"Authorization": f"Bearer {token}", "Accept": "application/json"},
        )
        try:
            with urllib.request.urlopen(req, timeout=self.timeout) as resp:
                payload = json.loads(resp.read())
        except (urllib.error.URLError, ValueError) as e:
            raise OAuthError(f"userinfo against {self.userinfo_url} failed: {e}") from e
        # `subject` is the provider's STABLE identity (github numeric id /
        # google sub) — account linking must key on it, never on the
        # user-editable display name (anyone can rename themselves "root").
        subject = payload.get("id") or payload.get("sub") or payload.get("login") or ""
        name = payload.get("login") or payload.get("name") or ""
        if not subject or not name:
            raise OAuthError(f"provider userinfo has no usable identity: {payload}")
        return {
            "subject": str(subject),
            "name": str(name),
            "email": payload.get("email") or "",
            "avatar": payload.get("avatar_url") or payload.get("picture") or "",
        }


def provider_from_record(record: dict) -> OAuthProvider:
    """Build a provider from an `oauth` table row (manager/models Oauth:
    name/client_id/client_secret/redirect_url; the *_url extension columns
    let tests and self-hosted IdPs override the endpoints)."""
    return OAuthProvider(
        name=record["name"],
        client_id=record.get("client_id", ""),
        client_secret=record.get("client_secret", ""),
        redirect_url=record.get("redirect_url", ""),
        auth_url=record.get("auth_url", ""),
        token_url=record.get("token_url", ""),
        userinfo_url=record.get("userinfo_url", ""),
    )
