"""Probe ring buffer + folded EWMA tests (reference:
scheduler/networktopology/probes_test.go behaviors)."""

import numpy as np

from dragonfly2_tpu.ops import ewma


def python_fold(samples, w=0.1):
    if not samples:
        return 0.0
    avg = samples[0]
    for s in samples[1:]:
        avg = w * avg + (1 - w) * s
    return avg


def test_fold_average_matches_reference_fold():
    q = 5
    ring = np.zeros((3, q), np.float32)
    cursor = np.zeros(3, np.int32)
    count = np.zeros(3, np.int32)
    # pair 0: 3 samples (partial); pair 1: empty; pair 2: full wrapped ring
    ring[0, :3] = [10.0, 20.0, 30.0]
    cursor[0], count[0] = 3, 3
    samples2 = [1.0, 2.0, 3.0, 4.0, 5.0, 6.0, 7.0]  # last 5 live, cursor wrapped
    for i, s in enumerate(samples2):
        ring[2, i % q] = s
    cursor[2], count[2] = len(samples2) % q, q
    got = np.asarray(ewma.fold_average(ring, cursor, count))
    assert got[0] == np.float32(python_fold([10.0, 20.0, 30.0]))
    assert got[1] == 0.0
    assert np.isclose(got[2], python_fold(samples2[-5:]), rtol=1e-6)


def test_enqueue_drops_oldest_and_updates_average():
    q = 5
    n = 4
    ring = np.zeros((n, q), np.float32)
    cursor = np.zeros(n, np.int32)
    count = np.zeros(n, np.int32)
    history = {i: [] for i in range(n)}
    rng = np.random.default_rng(2)
    for step in range(12):
        pair = np.array([int(rng.integers(n))], np.int32)
        rtt = np.array([float(rng.uniform(1, 100))], np.float32)
        history[int(pair[0])].append(float(rtt[0]))
        ring, cursor, count, avg = ewma.enqueue(ring, cursor, count, pair, rtt)
        ring, cursor, count, avg = map(np.asarray, (ring, cursor, count, avg))
        for i in range(n):
            assert count[i] == min(len(history[i]), q)
            want = python_fold(history[i][-q:])
            assert np.isclose(avg[i], want, rtol=1e-5), (step, i)


def test_probed_count_and_least_probed():
    import jax

    probed = np.array([5, 0, 2, 9, 1], np.int64)
    probed = np.asarray(ewma.probed_count_increment(probed, np.array([1, 1, 4], np.int32)))
    assert probed.tolist() == [5, 2, 2, 9, 2]

    alive = np.array([True, True, True, True, False])
    idx, valid = ewma.least_probed_hosts(probed, alive, jax.random.key(0), k=3)
    idx, valid = np.asarray(idx), np.asarray(valid)
    assert valid.all()
    assert 3 not in idx.tolist()  # most-probed host not picked
    assert 4 not in idx.tolist()  # dead host not picked
    assert set(idx.tolist()) == {0, 1, 2}
