"""dftail: per-download lifecycle ledger and critical-path TTC
decomposition.

The observability planes before this one can say WHICH parent was chosen
(telemetry/decisions.py), WHETHER the planet is healthy (telemetry/slo.py)
and WHAT the device pays (telemetry/costcard.py); none of them can answer
"why was download X slow". :class:`TailTrace` closes that gap: a bounded
columnar (SoA — numpy columns, no per-download Python dicts on any hot
path) ledger that attributes every completed download's time-to-complete
to the lifecycle phases it traversed —

    register -> schedule-wait -> parent fetch -> piece retries ->
    failover/re-announce -> back-to-source -> digest verify -> complete

— such that the attributed phases sum to the measured TTC exactly (the
caller constructs the phase vector from disjoint components; the
``decomp_ratio`` cell in every report is the audit of that invariant).

Two planes feed it:

- the megascale ``EventBatchEngine`` on the EVENT clock (one ``observe``
  per completion, phases in virtual ns) — everything recorded there is a
  pure function of (spec, seed), so paired-seed runs produce
  bit-identical ``deterministic_digest()`` values;
- the real client path (client/daemon.py + client/conductor.py), where
  phase durations are measured by the CALLERS with ``perf_counter_ns``
  and handed in — this module itself never reads a clock (it sits in the
  dflint DET decision domain next to telemetry/slo.py).

Bounded memory at planet scale: aggregates are per-(region, phase)
sketches/sums (independent of host count) and exemplar retention is
deterministic sampling — always-keep slowest-K per region plus a
counter-hashed uniform sample (the splitmix64 ``hash_u01`` construction,
never process-global rng) into a fixed-capacity ring, so a 1M-host day
keeps the same footprint as a 10k-host smoke.

Surfaces: the ``tail`` block in ``run_megascale`` reports /
``BENCH_mega.json`` (:meth:`TailTrace.report`), the ``tail`` section of
``flight.dump()`` / ``/debug/flight`` (:meth:`TailTrace.dump` via the
weak registry), the ``dragonfly_tail_*`` metric families
(telemetry/series.tail_series), dfslo cause enrichment (the per-sample
dominant phase rides the timeline), and ``tools/dftail.py`` offline.
"""

from __future__ import annotations

import hashlib
import threading
import weakref
from typing import Any, Iterable, Sequence

import numpy as np

from dragonfly2_tpu.telemetry.timeline import QuantileSketch

# Lifecycle phases, in causal order. Index constants are the hot-path
# contract: callers accumulate into a float vector by index and hand the
# vector to observe() — never a dict per download.
PHASES: tuple[str, ...] = (
    "register",
    "schedule_wait",
    "parent_fetch",
    "retry",
    "failover",
    "back_to_source",
    "verify",
)
N_PHASES = len(PHASES)
(
    PH_REGISTER,
    PH_SCHEDULE_WAIT,
    PH_PARENT_FETCH,
    PH_RETRY,
    PH_FAILOVER,
    PH_BACK_TO_SOURCE,
    PH_VERIFY,
) = range(N_PHASES)

# attributed-sums-to-measured audit bound, shared with tools/dftail.py:
# phase vectors are built from disjoint components so the event plane
# sums exactly; the client plane books unmeasured glue as schedule wait
# and must still land within this
DEFAULT_TOLERANCE = 0.05


# --------------------------------------------------- deterministic sampling

_MASK64 = (1 << 64) - 1
_GOLD = 0x9E3779B97F4A7C15
_SM_A = 0xBF58476D1CE4E5B9
_SM_B = 0x94D049BB133111EB
_KIND_CODES: dict[str, int] = {}


def _kind_code(kind: str) -> int:
    """Stable 64-bit code for a sampling kind — blake2b of the name, so
    codes never depend on interpreter hash randomization (the same
    construction as megascale/topology._kind_code)."""
    code = _KIND_CODES.get(kind)
    if code is None:
        code = int.from_bytes(
            hashlib.blake2b(kind.encode(), digest_size=8).digest(), "big"
        )
        _KIND_CODES[kind] = code
    return code


def _mix64(h: int) -> int:
    h &= _MASK64
    h = ((h ^ (h >> 30)) * _SM_A) & _MASK64
    h = ((h ^ (h >> 27)) * _SM_B) & _MASK64
    return h ^ (h >> 31)


def hash_u01_scalar(seed: int, kind: str, *keys: int) -> float:
    """Scalar twin of ``megascale.topology.hash_u01`` (bit-identical for
    the same inputs): deterministic uniform in [0, 1) as a pure function
    of (seed, kind, keys). The hot path samples one download at a time,
    and a per-call numpy round-trip would cost more than the mix."""
    h = _mix64((seed & _MASK64) ^ _kind_code(kind))
    for k in keys:
        h = _mix64(((h ^ (int(k) & _MASK64)) * _GOLD) & _MASK64)
    return (h >> 11) * 2.0 ** -53


# --------------------------------------------------- process-wide registry

_TRACERS: dict[str, "weakref.ref[TailTrace]"] = {}
_tracers_mu = threading.Lock()


def register_tracer(name: str, tracer: "TailTrace") -> None:
    """Weak named registry (mirrors timeline.register_timeline /
    decisions.register_ledger) so the process-wide ``/debug/flight``
    dump finds live tracers without a handle on the engine or daemon
    that owns them. Last registration wins."""
    with _tracers_mu:
        _TRACERS[name] = weakref.ref(tracer)


def live_tracers() -> dict[str, "TailTrace"]:
    out: dict[str, "TailTrace"] = {}
    with _tracers_mu:
        for name, ref in list(_TRACERS.items()):
            tracer = ref()
            if tracer is None:
                del _TRACERS[name]
            else:
                out[name] = tracer
    return out


# ----------------------------------------------------------------- tracer


class TailTrace:
    """Bounded columnar tail-attribution ledger.

    ``observe(region, seq, ttc_ns, phase_ns, round_idx)`` records one
    completed download: its measured TTC and the per-phase attribution
    vector (both in ns — virtual ns on the event clock, wall ns on the
    client plane). Aggregates are SoA numpy arrays sized by
    (regions x phases) plus one growable (rounds x phases) matrix —
    never by download count — and exemplar retention is deterministic:
    the slowest ``slowest_k`` downloads per region always stay, plus a
    ``hash_u01``-sampled uniform slice into a fixed ring.
    """

    def __init__(
        self,
        regions: Sequence[str] = ("region-0",),
        *,
        seed: int = 0,
        name: str | None = None,
        slowest_k: int = 8,
        sample_rate: float = 1.0 / 64.0,
        exemplar_capacity: int = 256,
        registry: Any = None,
    ) -> None:
        self.regions = tuple(str(r) for r in regions) or ("region-0",)
        n = len(self.regions)
        self.seed = int(seed)
        self.name = name
        self.slowest_k = max(int(slowest_k), 1)
        self.sample_rate = float(sample_rate)
        self.exemplar_capacity = max(int(exemplar_capacity), 1)
        self._mu = threading.Lock()
        self._seq = 0
        # --- aggregates: (regions,) / (regions, phases), host-count-free
        self._completions = np.zeros(n, np.int64)
        self._ttc_sum_ns = np.zeros(n, np.float64)
        self._phase_sum_ns = np.zeros((n, N_PHASES), np.float64)
        self._dominant = np.zeros((n, N_PHASES), np.int64)
        self._ttc_sketch = [
            QuantileSketch(relative_accuracy=0.01) for _ in range(n)
        ]
        self._phase_sketch = [
            [QuantileSketch(relative_accuracy=0.01) for _ in range(N_PHASES)]
            for _ in range(n)
        ]
        # --- per-round phase attribution matrix: grows with ROUNDS (one
        # compressed day is ~10^2 rows), never with hosts — the basis of
        # the kill-window dominant-phase report
        self._round_phase_ns = np.zeros((128, N_PHASES), np.float64)
        # the single slowest completion per round (TTC + its phase
        # vector): the per-window TAIL view. The mass matrix above can
        # bury a scheduler kill under hundreds of healthy completions;
        # the worst download in the window cannot be buried.
        self._round_slow_ttc = np.full(128, -1.0, np.float64)
        self._round_slow_phase = np.zeros((128, N_PHASES), np.float64)
        self._max_round = -1
        # --- slowest-K exemplars per region (always kept)
        k = self.slowest_k
        self._slow_ttc = np.full((n, k), -1.0, np.float64)
        self._slow_seq = np.full((n, k), -1, np.int64)
        self._slow_round = np.full((n, k), -1, np.int64)
        self._slow_phase = np.zeros((n, k, N_PHASES), np.float64)
        # --- counter-hashed uniform exemplar ring (fixed capacity)
        cap = self.exemplar_capacity
        self._ring_seq = np.full(cap, -1, np.int64)
        self._ring_region = np.full(cap, -1, np.int32)
        self._ring_round = np.full(cap, -1, np.int64)
        self._ring_ttc = np.zeros(cap, np.float64)
        self._ring_phase = np.zeros((cap, N_PHASES), np.float64)
        self._ring_count = 0
        from dragonfly2_tpu.telemetry import metrics as _metrics
        from dragonfly2_tpu.telemetry.series import tail_series

        reg = registry if registry is not None else _metrics.default_registry()
        self._series = tail_series(reg)
        self._children: dict[tuple, Any] = {}
        if name is not None:
            register_tracer(name, self)

    # ------------------------------------------------------------- feeding

    def next_seq(self) -> int:
        """Monotone download sequence for callers without a natural one
        (the client plane; the megascale plane uses its registration
        counter)."""
        with self._mu:
            seq = self._seq
            self._seq += 1
            return seq

    def observe(
        self,
        region: int,
        seq: int,
        ttc_ns: float,
        phase_ns: "np.ndarray | Sequence[float]",
        round_idx: int = 0,
    ) -> None:
        """Record one completed download. ``phase_ns`` is the length-
        ``N_PHASES`` attribution vector (indices ``PH_*``); callers build
        it from disjoint components so it sums to ``ttc_ns``."""
        vec = np.asarray(phase_ns, np.float64)
        r = int(region)
        name = self.regions[r] if 0 <= r < len(self.regions) else str(r)
        with self._mu:
            if not 0 <= r < len(self.regions):
                return
            if seq >= self._seq:
                self._seq = int(seq) + 1
            self._completions[r] += 1
            self._ttc_sum_ns[r] += float(ttc_ns)
            self._phase_sum_ns[r] += vec
            dom = int(np.argmax(vec))
            self._dominant[r, dom] += 1
            self._ttc_sketch[r].add(float(ttc_ns) / 1e6)
            sketches = self._phase_sketch[r]
            for p in range(N_PHASES):
                sketches[p].add(float(vec[p]) / 1e6)
            # per-round matrix row (kill-window attribution basis)
            ri = max(int(round_idx), 0)
            if ri >= self._round_phase_ns.shape[0]:
                rows = max(self._round_phase_ns.shape[0] * 2, ri + 1)
                grown = np.zeros((rows, N_PHASES), np.float64)
                grown[: self._round_phase_ns.shape[0]] = self._round_phase_ns
                self._round_phase_ns = grown
                grown_ttc = np.full(rows, -1.0, np.float64)
                grown_ttc[: self._round_slow_ttc.shape[0]] = self._round_slow_ttc
                self._round_slow_ttc = grown_ttc
                grown_ph = np.zeros((rows, N_PHASES), np.float64)
                grown_ph[: self._round_slow_phase.shape[0]] = self._round_slow_phase
                self._round_slow_phase = grown_ph
            self._round_phase_ns[ri] += vec
            if float(ttc_ns) > self._round_slow_ttc[ri]:
                self._round_slow_ttc[ri] = float(ttc_ns)
                self._round_slow_phase[ri] = vec
            if ri > self._max_round:
                self._max_round = ri
            # slowest-K: replace the region's current minimum when slower
            # (strict >, so observation order breaks ties deterministically)
            slot = int(np.argmin(self._slow_ttc[r]))
            if float(ttc_ns) > self._slow_ttc[r, slot]:
                self._slow_ttc[r, slot] = float(ttc_ns)
                self._slow_seq[r, slot] = int(seq)
                self._slow_round[r, slot] = ri
                self._slow_phase[r, slot] = vec
            # counter-hashed uniform sample into the fixed ring
            if hash_u01_scalar(self.seed, "tail_exemplar", seq) < self.sample_rate:
                pos = self._ring_count % self.exemplar_capacity
                self._ring_seq[pos] = int(seq)
                self._ring_region[pos] = r
                self._ring_round[pos] = ri
                self._ring_ttc[pos] = float(ttc_ns)
                self._ring_phase[pos] = vec
                self._ring_count += 1
        source = self.name or "tail"
        self._child(self._series.completions, source, name).inc()
        self._child(self._series.dominant, source, name, PHASES[dom]).inc()

    # ------------------------------------------------------------ queries

    def round_dominant(self, round_idx: int) -> str | None:
        """Dominant phase among the attributed time of downloads that
        COMPLETED in ``round_idx`` (None when that round completed
        nothing) — the per-sample cause hint the SLO plane rides."""
        with self._mu:
            ri = int(round_idx)
            if not 0 <= ri <= self._max_round:
                return None
            row = self._round_phase_ns[ri]
            if float(row.sum()) <= 0.0:
                return None
            return PHASES[int(np.argmax(row))]

    def round_phase_matrix_ms(self) -> list[list[float]]:
        """The per-round phase-attribution matrix (rounds x phases, ms)
        — the complete offline basis for window/dominant recomputation:
        ``tools/dftail.py`` re-derives the report's window attribution
        from this alone and drift-checks it against the recorded one."""
        with self._mu:
            matrix = self._round_phase_ns[: self._max_round + 1] / 1e6
            return [[round(float(v), 3) for v in row] for row in matrix]

    def round_slow_matrix_ms(self) -> list[list[float]]:
        """Per-round slowest-completion rows (``[ttc_ms, *phase_ms]``;
        ttc -1 when the round completed nothing) — the offline basis
        for the windows' tail view, same contract as
        :meth:`round_phase_matrix_ms`."""
        with self._mu:
            n = self._max_round + 1
            ttc = self._round_slow_ttc[:n]
            phase = self._round_slow_phase[:n]
            return [
                [round(float(ttc[i]) / 1e6, 3) if ttc[i] > 0.0 else -1.0]
                + [round(float(v) / 1e6, 3) for v in phase[i]]
                for i in range(n)
            ]

    def exemplar_rows(self) -> list[dict]:
        """Kept exemplars as plain rows, shed-friendly order: the uniform
        ring first (oldest retained first), then the slowest-K blocks
        ascending by TTC — so a byte-capped dump drops uniform samples
        before it drops the slowest downloads on the planet."""
        with self._mu:
            rows: list[dict] = []
            kept = min(self._ring_count, self.exemplar_capacity)
            start = self._ring_count - kept
            for i in range(start, self._ring_count):
                pos = i % self.exemplar_capacity
                rows.append(self._exemplar_row(
                    "uniform", int(self._ring_seq[pos]),
                    int(self._ring_region[pos]), int(self._ring_round[pos]),
                    float(self._ring_ttc[pos]), self._ring_phase[pos],
                ))
            slow: list[dict] = []
            for r in range(len(self.regions)):
                for slot in range(self.slowest_k):
                    if self._slow_seq[r, slot] < 0:
                        continue
                    slow.append(self._exemplar_row(
                        "slowest", int(self._slow_seq[r, slot]), r,
                        int(self._slow_round[r, slot]),
                        float(self._slow_ttc[r, slot]),
                        self._slow_phase[r, slot],
                    ))
            slow.sort(key=lambda e: (e["ttc_ms"], e["seq"]))
            rows.extend(slow)
            return rows

    def _exemplar_row(
        self, kind: str, seq: int, region: int, round_idx: int,
        ttc_ns: float, vec: np.ndarray,
    ) -> dict:
        name = (
            self.regions[region] if 0 <= region < len(self.regions)
            else str(region)
        )
        return {
            "kind": kind,
            "seq": seq,
            "region": name,
            "round": round_idx,
            "ttc_ms": round(ttc_ns / 1e6, 3),
            "phases_ms": {
                PHASES[p]: round(float(vec[p]) / 1e6, 3)
                for p in range(N_PHASES)
                if float(vec[p]) > 0.0
            },
        }

    # ---------------------------------------------------------- reporting

    # a kill's victims drain over the re-announce/retire cycle, not the
    # crash round alone — the soak's recovery completions land ~8 rounds
    # after the kill, so the window must reach past them (kills are 16
    # rounds apart; 12 keeps windows disjoint)
    DEFAULT_WINDOW_ROUNDS = 12

    def report(
        self,
        crash_rounds: Iterable[int] = (),
        window_rounds: int = DEFAULT_WINDOW_ROUNDS,
    ) -> dict:
        """The deterministic tail block for ``run_megascale`` reports and
        BENCH_mega artifacts: per-region TTC quantiles with their
        per-phase decomposition, phase shares, dominant-phase histogram,
        kill-window attribution over ``crash_rounds``, kept exemplars,
        and the paired-seed digest."""
        with self._mu:
            regions: dict[str, dict] = {}
            for r, name in enumerate(self.regions):
                regions[name] = self._region_block_locked(r)
            dominant_hist = {
                PHASES[p]: int(self._dominant[:, p].sum())
                for p in range(N_PHASES)
                if int(self._dominant[:, p].sum())
            }
            windows, baseline = self._windows_locked(
                sorted(int(k) for k in crash_rounds), max(int(window_rounds), 1)
            )
            digest = self._digest_locked()
            completions = int(self._completions.sum())
            sampling = {
                "slowest_k": self.slowest_k,
                "uniform_rate": self.sample_rate,
                "ring_capacity": self.exemplar_capacity,
                "uniform_kept": min(self._ring_count, self.exemplar_capacity),
                "uniform_sampled": self._ring_count,
            }
        self.mirror_metrics()
        return {
            "phases": list(PHASES),
            "completions": completions,
            "regions": regions,
            "dominant_hist": dominant_hist,
            "windows": windows,
            "baseline_dominant_phase": baseline,
            "sampling": sampling,
            "exemplars": self.exemplar_rows(),
            "digest": digest,
        }

    def _region_block_locked(self, r: int) -> dict:
        completed = int(self._completions[r])
        ttc_sk = self._ttc_sketch[r]
        ttc_ms = {
            "p50": _round_opt(ttc_sk.quantile(0.50)),
            "p95": _round_opt(ttc_sk.quantile(0.95)),
            "p99": _round_opt(ttc_sk.quantile(0.99)),
        }
        decomposition: dict[str, dict] = {}
        for p in range(N_PHASES):
            sk = self._phase_sketch[r][p]
            decomposition[PHASES[p]] = {
                "p50": _round_opt(sk.quantile(0.50)),
                "p95": _round_opt(sk.quantile(0.95)),
                "p99": _round_opt(sk.quantile(0.99)),
            }
        total = float(self._phase_sum_ns[r].sum())
        share = {
            PHASES[p]: round(float(self._phase_sum_ns[r, p]) / total, 6)
            for p in range(N_PHASES)
            if total > 0.0 and float(self._phase_sum_ns[r, p]) > 0.0
        }
        # the attribution audit: attributed phase time over measured TTC
        # — 1.0 by construction, drifts only if a caller's vector stops
        # summing to its measured total
        ttc_total = float(self._ttc_sum_ns[r])
        ratio = round(total / ttc_total, 6) if ttc_total > 0.0 else None
        dominant = (
            PHASES[int(np.argmax(self._dominant[r]))] if completed else None
        )
        tail_block = self._tail_block_locked(r, ttc_ms["p99"])
        return {
            "completed": completed,
            "ttc_ms": ttc_ms,
            "decomposition_ms": decomposition,
            "phase_share": share,
            "decomp_ratio": ratio,
            "dominant_phase": dominant,
            "tail": tail_block,
        }

    def _tail_block_locked(self, r: int, p99_ms: float | None) -> dict:
        """The slowest-K view of one region: which phase dominates the
        kept tail, and the exemplar nearest the p99 as a concrete
        end-to-end decomposition that sums to ITS measured TTC."""
        kept = self._slow_seq[r] >= 0
        if not bool(kept.any()):
            return {"kept": 0, "dominant_phase": None, "p99_exemplar": None}
        phases = self._slow_phase[r][kept]
        dominant = PHASES[int(np.argmax(phases.sum(axis=0)))]
        exemplar = None
        if p99_ms is not None:
            ttc = self._slow_ttc[r][kept]
            order = np.argsort(np.abs(ttc / 1e6 - p99_ms), kind="stable")
            pick = int(order[0])
            exemplar = {
                "seq": int(self._slow_seq[r][kept][pick]),
                "ttc_ms": round(float(ttc[pick]) / 1e6, 3),
                "phases_ms": {
                    PHASES[p]: round(float(phases[pick, p]) / 1e6, 3)
                    for p in range(N_PHASES)
                    if float(phases[pick, p]) > 0.0
                },
                "sum_ms": round(float(phases[pick].sum()) / 1e6, 3),
            }
        return {
            "kept": int(kept.sum()),
            "dominant_phase": dominant,
            "p99_exemplar": exemplar,
        }

    def _windows_locked(
        self, crash_rounds: list[int], window_rounds: int
    ) -> tuple[list[dict], str | None]:
        """Per-kill-window dominant phases from the round matrix, plus
        the baseline dominant phase over every round outside a window."""
        last = self._max_round
        in_window = np.zeros(max(last + 1, 1), bool)
        windows: list[dict] = []
        for k in crash_rounds:
            lo = max(int(k), 0)
            hi = min(lo + window_rounds - 1, last)
            if hi < lo:
                windows.append({
                    "round": int(k), "until": int(k),
                    "dominant_phase": None, "phase_ms": {},
                    "tail_dominant_phase": None, "slowest_ttc_ms": None,
                })
                continue
            in_window[lo:hi + 1] = True
            row = self._round_phase_ns[lo:hi + 1].sum(axis=0)
            # tail view: the window's single slowest completion. Mass
            # argmax can bury a kill under healthy traffic (a trough
            # kill hurts few downloads); the worst download cannot hide.
            slow = self._round_slow_ttc[lo:hi + 1]
            s = int(np.argmax(slow))
            tail_dom = None
            slowest_ms = None
            if float(slow[s]) > 0.0:
                tail_dom = PHASES[int(np.argmax(self._round_slow_phase[lo + s]))]
                slowest_ms = round(float(slow[s]) / 1e6, 2)
            windows.append({
                "round": int(k),
                "until": hi,
                "dominant_phase": (
                    PHASES[int(np.argmax(row))] if float(row.sum()) > 0.0
                    else None
                ),
                "phase_ms": {
                    PHASES[p]: round(float(row[p]) / 1e6, 2)
                    for p in range(N_PHASES)
                    if float(row[p]) > 0.0
                },
                "tail_dominant_phase": tail_dom,
                "slowest_ttc_ms": slowest_ms,
            })
        baseline = None
        if last >= 0:
            base = self._round_phase_ns[: last + 1][~in_window[: last + 1]]
            if base.size:
                row = base.sum(axis=0)
                if float(row.sum()) > 0.0:
                    baseline = PHASES[int(np.argmax(row))]
        return windows, baseline

    def dump(self, last_n: int = 64) -> dict:
        """Plain-data snapshot for ``flight.dump()`` / ``/debug/flight``:
        the per-region summary plus the newest ``last_n`` exemplars (the
        byte-cap truncation loop sheds the ``exemplars`` list)."""
        with self._mu:
            regions = {
                name: self._region_block_locked(r)
                for r, name in enumerate(self.regions)
            }
            completions = int(self._completions.sum())
            digest = self._digest_locked()
        exemplars = self.exemplar_rows()
        exemplars = exemplars[-last_n:] if last_n > 0 else []
        self.mirror_metrics()
        return {
            "name": self.name or "tail",
            "phases": list(PHASES),
            "completions": completions,
            "regions": regions,
            "exemplars": exemplars,
            "digest": digest,
        }

    # ------------------------------------------------------------- digest

    def _digest_locked(self) -> str:
        """blake2b over every deterministic column and aggregate. All
        recorded values derive from the caller's clock (virtual ns on
        the event plane), so paired-seed megascale runs must match bit
        for bit — the tail twin of DecisionLedger.deterministic_digest."""
        h = hashlib.blake2b(digest_size=16)
        h.update(np.int64(self._completions.sum()).tobytes())
        h.update(np.int64(self._ring_count).tobytes())
        for arr in (
            self._completions, self._ttc_sum_ns, self._phase_sum_ns,
            self._dominant, self._round_phase_ns[: self._max_round + 1],
            self._round_slow_ttc[: self._max_round + 1],
            self._round_slow_phase[: self._max_round + 1],
            self._slow_ttc, self._slow_seq, self._slow_round,
            self._slow_phase, self._ring_seq, self._ring_region,
            self._ring_round, self._ring_ttc, self._ring_phase,
        ):
            h.update(np.ascontiguousarray(arr).tobytes())
        for sk in self._ttc_sketch:
            self._digest_sketch(h, sk)
        for row in self._phase_sketch:
            for sk in row:
                self._digest_sketch(h, sk)
        return h.hexdigest()

    @staticmethod
    def _digest_sketch(h: "hashlib._Hash", sk: QuantileSketch) -> None:
        h.update(np.int64(sk.count).tobytes())
        h.update(np.int64(sk._zero).tobytes())
        for idx in sorted(sk._buckets):
            h.update(np.int64(idx).tobytes())
            h.update(np.int64(sk._buckets[idx]).tobytes())

    def deterministic_digest(self) -> str:
        with self._mu:
            return self._digest_locked()

    # ------------------------------------------------------------ metrics

    def _child(self, family: Any, *labels: str) -> Any:
        key = (id(family),) + labels
        child = self._children.get(key)
        if child is None:
            child = self._children[key] = family.labels(*labels)
        return child

    def mirror_metrics(self) -> None:
        """Refresh the gauge families from the aggregates (quantiles and
        shares move on every observe; exporting them lazily at dump/
        report time keeps the hot path to two counter bumps)."""
        source = self.name or "tail"
        with self._mu:
            per_region = [
                (name, self._ttc_sketch[r], self._phase_sum_ns[r].copy())
                for r, name in enumerate(self.regions)
            ]
            kept_uniform = min(self._ring_count, self.exemplar_capacity)
            kept_slow = int((self._slow_seq >= 0).sum())
        for name, sketch, sums in per_region:
            for q in (0.50, 0.95, 0.99):
                v = sketch.quantile(q)
                if v is not None:
                    self._child(
                        self._series.ttc_ms, source, name, f"p{int(q * 100)}"
                    ).set(v)
            total = float(sums.sum())
            if total > 0.0:
                for p in range(N_PHASES):
                    self._child(
                        self._series.phase_share, source, name, PHASES[p]
                    ).set(float(sums[p]) / total)
        self._child(self._series.exemplars_kept, source, "uniform").set(
            float(kept_uniform)
        )
        self._child(self._series.exemplars_kept, source, "slowest").set(
            float(kept_slow)
        )


def _round_opt(v: float | None, nd: int = 2) -> float | None:
    return None if v is None else round(v, nd)


# ----------------------------------------------------------- client plane

_default_mu = threading.Lock()
_DEFAULT: TailTrace | None = None


def default_tailtrace() -> TailTrace:
    """The daemon-side tracer (real client plane, wall-ns phases measured
    by client/daemon.py + client/conductor.py with ``perf_counter_ns``).
    Lazy so importing this module never allocates columns."""
    global _DEFAULT
    with _default_mu:
        if _DEFAULT is None:
            _DEFAULT = TailTrace(regions=("local",), name="dfdaemon.tail")
        return _DEFAULT
