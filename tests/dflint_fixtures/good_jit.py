"""dflint green fixture: jit idioms the pass must accept — branching on
static args and shape metadata, None-structure gates, host math on
non-traced locals, and bucket-padded call sites."""

import functools

import jax
import jax.numpy as jnp
import numpy as np


@functools.partial(jax.jit, static_argnames=("algorithm", "k"))
def select(batch, mask, algorithm, k):
    if algorithm == "nt":  # static arg: legal python branch
        batch = batch * 2.0
    if batch.ndim > 1:  # shape metadata is static under trace
        batch = batch.reshape(batch.shape[0], -1)
    if mask is None:  # pytree-structure gate: static, legal
        mask = jnp.ones_like(batch)
    return jnp.where(mask > 0, batch, -jnp.inf)


def pad_pow2(n: int) -> int:
    p = 1
    while p < n:
        p *= 2
    return p


def host_caller(rows):
    # host-side padding BEFORE the jit call: the blessed idiom
    padded = np.zeros((pad_pow2(rows.shape[0]), rows.shape[1]), rows.dtype)
    padded[: rows.shape[0]] = rows
    n = int(rows.shape[0])  # host value, not a tracer
    return select(padded, None, "default", 4), float(n)
