"""dflint core: file contexts, waiver/marker parsing, pass runner.

Waivers are inline and must carry a reason::

    self._seed_rr += 1  # dflint: waive[LOCK001] -- single-writer by design

A waiver with an empty reason does NOT suppress the finding (the tier-1
gate additionally fails on reason-less waivers so they cannot silently
accumulate). A waiver comment may sit on the flagged line, on the line
directly above it, or on the enclosing ``def`` line (function-scoped).

``# dflint: under[<lock>]`` on a ``def`` line is not a waiver but a
contract marker: "every caller holds ``self.<lock>``". The
lock-discipline pass treats the whole body as guarded by that lock; the
runtime harness (lockorder.py) is the dynamic check that the contract
actually holds in the concurrency tests.
"""

from __future__ import annotations

import ast
import dataclasses
import re
import time
from pathlib import Path

WAIVE_RE = re.compile(
    r"#\s*dflint:\s*waive\[([A-Z]+\d{3})\]\s*(?:--\s*(\S.*?))?\s*$"
)
UNDER_RE = re.compile(r"#\s*dflint:\s*under\[([A-Za-z_][A-Za-z0-9_]*)\]")

DEFAULT_PACKAGE = "dragonfly2_tpu"


@dataclasses.dataclass(frozen=True)
class Finding:
    """One lint finding. ``finding_id`` is stable across line churn
    (rule + file + symbol), which is what the fixture golden tests pin;
    ``location`` is the clickable exact site."""

    rule: str
    path: str  # repo-relative
    line: int
    symbol: str  # Class.method / function qualname ("" at module scope)
    message: str
    waived: bool = False
    waive_reason: str = ""
    # line of the waiver comment this finding matched (0 = none): the
    # waiver audit uses it to tell live waivers from stale ones
    waive_line: int = 0

    @property
    def finding_id(self) -> str:
        return f"{self.rule}@{self.path}:{self.symbol or 'module'}"

    @property
    def location(self) -> str:
        return f"{self.path}:{self.line}"

    def to_json(self) -> dict:
        """Machine-readable row (--json): the stable id plus everything
        a CI annotator needs to place and explain the finding."""
        return {
            "id": self.finding_id,
            "rule": self.rule,
            "path": self.path,
            "line": self.line,
            "symbol": self.symbol,
            "message": self.message,
            "waived": self.waived,
            "waive_reason": self.waive_reason,
        }

    def render(self) -> str:
        tag = f" [waived: {self.waive_reason}]" if self.waived else ""
        sym = f" ({self.symbol})" if self.symbol else ""
        return f"{self.location}: {self.rule}{sym}: {self.message}{tag}"


class FileContext:
    """Parsed source + waiver/marker tables for one file."""

    def __init__(self, path: Path, rel: str):
        self.path = path
        self.rel = rel
        self.source = path.read_text()
        self.lines = self.source.splitlines()
        self.tree = ast.parse(self.source, filename=str(path))
        # line -> [(rule, reason)]
        self.waivers: dict[int, list[tuple[str, str]]] = {}
        # line -> lock name (under[...] markers, keyed by the def line)
        self.under: dict[int, str] = {}
        for i, text in enumerate(self.lines, start=1):
            m = WAIVE_RE.search(text)
            if m:
                self.waivers.setdefault(i, []).append(
                    (m.group(1), (m.group(2) or "").strip())
                )
            m = UNDER_RE.search(text)
            if m:
                self.under[i] = m.group(1)

    def waiver_at(self, rule: str, *lines: int) -> tuple[str, int] | None:
        """(reason, waiver line) for the first waiver of `rule` at any of
        the candidate lines (the flagged line, the line above, the def
        line). The line rides as an int — the stale-waiver audit keys on
        it, so it must never round-trip through display text."""
        for line in lines:
            for wrule, reason in self.waivers.get(line, ()):
                if wrule == rule:
                    return reason, line
        return None

    def under_lock(self, func: ast.FunctionDef | ast.AsyncFunctionDef) -> str | None:
        """Lock named by an under[...] marker on (or just above) the def."""
        for line in (func.lineno, func.lineno - 1):
            if line in self.under:
                return self.under[line]
        return None

    def make_finding(
        self,
        rule: str,
        node: ast.AST,
        message: str,
        symbol: str = "",
        def_line: int | None = None,
    ) -> Finding:
        line = getattr(node, "lineno", 1)
        candidates = [line, line - 1]
        if def_line is not None:
            candidates.append(def_line)
        waiver = self.waiver_at(rule, *candidates)
        if waiver is not None:
            reason, waive_line = waiver
            return Finding(rule, self.rel, line, symbol, message,
                           waived=bool(reason), waive_reason=reason,
                           waive_line=waive_line)
        return Finding(rule, self.rel, line, symbol, message)


@dataclasses.dataclass
class LintReport:
    findings: list[Finding]
    files_scanned: int
    duration_s: float

    def unwaived(self) -> list[Finding]:
        return [f for f in self.findings if not f.waived]

    def waived(self) -> list[Finding]:
        return [f for f in self.findings if f.waived]

    def by_rule(self) -> dict[str, list[Finding]]:
        out: dict[str, list[Finding]] = {}
        for finding in self.findings:
            out.setdefault(finding.rule, []).append(finding)
        return out

    def reasonless_waivers(self, contexts: list[FileContext]) -> list[str]:
        """Waiver comments whose reason is empty — the gate fails on
        these: a waiver without an argument is just a muzzle."""
        bad = []
        for ctx in contexts:
            for line, entries in sorted(ctx.waivers.items()):
                for rule, reason in entries:
                    if not reason:
                        bad.append(f"{ctx.rel}:{line}: waive[{rule}] has no reason")
        return bad

    def stale_waivers(self, contexts: list[FileContext]) -> list[str]:
        """Waiver comments whose rule no longer fires at their site — a
        stale waiver is a muzzle aimed at nothing, waiting to silently
        swallow the NEXT finding that lands on its line. The audit mode
        (`--audit-waivers`) and the tier-1 gate both fail on these, so
        an argued waiver dies when its argument stops being needed."""
        claimed = {
            (f.path, f.waive_line, f.rule)
            for f in self.findings if f.waive_line
        }
        stale = []
        for ctx in contexts:
            for line, entries in sorted(ctx.waivers.items()):
                for rule, _reason in entries:
                    if (ctx.rel, line, rule) not in claimed:
                        stale.append(
                            f"{ctx.rel}:{line}: waive[{rule}] is stale — "
                            f"the rule no longer fires here; delete the "
                            f"waiver"
                        )
        return stale

    def render(self, include_waived: bool = False) -> str:
        rows = [
            f.render() for f in self.findings if include_waived or not f.waived
        ]
        summary = (
            f"dflint: {len(self.unwaived())} finding(s), "
            f"{len(self.waived())} waived, {self.files_scanned} file(s), "
            f"{self.duration_s:.2f}s"
        )
        return "\n".join(rows + [summary])


# --------------------------------------------------------- AST utilities


def attr_chain(node: ast.AST) -> str | None:
    """Dotted name for Name/Attribute chains: ``self.state.peer_host`` ->
    "self.state.peer_host"; None when the chain roots in a call/subscript
    (e.g. ``foo().bar`` — not a stable name)."""
    parts: list[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


def self_attr(node: ast.AST) -> str | None:
    """"x" for ``self.x`` (exactly one level), else None."""
    if (
        isinstance(node, ast.Attribute)
        and isinstance(node.value, ast.Name)
        and node.value.id == "self"
    ):
        return node.attr
    return None


def iter_class_functions(cls: ast.ClassDef):
    """(funcdef) for every method directly on the class (nested defs are
    walked by the passes themselves so with-scope context is preserved)."""
    for stmt in cls.body:
        if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
            yield stmt


def call_name(node: ast.Call) -> str | None:
    """Dotted callee name, or None for computed callees."""
    return attr_chain(node.func)


# --------------------------------------------------------------- runner


def collect_files(root: Path, package: str = DEFAULT_PACKAGE) -> list[Path]:
    base = root / package
    return sorted(p for p in base.rglob("*.py") if p.is_file())


def parse_contexts(root: Path, files: list[Path]) -> list[FileContext]:
    contexts = []
    for path in files:
        try:
            rel = str(path.relative_to(root))
        except ValueError:  # outside the repo root (fixture tmp dirs)
            rel = str(path)
        contexts.append(FileContext(path, rel))
    return contexts


def default_passes():
    from tools.dflint.passes.collective import CollectivePass
    from tools.dflint.passes.determinism import DeterminismPass
    from tools.dflint.passes.flush_valve import FlushValvePass
    from tools.dflint.passes.jit_hygiene import JitHygienePass
    from tools.dflint.passes.lock_discipline import LockDisciplinePass
    from tools.dflint.passes.shape import ShapeDonationPass
    from tools.dflint.passes.wire import WirePass

    return [
        LockDisciplinePass(),
        FlushValvePass(),
        JitHygienePass(),
        DeterminismPass(),
        ShapeDonationPass(),
        CollectivePass(),
        WirePass(),
    ]


def run_dflint(
    root: str | Path,
    package: str = DEFAULT_PACKAGE,
    passes=None,
    files: list[Path] | None = None,
) -> tuple[LintReport, list[FileContext]]:
    """Run all (or the given) passes over `root/package` (or explicit
    `files`). Returns the report plus the parsed contexts so callers
    (the tier-1 gate) can audit waiver reasons."""
    root = Path(root)
    t0 = time.perf_counter()
    if files is None:
        files = collect_files(root, package)
    contexts = parse_contexts(root, files)
    if passes is None:
        passes = default_passes()
    findings: list[Finding] = []
    for ctx in contexts:
        for lint_pass in passes:
            findings.extend(lint_pass.run(ctx))
    # Cross-file passes (dfwire's producer/consumer closure needs the
    # whole parsed tree at once) emit from an optional finalize hook
    # after every context has been seen; per-file passes simply lack it.
    for lint_pass in passes:
        finalize = getattr(lint_pass, "finalize", None)
        if finalize is not None:
            findings.extend(finalize(contexts))
    findings.sort(key=lambda f: (f.path, f.line, f.rule))
    return (
        LintReport(findings, len(contexts), time.perf_counter() - t0),
        contexts,
    )
