"""Dynamic config: poll a source with an on-disk cache fallback.

Capability parity with internal/dynconfig/dynconfig.go: a generic
poll-manager-with-cache engine — `get()` returns cached data within the
expiry window, refreshes from the client otherwise, and falls back to the
last persisted snapshot when the source is unreachable (how schedulers and
daemons survive a manager outage). Observers are notified on change
(scheduler/config/dynconfig.go Register/Notify semantics).
"""

from __future__ import annotations

import json
import pathlib
import threading
import time
from typing import Any, Callable

from dragonfly2_tpu.utils import dferrors


class Dynconfig:
    def __init__(
        self,
        client: Callable[[], dict],
        cache_path: str | pathlib.Path,
        expire: float = 60.0,
    ):
        if expire <= 0:
            raise ValueError("expire must be positive")
        self._client = client
        self._cache_path = pathlib.Path(cache_path)
        self._expire = expire
        self._lock = threading.Lock()
        # Serializes whole refresh cycles (fetch + set + disk write) so a
        # stalled fetch can't clobber a newer snapshot behind it.
        self._refresh_lock = threading.Lock()
        self._data: dict | None = None
        self._fetched_at = 0.0
        self._observers: list[Callable[[dict], None]] = []

    def get(self) -> dict:
        with self._lock:
            if self._data is not None and time.monotonic() - self._fetched_at < self._expire:
                return self._data
        return self.refresh()

    def refresh(self) -> dict:
        """Fetch from the source; on failure serve the disk snapshot."""
        with self._refresh_lock:
            return self._refresh_locked()

    def _refresh_locked(self) -> dict:
        try:
            data = self._client()
        except Exception as e:  # noqa: BLE001 - any source failure falls back
            cached = self._load_disk()
            if cached is None:
                raise dferrors.Unavailable(f"dynconfig source failed and no cache: {e}")
            with self._lock:
                changed = cached != self._data
                self._data = cached
                self._fetched_at = time.monotonic()
            if changed:
                for fn in list(self._observers):
                    fn(cached)
            return cached
        changed = False
        with self._lock:
            changed = data != self._data
            self._data = data
            self._fetched_at = time.monotonic()
        self._store_disk(data)
        if changed:
            for fn in list(self._observers):
                fn(data)
        return data

    def register(self, observer: Callable[[dict], None]) -> None:
        self._observers.append(observer)

    # ------------------------------------------------------------ internal

    def _load_disk(self) -> dict | None:
        try:
            with open(self._cache_path) as f:
                return json.load(f)
        except (OSError, json.JSONDecodeError):
            return None

    def _store_disk(self, data: dict) -> None:
        self._cache_path.parent.mkdir(parents=True, exist_ok=True)
        # UNIQUE temp per writer: two processes sharing one cache file
        # (same-cluster schedulers on one data_dir) must not interleave
        # writes into a common .tmp and rename a torn snapshot into place
        import os
        import tempfile

        fd, tmp = tempfile.mkstemp(
            prefix=self._cache_path.name + ".", suffix=".tmp",
            dir=self._cache_path.parent,
        )
        try:
            with os.fdopen(fd, "w") as f:
                json.dump(data, f)
            os.replace(tmp, self._cache_path)
        except BaseException:
            try:
                os.unlink(tmp)
            except OSError:
                pass
            raise
