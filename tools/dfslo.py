#!/usr/bin/env python
"""dfslo — replay a recorded timeline against an SLO config and answer
"would this run have paged?".

The megascale lab's SLO engine (telemetry/slo.py) derives every SLI from
the per-round timeline sample it just recorded, so the judgment is a
PURE function of the timeline array: this tool re-runs the exact same
evaluation offline over any artifact that carries one —

- a ``BENCH_mega.json`` (``{"runs": [...]}``; every run replays),
- a single ``run_megascale`` report (``{"timeline": [...], ...}``),
- or a bare ``{"timeline": [...], "minutes_per_round": N}`` dump

— and prints per-run verdicts with the full burn-rate alert log. When
the artifact already carries the in-run ``slo`` block / per-sample
``slo_*`` columns, the replay is cross-checked against them and any
drift is reported loudly (the recorded judgment and the offline one can
only differ if the SLI derivation changed since the run).

Usage:
    python tools/dfslo.py BENCH_mega.json [--run soak] [--json]

Exit codes: 0 = no alerts fired in any selected run, 1 = ticket-severity
alerts only, 2 = at least one page fired (or the artifact/replay
disagree — a page you can't trust offline is still a page).
"""

from __future__ import annotations

import argparse
import json
import pathlib
import sys

sys.path.insert(0, str(pathlib.Path(__file__).resolve().parents[1]))


def _extract_runs(doc: dict, which: str | None) -> list[dict]:
    if isinstance(doc.get("runs"), list):
        runs = [r for r in doc["runs"] if isinstance(r, dict)]
    elif isinstance(doc.get("timeline"), list):
        runs = [doc]
    else:
        raise SystemExit(
            "dfslo: artifact carries neither 'runs' nor a 'timeline' array"
        )
    if which is not None:
        runs = [
            r for r in runs
            if str(r.get("scenario", "")) == which
            or f"{r.get('scenario')}_{r.get('hosts')}" == which
        ]
        if not runs:
            raise SystemExit(f"dfslo: no run matches --run {which!r}")
    out = []
    for r in runs:
        if not r.get("timeline"):
            print(
                f"dfslo: skipping {r.get('scenario', '?')} "
                f"(no timeline array — artifact predates the SLO plane)",
                file=sys.stderr,
            )
            continue
        out.append(r)
    if not out:
        raise SystemExit("dfslo: no selected run carries a timeline array")
    return out


def _check_recorded(run: dict, replay: dict) -> list[str]:
    """Cross-check the offline replay against what the run recorded:
    the report's slo block and the per-sample slo_* columns."""
    drift: list[str] = []
    recorded = run.get("slo")
    if isinstance(recorded, dict):
        for key in ("pages_fired", "tickets_fired", "verdict_final"):
            if key in recorded and recorded[key] != replay[key]:
                drift.append(
                    f"{key}: recorded {recorded[key]!r} != "
                    f"replayed {replay[key]!r}"
                )
        rec_log = recorded.get("alert_log")
        if isinstance(rec_log, list):
            # the report's log is a bounded tail (slo_report last_n);
            # compare against the same-length tail of the replayed log
            tail = replay["alert_log"][-len(rec_log):] if rec_log else []
            if rec_log != tail:
                drift.append(
                    f"alert_log: recorded {len(rec_log)} entries != "
                    f"replayed {len(replay['alert_log'])} (or contents "
                    f"differ)"
                )
    by_t = {c["t"]: c for c in replay["samples"]}
    for sample in run["timeline"]:
        if "slo_verdict" not in sample:
            continue
        col = by_t.get(sample["t"])
        if col is None:
            continue
        for key in ("slo_verdict", "slo_alerts_firing",
                    "slo_pages_fired", "slo_tickets_fired"):
            if key in sample and sample[key] != col[key]:
                drift.append(
                    f"t={sample['t']} {key}: recorded {sample[key]} != "
                    f"replayed {col[key]}"
                )
    return drift


def judge(doc: dict, which: str | None = None) -> tuple[int, list[dict]]:
    """Replay every selected run; return (exit_code, per-run results)."""
    from dragonfly2_tpu.telemetry.slo import replay_timeline

    results: list[dict] = []
    worst = 0
    for run in _extract_runs(doc, which):
        mpr = float(run.get("minutes_per_round") or 15.0)
        replay = replay_timeline(run["timeline"], mpr)
        drift = _check_recorded(run, replay)
        if replay["pages_fired"] > 0 or drift:
            rc = 2
        elif replay["tickets_fired"] > 0:
            rc = 1
        else:
            rc = 0
        worst = max(worst, rc)
        results.append({
            "run": f"{run.get('scenario', '?')}_{run.get('hosts', '?')}",
            "minutes_per_round": mpr,
            "samples": len(run["timeline"]),
            "paged": replay["paged"],
            "pages_fired": replay["pages_fired"],
            "tickets_fired": replay["tickets_fired"],
            "verdict_final": replay["verdict_final"],
            "worst_verdict": replay["worst_verdict"],
            "budget_remaining": replay["budget_remaining"],
            "alert_log": replay["alert_log"],
            "recorded_drift": drift,
            "exit_code": rc,
        })
    return worst, results


def main(argv: list[str] | None = None) -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("artifact", help="BENCH_mega.json / run report / timeline dump")
    ap.add_argument("--run", default=None,
                    help="select one run by scenario name or scenario_hosts")
    ap.add_argument("--json", action="store_true", dest="as_json",
                    help="machine-readable results on stdout")
    args = ap.parse_args(argv)

    with open(args.artifact) as f:
        doc = json.load(f)
    rc, results = judge(doc, args.run)
    if args.as_json:
        print(json.dumps({"exit_code": rc, "runs": results}, indent=1))
        return rc
    for r in results:
        verdict = (
            "PAGED" if r["pages_fired"] else
            ("TICKETED" if r["tickets_fired"] else "clean")
        )
        print(
            f"dfslo: {r['run']}: {verdict} — {r['pages_fired']} page(s), "
            f"{r['tickets_fired']} ticket(s) over {r['samples']} intervals; "
            f"final verdict {r['verdict_final']} "
            f"(worst {r['worst_verdict']})"
        )
        for e in r["alert_log"]:
            print(
                f"  t={e['t']:g} {e['slo']}/{e['rule']} "
                f"[{e['severity']}] {e['event']} "
                f"(burn long {e['burn_long']:g}x / short {e['burn_short']:g}x)"
            )
        for d in r["recorded_drift"]:
            print(f"  DRIFT vs recorded judgment: {d}")
    return rc


if __name__ == "__main__":
    sys.exit(main())
