"""Trainer-throughput benchmark: JAX/TPU GraphSAGE vs a torch-CPU
reference implementation of the SAME architecture and workload.

North star (BASELINE.md): trainer GNN throughput >= 50x a CPU reference,
in samples/sec/chip, converging on a 10k-peer trace. The reference repo
has no trainer at all (trainer/training/training.go:82-98 is a TODO
stub), so the CPU baseline is what the stub would most plausibly have
been: the same 2-layer mean-aggregation GraphSAGE ranker in torch on the
host CPU, full-precision, batch 1024.

Prints one JSON line:
  {"metric": "trainer_gnn_samples_per_sec", "value": <tpu>, "unit":
   "samples/s", "vs_baseline": <tpu / cpu_torch>}

(bench.py remains the driver's headline metric; this script documents the
second north star and is run manually / by CI.)
"""

from __future__ import annotations

import json
import sys
import time

import numpy as np

HIDDEN = 128
BATCH = 1024
EPOCHS = 4
NUM_HOSTS = 10_000
NUM_RECORDS = 20_000


def _dataset():
    from dragonfly2_tpu.records import synth
    from dragonfly2_tpu.records.features import downloads_to_ranking_dataset

    cluster = synth.make_cluster(NUM_HOSTS, seed=0)
    records = synth.gen_download_records(
        cluster, NUM_RECORDS, num_tasks=512, max_parents=20
    )
    return downloads_to_ranking_dataset(records)


# TPU v5e (v5 lite) peak: 197 TFLOP/s bf16 per chip — the denominator for
# MFU. The trainers run f32 matmuls, so MFU against the bf16 peak is the
# conservative convention (a bf16 port could only look better).
PEAK_TFLOPS = 197.0


def tpu_train_result(ds, graph):
    from dragonfly2_tpu.config.config import TrainerConfig
    from dragonfly2_tpu.training.train import train_gnn

    cfg = TrainerConfig(hidden_dim=HIDDEN, batch_size=BATCH, epochs=EPOCHS)
    return train_gnn(ds, graph, cfg)


def torch_cpu_samples_per_sec(ds, graph, max_steps: int = 8, hidden: int = None, batch: int = None) -> float:
    """Same model family in torch on CPU: 2 SAGE layers (self + neighbor
    mean + edge mean), listwise softmax rank loss, AdamW."""
    import torch

    hidden = hidden or HIDDEN
    batch = batch or BATCH
    torch.manual_seed(0)
    torch.set_num_threads(max(1, torch.get_num_threads()))

    node_feats = torch.tensor(graph.node_feats, dtype=torch.float32)
    edge_src = torch.tensor(graph.edge_src, dtype=torch.long)
    edge_dst = torch.tensor(graph.edge_dst, dtype=torch.long)
    edge_feats = torch.tensor(graph.edge_feats, dtype=torch.float32)
    n_nodes = node_feats.shape[0]
    f_node, f_edge = node_feats.shape[1], edge_feats.shape[1]

    class Sage(torch.nn.Module):
        def __init__(self, f_in, f_edge, hidden):
            super().__init__()
            self.self0 = torch.nn.Linear(f_in, hidden)
            self.neigh0 = torch.nn.Linear(f_in, hidden, bias=False)
            self.edge0 = torch.nn.Linear(f_edge, hidden, bias=False)
            self.self1 = torch.nn.Linear(hidden, hidden)
            self.neigh1 = torch.nn.Linear(hidden, hidden, bias=False)
            self.edge1 = torch.nn.Linear(f_edge, hidden, bias=False)
            self.score = torch.nn.Sequential(
                torch.nn.Linear(2 * hidden + 2, hidden),
                torch.nn.GELU(),
                torch.nn.Linear(hidden, 1),
            )

        def embed(self):
            h = node_feats
            cnt = torch.zeros(n_nodes, 1).index_add_(
                0, edge_src, torch.ones(edge_src.shape[0], 1)
            ).clamp(min=1.0)
            for self_l, neigh_l, edge_l in (
                (self.self0, self.neigh0, self.edge0),
                (self.self1, self.neigh1, self.edge1),
            ):
                agg = torch.zeros(n_nodes, h.shape[1]).index_add_(0, edge_src, h[edge_dst])
                eag = torch.zeros(n_nodes, f_edge).index_add_(0, edge_src, edge_feats)
                h = torch.nn.functional.gelu(
                    self_l(h) + neigh_l(agg / cnt) + edge_l(eag / cnt)
                )
            return h

        def forward(self, child_idx, parent_idx, pair_feats):
            h = self.embed()
            child = h[child_idx][:, None, :].expand(-1, parent_idx.shape[1], -1)
            parent = h[parent_idx]
            x = torch.cat([child, parent, pair_feats], dim=-1)
            return self.score(x)[..., 0]

    model = Sage(f_node, f_edge, hidden)
    opt = torch.optim.AdamW(model.parameters(), lr=1e-3)
    rng = np.random.default_rng(0)
    n = ds.child.shape[0]
    pair = np.concatenate(
        [ds.same_idc[..., None], ds.loc_match[..., None]], axis=-1
    ).astype(np.float32)

    steps = 0
    t0 = time.perf_counter()
    while steps < max_steps:
        idx = rng.choice(n, min(batch, n), replace=False)
        child_idx = torch.tensor(ds.child_host_idx[idx], dtype=torch.long)
        parent_idx = torch.tensor(ds.parent_host_idx[idx], dtype=torch.long)
        pf = torch.tensor(pair[idx])
        tp = torch.tensor(ds.throughput[idx])
        mask = torch.tensor(ds.mask[idx])
        scores = model(child_idx, parent_idx, pf)
        scores = scores.masked_fill(~mask, -1e30)
        target = torch.softmax(tp.masked_fill(~mask, -1e30), dim=-1)
        logp = torch.log_softmax(scores, dim=-1)
        loss = -(target * logp.masked_fill(~mask, 0.0)).sum(-1).mean()
        opt.zero_grad()
        loss.backward()
        opt.step()
        steps += 1
    dt = time.perf_counter() - t0
    return steps * min(batch, n) / dt


def main() -> int:
    ds, graph = _dataset()
    cpu = torch_cpu_samples_per_sec(ds, graph)
    result = tpu_train_result(ds, graph)
    tpu = result.samples_per_sec
    # MFU basis from the ONE shared policy (training.train.flops_basis)
    from dragonfly2_tpu.training.train import flops_basis

    flops_src, flops_ps = flops_basis(result)
    achieved_tflops = flops_ps * tpu / 1e12
    print(
        json.dumps(
            {
                "metric": "trainer_gnn_samples_per_sec",
                "value": round(tpu, 1),
                "unit": "samples/s",
                "vs_baseline": round(tpu / cpu, 2),
                "cpu_torch_baseline": round(cpu, 1),
                # "is it actually fast" vs chip peak (VERDICT r1 weak #6)
                "achieved_tflops": round(achieved_tflops, 3),
                "mfu_pct": round(100.0 * achieved_tflops / PEAK_TFLOPS, 3),
                "flops_source": flops_src,
                "flops_per_sample_xla": round(result.flops_per_sample, 1),
            }
        )
    )
    return 0


if __name__ == "__main__":
    sys.exit(main())
