"""HTTP piece server: parents serve stored pieces to children.

Capability parity with client/daemon/upload/upload_manager.go:270 (the
peer-to-peer data path — piece bytes move as HTTP range responses, SURVEY
§2.6). Routes:
  GET /download/{task_id}?piece={n}      -> one piece's bytes
  GET /download/{task_id}                -> whole stored file (Range ok)
  GET /pieces/{task_id}                  -> stored piece metadata (JSON) —
                                            the GetPieceTasks/SyncPieceTasks
                                            equivalent children use to learn
                                            what a parent can serve
  GET /pieces/{task_id}?wait_after=N[&timeout=S]
                                         -> LONG-POLL: block until the task
                                            holds MORE than N pieces (or is
                                            done, or S seconds pass), then
                                            answer with the current listing.
                                            This is the push half of piece
                                            announcements: a child subscribes
                                            to an in-progress parent and
                                            learns each new piece within one
                                            notification instead of one
                                            re-poll round trip per wave
                                            (peertask_piecetask_synchronizer
                                            .go's per-parent sync stream)
  GET /healthy                           -> liveness
Headers carry the piece digest so children can verify before commit.
"""

from __future__ import annotations

import http.server
import json
import threading
import time
import urllib.parse

from dragonfly2_tpu.client.storage import StorageManager
from dragonfly2_tpu.utils import dferrors
from dragonfly2_tpu.utils.digest import md5_from_bytes


class UploadServer:
    def __init__(self, storage: StorageManager, host: str = "127.0.0.1", port: int = 0,
                 fault_injector=None, on_piece_rot=None):
        self.storage = storage
        # Verify-on-serve hook: called as on_piece_rot(task_id, number)
        # when a stored piece's bytes no longer match their recorded
        # digest (local disk rot / torn write). The daemon wires this to a
        # self-reported reason="corruption" piece failure so the scheduler
        # stops advertising this peer for the task instead of letting
        # children discover the rot one wasted transfer at a time.
        self.on_piece_rot = on_piece_rot
        # Scenario-lab hook (scenarios/engine.FaultInjector): when set,
        # piece serving consults it per (task, piece, attempt) and may
        # answer 503 or stall before serving — faults injected at the
        # PARENT so the child daemon exercises its real retry path
        # (piece failure -> DownloadPieceFailed -> reschedule), not a
        # simulator-only shortcut. None (production) costs one attribute
        # read per piece request.
        self.fault_injector = fault_injector
        manager = self

        class Handler(http.server.BaseHTTPRequestHandler):
            protocol_version = "HTTP/1.1"

            def log_message(self, *args):
                pass

            def do_GET(self):  # noqa: N802 - stdlib API
                parts = urllib.parse.urlsplit(self.path)
                if parts.path == "/healthy":
                    self._reply(200, b"ok")
                    return
                if parts.path.startswith("/pieces/"):
                    q = urllib.parse.parse_qs(parts.query)
                    wait_after = (
                        int(q["wait_after"][0]) if "wait_after" in q else None
                    )
                    timeout = float(q.get("timeout", ["10.0"])[0])
                    self._serve_piece_list(
                        parts.path[len("/pieces/") :], wait_after, timeout
                    )
                    return
                if not parts.path.startswith("/download/"):
                    self._reply(404, b"not found")
                    return
                task_id = parts.path[len("/download/") :]
                ts = manager.storage.get(task_id)
                if ts is None:
                    self._reply(404, b"task not stored")
                    return
                query = urllib.parse.parse_qs(parts.query)
                if "piece" in query:
                    self._serve_piece(ts, int(query["piece"][0]))
                else:
                    self._serve_file(ts)

            def _serve_piece_list(
                self, task_id: str, wait_after: int | None = None,
                timeout: float = 10.0,
            ):
                ts = manager.storage.get(task_id)
                if ts is None:
                    self._reply(404, b"task not stored")
                    return
                if wait_after is not None:
                    # long-poll: parks THIS handler thread on the task's
                    # piece condition (bounded by the capped timeout) —
                    # ThreadingHTTPServer spawns per-connection threads,
                    # so parked subscribers do not block other uploads
                    ts.wait_for_pieces(wait_after, min(timeout, 30.0))
                meta = ts.meta
                body = json.dumps(
                    {
                        "task_id": meta.task_id,
                        "content_length": meta.content_length,
                        "piece_length": meta.piece_length,
                        "total_pieces": meta.total_pieces,
                        "done": meta.done,
                        "pieces": [
                            {
                                "number": p.number,
                                "offset": p.offset,
                                "length": p.length,
                                "digest": p.digest,
                            }
                            for p in sorted(meta.pieces.values(), key=lambda p: p.number)
                        ],
                    }
                ).encode()
                self.send_response(200)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def _serve_piece(self, ts, number: int):
                if not ts.has_piece(number):
                    self._reply(404, b"piece not stored")
                    return
                injector = manager.fault_injector
                verdict = None
                if injector is not None:
                    verdict = injector.piece_fault(ts.meta.task_id, number)
                    if verdict == "error":
                        self._reply(503, b"injected fault")
                        return
                    if verdict == "stall":
                        time.sleep(injector.stall_seconds)
                try:
                    piece = ts.meta.pieces[number]
                    data = ts.read_piece(number)
                except (KeyError, dferrors.NotFound):
                    # raced a concurrent eviction (another serve of this
                    # rotted piece, or the conductor's mark_done recovery)
                    # between has_piece and the read
                    self._reply(404, b"piece not stored")
                    return
                digest = piece.digest
                if verdict == "corrupt":
                    # Scenario-lab adversary: serve deterministically
                    # corrupted bytes under a SELF-CONSISTENT advisory
                    # header (a lying parent, not a clumsy one) — only
                    # verification against the scheduler-attested chain
                    # catches this.
                    data = injector.corrupt_bytes(ts.meta.task_id, number, data)
                    digest = md5_from_bytes(data)
                elif digest and md5_from_bytes(data) != digest:
                    # Verify-on-serve: the stored bytes no longer hash to
                    # the digest recorded at commit — local disk rot. Never
                    # serve them; EVICT the piece (it leaves the finished
                    # set so the daemon re-fetches instead of answering 503
                    # for this piece forever) and self-report so the
                    # scheduler quarantines this host rather than keeping
                    # it advertised. Only the thread whose evict actually
                    # removed the piece reports — N concurrent serves of
                    # one rot event must not multiply the quarantine
                    # penalty (the conductor dedups its reports the same
                    # way via _reported_corrupt).
                    if ts.evict_piece(number) and manager.on_piece_rot is not None:
                        manager.on_piece_rot(ts.meta.task_id, number)
                    self._reply(503, b"piece failed integrity check")
                    return
                self.send_response(200)
                self.send_header("Content-Length", str(len(data)))
                self.send_header("X-Dragonfly-Piece-Digest", digest)
                self.send_header("X-Dragonfly-Piece-Offset", str(piece.offset))
                self.end_headers()
                self.wfile.write(data)

            def _serve_file(self, ts):
                size = ts.size_on_disk()
                range_header = self.headers.get("Range")
                offset, length = 0, size
                status = 200
                if range_header and range_header.startswith("bytes="):
                    spec = range_header[len("bytes=") :].split("-")
                    offset = int(spec[0]) if spec[0] else 0
                    end = int(spec[1]) if len(spec) > 1 and spec[1] else size - 1
                    length = end - offset + 1
                    status = 206
                data = ts.read_range(offset, length)
                self.send_response(status)
                if status == 206:
                    self.send_header(
                        "Content-Range", f"bytes {offset}-{offset + len(data) - 1}/{size}"
                    )
                self.send_header("Content-Length", str(len(data)))
                self.end_headers()
                self.wfile.write(data)

            def _reply(self, code: int, body: bytes):
                self.send_response(code)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

        self._server = http.server.ThreadingHTTPServer((host, port), Handler)
        self.host, self.port = self._server.server_address[:2]
        self._thread: threading.Thread | None = None

    def start(self) -> tuple[str, int]:
        self._thread = threading.Thread(target=self._server.serve_forever, daemon=True)
        self._thread.start()
        return self.host, self.port

    def stop(self) -> None:
        self._server.shutdown()
        self._server.server_close()
