"""Object storage: backends, daemon HTTP service, dfstore SDK/CLI
(pkg/objectstorage + client/daemon/objectstorage + client/dfstore parity)."""

import pytest

from dragonfly2_tpu.client.storage import StorageManager
from dragonfly2_tpu.objectstorage.backends import (
    FilesystemBackend,
    new_backend,
    object_task_id,
)
from dragonfly2_tpu.objectstorage.service import DfstoreClient, ObjectStorageService
from dragonfly2_tpu.utils import dferrors


def test_fs_backend_bucket_and_object_crud(tmp_path):
    be = FilesystemBackend(tmp_path)
    be.create_bucket("models")
    assert be.is_bucket_exist("models")
    meta = be.put_object("models", "ranker/1/model.bin", b"weights")
    assert meta.content_length == 7 and meta.etag
    assert be.get_object("models", "ranker/1/model.bin") == b"weights"
    assert be.get_object("models", "ranker/1/model.bin", range_=(1, 3)) == b"eig"
    be.copy_object("models", "ranker/1/model.bin", "ranker/2/model.bin")
    keys = [m.key for m in be.get_object_metadatas("models", prefix="ranker/")]
    assert keys == ["ranker/1/model.bin", "ranker/2/model.bin"]
    with pytest.raises(dferrors.InvalidArgument):
        be.delete_bucket("models")  # not empty
    be.delete_object("models", "ranker/1/model.bin")
    be.delete_object("models", "ranker/2/model.bin")
    be.delete_bucket("models")
    assert not be.is_bucket_exist("models")


def test_fs_backend_rejects_escapes(tmp_path):
    be = FilesystemBackend(tmp_path)
    be.create_bucket("b")
    with pytest.raises(dferrors.InvalidArgument):
        be.put_object("b", "../escape", b"x")
    with pytest.raises(dferrors.InvalidArgument):
        be.create_bucket("nested/bucket")
    with pytest.raises(dferrors.NotFound):
        be.get_object("b", "missing")


def test_new_backend_vendor_gating(tmp_path):
    assert new_backend("fs", tmp_path).name == "fs"
    for vendor in ("s3", "oss", "obs"):
        with pytest.raises(dferrors.Unavailable):
            new_backend(vendor)
    with pytest.raises(dferrors.InvalidArgument):
        new_backend("gcs")


@pytest.fixture()
def service(tmp_path):
    storage = StorageManager(tmp_path / "tasks")
    svc = ObjectStorageService(FilesystemBackend(tmp_path / "objects"), storage=storage)
    svc.start()
    yield svc
    svc.stop()


def test_object_service_http_roundtrip(service):
    client = DfstoreClient(f"http://{service.host}:{service.port}")
    client.create_bucket("blobs")
    assert [b["name"] for b in client.list_buckets()] == ["blobs"]
    payload = bytes(range(256)) * 100
    client.put_object("blobs", "dir/a.bin", payload)
    assert client.get_object("blobs", "dir/a.bin") == payload
    assert client.is_object_exist("blobs", "dir/a.bin")
    assert not client.is_object_exist("blobs", "nope")
    client.copy_object("blobs", "dir/a.bin", "dir/b.bin")
    keys = [m["key"] for m in client.object_metadatas("blobs", prefix="dir/")]
    assert keys == ["dir/a.bin", "dir/b.bin"]
    client.delete_object("blobs", "dir/a.bin")
    with pytest.raises(dferrors.NotFound):
        client.get_object("blobs", "dir/a.bin")


def test_put_imports_into_p2p_task_storage(service):
    """PUT seeds the object into task storage so peers can pull pieces
    (the reference's import-to-seed-peer modes)."""
    client = DfstoreClient(f"http://{service.host}:{service.port}")
    client.create_bucket("b")
    client.put_object("b", "k.bin", b"shared-bytes")
    ts = service.storage.find_completed_task(object_task_id("b", "k.bin"))
    assert ts is not None and ts.meta.done
    assert ts.read_range(0, 12) == b"shared-bytes"
    # backend miss falls back to the P2P cache
    service.backend.delete_object("b", "k.bin")
    assert client.get_object("b", "k.bin") == b"shared-bytes"


def test_dfstore_cli_remote(service, tmp_path, capsys):
    from dragonfly2_tpu.client.cli import main

    client = DfstoreClient(f"http://{service.host}:{service.port}")
    client.create_bucket("cli")
    src = tmp_path / "upload.bin"
    src.write_bytes(b"cli-payload")
    endpoint = f"http://{service.host}:{service.port}"
    assert main(["dfstore", "put", "--endpoint", endpoint, "--bucket", "cli",
                 "--key", "x.bin", "--path", str(src)]) == 0
    assert main(["dfstore", "get", "--endpoint", endpoint, "--bucket", "cli",
                 "--key", "x.bin"]) == 0
    out = capsys.readouterr().out
    assert "cli-payload" in out
    assert main(["dfstore", "get", "--endpoint", endpoint, "--bucket", "cli",
                 "--key", "missing"]) == 1


def test_daemon_object_storage_listener(tmp_path):
    import asyncio

    from dragonfly2_tpu.client.daemon import Daemon
    from dragonfly2_tpu.cluster.scheduler import SchedulerService
    from dragonfly2_tpu.config.config import Config
    from dragonfly2_tpu.rpc.server import SchedulerRPCServer

    async def run():
        cfg = Config()
        cfg.scheduler.max_hosts = 8
        cfg.scheduler.max_tasks = 8
        server = SchedulerRPCServer(SchedulerService(config=cfg), tick_interval=0.01)
        host, port = await server.start()
        daemon = Daemon(tmp_path / "d", [(host, port)], hostname="obj-host", object_storage=True)
        await daemon.start()
        try:
            assert daemon.object_storage is not None
            client = DfstoreClient(
                f"http://{daemon.object_storage.host}:{daemon.object_storage.port}"
            )
            client.create_bucket("x")
            client.put_object("x", "y", b"z")
            assert client.get_object("x", "y") == b"z"
        finally:
            await daemon.stop()
            await server.stop()

    asyncio.run(run())


def test_fs_backend_sibling_bucket_prefix_escape(tmp_path):
    """Keys must not traverse into sibling buckets sharing a name prefix
    (string-prefix path checks are not containment checks)."""
    be = FilesystemBackend(tmp_path)
    be.create_bucket("a")
    be.create_bucket("ab")
    be.put_object("ab", "secret", b"private")
    with pytest.raises(dferrors.InvalidArgument):
        be.get_object("a", "../ab/secret")
    with pytest.raises(dferrors.InvalidArgument):
        be.put_object("a", "../ab/planted", b"x")
