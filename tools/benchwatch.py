#!/usr/bin/env python
"""benchwatch — one schema + a longitudinal registry for every bench
artifact.

The repo accumulates one BENCH artifact per growth round in four shapes
(the driver-captured bench.py tail records r01..r05, the bench_loop
BENCH_rXX format, BENCH_mega, BENCH_scenarios) and nothing ever read
them TOGETHER: a perf regression between rounds was only caught if a
human diffed JSON by hand. benchwatch gives them one registry:

- every checked-in ``BENCH_*.json`` validates against a per-kind schema
  (legacy shapes stay legal; new artifacts carry ``schema_version`` via
  tools/bench_schema.py);
- all artifacts normalize into ONE trajectory — per-artifact entries of
  ``{round, kind, platform fingerprint, flat metrics}`` — written to
  ``BENCH_trajectory.json`` (``--write``);
- ``--check`` is the tier-1/CI gate (tools/lint_all.py runs it): any
  unparseable/invalid artifact fails, and any metric that regressed
  beyond ``--threshold`` (default 10%) between ADJACENT comparable
  rounds fails.

Comparability is deliberately strict — a flagged regression must mean
"same benchmark, same platform, got worse", never "we moved rigs":

- only entries of the same kind AND the same platform fingerprint
  compare (fingerprint = machine + device class + jax version for
  artifacts with a platform block; the measurement method for the
  driver records, which predate the block);
- only STRICTLY adjacent rounds compare (rN vs rN-1) — a corrupt or
  missing intermediate round breaks the chain instead of silently
  comparing across it;
- physically invalid values are quarantined from comparison, not from
  the record: an MFU above 100% (the BENCH_r03 block_until_ready
  artifact corruption) or a latency equal to the bench's own clamp
  floor (a bound, not a measurement — bench.py VERDICT r3 weak #2)
  stays visible in the trajectory but anchors no regression verdict.

Usage:
    python tools/benchwatch.py --check [--root DIR] [--threshold 0.1]
    python tools/benchwatch.py --write [--root DIR]
    python tools/benchwatch.py [files...]        # normalize + print
"""

from __future__ import annotations

import argparse
import json
import re
import sys
from pathlib import Path

TRAJECTORY_SCHEMA_VERSION = 1
TRAJECTORY_FILE = "BENCH_trajectory.json"
DEFAULT_THRESHOLD = 0.10

# metrics where smaller is better; everything else is higher-better.
# Suffix rules cover the families (latencies, fractions); exact names
# pin the ambiguous ones. `_regret_fail_rate` precedes the `_fraction`-
# style reasoning: regret is the active arm's outcome delta vs the
# shadow pick, and less of it is better.
_LOWER_BETTER_SUFFIXES = (
    "_ms", "_s", "_fraction", "_regret_fail_rate",
    # SLO verdict plane (telemetry/slo.py): alerts fired and error-budget
    # burn are failure accounting — less is strictly better
    "_pages_fired", "_tickets_fired", "_alerts_fired", "_budget_burn",
    # process planet (procworld): lost downloads break THE invariant and
    # stop escalations mean graceful shutdown blew its grace window —
    # both strictly lower-better
    "_lost_downloads", "_escalations",
)
_LOWER_BETTER_EXACT = {
    "control_dispatch", "device_call", "candidate_fill", "apply_selection",
    "report_ingest", "pack", "pre_schedule", "link_rtt_probe",
    "shadow_score",
    # fused-tick phase split (ISSUE 19): fused_device_call is the fused
    # program's dispatch+d2h aggregate — a NEW key, never compared
    # against the pre-fused trivial-transport device_call (adjacent
    # rounds only share keys they both carry)
    "legality_recheck", "emit", "fused_dispatch", "d2h_wait",
    "fused_device_call",
}

# Metrics with NO monotonic better-direction — excluded from regression
# comparison entirely (normalizers drop them): ratio-to-ideal numbers
# (perfect = 1.0) and the decision-ledger divergence family (top-1
# disagreement / rank correlation measure WHERE the arms differ, not
# which is right — the directional verdict is the regret metric).
_NO_DIRECTION_SUFFIXES = (
    "_model_vs_measured", "_disagreement", "_divergence", "_rank_corr",
    # verdict states are categories (0=ok/1=degraded/2=critical), not a
    # magnitude — the directional cells are the alert/budget ones above
    "_verdict_state",
    # tail plane (telemetry/tailtrace.py): a phase's share of attributed
    # time is a composition (shifting time between phases moves it with
    # no better direction), and the decomposition ratio is a
    # consistency audit (perfect = 1.0) — the directional tail cell is
    # tail_ttc_p99_ms, which _ms already pins lower-better
    "_phase_share", "_decomp_ratio",
    # fleet plane (megascale/fleet.py): handoff counts scale with how
    # much chaos the scenario injected and how the ring cut fell — more
    # handoffs is neither regression nor improvement (the directional
    # fleet cell is aggregate pieces/s, higher-better by default)
    "_handoffs",
    # process planet (procworld): kill and restart counts scale with how
    # much chaos the harness injected (the scenario's crash epochs and
    # upgrade waves), not with how well the planet handled it — the
    # directional proc cells are lost_downloads/escalations above
    "_restarts", "_kills",
)


# Per-tick cells are SEAM-SCOPED: when the tick's program shape changes
# (the artifact's tick record carries a `phase_seam` — "fused" moved
# fill/gather/score/top-k into one device program), per-tick wall and
# per-phase cells measure a DIFFERENT program, so a cross-seam
# comparison is "we moved rigs", not "same benchmark got worse" — the
# fused_device_call-vs-device_call new-key argument, applied to every
# cell the seam redefines. Seam-scoped cells normalize under a
# `<seam>_` prefix and re-enter the gate as a new series from their
# first seam round. Deliberately NOT seam-scoped: `control_dispatch`
# ("all host-side work per tick" — the seam preserves that meaning by
# construction), `link_rtt_probe` (bare transport, no program inside),
# and every loop-level cell (pieces/s, ml/decision/ab families).
_SEAM_SCOPED = {
    "tick_p50_ms", "candidate_fill", "apply_selection", "report_ingest",
    "legality_recheck", "pack", "emit", "dispatch", "d2h_wait",
    "device_call", "feature_gather", "shadow_score", "pre_schedule",
    "overlap",
}
_KNOWN_SEAMS = ("fused", "packed")

# Phase timers at these batch sizes jitter by tens of microseconds run
# to run; a relative threshold alone flags 1 us -> 2 us as +100%. A
# lower-better ms-scale cell must regress by at least this much in
# ABSOLUTE terms before it anchors a verdict.
NOISE_FLOOR_MS = 0.05


def _seam_stripped(metric: str) -> str:
    head, _, rest = metric.partition("_")
    return rest if head in _KNOWN_SEAMS and rest else metric


def direction_exempt(metric: str) -> bool:
    return metric.endswith(_NO_DIRECTION_SUFFIXES)


def lower_is_better(metric: str) -> bool:
    return (
        metric in _LOWER_BETTER_EXACT
        or metric.endswith(_LOWER_BETTER_SUFFIXES)
        # seam-scoped per-tick cells keep their direction under the prefix
        or _seam_stripped(metric) in _LOWER_BETTER_EXACT
    )


def _ms_scale(metric: str) -> bool:
    """Cells measured in milliseconds (the phase/latency families) —
    the only cells the absolute noise floor applies to."""
    stripped = _seam_stripped(metric)
    return (
        metric.endswith("_ms")
        or stripped.endswith("_ms")
        or stripped in _LOWER_BETTER_EXACT
    )


# ------------------------------------------------------------ validation


class SchemaError(Exception):
    pass


def _require(doc: dict, key: str, types, where: str) -> None:
    if key not in doc:
        raise SchemaError(f"{where}: missing required key {key!r}")
    if types is not None and not isinstance(doc[key], types):
        raise SchemaError(
            f"{where}: {key!r} must be {types}, got {type(doc[key]).__name__}"
        )


def detect_kind(doc: dict, name: str) -> str:
    """driver | bench | loop | mega | proc | scenarios — by structural
    signature. `bench` is `python bench.py --artifact` (the schema-v2
    successor of the driver-captured tail records: the same parsed
    record, under `record`, plus the shared platform block). `proc` is
    tools/dfproc.py (a mega-shaped run plus the sim-vs-real divergence
    report), so its check must precede the `runs` -> mega one."""
    if not isinstance(doc, dict):
        raise SchemaError(f"{name}: artifact must be a JSON object")
    keys = set(doc)
    if {"cmd", "rc", "tail"} <= keys:
        return "driver"
    if "record" in keys:
        return "bench"
    if "divergence" in keys:
        return "proc"
    if "runs" in keys:
        return "mega"
    if "results" in keys:
        return "loop"
    if "scenarios" in keys:
        return "scenarios"
    raise SchemaError(f"{name}: unrecognized artifact shape (keys={sorted(keys)})")


def validate(doc: dict, kind: str, name: str) -> None:
    """Raise SchemaError on the first contract violation."""
    if kind == "driver":
        _require(doc, "cmd", str, name)
        _require(doc, "rc", int, name)
        _require(doc, "tail", str, name)
        parsed = doc.get("parsed")
        if parsed is not None:
            if not isinstance(parsed, dict):
                raise SchemaError(f"{name}: parsed must be an object or null")
            _require(parsed, "metric", str, f"{name}.parsed")
            _require(parsed, "value", (int, float), f"{name}.parsed")
        return
    if kind in ("bench", "loop", "mega", "proc"):
        _require(doc, "cmd", str, name)
        _require(doc, "platform", dict, name)
        _require(doc["platform"], "jax", str, f"{name}.platform")
        _require(doc["platform"], "devices", list, f"{name}.platform")
        _require(doc["platform"], "machine", str, f"{name}.platform")
        _require(doc, "summary", dict, name)
        if kind == "bench":
            _require(doc, "record", dict, name)
            _require(doc["record"], "metric", str, f"{name}.record")
            _require(doc["record"], "value", (int, float), f"{name}.record")
            return
        if kind == "loop":
            _require(doc, "results", list, name)
            for i, leg in enumerate(doc["results"]):
                if not isinstance(leg, dict):
                    raise SchemaError(f"{name}.results[{i}]: must be an object")
                _require(leg, "metric", str, f"{name}.results[{i}]")
        else:
            _require(doc, "runs", list, name)
            for i, run in enumerate(doc["runs"]):
                where = f"{name}.runs[{i}]"
                for key, types in (("scenario", str), ("hosts", int),
                                   ("stats", dict), ("timing", dict)):
                    _require(run, key, types, where)
        if kind == "proc":
            # the divergence report's contract: every comparison carries
            # its band AND the argument for the band — a band whose
            # provenance is lost cannot be audited
            _require(doc, "divergence", dict, name)
            div = doc["divergence"]
            _require(div, "metrics", dict, f"{name}.divergence")
            _require(div, "all_within", bool, f"{name}.divergence")
            for mname, entry in div["metrics"].items():
                where = f"{name}.divergence.metrics[{mname}]"
                if not isinstance(entry, dict):
                    raise SchemaError(f"{where}: must be an object")
                _require(entry, "band", list, where)
                _require(entry, "within", bool, where)
                _require(entry, "argument", str, where)
        return
    if kind == "scenarios":
        _require(doc, "scenarios", dict, name)
        for sname, s in doc["scenarios"].items():
            if not isinstance(s, dict):
                raise SchemaError(f"{name}.scenarios[{sname}]: must be an object")
        return
    raise SchemaError(f"{name}: unknown kind {kind!r}")


# ---------------------------------------------------------- normalization


_ROUND_RE = re.compile(r"BENCH_r0*(\d+)\.json$")


def _round_of(name: str) -> int | None:
    m = _ROUND_RE.search(name)
    return int(m.group(1)) if m else None


def _device_class(device: str) -> str:
    # "TFRT_CPU_0" -> "TFRT_CPU"; "axon:0" stays itself
    return re.sub(r"_\d+$", "", device)


def _fingerprint(doc: dict, kind: str) -> str:
    platform = doc.get("platform")
    if isinstance(platform, dict):
        devices = platform.get("devices") or ["?"]
        return "|".join((
            kind, platform.get("machine", "?"),
            _device_class(str(devices[0])), platform.get("jax", "?"),
        ))
    if kind == "driver":
        parsed = doc.get("parsed") or {}
        return f"driver|{parsed.get('method', 'unparsed')}"
    return f"{kind}|legacy"


def _put(metrics: dict, quarantined: dict, key: str, value,
         invalid_reason: str | None = None) -> None:
    if not isinstance(value, (int, float)) or isinstance(value, bool):
        return
    if invalid_reason:
        quarantined[key] = {"value": float(value), "reason": invalid_reason}
    else:
        metrics[key] = float(value)


def _normalize_driver(doc: dict, metrics: dict, quarantined: dict) -> None:
    parsed = doc.get("parsed") or {}
    value = parsed.get("value")
    clamped = (
        isinstance(value, (int, float)) and value <= 0.01
        and parsed.get("method") == "pipelined_steady_state"
    )
    _put(metrics, quarantined, "headline_p50_ms", value,
         "equals the 10us clamp floor — a bound, not a measurement"
         if clamped else None)
    trainer = parsed.get("trainer") or {}
    flat = {**trainer, **{k: v for k, v in parsed.items() if k != "trainer"}}
    for key in ("gnn_mfu_pct", "gnn_vs_cpu_torch", "attention_fwd_mfu_pct",
                "attention_mfu_pct", "loop_pieces_per_sec",
                "loop_tick_p50_ms", "recall", "ab_ml_vs_default_cost"):
        v = flat.get(key)
        invalid = None
        if key.endswith("mfu_pct") and isinstance(v, (int, float)) and v > 100:
            invalid = "MFU above 100% is physically impossible (corrupt timing)"
        _put(metrics, quarantined, key, v, invalid)


def _normalize_bench(doc: dict, metrics: dict, quarantined: dict) -> None:
    # same record shape the driver tail parses — reuse its extraction
    # (incl. the clamp-floor / >100%-MFU quarantine rules)
    _normalize_driver({"parsed": doc.get("record")}, metrics, quarantined)


def _loop_seam(doc: dict) -> str | None:
    """The tick record's phase_seam, if the artifact carries one (the
    pre-seam artifacts r01..r06 don't — their cells keep their
    historical unprefixed names, anchoring the pre-seam series)."""
    for rec in doc.get("results") or []:
        if isinstance(rec, dict) and rec.get("phase_seam"):
            return str(rec["phase_seam"])
    return None


def _normalize_loop(doc: dict, metrics: dict, quarantined: dict) -> None:
    seam = _loop_seam(doc)
    for key, v in (doc.get("summary") or {}).items():
        if key in ("metric", "control_under_device"):
            continue
        if direction_exempt(key):
            # no monotonic better-direction (ratio-to-ideal numbers,
            # divergence/disagreement rates); drift is caught by the
            # bench's own assertions, not the trajectory gate
            continue
        if seam and key in _SEAM_SCOPED:
            # per-tick cells measure the seam's program — new series
            key = f"{seam}_{key}"
        _put(metrics, quarantined, key, v)


def _normalize_mega(doc: dict, metrics: dict, quarantined: dict) -> None:
    for cell, s in (doc.get("summary") or {}).items():
        if not isinstance(s, dict):
            continue
        _put(metrics, quarantined, f"{cell}_pieces_per_sec",
             s.get("pieces_per_sec"))
        _put(metrics, quarantined, f"{cell}_origin_traffic_fraction",
             s.get("origin_traffic_fraction"))
        _put(metrics, quarantined, f"{cell}_completed", s.get("completed"))
        # decision-ledger cells: regret compares directionally (lower is
        # better); the disagreement rate is direction-exempt and skipped
        _put(metrics, quarantined, f"{cell}_decision_regret_fail_rate",
             s.get("decision_regret_fail_rate"))
        # SLO cells: alert counts + budget burn compare lower-is-better;
        # the categorical verdict state is direction-exempt and skipped
        # tail cells: p99 TTC compares lower-is-better (_ms); the
        # decomposition-ratio audit and phase shares are direction-exempt
        for key in ("slo_pages_fired", "slo_tickets_fired",
                    "slo_alerts_fired", "slo_budget_burn",
                    "slo_verdict_state", "tail_ttc_p99_ms",
                    "tail_decomp_ratio", "tail_failover_phase_share",
                    # fleet cells (megascale/fleet.py): aggregate
                    # pieces/s against the modeled parallel wall is the
                    # 1-vs-K scaling number (higher-better by default);
                    # handoff counts are direction-exempt and skipped
                    "aggregate_pieces_per_sec", "fleet_handoffs"):
            metric = f"{cell}_{key}"
            if direction_exempt(metric):
                continue
            _put(metrics, quarantined, metric, s.get(key))


def _normalize_proc(doc: dict, metrics: dict, quarantined: dict) -> None:
    # the directional proc cells: lost_downloads/escalations/pages_fired
    # lower-better (suffix tables), completed/downloads_per_sec higher-
    # better by default; kills/restarts are chaos dosage (direction-
    # exempt) and the divergence ratios are ratio-to-ideal comparisons
    # gated by the artifact's own all_within flag plus the replay test —
    # neither family enters the trajectory comparison
    summary = doc.get("summary") or {}
    for key in ("completed", "lost_downloads", "kills", "restarts",
                "escalations", "pages_fired"):
        metric = f"proc_{key}"
        if direction_exempt(metric):
            continue
        _put(metrics, quarantined, metric, summary.get(key))
    runs = doc.get("runs") or []
    if runs and isinstance(runs[0], dict):
        timing = runs[0].get("timing") or {}
        _put(metrics, quarantined, "proc_downloads_per_sec",
             timing.get("downloads_per_sec"))


def _normalize_scenarios(doc: dict, metrics: dict, quarantined: dict) -> None:
    for sname, s in (doc.get("scenarios") or {}).items():
        ratio = (s.get("ml_vs_default") or {}).get("mean")
        _put(metrics, quarantined, f"{sname}_ml_vs_default", ratio)
    model = doc.get("model") or {}
    _put(metrics, quarantined, "model_recall", model.get("recall"))
    _put(metrics, quarantined, "model_f1", model.get("f1"))


def normalize(doc: dict, kind: str, name: str) -> dict:
    """One trajectory entry: flat comparable metrics + provenance."""
    metrics: dict = {}
    quarantined: dict = {}
    {
        "driver": _normalize_driver,
        "bench": _normalize_bench,
        "loop": _normalize_loop,
        "mega": _normalize_mega,
        "proc": _normalize_proc,
        "scenarios": _normalize_scenarios,
    }[kind](doc, metrics, quarantined)
    return {
        "source": name,
        "kind": kind,
        "round": _round_of(name),
        "fingerprint": _fingerprint(doc, kind),
        "schema_version": doc.get("schema_version"),
        "metrics": metrics,
        "quarantined_metrics": quarantined,
    }


# ------------------------------------------------------------ regression


def find_regressions(entries: list[dict],
                     threshold: float = DEFAULT_THRESHOLD) -> list[dict]:
    """Metric regressions between ADJACENT comparable rounds.

    Two entries compare only when kind AND fingerprint match and their
    rounds are strictly consecutive integers; each shared, unquarantined
    metric is then checked directionally (lower_is_better) against the
    threshold."""
    by_series: dict[tuple[str, str], list[dict]] = {}
    for e in entries:
        if e["round"] is None:
            continue
        by_series.setdefault((e["kind"], e["fingerprint"]), []).append(e)
    out: list[dict] = []
    for series in by_series.values():
        series.sort(key=lambda e: e["round"])
        for prev, curr in zip(series, series[1:]):
            if curr["round"] != prev["round"] + 1:
                continue  # a broken chain never compares across the gap
            for metric in sorted(set(prev["metrics"]) & set(curr["metrics"])):
                a, b = prev["metrics"][metric], curr["metrics"][metric]
                if a == 0:
                    continue
                change = (b - a) / abs(a)
                worse = change > threshold if lower_is_better(metric) \
                    else change < -threshold
                if (
                    worse
                    and lower_is_better(metric)
                    and _ms_scale(metric)
                    and (b - a) < NOISE_FLOOR_MS
                ):
                    continue  # sub-floor absolute delta: timer noise
                if worse:
                    out.append({
                        "metric": metric,
                        "from": {"source": prev["source"], "value": a},
                        "to": {"source": curr["source"], "value": b},
                        "change_pct": round(100.0 * change, 2),
                        "direction": "lower_is_better"
                        if lower_is_better(metric) else "higher_is_better",
                    })
    return out


# --------------------------------------------------------------- registry


def artifact_files(root: Path) -> list[Path]:
    return sorted(
        p for p in root.glob("BENCH_*.json") if p.name != TRAJECTORY_FILE
    )


def load_entries(files: list[Path]) -> tuple[list[dict], list[str]]:
    entries, errors = [], []
    for path in files:
        try:
            doc = json.loads(path.read_text())
            kind = detect_kind(doc, path.name)
            validate(doc, kind, path.name)
            entries.append(normalize(doc, kind, path.name))
        except (json.JSONDecodeError, SchemaError) as e:
            errors.append(f"{path.name}: {e}")
    return entries, errors


def trajectory_body(entries: list[dict]) -> dict:
    return {
        "schema_version": TRAJECTORY_SCHEMA_VERSION,
        "entries": sorted(
            entries,
            key=lambda e: (e["kind"], e["round"] if e["round"] is not None
                           else 1 << 30, e["source"]),
        ),
    }


def write_trajectory(root: Path, entries: list[dict]) -> Path:
    path = root / TRAJECTORY_FILE
    path.write_text(json.dumps(trajectory_body(entries), indent=1) + "\n")
    return path


def validate_trajectory_file(root: Path) -> list[str]:
    path = root / TRAJECTORY_FILE
    if not path.exists():
        return []
    try:
        doc = json.loads(path.read_text())
    except json.JSONDecodeError as e:
        return [f"{TRAJECTORY_FILE}: {e}"]
    errors = []
    if doc.get("schema_version") != TRAJECTORY_SCHEMA_VERSION:
        errors.append(
            f"{TRAJECTORY_FILE}: schema_version must be "
            f"{TRAJECTORY_SCHEMA_VERSION}"
        )
    if not isinstance(doc.get("entries"), list):
        errors.append(f"{TRAJECTORY_FILE}: entries must be a list")
    else:
        for i, e in enumerate(doc["entries"]):
            for key in ("source", "kind", "fingerprint", "metrics"):
                if key not in e:
                    errors.append(
                        f"{TRAJECTORY_FILE}: entries[{i}] missing {key!r}"
                    )
                    break
    return errors


def check(root: Path, threshold: float = DEFAULT_THRESHOLD,
          out=sys.stdout) -> int:
    """The gate: schema-validate every artifact, validate the checked-in
    trajectory, flag adjacent-round regressions. Exit code 0/1."""
    files = artifact_files(root)
    entries, errors = load_entries(files)
    errors.extend(validate_trajectory_file(root))
    regressions = find_regressions(entries, threshold)
    for err in errors:
        print(f"benchwatch: SCHEMA {err}", file=out)
    for r in regressions:
        print(
            f"benchwatch: REGRESSION {r['metric']} "
            f"{r['from']['value']} -> {r['to']['value']} "
            f"({r['change_pct']:+.1f}%, {r['direction']}) "
            f"[{r['from']['source']} -> {r['to']['source']}]",
            file=out,
        )
    ok = not errors and not regressions
    print(
        f"benchwatch: {len(files)} artifacts, {len(entries)} parsed, "
        f"{len(errors)} schema errors, {len(regressions)} regressions "
        f"(threshold {threshold:.0%}) -> {'OK' if ok else 'FAILED'}",
        file=out,
    )
    return 0 if ok else 1


def main(argv: list[str] | None = None) -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--root", default=".",
                    help="directory holding BENCH_*.json (default: cwd)")
    ap.add_argument("--check", action="store_true",
                    help="schema + regression gate (exit 1 on failure)")
    ap.add_argument("--write", action="store_true",
                    help=f"(re)write {TRAJECTORY_FILE} from the artifacts")
    ap.add_argument("--threshold", type=float, default=DEFAULT_THRESHOLD,
                    help="regression threshold as a fraction (default 0.10)")
    ap.add_argument("files", nargs="*",
                    help="normalize just these artifacts and print entries")
    args = ap.parse_args(argv)
    root = Path(args.root)

    if args.files:
        entries, errors = load_entries([Path(f) for f in args.files])
        print(json.dumps({"entries": entries, "errors": errors}, indent=1))
        return 1 if errors else 0
    if args.check:
        return check(root, args.threshold)
    entries, errors = load_entries(artifact_files(root))
    if args.write:
        path = write_trajectory(root, entries)
        print(f"benchwatch: wrote {path} ({len(entries)} entries)")
        for err in errors:
            print(f"benchwatch: SCHEMA {err}")
        return 1 if errors else 0
    print(json.dumps(trajectory_body(entries), indent=1))
    return 1 if errors else 0


if __name__ == "__main__":
    sys.exit(main())
