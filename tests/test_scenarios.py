"""Scenario lab: spec codecs, deterministic engine, simulator injection,
and the scenario-matrix A/B harness's determinism contract (same seed +
spec => identical injected fault schedule and identical A/B summary —
no wall-clock nondeterminism may leak into results)."""

import copy

import pytest

from dragonfly2_tpu.cluster.scheduler import SchedulerService
from dragonfly2_tpu.cluster.simulator import ClusterSimulator
from dragonfly2_tpu.config.config import Config
from dragonfly2_tpu.scenarios import (
    ScenarioEngine,
    ScenarioSpec,
    builtin_scenarios,
    load_scenario,
)
from dragonfly2_tpu.scenarios.ab import (
    MatrixConfig,
    _ratio_stats,
    deterministic_view,
    run_matrix,
)
from dragonfly2_tpu.scenarios.spec import ChurnSpec, FlakySpec, LinkSpec, SkewSpec


# ---------------------------------------------------------------- spec


def test_spec_dict_roundtrip():
    spec = ScenarioSpec(
        name="x",
        link=LinkSpec(slow_fraction=0.3, slow_nic_count=2),
        churn=ChurnSpec(peer_crash_rate=0.1),
        flaky=FlakySpec(parent_fraction=0.2, piece_error_rate=0.4),
        skew=SkewSpec(zipf_alpha=1.1),
    )
    again = ScenarioSpec.from_dict(spec.to_dict())
    assert again == spec
    with pytest.raises(ValueError):
        ScenarioSpec.from_dict({"nonsense": 1})
    with pytest.raises(ValueError):
        ScenarioSpec.from_dict({"link": {"bad_knob": 1}})


def test_spec_loads_toml_and_json(tmp_path):
    toml = tmp_path / "s.toml"
    toml.write_text(
        'name = "skewed"\n'
        'description = "test"\n'
        "[link]\n"
        "slow_fraction = 0.4\n"
        "slow_nic_count = 1\n"
        "[skew]\n"
        "zipf_alpha = 1.2\n"
    )
    spec = load_scenario(toml)
    assert spec.name == "skewed"
    assert spec.link.slow_fraction == 0.4
    assert spec.link.slow_nic_count == 1
    assert spec.skew.zipf_alpha == 1.2

    js = tmp_path / "s.json"
    js.write_text(spec.dumps())
    assert load_scenario(js) == spec


def _all_builtin_specs():
    from dragonfly2_tpu.scenarios import megascale_scenarios

    return {**builtin_scenarios(), **megascale_scenarios()}


def test_toml_roundtrip_every_builtin():
    """to_toml → the hand-rolled fallback parser → from_dict reproduces
    every builtin (incl. megascale) exactly — the fallback grammar covers
    the whole spec surface, WAN/traffic sections included."""
    from dragonfly2_tpu.scenarios.spec import _parse_toml_fallback

    for name, spec in _all_builtin_specs().items():
        parsed = ScenarioSpec.from_dict(_parse_toml_fallback(spec.to_toml()))
        assert parsed == spec, f"fallback TOML round-trip broke {name!r}"


def test_tomllib_and_fallback_agree_on_every_builtin():
    """Satellite contract: stdlib tomllib (py3.11+, the primary parser)
    and the <3.11 fallback read every builtin scenario identically —
    values AND types. Skips where tomllib does not exist (the fallback
    is then the only parser, covered by the round-trip test above)."""
    tomllib = pytest.importorskip("tomllib")
    from dragonfly2_tpu.scenarios.spec import _parse_toml_fallback

    def typed(d):
        return {
            k: typed(v) if isinstance(v, dict) else (type(v).__name__, v)
            for k, v in d.items()
        }

    for name, spec in _all_builtin_specs().items():
        text = spec.to_toml()
        assert typed(tomllib.loads(text)) == typed(_parse_toml_fallback(text)), (
            f"parser disagreement on builtin {name!r}"
        )


def test_megascale_builtins_default_disabled_elsewhere():
    """Pre-existing builtins carry the megascale extensions DISABLED —
    the oracle's replays are bit-unchanged by the new spec fields."""
    for name, spec in builtin_scenarios().items():
        assert spec.wan.regions == 0, name
        assert spec.traffic.day_rounds == 0, name
        assert spec.flash.events_per_day == 0, name
        assert spec.upgrade.waves_per_day == 0, name
    from dragonfly2_tpu.scenarios import megascale_scenarios

    soak = megascale_scenarios()["soak"]
    assert soak.wan.regions > 0 and soak.traffic.day_rounds > 0


def test_builtin_scenarios_cover_required_grid():
    names = set(builtin_scenarios())
    assert {"homogeneous", "bandwidth_skew", "churn", "flaky_parent"} <= names
    control = builtin_scenarios()["homogeneous"]
    assert control.flaky.piece_error_rate == 0
    assert control.churn.peer_crash_rate == 0
    assert control.link.slow_fraction == 0


# -------------------------------------------------------------- engine


def _hosts(n=32, seed=0):
    from dragonfly2_tpu.records import synth

    return synth.make_cluster(n, seed=seed).hosts


def test_engine_assignments_deterministic_and_order_free():
    spec = builtin_scenarios()["bandwidth_skew"]
    hosts = _hosts()
    a = ScenarioEngine(spec, hosts, seed=1)
    b = ScenarioEngine(spec, list(reversed(hosts)), seed=1)  # order must not matter
    assert a.bandwidth == b.bandwidth
    assert a.flaky_hosts == b.flaky_hosts
    # the bimodal split and the slow NICs actually exist
    slow = [h for h in hosts if a.bandwidth[h.id] < spec.link.base_bandwidth_bps]
    assert slow
    worst = min(a.bandwidth.values())
    assert worst <= spec.link.base_bandwidth_bps * spec.link.slow_nic_multiplier * 1.001
    # a different seed re-rolls the assignment
    c = ScenarioEngine(spec, hosts, seed=2)
    assert c.bandwidth != a.bandwidth


def test_engine_rtt_structure_and_spine_penalty():
    spec = builtin_scenarios()["bandwidth_skew"]
    hosts = _hosts(64)
    eng = ScenarioEngine(spec, hosts, seed=0)
    cross = None
    for h in hosts[1:]:
        # the tier check mirrors records/synth.rtt_ns: idc first, then
        # region — a truly cross-region pair must differ in BOTH
        if (
            eng._region[h.id] != eng._region[hosts[0].id]
            and eng._idc[h.id] != eng._idc[hosts[0].id]
            and cross is None
        ):
            cross = h
    if cross is not None:
        assert eng.rtt_ns(hosts[0], cross, key=(1,)) > 5_000_000  # ≥ regional band
        # spine oversubscription divides cross-rack bandwidth
        bw_cross = eng.pair_bandwidth(hosts[0], cross)
        assert bw_cross <= eng.bandwidth[cross.id] / spec.link.spine_oversubscription + 1
    # rtt is deterministic per key and varies across keys (jitter)
    r1 = eng.rtt_ns(hosts[0], hosts[1], key=(7,))
    assert r1 == eng.rtt_ns(hosts[0], hosts[1], key=(7,))
    assert r1 != eng.rtt_ns(hosts[0], hosts[1], key=(8,))


def test_engine_zipf_weights_and_crash_points():
    eng = ScenarioEngine(builtin_scenarios()["hotspot"], _hosts(8), seed=0)
    w = eng.task_weights(10)
    assert w is not None and len(w) == 10
    assert w[0] > w[1] > w[-1] and abs(sum(w) - 1.0) < 1e-9
    assert ScenarioEngine(ScenarioSpec(), _hosts(8), seed=0).task_weights(10) is None

    churn_eng = ScenarioEngine(builtin_scenarios()["churn"], _hosts(8), seed=0)
    points = [churn_eng.crash_point(i, 20) for i in range(200)]
    crashes = [p for p in points if p is not None]
    assert crashes and all(1 <= p <= 20 for p in crashes)
    # ~15% rate with deterministic keying: identical on a second pass
    again = ScenarioEngine(builtin_scenarios()["churn"], _hosts(8), seed=0)
    assert [again.crash_point(i, 20) for i in range(200)] == points


# ----------------------------------------------------------- simulator


def _small_service():
    cfg = Config()
    cfg.scheduler.max_hosts = 256
    cfg.scheduler.max_tasks = 64
    return SchedulerService(config=cfg)


def _drive(sim, pieces=300, rounds_cap=300):
    rounds = 0
    while sim.stats.pieces < pieces and rounds < rounds_cap:
        sim.run_round(8)
        rounds += 1
    return sim.stats


def test_simulator_scenarios_inject_expected_event_classes():
    flaky = _drive(ClusterSimulator(
        _small_service(), num_hosts=48, num_tasks=8, seed=3,
        scenario=builtin_scenarios()["flaky_parent"],
    ))
    assert flaky.injected_piece_failures > 0
    assert flaky.retry_waves > 0  # aborted waves actually retried

    churn = _drive(ClusterSimulator(
        _small_service(), num_hosts=48, num_tasks=8, seed=3,
        scenario=builtin_scenarios()["churn"],
    ))
    assert churn.injected_crashes > 0 or churn.injected_host_leaves > 0

    skewed = ClusterSimulator(
        _small_service(), num_hosts=48, num_tasks=8, seed=3,
        scenario=builtin_scenarios()["bandwidth_skew"],
    )
    control = ClusterSimulator(
        _small_service(), num_hosts=48, num_tasks=8, seed=3,
        scenario=builtin_scenarios()["homogeneous"],
    )
    s, c = _drive(skewed), _drive(control)
    # same seed => same arrivals; the skewed link model must cost more
    assert s.piece_cost_ns_total / max(s.pieces, 1) > \
        1.5 * c.piece_cost_ns_total / max(c.pieces, 1)


def test_simulator_without_scenario_keeps_legacy_path():
    sim = ClusterSimulator(_small_service(), num_hosts=32, num_tasks=4, seed=1)
    assert sim.engine is None
    stats = _drive(sim, pieces=100)
    assert stats.pieces >= 100
    assert stats.injected_piece_failures == 0
    assert stats.injected_crashes == 0


def test_probe_rtts_come_from_scenario_link_model():
    """Probe measurements must reflect the scenario's link structure so
    topology snapshots carry it into training data: the skewed scenario's
    cross-region RTT band is far above homogeneous same-rack floors."""
    cfg = Config()
    cfg.scheduler.max_hosts = 256
    cfg.scheduler.max_tasks = 64
    from dragonfly2_tpu.cluster.probes import ProbeStore

    probes = ProbeStore(max_pairs=4096, max_hosts=256)
    svc = SchedulerService(config=cfg, probes=probes)
    sim = ClusterSimulator(
        svc, num_hosts=32, num_tasks=4, seed=2,
        scenario=builtin_scenarios()["bandwidth_skew"],
    )
    sim.run_round(8)
    assert sim.run_probe_round(sources=8) > 0
    avgs = probes.average[: probes._next]
    assert (avgs > 0).any()
    # deterministic: same seed + spec reproduces the same measurements
    probes2 = ProbeStore(max_pairs=4096, max_hosts=256)
    svc2 = SchedulerService(config=cfg, probes=probes2)
    sim2 = ClusterSimulator(
        svc2, num_hosts=32, num_tasks=4, seed=2,
        scenario=builtin_scenarios()["bandwidth_skew"],
    )
    sim2.run_round(8)
    sim2.run_probe_round(sources=8)
    assert (probes2.average[: probes2._next] == avgs).all()


# -------------------------------------------------- determinism contract


def test_matrix_is_deterministic_and_digests_match():
    """Same (config, scenarios) => identical deterministic view AND
    identical injected-fault schedule digests. Two full runs."""
    cfg = MatrixConfig(
        hosts=48, tasks=6, target_pieces=300, downloads_per_round=8,
        seeds=(5,), evaluators=("default", "random"), probe_every=10,
    )
    scen = {
        k: v for k, v in builtin_scenarios().items()
        if k in ("flaky_parent", "churn")
    }
    r1 = run_matrix(copy.deepcopy(scen), cfg)
    r2 = run_matrix(copy.deepcopy(scen), cfg)
    assert deterministic_view(r1) == deterministic_view(r2)
    for name in scen:
        for ev in cfg.evaluators:
            d1 = r1["scenarios"][name]["arms"][ev]["seeds"]["5"]["schedule_digest"]
            d2 = r2["scenarios"][name]["arms"][ev]["seeds"]["5"]["schedule_digest"]
            assert d1 == d2
            # paired arms share the seed, so they see the SAME schedule
            # only when the evaluator doesn't change which transfers
            # happen — digests exist per arm, not per scenario
    # the faulty scenarios actually injected something
    flaky_arm = r1["scenarios"]["flaky_parent"]["arms"]["default"]["seeds"]["5"]
    assert flaky_arm["injected"]["piece_failures"] > 0
    # timing fields exist in the raw artifact but not the view
    assert "timing" in flaky_arm
    assert "timing" not in deterministic_view(flaky_arm)


def test_nt_arm_is_paired_and_probe_warm_seeds_the_store():
    """The nt arm must stay PAIRED with its siblings: attaching a probe
    store to every arm keeps the shared rng stream (and so the download
    arrival order) identical, and warm_from_link_model pre-seeds the nt
    arm's probe term from the scenario link model."""
    from dragonfly2_tpu.cluster.probes import ProbeStore, warm_from_link_model
    from dragonfly2_tpu.records import synth
    from dragonfly2_tpu.scenarios.engine import ScenarioEngine

    # direct warm: every source host gets pairs_per_src measurements
    hosts = synth.make_cluster(12, seed=0).hosts
    eng = ScenarioEngine(builtin_scenarios()["bandwidth_skew"], hosts, seed=1)
    store = ProbeStore(max_pairs=256, max_hosts=64)
    slotted = [(h, i) for i, h in enumerate(hosts)]
    n = warm_from_link_model(store, slotted, eng.rtt_ns, pairs_per_src=3)
    assert n == 12 * 3
    assert (store.average[: store._next] > 0).all()
    # deterministic: a second warm of a fresh store lands identical rows
    store2 = ProbeStore(max_pairs=256, max_hosts=64)
    warm_from_link_model(store2, slotted, eng.rtt_ns, pairs_per_src=3)
    assert (store2.average[: store2._next] == store.average[: store._next]).all()

    # matrix level: nt rides the grid; paired arms replay the SAME
    # arrivals (identical pieces per seed across evaluators)
    cfg = MatrixConfig(
        hosts=48, tasks=6, target_pieces=250, downloads_per_round=8,
        seeds=(5,), evaluators=("default", "nt"), probe_every=5,
    )
    r = run_matrix(
        {"bandwidth_skew": builtin_scenarios()["bandwidth_skew"]}, cfg
    )
    arms = r["scenarios"]["bandwidth_skew"]["arms"]
    assert "nt_vs_default" in r["scenarios"]["bandwidth_skew"]
    assert (
        arms["default"]["seeds"]["5"]["pieces"]
        == arms["nt"]["seeds"]["5"]["pieces"]
    )
    assert (
        arms["default"]["seeds"]["5"]["schedule_digest"]
        == arms["nt"]["seeds"]["5"]["schedule_digest"]
    )


def test_ratio_stats_ci():
    tied = _ratio_stats([1.0, 1.0, 1.0], [1.0, 1.0, 1.0])
    assert tied["mean"] == 1.0 and not tied["resolvable"]
    gap = _ratio_stats([2.0, 2.1, 1.9], [1.0, 1.0, 1.0])
    assert gap["resolvable"] and gap["ci95"][0] > 1.0
    single = _ratio_stats([1.5], [1.0])
    assert not single["resolvable"]
