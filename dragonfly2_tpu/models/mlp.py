"""MLP probe-RTT regressor — the model the reference's trainMLP stub was
meant to produce (trainer/training/training.go:92-98, fed by
TrainMlpRequest download/networktopology datasets, trainer/service/
service_v1.go:59-162).

Input: pairwise (src, dst) host features (records/features.topology_to_pairs,
NUM_PAIR_FEATURES columns). Output: predicted log1p(average RTT in ms).
bfloat16 matmuls on the MXU with float32 params and loss.
"""

from __future__ import annotations

import flax.linen as nn
import jax
import jax.numpy as jnp


class ProbeRTTRegressor(nn.Module):
    hidden_dim: int = 128
    num_layers: int = 3
    compute_dtype: jnp.dtype = jnp.bfloat16

    @nn.compact
    def __call__(self, x: jax.Array) -> jax.Array:
        x = x.astype(self.compute_dtype)
        for _ in range(self.num_layers - 1):
            x = nn.Dense(self.hidden_dim, dtype=self.compute_dtype)(x)
            x = nn.gelu(x)
        x = nn.Dense(1, dtype=self.compute_dtype)(x)
        return x[..., 0].astype(jnp.float32)


def mse_loss(model: ProbeRTTRegressor, params, x: jax.Array, y: jax.Array,
             mask: jax.Array | None = None) -> jax.Array:
    pred = model.apply(params, x)
    err = (pred - y) ** 2
    if mask is not None:
        return (err * mask).sum() / jnp.maximum(mask.sum(), 1.0)
    return err.mean()
