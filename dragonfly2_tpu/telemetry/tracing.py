"""Span tracing at service boundaries.

Capability parity with the reference's OpenTelemetry usage: every binary
initializes a tracer with an exporter (cmd/dependency/dependency.go:263-280
jaeger flag) and services create spans at boundaries (scheduler service,
client conductor/piece_downloader, manager jobs). This implementation is
OTel-shaped (trace_id/span_id/parent, attributes, events, status) with
pluggable exporters: in-memory (tests), JSONL file, or a user callable —
zero required external infrastructure.
"""

from __future__ import annotations

import contextlib
import contextvars
import dataclasses
import json
import pathlib
import secrets
import threading
import time
from typing import Any, Callable

_current_span: contextvars.ContextVar["Span | None"] = contextvars.ContextVar(
    "dragonfly2_tpu_span", default=None
)


@dataclasses.dataclass
class Span:
    name: str
    trace_id: str
    span_id: str
    parent_id: str | None
    start_ns: int
    end_ns: int | None = None
    attributes: dict[str, Any] = dataclasses.field(default_factory=dict)
    events: list[dict] = dataclasses.field(default_factory=list)
    status: str = "OK"

    def set_attribute(self, key: str, value: Any) -> None:
        self.attributes[key] = value

    def add_event(self, name: str, **attrs) -> None:
        self.events.append({"name": name, "ts_ns": time.time_ns(), **attrs})

    def record_exception(self, exc: BaseException) -> None:
        self.status = "ERROR"
        self.add_event("exception", type=type(exc).__name__, message=str(exc))

    def duration_ms(self) -> float | None:
        if self.end_ns is None:
            return None
        return (self.end_ns - self.start_ns) / 1e6

    def to_dict(self) -> dict:
        return dataclasses.asdict(self)


class Tracer:
    def __init__(self, service: str = "dragonfly2-tpu"):
        self.service = service
        self._exporters: list[Callable[[Span], None]] = []
        self._lock = threading.Lock()

    def add_exporter(self, fn: Callable[[Span], None]) -> None:
        self._exporters.append(fn)

    def export_to_memory(self) -> list[Span]:
        """Attach an in-memory exporter; returns the live list of spans."""
        spans: list[Span] = []
        self.add_exporter(spans.append)
        return spans

    def export_to_file(self, path: str | pathlib.Path) -> None:
        path = pathlib.Path(path)
        path.parent.mkdir(parents=True, exist_ok=True)
        lock = threading.Lock()

        def write(span: Span) -> None:
            with lock, open(path, "a") as f:
                f.write(json.dumps(span.to_dict()) + "\n")

        self.add_exporter(write)

    @contextlib.contextmanager
    def span(self, name: str, **attributes):
        parent = _current_span.get()
        span = Span(
            name=name,
            trace_id=parent.trace_id if parent else secrets.token_hex(16),
            span_id=secrets.token_hex(8),
            parent_id=parent.span_id if parent else None,
            start_ns=time.time_ns(),
            attributes={"service": self.service, **attributes},
        )
        token = _current_span.set(span)
        try:
            yield span
        except BaseException as e:
            span.record_exception(e)
            raise
        finally:
            span.end_ns = time.time_ns()
            _current_span.reset(token)
            with self._lock:
                exporters = list(self._exporters)
            for fn in exporters:
                try:
                    fn(span)
                except Exception:  # noqa: BLE001 - exporters must not break the traced path
                    pass


def current_span() -> Span | None:
    return _current_span.get()


_DEFAULT = Tracer()


def default_tracer() -> Tracer:
    return _DEFAULT
