"""The CLOSED dynconfig loop (round-2 gap: engine + endpoint + hook all
existed, nothing polled): schedulers hot-apply manager-pushed limits into
the live tick (scheduler/config/dynconfig.go:457), daemons learn their
scheduler list from the manager (client/config/dynconfig_manager.go:346),
and the Dynconfig engine carries both over the real manager RPC."""

import asyncio

from dragonfly2_tpu.cluster import messages as msg
from dragonfly2_tpu.cluster.scheduler import SchedulerService
from dragonfly2_tpu.manager import rpc as mrpc
from dragonfly2_tpu.manager.models import Database
from dragonfly2_tpu.manager.service import ManagerService
from dragonfly2_tpu.utils.dynconfig import Dynconfig


def host(i, seed=False):
    return msg.HostInfo(
        host_id=f"host-{i}", hostname=f"node-{i}", ip=f"10.0.0.{i}",
        host_type="super" if seed else "normal",
    )


def register(svc, peer_id, task_id, h, pieces=4):
    return svc.register_peer(msg.RegisterPeerRequest(
        peer_id=peer_id, task_id=task_id, host=h, url="https://e.com/blob",
        content_length=pieces * (4 << 20), total_piece_count=pieces,
    ))


def test_apply_dynconfig_changes_the_next_tick():
    """A manager-pushed candidate_parent_limit must bound the very next
    scheduling batch — the observer writes the field tick() reads live."""
    svc = SchedulerService()
    for i in range(4):
        register(svc, f"parent-{i}", "task-1", host(i, seed=i == 0))
        svc.peer_finished(msg.DownloadPeerFinishedRequest(peer_id=f"parent-{i}", piece_count=4))
    svc.tick()
    register(svc, "child-wide", "task-1", host(10))
    wide = [r for r in svc.tick() if isinstance(r, msg.NormalTaskResponse)]
    assert wide and len(wide[0].candidate_parents) > 1

    svc.apply_dynconfig({"scheduler_cluster_config": {"candidate_parent_limit": 1}})
    assert svc.config.scheduler.candidate_parent_limit == 1
    register(svc, "child-narrow", "task-1", host(11))
    narrow = [r for r in svc.tick() if isinstance(r, msg.NormalTaskResponse)]
    assert narrow and len(narrow[0].candidate_parents) == 1

    # hostile payloads are ignored, not applied
    svc.apply_dynconfig({"scheduler_cluster_config": {
        "candidate_parent_limit": 0, "filter_parent_limit": "bogus",
    }})
    assert svc.config.scheduler.candidate_parent_limit == 1


def test_scheduler_polls_manager_dynconfig_over_rpc(tmp_path):
    """End-to-end limit push: REST PATCH on the scheduler cluster ->
    GetDynconfig RPC payload -> Dynconfig refresh -> live service config
    (the loop the launcher's dynconfig_loop runs on a cadence)."""

    async def run():
        mgr = ManagerService(Database())
        mgr.create_cluster({"name": "c1"})
        mgr.register_scheduler({
            "host_name": "sched-1", "ip": "127.0.0.1", "port": 9000,
            "scheduler_cluster_id": 1,
        })
        server = mrpc.ManagerRPCServer(mgr)
        mhost, mport = await server.start()
        sched = SchedulerService()
        try:
            def fetch():
                async def go():
                    client = await mrpc.ManagerClient(mhost, mport).connect()
                    try:
                        resp = await client.call(mrpc.GetDynconfigRequest(
                            scheduler_cluster_id=1))
                        return resp.data
                    finally:
                        await client.close()
                return asyncio.run(go())

            dyn = Dynconfig(fetch, cache_path=tmp_path / "dyn.json", expire=3600.0)
            dyn.register(sched.apply_dynconfig)
            await asyncio.to_thread(dyn.get)
            default_limit = sched.config.scheduler.candidate_parent_limit

            # the operator patches the cluster config via the manager
            # (REST PATCH /scheduler-clusters/:id writes the same table)
            mgr.db.update("scheduler_clusters", 1, {
                "config": {"candidate_parent_limit": 2, "filter_parent_limit": 9},
            })
            await asyncio.to_thread(dyn.refresh)
            assert sched.config.scheduler.candidate_parent_limit == 2
            assert sched.config.scheduler.filter_parent_limit == 9
            assert sched.config.scheduler.candidate_parent_limit != default_limit

            # manager outage: the disk snapshot keeps serving the last limits
            await server.stop()
            await asyncio.to_thread(dyn.refresh)
            assert sched.config.scheduler.candidate_parent_limit == 2
        finally:
            await server.stop()

    asyncio.new_event_loop().run_until_complete(run())


def test_daemon_refreshes_scheduler_list_from_manager(tmp_path):
    """A daemon pointed at the manager re-resolves its scheduler set: an
    inactive scheduler leaves the hash ring, a newly registered one joins
    (pkg/resolver semantics through SchedulerClientPool.update_addresses)."""

    async def run():
        from dragonfly2_tpu.client.daemon import Daemon

        mgr = ManagerService(Database())
        mgr.create_cluster({"name": "c1"})
        mgr.register_scheduler({
            "host_name": "s-a", "ip": "10.9.0.1", "port": 9001,
            "scheduler_cluster_id": 1, "state": "active",
        })
        mgr.register_scheduler({
            "host_name": "s-b", "ip": "10.9.0.2", "port": 9002,
            "scheduler_cluster_id": 1, "state": "active",
        })
        server = mrpc.ManagerRPCServer(mgr)
        mhost, mport = await server.start()
        try:
            daemon = Daemon(
                data_dir=tmp_path / "daemon",
                scheduler_addresses=[("10.9.0.1", 9001)],  # static bootstrap
                manager_address=(mhost, mport),
            )
            data = await asyncio.to_thread(daemon._fetch_scheduler_list)
            daemon._apply_scheduler_list(data)
            assert set(daemon.pool._addr.values()) == {
                ("10.9.0.1", 9001), ("10.9.0.2", 9002),
            }

            # s-a misses keepalives -> inactive -> next refresh drops it
            mgr.db.update("schedulers", 1, {"state": "inactive"})
            data = await asyncio.to_thread(daemon._fetch_scheduler_list)
            daemon._apply_scheduler_list(data)
            assert set(daemon.pool._addr.values()) == {("10.9.0.2", 9002)}

            # an all-inactive payload must NOT strand the daemon
            mgr.db.update("schedulers", 2, {"state": "inactive"})
            data = await asyncio.to_thread(daemon._fetch_scheduler_list)
            daemon._apply_scheduler_list(data)
            assert set(daemon.pool._addr.values()) == {("10.9.0.2", 9002)}
        finally:
            await server.stop()

    asyncio.new_event_loop().run_until_complete(run())


def test_pool_update_swaps_atomically_and_prunes_connections():
    """update_addresses runs on the dynconfig worker thread while the
    event loop reads the ring: the (ring, addr) pair must swap as one
    tuple, and connections to removed schedulers must be closed on the
    loop, not leaked (ADVICE r3)."""

    async def run():
        from dragonfly2_tpu.rpc.client import SchedulerClientPool

        pool = SchedulerClientPool([("10.0.0.1", 1), ("10.0.0.2", 2)])

        class FakeConn:
            closed = False
            is_closed = False  # pool liveness probe (SchedulerConnection)

            async def close(self):
                self.closed = True

        a, b = FakeConn(), FakeConn()
        pool._conns["10.0.0.1:1"] = a
        pool._conns["10.0.0.2:2"] = b
        pool.update_addresses([("10.0.0.2", 2), ("10.0.0.3", 3)])
        ring, addr = pool._state  # one tuple: never a new ring + old addr
        assert set(addr) == {"10.0.0.2:2", "10.0.0.3:3"}
        assert all(ring.pick(f"t-{i}") in addr for i in range(32))
        assert "10.0.0.1:1" not in pool._conns

        # next for_task drains the parked stale connection on the loop —
        # after a grace period so in-flight RPCs on the removed scheduler
        # finish first (zero here to test the close itself)
        pool.STALE_CLOSE_GRACE_S = 0.0
        tid = next(
            t for t in (f"t-{i}" for i in range(1000))
            if ring.pick(t) == "10.0.0.2:2"
        )
        conn = await pool.for_task(tid)
        assert conn is b
        assert a.closed and not b.closed

    asyncio.new_event_loop().run_until_complete(run())
