"""Device-resident fused tick (ISSUE 19): the donated single-dispatch
control plane (ops/tick.py) is pinned decision-equivalent to the
vectorised numpy oracle, and its compile-signature set stays closed
over the proven buckets.

Equivalence is the acceptance contract: candidate fill -> feature
gather -> scoring -> selection fused into one XLA program must produce
IDENTICAL parent selections — scores included — to the host-side
`_fill_candidates_vec`/`_apply_chunk_batch` path on paired seeded
simulator runs. Both paths draw candidates through one sampler
(scheduler._sample_rows) and score through the same traced evaluator
functions, so any divergence is a real defect in the mirror sync, the
staging transport, or the device-side gather/masking — not noise.

The shape test is the other half of the perf story: warmup() compiles
every (bucket, static) signature the fused entry will ever serve, and
ticks across all bucket regimes add ZERO new compiles (the
retrace-tripwire contract, same as the packed evaluator entry).
"""

from __future__ import annotations

from pathlib import Path

import pytest

from dragonfly2_tpu.cluster import messages as msg
from dragonfly2_tpu.cluster.scheduler import _EVAL_BUCKETS, SchedulerService
from dragonfly2_tpu.cluster.simulator import ClusterSimulator
from dragonfly2_tpu.config.config import Config
from dragonfly2_tpu.scenarios import builtin_scenarios
from dragonfly2_tpu.telemetry.flight import jit_wrappers

ROOT = Path(__file__).resolve().parents[1]


def _run(fused: bool, scenario, seed: int, rounds: int = 10):
    cfg = Config()
    cfg.scheduler.vectorized_control = True
    cfg.scheduler.fused_tick = fused
    svc = SchedulerService(config=cfg, seed=seed + 100)
    # the flag must actually select the path under test
    assert (svc._tick_mirror is not None) == fused
    sim = ClusterSimulator(
        svc, num_hosts=40, num_tasks=5, seed=seed,
        scenario=scenario, deterministic_peer_ids=True,
    )
    selections = []
    for _ in range(rounds):
        for resp in sim.run_round(new_downloads=5):
            if hasattr(resp, "candidate_parents"):
                selections.append((
                    resp.peer_id,
                    tuple((p.peer_id, round(p.score, 6))
                          for p in resp.candidate_parents),
                ))
    return selections, sim.stats


@pytest.mark.parametrize("topology", [None, "bandwidth_skew", "chaos"])
def test_fused_matches_vectorized_oracle_selections(topology):
    scenario = builtin_scenarios()[topology] if topology else None
    for seed in (3, 17):
        fused, st_fused = _run(True, scenario, seed)
        oracle, st_oracle = _run(False, scenario, seed)
        assert fused, f"no selections produced (topology={topology})"
        assert fused == oracle, (
            f"fused/oracle divergence on topology={topology} "
            f"seed={seed}: first mismatch "
            f"{next((a, b) for a, b in zip(fused, oracle) if a != b)}"
        )
        # the downstream replay stayed paired too
        assert st_fused.pieces == st_oracle.pieces
        assert st_fused.completed == st_oracle.completed
        assert st_fused.piece_cost_ns_total == st_oracle.piece_cost_ns_total


# ------------------------------------------------- compile-shape stability


def _host(i: int, seed: bool = False) -> msg.HostInfo:
    return msg.HostInfo(
        host_id=f"ft-h{i}", hostname=f"ft-n{i}",
        ip=f"10.13.{i // 250}.{i % 250}",
        host_type="super" if seed else "normal", idc="idc-a",
        location="na|zone|rack",
        concurrent_upload_limit=100_000,
    )


def _register(svc, peer_id, host, task_id):
    return svc.register_peer(
        msg.RegisterPeerRequest(
            peer_id=peer_id, task_id=task_id, host=host,
            url="https://e.com/blob", content_length=4 * (4 << 20),
            total_piece_count=4,
        )
    )


def test_fused_tick_compile_shapes_stable_across_buckets():
    """Ticks across all three bucket regimes, twice each, add ZERO jit
    signatures beyond what warmup() compiled — for the fused entry AND
    the mirror's scatter. A failure here means a tick can eat an XLA
    compile mid-serving, which is the exact stall the fused design
    exists to kill."""
    from dragonfly2_tpu.telemetry import metrics as m

    svc = SchedulerService(metrics_registry=m.Registry())
    assert svc._tick_mirror is not None, "fused tick must be on by default"
    hosts = [_host(i) for i in range(64)]
    for i in range(16):
        seed_host = _host(1000 + i, seed=True)
        _register(svc, f"ft-seed-{i}", seed_host, f"ft-task-{i}")
        svc.peer_finished(
            msg.DownloadPeerFinishedRequest(peer_id=f"ft-seed-{i}",
                                            piece_count=4)
        )
    svc.tick()  # drain the pre_schedule-only seed tick
    svc.warmup()
    tick_wrapper = jit_wrappers()["scheduler.tick.fused_tick_chunk"]
    scatter_wrapper = jit_wrappers()["scheduler.tick.scatter_rows"]
    after_warmup = (
        tick_wrapper.stats()["signatures"],
        scatter_wrapper.stats()["signatures"],
    )

    reg_counter = [0]

    def _top_up(target: int) -> None:
        while len(svc._pending) < target:
            i = reg_counter[0]
            reg_counter[0] += 1
            _register(
                svc, f"ft-child-{i}", hosts[i % len(hosts)],
                f"ft-task-{i % 16}",
            )

    # one tick per bucket regime, twice: 64 -> single 64-chunk;
    # 300 -> 256 + 64 chunks; 1025 -> 1024 + 64 chunks
    for _ in range(2):
        for target in (64, 300, 1025):
            _top_up(target)
            svc.tick()
    assert (
        tick_wrapper.stats()["signatures"],
        scatter_wrapper.stats()["signatures"],
    ) == after_warmup, (
        "fused tick reached a signature warmup never compiled"
    )

    # dfshape acceptance: the statically-derived bucket set (retracer
    # parses _EVAL_BUCKETS out of scheduler.py) exactly matches the
    # runtime-observed batch dims of the fused entry — warmup plus ticks
    # across every regime compiled all proven buckets and nothing else
    from tools.dflint import retracer

    name = "scheduler.tick.fused_tick_chunk"
    derived = retracer.derive_static_signature_sets(ROOT)[name]
    observed = retracer.observed_batch_buckets(
        tick_wrapper, retracer.SERVING_B_ARGS[name]
    )
    assert observed == set(derived), (observed, derived)
    # the scatter's update batches are bucket-padded too
    sname = "scheduler.tick.scatter_rows"
    sobserved = retracer.observed_batch_buckets(
        scatter_wrapper, retracer.SERVING_B_ARGS[sname]
    )
    assert sobserved <= set(
        retracer.derive_static_signature_sets(ROOT)[sname]
    ), sobserved


def test_fused_tick_records_split_phases():
    """The phase seam (ISSUE 19 satellite 6): a fused tick records the
    fused split — candidate_fill / legality_recheck / pack /
    fused_dispatch / d2h_wait / emit — and control_dispatch is
    re-derived as the HOST-side sum (device wait excluded), while
    fused_device_call carries the device dispatch+wait. The aggregate
    keeps meaning 'all host work per tick' across the oracle and fused
    paths, so BENCH trajectories stay comparable."""
    from dragonfly2_tpu.telemetry import metrics as m

    svc = SchedulerService(metrics_registry=m.Registry())
    assert svc._tick_mirror is not None
    hosts = [_host(i) for i in range(32)]
    for i in range(8):
        seed_host = _host(2000 + i, seed=True)
        _register(svc, f"ft-ph-seed-{i}", seed_host, f"ft-ph-task-{i}")
        svc.peer_finished(
            msg.DownloadPeerFinishedRequest(peer_id=f"ft-ph-seed-{i}",
                                            piece_count=4)
        )
    svc.tick()
    for i in range(80):
        _register(svc, f"ft-ph-{i}", hosts[i % len(hosts)],
                  f"ft-ph-task-{i % 8}")
    svc.tick()
    phases = svc.recorder.ring[-1]
    for key in ("candidate_fill", "legality_recheck", "pack",
                "fused_dispatch", "d2h_wait", "emit",
                "control_dispatch", "fused_device_call"):
        assert key in phases, (key, sorted(phases))
    host_side = (
        phases.get("report_ingest", 0.0) + phases.get("pre_schedule", 0.0)
        + phases["candidate_fill"] + phases["legality_recheck"]
        + phases["pack"] + phases["emit"]
    )
    assert phases["control_dispatch"] == pytest.approx(host_side, rel=1e-6)
    assert phases["fused_device_call"] == pytest.approx(
        phases["fused_dispatch"] + phases["d2h_wait"], rel=1e-6
    )
