"""dflint red fixture: one finding per jit-hygiene rule.

JIT001 x2 (``.item()`` + ``float(tracer)``), JIT002 (``if`` on a
tracer), JIT003 x2 (un-allowlisted host sync + a cost-card
``cost_analysis`` capture in a hot function — the test configures
``hot_tick`` as hot; a capture pays a full XLA recompile, so the tick
path may never run one), JIT004 (dynamic slice into a jit call).
"""

import functools

import jax
import numpy as np


@functools.partial(jax.jit, static_argnames=("limit",))
def score(batch, limit):
    peak = batch.max().item()  # <- JIT001 (.item() host sync)
    scale = float(batch[0, 0])  # <- JIT001 (cast concretizes tracer)
    if batch.sum() > 0:  # <- JIT002 (python branch on tracer)
        peak = peak + scale
    return batch * peak


def hot_tick(packed, compiled):
    out = np.asarray(packed)  # <- JIT003 (not on the d2h allowlist)
    compiled.cost_analysis()  # <- JIT003 (cost-card capture on the hot path)
    return out


def caller(rows, n):
    return score(rows[:n], 4)  # <- JIT004 (runtime-length slice into jit)
