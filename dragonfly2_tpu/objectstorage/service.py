"""Daemon object-storage HTTP service + dfstore client SDK.

Capability parity with client/daemon/objectstorage/objectstorage.go:724
(the S3-compatible-ish HTTP API the daemon serves: bucket listing, object
GET/PUT/HEAD/DELETE, metadata listing, copy) and client/dfstore/dfstore.go
(the SDK/CLI wrapping that API: GetObject/PutObject/CopyObject/
IsObjectExist/...). P2P integration: PUT imports the object into the
daemon's task storage under a stable object task id so child peers can
pull it over the piece upload server; GET falls back to the local task
cache when the backend misses.
"""

from __future__ import annotations

import json
import threading
import urllib.error
import urllib.parse
import urllib.request
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

from dragonfly2_tpu.objectstorage.backends import object_task_id
from dragonfly2_tpu.utils import dferrors


class ObjectStorageService:
    def __init__(self, backend, storage=None, host: str = "127.0.0.1", port: int = 0):
        """`backend` is an objectstorage backend; `storage` optionally a
        client StorageManager for P2P import/serve."""
        self.backend = backend
        self.storage = storage
        outer = self

        class Handler(BaseHTTPRequestHandler):
            protocol_version = "HTTP/1.1"

            def log_message(self, *args):
                pass

            def _run(self):
                try:
                    status, headers, body = outer.handle(
                        self.command,
                        self.path,
                        self.rfile.read(int(self.headers.get("Content-Length") or 0)),
                    )
                except dferrors.NotFound as e:
                    status, headers, body = 404, {}, str(e).encode()
                except dferrors.InvalidArgument as e:
                    status, headers, body = 400, {}, str(e).encode()
                except Exception as e:  # noqa: BLE001 - surface as 500
                    status, headers, body = 500, {}, f"{type(e).__name__}: {e}".encode()
                self.send_response(status)
                for k, v in headers.items():
                    self.send_header(k, v)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                if self.command != "HEAD":
                    self.wfile.write(body)

            do_GET = do_PUT = do_POST = do_DELETE = do_HEAD = _run

        self._httpd = ThreadingHTTPServer((host, port), Handler)
        self.host, self.port = self._httpd.server_address[:2]
        self._thread: threading.Thread | None = None

    def start(self) -> tuple[str, int]:
        self._thread = threading.Thread(target=self._httpd.serve_forever, daemon=True)
        self._thread.start()
        return self.host, self.port

    def stop(self) -> None:
        self._httpd.shutdown()
        self._httpd.server_close()
        if self._thread:
            self._thread.join(timeout=5)

    # -------------------------------------------------------------- routes

    def handle(self, method: str, path: str, body: bytes):
        path, _, query = path.partition("?")
        params = dict(urllib.parse.parse_qsl(query))
        parts = [urllib.parse.unquote(p) for p in path.split("/") if p]

        if parts == ["healthy"]:
            return 200, {}, b"ok"
        if parts == ["buckets"]:
            if method == "GET":
                return self._json([vars(b) for b in self.backend.get_bucket_metadatas()])
            if method == "POST":
                name = json.loads(body or b"{}").get("name", "")
                self.backend.create_bucket(name)
                return 200, {}, b"{}"
        if len(parts) == 2 and parts[0] == "buckets":
            if method == "DELETE":
                self.backend.delete_bucket(parts[1])
                return 200, {}, b"{}"
        if len(parts) == 3 and parts[0] == "buckets" and parts[2] == "metadatas":
            metas = self.backend.get_object_metadatas(parts[1], prefix=params.get("prefix", ""))
            return self._json([vars(m) for m in metas])
        if len(parts) >= 4 and parts[0] == "buckets" and parts[2] == "objects":
            bucket, key = parts[1], "/".join(parts[3:])
            return self._object(method, bucket, key, body, params)
        raise dferrors.InvalidArgument(f"no route {method} {path}")

    def _object(self, method: str, bucket: str, key: str, body: bytes, params: dict):
        if method == "PUT":
            meta = self.backend.put_object(bucket, key, body)
            # P2P import (mode=ImportModes in the reference): make the
            # object a completed local task so peers can pull pieces.
            if self.storage is not None:
                self._import_task(bucket, key, body)
            return self._json(vars(meta))
        if method == "HEAD":
            meta = self.backend.get_object_metadata(bucket, key)
            return 200, {
                "Content-Length-Object": str(meta.content_length),
                "Etag": meta.etag,
            }, b""
        if method == "GET":
            try:
                data = self.backend.get_object(bucket, key)
            except dferrors.NotFound:
                data = self._read_task(bucket, key)  # P2P cache fallback
                if data is None:
                    raise
            return 200, {"Content-Type": "application/octet-stream"}, data
        if method == "DELETE":
            self.backend.delete_object(bucket, key)
            if self.storage is not None:
                self.storage.delete_task(object_task_id(bucket, key))
            return 200, {}, b"{}"
        if method == "POST" and "copy_to" in params:
            meta = self.backend.copy_object(bucket, key, params["copy_to"])
            return self._json(vars(meta))
        raise dferrors.InvalidArgument(f"bad object op {method}")

    def _import_task(self, bucket: str, key: str, data: bytes) -> None:
        from dragonfly2_tpu.client.piece_manager import piece_layout
        from dragonfly2_tpu.client.storage import TaskMetadata

        task_id = object_task_id(bucket, key)
        # Overwrite semantics: a re-PUT must replace the P2P copy, never
        # leave peers pulling the previous object's bytes.
        self.storage.delete_task(task_id)
        ts = self.storage.register_task(TaskMetadata(task_id=task_id, peer_id="objstore"))
        layout = piece_layout(len(data), ts.meta.piece_length)
        for n, off, length in layout:
            ts.write_piece(n, off, data[off : off + length])
        ts.mark_done(len(data), len(layout))

    def _read_task(self, bucket: str, key: str) -> bytes | None:
        if self.storage is None:
            return None
        ts = self.storage.find_completed_task(object_task_id(bucket, key))
        if ts is None:
            return None
        return ts.read_range(0, max(ts.meta.content_length, 0))

    @staticmethod
    def _json(obj) -> tuple[int, dict, bytes]:
        return 200, {"Content-Type": "application/json"}, json.dumps(obj).encode()


class DfstoreClient:
    """client/dfstore SDK surface over the daemon's object-storage API."""

    def __init__(self, endpoint: str, timeout: float = 30.0):
        self.endpoint = endpoint.rstrip("/")
        self.timeout = timeout

    def create_bucket(self, bucket: str) -> None:
        self._request("POST", "/buckets", json.dumps({"name": bucket}).encode())

    def list_buckets(self) -> list[dict]:
        return json.loads(self._request("GET", "/buckets"))

    def put_object(self, bucket: str, key: str, data: bytes) -> dict:
        return json.loads(self._request("PUT", self._object_path(bucket, key), data))

    def get_object(self, bucket: str, key: str) -> bytes:
        return self._request("GET", self._object_path(bucket, key))

    def delete_object(self, bucket: str, key: str) -> None:
        self._request("DELETE", self._object_path(bucket, key))

    def copy_object(self, bucket: str, src: str, dst: str) -> dict:
        quoted = urllib.parse.quote(dst)
        return json.loads(
            self._request("POST", f"{self._object_path(bucket, src)}?copy_to={quoted}")
        )

    def is_object_exist(self, bucket: str, key: str) -> bool:
        try:
            self._request("HEAD", self._object_path(bucket, key))
            return True
        except dferrors.NotFound:
            return False

    def object_metadatas(self, bucket: str, prefix: str = "") -> list[dict]:
        quoted = urllib.parse.quote(prefix)
        return json.loads(self._request("GET", f"/buckets/{bucket}/metadatas?prefix={quoted}"))

    def _object_path(self, bucket: str, key: str) -> str:
        return f"/buckets/{bucket}/objects/{urllib.parse.quote(key)}"

    def _request(self, method: str, path: str, body: bytes | None = None) -> bytes:
        req = urllib.request.Request(self.endpoint + path, data=body, method=method)
        try:
            with urllib.request.urlopen(req, timeout=self.timeout) as resp:
                return resp.read()
        except urllib.error.HTTPError as e:
            detail = e.read().decode(errors="replace")
            if e.code == 404:
                raise dferrors.NotFound(detail) from None
            if e.code == 400:
                raise dferrors.InvalidArgument(detail) from None
            raise dferrors.Unavailable(f"{e.code}: {detail}") from None
