"""Attention parent ranker — the second model family.

Where GraphSAGE ranks via graph-structure embeddings
(models/graphsage.py), this model treats a download's candidate-parent
list as a SET and lets candidates attend to each other (a set
transformer): "is this parent good" depends on what else is on offer —
exactly the comparative judgement the reference's linear evaluator blend
cannot express (scheduler/scheduling/evaluator/evaluator_base.go:71-83
scores each parent independently).

TPU-first: tokens are [tasks, candidates, hidden] bf16 matmuls on the
MXU; the attention inner product is injectable so the same module runs
dense single-chip attention or ring attention over the mesh `sp` axis
(parallel/ring.py) when the "sequence" is a host's full transfer history
rather than a 64-candidate set.
"""

from __future__ import annotations

from typing import Callable

import flax.linen as nn
import jax.numpy as jnp

from dragonfly2_tpu.parallel.ring import dense_attention

AttentionFn = Callable  # (q, k, v, kv_mask) -> out, all [B, H, L, D]


class SelfAttentionBlock(nn.Module):
    """Pre-LN MHA + MLP with an injectable attention inner product.

    moe_experts > 0 swaps the dense MLP for a Switch-style top-1
    mixture of expert scorers (parallel/moe.py): different experts can
    specialize per traffic class/IDC. On a mesh with ep > 1 the expert
    queues ride the all_to_all kernel; single-device falls back to the
    exact no-drop reference."""

    hidden_dim: int
    num_heads: int = 4
    compute_dtype: jnp.dtype = jnp.bfloat16
    moe_experts: int = 0
    moe_capacity_factor: float = 2.0

    @nn.compact
    def __call__(self, x, mask, attention_fn: AttentionFn = dense_attention, mesh=None):
        batch, length, _ = x.shape
        head_dim = self.hidden_dim // self.num_heads
        h = nn.LayerNorm(dtype=self.compute_dtype)(x)
        qkv = nn.Dense(3 * self.hidden_dim, dtype=self.compute_dtype, name="qkv")(h)
        q, k, v = jnp.split(qkv, 3, axis=-1)

        def heads(t):  # [B, L, Hd] -> [B, H, L, D]
            return t.reshape(batch, length, self.num_heads, head_dim).transpose(0, 2, 1, 3)

        out = attention_fn(heads(q), heads(k), heads(v), mask)
        out = out.transpose(0, 2, 1, 3).reshape(batch, length, self.hidden_dim)
        x = x + nn.Dense(self.hidden_dim, dtype=self.compute_dtype, name="proj")(out)
        h = nn.LayerNorm(dtype=self.compute_dtype)(x)
        if self.moe_experts > 0:
            return x + self._moe(h, mesh)
        h = nn.Dense(4 * self.hidden_dim, dtype=self.compute_dtype, name="mlp_up")(h)
        h = nn.gelu(h)
        return x + nn.Dense(self.hidden_dim, dtype=self.compute_dtype, name="mlp_down")(h)

    def _moe(self, h, mesh):
        from dragonfly2_tpu.parallel import moe as moe_lib
        from dragonfly2_tpu.parallel.mesh import EP_AXIS

        f, e, wide = self.hidden_dim, self.moe_experts, 4 * self.hidden_dim
        init = nn.initializers.lecun_normal()
        gate_w = self.param("moe_gate", init, (f, e))
        w1 = self.param("moe_w1", init, (e, f, wide))
        b1 = self.param("moe_b1", nn.initializers.zeros, (e, wide))
        w2 = self.param("moe_w2", init, (e, wide, f))
        b2 = self.param("moe_b2", nn.initializers.zeros, (e, f))
        shape = h.shape
        tokens = h.reshape(-1, f)
        if mesh is not None and mesh.shape.get(EP_AXIS, 1) > 1:
            ep = mesh.shape[EP_AXIS]
            t_local = tokens.shape[0] // ep
            capacity = max(1, int(t_local / e * self.moe_capacity_factor))
            out = moe_lib.sharded_moe_ffn(
                mesh, tokens, gate_w, w1, b1, w2, b2, capacity=capacity
            )
        else:
            out = moe_lib.moe_reference(tokens, gate_w, w1, b1, w2, b2)
        return out.reshape(shape).astype(self.compute_dtype)


class AttentionRanker(nn.Module):
    """Scores [tasks, P] candidate parents from child/parent/pair features.

    Same input surface as the GraphSAGE ranker's RankingDataset
    (records/features.py:251) so the trainer can fit either family and
    the registry stores both (model type "attention" alongside
    "gnn"/"mlp", manager/models/model.go:19-46's type column)."""

    hidden_dim: int = 128
    num_heads: int = 4
    num_layers: int = 2
    compute_dtype: jnp.dtype = jnp.bfloat16
    moe_experts: int = 0
    moe_capacity_factor: float = 2.0

    @nn.compact
    def __call__(
        self,
        child_feats,  # [N, F]
        parent_feats,  # [N, P, F]
        pair_feats,  # [N, P, Fp]
        mask,  # [N, P] bool
        attention_fn: AttentionFn = dense_attention,
        mesh=None,
    ):
        n, p, _ = parent_feats.shape
        tokens = jnp.concatenate(
            [
                parent_feats.astype(self.compute_dtype),
                jnp.broadcast_to(
                    child_feats[:, None, :], (n, p, child_feats.shape[-1])
                ).astype(self.compute_dtype),
                pair_feats.astype(self.compute_dtype),
            ],
            axis=-1,
        )
        x = nn.Dense(self.hidden_dim, dtype=self.compute_dtype, name="embed")(tokens)
        for i in range(self.num_layers):
            x = SelfAttentionBlock(
                self.hidden_dim, self.num_heads, self.compute_dtype,
                moe_experts=self.moe_experts,
                moe_capacity_factor=self.moe_capacity_factor,
                name=f"block_{i}",
            )(x, mask, attention_fn, mesh=mesh)
        x = nn.LayerNorm(dtype=self.compute_dtype)(x)
        scores = nn.Dense(1, dtype=jnp.float32, name="score")(x)[..., 0]
        return jnp.where(mask, scores, -1e30)
