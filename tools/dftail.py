#!/usr/bin/env python
"""dftail — replay a recorded tail-attribution block and answer "what
made the slow downloads slow?".

The tail plane (telemetry/tailtrace.py) ships its complete offline
basis inside every ``run_megascale`` report: the per-round phase
matrix, the per-round slowest-completion rows, and the crash schedule.
The kill-window attribution is a PURE function of those arrays, so
this tool re-derives it offline over any artifact that carries a
``tail`` block —

- a ``BENCH_mega.json`` (``{"runs": [...]}``; every run replays),
- a single ``run_megascale`` report (``{"tail": {...}, ...}``),
- or a bare tail block (``{"round_phase_ms": [...], ...}``)

— prints the per-region TTC decomposition table and the kill-window
verdicts, and drift-checks the recomputation against the recorded
windows (they can only differ if the window derivation changed since
the run). The decomposition audit re-checks that attributed phase time
sums to measured TTC within tolerance, per region AND per kept
exemplar.

Usage:
    python tools/dftail.py BENCH_mega.json [--run soak] [--json]
    python tools/dftail.py report.json --list
    python tools/dftail.py report.json --download 1234

Exit codes: 0 = attribution consistent and recomputation matches the
recorded windows, 1 = decomposition tolerance violated (a region or
exemplar's phases no longer sum to its TTC within --tolerance), 2 = no
tail block / unreadable artifact / recomputed windows drift from the
recorded ones (an attribution you can't reproduce offline is not an
attribution).
"""

from __future__ import annotations

import argparse
import json
import pathlib
import sys

sys.path.insert(0, str(pathlib.Path(__file__).resolve().parents[1]))

from dragonfly2_tpu.telemetry.tailtrace import (  # noqa: E402
    DEFAULT_TOLERANCE,
    N_PHASES,
    PHASES,
    TailTrace,
)

DEFAULT_WINDOW_ROUNDS = TailTrace.DEFAULT_WINDOW_ROUNDS


def _extract_tails(doc: dict, which: str | None) -> list[tuple[str, dict]]:
    """(label, tail block) pairs from any supported artifact shape."""
    if isinstance(doc.get("runs"), list):
        runs = [r for r in doc["runs"] if isinstance(r, dict)]
    elif isinstance(doc.get("tail"), dict) or isinstance(
        doc.get("round_phase_ms"), list
    ):
        runs = [doc]
    else:
        raise SystemExit(
            "dftail: artifact carries neither 'runs' nor a tail block"
        )
    if which is not None:
        runs = [
            r for r in runs
            if str(r.get("scenario", "")) == which
            or f"{r.get('scenario')}_{r.get('hosts')}" == which
        ]
        if not runs:
            raise SystemExit(f"dftail: no run matches --run {which!r}")
    out: list[tuple[str, dict]] = []
    for r in runs:
        tail = r.get("tail") if isinstance(r.get("tail"), dict) else (
            r if isinstance(r.get("round_phase_ms"), list) else None
        )
        label = str(r.get("scenario") or r.get("name") or "run")
        if r.get("hosts"):
            label = f"{label}_{r['hosts']}"
        if tail is None:
            print(
                f"dftail: skipping {label} "
                "(no tail block — artifact predates the tail plane)",
                file=sys.stderr,
            )
            continue
        out.append((label, tail))
    if not out:
        raise SystemExit("dftail: no selected run carries a tail block")
    return out


def recompute_windows(
    tail: dict, window_rounds: int = DEFAULT_WINDOW_ROUNDS
) -> tuple[list[dict], str | None]:
    """Re-derive the kill-window attribution from the shipped round
    matrices — the same arithmetic as TailTrace._windows_locked, over
    the ms-rounded offline copies."""
    matrix = tail.get("round_phase_ms") or []
    slow = tail.get("round_slow_ms") or []
    crash_rounds = sorted(int(k) for k in tail.get("crash_rounds") or [])
    last = len(matrix) - 1
    in_window = [False] * (last + 1)
    windows: list[dict] = []
    for k in crash_rounds:
        lo = max(int(k), 0)
        hi = min(lo + window_rounds - 1, last)
        if hi < lo:
            windows.append({
                "round": int(k), "until": int(k),
                "dominant_phase": None, "tail_dominant_phase": None,
            })
            continue
        row = [0.0] * N_PHASES
        for r in range(lo, hi + 1):
            in_window[r] = True
            for p in range(N_PHASES):
                row[p] += matrix[r][p]
        dominant = (
            PHASES[max(range(N_PHASES), key=lambda p: row[p])]
            if sum(row) > 0.0 else None
        )
        tail_dom = None
        rows = [(slow[r][0], r) for r in range(lo, hi + 1) if r < len(slow)]
        if rows:
            best_ttc, best_r = max(rows)
            if best_ttc > 0.0:
                ph = slow[best_r][1:]
                tail_dom = PHASES[max(range(N_PHASES), key=lambda p: ph[p])]
        windows.append({
            "round": int(k), "until": hi,
            "dominant_phase": dominant, "tail_dominant_phase": tail_dom,
        })
    baseline = None
    base = [0.0] * N_PHASES
    for r in range(last + 1):
        if not in_window[r]:
            for p in range(N_PHASES):
                base[p] += matrix[r][p]
    if sum(base) > 0.0:
        baseline = PHASES[max(range(N_PHASES), key=lambda p: base[p])]
    return windows, baseline


def _check_recorded(tail: dict, windows: list[dict],
                    baseline: str | None) -> list[str]:
    """Recomputed-vs-recorded drift, dominants only: the offline matrix
    is ms-rounded, so sums differ in the noise but the argmax must not."""
    drift: list[str] = []
    recorded = tail.get("windows")
    if isinstance(recorded, list) and len(recorded) == len(windows):
        for rec, rep in zip(recorded, windows):
            for key in ("dominant_phase", "tail_dominant_phase"):
                if key in rec and rec.get(key) != rep.get(key):
                    drift.append(
                        f"window {rep['round']}: recorded {key}="
                        f"{rec.get(key)!r}, recomputed {rep.get(key)!r}"
                    )
    elif isinstance(recorded, list):
        drift.append(
            f"recorded {len(recorded)} windows, recomputed {len(windows)}"
        )
    rec_base = tail.get("baseline_dominant_phase")
    if "baseline_dominant_phase" in tail and rec_base != baseline:
        drift.append(
            f"recorded baseline={rec_base!r}, recomputed {baseline!r}"
        )
    return drift


def _check_tolerance(tail: dict, tolerance: float) -> list[str]:
    """Attributed-sums-to-measured audit over everything the block
    carries a pairing for."""
    bad: list[str] = []
    for name, reg in sorted((tail.get("regions") or {}).items()):
        ratio = reg.get("decomp_ratio")
        if ratio is not None and abs(float(ratio) - 1.0) > tolerance:
            bad.append(f"region {name}: decomp_ratio {ratio} off by "
                       f"more than {tolerance:.0%}")
        p99x = (reg.get("tail") or {}).get("p99_exemplar") or {}
        ttc, total = p99x.get("ttc_ms"), p99x.get("sum_ms")
        if ttc and total is not None and abs(total / ttc - 1.0) > tolerance:
            bad.append(f"region {name}: p99 exemplar phases sum to "
                       f"{total} of ttc {ttc}")
    for ex in tail.get("exemplars") or []:
        ttc = float(ex.get("ttc_ms") or 0.0)
        total = sum((ex.get("phases_ms") or {}).values())
        if ttc > 0.0 and abs(total / ttc - 1.0) > tolerance:
            bad.append(f"exemplar seq={ex.get('seq')}: phases sum to "
                       f"{round(total, 2)} of ttc {round(ttc, 2)}")
    return bad


def judge(doc: dict, which: str | None = None,
          window_rounds: int = DEFAULT_WINDOW_ROUNDS,
          tolerance: float = DEFAULT_TOLERANCE) -> tuple[int, list[dict]]:
    verdicts: list[dict] = []
    worst = 0
    for label, tail in _extract_tails(doc, which):
        windows, baseline = recompute_windows(tail, window_rounds)
        drift = _check_recorded(tail, windows, baseline)
        bad = _check_tolerance(tail, tolerance)
        rc = 2 if drift else (1 if bad else 0)
        worst = max(worst, rc)
        verdicts.append({
            "run": label,
            "exit": rc,
            "windows": windows,
            "baseline_dominant_phase": baseline,
            "drift": drift,
            "tolerance_violations": bad,
            "regions": {
                name: {
                    "completed": reg.get("completed"),
                    "ttc_ms": reg.get("ttc_ms"),
                    "dominant_phase": reg.get("dominant_phase"),
                    "decomp_ratio": reg.get("decomp_ratio"),
                    "phase_share": reg.get("phase_share"),
                }
                for name, reg in sorted((tail.get("regions") or {}).items())
            },
        })
    return worst, verdicts


def _print_verdict(v: dict) -> None:
    print(f"== {v['run']} ==")
    for name, reg in v["regions"].items():
        ttc = reg.get("ttc_ms") or {}
        share = ", ".join(
            f"{ph}={s:.1%}"
            for ph, s in sorted((reg.get("phase_share") or {}).items(),
                                key=lambda kv: -kv[1])
        )
        print(f"  {name}: n={reg.get('completed')} "
              f"p50={ttc.get('p50')} p95={ttc.get('p95')} "
              f"p99={ttc.get('p99')}ms "
              f"dom={reg.get('dominant_phase')} "
              f"ratio={reg.get('decomp_ratio')}")
        if share:
            print(f"    share: {share}")
    for w in v["windows"]:
        print(f"  kill@{w['round']}..{w['until']}: "
              f"mass={w['dominant_phase']} tail={w['tail_dominant_phase']}")
    print(f"  baseline: {v['baseline_dominant_phase']}")
    for line in v["drift"]:
        print(f"  DRIFT: {line}")
    for line in v["tolerance_violations"]:
        print(f"  TOLERANCE: {line}")


def _exemplars(doc: dict, which: str | None) -> list[tuple[str, dict]]:
    rows: list[tuple[str, dict]] = []
    for label, tail in _extract_tails(doc, which):
        for ex in tail.get("exemplars") or []:
            rows.append((label, ex))
    return rows


def main(argv: list[str] | None = None) -> int:
    ap = argparse.ArgumentParser(
        prog="dftail", description=__doc__.split("\n\n")[0]
    )
    ap.add_argument("artifact", help="BENCH_mega.json / report / tail dump")
    ap.add_argument("--run", help="replay only the run matching "
                    "scenario or scenario_hosts")
    ap.add_argument("--list", action="store_true",
                    help="list kept exemplars instead of judging")
    ap.add_argument("--download", type=int, metavar="SEQ",
                    help="print one kept download's decomposition")
    ap.add_argument("--window-rounds", type=int,
                    default=DEFAULT_WINDOW_ROUNDS,
                    help="kill-window width in rounds "
                    f"(default {DEFAULT_WINDOW_ROUNDS})")
    ap.add_argument("--tolerance", type=float, default=DEFAULT_TOLERANCE,
                    help="decomposition-sum tolerance "
                    f"(default {DEFAULT_TOLERANCE})")
    ap.add_argument("--json", action="store_true",
                    help="machine-readable verdicts")
    args = ap.parse_args(argv)

    try:
        doc = json.loads(pathlib.Path(args.artifact).read_text())
    except (OSError, ValueError) as e:
        print(f"dftail: cannot read {args.artifact}: {e}", file=sys.stderr)
        return 2
    if not isinstance(doc, dict):
        print("dftail: artifact is not a JSON object", file=sys.stderr)
        return 2

    try:
        if args.download is not None:
            hits = [
                (label, ex) for label, ex in _exemplars(doc, args.run)
                if int(ex.get("seq", -1)) == args.download
            ]
            if not hits:
                print(f"dftail: no kept exemplar with seq={args.download} "
                      "(exemplars are sampled; try --list)", file=sys.stderr)
                return 2
            for label, ex in hits:
                if args.json:
                    print(json.dumps(ex, indent=2, sort_keys=True))
                    continue
                print(f"{label} seq={ex['seq']} [{ex.get('kind')}] "
                      f"region={ex.get('region')} round={ex.get('round')} "
                      f"ttc={ex.get('ttc_ms')}ms")
                for ph, ms in sorted((ex.get("phases_ms") or {}).items(),
                                     key=lambda kv: -kv[1]):
                    print(f"  {ph:>16}: {ms}ms")
            return 0
        if args.list:
            rows = _exemplars(doc, args.run)
            if args.json:
                print(json.dumps([ex for _, ex in rows], sort_keys=True))
            else:
                for label, ex in rows:
                    dom = max(
                        (ex.get("phases_ms") or {"?": 0.0}).items(),
                        key=lambda kv: kv[1],
                    )[0]
                    print(f"{label} seq={ex.get('seq')} [{ex.get('kind')}] "
                          f"{ex.get('region')} r{ex.get('round')} "
                          f"ttc={ex.get('ttc_ms')}ms dom={dom}")
            return 0
        rc, verdicts = judge(doc, args.run, args.window_rounds,
                             args.tolerance)
    except SystemExit as e:
        print(e, file=sys.stderr)
        return 2
    if args.json:
        print(json.dumps({"exit": rc, "runs": verdicts},
                         indent=2, sort_keys=True))
    else:
        for v in verdicts:
            _print_verdict(v)
    return rc


if __name__ == "__main__":
    sys.exit(main())
