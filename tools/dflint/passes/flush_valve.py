"""FLUSH001/FLUSH002 — buffered columnar state must be flushed before
it is read.

PR-8 buffered piece-report ingestion: ``piece_finished`` /
``pieces_finished_batch`` enqueue into ``SchedulerService._piece_buf``
and the SoA columns only absorb the buffer at the tick's
``report_ingest`` phase or at an explicit flush valve
(``flush_piece_reports`` / ``_absorb_piece_reports``). The invariant —
"flush valves at every columnar reader" — means any code that READS one
of the buffered columns without flushing first can observe stale state:
a peer's finished count missing reports that already arrived, a GC
sweep reaping a peer whose liveness touch is still sitting in the
buffer.

- ``FLUSH001``: a read of a buffered column (``*.state.<column>`` chain,
  or a buffered read-method on the state object) with no flush earlier
  in the function, in a context that can be entered with a dirty
  buffer.
- ``FLUSH002``: direct read of ``_piece_buf`` outside the valve methods
  (producers may append; only the valves may consume or inspect).

Within the owner class (``SchedulerService``) the pass propagates flush
coverage through the in-class call graph: a private helper all of whose
callers flush before the call is covered; a public method is assumed
callable with a dirty buffer unless it flushes first itself. Outside
the owner class (e.g. the RPC server reading ``service.state.*``) the
check is per-function: flush before read, or carry a waiver.

The column owner (``state/cluster.py``) is exempt — the columns are its
storage; the valve contract binds consumers.
"""

from __future__ import annotations

import ast
import dataclasses

from tools.dflint.core import FileContext, Finding, attr_chain

# columns mutated by the buffered absorb (state.record_pieces_batch and
# the parent-side accounting in _absorb_piece_reports)
DEFAULT_BUFFERED_COLUMNS = frozenset({
    "peer_finished_bitset", "peer_finished_count", "peer_piece_costs",
    "peer_piece_cost_count", "peer_cost_cursor", "peer_updated_at",
    "host_updated_at", "host_upload_count",
})
# read-methods on the state object that internally read buffered columns
DEFAULT_BUFFERED_READ_METHODS = frozenset({
    "gather_candidates", "peer_piece_costs_ordered", "peer_finished_pieces",
})
DEFAULT_VALVES = frozenset({"flush_piece_reports", "_absorb_piece_reports"})
DEFAULT_OWNER_CLASS = "SchedulerService"
DEFAULT_BUFFER_ATTR = "_piece_buf"
# the column owner: reading its own storage is what it is for
DEFAULT_EXEMPT_SUFFIXES = ("state/cluster.py",)


@dataclasses.dataclass
class _Read:
    node: ast.AST
    what: str
    order: int  # source position index within the function


class FlushValvePass:
    name = "flush-valve"
    rules = ("FLUSH001", "FLUSH002")

    def __init__(
        self,
        buffered_columns: frozenset[str] = DEFAULT_BUFFERED_COLUMNS,
        buffered_read_methods: frozenset[str] = DEFAULT_BUFFERED_READ_METHODS,
        valves: frozenset[str] = DEFAULT_VALVES,
        owner_class: str = DEFAULT_OWNER_CLASS,
        buffer_attr: str = DEFAULT_BUFFER_ATTR,
        exempt_suffixes: tuple[str, ...] = DEFAULT_EXEMPT_SUFFIXES,
    ):
        self.buffered_columns = buffered_columns
        self.buffered_read_methods = buffered_read_methods
        self.valves = valves
        self.owner_class = owner_class
        self.buffer_attr = buffer_attr
        self.exempt_suffixes = exempt_suffixes

    def run(self, ctx: FileContext) -> list[Finding]:
        if any(ctx.rel.endswith(suffix) for suffix in self.exempt_suffixes):
            return []
        findings: list[Finding] = []
        for node in ctx.tree.body:
            if isinstance(node, ast.ClassDef):
                if node.name == self.owner_class:
                    findings.extend(self._check_owner_class(ctx, node))
                else:
                    findings.extend(self._check_plain_scope(ctx, node))
            elif isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                findings.extend(self._check_function(ctx, node, symbol=node.name))
        return findings

    # ------------------------------------------------- per-function scan

    def _scan(self, func) -> tuple[list[_Read], list[int], list[tuple[str, int]]]:
        """(buffered reads, flush positions, self-call sites) in source
        order. Source order is a deliberate approximation: a flush in a
        conditional branch counts as covering later reads — this is a
        lint for a discipline, not a proof system."""
        reads: list[_Read] = []
        flushes: list[int] = []
        calls: list[tuple[str, int]] = []
        order = 0
        for node in ast.walk(func):
            order = max(order, getattr(node, "lineno", order))
            if isinstance(node, ast.Call):
                chain = attr_chain(node.func)
                if chain is not None:
                    leaf = chain.rsplit(".", 1)[-1]
                    if leaf in self.valves:
                        flushes.append(node.lineno)
                    elif (
                        leaf in self.buffered_read_methods
                        and ".state." in f".{chain}."
                    ):
                        reads.append(_Read(node, f"{leaf}()", node.lineno))
                    elif chain.startswith("self.") and chain.count(".") == 1:
                        calls.append((chain.split(".", 1)[1], node.lineno))
            elif isinstance(node, ast.Attribute):
                if node.attr in self.buffered_columns:
                    chain = attr_chain(node)
                    # require the chain to pass through a `.state.` hop so
                    # unrelated attributes sharing a column name elsewhere
                    # in the tree do not alias into the invariant
                    if chain is not None and (
                        ".state." in chain or chain.startswith("state.")
                    ):
                        reads.append(_Read(node, node.attr, node.lineno))
        return reads, sorted(flushes), calls

    def _uncovered(self, func) -> tuple[list[_Read], list[tuple[str, int]], bool]:
        """Reads not preceded (in source order) by a flush, the call
        sites with a flag for whether a flush precedes them, and whether
        the function flushes at all."""
        reads, flushes, calls = self._scan(func)
        first_flush = flushes[0] if flushes else None
        uncovered = [
            r for r in reads if first_flush is None or r.order < first_flush
        ]
        call_flags = [
            (name, first_flush is not None and line >= first_flush)
            for name, line in calls
        ]
        return uncovered, call_flags, bool(flushes)

    # ------------------------------------------------------- owner class

    def _check_owner_class(self, ctx: FileContext, cls: ast.ClassDef) -> list[Finding]:
        methods = {
            f.name: f for f in cls.body
            if isinstance(f, (ast.FunctionDef, ast.AsyncFunctionDef))
        }
        info = {}
        for name, func in methods.items():
            if name in self.valves or name == "__init__":
                continue
            info[name] = self._uncovered(func)

        # fixpoint: can a method be ENTERED with a dirty buffer?
        # public -> yes (external callers make no promise); private ->
        # only if some caller reaches its call site without flushing.
        dirty_entry = {
            name: not name.startswith("_") for name in info
        }
        for _ in range(len(info) + 1):
            changed = False
            for name in info:
                if dirty_entry[name]:
                    continue
                entered_dirty = False
                for caller, (_, call_flags, _) in info.items():
                    for callee, flushed_before in call_flags:
                        if callee == name and dirty_entry.get(caller, False) \
                                and not flushed_before:
                            entered_dirty = True
                if entered_dirty:
                    dirty_entry[name] = True
                    changed = True
            if not changed:
                break

        findings = []
        for name, (uncovered, _, _) in sorted(info.items()):
            if not dirty_entry.get(name, True):
                continue
            func = methods[name]
            for read in uncovered:
                findings.append(ctx.make_finding(
                    "FLUSH001",
                    read.node,
                    (
                        f"read of buffered column/state '{read.what}' with no "
                        f"prior flush valve in a context reachable with a "
                        f"dirty _piece_buf — call flush_piece_reports() (or "
                        f"_absorb_piece_reports()) before reading"
                    ),
                    symbol=f"{cls.name}.{name}",
                    def_line=func.lineno,
                ))
            findings.extend(self._check_buffer_reads(ctx, cls.name, name, func))
        return findings

    def _check_buffer_reads(self, ctx, cls_name, name, func) -> list[Finding]:
        """FLUSH002: direct reads of the buffer outside the valves."""
        if name in self.valves:
            return []
        # producer idiom is allowed: `self._piece_buf.append/extend(...)`
        producer_nodes: set[int] = set()
        for node in ast.walk(func):
            if (
                isinstance(node, ast.Call)
                and isinstance(node.func, ast.Attribute)
                and node.func.attr in ("append", "extend")
                and isinstance(node.func.value, ast.Attribute)
                and node.func.value.attr == self.buffer_attr
            ):
                producer_nodes.add(id(node.func.value))
        out = []
        for node in ast.walk(func):
            if not (isinstance(node, ast.Attribute) and node.attr == self.buffer_attr):
                continue
            if id(node) in producer_nodes:
                continue
            out.append(ctx.make_finding(
                "FLUSH002",
                node,
                (
                    f"direct access to {self.buffer_attr} outside the flush "
                    f"valves — only the valves may consume or inspect the "
                    f"buffer (producers use the append/extend enqueue paths)"
                ),
                symbol=f"{cls_name}.{name}",
                def_line=func.lineno,
            ))
        return out

    # ------------------------------------------- non-owner scopes

    def _check_plain_scope(self, ctx: FileContext, cls: ast.ClassDef) -> list[Finding]:
        findings = []
        for func in cls.body:
            if isinstance(func, (ast.FunctionDef, ast.AsyncFunctionDef)):
                findings.extend(self._check_function(
                    ctx, func, symbol=f"{cls.name}.{func.name}"
                ))
        return findings

    def _check_function(self, ctx: FileContext, func, symbol: str) -> list[Finding]:
        uncovered, _, _ = self._uncovered(func)
        return [
            ctx.make_finding(
                "FLUSH001",
                read.node,
                (
                    f"read of buffered column/state '{read.what}' without a "
                    f"prior flush valve — buffered piece reports may not yet "
                    f"be visible in the SoA columns; call "
                    f"service.flush_piece_reports() first"
                ),
                symbol=f"{symbol}",
                def_line=func.lineno,
            )
            for read in uncovered
        ]
