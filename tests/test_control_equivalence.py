"""Selection-equivalence regression (PR 8 acceptance): the vectorised
control plane (columnar candidate fill + grouped DAG apply + batched
report ingest) produces IDENTICAL parent selections to the per-peer loop
path, decision-for-decision, on paired seeded simulator runs — pinned
for two scenario-lab topologies plus the scenario-less replay.

Both paths share one candidate sampler (scheduler._sample_rows), so a
paired seed yields the same candidate sets; from there every filter,
legality check, score and DAG accept must agree or the runs diverge
within a round (selections feed back into swarm state).
"""

from __future__ import annotations

import pytest

from dragonfly2_tpu.cluster.scheduler import SchedulerService
from dragonfly2_tpu.cluster.simulator import ClusterSimulator
from dragonfly2_tpu.config.config import Config
from dragonfly2_tpu.scenarios import builtin_scenarios


def _run(vectorized: bool, scenario, seed: int, rounds: int = 10):
    cfg = Config()
    cfg.scheduler.vectorized_control = vectorized
    # pin the numpy oracle: THIS test is the vectorised-vs-loop pairing;
    # the device-resident fused tick has its own equivalence suite
    # against the vectorised oracle (tests/test_fused_tick.py)
    cfg.scheduler.fused_tick = False
    svc = SchedulerService(config=cfg, seed=seed + 100)
    sim = ClusterSimulator(
        svc, num_hosts=40, num_tasks=5, seed=seed,
        scenario=scenario, deterministic_peer_ids=True,
    )
    selections = []
    for _ in range(rounds):
        for resp in sim.run_round(new_downloads=5):
            if hasattr(resp, "candidate_parents"):
                selections.append((
                    resp.peer_id,
                    tuple((p.peer_id, round(p.score, 6))
                          for p in resp.candidate_parents),
                ))
    return selections, sim.stats


@pytest.mark.parametrize("topology", [None, "bandwidth_skew", "chaos"])
def test_vectorized_matches_per_peer_selections(topology):
    scenario = builtin_scenarios()[topology] if topology else None
    for seed in (3, 17):
        vec, st_vec = _run(True, scenario, seed)
        loop, st_loop = _run(False, scenario, seed)
        assert vec, f"no selections produced (topology={topology})"
        assert vec == loop, (
            f"vectorized/per-peer divergence on topology={topology} "
            f"seed={seed}: first mismatch "
            f"{next((a, b) for a, b in zip(vec, loop) if a != b)}"
        )
        # the downstream replay stayed paired too
        assert st_vec.pieces == st_loop.pieces
        assert st_vec.completed == st_loop.completed
        assert st_vec.piece_cost_ns_total == st_loop.piece_cost_ns_total
