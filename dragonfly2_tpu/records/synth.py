"""Seeded synthetic cluster + trace generator.

Stands in for a live cluster when unit-testing and benchmarking: produces
``DownloadRecord``/``NetworkTopologyRecord`` streams with the same shape and
value ranges the reference's scheduler emits (scheduler/service/
service_v1.go:1418-1632 createDownloadRecord; networktopology
snapshot network_topology.go:386-497), with a *planted ground truth*: each
host has a latent "quality" and pairwise RTT drawn from an IDC-structured
model, so learned rankers/regressors have signal to recover and tests can
assert convergence.
"""

from __future__ import annotations

import dataclasses
import random

from dragonfly2_tpu.records.schema import (
    DestHostRecord,
    DownloadRecord,
    HostRecord,
    NetworkStat,
    NetworkTopologyRecord,
    ParentRecord,
    PieceRecord,
    ProbesRecord,
    SrcHostRecord,
    TaskRecord,
)
from dragonfly2_tpu.utils import idgen

IDCS = ["idc-a", "idc-b", "idc-c", "idc-d"]
REGIONS = ["as", "eu", "na"]

NS_PER_MS = 1_000_000


@dataclasses.dataclass
class SynthHost:
    id: str
    hostname: str
    ip: str
    idc: str
    location: str
    is_seed: bool
    quality: float          # latent upload quality in (0, 1)
    upload_count: int
    upload_failed_count: int
    concurrent_upload_limit: int
    concurrent_upload_count: int


@dataclasses.dataclass
class SynthCluster:
    hosts: list[SynthHost]
    rng: random.Random

    def host_record(self, h: SynthHost, now_ns: int) -> HostRecord:
        return HostRecord(
            id=h.id,
            type="super" if h.is_seed else "normal",
            hostname=h.hostname,
            ip=h.ip,
            port=8002,
            download_port=8001,
            os="linux",
            platform="ubuntu",
            concurrent_upload_limit=h.concurrent_upload_limit,
            concurrent_upload_count=h.concurrent_upload_count,
            upload_count=h.upload_count,
            upload_failed_count=h.upload_failed_count,
            network=NetworkStat(
                tcp_connection_count=int(self.rng.uniform(10, 500)),
                upload_tcp_connection_count=int(self.rng.uniform(0, 100)),
                location=h.location,
                idc=h.idc,
            ),
            scheduler_cluster_id=1,
            created_at=now_ns,
            updated_at=now_ns,
        )

    def rtt_ns(self, src: SynthHost, dst: SynthHost) -> int:
        """IDC-structured latent RTT: ~0.5ms same IDC, ~5ms same region, ~60ms cross."""
        src_region, dst_region = src.location.split("|")[0], dst.location.split("|")[0]
        if src.idc == dst.idc:
            base = 0.5
        elif src_region == dst_region:
            base = 5.0
        else:
            base = 60.0
        jitter = self.rng.lognormvariate(0.0, 0.3)
        return max(1, int(base * jitter * NS_PER_MS))


def make_cluster(num_hosts: int, seed: int = 0, seed_peer_fraction: float = 0.05) -> SynthCluster:
    rng = random.Random(seed)
    hosts = []
    for i in range(num_hosts):
        idc = rng.choice(IDCS)
        region = rng.choice(REGIONS)
        location = f"{region}|zone-{rng.randint(0, 3)}|rack-{rng.randint(0, 15)}"
        hostname = f"host-{i}"
        ip = f"10.{(i >> 16) & 255}.{(i >> 8) & 255}.{i & 255}"
        upload_count = rng.randint(0, 5000)
        hosts.append(
            SynthHost(
                id=idgen.host_id_v2(ip, hostname),
                hostname=hostname,
                ip=ip,
                idc=idc,
                location=location,
                is_seed=rng.random() < seed_peer_fraction,
                quality=rng.betavariate(4, 2),
                upload_count=upload_count,
                upload_failed_count=int(upload_count * rng.random() * 0.3),
                concurrent_upload_limit=50,
                concurrent_upload_count=rng.randint(0, 50),
            )
        )
    return SynthCluster(hosts=hosts, rng=rng)


def gen_download_records(
    cluster: SynthCluster,
    num_records: int,
    num_tasks: int = 64,
    max_parents: int = 20,
    max_pieces: int = 10,
) -> list[DownloadRecord]:
    """Peer download traces: parent piece-serving cost correlates with the
    parent host's latent quality and RTT to the child — the signal the
    GraphSAGE ranker should learn."""
    rng = cluster.rng
    now_ns = 1_700_000_000 * 1_000_000_000
    tasks = []
    for t in range(num_tasks):
        url = f"https://example.com/objects/blob-{t}.bin"
        piece_count = rng.randint(4, 512)
        tasks.append(
            TaskRecord(
                id=idgen.task_id_v2(url, tag="synth", application="bench", piece_length=4 << 20),
                url=url,
                type="standard",
                content_length=piece_count * (4 << 20),
                total_piece_count=piece_count,
                back_to_source_limit=3,
                state="Succeeded",
                created_at=now_ns,
                updated_at=now_ns,
            )
        )

    records = []
    for _ in range(num_records):
        task = rng.choice(tasks)
        child = rng.choice(cluster.hosts)
        n_parents = rng.randint(1, max_parents)
        parents = []
        for _ in range(n_parents):
            parent_host = rng.choice(cluster.hosts)
            if parent_host.id == child.id:
                continue
            rtt = cluster.rtt_ns(child, parent_host)
            n_pieces = rng.randint(1, max_pieces)
            pieces = []
            for _ in range(n_pieces):
                # piece cost ~ rtt + bandwidth term scaled by inverse quality
                service_ms = (4 << 20) / (max(parent_host.quality, 0.05) * 100e6) * 1e3
                cost = int(rtt + service_ms * rng.lognormvariate(0.0, 0.25) * NS_PER_MS)
                pieces.append(PieceRecord(length=4 << 20, cost=cost, created_at=now_ns))
            finished = sum(p.length for p in pieces)
            parents.append(
                ParentRecord(
                    id=idgen.peer_id_v2(),
                    tag="synth",
                    application="bench",
                    state="Succeeded",
                    cost=sum(p.cost for p in pieces),
                    upload_piece_count=len(pieces),
                    finished_piece_count=rng.randint(
                        min(len(pieces), task.total_piece_count), task.total_piece_count
                    ),
                    host=cluster.host_record(parent_host, now_ns),
                    pieces=pieces,
                    created_at=now_ns,
                    updated_at=now_ns,
                )
            )
            del finished
        records.append(
            DownloadRecord(
                id=idgen.peer_id_v2(),
                tag="synth",
                application="bench",
                state="Succeeded",
                cost=max((p.cost for p in parents), default=0),
                finished_piece_count=task.total_piece_count,
                task=task,
                host=cluster.host_record(child, now_ns),
                parents=parents,
                created_at=now_ns,
                updated_at=now_ns,
            )
        )
    return records


def gen_network_topology_records(
    cluster: SynthCluster,
    num_records: int,
    max_dest_hosts: int = 5,
) -> list[NetworkTopologyRecord]:
    rng = cluster.rng
    now_ns = 1_700_000_000 * 1_000_000_000
    records = []
    for i in range(num_records):
        src = rng.choice(cluster.hosts)
        dests = rng.sample([h for h in cluster.hosts if h.id != src.id],
                           k=min(max_dest_hosts, len(cluster.hosts) - 1))
        dest_records = []
        for dst in dests:
            rtt = cluster.rtt_ns(src, dst)
            dest_records.append(
                DestHostRecord(
                    id=dst.id,
                    type="super" if dst.is_seed else "normal",
                    hostname=dst.hostname,
                    ip=dst.ip,
                    port=8002,
                    network=NetworkStat(location=dst.location, idc=dst.idc),
                    probes=ProbesRecord(average_rtt=rtt, created_at=now_ns, updated_at=now_ns),
                )
            )
        records.append(
            NetworkTopologyRecord(
                id=f"nt-{i}",
                host=SrcHostRecord(
                    id=src.id,
                    type="super" if src.is_seed else "normal",
                    hostname=src.hostname,
                    ip=src.ip,
                    port=8002,
                    network=NetworkStat(location=src.location, idc=src.idc),
                ),
                dest_hosts=dest_records,
                created_at=now_ns,
            )
        )
    return records
