"""Perf observatory — cost-card ledger (telemetry/costcard.py).

Pins the capture contract end to end: every SERVING_JIT_REGISTRY entry
and the trainer epoch step gets a per-(entry, signature) CostCard whose
memory_analysis numbers match the pack layout byte-for-byte; capture is
queued at first compile but only MATERIALIZES at an off-hot-path drain
(warmup / flight dump), and the capture itself routes ZERO new compile
signatures through the serving wrappers (the retrace-tripwire
guarantee)."""

import numpy as np
import jax
import pytest

from dragonfly2_tpu.cluster.scheduler import _EVAL_BUCKETS, SchedulerService
from dragonfly2_tpu.config.config import Config, TrainerConfig
from dragonfly2_tpu.ops import evaluator as ev
from dragonfly2_tpu.telemetry import costcard, flight
from dragonfly2_tpu.telemetry.costcard import CostCard


def _service(**overrides):
    cfg = Config()
    cfg.scheduler.max_hosts = 64
    cfg.scheduler.max_tasks = 8
    for key, value in overrides.items():
        setattr(cfg.scheduler, key, value)
    return SchedulerService(config=cfg)


def _bucket_layout_totals(svc):
    from dragonfly2_tpu.records.features import CandidateFeatures

    k = svc.config.scheduler.filter_parent_limit
    fd = CandidateFeatures.zeros(1, k, svc.state.piece_cost_capacity).as_dict()
    c = fd["piece_costs"].shape[-1]
    l = fd["parent_location"].shape[-1]
    n = fd["numeric"].shape[-1]
    return {
        bsz: ev._packed_layout(bsz, k, c, l, n)[1] for bsz in _EVAL_BUCKETS
    }


# ------------------------------------------------------- serving coverage


def test_warmup_captures_a_card_per_bucket_signature():
    """SERVING_JIT_REGISTRY coverage, default path: after warmup every
    bucket's compiled signature has a card, and the card's argument
    bytes equal the pack layout EXACTLY (the one-H2D transport contract
    checked against the compiler instead of asserted in comments)."""
    svc = _service()
    svc.warmup()  # drains pending captures by design
    cards = costcard.ledger().cards("scheduler.evaluator.schedule_from_packed")
    by_arg_bytes = {c.argument_bytes: c for c in cards}
    for bucket, total in _bucket_layout_totals(svc).items():
        card = by_arg_bytes.get(total)
        assert card is not None, (
            f"no cost card for bucket {bucket} (arg bytes {total}); "
            f"have {sorted(by_arg_bytes)}"
        )
        assert card.flops > 0
        assert card.bytes_accessed > 0
        limit = svc.config.scheduler.candidate_parent_limit
        assert card.output_bytes == 4 * bucket * limit * 2  # packed f32 sel


def test_ml_serving_entry_captures_cards(tmp_path):
    """SERVING_JIT_REGISTRY coverage, ml path: the fused ml program and
    the embed program get cards too (captured from avals — the pending
    note must not pin the params/table snapshot)."""
    from dragonfly2_tpu.models import GraphSAGERanker
    from dragonfly2_tpu.records.features import CandidateFeatures
    from dragonfly2_tpu.registry import MLEvaluator, ModelRegistry, ModelServer
    from dragonfly2_tpu.registry.registry import MODEL_TYPE_GNN, ModelEvaluation
    from dragonfly2_tpu.state.fsm import PeerState

    rng = np.random.default_rng(0)
    n_nodes = 64
    graph = {
        "node_feats": rng.normal(size=(n_nodes, 12)).astype(np.float32),
        "edge_src": rng.integers(0, n_nodes - 1, 128).astype(np.int32),
        "edge_dst": rng.integers(0, n_nodes - 1, 128).astype(np.int32),
        "edge_feats": rng.normal(size=(128, 2)).astype(np.float32),
    }
    model = GraphSAGERanker(hidden_dim=16)
    params = model.init(
        jax.random.key(0), graph, np.zeros(4, np.int32),
        (np.arange(16, dtype=np.int32).reshape(4, 4) % n_nodes),
        np.zeros((4, 4, 2), np.float32),
    )
    reg = ModelRegistry(tmp_path)
    server = ModelServer(reg, "ranker", "h", MODEL_TYPE_GNN,
                         template_params=params)
    mv = reg.create_model_version(
        "ranker", MODEL_TYPE_GNN, "h", params, ModelEvaluation(),
        metadata={"hidden_dim": 16},
    )
    reg.activate(mv.model_id, mv.version)
    assert server.refresh()
    evaluator = MLEvaluator(server)
    try:
        evaluator.refresh_embeddings(dict(graph), wait=True)
        feats = CandidateFeatures.zeros(64, 8)
        feats.valid[:] = True
        feats.peer_state[:] = int(PeerState.SUCCEEDED)
        feats.upload_limit[:] = 10
        fd = feats.as_dict()
        buf = ev.pack_eval_batch(
            fd,
            child_host_slot=np.zeros(64, np.int32),
            cand_host_slot=np.zeros((64, 8), np.int32),
        )
        c = fd["piece_costs"].shape[-1]
        l = fd["parent_location"].shape[-1]
        n = fd["numeric"].shape[-1]
        np.asarray(evaluator.schedule_from_packed(buf, 64, 8, c, l, n))
        costcard.capture_pending()
    finally:
        evaluator.close()
    led = costcard.ledger()
    assert led.cards("scheduler.ml.schedule_from_packed"), (
        "no cost card for the fused ml serving program"
    )
    assert led.cards("scheduler.ml.embed_hosts"), (
        "no cost card for the embedding refresh program"
    )
    ml_card = led.cards("scheduler.ml.schedule_from_packed")[-1]
    assert ml_card.flops > 0 and ml_card.argument_bytes > 0


def test_trainer_step_captures_a_card():
    """Trainer coverage: train_gnn registers the epoch program's card
    from the SAME lowering its FLOP accounting already pays for, and the
    card's FLOPs agree with the hand matmul floor to within the bench's
    documented tolerance band."""
    from dragonfly2_tpu.records import synth
    from dragonfly2_tpu.training.train import train_gnn

    cluster = synth.make_cluster(64, seed=0)
    ds, graph = synth.gen_ranking_dataset(cluster, 512)
    result = train_gnn(ds, graph, TrainerConfig(
        hidden_dim=16, batch_size=64, epochs=2,
    ))
    cards = costcard.ledger().cards("trainer.trainer.epoch_indexed")
    assert cards, "train_gnn registered no trainer cost card"
    card = max(cards, key=lambda c: c.flops)
    assert card.flops > 0 and card.bytes_accessed > 0
    # same numbers one level up: TrainResult.flops_per_sample came from
    # this card (flops / trained samples)
    assert result.flops_per_sample > 0
    # agreement vs the analytic matmul floor: order-of-magnitude sanity
    # (backends under/over-count differently; the bench publishes the
    # exact ratio with its tolerance — here we pin it's not garbage)
    ratio = result.flops_per_sample / result.analytic_flops_per_sample
    assert 0.05 < ratio < 20, ratio


# ----------------------------------------------------- capture discipline


def test_capture_adds_zero_new_compile_signatures():
    """The tripwire guarantee: draining pending captures lowers from
    avals through the AOT path and never CALLS the serving wrapper, so
    the wrapper's observed-signature set — what the retrace tripwire
    validates — is identical before and after."""
    svc = _service()
    svc.warmup()
    wrapper = flight.jit_wrappers()["scheduler.evaluator.schedule_from_packed"]
    seen_before = set(wrapper._seen)
    calls_before = wrapper.stats()["calls"]
    costcard.capture_pending()  # idempotent re-drain
    flight.dump()               # the other drain surface
    assert set(wrapper._seen) == seen_before
    assert wrapper.stats()["calls"] == calls_before


def test_pending_note_stores_avals_not_buffers():
    """A pending note must hold ShapeDtypeStructs, never live arrays —
    retaining a donated staging buffer or a table snapshot until the
    next drain would pin memory and re-trace data as constants."""
    led = costcard.CostCardLedger()

    @jax.jit
    def f(x):
        return x * 2.0

    x = np.ones((8, 8), np.float32)
    led.note_pending("test.avals", f.lower, (x,), {})
    (pending,) = led._pending.values()
    (leaf,) = jax.tree_util.tree_leaves(pending.args)
    assert isinstance(leaf, jax.ShapeDtypeStruct)
    # and the capture still compiles + analyzes from the avals alone
    del x
    (card,) = led.capture_pending()
    assert card.entry == "test.avals"
    assert card.output_bytes == 8 * 8 * 4


def test_distinct_static_kwarg_values_get_distinct_cards():
    """Two compiles differing only in a static KWARG value (the
    evaluator's algorithm='default' vs 'nt' at identical shapes) are
    distinct programs and must keep distinct cards — the signature
    digest covers kwarg VALUES, not just names."""
    import functools

    led = costcard.CostCardLedger()

    @functools.partial(jax.jit, static_argnames=("mode",))
    def f(x, mode="a"):
        return x + 1 if mode == "a" else x * 2

    x = np.ones((4,), np.float32)
    led.note_pending("test.kw", f.lower, (x,), {"mode": "a"})
    led.note_pending("test.kw", f.lower, (x,), {"mode": "b"})
    cards = led.capture_pending()
    assert len(cards) == 2
    assert len({c.signature for c in cards}) == 2


def test_capture_errors_are_recorded_not_raised():
    led = costcard.CostCardLedger()

    class Boom:
        def lower(self, *a, **k):
            raise RuntimeError("no AOT on this backend")

    led.note_pending("test.boom", Boom().lower, (np.ones(2, np.float32),), {})
    assert led.capture_pending() == []
    dump = led.dump()
    assert dump["cards"] == []
    (err,) = dump["capture_errors"].values()
    assert "RuntimeError" in err


# ------------------------------------------------------------- verdicts


def test_costcard_roofline_verdicts():
    card = CostCard(
        entry="e", signature="s", signature_repr="r",
        flops=1e9, bytes_accessed=1e6, transcendentals=0,
        argument_bytes=500_000, output_bytes=1000, temp_bytes=2000,
        generated_code_bytes=0,
    )
    # AI = 1000 flops/byte, far above the v5e ridge (~240) -> compute
    assert card.arithmetic_intensity() == 1000.0
    assert card.bound() == "compute"
    mem = CostCard(
        entry="e", signature="s2", signature_repr="r",
        flops=1e6, bytes_accessed=1e9, transcendentals=0,
        argument_bytes=0, output_bytes=0, temp_bytes=0,
        generated_code_bytes=0,
    )
    assert mem.bound() == "memory"
    # measured-time MFU: 1e9 flops in 1 ms on a 197 TF chip
    assert card.mfu_pct(1e-3) == pytest.approx(
        100.0 * 1e9 / (197.0e12 * 1e-3)
    )
    # roofline floor: memory-bound program's floor is bytes/bw
    assert mem.time_lower_bound_s() == pytest.approx(1e9 / 819.0e9)


def test_dump_and_gauges_export():
    """Cards land in /debug/flight and as dragonfly_costcard_* gauges."""
    from dragonfly2_tpu.telemetry.metrics import default_registry

    svc = _service()
    svc.warmup()
    dump = flight.dump()
    assert dump["costcards"]["cards"], "flight dump carries no cost cards"
    entries = {c["entry"] for c in dump["costcards"]["cards"]}
    assert "scheduler.evaluator.schedule_from_packed" in entries
    text = default_registry().expose()
    assert "# TYPE dragonfly_costcard_flops gauge" in text
    assert 'dragonfly_costcard_flops{entry="scheduler.evaluator' in text
