"""Interval-driven GC task runner.

Capability parity with pkg/gc/gc.go:28-63: named tasks with an interval,
timeout, and runner; Add/Run/RunAll/Start/Stop. Used by cluster state TTL
reclamation and the client piece store, the same seams the reference wires
it into (scheduler resource managers, client storage).
"""

from __future__ import annotations

import dataclasses
import logging
import threading
from typing import Callable, Protocol

logger = logging.getLogger(__name__)


class Runner(Protocol):
    def run_gc(self) -> None: ...


@dataclasses.dataclass
class Task:
    id: str
    interval: float  # seconds
    timeout: float
    runner: Callable[[], None]

    def validate(self) -> None:
        if not self.id:
            raise ValueError("gc task requires an id")
        if self.interval <= 0:
            raise ValueError(f"gc task {self.id}: interval must be positive")
        if self.timeout <= 0 or self.timeout > self.interval:
            raise ValueError(f"gc task {self.id}: need 0 < timeout <= interval")


class GC:
    def __init__(self):
        self._tasks: dict[str, Task] = {}
        self._lock = threading.Lock()
        self._threads: list[threading.Thread] = []
        self._stop = threading.Event()
        self._started = False

    def add(self, task: Task) -> None:
        task.validate()
        with self._lock:
            if task.id in self._tasks:
                raise ValueError(f"gc task {task.id} already registered")
            self._tasks[task.id] = task
        if self._started:
            self._spawn(task)

    def run(self, task_id: str) -> None:
        with self._lock:
            task = self._tasks.get(task_id)
        if task is None:
            raise KeyError(f"gc task {task_id} not found")
        self._run_one(task)

    def run_all(self) -> None:
        with self._lock:
            tasks = list(self._tasks.values())
        for task in tasks:
            self._run_one(task)

    def start(self) -> None:
        with self._lock:
            if self._started:
                return
            self._started = True
            tasks = list(self._tasks.values())
        for task in tasks:
            self._spawn(task)

    def stop(self) -> None:
        self._stop.set()
        for t in self._threads:
            t.join(timeout=1.0)
        self._threads.clear()
        # Reset so the runner can be started again (tasks stay registered).
        self._stop = threading.Event()
        with self._lock:
            self._started = False

    # ------------------------------------------------------------ internal

    def _spawn(self, task: Task) -> None:
        t = threading.Thread(
            target=self._loop, args=(task, self._stop), daemon=True, name=f"gc-{task.id}"
        )
        t.start()
        self._threads.append(t)

    def _loop(self, task: Task, stop: threading.Event) -> None:
        while not stop.wait(task.interval):
            self._run_one(task)

    def _run_one(self, task: Task) -> None:
        # The runner gets a watchdog thread instead of the reference's
        # context deadline; an overrun is logged, not killed (no safe way to
        # kill a Python thread), which matches -what- the timeout is for:
        # flagging stuck GC, not resource enforcement.
        done = threading.Event()

        def run():
            try:
                task.runner()
            except Exception:  # noqa: BLE001 - GC must never take down the host loop
                logger.exception("gc task %s failed", task.id)
            finally:
                done.set()

        worker = threading.Thread(target=run, daemon=True, name=f"gc-run-{task.id}")
        worker.start()
        if not done.wait(task.timeout):
            logger.warning("gc task %s exceeded timeout %.1fs", task.id, task.timeout)
