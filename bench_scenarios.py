"""Scenario-matrix A/B bench: where does the learned evaluator win?

Runs {default, ml, random} (optionally nt) evaluators across the
scenario grid (homogeneous control, bandwidth-skewed racks/spine/NICs,
churn, flaky parents, corrupting parents (digest-verified -> quarantine),
hotspot Zipf, control-plane chaos — scenarios/spec.builtin_scenarios)
with PAIRED seeds, and writes
`BENCH_scenarios.json`: per-scenario
`ml_vs_default` piece-cost ratios with 95% confidence intervals, per-arm
injected-fault counts, and the flight-recorder per-phase tick timings.
The ml arm serves a GNN trained on traces from a scenario-driven replay
(the full schedule→trace→train→serve loop, scenarios/ab.py).

ml_vs_default > 1 means the served model picks cheaper parents than the
rule blend in that scenario; `resolvable` means the CI excludes 1.0 —
a measured gap in either direction, not a guaranteed win.

Prints one JSON line per scenario plus a final compact summary line.

Usage: python bench_scenarios.py [--quick] [--hosts N] [--pieces N]
       [--tasks N] [--seeds 11,12,13] [--evaluators default,ml,random]
       [--scenarios name1,name2] [--out BENCH_scenarios.json]
"""

from __future__ import annotations

import argparse
import json
import sys
import time


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--hosts", type=int, default=800)
    ap.add_argument("--tasks", type=int, default=16)
    ap.add_argument("--pieces", type=int, default=20_000)
    ap.add_argument("--downloads-per-round", type=int, default=48)
    ap.add_argument("--seeds", default="11,12,13,14,15")
    ap.add_argument("--evaluators", default="default,ml,random")
    ap.add_argument("--scenarios", default="",
                    help="comma-separated builtin names (default: all)")
    ap.add_argument("--train-pieces", type=int, default=30_000)
    ap.add_argument("--trainer-epochs", type=int, default=4)
    ap.add_argument("--hidden-dim", type=int, default=32)
    ap.add_argument("--out", default="BENCH_scenarios.json")
    ap.add_argument("--workdir", default=None)
    ap.add_argument("--quick", action="store_true",
                    help="small smoke configuration (CI-sized)")
    args = ap.parse_args()
    if args.quick:
        args.hosts, args.tasks, args.pieces = 128, 8, 2500
        args.train_pieces, args.trainer_epochs = 4000, 2
        args.seeds = "11,12"

    from dragonfly2_tpu.scenarios import builtin_scenarios
    from dragonfly2_tpu.scenarios.ab import MatrixConfig, run_matrix

    scenarios = builtin_scenarios()
    if args.scenarios:
        keep = {s.strip() for s in args.scenarios.split(",") if s.strip()}
        unknown = keep - set(scenarios)
        if unknown:
            raise SystemExit(f"unknown scenarios: {sorted(unknown)}")
        scenarios = {k: v for k, v in scenarios.items() if k in keep}

    cfg = MatrixConfig(
        hosts=args.hosts,
        tasks=args.tasks,
        target_pieces=args.pieces,
        downloads_per_round=args.downloads_per_round,
        seeds=tuple(int(s) for s in args.seeds.split(",")),
        evaluators=tuple(e.strip() for e in args.evaluators.split(",")),
        train_pieces=args.train_pieces,
        trainer_epochs=args.trainer_epochs,
        hidden_dim=args.hidden_dim,
    )

    t0 = time.perf_counter()
    result = run_matrix(scenarios, cfg, workdir=args.workdir,
                        log=lambda line: print(f"# {line}", file=sys.stderr))
    result["bench_wall_s"] = round(time.perf_counter() - t0, 1)

    # one JSON line per scenario (driver-friendly), then a compact summary
    for name, s in result["scenarios"].items():
        line = {
            "metric": "scenario_ab",
            "scenario": name,
            "mean_piece_cost_ms": s["mean_piece_cost_ms"],
        }
        for key in ("ml_vs_default", "default_vs_random", "nt_vs_default"):
            if key in s:
                line[key] = {k: s[key][k] for k in ("mean", "ci95", "resolvable")}
        print(json.dumps(line))
    summary = {
        "metric": "scenario_matrix",
        "scenarios": len(result["scenarios"]),
        "evaluators": list(cfg.evaluators),
        "seeds": list(cfg.seeds),
        "ml_vs_default": {
            name: s["ml_vs_default"]["mean"]
            for name, s in result["scenarios"].items()
            if "ml_vs_default" in s
        },
        "resolvable": sorted(
            name
            for name, s in result["scenarios"].items()
            if any(
                s.get(k, {}).get("resolvable")
                for k in ("ml_vs_default", "default_vs_random", "nt_vs_default")
            )
        ),
        "out": args.out,
        "wall_s": result["bench_wall_s"],
    }
    # the shared schema writer (tools/bench_schema.py): schema_version +
    # platform block join the matrix result (benchwatch validates both
    # this shape and the legacy platform-less one)
    from tools.bench_schema import write_artifact

    write_artifact(args.out, ["python", "bench_scenarios.py"] + sys.argv[1:],
                   summary, extra=result)
    print(json.dumps(summary))
    return 0


if __name__ == "__main__":
    sys.exit(main())
