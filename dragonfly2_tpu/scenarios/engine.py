"""Deterministic scenario engine: (spec, seed) → heterogeneity + faults.

Every stochastic decision is COUNTER-BASED, not stream-based: a decision
is ``u = blake2b(seed, kind, *key) / 2^64`` over a semantic key (host
ids, task index, piece number, attempt number) rather than a draw from a
shared RNG stream. That makes the injected fault schedule a pure function
of (spec, seed, event identity): two runs of the same replay produce the
same schedule even though the surrounding code allocates uuids, runs GC
off wall clocks, or interleaves differently — the determinism contract
the scenario A/B test pins (no ``Date.now``-style nondeterminism can leak
in, because no decision reads a clock or an ordered stream).

The engine serves three consumers:

- ``cluster/simulator.py``: piece costs from the link model, churn and
  flaky-parent events, Zipf task popularity, probe RTTs;
- ``client/upload.py`` via ``FaultInjector``: piece-serving errors and
  stalls injected at a REAL parent daemon, so a child's conductor
  exercises its genuine retry path (DownloadPieceFailedRequest →
  reschedule → blocklist → back-to-source). Verdicts are per-attempt
  deterministic; bit-exact schedule replay additionally needs a
  deterministic serve order (see FaultInjector's docstring);
- ``scenarios/ab.py``: schedule digests for the determinism check.
"""

from __future__ import annotations

import hashlib
import math
import statistics
import threading

from dragonfly2_tpu.scenarios.spec import ScenarioSpec

_U64 = float(1 << 64)
_NORM = statistics.NormalDist()


def _u(seed: int, kind: str, *key) -> float:
    """Deterministic uniform in [0, 1) from (seed, kind, key...)."""
    h = hashlib.blake2b(digest_size=8)
    h.update((f"{seed}:{kind}:" + ":".join(str(k) for k in key)).encode())
    return int.from_bytes(h.digest(), "big") / _U64


def _lognorm(u: float, sigma: float) -> float:
    """Deterministic lognormal(0, sigma) sample from one uniform via the
    inverse-CDF transform (stdlib NormalDist probit)."""
    u = min(max(u, 1e-12), 1.0 - 1e-12)
    return math.exp(sigma * _NORM.inv_cdf(u))


NS_PER_MS = 1_000_000


class ScenarioEngine:
    """Deterministic sampler for one (spec, seed, host population)."""

    def __init__(self, spec: ScenarioSpec, hosts, seed: int = 0):
        """`hosts` is any sequence of objects with ``.id``, ``.idc``,
        ``.location`` (records/synth.SynthHost or equivalents)."""
        self.spec = spec
        self.seed = seed
        self.hosts = list(hosts)
        self._schedule = hashlib.blake2b(digest_size=16)
        self._schedule_events = 0
        link = spec.link

        # ---- per-host assignments: deterministic in host ID, not order
        self.bandwidth: dict[str, float] = {}
        self.flaky_hosts: set[str] = set()
        self._rack: dict[str, str] = {}
        self._region: dict[str, str] = {}
        self._idc: dict[str, str] = {}
        for h in self.hosts:
            bw = link.base_bandwidth_bps
            if link.slow_fraction > 0 and _u(seed, "slow_mode", h.id) < link.slow_fraction:
                bw *= link.slow_multiplier
            self.bandwidth[h.id] = bw
            if (
                spec.flaky.parent_fraction > 0
                and _u(seed, "flaky_host", h.id) < spec.flaky.parent_fraction
            ):
                self.flaky_hosts.add(h.id)
            loc = h.location.split("|")
            self._region[h.id] = loc[0] if loc else ""
            self._rack[h.id] = h.location  # full zone|rack path = the rack key
            self._idc[h.id] = h.idc
        # "one slow NIC": the k hosts with the smallest assignment hash —
        # a deterministic choice independent of host-list order
        if link.slow_nic_count > 0 and self.hosts:
            ranked = sorted(self.hosts, key=lambda h: _u(seed, "slow_nic", h.id))
            for h in ranked[: link.slow_nic_count]:
                self.bandwidth[h.id] = (
                    link.base_bandwidth_bps * link.slow_nic_multiplier
                )
        # per-epoch membership caches (offline/partitioned are pure
        # functions of (spec, seed, epoch) — recomputing them every round
        # is O(hosts) of blake2b at megascale)
        self._offline_cache: tuple[int, set[str]] | None = None
        self._partition_cache: tuple[int, set[str]] | None = None

    # -------------------------------------------------------- link model

    def rtt_ns(self, src, dst, key=()) -> int:
        """IDC/rack-structured RTT with deterministic jitter. `key`
        disambiguates repeated samples of the same pair (probe sequence
        numbers, piece attempts)."""
        link = self.spec.link
        if self._rack.get(src.id) == self._rack.get(dst.id) and src.id != dst.id:
            base = link.same_rack_rtt_ms
        elif self._idc.get(src.id) == self._idc.get(dst.id):
            base = link.same_idc_rtt_ms
        elif self._region.get(src.id) == self._region.get(dst.id):
            base = link.same_region_rtt_ms
        else:
            base = link.cross_region_rtt_ms
        jitter = _lognorm(
            _u(self.seed, "rtt", src.id, dst.id, *key), link.rtt_jitter_sigma
        )
        return max(1, int(base * jitter * NS_PER_MS))

    def pair_bandwidth(self, child, parent) -> float:
        """Effective parent→child bandwidth: the parent NIC's capacity,
        divided by the spine oversubscription when the path crosses
        racks."""
        link = self.spec.link
        bw = self.bandwidth.get(parent.id, link.base_bandwidth_bps)
        if (
            link.spine_oversubscription > 1.0
            and self._rack.get(child.id) != self._rack.get(parent.id)
        ):
            bw /= link.spine_oversubscription
        return max(bw, 1.0)

    def piece_cost_ns(
        self, child, parent, piece_length: int, task_idx: int,
        piece: int, attempt: int,
    ) -> tuple[int, str | None]:
        """(cost_ns, fault) for one piece transfer. fault ∈ {None,
        "error", "stall", "corrupt"}: an error aborts the transfer through
        the retry path; a stall completes but carries the stall in its
        cost; a corrupt transfer completes with WRONG bytes — the child's
        digest verification refuses them and reports reason="corruption"
        (the quarantine path)."""
        key = (task_idx, piece, attempt)
        rtt = self.rtt_ns(child, parent, key=key)
        bw = self.pair_bandwidth(child, parent)
        service_s = piece_length / bw
        jitter = _lognorm(
            _u(self.seed, "svc", child.id, parent.id, *key),
            self.spec.link.bandwidth_jitter_sigma,
        )
        cost = rtt + int(service_s * jitter * 1e9)
        fault = None
        flaky = self.spec.flaky
        if parent.id in self.flaky_hosts:
            roll = _u(self.seed, "flake", child.id, parent.id, *key)
            if roll < flaky.piece_error_rate:
                fault = "error"
            elif roll < flaky.piece_error_rate + flaky.piece_stall_rate:
                fault = "stall"
                cost += int(flaky.stall_seconds * 1e9)
            elif roll < (flaky.piece_error_rate + flaky.piece_stall_rate
                         + flaky.piece_corrupt_rate):
                fault = "corrupt"
            if fault is not None:
                self._record(fault, parent.id, *key)
        return cost, fault

    # ------------------------------------------------------------- churn

    def crash_point(self, registration_index: int, n_pieces: int) -> int | None:
        """Piece count after which this download crashes, or None. Keyed
        on the simulator's deterministic registration counter (peer uuids
        are process-random and MUST NOT key schedule decisions)."""
        churn = self.spec.churn
        if churn.peer_crash_rate <= 0:
            return None
        if _u(self.seed, "crash", registration_index) >= churn.peer_crash_rate:
            return None
        self._record("crash", registration_index)
        return max(1, int(n_pieces * churn.crash_progress))

    def offline_hosts(self, round_idx: int) -> set[str]:
        """Host ids off the announce plane during this round's epoch.
        Membership re-rolls per epoch so hosts flap rather than die.
        Cached per epoch — the membership is a pure function of (spec,
        seed, epoch), and re-hashing every host every round was O(hosts)
        per round at megascale (0.5 s/round at 10^5 hosts). Callers must
        not mutate the returned set."""
        churn = self.spec.churn
        if churn.host_leave_rate <= 0:
            return set()
        epoch = round_idx // max(churn.leave_epoch_rounds, 1)
        if self._offline_cache is not None and self._offline_cache[0] == epoch:
            return self._offline_cache[1]
        out = {
            h.id
            for h in self.hosts
            if _u(self.seed, "leave", epoch, h.id) < churn.host_leave_rate
        }
        self._offline_cache = (epoch, out)
        return out

    # ----------------------------------------------------- control plane

    def scheduler_crashed(self, round_idx: int) -> bool:
        """True exactly on the FIRST round of an epoch whose crash roll
        hit: the scheduler loses its in-memory state and every announce
        stream at once. Deterministic in (spec, seed, epoch) — replays
        crash at identical rounds."""
        control = self.spec.control
        if control.scheduler_crash_rate <= 0:
            return False
        epoch_len = max(control.crash_epoch_rounds, 1)
        if round_idx % epoch_len != 0 or round_idx == 0:
            return False
        epoch = round_idx // epoch_len
        if _u(self.seed, "sched_crash", epoch) >= control.scheduler_crash_rate:
            return False
        self._record("sched_crash", epoch)
        return True

    def crash_rounds(self, rounds: int) -> list[int]:
        """Pure PREVIEW of every round ``scheduler_crashed`` will fire on
        in ``[1, rounds]`` — same (spec, seed, epoch) arithmetic, but
        WITHOUT recording into the schedule digest, so reports and tests
        can annotate a soak timeline's kill schedule up front (the
        megascale engine marks the live events as they land; this is the
        expected-schedule cross-check)."""
        control = self.spec.control
        if control.scheduler_crash_rate <= 0:
            return []
        epoch_len = max(control.crash_epoch_rounds, 1)
        return [
            r for r in range(epoch_len, rounds + 1, epoch_len)
            if _u(self.seed, "sched_crash", r // epoch_len)
            < control.scheduler_crash_rate
        ]

    def scheduler_crash_point(self, task_idx: int, n_pieces: int) -> int | None:
        """Real-socket chaos e2e: the piece count after which the task's
        hashring-primary scheduler is killed, or None when this task's
        crash roll missed. Keyed on the task index so the same (spec,
        seed, task) always kills at the same progress point."""
        control = self.spec.control
        if control.scheduler_crash_rate <= 0:
            return None
        if _u(self.seed, "sched_crash_task", task_idx) >= control.scheduler_crash_rate:
            return None
        self._record("sched_crash_task", task_idx)
        return max(1, min(n_pieces - 1, int(n_pieces * control.crash_progress)))

    def partitioned_hosts(self, round_idx: int) -> set[str]:
        """Hosts whose announce-plane link is silently blackholed this
        epoch: unlike churn's leave/rejoin, the scheduler receives no
        LeaveHost — their requests and its responses just vanish, the
        shape a stateful-firewall drop or asymmetric route takes."""
        control = self.spec.control
        if control.partition_rate <= 0:
            return set()
        epoch = round_idx // max(control.partition_epoch_rounds, 1)
        if self._partition_cache is not None and self._partition_cache[0] == epoch:
            return self._partition_cache[1]
        out = {
            h.id
            for h in self.hosts
            if _u(self.seed, "partition", epoch, h.id) < control.partition_rate
        }
        self._partition_cache = (epoch, out)
        return out

    # ----------------------------------------------- megascale traffic

    def diurnal_multiplier(self, round_idx: int) -> float:
        """Arrival-rate multiplier for this round of the compressed day:
        a raised cosine between trough and peak (trough at round 0, peak
        mid-day). Pure function of (spec, round) — no sampling."""
        traffic = self.spec.traffic
        if traffic.day_rounds <= 0:
            return 1.0
        phase = (round_idx % traffic.day_rounds) / traffic.day_rounds
        lo, hi = traffic.trough_multiplier, traffic.peak_multiplier
        return lo + (hi - lo) * 0.5 * (1.0 - math.cos(2.0 * math.pi * phase))

    def flash_crowds(self, round_idx: int, n_tasks: int) -> list[int]:
        """Hot task ranks under an active flash-crowd storm this round
        (empty = no storm). Each of the day's `events_per_day` storms
        starts at a deterministic (seed, day, event) round and pins
        `hot_tasks` deterministic task ranks for `duration_rounds`."""
        flash = self.spec.flash
        day = self.spec.traffic.day_rounds or max(flash.duration_rounds * 8, 1)
        if flash.events_per_day <= 0 or n_tasks <= 0:
            return []
        d, r = divmod(round_idx, day)
        hot: list[int] = []
        span = max(day - flash.duration_rounds, 1)
        for e in range(flash.events_per_day):
            start = int(_u(self.seed, "flash_start", d, e) * span)
            if start <= r < start + flash.duration_rounds:
                if r == start:
                    self._record("flash", d, e)
                for t in range(flash.hot_tasks):
                    hot.append(int(_u(self.seed, "flash_task", d, e, t) * n_tasks))
        return hot

    def upgrade_window(self, round_idx: int) -> tuple[float, float] | None:
        """Host-order fraction window [lo, hi) currently restarting under
        a rolling-upgrade wave, or None. The window (width =
        `cohort_fraction`) sweeps 0 → 1 across the host order over
        `wave_rounds`; with hosts laid out in contiguous region blocks
        (megascale topology) that is a region-by-region rollout. Wave
        start rounds are deterministic in (seed, day, wave)."""
        upgrade = self.spec.upgrade
        day = self.spec.traffic.day_rounds or max(upgrade.wave_rounds * 2, 1)
        if upgrade.waves_per_day <= 0:
            return None
        d, r = divmod(round_idx, day)
        span = max(day - upgrade.wave_rounds, 1)
        for w in range(upgrade.waves_per_day):
            start = int(_u(self.seed, "upgrade_start", d, w) * span)
            if start <= r < start + upgrade.wave_rounds:
                progress = (r - start) / max(upgrade.wave_rounds, 1)
                lo = progress * (1.0 - upgrade.cohort_fraction)
                return (lo, lo + upgrade.cohort_fraction)
        return None

    def rotated_task_weights(self, n_tasks: int, round_idx: int) -> list[float] | None:
        """Time-varying Zipf popularity for the diurnal traffic model:
        the rank → task assignment rotates `rotate_hot_tasks` times per
        day by a deterministic (seed, rotation-epoch) offset, so WHICH
        content is hot changes through the day while the popularity
        SHAPE stays Zipf(traffic.zipf_alpha). Falls back to the static
        skew weights when the traffic model is off."""
        traffic = self.spec.traffic
        if traffic.day_rounds <= 0 or traffic.zipf_alpha <= 0:
            return self.task_weights(n_tasks)
        base = [
            1.0 / (rank + 1) ** traffic.zipf_alpha for rank in range(n_tasks)
        ]
        if traffic.rotate_hot_tasks > 0:
            phase_len = max(traffic.day_rounds // traffic.rotate_hot_tasks, 1)
            epoch = round_idx // phase_len
            offset = int(_u(self.seed, "task_rotation", epoch) * n_tasks)
            base = [base[(rank + offset) % n_tasks] for rank in range(n_tasks)]
        total = sum(base)
        return [x / total for x in base]

    # ------------------------------------------------------------- skew

    def task_weights(self, n_tasks: int) -> list[float] | None:
        """Zipf popularity weights over task indices (None = uniform)."""
        alpha = self.spec.skew.zipf_alpha
        if alpha <= 0:
            return None
        w = [1.0 / (rank + 1) ** alpha for rank in range(n_tasks)]
        total = sum(w)
        return [x / total for x in w]

    # --------------------------------------------------------- schedule

    def _record(self, kind: str, *key) -> None:
        self._schedule.update(f"{kind}:{':'.join(str(k) for k in key)};".encode())
        self._schedule_events += 1

    def schedule_digest(self) -> str:
        """Hash over every fault/churn event decided so far — two runs of
        the same (spec, seed, replay) must produce identical digests."""
        return f"{self._schedule_events}:{self._schedule.copy().hexdigest()}"

    def fault_injector(self) -> "FaultInjector":
        return FaultInjector(self.spec, seed=self.seed)


class FaultInjector:
    """Piece-serving fault decisions for a REAL parent daemon's upload
    server (client/upload.py): the verdict is a pure function of (task,
    piece, serve-attempt NUMBER), so the first fetch of a piece may error
    while the retry succeeds — and the retry path actually recovers.

    Determinism scope: the bit-exact same-schedule guarantee holds when
    the serve ORDER is itself deterministic (one child per task, or the
    in-proc simulator/matrix path, whose events are counter-hashed).
    With multiple children racing fetches of the same piece over real
    sockets, which request lands attempt 0 vs 1 follows socket timing —
    per-attempt verdicts stay reproducible, attempt attribution does
    not. Attach to a daemon to make it the flaky parent (the engine's
    per-host flaky split does not apply here: the injector IS the flaky
    parent)."""

    def __init__(self, spec: ScenarioSpec, seed: int = 0):
        self.spec = spec
        self.seed = seed
        self.stall_seconds = spec.flaky.stall_seconds
        self._mu = threading.Lock()
        self._attempts: dict[tuple[str, int], int] = {}
        self.injected: dict[str, int] = {"error": 0, "stall": 0, "corrupt": 0}

    def piece_fault(self, task_id: str, piece: int) -> str | None:
        with self._mu:
            attempt = self._attempts.get((task_id, piece), 0)
            self._attempts[(task_id, piece)] = attempt + 1
        flaky = self.spec.flaky
        roll = _u(self.seed, "inj", task_id, piece, attempt)
        if roll < flaky.piece_error_rate:
            verdict = "error"
        elif roll < flaky.piece_error_rate + flaky.piece_stall_rate:
            verdict = "stall"
        elif roll < (flaky.piece_error_rate + flaky.piece_stall_rate
                     + flaky.piece_corrupt_rate):
            verdict = "corrupt"
        else:
            return None
        with self._mu:
            self.injected[verdict] += 1
        return verdict

    def corrupt_bytes(self, task_id: str, piece: int, data: bytes) -> bytes:
        """Deterministically corrupt one piece's bytes (the trust-boundary
        adversary): the SAME (task, piece) always corrupts the same way,
        so replays and the chaos e2e's byte-level assertions are stable.
        "bitflip" flips one deterministic bit; "truncate" drops a
        deterministic 1..64-byte tail. The serving side rewrites its
        advisory digest header to match (a consistent liar) — only the
        scheduler-attested chain catches the result."""
        if not data:
            return data
        mode = self.spec.flaky.corrupt_mode
        u = _u(self.seed, "corrupt_at", task_id, piece)
        if mode == "truncate":
            drop = 1 + int(u * min(len(data) - 1, 63)) if len(data) > 1 else 0
            return data[: len(data) - drop] if drop else b""
        # bitflip (default)
        bit = int(u * len(data) * 8)
        byte_i, bit_i = divmod(bit, 8)
        out = bytearray(data)
        out[byte_i] ^= 1 << bit_i
        return bytes(out)
