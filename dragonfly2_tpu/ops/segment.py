"""Segment reductions — the graph-aggregation primitive.

Where the reference walks pointer DAGs (pkg/graph/dag/dag.go), the TPU
build lowers neighborhood aggregation to `jax.ops.segment_sum` over COO
edge arrays (SURVEY.md §2.6/§7): gather node states at edge endpoints,
reduce by segment id. All wrappers take a static `num_segments` so shapes
stay compile-time constant.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp


@functools.partial(jax.jit, static_argnames=("num_segments",))
def segment_sum(data: jax.Array, segment_ids: jax.Array, num_segments: int) -> jax.Array:
    return jax.ops.segment_sum(data, segment_ids, num_segments=num_segments)


@functools.partial(jax.jit, static_argnames=("num_segments",))
def segment_mean(data: jax.Array, segment_ids: jax.Array, num_segments: int) -> jax.Array:
    totals = jax.ops.segment_sum(data, segment_ids, num_segments=num_segments)
    ones = jnp.ones(data.shape[:1], dtype=data.dtype)
    counts = jax.ops.segment_sum(ones, segment_ids, num_segments=num_segments)
    counts = jnp.maximum(counts, 1)
    if data.ndim > 1:
        counts = counts.reshape((-1,) + (1,) * (data.ndim - 1))
    return totals / counts


@functools.partial(jax.jit, static_argnames=("num_segments",))
def segment_max(data: jax.Array, segment_ids: jax.Array, num_segments: int) -> jax.Array:
    return jax.ops.segment_max(data, segment_ids, num_segments=num_segments)


@functools.partial(jax.jit, static_argnames=("num_segments",))
def segment_count(segment_ids: jax.Array, num_segments: int) -> jax.Array:
    ones = jnp.ones(segment_ids.shape, dtype=jnp.int32)
    return jax.ops.segment_sum(ones, segment_ids, num_segments=num_segments)


def pad_pow2(n: int, min_pad: int = 64) -> int:
    """Min-`min_pad` power-of-two bucket for a count — THE serving-graph
    padding policy. The producer (scheduler.serving_graph_arrays: node
    and edge padding, whose last node row is the zero-feature sink) and
    the consumer (gather_coo_subgraph below) must bucket identically or
    the full-refresh and incremental jit caches silently diverge."""
    import numpy as np

    return max(min_pad, 1 << int(np.ceil(np.log2(max(n, 1)))))


# ------------------------------------------------------- subgraph gathering
#
# Host-side companion to the segment reductions above: the incremental
# serving-embedding refresh (registry/serving.py) recomputes only the
# dirty hosts' k-hop in-neighborhoods. This helper cuts that neighborhood
# out of the full COO arrays as a LOCALLY-indexed subgraph whose
# node/edge/target counts are padded to power-of-two buckets, so the
# jitted `GraphSAGERanker.embed_subset` program compiles once per bucket
# instead of once per frontier.


def gather_coo_subgraph(
    edge_src,  # (E,) int array-like
    edge_dst,  # (E,) int array-like
    dirty,     # (D,) int array-like — frontier node ids
    num_nodes: int,
    hops: int = 2,
    max_frac: float = 0.25,
    min_pad: int = 64,
):
    """Gather the subgraph needed to recompute `hops`-layer GNN embeddings
    of every node whose embedding is affected by the `dirty` input nodes.

    Aggregation for node v rides edges with src == v gathering dst
    (SAGELayer), so v READS its out-neighbors: the TARGET set (nodes
    whose embeddings change when `dirty` inputs change) expands
    REVERSE (dst->src: dependents of the dirty nodes), while the
    SUPPORT set (nodes whose features the recompute reads) expands
    FORWARD (src->dst) from the targets. On the serving graph the two
    coincide (serving_graph_arrays stores every edge in both
    directions), but the directed semantics are what make this helper
    correct for any COO graph. Every edge a target's layer-i value
    consumes has src inside the forward-(k-1)-ball of the targets,
    which the both-endpoints-in-support keep rule covers.

    Precondition: row `num_nodes - 1` is a sacrificial sink (the serving
    graph's zero-feature padding row, scheduler.serving_graph_arrays).
    Padding nodes alias it and padding edges are self-loops on it, so
    only the sink's (never-served) embedding absorbs the padding — a
    graph whose last row were a real node would see that row's aggregate
    polluted.

    Returns None when the support set exceeds `max_frac` of the graph —
    the caller falls back to a full recompute (the gather would not pay
    for itself). Otherwise returns a dict of numpy arrays:
      nodes         (Ns,) int32  global ids of subgraph nodes (padding
                                 rows point at `num_nodes - 1`, the
                                 serving graph's zero-feature sink)
      edge_src/dst  (Es,) int32  LOCAL endpoint indices (padding edges
                                 are sink self-loops)
      edge_index    (Es,) int64  indices into the FULL edge arrays for
                                 gathering edge features (padding -> 0,
                                 masked by sink endpoints)
      edge_pad      (Es,) bool   True on padding edges (zero their feats)
      target_local  (Nt,) int32  local rows whose fresh embedding to keep
      target_global (Nt,) int32  global rows to scatter them into
                                 (padding -> num_nodes, dropped by the
                                 out-of-bounds scatter mode)
    """
    import numpy as np

    edge_src = np.asarray(edge_src, np.int64)
    edge_dst = np.asarray(edge_dst, np.int64)
    dirty = np.asarray(dirty, np.int64)
    dirty = dirty[(dirty >= 0) & (dirty < num_nodes)]
    if dirty.size == 0:
        return None

    mask = np.zeros(num_nodes, bool)
    mask[dirty] = True

    def _expand_fwd(m):
        # what X reads: dst endpoints of edges leaving X
        out = m.copy()
        out[edge_dst[m[edge_src]]] = True
        return out

    def _expand_rev(m):
        # what reads X: src endpoints of edges arriving in X
        out = m.copy()
        out[edge_src[m[edge_dst]]] = True
        return out

    for _ in range(hops):  # targets: reverse ball_k(dirty) — dependents
        mask = _expand_rev(mask)
    target_mask = mask.copy()
    for _ in range(hops):  # support: forward ball_k(targets) — inputs
        mask = _expand_fwd(mask)
    support_count = int(mask.sum())
    if support_count > max_frac * num_nodes:
        return None

    sink = num_nodes - 1
    mask[sink] = True  # padding rows alias the zero-feature sink
    nodes = np.nonzero(mask)[0].astype(np.int64)
    local_of = np.full(num_nodes, -1, np.int64)
    local_of[nodes] = np.arange(nodes.size)
    local_sink = int(local_of[sink])

    # keep every edge whose BOTH endpoints live in the support set; src
    # of every edge a target's recompute actually consumes is inside the
    # (2k-1)-ball subset of support, so this superset is always complete
    keep = mask[edge_src] & mask[edge_dst]
    edge_index = np.nonzero(keep)[0]
    sub_src = local_of[edge_src[edge_index]]
    sub_dst = local_of[edge_dst[edge_index]]

    targets = np.nonzero(target_mask)[0].astype(np.int64)

    def _pad_to(n: int) -> int:
        return pad_pow2(n, min_pad)

    ns = _pad_to(nodes.size)
    nodes_p = np.full(ns, sink, np.int32)
    nodes_p[: nodes.size] = nodes
    es = _pad_to(edge_index.size)
    src_p = np.full(es, local_sink, np.int32)
    dst_p = np.full(es, local_sink, np.int32)
    idx_p = np.zeros(es, np.int64)
    pad_e = np.ones(es, bool)
    src_p[: sub_src.size] = sub_src
    dst_p[: sub_dst.size] = sub_dst
    idx_p[: edge_index.size] = edge_index
    pad_e[: edge_index.size] = False
    nt = _pad_to(targets.size)
    tloc_p = np.full(nt, local_sink, np.int32)
    tglob_p = np.full(nt, num_nodes, np.int32)  # out of range -> dropped
    tloc_p[: targets.size] = local_of[targets]
    tglob_p[: targets.size] = targets
    return {
        "nodes": nodes_p,
        "edge_src": src_p,
        "edge_dst": dst_p,
        "edge_index": idx_p,
        "edge_pad": pad_e,
        "target_local": tloc_p,
        "target_global": tglob_p,
    }
