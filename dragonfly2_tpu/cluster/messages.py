"""Scheduler control-plane message set — the AnnouncePeer v2 oneof as typed
dataclasses.

Capability parity with the d7y.io/api schedulerv2 message set consumed by
scheduler/service/service_v2.go:89-204 (RegisterPeerRequest,
DownloadPieceFinished/Failed, DownloadPeerFinished/Failed,
DownloadPeerBackToSourceStarted, Reschedule) and the responses the
scheduling loop sends (NormalTaskResponse with candidate parents,
NeedBackToSourceResponse, scheduling.go:85-213). Transport-neutral: the
asyncio gRPC edge (cluster/rpc.py) and in-proc tests both speak these.
"""

from __future__ import annotations

import dataclasses
import enum

from dragonfly2_tpu.records.schema import CPUStat, DiskStat, MemoryStat


class SizeScope(enum.IntEnum):
    """Task size classes driving the register fast paths
    (service_v1.go:1005-1110 / service_v2 handleRegisterPeerRequest)."""

    NORMAL = 0
    SMALL = 1
    TINY = 2
    EMPTY = 3

    @staticmethod
    def of(content_length: int, piece_length: int = 4 << 20) -> "SizeScope":
        if content_length == 0:
            return SizeScope.EMPTY
        if content_length <= 128:  # TinyFileSize
            return SizeScope.TINY
        if content_length <= piece_length:
            return SizeScope.SMALL
        return SizeScope.NORMAL


@dataclasses.dataclass
class HostInfo:
    host_id: str
    hostname: str = ""
    ip: str = ""
    host_type: str = "normal"
    idc: str = ""
    location: str = ""
    port: int = 8002
    download_port: int = 8001
    concurrent_upload_limit: int = 50
    upload_count: int = 0
    upload_failed_count: int = 0
    # Live resource stats sampled by the daemon at announce time
    # (announcer.go:186-252 gopsutil) — the host feature columns of the
    # training CSV; location/idc already ride the fields above.
    cpu: CPUStat = dataclasses.field(default_factory=CPUStat)
    memory: MemoryStat = dataclasses.field(default_factory=MemoryStat)
    disk: DiskStat = dataclasses.field(default_factory=DiskStat)
    tcp_connection_count: int = 0
    upload_tcp_connection_count: int = 0


@dataclasses.dataclass
class RegisterPeerRequest:
    peer_id: str
    task_id: str
    host: HostInfo
    url: str = ""
    content_length: int = -1  # -1 unknown
    piece_length: int = 4 << 20
    total_piece_count: int = 0
    priority: int = 0
    tag: str = ""
    application: str = ""
    # Mid-task re-announce (failure-domain failover): pieces this peer
    # ALREADY holds on disk. A daemon that failed over to another
    # scheduler — or re-dialed a restarted one — announces its kept
    # progress so the scheduler adopts the partial download instead of
    # treating it as a brand-new peer; a seed answering a trigger for a
    # task it has fully cached announces all pieces, becoming a parent
    # without moving a byte.
    finished_pieces: list[int] | None = None


@dataclasses.dataclass
class DownloadPieceFinishedRequest:
    peer_id: str
    piece_number: int
    length: int
    cost_ns: int
    parent_peer_id: str = ""
    # Per-piece md5 (pkg/digest dialect). The scheduler TRUSTS this only
    # on back-to-source reports (parent_peer_id == ""): the origin/seed
    # fetch is the trust anchor of the task's digest chain — a
    # parent-relayed digest is what the chain exists to check.
    digest: str = ""


@dataclasses.dataclass
class DownloadPieceFailedRequest:
    peer_id: str
    parent_peer_id: str
    temporary: bool = True
    # failure attribution: "" = transport/serve error (blocklist only),
    # "corruption" = the piece's bytes failed digest verification against
    # the scheduler-attested chain — the scheduler quarantines the parent
    reason: str = ""


@dataclasses.dataclass
class DownloadPeerFinishedRequest:
    peer_id: str
    content_length: int = 0
    piece_count: int = 0


@dataclasses.dataclass
class DownloadPeerFailedRequest:
    peer_id: str
    description: str = ""


@dataclasses.dataclass
class DownloadPeerBackToSourceStartedRequest:
    peer_id: str
    description: str = ""


@dataclasses.dataclass
class DownloadPeerBackToSourceFinishedRequest:
    peer_id: str
    content_length: int = 0
    piece_count: int = 0
    # whole-task sha256 computed by the origin fetcher at mark_done — the
    # root of the task's digest chain (children verify it at completion)
    task_digest: str = ""


@dataclasses.dataclass
class DownloadPeerBackToSourceFailedRequest:
    peer_id: str
    description: str = ""


@dataclasses.dataclass
class RescheduleRequest:
    peer_id: str
    candidate_parent_ids: list[str] = dataclasses.field(default_factory=list)
    description: str = ""


# --------------------------------------------------------------- responses

@dataclasses.dataclass
class CandidateParent:
    peer_id: str
    host_id: str
    ip: str
    port: int
    download_port: int
    state: str
    score: float


@dataclasses.dataclass
class NormalTaskResponse:
    peer_id: str
    candidate_parents: list[CandidateParent]
    # Scheduler-ATTESTED digest chain for the task (origin-reported piece
    # md5s keyed by STRINGIFIED piece number — the wire codec's hardened
    # msgpack unpack refuses int map keys — plus the whole-task sha256).
    # The child verifies every parent-fetched piece against these: the
    # parent's X-Dragonfly-Piece-Digest header is advisory once an
    # attested digest exists, so a parent that lies consistently (header
    # matching its corrupted bytes) is still caught. Empty until the
    # origin fetch reports the chain.
    piece_digests: dict = dataclasses.field(default_factory=dict)
    task_digest: str = ""


@dataclasses.dataclass
class NeedBackToSourceResponse:
    peer_id: str
    description: str


@dataclasses.dataclass
class EmptyTaskResponse:
    peer_id: str


@dataclasses.dataclass
class ScheduleFailure:
    peer_id: str
    code: str
    description: str


# ------------------------------------------------- host + probe streams

@dataclasses.dataclass
class AnnounceHostRequest:
    host: HostInfo


@dataclasses.dataclass
class LeaveHostRequest:
    host_id: str


@dataclasses.dataclass
class LeavePeerRequest:
    peer_id: str


@dataclasses.dataclass
class ProbeStartedRequest:
    """SyncProbes: daemon asks which hosts to ping (service_v2.go:675)."""

    host_id: str
    count: int = 10


@dataclasses.dataclass
class ProbeTarget:
    host_id: str
    ip: str
    port: int


@dataclasses.dataclass
class ProbeTargetsResponse:
    targets: list[ProbeTarget]


@dataclasses.dataclass
class ProbeResult:
    host_id: str
    rtt_ns: int
    ok: bool = True


@dataclasses.dataclass
class ProbeFinishedRequest:
    host_id: str
    results: list[ProbeResult]


# ------------------------------------------------------ seed-peer trigger

@dataclasses.dataclass
class TriggerSeedRequest:
    """Scheduler -> seed daemon: download this task from origin so the
    cluster has a parent (resource/seed_peer.go:101 TriggerTask /
    cdnsystem ObtainSeeds, client rpcserver/seeder.go:53). Pushed over the
    seed host's announce connection."""

    host_id: str
    task_id: str
    url: str
    piece_length: int = 4 << 20
    tag: str = ""
    application: str = ""
    # auth/extra headers for the back-source fetch (image preheat carries
    # the registry bearer token here, manager/job/preheat.go:297-311)
    headers: dict = dataclasses.field(default_factory=dict)


# --------------------------------------------- scheduler fleet handoff

@dataclasses.dataclass
class PeerHandoffRequest:
    """Scheduler -> scheduler: adopt an in-flight peer whose task's ring
    owner moved (replica crash/restart or a rolling-upgrade restart
    rebalancing the consistent hashring — the fleet analogue of the
    daemon-side failover walk over ``HashRing.successors``). Carries
    everything the new owner needs to re-register the peer as a
    load-not-create plus the pieces the daemon kept on disk, so the
    receiving scheduler ADOPTS the partial download through the same
    ``RegisterPeerRequest.finished_pieces`` path instead of restarting
    it. New fields must default (add-field-with-default wire
    discipline): an N-1 scheduler that drops them still performs a
    correct, if less attributed, adoption."""

    peer_id: str
    task_id: str
    host: HostInfo
    url: str = ""
    content_length: int = -1
    piece_length: int = 4 << 20
    total_piece_count: int = 0
    tag: str = ""
    application: str = ""
    # pieces the peer holds at handoff time (None = unknown/none): the
    # adoption payload, same semantics as RegisterPeerRequest
    finished_pieces: list[int] | None = None
    # provenance for per-shard attribution: which replica released the
    # peer and why ("crash" | "upgrade" | "rebalance")
    from_scheduler: str = ""
    reason: str = ""


# ------------------------------------------------------ manager job edge

@dataclasses.dataclass
class JobTriggerSeedRequest:
    """Manager -> scheduler: enqueue a preheat seed trigger (the
    machinery preheat job hop, manager/job/preheat.go:90-286 ->
    scheduler/job.go:152). host_id empty = the scheduler round-robins
    its own announced seed hosts."""

    task_id: str
    url: str
    piece_length: int = 4 << 20
    tag: str = ""
    application: str = ""
    host_id: str = ""
    headers: dict = dataclasses.field(default_factory=dict)


@dataclasses.dataclass
class JobTriggerSeedResponse:
    ok: bool
    description: str = ""


@dataclasses.dataclass
class TaskStatesRequest:
    """Manager -> scheduler: poll task FSM states for job progress
    (the machinery group-state poll, internal/job/job.go:53-87)."""

    task_ids: list[str]


@dataclasses.dataclass
class TaskStatesResponse:
    # state int per requested task id; -1 = unknown to this scheduler
    states: list[int]


@dataclasses.dataclass
class SchedulerInfoRequest:
    """Manager -> scheduler: entity counts + announced hosts (the
    sync_peers job's per-scheduler collection, scheduler/job/job.go:224)."""


@dataclasses.dataclass
class SchedulerInfoResponse:
    counts: dict
    hosts: list


@dataclasses.dataclass
class FlightRecorderRequest:
    """Manager/operator -> scheduler: dump the in-product flight recorder
    (telemetry/flight.py — last-N tick phase breakdowns, jit compile/
    retrace counters, spans currently open)."""

    last_n: int = 64


@dataclasses.dataclass
class FlightRecorderResponse:
    dump: dict = dataclasses.field(default_factory=dict)


# ----------------------------------------------------------------- stat

@dataclasses.dataclass
class StatPeerRequest:
    peer_id: str


@dataclasses.dataclass
class StatTaskRequest:
    task_id: str


@dataclasses.dataclass
class StatResponse:
    found: bool
    state: str = ""
    detail: dict = dataclasses.field(default_factory=dict)


# ------------------------------------------------------- trainer stream

@dataclasses.dataclass
class TrainRequest:
    """One chunk of the scheduler->trainer dataset upload
    (trainer/service/service_v1.go:59-162; 128 MiB chunks announcer.go:40).
    dataset is 'download' or 'networktopology'."""

    host_id: str
    ip: str
    hostname: str
    dataset: str
    chunk: bytes


@dataclasses.dataclass
class TrainEndRequest:
    """Explicit end-of-upload commit marker. A torn connection shows up as
    bare EOF, which the trainer treats as an abort; only this frame starts
    training — the role CloseSend/io.EOF separation plays in the reference
    (trainer/service/service_v1.go stream handling)."""

    host_id: str = ""


@dataclasses.dataclass
class TrainResponse:
    ok: bool
    description: str = ""
