"""Embedded web console — the manager's browser UI.

Capability parity with the reference's embedded console SPA
(manager/manager.go:61-63 embeds `dist/` and serves it at `/`): a single
self-contained page (no build step, no external assets) served by
ManagerREST at `/` that signs in against `/api/v1/users/signin`, then
browses clusters, schedulers, seed peers, peers, jobs, applications and
models, and can submit preheat jobs — every call goes through the same
REST surface external clients use, so the console exercises nothing
private.
"""

CONSOLE_HTML = """<!DOCTYPE html>
<html lang="en">
<head>
<meta charset="utf-8">
<title>Dragonfly2-TPU Manager</title>
<style>
  :root { color-scheme: light dark; }
  body { font-family: system-ui, sans-serif; margin: 0; background: #f5f6f8; color: #1c2330; }
  header { background: #16324f; color: #fff; padding: 10px 20px; display: flex;
           align-items: center; gap: 16px; }
  header h1 { font-size: 16px; margin: 0; font-weight: 600; }
  header .who { margin-left: auto; font-size: 13px; opacity: .85; }
  nav { display: flex; gap: 4px; padding: 8px 16px; background: #fff;
        border-bottom: 1px solid #dde1e7; flex-wrap: wrap; }
  nav button { border: 0; background: none; padding: 8px 12px; cursor: pointer;
               font-size: 14px; border-radius: 6px; color: #3b4456; }
  nav button.on { background: #e8f0fe; color: #16324f; font-weight: 600; }
  main { padding: 16px 20px; }
  table { border-collapse: collapse; width: 100%; background: #fff;
          box-shadow: 0 1px 2px rgba(20,30,50,.08); border-radius: 8px; overflow: hidden; }
  th, td { text-align: left; padding: 8px 12px; border-bottom: 1px solid #eef0f4;
           font-size: 13px; vertical-align: top; max-width: 420px; overflow-wrap: anywhere; }
  th { background: #fafbfc; font-weight: 600; color: #5a6372; }
  .error { color: #b3261e; margin: 8px 0; }
  form.card, .card { background: #fff; padding: 16px; border-radius: 8px; max-width: 440px;
                     box-shadow: 0 1px 2px rgba(20,30,50,.08); margin-bottom: 16px; }
  input, select { padding: 7px 9px; margin: 4px 0; width: 100%; box-sizing: border-box;
                  border: 1px solid #cdd3dc; border-radius: 6px; font-size: 14px; }
  button.go { background: #16324f; color: #fff; border: 0; padding: 8px 14px;
              border-radius: 6px; cursor: pointer; margin-top: 8px; font-size: 14px; }
  .muted { color: #7a8394; font-size: 12px; }
</style>
</head>
<body>
<header><h1>Dragonfly2-TPU Manager</h1><span class="who" id="who"></span></header>
<nav id="nav" hidden></nav>
<main id="main"></main>
<script>
"use strict";
const GROUPS = ["overview", "clusters", "schedulers", "seed-peers", "peers",
                "jobs", "applications", "models"];
let token = null, user = null, tab = "overview";

async function api(method, path, body) {
  const headers = {"Content-Type": "application/json"};
  if (token) headers["Authorization"] = "Bearer " + token;
  const resp = await fetch("/api/v1/" + path, {
    method, headers, body: body === undefined ? undefined : JSON.stringify(body),
  });
  const data = await resp.json().catch(() => ({}));
  if (!resp.ok) {
    const err = new Error(data.error || resp.status);
    err.status = resp.status;  // message text alone can't signal auth
    throw err;
  }
  return data;
}

function el(tag, attrs, ...children) {
  const node = document.createElement(tag);
  for (const [k, v] of Object.entries(attrs || {}))
    (k.startsWith("on")) ? node.addEventListener(k.slice(2), v) : node.setAttribute(k, v);
  for (const c of children)
    node.append(c instanceof Node ? c : document.createTextNode(String(c)));
  return node;
}

function renderLogin(message) {
  document.getElementById("nav").hidden = true;
  const main = document.getElementById("main");
  main.replaceChildren(el("form", {class: "card", onsubmit: async (e) => {
    e.preventDefault();
    try {
      const body = {name: e.target.name.value, password: e.target.password.value};
      token = (await api("POST", "users/signin", body)).token;
      user = body.name;
      renderApp();
    } catch (err) { renderLogin(String(err)); }
  }},
    el("h2", {}, "Sign in"),
    message ? el("div", {class: "error"}, message) : "",
    el("input", {name: "name", placeholder: "user (root)", required: ""}),
    el("input", {name: "password", type: "password", placeholder: "password", required: ""}),
    el("button", {class: "go"}, "Sign in"),
    el("div", {class: "muted"}, "default root / dragonfly")));
}

function renderApp() {
  document.getElementById("who").textContent = user || "";
  const nav = document.getElementById("nav");
  nav.hidden = false;
  nav.replaceChildren(...GROUPS.map(g =>
    el("button", {class: g === tab ? "on" : "", onclick: () => { tab = g; renderApp(); }}, g)),
    el("button", {onclick: () => { token = null; renderLogin(); }}, "sign out"));
  renderTab().catch(err =>
    document.getElementById("main").replaceChildren(el("div", {class: "error"}, String(err))));
}

async function renderTab() {
  const main = document.getElementById("main");
  if (tab === "overview") { main.replaceChildren(...await overview()); return; }
  const rows = await api("GET", tab);
  const children = [];
  if (tab === "jobs") children.push(preheatForm());
  if (!rows.length) {
    children.push(el("div", {class: "card"}, "no " + tab + " yet"));
  } else {
    const cols = [...new Set(rows.flatMap(r => Object.keys(r)))].slice(0, 9);
    const extra = tab === "models" ? 1 : 0;
    children.push(el("table", {},
      el("thead", {}, el("tr", {}, ...cols.map(c => el("th", {}, c)),
                         ...(extra ? [el("th", {}, "actions")] : []))),
      el("tbody", {}, ...rows.map(r => el("tr", {}, ...cols.map(c =>
        el("td", {}, r[c] === undefined ? "" :
          (typeof r[c] === "object" ? JSON.stringify(r[c]) : r[c]))),
        ...(extra ? [el("td", {}, modelActions(r))] : []))))));
  }
  main.replaceChildren(...children);
}

function modelActions(row) {
  // activate = the reference's version-policy flip (PATCH state: active)
  if (row.state === "active") return el("span", {class: "muted"}, "active");
  return el("button", {class: "go", onclick: async () => {
    try { await api("PATCH", "models/" + row.id, {state: "active"}); renderApp(); }
    catch (err) { alert(err); }
  }}, "activate");
}

async function overview() {
  // stat tiles + a scheduler-state bar, all through the public REST
  // surface; auth failures must NOT render as healthy-looking zeros
  const CAP = 1000;
  const groups = GROUPS.filter(g => g !== "overview");
  // every non-OK fetch (500, network) marks its tile "?" instead of
  // rendering 0 — a broken manager must not look like an empty-but-
  // healthy cluster (ADVICE r4 low); 401 still aborts to the login view
  const failed = {};
  const results = await Promise.all(groups.map(g =>
    api("GET", g + "?per_page=" + CAP).catch(err => {
      if (err.status === 401) throw err;  // never render auth failure as zeros
      failed[g] = String(err);
      return [];
    })));
  const counts = Object.fromEntries(groups.map((g, i) =>
    [g, failed[g] ? "?" : (results[i].length >= CAP ? CAP + "+" : results[i].length)]));
  const scheds = results[groups.indexOf("schedulers")];
  const active = scheds.filter(s => s.state === "active").length;
  const tiles = el("div", {style: "display:flex;gap:12px;flex-wrap:wrap;margin-bottom:16px"},
    ...groups.map(g => el("div", {class: "card", style: "max-width:130px;text-align:center",
        ...(failed[g] ? {title: failed[g]} : {})},
      el("div", {style: "font-size:26px;font-weight:700" +
        (failed[g] ? ";color:#b4231f" : "")}, counts[g]),
      el("div", {class: "muted"}, g))));
  const ns = "http://www.w3.org/2000/svg";
  // SVG elements need the SVG namespace: el() uses createElement, which
  // would yield an HTMLUnknownElement whose child rects never render
  const svg = document.createElementNS(ns, "svg");
  svg.setAttribute("width", "400"); svg.setAttribute("height", "28");
  svg.setAttribute("role", "img");
  svg.setAttribute("aria-label", active + " of " + scheds.length + " schedulers active");
  const total = Math.max(scheds.length, 1);
  const seg = (x, w, fill) => {
    const r = document.createElementNS(ns, "rect");
    r.setAttribute("x", x); r.setAttribute("y", 4);
    r.setAttribute("width", w); r.setAttribute("height", 18);
    r.setAttribute("rx", 4); r.setAttribute("fill", fill);
    svg.appendChild(r);
  };
  seg(0, 400, "#dde1e7");
  if (active) seg(0, 400 * active / total, "#2c7a4b");
  const bar = el("div", {class: "card"},
    el("h3", {style: "margin-top:0"}, "scheduler health"),
    svg,
    el("div", {class: "muted"}, failed["schedulers"]
       ? "unavailable: " + failed["schedulers"]
       : active + " active / " + (scheds.length - active) +
         " inactive of " + scheds.length));
  return [tiles, bar];
}

function preheatForm() {
  return el("form", {class: "card", onsubmit: async (e) => {
    e.preventDefault();
    try {
      await api("POST", "jobs", {type: "preheat", args: {
        type: e.target.ptype.value, url: e.target.url.value,
      }});
      renderApp();
    } catch (err) { alert(err); }
  }},
    el("h3", {}, "Preheat"),
    el("input", {name: "url", placeholder: "https://... (file or image manifest URL)", required: ""}),
    el("select", {name: "ptype"},
      el("option", {value: ""}, "auto"),
      el("option", {value: "file"}, "file"),
      el("option", {value: "image"}, "image")),
    el("button", {class: "go"}, "Create preheat job"));
}

renderLogin();
</script>
</body>
</html>
"""
