"""Manager service-facing RPC: the gRPC surface schedulers and daemons use.

Capability parity with manager/rpcserver (manager_server_v1.go):
GetScheduler/ListSchedulers for joining daemons, scheduler/seed-peer
registration (UpdateScheduler/UpdateSeedPeer upserts), the KeepAlive
client-stream (manager_server_v1.go:955-1000) that flips instances
active/inactive, CreateModel (:802-952) streaming trained params into the
registry, and the dynconfig fetch schedulers poll. Same length-prefixed
msgpack wire protocol as the scheduler edge (rpc/wire.py); params ride as
msgpack-serializable nested lists produced by the trainer's checkpoint
codec.
"""

from __future__ import annotations

import asyncio
import dataclasses
import logging

from dragonfly2_tpu.rpc import mux, wire
from dragonfly2_tpu.utils.conntrack import ConnTracker

logger = logging.getLogger(__name__)


# ------------------------------------------------------------------ messages


@dataclasses.dataclass
class GetSchedulersRequest:
    ip: str
    hostname: str
    idc: str = ""
    location: str = ""


@dataclasses.dataclass
class SchedulerEntry:
    id: int
    host_name: str
    ip: str
    port: int
    state: str
    scheduler_cluster_id: int


@dataclasses.dataclass
class GetSchedulersResponse:
    schedulers: list[SchedulerEntry]


@dataclasses.dataclass
class RegisterInstanceRequest:
    source_type: str  # "scheduler" | "seed_peer"
    host_name: str
    ip: str
    port: int
    cluster_id: int
    idc: str = ""
    location: str = ""


@dataclasses.dataclass
class RegisterInstanceResponse:
    id: int
    cluster_id: int


@dataclasses.dataclass
class KeepAliveRequest:
    source_type: str
    host_name: str
    ip: str
    cluster_id: int


@dataclasses.dataclass
class CreateModelRequest:
    name: str
    type: str
    scheduler_host_id: str
    params_blob: bytes
    evaluation: dict


@dataclasses.dataclass
class CreateModelResponse:
    model_id: str
    version: int


@dataclasses.dataclass
class GetDynconfigRequest:
    scheduler_cluster_id: int


@dataclasses.dataclass
class DynconfigResponse:
    data: dict


@dataclasses.dataclass
class IssueCertificateRequest:
    """CSR-based cert issuance (pkg/issuer DragonflyIssuer + the security
    client every service runs when mTLS is on, scheduler.go:180-219)."""

    csr_pem: bytes
    validity_days: int = 365
    # Shared enrollment secret: required when the manager was started with
    # one, so CA issuance is not granted by mere network reachability.
    token: str = ""


@dataclasses.dataclass
class IssueCertificateResponse:
    # leaf first, then the CA — the chain order ssl.load_cert_chain wants
    certificate_chain: list[bytes]


@dataclasses.dataclass
class Ack:
    ok: bool = True
    error: str = ""


wire.register_messages(
    IssueCertificateRequest,
    IssueCertificateResponse,
    GetSchedulersRequest,
    SchedulerEntry,
    GetSchedulersResponse,
    RegisterInstanceRequest,
    RegisterInstanceResponse,
    KeepAliveRequest,
    CreateModelRequest,
    CreateModelResponse,
    GetDynconfigRequest,
    DynconfigResponse,
    Ack,
)


# -------------------------------------------------------------------- server


class ManagerRPCServer:
    def __init__(self, service, host: str = "127.0.0.1", port: int = 0,
                 health_check=None, ssl_context=None):
        self.service = service
        self.health_check = health_check
        self.host = host
        self.port = port
        self.ssl_context = ssl_context
        self._server: asyncio.AbstractServer | None = None
        self._tracker = ConnTracker()

    async def start(self) -> tuple[str, int]:
        self._server = await asyncio.start_server(
            self._tracker.tracked(self._serve_conn), self.host, self.port,
            ssl=self.ssl_context,
        )
        addr = self._server.sockets[0].getsockname()
        self.host, self.port = addr[0], addr[1]
        logger.info("manager rpc listening on %s:%d", self.host, self.port)
        return self.host, self.port

    async def stop(self) -> None:
        if self._server:
            self._server.close()
            # Cancel in-flight handlers first: keepalive clients hold their
            # connection open forever, and 3.12's wait_closed() waits for
            # every live handler (utils/conntrack.py).
            await self._tracker.cancel_all()
            await self._server.wait_closed()

    async def _serve_conn(self, reader: asyncio.StreamReader, writer: asyncio.StreamWriter):
        try:
            while True:
                request = await wire.read_frame(reader)
                if request is None:
                    return
                # Wire-envelope propagation (dflint WIRE003) via the
                # shared mux.dispatch_anchored: a preheat job's budget
                # now bounds the manager-side work it triggers and its
                # trace continues across this hop. Replies always go out
                # — the manager edge is strict request/response
                # (keepalive loops, certify flows) and a dropped Ack
                # would wedge the caller on a shared connection.
                response = await asyncio.to_thread(
                    mux.dispatch_anchored, self._dispatch, request,
                    "manager.rpc",
                )
                if response is not None:
                    wire.write_frame(writer, response)
                    await writer.drain()
        except Exception:  # noqa: BLE001 - one bad conn must not kill the server
            logger.exception("manager connection handler failed")
        finally:
            writer.close()

    def _dispatch(self, request):
        health = mux.handle_health_request(request, self.health_check)
        if health is not None:
            return health
        svc = self.service
        try:
            if isinstance(request, GetSchedulersRequest):
                conditions = {"idc": request.idc, "location": request.location}
                rows = svc.list_schedulers(request.ip, request.hostname, conditions)
                return GetSchedulersResponse(
                    schedulers=[
                        SchedulerEntry(
                            id=r["id"],
                            host_name=r["host_name"],
                            ip=r["ip"],
                            port=r.get("port", 0),
                            state=r["state"],
                            scheduler_cluster_id=r["scheduler_cluster_id"],
                        )
                        for r in rows
                    ]
                )
            if isinstance(request, RegisterInstanceRequest):
                body = {
                    "host_name": request.host_name,
                    "ip": request.ip,
                    "port": request.port,
                    "idc": request.idc,
                    "location": request.location,
                }
                if request.source_type == "scheduler":
                    body["scheduler_cluster_id"] = request.cluster_id
                    record = svc.register_scheduler(body)
                else:
                    body["seed_peer_cluster_id"] = request.cluster_id
                    record = svc.register_seed_peer(body)
                return RegisterInstanceResponse(id=record["id"], cluster_id=request.cluster_id)
            if isinstance(request, KeepAliveRequest):
                svc.keepalive(request.source_type, request.host_name, request.ip, request.cluster_id)
                return Ack()
            if isinstance(request, CreateModelRequest):
                from dragonfly2_tpu.registry.registry import ModelEvaluation
                from dragonfly2_tpu.training.checkpoint import params_from_bytes

                params = params_from_bytes(request.params_blob)
                record = svc.create_model(
                    request.name,
                    request.type,
                    request.scheduler_host_id,
                    params,
                    ModelEvaluation(**request.evaluation),
                )
                return CreateModelResponse(model_id=record["model_id"], version=record["version"])
            if isinstance(request, GetDynconfigRequest):
                return DynconfigResponse(data=svc.scheduler_dynconfig(request.scheduler_cluster_id))
            if isinstance(request, IssueCertificateRequest):
                chain = svc.issue_certificate(
                    request.csr_pem, request.validity_days, token=request.token
                )
                return IssueCertificateResponse(certificate_chain=chain)
        except Exception as e:  # noqa: BLE001 - errors cross the wire as acks
            return Ack(ok=False, error=f"{type(e).__name__}: {e}")
        return Ack(ok=False, error=f"unknown request {type(request).__name__}")


# -------------------------------------------------------------------- client


class ManagerClient:
    """Typed client with one connection, used by schedulers/daemons
    (pkg/rpc/manager/client surface)."""

    def __init__(self, host: str, port: int, ssl_context=None):
        self.host = host
        self.port = port
        self.ssl_context = ssl_context
        self._reader: asyncio.StreamReader | None = None
        self._writer: asyncio.StreamWriter | None = None
        self._lock = asyncio.Lock()

    async def connect(self) -> "ManagerClient":
        self._reader, self._writer = await asyncio.open_connection(
            self.host, self.port, ssl=self.ssl_context
        )
        return self

    async def close(self) -> None:
        if self._writer:
            self._writer.close()

    async def call(self, request):
        async with self._lock:
            assert self._writer is not None and self._reader is not None
            wire.write_frame(self._writer, request)
            await self._writer.drain()
            response = await wire.read_frame(self._reader)
        if isinstance(response, Ack) and not response.ok:
            raise RuntimeError(response.error)
        return response

    async def keepalive_loop(self, request: KeepAliveRequest, interval: float = 5.0) -> None:
        """The KeepAlive stream: fire until cancelled."""
        while True:
            try:
                await self.call(request)
            except (ConnectionError, RuntimeError) as e:
                logger.warning("keepalive failed: %s", e)
            await asyncio.sleep(interval)


async def obtain_certificate(
    manager_host: str,
    manager_port: int,
    common_name: str,
    cert_dir,
    san_hosts: list[str] | None = None,
    ssl_context=None,
    validity_days: int = 365,
    enrollment_token: str = "",
):
    """Service-side certify flow (the reference's security client: generate
    keypair + CSR locally, IssueCertificate against the manager, install
    the returned chain). Returns a ready `utils.certs.TLSMaterial` whose
    server/client contexts speak cluster mTLS. `ssl_context` lets the
    issuance call itself ride TLS (server-auth-only bootstrap) when the
    manager already serves it."""
    from dragonfly2_tpu.utils import certs

    csr_pem, key_pem = certs.generate_csr(
        common_name, san_hosts or ["127.0.0.1", "localhost"]
    )
    client = await ManagerClient(manager_host, manager_port, ssl_context=ssl_context).connect()
    try:
        resp = await client.call(
            IssueCertificateRequest(
                csr_pem=csr_pem, validity_days=validity_days, token=enrollment_token
            )
        )
    finally:
        await client.close()
    chain = resp.certificate_chain
    if not chain or len(chain) < 2:
        raise RuntimeError("manager returned an incomplete certificate chain")
    mat = certs.TLSMaterial(cert_dir)
    mat.write(cert_pem=chain[0], key_pem=key_pem, ca_pem=chain[-1])
    return mat
