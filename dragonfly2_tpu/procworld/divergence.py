"""Sim-vs-real divergence report — the capstone of the process planet.

The same ScenarioSpec runs twice: once through ``run_megascale`` (the
modeled daemon inside EventBatchEngine) and once through the process
planet (real schedulers, real dfdaemons, real sockets, real SIGKILL).
This module compares the two runs metric by metric and emits a report
in which every comparison carries its OWN tolerance band and the
argument for that band — the bands travel in the artifact, so the test
that gates on them asserts ``within`` flags it can audit, instead of
hardcoding numbers whose provenance is lost.

Three comparison kinds:

- ``ratio``  — real/sim; right for throughput-like magnitudes where the
  planes differ by modeled-vs-loopback transport but not by structure.
- ``delta``  — real − sim; right for bounded fractions.
- ``equal``  — invariants both planes must agree on exactly (lost
  downloads, page-at-the-kill, final verdict): value 1.0 on agreement.

This module is a dflint DET domain (replay-facing): the report is a
pure function of the two run dicts — no wall clocks, no randomness,
no set-ordered iteration — so re-running it over a checked-in artifact
reproduces the shipped verdicts bit for bit.
"""

from __future__ import annotations

from typing import Mapping

# name -> (lo, hi, argument). The ttc entry is a per-region template.
# These are the DEFAULT bands; the report embeds whichever bands it was
# computed with, and tests assert the embedded ``within`` flags — the
# bands are data in the artifact, not constants in a test.
DEFAULT_BANDS: dict = {
    "ttc_p95_ratio": (
        0.0, 1.5,
        "real transport is loopback TCP while the simulator prices the "
        "scenario's WAN matrix (~85ms RTT, ~20MB/s cross-region per the "
        "analytic model of PAPERS.md 2103.10515), so real p95 TTC must "
        "land well BELOW the modeled p95; the 1.5x ceiling only guards "
        "against the real path being pathologically slower than a "
        "simulated WAN, which would mean a stall bug, not a model gap",
    ),
    "origin_fraction_delta": (
        -0.05, 0.5,
        "a 3-daemon planet pays the first-fetch origin cost once per "
        "content object over a tiny swarm, while the simulator amortizes "
        "it over thousands of modeled peers — real origin share is "
        "structurally inflated by O(1/M); it must never be materially "
        "BELOW sim (that would mean phantom P2P traffic) and may exceed "
        "it by at most the small-swarm inflation bound",
    ),
    "pieces_per_download_ratio": (
        0.25, 4.0,
        "piece count per completed download is payload_size/piece_length "
        "for the planet and the synthetic task-size model for the sim; "
        "the payload is sized to match the modeled mean within one "
        "octave each way, so a ratio outside [0.25, 4] means piece "
        "accounting broke (double counts or lost pieces), not sizing",
    ),
    "lost_downloads": (
        1.0, 1.0,
        "zero lost downloads is THE invariant both planes assert "
        "independently; the comparison must find exact agreement at 0 — "
        "there is no tolerance to argue",
    ),
    "paged_at_kill": (
        1.0, 1.0,
        "the announce-stability page firing AT the kill (and only at "
        "kills) is the alert contract the SLO plane exists for; both "
        "planes feed the same burn rules, so both must page on the kill "
        "rounds and nowhere else",
    ),
    "verdict_match": (
        1.0, 1.0,
        "one verdict plane: megascale_slo_specs + the same burn rules "
        "judge both runs, so the final verdict string must agree — a "
        "mismatch means the planes saw structurally different days",
    ),
    "failover_per_kill": (
        1.0, 1.0,
        "every scheduler kill must produce observable failover on both "
        "planes (daemon redial + PR-3 re-announce in the planet, "
        "crash_reannounced_peers in the sim); a kill nobody noticed is "
        "a dead assertion",
    ),
}


def _sim_final_ttc_p95(timeline: list, regions: list) -> dict:
    """Last recorded per-region p95 — the megascale sketches are
    cumulative, so the final non-None value is the whole-run p95."""
    final: dict = {r: None for r in regions}
    for sample in timeline:
        p95 = sample.get("ttc_ms_p95")
        if not isinstance(p95, Mapping):
            continue
        for r in regions:
            v = p95.get(r)
            if v is not None:
                final[r] = float(v)
    return final


def _page_rounds(slo_block: Mapping, slo_name: str = "announce_stability"):
    return sorted(
        float(e["t"]) for e in slo_block.get("alert_log", [])
        if e.get("slo") == slo_name and e.get("severity") == "page"
        and e.get("event") == "fired"
    )


def _paged_at_kills_only(page_rounds: list, kill_rounds: list) -> int:
    """1 iff at least one page fired and every page landed on a kill
    round — pages happen at kills, and only at kills."""
    if not page_rounds or not kill_rounds:
        return 0
    kills = {float(k) for k in kill_rounds}
    return 1 if all(float(t) in kills for t in page_rounds) else 0


def compute_divergence(real: Mapping, sim: Mapping,
                       bands: Mapping = DEFAULT_BANDS) -> dict:
    """Build the divergence report.

    ``real`` is the planet's reduced fact sheet (built by
    ``planet.run_procday``): ttc_ms_p95 per region, origin_fraction,
    pieces, completed, lost_downloads, kills, failovers, kill_rounds,
    the run's ``slo`` block and scenario/seed identity.

    ``sim`` is the full ``run_megascale`` report for the same spec.

    Returns ``{"scenario", "seed", "metrics": {name: entry}, and
    "all_within"}`` where each entry is ``{kind, real, sim, value,
    band, argument, within}``.
    """
    metrics: dict = {}

    def add(name: str, band_key: str, kind: str, real_v, sim_v, value):
        lo, hi, argument = bands[band_key]
        within = value is not None and lo <= float(value) <= hi
        metrics[name] = {
            "kind": kind,
            "real": real_v,
            "sim": sim_v,
            "value": None if value is None else round(float(value), 6),
            "band": [lo, hi],
            "argument": argument,
            "within": bool(within),
        }

    # --- per-region TTC p95 ratio (real loopback vs modeled WAN)
    regions = sorted(real.get("ttc_ms_p95", {}))
    sim_p95 = _sim_final_ttc_p95(sim.get("timeline", []), regions)
    for r in regions:
        rv = real["ttc_ms_p95"].get(r)
        sv = sim_p95.get(r)
        ratio = (float(rv) / float(sv)) if rv and sv else None
        add(f"ttc_p95_ratio_{r}", "ttc_p95_ratio", "ratio", rv, sv, ratio)

    # --- origin fraction: real observed vs sim byte-accounted
    mega = sim.get("mega", {})
    ob, pb = mega.get("origin_bytes", 0), mega.get("p2p_bytes", 0)
    sim_of = (float(ob) / float(ob + pb)) if (ob + pb) > 0 else 0.0
    real_of = float(real.get("origin_fraction", 0.0))
    add("origin_fraction_delta", "origin_fraction_delta", "delta",
        round(real_of, 6), round(sim_of, 6), real_of - sim_of)

    # --- piece accounting per completed download
    st = sim.get("stats", {})
    sim_ppd = (st.get("pieces", 0) / max(st.get("completed", 0), 1))
    real_ppd = (real.get("pieces", 0) / max(real.get("completed", 0), 1))
    add("pieces_per_download_ratio", "pieces_per_download_ratio", "ratio",
        round(real_ppd, 3), round(sim_ppd, 3),
        real_ppd / sim_ppd if sim_ppd > 0 else None)

    # --- exact-agreement invariants
    sim_lost = int(st.get("failed", 0))
    real_lost = int(real.get("lost_downloads", 0))
    add("lost_downloads", "lost_downloads", "equal", real_lost, sim_lost,
        1.0 if real_lost == sim_lost == 0 else 0.0)

    real_paged = _paged_at_kills_only(
        _page_rounds(real.get("slo", {})), real.get("kill_rounds", []))
    sim_paged = _paged_at_kills_only(
        _page_rounds(sim.get("slo", {})),
        sim.get("expected_crash_rounds", []))
    add("paged_at_kill", "paged_at_kill", "equal", real_paged, sim_paged,
        1.0 if real_paged == sim_paged == 1 else 0.0)

    real_verdict = real.get("slo", {}).get("verdict_final")
    sim_verdict = sim.get("slo", {}).get("verdict_final")
    add("verdict_match", "verdict_match", "equal", real_verdict,
        sim_verdict, 1.0 if real_verdict == sim_verdict else 0.0)

    real_fo = 1 if (real.get("kills", 0) > 0
                    and real.get("failovers", 0) > 0) else 0
    fo = sim.get("failover", {})
    sim_fo = 1 if (fo.get("scheduler_crashes", 0) > 0
                   and fo.get("crash_reannounced_peers", 0) > 0) else 0
    add("failover_per_kill", "failover_per_kill", "equal", real_fo,
        sim_fo, 1.0 if real_fo == sim_fo == 1 else 0.0)

    return {
        "scenario": real.get("scenario"),
        "seed": real.get("seed"),
        "metrics": metrics,
        "all_within": all(m["within"] for m in metrics.values()),
    }


def publish_divergence(report: Mapping, metrics_ns) -> None:
    """Mirror each numeric comparison onto the
    ``dragonfly_proc_sim_real_divergence`` gauge family (one series per
    metric name) so the proc-observatory dashboard plots the live gap."""
    for name in sorted(report.get("metrics", {})):
        entry = report["metrics"][name]
        if entry.get("value") is not None:
            metrics_ns.sim_real_divergence.labels(name).set(
                float(entry["value"]))
