"""dflint green fixture: every LOCK001-adjacent idiom the pass must
accept — under[...] markers, call-graph propagation through private
helpers, reentrant public entry points, and lock-free READS."""

import threading


class Board:
    def __init__(self):
        self._mu = threading.RLock()
        self.count = 0
        self.items = []

    def bump(self):
        with self._mu:
            self.count += 1
            self._bump_locked()

    def _bump_locked(self):
        # no marker needed: every in-class call site holds _mu, the
        # pass's propagation proves it
        self.count += 1
        self.items.append(self.count)

    def helper_with_marker(self):  # dflint: under[_mu]
        self.count -= 1

    def read_without_lock(self) -> int:
        # reads are never flagged: atomic-swap readers are an idiom
        return self.count

    def swap(self):
        with self._mu:
            self.items = []
