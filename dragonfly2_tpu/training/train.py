"""Sharded training loops — making trainer/training/training.go:60-98 real.

The reference spells out the intended pipeline in TODO comments (load from
storage -> preprocess -> train -> upload model); here it exists:

- `train_mlp`: probe-RTT regressor over topology pairs.
- `train_gnn`: GraphSAGE ranker over download traces + host graph.

Parallelism: data-parallel over the mesh's `dp` axis — batches sharded on
their leading dim, params replicated, XLA inserts the gradient all-reduce
over ICI (the pjit recipe from the scaling playbook). For graphs too big
for one chip, `embed_graph_sharded` shards the EDGE set over the mesh and
combines partial segment-sums with `psum` under `shard_map` — the
"pkg/graph DAG ops lower to scatter/segment_sum with psum across chips"
north star (BASELINE.json).
"""

from __future__ import annotations

import dataclasses
import functools
import time
from typing import Callable

import jax
import jax.numpy as jnp
import numpy as np
import optax
from jax.sharding import NamedSharding, PartitionSpec as P
from dragonfly2_tpu.utils.jaxcompat import shard_map

from dragonfly2_tpu.config.config import TrainerConfig
from dragonfly2_tpu.telemetry import costcard as _costcard
from dragonfly2_tpu.models.graphsage import GraphSAGERanker, RankBatch, listwise_rank_loss
from dragonfly2_tpu.models.mlp import ProbeRTTRegressor
from dragonfly2_tpu.models import metrics as M
from dragonfly2_tpu.parallel.mesh import (
    DP_AXIS,
    GRAPH_AXIS,
    replicated,
    shard_batch,
    shard_stacked_batches,
)
from dragonfly2_tpu.records.features import HostGraph, RankingDataset
from dragonfly2_tpu.training import data as D


@dataclasses.dataclass
class TrainResult:
    params: dict
    losses: list[float]
    eval_metrics: dict[str, float]
    samples_per_sec: float
    steps: int
    # XLA-counted model FLOPs per trained sample (cost_analysis of the
    # compiled epoch program; 0.0 when the backend reports none). Callers
    # derive achieved FLOP/s = flops_per_sample * samples_per_sec and
    # MFU = achieved / chip peak (bench_trainer.py, bench.py).
    flops_per_sample: float = 0.0
    # Hand-counted matmul-only FLOPs per sample (a LOWER bound on the work
    # the compiled program must do — XLA cannot skip the model's matmuls).
    # Cross-checks `flops_per_sample`: on some backends cost_analysis is
    # unreliable (BENCH_r03 reported ~250x below the dense-adjacency
    # cost); bench.py publishes min(positive of the two) with provenance.
    analytic_flops_per_sample: float = 0.0
    # Best single timed block's rate (compile-carrying first block
    # excluded): on a tunneled device whose latency swings by minutes,
    # the peak is the honest steady-state number — degradation only ever
    # slows a block down. Equals samples_per_sec when only one block ran.
    peak_samples_per_sec: float = 0.0

    @property
    def flops_per_sec(self) -> float:
        return self.flops_per_sample * self.samples_per_sec


def flops_basis(result: "TrainResult") -> tuple[str, float]:
    """(source, flops_per_sample) every MFU claim must use — ONE policy
    shared by bench.py and bench_trainer.py so the two artifacts can
    never report utilization on different bases. The analytic matmul
    floor wins when present (a lower bound on executed work, so MFU can
    only be understated); XLA cost_analysis BELOW that floor is invalid
    data and flagged as such; "none" when no basis exists at all."""
    analytic, xla = result.analytic_flops_per_sample, result.flops_per_sample
    if analytic > 0:
        if 0 < xla < analytic:
            return (
                "analytic_matmul_floor (xla_cost_analysis invalid: below floor)",
                analytic,
            )
        return "analytic_matmul_floor", analytic
    if xla > 0:
        return "xla_cost_analysis", xla
    return "none", 0.0


def analytic_gnn_flops_per_sample(
    n_nodes: int,
    node_feat_dim: int,
    edge_feat_dim: int,
    hidden: int,
    batch: int,
    parents: int,
    pair_feat_dim: int,
    num_layers: int = 2,
    dense_adj: bool = True,
) -> float:
    """Matmul-only FLOP lower bound per trained sample for one
    GraphSAGERanker train step (fwd + bwd ~ 3x fwd). Counts only the
    dense-layer and adjacency matmuls (models/graphsage.py) — gathers,
    segment reductions, activations, and the optimizer are excluded, so
    this is a floor on true executed FLOPs. The graph embedding runs once
    per STEP and is shared by the whole batch; per-sample cost divides it
    by `batch`. Dense-adjacency aggregation (dense_graph_arrays) adds the
    2*N^2*F_in matmul per layer that dominates at bench scale
    (VERDICT r3 weak #1: the published rate implied ~250x fewer FLOPs
    than this floor)."""
    fwd = 0.0
    f_in = node_feat_dim
    for _ in range(num_layers):
        if dense_adj:
            fwd += 2.0 * n_nodes * n_nodes * f_in          # adj @ h
        fwd += 2.0 * n_nodes * f_in * hidden               # W_self
        fwd += 2.0 * n_nodes * f_in * hidden               # W_neigh
        fwd += 2.0 * n_nodes * edge_feat_dim * hidden      # W_edge
        f_in = hidden
    # scoring head: B*P rows of [child, parent, pair] -> hidden -> hidden/2 -> 1
    rows = float(batch) * parents
    head_in = 2 * hidden + pair_feat_dim
    fwd += 2.0 * rows * head_in * hidden
    fwd += 2.0 * rows * hidden * (hidden // 2)
    fwd += 2.0 * rows * (hidden // 2)
    step = 3.0 * fwd  # value_and_grad ~ fwd + 2x fwd for the backward
    return step / max(batch, 1)


def gnn_roofline_bound(
    n_nodes: int,
    node_feat_dim: int,
    edge_feat_dim: int,
    hidden: int,
    batch: int,
    parents: int,
    pair_feat_dim: int,
    num_layers: int = 2,
    dense_adj: bool = True,
    # the shared roofline platform model (telemetry/costcard.py) — one
    # source of truth with bench.py and the cost-card verdicts
    peak_flops: float = _costcard.PEAK_FLOPS_BF16,
    hbm_bytes_per_s: float = _costcard.HBM_BYTES_PER_S,
    compute_bytes: int = 2,         # bf16 activations/weights
) -> dict:
    """Per-train-step roofline for the GraphSAGERanker: which stages are
    compute- vs memory-bound, and the MFU CEILING their byte traffic
    imposes (VERDICT r5 next #3 — the number the bench publishes so
    'GNN at 24.6% MFU' stops being folklore).

    Per-stage: matmul FLOPs + the HBM bytes its operands/results move;
    time lower bound = max(flops/peak, bytes/bw) per stage, summed
    (stages are data-dependent, so no overlap credit); ceiling =
    total_flops / (peak * Σ time_lb). Backward counted as 2× forward for
    both FLOPs and bytes (grad matmuls re-read activations at the same
    shapes). Elementwise ops, the optimizer, and XLA fusion wins are NOT
    modeled — real MFU lands below this ceiling, never above it.

    The structural story the numbers tell: the layer-0 adjacency matmul
    is [N,N]@[N,F] with F = node_feat_dim (~12) — arithmetic intensity
    2·F FLOPs per adjacency byte, far under the v5e ridge
    (peak/bw ≈ 240 FLOPs/byte), so the biggest FLOP consumer of the
    embed runs memory-bound; the segment_sum/scatter serving path is
    worse (≈0 matmul FLOPs per byte — pure bandwidth)."""
    ridge = peak_flops / hbm_bytes_per_s
    stages: list[dict] = []

    def stage(name: str, flops: float, nbytes: float) -> None:
        t = max(flops / peak_flops, nbytes / hbm_bytes_per_s)
        stages.append({
            "stage": name,
            "gflops": round(flops / 1e9, 2),
            "mbytes": round(nbytes / 1e6, 2),
            "ai_flops_per_byte": round(flops / max(nbytes, 1.0), 1),
            "bound": "compute" if flops / max(nbytes, 1.0) >= ridge else "memory",
            "time_us_lb": round(t * 1e6, 2),
        })

    f_in = node_feat_dim
    for layer in range(num_layers):
        if dense_adj:
            stage(
                f"sage_{layer}.adj_matmul",
                2.0 * n_nodes * n_nodes * f_in,
                # adjacency + input nodes + aggregated output
                compute_bytes * (n_nodes * n_nodes + 2.0 * n_nodes * f_in),
            )
        else:
            # gather + segment-sum path: ~zero matmul FLOPs, pure traffic
            stage(
                f"sage_{layer}.segment_sum",
                0.0,
                compute_bytes * 3.0 * n_nodes * f_in,  # gather+scatter+out
            )
        stage(
            f"sage_{layer}.dense",
            2.0 * n_nodes * f_in * hidden * 2        # W_self + W_neigh
            + 2.0 * n_nodes * edge_feat_dim * hidden,  # W_edge
            compute_bytes * (
                n_nodes * (2.0 * f_in + edge_feat_dim + hidden)
                + (2.0 * f_in + edge_feat_dim) * hidden
            ),
        )
        f_in = hidden
    rows = float(batch) * parents
    head_in = 2 * hidden + pair_feat_dim
    stage(
        "emb_gather",
        0.0,
        compute_bytes * (batch * hidden + rows * hidden),
    )
    stage(
        "score_head",
        2.0 * rows * (head_in * hidden + hidden * (hidden // 2) + (hidden // 2)),
        compute_bytes * (
            rows * (head_in + hidden + hidden // 2 + 1)
            + head_in * hidden + hidden * (hidden // 2) + hidden // 2
        ),
    )

    fwd_flops = sum(s["gflops"] for s in stages) * 1e9
    fwd_time = sum(s["time_us_lb"] for s in stages) / 1e6
    step_flops = 3.0 * fwd_flops          # fwd + ~2x fwd backward
    step_time_lb = 3.0 * fwd_time
    ceiling = 100.0 * step_flops / (peak_flops * max(step_time_lb, 1e-12))
    mem_stages = [s["stage"] for s in stages if s["bound"] == "memory"]
    out = {
        "peak_tflops": peak_flops / 1e12,
        "hbm_gbps": hbm_bytes_per_s / 1e9,
        "ridge_flops_per_byte": round(ridge, 1),
        "stages": stages,
        "step_gflops": round(step_flops / 1e9, 2),
        "step_time_us_lb": round(step_time_lb * 1e6, 2),
        "mfu_ceiling_pct": round(ceiling, 1),
        "memory_bound_stages": mem_stages,
        "method": (
            "per-stage max(flops/peak, bytes/bw), summed (no overlap "
            "credit); bwd = 2x fwd; elementwise/optimizer unmodeled, so "
            "achieved MFU must land BELOW the ceiling"
        ),
    }
    # name the actual dominant memory-bound stage (the adjacency matmul
    # on the dense path, segment_sum on the serving path) rather than
    # assuming stage order
    dominant = max(
        (s for s in stages if s["bound"] == "memory"),
        key=lambda s: s["time_us_lb"],
        default=stages[0],
    )
    out["statement"] = (
        f"matmul roofline ceiling {out['mfu_ceiling_pct']}% MFU at this "
        f"shape (ridge {out['ridge_flops_per_byte']} FLOPs/B): "
        f"{len(mem_stages)}/{len(stages)} stages memory-bound, led by "
        f"{dominant['stage']} (AI ~{dominant['ai_flops_per_byte']}); "
        "the scatter/segment_sum serving path is pure bandwidth (AI ~0)"
    )
    return out


def analytic_mlp_flops_per_sample(
    feat_dim: int, hidden: int, num_layers: int = 3
) -> float:
    """Matmul-only FLOP floor per trained sample for ProbeRTTRegressor
    (models/mlp.py: (num_layers-1) hidden Dense + 1 output Dense;
    fwd + bwd ~ 3x fwd)."""
    fwd = 2.0 * feat_dim * hidden
    fwd += max(num_layers - 2, 0) * 2.0 * hidden * hidden
    fwd += 2.0 * hidden
    return 3.0 * fwd


def analytic_attention_flops_per_sample(
    token_feat_dim: int,
    hidden: int,
    parents: int,
    num_layers: int = 2,
) -> float:
    """Matmul-only FLOP lower bound per trained sample for one
    AttentionRanker train step (models/attention.py: embed Dense, per
    block qkv/attention/proj + 4x FFN, score head; fwd + bwd ~ 3x fwd).
    Each sample is one row of P candidate tokens, so per-sample cost is
    P tokens' worth of transformer math — no batch-shared embedding to
    amortize like the GNN's graph pass."""
    p, h = float(parents), float(hidden)
    fwd = 2.0 * p * token_feat_dim * h                      # embed
    per_block = (
        2.0 * p * h * 3 * h        # qkv
        + 4.0 * p * p * h          # scores + weighted sum (2 matmuls)
        + 2.0 * p * h * h          # proj
        + 2.0 * p * h * 4 * h      # mlp_up
        + 2.0 * p * 4 * h * h      # mlp_down
    )
    fwd += num_layers * per_block
    fwd += 2.0 * p * h             # score head
    return 3.0 * fwd


def _epoch_flops(jitted, *args) -> float:
    """Total FLOPs of one compiled epoch call per XLA's cost analysis;
    the lowering is cached, so the real epoch call pays no extra compile.
    The SAME compiled executable also lands in the cost-card ledger
    (telemetry/costcard.py) — the trainer step's per-(entry, signature)
    CostCard costs zero extra compiles because this one-shot lowering
    already exists for the FLOP accounting."""
    try:
        compiled = jitted.lower(*args).compile()
    except Exception:  # noqa: BLE001 - metrics must never break training
        return 0.0
    try:
        from dragonfly2_tpu.telemetry import costcard

        entry = (
            f"{jitted.service}.{jitted.name}"
            if hasattr(jitted, "service") and hasattr(jitted, "name")
            else "trainer.epoch"
        )
        card = costcard.ledger().register_compiled(
            entry, compiled, signature_repr=costcard._sig_repr(args)
        )
        return card.flops
    except Exception:  # noqa: BLE001
        pass
    try:
        analysis = compiled.cost_analysis()
        if isinstance(analysis, list):
            analysis = analysis[0] if analysis else {}
        return float(analysis.get("flops", 0.0) or 0.0)
    except Exception:  # noqa: BLE001
        return 0.0


def _make_step(loss_fn: Callable, optimizer: optax.GradientTransformation):
    @jax.jit
    def step(params, opt_state, batch):
        loss, grads = jax.value_and_grad(loss_fn)(params, batch)
        updates, opt_state = optimizer.update(grads, opt_state, params)
        params = optax.apply_updates(params, updates)
        return params, opt_state, loss

    return step


def _make_epoch(loss_fn: Callable, optimizer: optax.GradientTransformation):
    """Whole-epoch step: `lax.scan` over a [S, B, ...] batch stack in ONE
    jit-compiled device call — the per-step host round-trip (a dispatch +
    a blocking loss read) is the trainer's real bottleneck on TPU, not the
    math. Buffers are donated so params/opt_state update in place."""

    @functools.partial(jax.jit, donate_argnums=(0, 1))
    def run_epoch(params, opt_state, batches):
        def body(carry, batch):
            params, opt_state = carry
            loss, grads = jax.value_and_grad(loss_fn)(params, batch)
            updates, opt_state = optimizer.update(grads, opt_state, params)
            params = optax.apply_updates(params, updates)
            return (params, opt_state), loss

        (params, opt_state), losses = jax.lax.scan(body, (params, opt_state), batches)
        return params, opt_state, losses

    # flight-recorder wrapper (telemetry/flight.py): compile/retrace count
    # per batch-stack signature + dispatch/device time split; attribute
    # access (.lower for _epoch_flops) forwards to the jitted fn
    from dragonfly2_tpu.telemetry.flight import instrument_jit

    return instrument_jit(run_epoch, "trainer.epoch", service="trainer")


def _stack_batches(batches: list) -> object:
    """list of same-shape batch pytrees -> one pytree with leading [S]."""
    return jax.tree_util.tree_map(lambda *xs: np.stack(xs), *batches)


def _make_epoch_indexed(loss_fn: Callable, optimizer: optax.GradientTransformation):
    """Epoch step over a DEVICE-RESIDENT dataset: the full training arrays
    live on the chip once; each epoch ships only an [S, B] permutation of
    row indices (~KBs) and the scan body gathers its batch on device.
    Removes the per-epoch host->device batch transfer, which costs more
    than the compute itself on a tunneled/busy PCIe path."""

    @functools.partial(jax.jit, donate_argnums=(0, 1))
    def run_epoch(params, opt_state, data, static, idx):
        def body(carry, idx_row):
            params, opt_state = carry
            batch = jax.tree_util.tree_map(lambda a: a[idx_row], data)
            loss, grads = jax.value_and_grad(loss_fn)(params, batch, static)
            updates, opt_state = optimizer.update(grads, opt_state, params)
            params = optax.apply_updates(params, updates)
            return (params, opt_state), loss

        (params, opt_state), losses = jax.lax.scan(body, (params, opt_state), idx)
        return params, opt_state, losses

    # flight-recorder wrapper: a retrace here means a new [S, B] index
    # shape slipped into the epoch loop — exactly the regression the
    # epoch-fusion divisor logic exists to prevent
    from dragonfly2_tpu.telemetry.flight import instrument_jit

    return instrument_jit(run_epoch, "trainer.epoch_indexed", service="trainer")


def _index_epochs(
    loss_fn, optimizer, data_full, n_rows, batch_size, epochs, rng,
    static_data=None, start_epoch=0, on_epoch=None, epoch_fusion=1,
):
    """Run `epochs` scanned epochs over device-resident `data_full`
    (single-chip path). `static_data` (e.g. graph arrays) rides along as a
    runtime argument rather than a closure capture — captured arrays bake
    into the compiled program as constants, which a 400 MB adjacency must
    not. loss_fn(params, batch, static_data). `start_epoch`/`on_epoch`
    support checkpoint resume (losses cover only the epochs actually run;
    the minibatch permutation stream restarts on resume)."""
    epoch_fn = _make_epoch_indexed(loss_fn, optimizer)
    data_dev = jax.device_put(data_full)
    static_dev = jax.device_put(static_data) if static_data is not None else None

    def run(params, opt_state):
        losses, epoch_samples, epoch_secs = [], [], []
        flops_per_sample = 0.0
        # Normalize fusion to a DIVISOR of the epoch span: a shorter final
        # block would have a different idx shape and recompile inside the
        # timed region, corrupting the steady-state throughput the fusion
        # exists to protect.
        span = max(epochs - start_epoch, 1)
        fusion = max(min(int(epoch_fusion), span), 1)
        while span % fusion:
            fusion -= 1
        e = start_epoch
        while e < epochs:
            # fuse `fusion` epochs' permutations into one scanned device
            # call — on a tunneled device a tiny epoch costs less than the
            # dispatch round-trip, which would otherwise BE the measured
            # (and paid) per-epoch time
            k = min(fusion, epochs - e)
            idx = np.concatenate(
                [
                    np.stack(list(D.minibatches(n_rows, batch_size, rng)))
                    for _ in range(k)
                ]
            ).astype(np.int32)
            if not flops_per_sample:
                total = _epoch_flops(epoch_fn, params, opt_state, data_dev, static_dev, idx)
                flops_per_sample = total / max(idx.shape[0] * batch_size, 1)
            t0 = time.perf_counter()
            params, opt_state, ep_losses = epoch_fn(
                params, opt_state, data_dev, static_dev, idx
            )
            # Time via a forced device->host fetch of the (tiny) loss
            # vector, NOT block_until_ready: on the tunneled `axon`
            # backend block_until_ready returns before execution finishes,
            # which produced BENCH_r03's physically impossible 156% MFU.
            # A D2H read cannot complete until the computation has.
            ep_np = np.asarray(jax.device_get(ep_losses))
            epoch_secs.append(time.perf_counter() - t0)
            epoch_samples.append(idx.shape[0] * batch_size)
            losses.append(ep_np)
            e += k
            if on_epoch is not None:
                on_epoch(e - 1, params, opt_state)
        flat = [float(v) for ep in losses for v in np.asarray(ep, np.float64)]
        n_samples, dt = _steady_state_throughput(epoch_samples, epoch_secs)
        peak = _peak_rate(epoch_samples, epoch_secs)
        return params, opt_state, flat, n_samples, dt, flops_per_sample, peak

    return run


def _stacked_epochs(
    loss_fn, optimizer, mesh, epochs, batch_size, make_epoch_batches: Callable,
    start_epoch=0, on_epoch=None,
):
    """Mesh-path counterpart of `_index_epochs`: per epoch, build host
    batches via `make_epoch_batches()`, stack + shard them over dp, and run
    one scanned device call. One implementation so the timing/throughput
    bookkeeping can't drift between the three trainers."""
    epoch_fn = _make_epoch(loss_fn, optimizer)

    def run(params, opt_state):
        losses, epoch_samples, epoch_secs = [], [], []
        flops_per_sample = 0.0
        for e in range(start_epoch, epochs):
            batches = make_epoch_batches()
            if not batches:
                continue
            stack = shard_stacked_batches(mesh, _stack_batches(batches))
            if not flops_per_sample:
                total = _epoch_flops(epoch_fn, params, opt_state, stack)
                flops_per_sample = total / max(len(batches) * batch_size, 1)
            t0 = time.perf_counter()
            params, opt_state, ep_losses = epoch_fn(params, opt_state, stack)
            # Forced D2H fetch, not block_until_ready — see _index_epochs.
            ep_np = np.asarray(jax.device_get(ep_losses))
            epoch_secs.append(time.perf_counter() - t0)
            epoch_samples.append(len(batches) * batch_size)
            losses.extend(np.asarray(ep_np, np.float64).tolist())
            if on_epoch is not None:
                on_epoch(e, params, opt_state)
        n_samples, dt = _steady_state_throughput(epoch_samples, epoch_secs)
        peak = _peak_rate(epoch_samples, epoch_secs)
        return params, opt_state, losses, n_samples, dt, flops_per_sample, peak

    return run


def _resume_hooks(checkpointer, params, opt_state):
    """(params, opt_state, start_epoch, on_epoch) for optional
    checkpoint/resume (training/checkpoint.py): restore the newest epoch if
    one exists, and save after every epoch. The data-plane analogue is the
    daemon's persistent-task reload + piece-bitset resume
    (storage_manager.go:545,674); the reference has no ML equivalent."""
    if checkpointer is None:
        return params, opt_state, 0, None
    saved = checkpointer.restore(
        template={"params": params, "opt_state": opt_state, "epoch": 0}
    )
    start_epoch = 0
    if saved is not None:
        params, opt_state = saved["params"], saved["opt_state"]
        start_epoch = int(np.asarray(saved["epoch"])) + 1

    def on_epoch(e, p, o):
        checkpointer.save(e, {"params": p, "opt_state": o, "epoch": e})

    return params, opt_state, start_epoch, on_epoch


def _peak_rate(epoch_samples: list, epoch_secs: list) -> float:
    """Best timed block's samples/s, first (compile-carrying) block
    excluded when more than one ran."""
    rates = [s / max(t, 1e-9) for s, t in zip(epoch_samples, epoch_secs)]
    if not rates:
        return 0.0
    return max(rates[1:] if len(rates) > 1 else rates)


def _steady_state_throughput(epoch_samples: list, epoch_secs: list) -> tuple:
    """(samples, seconds) for the throughput metric: the first epoch's
    device call carries the XLA compile (~tens of seconds over the dev
    tunnel), so with 2+ epochs it is excluded — samples_per_sec reports
    steady-state training speed, the number the >=50x-CPU north star is
    about (BASELINE.md)."""
    if len(epoch_secs) > 1:
        return sum(epoch_samples[1:]), max(sum(epoch_secs[1:]), 1e-9)
    return sum(epoch_samples), max(sum(epoch_secs), 1e-9)



def _train_eval_split(perm, eval_fraction: float):
    """Shuffled (eval_idx, train_idx). Degenerate datasets (a couple of
    rows from a smoke run) must still train: the eval split is capped so
    at least one training sample remains — an empty train_idx would make
    the batch step zero and crash; n == 1 trains and evals on the row."""
    n = len(perm)
    if n == 0:
        raise ValueError("cannot train on an empty dataset")
    n_eval = min(max(1, int(n * eval_fraction)), max(n - 1, 1))
    eval_idx, train_idx = perm[:n_eval], perm[n_eval:]
    if len(train_idx) == 0:
        train_idx = eval_idx
    return eval_idx, train_idx


def train_mlp(
    x: np.ndarray,
    y: np.ndarray,
    config: TrainerConfig | None = None,
    mesh=None,
    seed: int = 0,
    eval_fraction: float = 0.2,
    checkpointer=None,
) -> TrainResult:
    """Train the probe-RTT regressor; returns params + MSE/MAE on held-out
    pairs (the registry's evaluation fields)."""
    config = config or TrainerConfig()
    rng = np.random.default_rng(seed)
    n = x.shape[0]
    perm = rng.permutation(n)
    eval_idx, train_idx = _train_eval_split(perm, eval_fraction)

    model = ProbeRTTRegressor(hidden_dim=config.hidden_dim)
    params = model.init(jax.random.key(seed), jnp.zeros((1, x.shape[1]), jnp.float32))
    optimizer = optax.adamw(config.learning_rate)
    opt_state = optimizer.init(params)
    params, opt_state, start_epoch, on_epoch = _resume_hooks(
        checkpointer, params, opt_state
    )

    def loss_fn(params, batch):
        pred = model.apply(params, batch["x"])
        return ((pred - batch["y"]) ** 2 * batch["w"]).sum() / jnp.maximum(batch["w"].sum(), 1.0)

    batch_size = min(config.batch_size, len(train_idx))
    if mesh is None:
        data_full = {
            "x": x[train_idx],
            "y": y[train_idx],
            "w": np.ones(len(train_idx), np.float32),
        }
        run = _index_epochs(
            lambda p, b, _s: loss_fn(p, b),
            optimizer, data_full, len(train_idx), batch_size, config.epochs, rng,
            start_epoch=start_epoch, on_epoch=on_epoch,
            epoch_fusion=config.epoch_fusion,
        )
        params, opt_state, losses, n_samples, dt, flops_per_sample, peak = run(params, opt_state)
    else:
        params = jax.device_put(params, replicated(mesh))
        opt_state = jax.device_put(opt_state, replicated(mesh))

        def make_epoch_batches():
            return [
                {
                    "x": x[train_idx[idx]],
                    "y": y[train_idx[idx]],
                    "w": np.ones(len(idx), np.float32),
                }
                for idx in D.minibatches(len(train_idx), batch_size, rng)
            ]

        run = _stacked_epochs(
            loss_fn, optimizer, mesh, config.epochs, batch_size, make_epoch_batches,
            start_epoch=start_epoch, on_epoch=on_epoch,
        )
        params, opt_state, losses, n_samples, dt, flops_per_sample, peak = run(params, opt_state)

    pred = model.apply(params, jnp.asarray(x[eval_idx]))
    eval_metrics = M.regression_report(np.asarray(pred), y[eval_idx])
    return TrainResult(
        params=params,
        losses=losses,
        eval_metrics=eval_metrics,
        samples_per_sec=n_samples / max(dt, 1e-9),
        steps=len(losses),
        flops_per_sample=flops_per_sample,
        peak_samples_per_sec=peak,
        analytic_flops_per_sample=analytic_mlp_flops_per_sample(
            x.shape[1], config.hidden_dim, model.num_layers
        ),
    )


def train_gnn(
    ds: RankingDataset,
    graph: HostGraph,
    config: TrainerConfig | None = None,
    mesh=None,
    seed: int = 0,
    eval_fraction: float = 0.2,
    checkpointer=None,
) -> TrainResult:
    """Train the GraphSAGE parent ranker; eval = precision/recall/F1 of its
    top-1 parent picks on held-out downloads (manager/types/model.go:58-64)."""
    config = config or TrainerConfig()
    rng = np.random.default_rng(seed)
    n = ds.child.shape[0]
    perm = rng.permutation(n)
    eval_idx, train_idx = _train_eval_split(perm, eval_fraction)

    # Single-chip with a graph that fits: dense row-normalized adjacency
    # puts neighbor aggregation on the MXU (one matmul per layer) instead
    # of gather + scatter-add — same params, same math, ~5x faster step.
    use_dense = mesh is None and graph.node_feats.shape[0] <= D.DENSE_ADJ_MAX_NODES
    if use_dense:
        garrs = D.dense_graph_arrays(graph)
    else:
        garrs = D.graph_arrays(graph, pad_edges_to=D.edge_bucket(graph.edge_src.shape[0]))
    model = GraphSAGERanker(hidden_dim=config.hidden_dim)
    sample = _take_rank_batch(ds, train_idx[: min(2, len(train_idx))])
    params = model.init(
        jax.random.key(seed), garrs, sample.child_idx, sample.parent_idx, sample.pair_feats
    )
    optimizer = optax.adamw(config.learning_rate)
    opt_state = optimizer.init(params)
    params, opt_state, start_epoch, on_epoch = _resume_hooks(
        checkpointer, params, opt_state
    )

    def loss_fn(params, batch: RankBatch, graph_static=None):
        g = graph_static if graph_static is not None else garrs_dev
        scores = model.apply(params, g, batch.child_idx, batch.parent_idx, batch.pair_feats)
        return listwise_rank_loss(scores, batch.throughput, batch.mask)

    if mesh is not None:
        params = jax.device_put(params, replicated(mesh))
        opt_state = jax.device_put(opt_state, replicated(mesh))
        garrs_dev = jax.device_put(garrs, replicated(mesh))
    else:
        garrs_dev = jax.device_put(garrs)

    batch_size = min(config.batch_size, len(train_idx))
    if mesh is None:
        data_full = _take_rank_batch(ds, train_idx)
        run = _index_epochs(
            loss_fn, optimizer, data_full, len(train_idx), batch_size, config.epochs,
            rng, static_data=garrs_dev, start_epoch=start_epoch, on_epoch=on_epoch,
            epoch_fusion=config.epoch_fusion,
        )
        params, opt_state, losses, n_samples, dt, flops_per_sample, peak = run(params, opt_state)
    else:
        sub = _subset_rank_dataset(ds, train_idx)
        run = _stacked_epochs(
            loss_fn, optimizer, mesh, config.epochs, batch_size,
            lambda: list(D.rank_batches(sub, batch_size, rng)),
            start_epoch=start_epoch, on_epoch=on_epoch,
        )
        params, opt_state, losses, n_samples, dt, flops_per_sample, peak = run(params, opt_state)

    eval_batch = _take_rank_batch(ds, eval_idx)
    scores = model.apply(
        params, garrs_dev, eval_batch.child_idx, eval_batch.parent_idx, eval_batch.pair_feats
    )
    stats = M.top1_selection_stats(
        np.asarray(scores), eval_batch.throughput, eval_batch.mask
    )
    eval_metrics = {k: float(v) for k, v in stats.items()}
    analytic = analytic_gnn_flops_per_sample(
        n_nodes=graph.node_feats.shape[0],
        node_feat_dim=graph.node_feats.shape[1],
        edge_feat_dim=graph.edge_feats.shape[1],
        hidden=config.hidden_dim,
        batch=batch_size,
        parents=sample.parent_idx.shape[1],
        pair_feat_dim=sample.pair_feats.shape[-1],
        num_layers=model.num_layers,
        dense_adj=use_dense,
    )
    return TrainResult(
        params=params,
        losses=losses,
        eval_metrics=eval_metrics,
        samples_per_sec=n_samples / max(dt, 1e-9),
        steps=len(losses),
        flops_per_sample=flops_per_sample,
        peak_samples_per_sec=peak,
        analytic_flops_per_sample=analytic,
    )


def train_attention(
    ds: RankingDataset,
    config: TrainerConfig | None = None,
    mesh=None,
    seed: int = 0,
    eval_fraction: float = 0.2,
    checkpointer=None,
    sp_strategy: str | None = None,
) -> TrainResult:
    """Train the set-transformer parent ranker (models/attention.py) on
    the same RankingDataset the GNN consumes — candidates attend to each
    other, no graph needed.

    Every parallelism axis turns on from TrainerConfig alone (SURVEY
    §2.6; the round-2 gap was sp being the only reachable knob):
    - mesh dp > 1: batches shard over dp (always on with a mesh)
    - mesh sp > 1: ring or ulysses attention per `config.sp_strategy`
    - mesh tp > 1 + `config.attention_tp`: Megatron column/row split of
      qkv/proj/FFN via GSPMD param shardings — XLA inserts the psum
    - mesh ep > 1 + `config.attention_moe_experts`: top-1 MoE scorer
      FFN, expert queues over all_to_all (parallel/moe.py)
    - mesh pp > 1 + `config.attention_pp`: deep variant, one block per
      pp stage on the GPipe schedule (parallel/pipeline.py)
    """
    import functools

    from dragonfly2_tpu.models.attention import AttentionRanker
    from dragonfly2_tpu.parallel.ring import sharded_ring_attention
    from dragonfly2_tpu.parallel.ulysses import sharded_ulysses_attention
    from dragonfly2_tpu.parallel.mesh import PP_AXIS, SP_AXIS, TP_AXIS

    config = config or TrainerConfig()
    sp_strategy = sp_strategy or config.sp_strategy
    rng = np.random.default_rng(seed)
    n = ds.child.shape[0]
    perm = rng.permutation(n)
    eval_idx, train_idx = _train_eval_split(perm, eval_fraction)

    # ring and ulysses are drop-in swaps (same global-shape contract); ring
    # moves KV around the ICI ring, ulysses all-to-alls heads — pick per
    # workload (ulysses needs heads % sp == 0). Validated regardless of
    # mesh so a typo fails on single-chip runs too, not only at sp>1.
    strategies = {
        "ring": sharded_ring_attention,
        "ulysses": sharded_ulysses_attention,
    }
    if sp_strategy not in strategies:
        raise ValueError(f"unknown sp_strategy {sp_strategy!r}")
    attention_fn = None
    if mesh is not None and mesh.shape.get(SP_AXIS, 1) > 1:
        attention_fn = functools.partial(strategies[sp_strategy], mesh)

    def take(idx):
        return {
            "child": ds.child[idx],
            "parents": ds.parents[idx],
            "pair": _pair_feats(ds, idx),
            "mask": ds.mask[idx],
            "throughput": ds.throughput[idx],
        }

    sample = take(train_idx[: min(2, len(train_idx))])
    use_pp = (
        config.attention_pp and mesh is not None and mesh.shape.get(PP_AXIS, 1) > 1
    )
    if use_pp:
        apply, params = _build_pp_ranker(config, mesh, sample, seed)
    else:
        model = AttentionRanker(
            hidden_dim=config.hidden_dim,
            num_layers=config.attention_num_layers,
            moe_experts=config.attention_moe_experts,
        )

        def apply(params, child, parents, pair, mask):
            if attention_fn is not None:
                return model.apply(
                    params, child, parents, pair, mask,
                    attention_fn=attention_fn, mesh=mesh,
                )
            return model.apply(params, child, parents, pair, mask, mesh=mesh)

        params = model.init(
            jax.random.key(seed), sample["child"], sample["parents"],
            sample["pair"], sample["mask"], mesh=mesh,
        )
    optimizer = optax.adamw(config.learning_rate)
    opt_state = optimizer.init(params)
    params, opt_state, start_epoch, on_epoch = _resume_hooks(
        checkpointer, params, opt_state
    )

    def loss_fn(params, batch):
        scores = apply(params, batch["child"], batch["parents"], batch["pair"], batch["mask"])
        return listwise_rank_loss(scores, batch["throughput"], batch["mask"])

    if mesh is not None:
        if config.attention_tp and mesh.shape.get(TP_AXIS, 1) > 1 and not use_pp:
            params = jax.device_put(params, _attention_tp_shardings(mesh, params))
        else:
            params = jax.device_put(params, replicated(mesh))
        # opt state starts replicated; GSPMD re-shards the adam moments to
        # follow their (possibly tp-sharded) params inside the jitted step
        opt_state = jax.device_put(opt_state, replicated(mesh))

    batch_size = min(config.batch_size, len(train_idx))
    if mesh is None:
        data_full = take(train_idx)
        run = _index_epochs(
            lambda p, b, _s: loss_fn(p, b),
            optimizer, data_full, len(train_idx), batch_size, config.epochs, rng,
            start_epoch=start_epoch, on_epoch=on_epoch,
            epoch_fusion=config.epoch_fusion,
        )
        params, opt_state, losses, n_samples, dt, flops_per_sample, peak = run(params, opt_state)
    else:
        def make_epoch_batches():
            order = rng.permutation(len(train_idx))
            return [
                take(train_idx[order[start : start + batch_size]])
                for start in range(0, len(order) - batch_size + 1, batch_size)
            ]

        run = _stacked_epochs(
            loss_fn, optimizer, mesh, config.epochs, batch_size, make_epoch_batches,
            start_epoch=start_epoch, on_epoch=on_epoch,
        )
        params, opt_state, losses, n_samples, dt, flops_per_sample, peak = run(params, opt_state)

    eb = take(eval_idx)
    n_real = eb["mask"].shape[0]
    if mesh is not None:
        # The sharded attention path requires the batch dim to divide dp;
        # pad with masked-out rows and slice the scores back.
        dp = mesh.shape.get(DP_AXIS, 1)
        pad = (-n_real) % dp
        if pad:
            eb = {
                k: np.concatenate([v, np.zeros((pad,) + v.shape[1:], v.dtype)])
                for k, v in eb.items()
            }
    scores = apply(
        jax.device_put(params) if mesh is None else params,
        eb["child"], eb["parents"], eb["pair"], eb["mask"],
    )
    stats = M.top1_selection_stats(
        np.asarray(scores)[:n_real], eb["throughput"][:n_real], eb["mask"][:n_real]
    )
    analytic = analytic_attention_flops_per_sample(
        # dims read from the ACTUAL eval batch (the same arrays the model
        # consumed), never re-derived: an overstated floor would inflate
        # every published attention MFU with no error
        token_feat_dim=(
            eb["parents"].shape[-1] + eb["child"].shape[1] + eb["pair"].shape[-1]
        ),
        hidden=config.hidden_dim,
        parents=eb["parents"].shape[1],
        num_layers=config.attention_num_layers,
    )
    return TrainResult(
        params=params,
        losses=losses,
        eval_metrics={k: float(v) for k, v in stats.items()},
        samples_per_sec=n_samples / max(dt, 1e-9),
        steps=len(losses),
        flops_per_sample=flops_per_sample,
        peak_samples_per_sec=peak,
        analytic_flops_per_sample=analytic,
    )


def _attention_tp_shardings(mesh, params):
    """Megatron tensor-parallel GSPMD shardings for AttentionRanker
    params: qkv and mlp_up kernels column-split over tp (their biases
    follow the split output dim), proj and mlp_down kernels row-split
    (bias replicated — it adds after the psum XLA inserts). Everything
    else (embed, layer norms, score head) is replicated. No shard_map
    needed: annotating the params is the whole mechanism (scaling-book
    recipe; the hand-written kernel contract lives in parallel/tensor.py
    and its oracle tests)."""
    from jax.sharding import NamedSharding, PartitionSpec as P

    from dragonfly2_tpu.parallel.mesh import TP_AXIS

    def spec_for(path, leaf):
        joined = "/".join(str(getattr(k, "key", k)) for k in path)
        if "qkv" in joined or "mlp_up" in joined:
            if leaf.ndim == 2:
                spec = P(None, TP_AXIS)
            else:
                spec = P(TP_AXIS)
        elif ("proj" in joined or "mlp_down" in joined) and leaf.ndim == 2:
            spec = P(TP_AXIS, None)
        else:
            spec = P()
        return NamedSharding(mesh, spec)

    return jax.tree_util.tree_map_with_path(spec_for, params)


def _build_pp_ranker(config: TrainerConfig, mesh, sample: dict, seed: int):
    """Deep pipeline-parallel variant of the attention ranker: embed and
    score stay replicated on every device; the transformer blocks (one
    per pp stage) run the GPipe schedule (parallel/pipeline.py). The
    candidate mask rides the microbatch tensor as an extra channel so
    the single-argument stage contract holds. Returns (apply, params)."""
    import flax.linen as nn

    from dragonfly2_tpu.models.attention import SelfAttentionBlock
    from dragonfly2_tpu.parallel.mesh import PP_AXIS
    from dragonfly2_tpu.parallel.pipeline import sharded_pipeline_apply

    pp = mesh.shape[PP_AXIS]
    hidden = config.hidden_dim
    num_micro = config.attention_pp_microbatches
    dtype = jnp.bfloat16

    embed = nn.Dense(hidden, dtype=dtype)
    block = SelfAttentionBlock(hidden, compute_dtype=dtype)
    final_ln = nn.LayerNorm(dtype=dtype)
    score = nn.Dense(1, dtype=jnp.float32)

    def tokens_of(child, parents, pair):
        n, p, _ = parents.shape
        return jnp.concatenate(
            [
                parents.astype(dtype),
                jnp.broadcast_to(child[:, None, :], (n, p, child.shape[-1])).astype(dtype),
                pair.astype(dtype),
            ],
            axis=-1,
        )

    def stage_fn(p_block, a):  # a: [mb, P, hidden+1]
        tok, flag = a[..., :hidden], a[..., hidden:]
        y = block.apply(p_block, tok, flag[..., 0] > 0.5)
        return jnp.concatenate([y, flag], axis=-1)

    def apply(params, child, parents, pair, mask):
        x = embed.apply(params["embed"], tokens_of(child, parents, pair))
        n, p, _ = x.shape
        pad = (-n) % num_micro
        if pad:
            x = jnp.concatenate([x, jnp.zeros((pad, p, hidden), x.dtype)])
            mask_p = jnp.concatenate([mask, jnp.zeros((pad, p), mask.dtype)])
        else:
            mask_p = mask
        a = jnp.concatenate([x, mask_p[..., None].astype(x.dtype)], axis=-1)
        a = a.reshape(num_micro, -1, p, hidden + 1)
        y = sharded_pipeline_apply(mesh, stage_fn, params["blocks"], a)
        y = y.reshape(-1, p, hidden + 1)[:n, :, :hidden]
        h = final_ln.apply(params["ln"], y)
        scores = score.apply(params["score"], h)[..., 0]
        return jnp.where(mask, scores, -1e30)

    key = jax.random.key(seed)
    k_embed, k_ln, k_score, *k_blocks = jax.random.split(key, 3 + pp)
    tok = tokens_of(
        jnp.asarray(sample["child"]), jnp.asarray(sample["parents"]),
        jnp.asarray(sample["pair"]),
    )
    x0 = embed.init(k_embed, tok)
    x_sample = jnp.zeros(tok.shape[:-1] + (hidden,), dtype)
    stage_params = [
        block.init(k, x_sample, jnp.asarray(sample["mask"])) for k in k_blocks
    ]
    stacked = jax.tree_util.tree_map(lambda *xs: jnp.stack(xs), *stage_params)
    params = {
        "embed": x0,
        "blocks": stacked,
        "ln": final_ln.init(k_ln, x_sample),
        "score": score.init(k_score, x_sample),
    }
    return apply, params


def _pair_feats(ds: RankingDataset, idx: np.ndarray) -> np.ndarray:
    """(B, P, 2) pair features — the single definition both the GNN and
    attention trainers consume, so the families can never drift apart."""
    return np.concatenate(
        [ds.same_idc[idx, :, None], ds.loc_match[idx, :, None]], axis=-1
    ).astype(np.float32)


def _take_rank_batch(ds: RankingDataset, idx: np.ndarray) -> RankBatch:
    return RankBatch(
        child_idx=ds.child_host_idx[idx],
        parent_idx=ds.parent_host_idx[idx],
        pair_feats=_pair_feats(ds, idx),
        throughput=ds.throughput[idx],
        mask=ds.mask[idx],
    )


def _subset_rank_dataset(ds: RankingDataset, idx: np.ndarray) -> RankingDataset:
    return RankingDataset(
        child=ds.child[idx],
        parents=ds.parents[idx],
        same_idc=ds.same_idc[idx],
        loc_match=ds.loc_match[idx],
        mask=ds.mask[idx],
        throughput=ds.throughput[idx],
        child_host_idx=ds.child_host_idx[idx],
        parent_host_idx=ds.parent_host_idx[idx],
    )


def embed_graph_sharded(model: GraphSAGERanker, params, graph_arrays: dict, mesh):
    """Host embeddings with the EDGE set sharded across the whole mesh.

    Each device owns an edge shard, computes partial neighbor sums via
    `segment_sum` into a full-size node accumulator, then `psum` over both
    mesh axes combines partials — ICI traffic is 2 x nodes x dim per layer
    instead of the whole edge list. This is the scale path for 1M-piece /
    10k-peer traces (BASELINE.json configs[3]).
    """
    n_nodes = graph_arrays["node_feats"].shape[0]
    axes = (DP_AXIS, GRAPH_AXIS)
    n_shards = mesh.size

    # Pad the edge set to a multiple of the shard count; pads carry weight 0
    # so their segment contributions vanish.
    e = graph_arrays["edge_src"].shape[0]
    pad = (-e) % n_shards
    edge_src = jnp.concatenate([jnp.asarray(graph_arrays["edge_src"]), jnp.zeros(pad, jnp.int32)])
    edge_dst = jnp.concatenate([jnp.asarray(graph_arrays["edge_dst"]), jnp.zeros(pad, jnp.int32)])
    edge_feats = jnp.concatenate(
        [jnp.asarray(graph_arrays["edge_feats"]),
         jnp.zeros((pad,) + graph_arrays["edge_feats"].shape[1:], jnp.float32)]
    )
    edge_weight = jnp.concatenate([jnp.ones(e, jnp.float32), jnp.zeros(pad, jnp.float32)])

    def shard_fn(node_feats, edge_src, edge_dst, edge_feats, edge_weight):
        h = node_feats
        w = edge_weight.astype(jnp.float32)[:, None]
        for i in range(model.num_layers):
            layer_params = params["params"][f"sage_{i}"]
            h_c = h.astype(model.compute_dtype)
            # float32 segment accumulation, matching SAGELayer exactly
            ef = edge_feats.astype(jnp.float32) * w
            msgs = h_c[edge_dst].astype(jnp.float32) * w
            agg = jax.ops.segment_sum(msgs, edge_src, num_segments=n_nodes)
            cnt = jax.ops.segment_sum(w, edge_src, num_segments=n_nodes)
            e_agg = jax.ops.segment_sum(ef, edge_src, num_segments=n_nodes)
            # combine partial sums from every edge shard over ICI
            agg = jax.lax.psum(agg, axes)
            cnt = jax.lax.psum(cnt, axes)
            e_agg = jax.lax.psum(e_agg, axes)
            agg = (agg / jnp.maximum(cnt, 1.0)).astype(model.compute_dtype)
            e_agg = (e_agg / jnp.maximum(cnt, 1.0)).astype(model.compute_dtype)
            out = (
                h_c @ layer_params["self"]["kernel"].astype(model.compute_dtype)
                + layer_params["self"]["bias"].astype(model.compute_dtype)
                + agg @ layer_params["neigh"]["kernel"].astype(model.compute_dtype)
                + e_agg @ layer_params["edge"]["kernel"].astype(model.compute_dtype)
            )
            h = jax.nn.gelu(out)
        return h

    edge_spec = P((DP_AXIS, GRAPH_AXIS))
    fn = shard_map(
        shard_fn,
        mesh=mesh,
        in_specs=(P(), edge_spec, edge_spec, edge_spec, edge_spec),
        out_specs=P(),
        check_vma=False,
    )
    return jax.jit(fn)(
        jnp.asarray(graph_arrays["node_feats"]), edge_src, edge_dst, edge_feats, edge_weight
    )
