"""Native model serving into the scheduler — the loop the reference never
closed.

The reference's intended flow (SURVEY.md §2.3): trainer trains -> manager
CreateModel -> operator activates -> scheduler's "ml" evaluator calls a
*Triton sidecar* ModelInfer (pkg/rpc/inference/client/client_v1.go:83-123)
— except the "ml" evaluator silently falls back to the rule blend
(evaluator.go:84-86) and nothing is wired. Here the whole loop is native:

- `ModelServer` watches the registry's active-version pointer and hot-swaps
  params into jit-compiled apply fns (no recompilation: same shapes).
- `MLEvaluator` = the "ml" algorithm: GraphSAGE embeddings cached per host
  slot, per-request candidate scoring is one device call, then the SAME
  filter rules as the rule-based path (ops/evaluator.select_with_scores).
"""

from __future__ import annotations

import functools
from typing import Any

import jax
import jax.numpy as jnp
import numpy as np

from dragonfly2_tpu.config.constants import CONSTANTS
from dragonfly2_tpu.models.graphsage import GraphSAGERanker
from dragonfly2_tpu.models.mlp import ProbeRTTRegressor
from dragonfly2_tpu.ops import evaluator as ev
from dragonfly2_tpu.registry.registry import (
    MODEL_TYPE_ATTENTION,
    MODEL_TYPE_GNN,
    MODEL_TYPE_MLP,
    ModelRegistry,
)


class ModelServer:
    """Serves the ACTIVE version of one registered model, reloading on
    activation flips — the native ModelInfer replacement."""

    def __init__(
        self,
        registry: ModelRegistry,
        name: str,
        scheduler_host_id: str,
        model_type: str,
        template_params: Any,
        model: Any = None,
    ):
        self.registry = registry
        self.name = name
        self.model_type = model_type
        self.model_id = registry.model_id(name, scheduler_host_id)
        self._template = template_params
        self.params: Any = None
        self.version: int | None = None
        if model is not None:
            self.model = model
        elif model_type == MODEL_TYPE_GNN:
            self.model = GraphSAGERanker()
        elif model_type == MODEL_TYPE_MLP:
            self.model = ProbeRTTRegressor()
        elif model_type == MODEL_TYPE_ATTENTION:
            from dragonfly2_tpu.models.attention import AttentionRanker

            self.model = AttentionRanker()
        else:
            raise ValueError(model_type)

    def refresh(self) -> bool:
        """Pick up a newly activated version; returns True if swapped. The
        version's metadata records its architecture (hidden_dim), so the
        served module always matches the trained one."""
        active = self.registry.active_version(self.model_id)
        if active is None or active.version == self.version:
            return False
        # Rebuild the module if the version's recorded architecture differs
        # from the served one — hidden_dim alone is not enough for families
        # with more knobs (AttentionRanker: num_heads/num_layers, whose
        # param shapes can even agree while computing different functions).
        arch = {
            key: active.metadata[key]
            for key in ("hidden_dim", "num_heads", "num_layers")
            if key in active.metadata and active.metadata[key] is not None
        }
        changed = {
            key: value
            for key, value in arch.items()
            if hasattr(self.model, key) and getattr(self.model, key) != value
        }
        new_model = self.model
        if changed:
            cls = type(self.model)
            # start from the currently-served knobs and overlay the new
            # metadata: a knob omitted from v_{n+1}'s metadata means
            # "unchanged", never "reset to class default"
            kwargs = {
                key: getattr(self.model, key)
                for key in ("hidden_dim", "num_heads", "num_layers")
                if hasattr(self.model, key)
            }
            kwargs.update({k: v for k, v in arch.items() if k in kwargs})
            new_model = cls(**kwargs)
        # Load BEFORE assigning anything: a failed params read must leave
        # the served (model, params, version) triple untouched — swapping
        # the module first and then raising would leave a mismatched pair
        # behind for callers that catch the error and keep serving.
        new_params = self.registry.load_params(
            self.model_id, active.version, template=self._template
        )
        # Commit to device ONCE here: load_params returns numpy leaves
        # (topology portability), and numpy params passed to every jitted
        # infer/schedule call would re-pay one host->device transfer PER
        # LEAF PER CALL — ~25 round-trips on the tunneled TPU, which
        # dominated the ml tick (~2 s/tick in a degraded window).
        self.model = new_model
        self.params = jax.device_put(new_params)
        self.version = active.version
        return True

    @property
    def ready(self) -> bool:
        return self.params is not None

    # ------------------------------------------------------------- infer

    def infer_mlp(self, x: jax.Array) -> jax.Array:
        """Predicted log1p(rtt_ms) for (N, F) pair features."""
        return mlp_apply(self.model, self.params, x)

    def embed_hosts(self, graph_arrays: dict) -> jax.Array:
        """(H, D) host embeddings for the current params."""
        return _gnn_embed(self.model, self.params, graph_arrays)

    def snapshot(self) -> tuple[Any, Any, int | None]:
        """(model, params, version) read together — callers that must not
        see a concurrent refresh() swap half-applied (the inference RPC)
        take this under their lock and run the pure apply fns on it."""
        return self.model, self.params, self.version

    def score_set(self, child_feats, parent_feats, pair_feats, mask) -> jax.Array:
        """(B, P) candidate scores from the set-transformer ranker
        (models/attention.py) — candidates attend to each other, no
        embedding cache needed."""
        return attention_score(
            self.model, self.params, child_feats, parent_feats, pair_feats, mask
        )


@functools.partial(jax.jit, static_argnames=("model",))
def mlp_apply(model, params, x):
    return model.apply(params, x)


@functools.partial(jax.jit, static_argnames=("model",))
def _gnn_embed(model, params, graph_arrays):
    return model.apply(
        params,
        graph_arrays["node_feats"],
        graph_arrays["edge_src"],
        graph_arrays["edge_dst"],
        graph_arrays["edge_feats"],
        method="embed",
    )


@functools.partial(jax.jit, static_argnames=("model",))
def attention_score(model, params, child_feats, parent_feats, pair_feats, mask):
    return model.apply(params, child_feats, parent_feats, pair_feats, mask)


@functools.partial(jax.jit, static_argnames=("model",))
def gnn_score(model, params, host_emb, child_host, cand_host, pair_feats):
    child_emb = host_emb[child_host]
    parent_emb = host_emb[cand_host]
    return model.apply(params, child_emb, parent_emb, pair_feats, method="score")


class MLEvaluator:
    """The "ml" scheduling algorithm, actually wired.

    Scores candidates with the served GraphSAGE ranker when a version is
    active; falls back to the rule blend otherwise (the reference's
    fallback, evaluator.go:76-90, except here the ml path exists).
    """

    def __init__(self, server: ModelServer, fallback_algorithm: str = "default"):
        self.server = server
        self.fallback = fallback_algorithm
        # the ensemble's residual base: the same rule blend the fallback
        # path uses ("plugin" has no in-jit blend, so it bases on default)
        self._base_alg = (
            fallback_algorithm if fallback_algorithm in ("default", "nt")
            else "default"
        )
        self._host_emb: jax.Array | None = None

    def refresh_embeddings(self, graph_arrays: dict) -> None:
        """Recompute host-slot embeddings (call after topology/trace sync,
        and after server.refresh() swaps params)."""
        if self.server.ready:
            self._host_emb = self.server.embed_hosts(graph_arrays)

    def schedule(
        self,
        feats: dict,
        child_host_slot: np.ndarray | None = None,
        cand_host_slot: np.ndarray | None = None,
        blocklist=None,
        in_degree=None,
        can_add_edge=None,
        limit: int = CONSTANTS.CANDIDATE_PARENT_LIMIT,
    ) -> dict:
        if self.server.ready and self._host_emb is not None and child_host_slot is not None:
            # ONE fused device call per chunk (pair features + embedding
            # gathers + scoring + masked selection). Dispatching these as
            # separate eager/jit calls cost 4 round trips per tick — over
            # a tunneled device that made the ml path ~10x slower than the
            # rule blend, which needs exactly one dispatch.
            return _ml_schedule(
                self.server.model,
                self.server.params,
                self._host_emb,
                child_host_slot,
                cand_host_slot,
                feats,
                blocklist,
                in_degree,
                can_add_edge,
                limit,
                algorithm=self._base_alg,
            )
        return ev.schedule_candidate_parents(
            feats, blocklist, in_degree, can_add_edge, algorithm=self.fallback, limit=limit
        )

    def schedule_packed(
        self,
        feats: dict,
        child_host_slot: np.ndarray | None = None,
        cand_host_slot: np.ndarray | None = None,
        blocklist=None,
        in_degree=None,
        can_add_edge=None,
        limit: int = CONSTANTS.CANDIDATE_PARENT_LIMIT,
    ):
        """Serving-path twin of `schedule`: one fused device call whose only
        output is the packed (B, limit, 2) selection (ops/evaluator.py
        `_pack_selection`) — one D2H per tick chunk."""
        if self.server.ready and self._host_emb is not None and child_host_slot is not None:
            return _ml_schedule_packed(
                self.server.model,
                self.server.params,
                self._host_emb,
                child_host_slot,
                cand_host_slot,
                feats,
                blocklist,
                in_degree,
                can_add_edge,
                limit,
                algorithm=self._base_alg,
            )
        return ev.schedule_candidate_parents_packed(
            feats, blocklist, in_degree, can_add_edge, algorithm=self.fallback, limit=limit
        )

    def schedule_from_packed(
        self, buf, b, k, c, l, n,
        limit: int = CONSTANTS.CANDIDATE_PARENT_LIMIT,
    ):
        """Single-buffer-transport twin of `schedule_packed` (the tick's
        one-H2D contract; ops/evaluator.pack_eval_batch). Falls back to
        the linear blend over the same buffer until a model is served."""
        if self.server.ready and self._host_emb is not None:
            return _ml_schedule_from_packed(
                self.server.model, self.server.params, self._host_emb,
                buf, b, k, c, l, n, limit, algorithm=self._base_alg,
            )
        return ev.schedule_from_packed(
            buf, b, k, c, l, n, algorithm=self.fallback, limit=limit
        )


@jax.jit
def _loc_match_fraction(parent_loc, child_loc):
    child = child_loc[:, None, :]
    elem_eq = (parent_loc == child) & (parent_loc != 0) & (child != 0)
    prefix = jnp.cumprod(elem_eq.astype(jnp.int32), axis=-1)
    return prefix.sum(-1).astype(jnp.float32) / CONSTANTS.MAX_LOCATION_ELEMENTS


# The served model REFINES the rule blend instead of replacing it: final
# score = blend + ALPHA * z(gnn) * max(std(blend_row), STD_FLOOR). The
# learned logits are z-scored within each candidate row (scale-free), then
# bounded by the row's own blend spread, so the model can reorder
# candidates the blend finds comparable but can never promote one the
# blend rules out — and a cold/weak model degrades to the blend, not to
# noise. (Full-scale A/B, BENCH r5 loop leg: the pure-model scorer landed
# between random and the blend; the residual form is how the learned
# signal adds to the engineered priors rather than competing with them.
# The reference never reached this question — its ml path is dead code,
# evaluator.go:84-86.)
ML_RESIDUAL_ALPHA = 0.5
ML_RESIDUAL_STD_FLOOR = 0.02


def _ensemble_scores(feats: dict, gnn_logits: jax.Array,
                     algorithm: str = "default") -> jax.Array:
    valid = feats["valid"].astype(jnp.float32)
    cnt = jnp.maximum(valid.sum(-1, keepdims=True), 1.0)

    def _masked_moments(x):
        mean = (x * valid).sum(-1, keepdims=True) / cnt
        var = (((x - mean) ** 2) * valid).sum(-1, keepdims=True) / cnt
        return mean, var

    # the residual base is the CONFIGURED rule blend (the evaluator's
    # fallback algorithm), not a hardcoded "default": an nt cluster must
    # keep its probe/RTT prior when the model comes online
    blend = ev.evaluate(feats, algorithm)
    g_mean, g_var = _masked_moments(gnn_logits)
    z = (gnn_logits - g_mean) * jax.lax.rsqrt(g_var + 1e-6)
    _, b_var = _masked_moments(blend)
    scale = jnp.maximum(jnp.sqrt(b_var), ML_RESIDUAL_STD_FLOOR)
    return blend + ML_RESIDUAL_ALPHA * z * scale


@functools.partial(jax.jit, static_argnames=("model", "limit", "algorithm"))
def _ml_schedule(
    model, params, host_emb, child_host, cand_host, feats,
    blocklist, in_degree, can_add_edge, limit, algorithm="default",
):
    """Fused ml-path schedule: everything from raw candidate features to
    the selected parents in one compiled program."""
    child_idc = feats["child_idc"][..., None]
    pair_feats = jnp.stack(
        [
            ((feats["parent_idc"] == child_idc) & (child_idc != 0)).astype(jnp.float32),
            _loc_match_fraction(feats["parent_location"], feats["child_location"]),
        ],
        axis=-1,
    )
    scores = _ensemble_scores(
        feats,
        gnn_score(model, params, host_emb, child_host, cand_host, pair_feats),
        algorithm,
    )
    return ev.select_with_scores(
        feats, scores, blocklist, in_degree, can_add_edge, limit=limit
    )


@functools.partial(jax.jit, static_argnames=("model", "limit", "algorithm"))
def _ml_schedule_packed(
    model, params, host_emb, child_host, cand_host, feats,
    blocklist, in_degree, can_add_edge, limit, algorithm="default",
):
    """`_ml_schedule` with the packed single-output selection contract."""
    child_idc = feats["child_idc"][..., None]
    pair_feats = jnp.stack(
        [
            ((feats["parent_idc"] == child_idc) & (child_idc != 0)).astype(jnp.float32),
            _loc_match_fraction(feats["parent_location"], feats["child_location"]),
        ],
        axis=-1,
    )
    scores = _ensemble_scores(
        feats,
        gnn_score(model, params, host_emb, child_host, cand_host, pair_feats),
        algorithm,
    )
    return ev.select_with_scores_packed(
        feats, scores, blocklist, in_degree, can_add_edge, limit=limit
    )


@functools.partial(
    jax.jit, static_argnames=("model", "b", "k", "c", "l", "n", "limit", "algorithm")
)
def _ml_schedule_from_packed(model, params, host_emb, buf, b, k, c, l, n, limit,
                             algorithm="default"):
    """`_ml_schedule_packed` over the single-buffer transport
    (ops/evaluator.pack_eval_batch): the whole ml tick is one H2D + one
    dispatch + one D2H like the linear-blend path — only the (device-
    resident) embedding table and params stay out of the buffer."""
    f = ev.unpack_eval_batch(buf, b, k, c, l, n)
    child_idc = f["child_idc"][..., None]
    pair_feats = jnp.stack(
        [
            ((f["parent_idc"] == child_idc) & (child_idc != 0)).astype(jnp.float32),
            _loc_match_fraction(f["parent_location"], f["child_location"]),
        ],
        axis=-1,
    )
    scores = _ensemble_scores(f, gnn_score(
        model, params, host_emb, f["child_host_slot"], f["cand_host_slot"], pair_feats
    ), algorithm)
    return ev.select_with_scores_packed(
        f, scores, f["blocklist"], f["in_degree"], f["can_add_edge"], limit=limit
    )


# Flight-recorder instrumentation (telemetry/flight.py) on the ml serving
# entry points: the fused ml tick call and the embedding refresh — the two
# programs whose silent retraces used to be invisible until a 35 s compile
# landed mid-tick.
from dragonfly2_tpu.telemetry.flight import instrument_jit as _instrument_jit  # noqa: E402

_ml_schedule_from_packed = _instrument_jit(
    _ml_schedule_from_packed, "ml.schedule_from_packed", service="scheduler"
)
_gnn_embed = _instrument_jit(_gnn_embed, "ml.embed_hosts", service="scheduler")
