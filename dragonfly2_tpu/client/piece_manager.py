"""Piece acquisition: from a parent peer or back-to-source.

Capability parity with client/daemon/peer/piece_manager.go (DownloadPiece
:170 — HTTP GET from the parent's upload server with digest verification;
DownloadSource :303 + concurrent piece groups :793-921 — ranged source
reads split into pieces and written concurrently).
"""

from __future__ import annotations

import concurrent.futures
import time
import urllib.error
import urllib.request

from dragonfly2_tpu.client import source as source_pkg
from dragonfly2_tpu.client.storage import TaskStorage
from dragonfly2_tpu.utils import dferrors
from dragonfly2_tpu.utils.digest import md5_from_bytes


def piece_layout(content_length: int, piece_length: int) -> list[tuple[int, int, int]]:
    """[(number, offset, length)] covering content_length."""
    if content_length < 0:
        raise ValueError("content_length unknown")
    out = []
    n = 0
    off = 0
    while off < content_length:
        length = min(piece_length, content_length - off)
        out.append((n, off, length))
        n += 1
        off += length
    return out


class PieceManager:
    def __init__(self, timeout: float = 30.0, concurrency: int = 4):
        self.timeout = timeout
        self.concurrency = concurrency

    # ------------------------------------------------------------- parents

    def download_piece_from_parent(
        self, ts: TaskStorage, parent_ip: str, parent_port: int, number: int, offset: int,
        expected_digest: str = "",
    ) -> int:
        """Fetch one piece over the parent's upload server; returns bytes
        written. `expected_digest` is the scheduler-ATTESTED md5 for this
        piece (origin-reported, distributed in schedule responses); when
        present it is authoritative and the parent's header is advisory
        only — a parent serving corrupt bytes under a self-consistent
        header still fails here. Verification happens BEFORE commit, so
        corrupt bytes never reach disk; a mismatch raises the typed
        PieceCorrupted the conductor reports as reason="corruption"."""
        url = f"http://{parent_ip}:{parent_port}/download/{ts.meta.task_id}?piece={number}"
        t0 = time.perf_counter_ns()
        try:
            with urllib.request.urlopen(url, timeout=self.timeout) as resp:
                data = resp.read()
                header_digest = resp.headers.get("X-Dragonfly-Piece-Digest", "")
        except (urllib.error.URLError, ConnectionError, TimeoutError) as e:
            raise dferrors.Unavailable(f"parent piece fetch {url}: {e}") from e
        cost = time.perf_counter_ns() - t0
        digest = expected_digest or header_digest
        if digest:
            actual = md5_from_bytes(data)
            if actual != digest:
                raise dferrors.PieceCorrupted(
                    f"piece {number} from {parent_ip}:{parent_port}: digest "
                    f"{actual} != {'attested' if expected_digest else 'header'} "
                    f"{digest}"
                )
        # verified=True: the check above already hashed this exact buffer
        ts.write_piece(number, offset, data, digest=digest, cost_ns=cost,
                       verified=bool(digest))
        return len(data)

    # -------------------------------------------------------------- source

    def download_source(
        self, ts: TaskStorage, url: str, headers: dict | None = None,
        on_piece=None,
    ) -> tuple[int, int]:
        """Back-to-source download of the whole task; returns
        (content_length, piece_count). Known-length sources fan out ranged
        piece-group fetches; unknown-length streams sequentially.
        `on_piece(number, length, cost_ns, digest)` fires per committed
        piece with the md5 this fetcher computed — the origin fetch is the
        digest chain's trust anchor, so the conductor reports these to the
        scheduler with each piece-finished message."""
        content_length = source_pkg.content_length(url, headers)
        piece_length = ts.meta.piece_length
        use_ranges = content_length >= 0
        if use_ranges:
            layout = piece_layout(content_length, piece_length)
            if len(layout) > 1 and not source_pkg.supports_range(url, headers):
                # Server ignores Range (python -m http.server, some CDNs):
                # concurrent ranged workers would each re-download and
                # discard the file head — O(N^2) transfer. Stream once.
                use_ranges = False
        if use_ranges:
            with concurrent.futures.ThreadPoolExecutor(self.concurrency) as pool:
                futures = {
                    pool.submit(self._fetch_range, url, headers, off, length): (n, off, length)
                    for n, off, length in layout
                }
                for future in concurrent.futures.as_completed(futures):
                    n, off, length = futures[future]
                    data, cost = future.result()
                    if len(data) != length:
                        raise dferrors.Unavailable(
                            f"source range {off}+{length} returned {len(data)} bytes"
                        )
                    digest = md5_from_bytes(data)
                    ts.write_piece(n, off, data, digest=digest, cost_ns=cost,
                                   verified=True)
                    if on_piece is not None:
                        on_piece(n, length, cost, digest)
            ts.mark_done(content_length, len(layout))
            return content_length, len(layout)
        # unknown length: sequential stream, cut into pieces as it arrives
        number, offset, buf = 0, 0, b""
        t0 = time.perf_counter_ns()
        for chunk in source_pkg.download(url, headers):
            buf += chunk
            while len(buf) >= piece_length:
                piece, buf = buf[:piece_length], buf[piece_length:]
                cost = time.perf_counter_ns() - t0
                digest = md5_from_bytes(piece)
                ts.write_piece(number, offset, piece, digest=digest, cost_ns=cost,
                               verified=True)
                if on_piece is not None:
                    on_piece(number, len(piece), cost, digest)
                number += 1
                offset += len(piece)
                t0 = time.perf_counter_ns()
        if buf:
            cost = time.perf_counter_ns() - t0
            digest = md5_from_bytes(buf)
            ts.write_piece(number, offset, buf, digest=digest, cost_ns=cost,
                           verified=True)
            if on_piece is not None:
                on_piece(number, len(buf), cost, digest)
            number += 1
            offset += len(buf)
        ts.mark_done(offset, number)
        return offset, number

    def _fetch_range(self, url: str, headers: dict | None, offset: int, length: int):
        t0 = time.perf_counter_ns()
        data = b"".join(source_pkg.download(url, headers, offset, length))
        return data, time.perf_counter_ns() - t0
