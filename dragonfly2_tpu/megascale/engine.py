"""Event-batch simulation engine — the megascale scenario lab's core.

``ClusterSimulator`` (the per-peer oracle) drives the scheduler one
response at a time and one PIECE at a time: a Python loop per wave draws
each piece's cost/fault and reports it. That tops out around 10^4 hosts.
``EventBatchEngine`` subclasses it and keeps every protocol interaction
(arrival draws, registration, seed triggers, churn/crash/partition
handling) bit-identical — that is what makes the small-scale paired-seed
equivalence test possible — while replacing the per-piece wave loop with
ONE vectorized event batch per round over columnar peer state:

- per-download columns (task, host, region, have-bitset, wave, virtual
  transfer time) indexed by the deterministic registration counter, so a
  response's peer id decodes to its row with integer math, no dicts;
- a round's NormalTaskResponses expand into a flat (event,) table —
  (child, parent, task, piece, wave) — missing pieces enumerated from
  the have-bitsets in one pass;
- costs and faults price per BATCH: the WAN topologies use the
  vectorized counter-hash model (megascale/topology.WanCostModel), plain
  scenario specs fall back to the oracle's per-event blake2b draws so
  paired runs match draw for draw;
- wave semantics (first error/corrupt aborts the wave, a churn crash
  lands after a piece-count threshold, stalls complete with their cost)
  reduce to per-row cutoffs computed with `np.minimum.at`;
- reports feed the scheduler's PR-8 bulk APIs: one
  ``pieces_finished_batch`` per completed wave slice,
  ``register_peers_batch`` for arrival waves, ``leave_hosts_batch`` for
  churn/upgrade cohorts.

On top of the engine ride the traffic models only the megascale lab can
express: diurnal Zipf arrivals, flash-crowd preheat storms, and
rolling-upgrade churn waves (scenarios/spec Wan/Traffic/FlashCrowd/
UpgradeSpec, sampled by the same deterministic ScenarioEngine).
"""

from __future__ import annotations

import bisect
import dataclasses
import hashlib

import numpy as np

from dragonfly2_tpu.cluster import messages as msg
from dragonfly2_tpu.cluster.simulator import ClusterSimulator
from dragonfly2_tpu.megascale.topology import (
    FAULT_CORRUPT,
    FAULT_ERROR,
    FAULT_STALL,
    WanCostModel,
    _FAULT_CODE,
    make_region_cluster,
)

# one uint64 have-bitset word per download: megascale tasks are capped at
# 64 pieces (the simulator draws 2..32); the oracle's generic path keeps
# the full 4096-piece bitset in scheduler state
MEGA_MAX_PIECES = 64

_BIG = np.int64(1 << 40)


@dataclasses.dataclass
class MegaStats:
    """Megascale-only counters beyond the oracle-shared SimStats (those
    stay in `stats` so the equivalence test compares them field for
    field)."""

    piece_events: int = 0          # events priced by the batch engine
    flash_arrivals: int = 0        # arrivals injected by flash-crowd storms
    upgrade_host_restarts: int = 0  # hosts cycled by rolling-upgrade waves
    origin_bytes: int = 0          # back-to-source + seed-trigger bytes
    p2p_bytes: int = 0             # bytes served peer-to-peer
    cross_region_b2s: int = 0      # b2s escalations outside the origin region
    # registrations the scheduler refused (hot task's peer DAG full under
    # a flash crowd) — the modeled daemon falls back to a direct origin
    # fetch, dfget's schedule-failure path, so these complete as origin
    # traffic instead of silently vanishing (the oracle ignores register
    # responses; at its scale the DAG never fills)
    refused_registrations: int = 0


class EventBatchEngine(ClusterSimulator):
    def __init__(
        self,
        scheduler,
        num_hosts: int = 1024,
        num_tasks: int = 64,
        seed: int = 0,
        piece_length: int = 4 << 20,
        scenario=None,
        retire_after_rounds: int | None = None,
        tail_capture: bool = True,
        tail_failover_horizon: int = 8,
    ):
        wan_active = scenario is not None and scenario.wan.regions > 0
        cluster = (
            make_region_cluster(num_hosts, scenario, seed=seed)
            if wan_active else None
        )
        super().__init__(
            scheduler, num_hosts=num_hosts, num_tasks=num_tasks, seed=seed,
            piece_length=piece_length, scenario=scenario,
            # registration-counter peer ids are the engine's row index —
            # a response decodes to its columns with integer math
            deterministic_peer_ids=True,
            cluster=cluster,
        )
        if any(t["pieces"] >= MEGA_MAX_PIECES for t in self._tasks):
            raise ValueError(f"megascale tasks cap at {MEGA_MAX_PIECES - 1} pieces")
        self.mega = MegaStats()
        hosts = self.cluster.hosts
        self._host_pos = {h.id: i for i, h in enumerate(hosts)}
        self._region_of = np.zeros(len(hosts), np.int32)
        if wan_active:
            for i, h in enumerate(hosts):
                region = h.location.split("|", 1)[0]
                self._region_of[i] = int(region.rsplit("-", 1)[1])
        self._wan = (
            WanCostModel.from_engine(scenario, hosts, self.engine, seed)
            if wan_active else None
        )
        self._task_pieces = np.asarray([t["pieces"] for t in self._tasks], np.int64)
        self._task_content = np.asarray(
            [t["content_length"] for t in self._tasks], np.int64
        )
        # --- columnar per-download state, indexed by registration counter
        cap = 1024
        self._col_task = np.full(cap, -1, np.int32)
        self._col_host = np.full(cap, -1, np.int32)
        self._col_have = np.zeros(cap, np.uint64)
        self._col_wave = np.zeros(cap, np.int32)
        self._col_cost_ns = np.zeros(cap, np.float64)
        self._col_done_round = np.full(cap, -1, np.int32)
        # --- tail-attribution columns (telemetry/tailtrace.py): the
        # registration round, rounds actually served a parent wave, and
        # the disjoint retry/back-to-source slices of _col_cost_ns —
        # everything _observe_tail needs to decompose a TTC, still SoA
        self._col_reg_round = np.full(cap, -1, np.int32)
        self._col_served = np.zeros(cap, np.int32)
        self._col_retry_ns = np.zeros(cap, np.float64)
        self._col_b2s_ns = np.zeros(cap, np.float64)
        # crash victimhood: the latest scheduler crash this row was alive
        # through (-1 = none) and the cost already accumulated at that
        # moment — everything after the mark is failover-phase time
        self._col_crash_round = np.full(cap, -1, np.int32)
        self._col_crash_cost_ns = np.zeros(cap, np.float64)
        # completed/failed downloads pending retirement, in completion
        # order (round-based, so retirement is deterministic — the
        # megascale stand-in for the wall-clock TTL GC the oracle never
        # drives); None disables
        self.retire_after_rounds = retire_after_rounds
        self._retire_queue: list[tuple[int, str]] = []
        self._retire_head = 0
        # run-to-run fault-schedule digest for the vectorized (WAN) path;
        # compat-mode draws land in engine.schedule_digest() as usual
        self._fault_digest = hashlib.blake2b(digest_size=16)
        self._fault_events = 0
        from dragonfly2_tpu.telemetry import default_registry
        from dragonfly2_tpu.telemetry.flight import PhaseRecorder
        from dragonfly2_tpu.telemetry.series import megascale_series
        from dragonfly2_tpu.telemetry.timeline import (
            QuantileSketch,
            TimelineRecorder,
        )

        series = megascale_series(default_registry())
        self._piece_event_counter = series.piece_events.labels()
        self.recorder = PhaseRecorder(
            histogram=series.step_phase, maxlen=4096, name="megascale.step"
        )
        # --- soak timeline (telemetry/timeline.py): one sample per round
        # off the EVENT clock. Every sampled value is a pure function of
        # the replay's counters, so paired-seed runs produce identical
        # timeline arrays (pinned by the megascale determinism test).
        day = (
            scenario.traffic.day_rounds
            if scenario is not None and scenario.traffic.day_rounds > 0
            else 96
        )
        self.minutes_per_round = 24.0 * 60.0 / day
        self.timeline = TimelineRecorder("megascale.timeline")
        n_regions = int(self._region_of.max()) + 1 if self._region_of.size else 1
        # per-region time-to-complete quantile sketches: bounded-error
        # streaming percentiles ride every sample without retaining
        # per-download arrays (1% relative accuracy)
        self._ttc_sketch = [
            QuantileSketch(relative_accuracy=0.01) for _ in range(n_regions)
        ]
        self._tl_prev: dict[str, float] = {}
        self._crash_rounds: list[int] = []
        # --- streaming SLO engine (telemetry/slo.py) on the EVENT clock:
        # fed one timeline sample per round (a PURE function of the
        # sample, so tools/dfslo.py replays the identical alert timeline
        # offline from the recorded samples), stepping burn-rate alert
        # state machines whose transitions annotate the timeline and
        # whose verdict columns ride every sample.
        from dragonfly2_tpu.telemetry.slo import SLOEngine, megascale_slo_specs

        self.slo = SLOEngine(
            megascale_slo_specs([f"region-{r}" for r in range(n_regions)]),
            name="megascale.slo",
            minutes_per_unit=self.minutes_per_round,
        )
        # --- tail-attribution plane (telemetry/tailtrace.py) on the
        # EVENT clock: every completion's virtual TTC decomposed into
        # lifecycle phases (waits priced at the round width, transfer
        # phases from the disjoint cost columns). Pure function of
        # (spec, seed) — the tail digest is paired-seed-pinned.
        from dragonfly2_tpu.telemetry import tailtrace as _tailtrace

        self.tail_capture = bool(tail_capture)
        self.tail_failover_horizon = int(tail_failover_horizon)
        self._round_ns = self.minutes_per_round * 60.0 * 1e9
        self._tail_vec = np.zeros(_tailtrace.N_PHASES, np.float64)
        self.tail = _tailtrace.TailTrace(
            [f"region-{r}" for r in range(n_regions)],
            seed=seed,
            name="megascale.tail",
        )

    # ------------------------------------------------------------ columns

    def _ensure_cols(self, n: int) -> None:
        cap = self._col_task.shape[0]
        if n <= cap:
            return
        new = max(cap * 2, n)
        for name in ("_col_task", "_col_host", "_col_have", "_col_wave",
                     "_col_cost_ns", "_col_done_round", "_col_reg_round",
                     "_col_served", "_col_retry_ns", "_col_b2s_ns",
                     "_col_crash_round", "_col_crash_cost_ns"):
            old = getattr(self, name)
            grown = np.zeros(new, old.dtype)
            if name in ("_col_task", "_col_host", "_col_done_round",
                        "_col_reg_round", "_col_crash_round"):
                grown[:] = -1
            grown[:cap] = old
            setattr(self, name, grown)

    @staticmethod
    def _reg_of(peer_id: str) -> int:
        return int(peer_id.rsplit("-", 1)[1])

    def _new_download_request(self, host=None, task=None):
        reg = self._reg_index
        req = super()._new_download_request(host, task)
        self._ensure_cols(self._reg_index)
        t = self._task_of[req.peer_id]
        hidx = self._host_pos[self._peer_host[req.peer_id]]
        self._col_task[reg] = t["index"]
        self._col_host[reg] = hidx
        self._col_have[reg] = 0
        self._col_wave[reg] = 0
        self._col_cost_ns[reg] = 0.0
        self._col_done_round[reg] = -1
        self._col_reg_round[reg] = self._round
        self._col_served[reg] = 0
        self._col_retry_ns[reg] = 0.0
        self._col_b2s_ns[reg] = 0.0
        self._col_crash_round[reg] = -1
        self._col_crash_cost_ns[reg] = 0.0
        return req

    def _finished_pieces(self, peer_id: str) -> list[int]:
        """Columnar override of the oracle's per-peer `have` sets: decode
        the uint64 bitset (ascending, like sorted(have))."""
        if not peer_id.startswith("peer-"):
            return []
        reg = self._reg_of(peer_id)
        if reg >= self._col_have.shape[0]:
            return []
        bits = int(self._col_have[reg])
        return [p for p in range(MEGA_MAX_PIECES) if bits >> p & 1]

    # ---------------------------------------------------------- traffic

    def _apply_scheduler_crash(self) -> None:
        """Columnar victim marking on top of the oracle's crash replay:
        every download alive when the scheduler dies gets stamped with
        the crash round and its cost-so-far, so the tail plane can
        attribute everything AFTER the re-announce — remaining waits and
        re-fetched waves alike — to the failover phase."""
        n = self._reg_index
        alive = (self._col_task[:n] >= 0) & (self._col_done_round[:n] < 0)
        self._col_crash_round[:n][alive] = self._round
        self._col_crash_cost_ns[:n][alive] = self._col_cost_ns[:n][alive]
        super()._apply_scheduler_crash()

    def _extra_offline(self, round_idx: int) -> set[str]:
        """Rolling-upgrade cohort: the host-order restart window the
        engine samples deterministically (region blocks are contiguous in
        host order, so the sweep is a region-by-region rollout)."""
        if self.engine is None:
            return set()
        window = self.engine.upgrade_window(round_idx)
        if window is None:
            return set()
        n = len(self.cluster.hosts)
        lo, hi = int(window[0] * n), max(int(window[1] * n), int(window[0] * n) + 1)
        cohort = {h.id for h in self.cluster.hosts[lo:hi]}
        self.mega.upgrade_host_restarts += len(cohort - self._offline)
        return cohort

    def _arrival_plan(self, base: int) -> tuple[int, list[int]]:
        """(diurnal-scaled arrival count, flash-crowd hot task ranks for
        extra arrivals this round)."""
        if self.engine is None:
            return base, []
        n = max(0, int(round(base * self.engine.diurnal_multiplier(self._round))))
        hot = self.engine.flash_crowds(self._round, len(self._tasks))
        if self.engine.spec.traffic.day_rounds > 0:
            # time-varying popularity: WHICH tasks are hot rotates through
            # the compressed day (the oracle's static Zipf can't express it)
            self._task_weights = self.engine.rotated_task_weights(
                len(self._tasks), self._round
            )
        return n, hot

    # ------------------------------------------------------------- round

    def run_round(self, new_downloads: int = 8) -> list:
        """One engine step: fault application, one arrival wave (diurnal
        x flash scaled) registered through the bulk API, one scheduler
        tick, then ALL normal responses advanced as one event batch."""
        recorder = self.recorder
        recorder.begin()
        self._round += 1
        crashed = False
        if self.engine is not None:
            self._apply_host_churn()
            if self.engine.scheduler_crashed(self._round):
                crashed = True
                self._crash_rounds.append(self._round)
                self.timeline.mark_event(self._round, "scheduler_crash")
                self._apply_scheduler_crash()
            self._apply_partitions()
        recorder.mark("faults")
        base_n, hot_ranks = self._arrival_plan(new_downloads)
        reqs = [self._new_download_request() for _ in range(base_n)]
        if hot_ranks:
            per_task = max(
                1,
                int(new_downloads * self.engine.spec.flash.arrival_multiplier)
                // len(hot_ranks),
            )
            for rank in hot_ranks:
                task = self._tasks[rank % len(self._tasks)]
                for _ in range(per_task):
                    reqs.append(self._new_download_request(task=task))
                    self.mega.flash_arrivals += 1
        if reqs:
            for req, resp in zip(reqs, self.scheduler.register_peers_batch(reqs)):
                if isinstance(resp, msg.ScheduleFailure):
                    self._register_refused(req)
        self.consume_seed_triggers()
        recorder.mark("arrivals")
        responses = self.scheduler.tick()
        recorder.mark("tick")
        # Acting non-normal responses inline and batching the normals
        # preserves the oracle's processing order: tick() emits every
        # pre-schedule decision (back-to-source, failures) BEFORE the
        # first NormalTaskResponse, so "non-normals in encounter order,
        # then all normals in list order" IS list order.
        normal: list = []
        for resp in responses:
            peer_id = getattr(resp, "peer_id", "")
            if self._peer_host.get(peer_id) in self._partitioned:
                # silent partition: the response never reaches the daemon
                # (same semantics as the oracle's run_round)
                self.stats.injected_partition_drops += 1
                self._partition_stalled.add(peer_id)
                continue
            if isinstance(resp, msg.NormalTaskResponse):
                normal.append(resp)
            else:
                self._act(resp)
        if normal:
            self._process_normal_batch(normal)
        recorder.mark("event_batch")
        self._retire_downloads()
        recorder.mark("retire")
        self._timeline_sample(crashed)
        recorder.mark("timeline")
        recorder.commit()
        return responses

    def _timeline_sample(self, crashed: bool) -> None:
        """One per-round timeline sample off the event clock: interval
        deltas of the replay counters (pieces, completions, origin/p2p
        bytes, re-announces, refused registrations), the quarantine
        population, the process breaker census, and per-region TTC
        percentiles from the streaming sketches. Deterministic in
        (spec, seed, replay) — no wall-clock reads."""
        from dragonfly2_tpu.rpc.resilience import open_breaker_census

        st, mega = self.stats, self.mega
        led = getattr(self.scheduler, "decisions", None)
        led_counters = led.counters() if led is not None else {}
        cur = {
            "pieces": float(st.pieces),
            "completed": float(st.completed),
            "origin_bytes": float(mega.origin_bytes),
            "p2p_bytes": float(mega.p2p_bytes),
            "reannounced": float(st.crash_reannounced_peers),
            "refused": float(mega.refused_registrations),
            "corruptions": float(st.injected_corruptions),
            # decision-ledger cumulative counters (wall-free by
            # construction — telemetry/decisions.counters), so the
            # divergence columns below stay paired-seed deterministic
            "decisions": float(led_counters.get("decisions", 0)),
            "shadow_compared": float(led_counters.get("shadow_compared", 0)),
            "shadow_disagree": float(
                led_counters.get("shadow_top1_disagree", 0)
            ),
        }
        prev = self._tl_prev
        delta = {k: v - prev.get(k, 0.0) for k, v in cur.items()}
        self._tl_prev = cur
        bytes_total = delta["origin_bytes"] + delta["p2p_bytes"]
        sample = {
            "sim_minutes": round(self._round * self.minutes_per_round, 2),
            "pieces": int(delta["pieces"]),
            "completed": int(delta["completed"]),
            "origin_fraction": (
                round(delta["origin_bytes"] / bytes_total, 6)
                if bytes_total > 0 else 0.0
            ),
            "quarantine_active": self.scheduler.quarantine.active_count(),
            "breaker_open": open_breaker_census(),
            "reannounce_backlog": int(delta["reannounced"]),
            "refused_registrations": int(delta["refused"]),
            "corruptions": int(delta["corruptions"]),
            "scheduler_crash": 1 if crashed else 0,
            # decision provenance columns: per-interval applied
            # selections and, when a shadow arm ran, its top-1
            # disagreement rate plus the deterministic failure-rate
            # regret basis (the TTC-ms basis is wall-derived and
            # deliberately excluded from the deterministic timeline)
            "decisions": int(delta["decisions"]),
            "shadow_divergence": (
                round(delta["shadow_disagree"] / delta["shadow_compared"], 4)
                if delta["shadow_compared"] > 0 else None
            ),
            "decision_regret_fail": self._regret_fail_sample(led),
            "ttc_ms_p50": {
                f"region-{r}": (
                    None if (q := sk.quantile(0.5)) is None else round(q, 2)
                )
                for r, sk in enumerate(self._ttc_sketch)
            },
            "ttc_ms_p95": {
                f"region-{r}": (
                    None if (q := sk.quantile(0.95)) is None else round(q, 2)
                )
                for r, sk in enumerate(self._ttc_sketch)
            },
            # which lifecycle phase dominated the attributed time of THIS
            # round's completions (telemetry/tailtrace.round_dominant) —
            # the cause hint a firing TTC page names, recorded in the
            # sample so dfslo's offline replay reproduces it exactly
            "tail_dominant_phase": (
                self.tail.round_dominant(self._round)
                if self.tail_capture else None
            ),
        }
        # SLO evaluation: derive every SLI from THIS sample and step the
        # engine at the event clock. The returned verdict columns ride
        # the sample (deterministic — pinned by the paired-seed test);
        # alert fire/clear transitions annotate the timeline next to the
        # scheduler_crash marks they judge.
        from dragonfly2_tpu.telemetry.slo import feed_megascale_sample

        step = feed_megascale_sample(
            self.slo, {**sample, "t": float(self._round)}
        )
        sample["slo_verdict"] = step["verdict_code"]
        sample["slo_alerts_firing"] = step["alerts_firing"]
        sample["slo_pages_fired"] = step["pages_fired"]
        sample["slo_tickets_fired"] = step["tickets_fired"]
        for tr in step["transitions"]:
            self.timeline.mark_event(
                self._round,
                f"slo_{tr['event']}:{tr['severity']}:{tr['slo']}:{tr['rule']}",
            )
        self.timeline.sample(self._round, sample)

    @staticmethod
    def _regret_fail_sample(led) -> float | None:
        """Deterministic per-sample regret: the active arm's mean
        failure-rate delta against the shadow pick on disagreement
        decisions (the ledger report's fail_rate basis — counts only,
        no wall reads). None until a disagreement has joined outcomes
        on both hosts."""
        return None if led is None else led.report()["regret_fail_rate"]

    def _record_ttc(self, reg: int) -> None:
        """Feed the completing download's virtual time-to-complete into
        its region's streaming quantile sketch."""
        host = int(self._col_host[reg])
        if host < 0:
            return
        region = int(self._region_of[host])
        if region < len(self._ttc_sketch):
            self._ttc_sketch[region].add(float(self._col_cost_ns[reg]) / 1e6)

    def _observe_tail(self, reg: int) -> None:
        """Decompose the completing download's virtual TTC into lifecycle
        phases and feed the tail plane. TTC here includes wait time —
        rounds alive but not served a parent wave, priced at the round
        width — on top of the transfer-cost column the region percentiles
        report; the phase vector is built from disjoint components
        (waits + retry/b2s/fetch slices of the cost), so it sums to the
        recorded TTC exactly. Failover absorbs everything a scheduler
        death cost the download: for crash victims (alive at the kill,
        per the crash-mark columns) ALL accrued wait is failover —
        the re-announce reset their queue position, so pre-crash queue
        time bought nothing and counting it as schedule_wait would hide
        the kill — plus every wave re-fetched after the re-announce.
        Downloads that registered into a still-recovering scheduler
        (within ``tail_failover_horizon`` rounds of a crash) also stall
        on the rebuild, not on steady-state backlog, so their waits are
        failover too; all other waits are schedule_wait."""
        if not self.tail_capture:
            return
        host = int(self._col_host[reg])
        if host < 0:
            return
        from dragonfly2_tpu.telemetry import tailtrace as tt

        cost_ns = float(self._col_cost_ns[reg])
        reg_round = int(self._col_reg_round[reg])
        done_round = int(self._col_done_round[reg])
        served = int(self._col_served[reg])
        wait_rounds = max(done_round - reg_round + 1 - max(served, 1), 0)
        crash_round = int(self._col_crash_round[reg])
        fail_cost = 0.0
        fail_wait = 0
        if crash_round >= 0:
            # lived through a crash: split the cost at the mark — the
            # pre-crash slice keeps its retry/b2s decomposition, the
            # post-re-announce slice is failover re-work — and charge
            # ALL wait to failover (wasted-wait attribution: the
            # re-announce threw away the queue position)
            pre = min(float(self._col_crash_cost_ns[reg]), cost_ns)
            fail_cost = cost_ns - pre
            fail_wait = wait_rounds
            cost_ns = pre
        elif wait_rounds and self._crash_rounds:
            # registered into a recovering scheduler: its waits are the
            # crash's queue backlog, not steady-state schedule wait
            k = bisect.bisect_right(self._crash_rounds, reg_round) - 1
            if k >= 0 and reg_round - self._crash_rounds[k] \
                    <= self.tail_failover_horizon:
                fail_wait = wait_rounds
        b2s = min(float(self._col_b2s_ns[reg]), cost_ns)
        retry = min(float(self._col_retry_ns[reg]), max(cost_ns - b2s, 0.0))
        fetch = max(cost_ns - b2s - retry, 0.0)
        rns = self._round_ns
        vec = self._tail_vec
        vec[:] = 0.0
        vec[tt.PH_SCHEDULE_WAIT] = (wait_rounds - fail_wait) * rns
        vec[tt.PH_FAILOVER] = fail_wait * rns + fail_cost
        vec[tt.PH_PARENT_FETCH] = fetch
        vec[tt.PH_RETRY] = retry
        vec[tt.PH_BACK_TO_SOURCE] = b2s
        self.tail.observe(
            int(self._region_of[host]), reg,
            cost_ns + fail_cost + wait_rounds * rns, vec,
            round_idx=done_round,
        )

    # -------------------------------------------------------- event batch

    def _process_normal_batch(self, responses: list) -> None:
        """Advance every in-flight download that received parents this
        tick by one wave, as one vectorized event batch. Scheduler calls
        are then issued per RESPONSE in response order — the exact call
        sequence the oracle produces, with the per-piece Python loop
        replaced by array math."""
        if self.engine is None:
            # scenario-less legacy replay: the oracle's wave path is
            # already vectorized per response and draws from a sequential
            # np rng — reuse it verbatim so paired runs stay bit-equal
            for resp in responses:
                self._download_from_parents(resp)
            return
        stats = self.stats
        m = len(responses)
        regs = np.empty(m, np.int64)
        n_par = np.empty(m, np.int64)
        crash_cut = np.full(m, _BIG)
        waves = np.empty(m, np.int64)
        max_par = max(len(r.candidate_parents) for r in responses)
        pmat = np.zeros((m, max_par), np.int64)
        parent_ids: list[list[str]] = []
        hosts_by_id = self._hosts_by_id
        for i, resp in enumerate(responses):
            reg = self._reg_of(resp.peer_id)
            regs[i] = reg
            wave = int(self._col_wave[reg]) + 1
            self._col_wave[reg] = wave
            waves[i] = wave
            if wave > 1:
                stats.retry_waves += 1
            parents = resp.candidate_parents
            n_par[i] = len(parents)
            ids = []
            for j, p in enumerate(parents):
                pmat[i, j] = self._host_pos[
                    self._peer_host.get(p.peer_id, p.host_id)
                ]
                ids.append(p.peer_id)
            parent_ids.append(ids)
            ca = self.engine.crash_point(
                self._peer_reg.get(resp.peer_id, 0),
                int(self._task_pieces[self._col_task[reg]]),
            )
            if ca is not None:
                prior = int(self._col_have[reg]).bit_count()
                crash_cut[i] = max(1, ca - prior)
        # one response per in-flight download per tick, so `regs` has no
        # duplicates: rounds NOT counted here are rounds the download sat
        # waiting for the scheduler (the tail plane's wait basis)
        self._col_served[regs] += 1

        total = self._task_pieces[self._col_task[regs]]
        have = self._col_have[regs]
        missing = ~have & ((np.uint64(1) << total.astype(np.uint64)) - np.uint64(1))
        bits = (
            (missing[:, None] >> np.arange(MEGA_MAX_PIECES, dtype=np.uint64)[None, :])
            & np.uint64(1)
        ).astype(bool)
        # row-major nonzero: events grouped per response, ascending piece
        ev_row, ev_piece = np.nonzero(bits)
        n_ev = bits.sum(axis=1).astype(np.int64)
        e = ev_row.shape[0]
        starts = np.zeros(m, np.int64)
        np.cumsum(n_ev[:-1], out=starts[1:])
        ev_rank = np.arange(e) - np.repeat(starts, n_ev)
        ev_sel = ev_piece % n_par[ev_row]
        ev_parent = pmat[ev_row, ev_sel]
        ev_child = self._col_host[regs[ev_row]].astype(np.int64)
        ev_task = self._col_task[regs[ev_row]].astype(np.int64)
        ev_wave = waves[ev_row]
        self.mega.piece_events += int(e)
        self._piece_event_counter.inc(int(e))

        if self._wan is not None:
            cost, fault = self._wan.piece_costs(
                ev_child, ev_parent, self.piece_length,
                ev_task, ev_piece.astype(np.int64), ev_wave,
            )
        else:
            # oracle-compat: the engine's per-event counter-hashed draws.
            # Order-independent by construction (semantic keys, no
            # stream), so pricing them here — instead of inside the
            # per-piece wave loop — cannot change any value the oracle
            # would have drawn; the batch just also prices events past an
            # abort, whose results are masked out below.
            hosts = self.cluster.hosts
            piece_cost_ns = self.engine.piece_cost_ns
            plen = self.piece_length
            cost = np.empty(e, np.int64)
            fault = np.zeros(e, np.int8)
            for k in range(e):
                c, f = piece_cost_ns(
                    hosts[ev_child[k]], hosts[ev_parent[k]], plen,
                    int(ev_task[k]), int(ev_piece[k]), int(ev_wave[k]),
                )
                cost[k] = c
                fault[k] = _FAULT_CODE[f]

        # --- wave cutoffs: first error/corrupt aborts; a crash lands
        # after `crash_cut` completed pieces; the earlier one wins
        abort_rank = np.full(m, _BIG)
        aborting = np.flatnonzero(fault >= FAULT_ERROR)
        if aborting.size:
            np.minimum.at(abort_rank, ev_row[aborting], ev_rank[aborting])
        cut = np.minimum(abort_rank, crash_cut)
        done = ev_rank < cut[ev_row]
        aborted = abort_rank < crash_cut            # a real event rank
        crashed = ~aborted & (crash_cut <= n_ev)
        abort_event = np.full(m, -1, np.int64)
        if aborting.size:
            hit = aborting[ev_rank[aborting] == abort_rank[ev_row[aborting]]]
            abort_event[ev_row[hit]] = hit

        # --- stats + columns, one pass each
        done_rows = ev_row[done]
        n_done = int(done.sum())
        stats.pieces += n_done
        stats.piece_cost_ns_total += int(cost[done].sum())
        stats.injected_stalls += int((fault[done] == FAULT_STALL).sum())
        abort_faults = fault[abort_event[aborted]]
        stats.injected_piece_failures += int((abort_faults == FAULT_ERROR).sum())
        stats.injected_corruptions += int((abort_faults == FAULT_CORRUPT).sum())
        stats.injected_crashes += int(crashed.sum())
        self.mega.p2p_bytes += n_done * self.piece_length
        if n_done:
            add_bits = np.zeros(m, np.uint64)
            np.bitwise_or.at(
                add_bits, done_rows,
                np.uint64(1) << ev_piece[done].astype(np.uint64),
            )
            self._col_have[regs] |= add_bits
            sums = np.zeros(m)
            np.add.at(sums, done_rows, cost[done].astype(np.float64))
            self._col_cost_ns[regs] += sums
            # waves past the first are the retry slice of the cost —
            # disjoint from the back-to-source slice by construction, so
            # the tail decomposition sums exactly
            retry_rows = waves > 1
            if retry_rows.any():
                self._col_retry_ns[regs[retry_rows]] += sums[retry_rows]
        faulted = np.flatnonzero(fault != 0)
        if faulted.size:
            self._fault_events += int(faulted.size)
            self._fault_digest.update(np.int64(self._round).tobytes())
            for col in (ev_task, ev_piece, ev_wave, fault):
                self._fault_digest.update(
                    np.ascontiguousarray(col[faulted]).tobytes()
                )

        # --- scheduler calls, per response in response order (the same
        # call sequence the oracle's per-response loop produces: the
        # completed slice reports first, then the wave's outcome)
        plen = self.piece_length
        finished_total = self._task_content
        for i, resp in enumerate(responses):
            peer_id = resp.peer_id
            s = int(starts[i])
            c = int(min(cut[i], n_ev[i]))
            if c:
                sl = slice(s, s + c)
                self.scheduler.pieces_finished_batch(
                    peer_id,
                    ev_piece[sl].tolist(),
                    [plen] * c,
                    cost[sl].tolist(),
                    parent_ids=parent_ids[i],
                    parent_sel=ev_sel[sl].tolist(),
                )
            if aborted[i]:
                kind = int(fault[abort_event[i]])
                self.scheduler.piece_failed(msg.DownloadPieceFailedRequest(
                    peer_id=peer_id,
                    parent_peer_id=parent_ids[i][int(ev_sel[abort_event[i]])],
                    reason="corruption" if kind == FAULT_CORRUPT else "",
                ))
            elif crashed[i]:
                self.scheduler.peer_failed(msg.DownloadPeerFailedRequest(
                    peer_id=peer_id, description="scenario churn: crashed"
                ))
                # dead row, but NOT a completion: no done_round, so the
                # region time-to-complete percentiles exclude it
                self._retire_later(peer_id)
            else:
                task_idx = int(self._col_task[regs[i]])
                self.scheduler.peer_finished(msg.DownloadPeerFinishedRequest(
                    peer_id=peer_id,
                    content_length=int(finished_total[task_idx]),
                    piece_count=int(self._task_pieces[task_idx]),
                ))
                stats.completed += 1
                self._complete(peer_id, int(regs[i]))

    def _charge_origin_fetch(self, reg: int, content: int) -> None:
        """Account one whole-task origin transfer against download row
        `reg`: origin bytes, the modeled transfer time at the base NIC
        tier, and — on the WAN topology — the cross-region back-to-source
        penalty when the downloader's region is not the origin's. Shared
        by the protocol back-to-source path and the refused-registration
        fallback so the origin-traffic split cannot drift between them."""
        self.mega.origin_bytes += content
        link = self.engine.spec.link if self.engine is not None else None
        base_bw = link.base_bandwidth_bps if link is not None else 100e6
        origin_ns = content / max(base_bw, 1.0) * 1e9
        if self._wan is not None:
            wan = self.engine.spec.wan
            if int(self._region_of[self._col_host[reg]]) != wan.origin_region:
                origin_ns += wan.back_to_source_penalty_ms * 1e6
                self.mega.cross_region_b2s += 1
        self._col_cost_ns[reg] += origin_ns
        self._col_b2s_ns[reg] += origin_ns

    def _register_refused(self, req) -> None:
        """Scheduler refused the registration (hot-task DAG full under a
        flash crowd, or peer table full): the modeled daemon downloads
        straight from origin — dfget's ScheduleFailure fallback — so the
        download completes as origin traffic with the WAN penalty when
        its region is not the origin's."""
        peer_id = req.peer_id
        reg = self._reg_of(peer_id)
        self.stats.schedule_failures += 1
        self.mega.refused_registrations += 1
        self._charge_origin_fetch(reg, int(req.content_length))
        self._col_done_round[reg] = self._round
        self._record_ttc(reg)
        self._observe_tail(reg)
        self.stats.completed += 1
        # never registered with the scheduler: nothing to retire, just
        # drop the sim-side identity maps
        self._task_of.pop(peer_id, None)
        self._peer_host.pop(peer_id, None)
        self._peer_reg.pop(peer_id, None)

    def _retire_later(self, peer_id: str) -> None:
        if self.retire_after_rounds is not None:
            self._retire_queue.append((self._round, peer_id))

    def _complete(self, peer_id: str, reg: int) -> None:
        self._col_done_round[reg] = self._round
        self._record_ttc(reg)
        self._observe_tail(reg)
        self._retire_later(peer_id)

    def _back_to_source(self, peer_id: str) -> None:
        super()._back_to_source(peer_id)
        reg = self._reg_of(peer_id)
        self._charge_origin_fetch(
            reg, int(self._task_content[self._col_task[reg]])
        )
        self._complete(peer_id, reg)

    def consume_seed_triggers(self) -> int:
        # snapshot the queued triggers' tasks before the superclass
        # drains them — seed downloads are origin traffic by design
        with self.scheduler.mu:
            pending = [t.task_id for t in self.scheduler.seed_triggers]
        n = super().consume_seed_triggers()
        if pending:
            by_task = {t["task_id"]: t for t in self._tasks}
            self.mega.origin_bytes += sum(
                by_task[tid]["content_length"] for tid in pending if tid in by_task
            )
        return n

    # -------------------------------------------------------- retirement

    def _retire_downloads(self) -> None:
        """Deterministic round-based retirement of long-completed
        downloads (LeavePeer): bounds live scheduler rows and per-task
        DAG slots over a compressed day the way the reference's peer-TTL
        GC does over wall time — without coupling the replay to the
        clock."""
        if self.retire_after_rounds is None:
            return
        horizon = self._round - self.retire_after_rounds
        q = self._retire_queue
        head = self._retire_head
        while head < len(q) and q[head][0] <= horizon:
            _, peer_id = q[head]
            head += 1
            self.scheduler.leave_peer(peer_id)
            self._task_of.pop(peer_id, None)
            self._peer_host.pop(peer_id, None)
            self._peer_reg.pop(peer_id, None)
            self._peer_waves.pop(peer_id, None)
            self._partition_stalled.discard(peer_id)
        if head > 4096 and head * 2 > len(q):
            del q[:head]
            head = 0
        self._retire_head = head

    # ---------------------------------------------------------- reporting

    def fault_schedule_digest(self) -> str:
        """Digest over every vectorized-path fault event plus the
        engine's own counter-hashed schedule — two runs of the same
        (spec, seed, replay) must match exactly (the megascale
        determinism contract)."""
        vec = f"{self._fault_events}:{self._fault_digest.copy().hexdigest()}"
        eng = self.engine.schedule_digest() if self.engine is not None else ""
        return f"{vec}|{eng}"

    def region_report(self) -> dict:
        """Per-region completion aggregates for the BENCH_mega artifact:
        completed downloads, virtual time-to-complete percentiles (ms),
        and the origin-traffic split."""
        n = self._reg_index
        done = self._col_done_round[:n] >= 0
        region = self._region_of[self._col_host[:n]]
        ttc_ms = self._col_cost_ns[:n] / 1e6
        regions = {}
        n_regions = int(self._region_of.max()) + 1 if self._region_of.size else 1
        for r in range(n_regions):
            mask = done & (region == r) & (self._col_host[:n] >= 0)
            vals = np.sort(ttc_ms[mask])
            regions[f"region-{r}"] = {
                "completed": int(mask.sum()),
                "ttc_ms_p50": round(float(np.percentile(vals, 50)), 2) if vals.size else None,
                "ttc_ms_p90": round(float(np.percentile(vals, 90)), 2) if vals.size else None,
                "ttc_ms_p99": round(float(np.percentile(vals, 99)), 2) if vals.size else None,
            }
        total_bytes = self.mega.origin_bytes + self.mega.p2p_bytes
        return {
            "regions": regions,
            "origin_bytes": self.mega.origin_bytes,
            "p2p_bytes": self.mega.p2p_bytes,
            "origin_traffic_fraction": round(
                self.mega.origin_bytes / total_bytes, 6
            ) if total_bytes else None,
            "cross_region_back_to_source": self.mega.cross_region_b2s,
        }


def megascale_service(
    num_hosts: int,
    num_tasks: int = 64,
    max_live_peers: int | None = None,
    algorithm: str = "default",
    seed: int = 0,
    max_peers_per_task: int = 2048,
):
    """SchedulerService sized for a megascale run: host/task tables fit
    the population, the peer table is sized to the LIVE download bound
    (arrival rate x retirement window — not total registrations; retired
    rows recycle through the free list), and the finished-piece bitset
    shrinks to one word (64-piece task cap). Returns the service."""
    from dragonfly2_tpu.cluster.scheduler import SchedulerService
    from dragonfly2_tpu.config.config import Config

    config = Config()
    config.evaluator.algorithm = algorithm
    sched = config.scheduler
    sched.max_hosts = num_hosts + 64
    sched.max_tasks = max(256, 2 * num_tasks)
    sched.max_peers = max_live_peers or max(4 * num_hosts, 4096)
    sched.max_peers_per_task = max_peers_per_task
    sched.piece_bitset_words = 1
    sched.region_aware_seeds = True
    return SchedulerService(config=config, seed=seed)
