"""Scheduler RPC server: the asyncio cluster edge.

Capability parity with scheduler/rpcserver (scheduler_server_v2.go:56-166):
one long-lived connection per daemon carrying AnnouncePeer oneof messages,
AnnounceHost, SyncProbes, Stat/Leave — dispatched into SchedulerService.
The TPU-first part is the tick loop: handlers only enqueue; every
`tick_interval` the service batches ALL pending peers into one device call
(cluster/scheduler.py tick) and the responses fan back out over whichever
connections own those peers.
"""

from __future__ import annotations

import asyncio
import logging
import threading
import time

from dragonfly2_tpu.cluster import messages as msg
from dragonfly2_tpu.rpc import mux, resilience, wire
from dragonfly2_tpu.telemetry import default_registry
from dragonfly2_tpu.telemetry.tracing import default_tracer
from dragonfly2_tpu.telemetry.series import (
    HOST_TRAFFIC_DOWNLOAD,
    HOST_TRAFFIC_UPLOAD,
    TRAFFIC_BACK_TO_SOURCE,
    TRAFFIC_P2P,
    register_version,
    resilience_series,
    scheduler_series,
    trainer_series,
)
from dragonfly2_tpu.utils.conntrack import ConnTracker

from dragonfly2_tpu.cluster import service_v1 as sv1

wire.register_module(msg)
wire.register_module(sv1)

logger = logging.getLogger(__name__)

# How long a seed trigger waits for *any* seed daemon to connect before it
# is declared undeliverable (preheat racing the seed's announce).
SEED_TRIGGER_TTL_S = 60.0

# Per-PIECE report types arrive at the cluster's aggregate piece rate —
# orders of magnitude above every other message. A handler span per piece
# report (token_hex + exporter fan-out) buys no diagnostic value, so these
# keep their wire trace context but are never span-wrapped server-side.
_UNTRACED_RPC_TYPES = (
    msg.DownloadPieceFinishedRequest,
    msg.DownloadPieceFailedRequest,
    msg.ProbeFinishedRequest,
    sv1.V1PieceResult,
)

# Requests eligible for deadline shedding: work someone is WAITING on,
# where a caller past its budget has stopped listening. Lifecycle
# mutations (Leave*/AnnounceHost) and progress reports are NEVER shed —
# dropping a LeavePeer because its frame arrived late would leak peer
# state, which is strictly worse than doing cheap work nobody awaits.
_SHEDDABLE_RPC_TYPES = (
    msg.RegisterPeerRequest,
    msg.RescheduleRequest,
    msg.StatPeerRequest,
    msg.StatTaskRequest,
    msg.ProbeStartedRequest,
    msg.JobTriggerSeedRequest,
    msg.TaskStatesRequest,
    msg.SchedulerInfoRequest,
    msg.FlightRecorderRequest,
)

# Of those, the types whose callers expect a per-peer scheduling verdict:
# they get an explicit DeadlineExceeded ScheduleFailure so the conductor
# fails fast instead of waiting out its schedule timeout. Stat/info
# droppers get silence — their caller aborts on its own expired budget,
# and a ScheduleFailure would be misrouted into the peer's response queue.
_SHED_WITH_FAILURE_TYPES = (msg.RegisterPeerRequest, msg.RescheduleRequest)


class SchedulerRPCServer:
    def __init__(self, service, host: str = "127.0.0.1", port: int = 0,
                 tick_interval: float = 0.005, health_check=None, ssl_context=None,
                 vsock_port: int | None = None):
        self.service = service
        self.health_check = health_check
        self.host = host
        self.port = port
        self.tick_interval = tick_interval
        self.ssl_context = ssl_context  # server SSLContext for mTLS; None = plaintext
        # optional AF_VSOCK listener alongside TCP (pkg/rpc/vsock.go /
        # pkg/dfnet VSOCK network type — VM guests dialing the host)
        self.vsock_port = vsock_port
        self._vsock_server: asyncio.AbstractServer | None = None
        self._server: asyncio.AbstractServer | None = None
        self._peer_conn: dict[str, asyncio.StreamWriter] = {}
        self._host_conn: dict[str, asyncio.StreamWriter] = {}
        self._writers: set[asyncio.StreamWriter] = set()
        self._tick_task: asyncio.Task | None = None
        self._warmup_thread: threading.Thread | None = None
        self._trigger_deadline: dict[str, float] = {}
        self._pending_triggers: list = []
        self._lock = asyncio.Lock()
        self._tracker = ConnTracker()
        # Adaptive tick: set whenever a dispatched message may have enqueued
        # scheduling work, so a lone request is served at kernel latency
        # instead of waiting out the full tick_interval (SURVEY §7 hard
        # part (b); the interval remains the RETRY cadence for peers that
        # stay pending with no eligible parents).
        self._tick_wake = asyncio.Event()
        # v1 compat surface (cluster/service_v1.py): peers that registered
        # through the v1 dialect get their scheduling responses converted
        # to PeerPacket frames (the reference serves both generations off
        # one resource layer, service_v1.go + service_v2.go).
        self.v1 = sv1.SchedulerServiceV1(service)
        # _v1_mu guards every _v1_peers mutation AND the tick thread's
        # snapshot copy: adds happen on dispatch threads (under
        # service.mu), but the connection-close discard runs on the event
        # loop where taking service.mu would stall the loop for a whole
        # tick — a dedicated lock held only across set ops costs nothing
        # and stops set(...) from racing a concurrent discard
        # (RuntimeError: set changed size during iteration).
        self._v1_mu = threading.Lock()
        self._v1_peers: set[str] = set()
        reg = default_registry()
        self.metrics = scheduler_series(reg)
        self.resilience_metrics = resilience_series(reg, "scheduler")
        register_version(reg, "scheduler")
        self._m_requests = self.metrics.announce_peer
        self._m_tick = self.metrics.schedule_tick
        self._m_batch = self.metrics.schedule_batch

    async def start(self) -> tuple[str, int]:
        self._server = await asyncio.start_server(
            self._tracker.tracked(self._serve_conn), self.host, self.port,
            ssl=self.ssl_context,
        )
        addr = self._server.sockets[0].getsockname()
        self.host, self.port = addr[0], addr[1]
        if self.vsock_port is not None:
            from dragonfly2_tpu.utils import vsock as vsock_mod

            # same ssl_context as the TCP listener: a plaintext vsock side
            # door would silently negate the cluster's mTLS boundary
            self._vsock_server = await vsock_mod.start_server(
                self._tracker.tracked(self._serve_conn), self.vsock_port,
                ssl_context=self.ssl_context,
            )
            logger.info("scheduler rpc also on vsock port %d", self.vsock_port)
        self._tick_task = asyncio.create_task(self._tick_loop())
        # Pre-compile the per-bucket serving programs off-loop so the
        # first real peers don't eat a multi-second XLA compile; READY is
        # not delayed (warmup touches no service state — scheduler.py).
        self._warmup_thread = threading.Thread(
            target=self._safe_warmup, name="eval-warmup", daemon=True
        )
        self._warmup_thread.start()
        logger.info("scheduler rpc listening on %s:%d", self.host, self.port)
        return self.host, self.port

    def _safe_warmup(self) -> None:
        try:
            self.service.warmup()
        except Exception:  # noqa: BLE001 - warmup is best-effort
            logger.exception("evaluator warmup failed")

    async def stop(self) -> None:
        if self._tick_task:
            self._tick_task.cancel()
            try:
                await self._tick_task
            except asyncio.CancelledError:
                pass
        if self._vsock_server:
            self._vsock_server.close()
        if self._server:
            self._server.close()
            # Announce streams are long-lived; cancel their handler tasks
            # before wait_closed() or 3.12 shutdown hangs (utils/conntrack.py).
            await self._tracker.cancel_all()
            await self._server.wait_closed()
        if self._vsock_server:
            await self._vsock_server.wait_closed()
        for w in list(self._writers):
            w.close()
        # Join any in-flight warmup compile before the interpreter can
        # finalize: XLA's compile pool aborts the whole process
        # ("terminate called without an active exception") if a daemon
        # compile thread is still alive when C++ static destructors run —
        # a SIGTERM inside the cold-start window would exit -6, not 0.
        warm = getattr(self.service, "_shadow_warm_thread", None)
        for t in (self._warmup_thread, warm):
            if t is not None and t.is_alive():
                await asyncio.to_thread(t.join)

    # ---------------------------------------------------------- connection

    async def _serve_conn(self, reader: asyncio.StreamReader, writer: asyncio.StreamWriter):
        self._writers.add(writer)
        owned_peers: set[str] = set()
        owned_hosts: set[str] = set()
        try:
            while True:
                request = await wire.read_frame(reader)
                if request is None:
                    return
                self._m_requests.labels(type(request).__name__).inc()
                health = mux.handle_health_request(request, self.health_check)
                if health is not None:
                    wire.write_frame(writer, health)
                    await writer.drain()
                    continue
                if isinstance(request, msg.AnnounceHostRequest):
                    async with self._lock:
                        self._host_conn[request.host.host_id] = writer
                        owned_hosts.add(request.host.host_id)
                # Propagated deadline budget (rpc/wire.py "dl"): awaited
                # work whose budget is already spent is SHED before it
                # touches the service — the caller stopped waiting, so
                # scheduling it only burns tick capacity (the grpc-timeout
                # contract the reference inherits from its interceptors).
                # Only _SHEDDABLE_RPC_TYPES qualify; lifecycle mutations
                # always execute.
                budget = getattr(request, "deadline_s", None)
                if (
                    budget is not None and budget <= 0
                    and isinstance(request, _SHEDDABLE_RPC_TYPES)
                ):
                    self.resilience_metrics.deadline_shed.labels(
                        type(request).__name__
                    ).inc()
                    if isinstance(request, _SHED_WITH_FAILURE_TYPES):
                        wire.write_frame(writer, msg.ScheduleFailure(
                            peer_id=request.peer_id, code="DeadlineExceeded",
                            description="deadline expired before dispatch",
                        ))
                        await writer.drain()
                    continue
                was_empty = not self.service._pending
                if budget is not None:
                    # re-anchor the remaining budget on this host's clock:
                    # dispatch time decrements it, and any frame the handler
                    # sends onward carries what is left (wire.encode reads
                    # the ambient scope)
                    with resilience.deadline(budget):
                        response = await self._dispatch_locked(
                            request, writer, owned_peers
                        )
                        if response is not None and resilience.expired():
                            self.resilience_metrics.deadline_shed.labels(
                                type(request).__name__
                            ).inc()
                            response = None  # nobody is waiting for this
                else:
                    response = await self._dispatch_locked(request, writer, owned_peers)
                if response is not None:
                    wire.write_frame(writer, response)
                    await writer.drain()
                # Wake ONLY on the empty->nonempty transition: waking while
                # work is already pending would let one unschedulable peer
                # (retrying on the interval cadence by design) turn a busy
                # message stream into back-to-back device scheduling calls.
                if was_empty and self.service._pending:
                    self._tick_wake.set()
                await self._drain_seed_triggers()
        except Exception:  # noqa: BLE001 - one bad conn must not kill the server
            logger.exception("connection handler failed")
        finally:
            self._writers.discard(writer)
            async with self._lock:
                for peer_id in owned_peers:
                    self._peer_conn.pop(peer_id, None)
                    # v1 marking follows the route entry's lifetime, or the
                    # set grows one string per v1 download forever
                    with self._v1_mu:
                        self._v1_peers.discard(peer_id)
                for host_id in owned_hosts:
                    self._host_conn.pop(host_id, None)
            writer.close()

    async def _drain_seed_triggers(self) -> None:
        """Push queued TriggerSeedRequests to their seed hosts' announce
        connections (the scheduler->seed-peer ObtainSeeds edge).

        Triggers that cannot be delivered yet — no seed connected (preheat
        racing the seed's announce), or the write failed mid-flight — are
        held in a server-side pending list and retried on later drains
        until SEED_TRIGGER_TTL_S, NOT silently dropped. The pending list
        lives here (not back in svc.seed_triggers) so the 5ms tick doesn't
        pay two thread hops per tick just to shuttle the same trigger."""
        svc = self.service
        if not svc.seed_triggers and not self._pending_triggers:
            return
        if not self._host_conn and not svc.seed_triggers:
            # nothing can be delivered; just expire long-waiting triggers
            now = time.monotonic()
            still = []
            for trigger in self._pending_triggers:
                if now < self._trigger_deadline.get(trigger.task_id, now + 1):
                    still.append(trigger)
                else:
                    self._trigger_deadline.pop(trigger.task_id, None)
                    logger.warning(
                        "seed trigger for task %s expired after %.0fs with no "
                        "connected seed host", trigger.task_id, SEED_TRIGGER_TTL_S,
                    )
            self._pending_triggers = still
            return

        def pop_triggers():
            # svc.mu may be held by the tick thread through a device call;
            # never block the event loop on it.
            with svc.mu:
                triggers, svc.seed_triggers = svc.seed_triggers, []
                return triggers, list(svc._seed_hosts)

        if svc.seed_triggers:
            triggers, seed_hosts = await asyncio.to_thread(pop_triggers)
        else:
            triggers, seed_hosts = [], list(svc._seed_hosts)
        triggers = self._pending_triggers + triggers
        self._pending_triggers = []
        undeliverable: list = []
        now = time.monotonic()
        for trigger in triggers:
            # Fall back to any connected seed host when the chosen host
            # has no live connection (crashed seed without LeaveHost): a
            # dropped trigger strands no-back-source peers.
            async with self._lock:
                writer = self._host_conn.get(trigger.host_id)
                if writer is None:
                    candidates = [h for h in seed_hosts if h in self._host_conn]
                    if candidates:
                        trigger.host_id = candidates[0]
                        writer = self._host_conn[trigger.host_id]
            delivered = False
            if writer is not None:
                try:
                    wire.write_frame(writer, trigger)
                    await writer.drain()
                    delivered = True
                except (ConnectionError, RuntimeError):
                    logger.warning(
                        "seed trigger to %s failed, will retry", trigger.host_id
                    )
            if delivered:
                self._trigger_deadline.pop(trigger.task_id, None)
                continue
            deadline = self._trigger_deadline.setdefault(
                trigger.task_id, now + SEED_TRIGGER_TTL_S
            )
            if now < deadline:
                undeliverable.append(trigger)
            else:
                self._trigger_deadline.pop(trigger.task_id, None)
                logger.warning(
                    "seed trigger for task %s expired undelivered after %.0fs",
                    trigger.task_id, SEED_TRIGGER_TTL_S,
                )
        self._pending_triggers = undeliverable

    async def _dispatch_locked(self, request, writer, owned_peers: set[str]):
        """Service mutations run off-loop under service.mu so they never
        race the batched tick thread or stall the event loop."""
        # route bookkeeping must happen on-loop (touches asyncio state)
        peer_id = getattr(request, "peer_id", None)
        if peer_id is not None and not isinstance(
            request, (msg.StatPeerRequest, msg.LeavePeerRequest, sv1.V1PeerTarget)
        ):
            async with self._lock:
                self._peer_conn[peer_id] = writer
                owned_peers.add(peer_id)

        # wire-propagated trace context (rpc/wire.py envelope): the
        # handler span continues the CALLER's trace. Untraced traffic and
        # per-piece report types pay nothing — no span, no token_hex, no
        # exporter fan-out. The span wraps service.mu (its duration shows
        # lock contention) and exporters fire only AFTER the lock drops.
        remote_ctx = getattr(request, "trace_context", None)
        traced = remote_ctx is not None and not isinstance(
            request, _UNTRACED_RPC_TYPES
        )

        def run():
            if not traced:
                with self.service.mu:
                    return self._dispatch(request, owned_peers)
            with default_tracer().span(
                f"scheduler.rpc.{type(request).__name__}",
                remote_parent=remote_ctx,
            ):
                with self.service.mu:
                    return self._dispatch(request, owned_peers)

        return await asyncio.to_thread(run)

    def _dispatch(self, request, owned_peers: set[str]):
        svc = self.service
        self._observe_request(request)
        if isinstance(request, msg.AnnounceHostRequest):
            svc.announce_host(request.host)
            return None
        if isinstance(request, msg.LeaveHostRequest):
            svc.leave_host(request.host_id)
            return None
        if isinstance(request, msg.LeavePeerRequest):
            svc.leave_peer(request.peer_id)
            owned_peers.discard(request.peer_id)
            return None
        if isinstance(request, msg.ProbeStartedRequest):
            return self._probe_targets(request)
        if isinstance(request, msg.ProbeFinishedRequest):
            self._probe_finished(request)
            return None
        if isinstance(request, msg.StatPeerRequest):
            return self._stat_peer(request.peer_id)
        if isinstance(request, msg.StatTaskRequest):
            return self._stat_task(request.task_id)
        # manager job edge (cross-process preheat/sync_peers; the
        # machinery hops manager/job/preheat.go:90-286 + job.go:224)
        if isinstance(request, msg.JobTriggerSeedRequest):
            ok = svc.trigger_seed_download(
                task_id=request.task_id, url=request.url,
                piece_length=request.piece_length, tag=request.tag,
                application=request.application, host_id=request.host_id,
                headers=request.headers or None,
            )
            return msg.JobTriggerSeedResponse(
                ok=ok, description="" if ok else "trigger queue full or no seed hosts"
            )
        if isinstance(request, msg.TaskStatesRequest):
            return msg.TaskStatesResponse(states=[
                -1 if s is None else int(s)
                for s in svc.task_states(request.task_ids)
            ])
        if isinstance(request, msg.SchedulerInfoRequest):
            return msg.SchedulerInfoResponse(
                counts=svc.counts(), hosts=svc.list_hosts()
            )
        if isinstance(request, msg.FlightRecorderRequest):
            return msg.FlightRecorderResponse(
                dump=svc.flight_dump(last_n=request.last_n)
            )
        if isinstance(request, sv1.V1_REQUEST_TYPES):
            return self._dispatch_v1(request, owned_peers)
        # announce-stream oneof (routing already recorded on-loop)
        return svc.handle(request)

    def _dispatch_v1(self, request, owned_peers: set[str]):
        """v1-dialect requests (cluster/service_v1.py) translated onto the
        service; immediate v2-shaped answers convert to PeerPacket here,
        tick-delivered ones convert inside the tick thread (under
        service.mu) via the _v1_peers snapshot in _tick_once."""
        v1 = self.v1
        if isinstance(request, sv1.V1PeerTaskRequest):
            with self._v1_mu:
                self._v1_peers.add(request.peer_id)
            return v1.register_peer_task(request)
        if isinstance(request, sv1.V1PieceResult):
            with self._v1_mu:
                self._v1_peers.add(request.src_pid)
            response = v1.report_piece_result(request)
            return v1.to_peer_packet(response) if response is not None else None
        if isinstance(request, sv1.V1PeerResult):
            return v1.report_peer_result(request)
        if isinstance(request, sv1.V1AnnounceTaskRequest):
            v1.announce_task(request)
            return None
        if isinstance(request, sv1.V1PeerTarget):
            v1.leave_task(request)
            owned_peers.discard(request.peer_id)
            with self._v1_mu:
                self._v1_peers.discard(request.peer_id)
            return None
        return None

    def _observe_request(self, request) -> None:
        """Per-RPC totals + traffic/duration series (scheduler/metrics/
        metrics.go:44-454). Runs under service.mu (called from _dispatch),
        so reading _peer_meta/_host_info is race-free."""
        m = self.metrics
        svc = self.service

        def peer_labels(peer_id: str) -> tuple[str, str, str]:
            meta = svc._peer_meta.get(peer_id)
            if meta is None:
                return "", "", "normal"
            info = svc._host_info.get(meta.host_id)
            return meta.tag, meta.application, info.host_type if info else "normal"

        if isinstance(request, msg.RegisterPeerRequest):
            m.register_peer.labels(
                str(request.priority), "STANDARD", request.tag, request.application
            ).inc()
        elif isinstance(request, msg.DownloadPieceFinishedRequest):
            tag, app, host_type = peer_labels(request.peer_id)
            ttype = TRAFFIC_P2P if request.parent_peer_id else TRAFFIC_BACK_TO_SOURCE
            m.download_piece_finished.labels(ttype, "STANDARD", tag, app).inc()
            m.traffic.labels(ttype, "STANDARD", tag, app, host_type).inc(request.length)
            meta = svc._peer_meta.get(request.peer_id)
            if meta is not None:
                m.host_traffic.labels(
                    HOST_TRAFFIC_DOWNLOAD, host_type, meta.host_id
                ).inc(request.length)
            pmeta = svc._peer_meta.get(request.parent_peer_id)
            if pmeta is not None:
                pinfo = svc._host_info.get(pmeta.host_id)
                m.host_traffic.labels(
                    HOST_TRAFFIC_UPLOAD,
                    pinfo.host_type if pinfo else "normal",
                    pmeta.host_id,
                ).inc(request.length)
        elif isinstance(request, msg.DownloadPieceFailedRequest):
            tag, app, _ = peer_labels(request.peer_id)
            m.download_piece_finished_failure.labels(
                TRAFFIC_P2P, "STANDARD", tag, app
            ).inc()
        elif isinstance(
            request,
            (msg.DownloadPeerFinishedRequest, msg.DownloadPeerBackToSourceFinishedRequest),
        ):
            tag, app, _ = peer_labels(request.peer_id)
            m.download_peer_finished.labels("0", "STANDARD", tag, app).inc()
            meta = svc._peer_meta.get(request.peer_id)
            if meta is not None and getattr(meta, "registered_at", 0.0):
                scope = msg.SizeScope.of(request.content_length).name
                m.download_peer_duration.labels(scope).observe(
                    (time.monotonic() - meta.registered_at) * 1e3
                )
        elif isinstance(
            request,
            (msg.DownloadPeerFailedRequest, msg.DownloadPeerBackToSourceFailedRequest),
        ):
            tag, app, _ = peer_labels(request.peer_id)
            m.download_peer_finished_failure.labels("0", "STANDARD", tag, app).inc()
        elif isinstance(request, msg.DownloadPeerBackToSourceStartedRequest):
            tag, app, _ = peer_labels(request.peer_id)
            m.download_peer_back_to_source_started.labels("0", "STANDARD", tag, app).inc()
        elif isinstance(request, msg.StatPeerRequest):
            m.stat_peer.labels().inc()
        elif isinstance(request, msg.LeavePeerRequest):
            m.leave_peer.labels().inc()
        elif isinstance(request, msg.StatTaskRequest):
            m.stat_task.labels().inc()
        elif isinstance(request, msg.AnnounceHostRequest):
            m.announce_host.labels().inc()
        elif isinstance(request, msg.LeaveHostRequest):
            m.leave_host.labels().inc()
        elif isinstance(request, msg.ProbeStartedRequest):
            m.sync_probes.labels().inc()

    # --------------------------------------------------------------- probes

    def _probe_targets(self, request: msg.ProbeStartedRequest) -> msg.ProbeTargetsResponse:
        import jax

        svc = self.service
        targets: list[msg.ProbeTarget] = []
        if svc.probes is not None:
            src_slot = svc.state.host_index(request.host_id)
            if src_slot is not None:
                alive = svc.state.host_alive_mask()
                alive[src_slot] = False
                key = jax.random.key(time.time_ns() % (1 << 31))
                for slot in svc.probes.find_probed_hosts(alive, key, request.count):
                    host_id = svc.state.host_id_at(int(slot))
                    info = svc._host_info.get(host_id)
                    if info is not None:
                        targets.append(
                            msg.ProbeTarget(host_id=host_id, ip=info.ip, port=info.port)
                        )
        return msg.ProbeTargetsResponse(targets=targets)

    def _probe_finished(self, request: msg.ProbeFinishedRequest) -> None:
        import numpy as np

        svc = self.service
        if svc.probes is None:
            return
        src = svc.state.host_index(request.host_id)
        if src is None:
            return
        dsts, rtts = [], []
        for r in request.results:
            if not r.ok:
                continue
            dst = svc.state.host_index(r.host_id)
            if dst is not None:
                dsts.append(dst)
                rtts.append(r.rtt_ns)
        if dsts:
            svc.probes.enqueue(
                np.full(len(dsts), src, np.int32),
                np.asarray(dsts, np.int32),
                np.asarray(rtts, np.float32),
            )

    # ----------------------------------------------------------------- stat

    def _stat_peer(self, peer_id: str) -> msg.StatResponse:
        from dragonfly2_tpu.state.fsm import PeerState

        # flush valve (dflint FLUSH001): finished_pieces reads the
        # buffered piece-report columns — without this, a StatPeer racing
        # the tick reported a count missing reports already acknowledged
        # to the reporting peer
        self.service.flush_piece_reports()
        idx = self.service.state.peer_index(peer_id)
        if idx is None:
            return msg.StatResponse(found=False)
        return msg.StatResponse(
            found=True,
            state=PeerState(int(self.service.state.peer_state[idx])).display,
            detail={"finished_pieces": int(self.service.state.peer_finished_count[idx])},
        )

    def _stat_task(self, task_id: str) -> msg.StatResponse:
        from dragonfly2_tpu.state.fsm import TaskState

        idx = self.service.state.task_index(task_id)
        if idx is None:
            return msg.StatResponse(found=False)
        return msg.StatResponse(
            found=True,
            state=TaskState(int(self.service.state.task_state[idx])).display,
            detail={
                "total_pieces": int(self.service.state.task_total_pieces[idx]),
                "content_length": int(self.service.state.task_content_length[idx]),
            },
        )

    # ----------------------------------------------------------------- tick

    async def _tick_loop(self) -> None:
        while True:
            # Fire immediately when new work arrives (empty->nonempty wake
            # from the connection handlers); otherwise tick on the interval,
            # which doubles as the retry cadence for still-pending peers and
            # the out-of-band drain cadence. Work arriving DURING a tick
            # leaves the event set, so the next tick runs back-to-back —
            # batching under load happens naturally because each device call
            # takes every pending peer with it.
            try:
                await asyncio.wait_for(self._tick_wake.wait(), timeout=self.tick_interval)
            except asyncio.TimeoutError:
                pass
            self._tick_wake.clear()
            try:
                await self._tick_once()
                # Seed triggers can be enqueued OUT of band (a manager
                # preheat job calls the service directly); per-connection
                # draining alone would leave them stuck until some peer
                # happens to send a message.
                await self._drain_seed_triggers()
                # Interval resource GC rides the same loop (pkg/gc wired
                # into the scheduler bootstrap, scheduler.go:110-299):
                # cheap due-check inline, the actual sweep off-loop since
                # it takes the service lock.
                if self.service.gc_due():
                    swept = await asyncio.to_thread(self.service.run_gc)
                    if any(swept.values()):
                        logger.info("resource gc reaped %s", swept)
            except Exception:  # noqa: BLE001 - keep ticking
                logger.exception("schedule tick failed")

    async def _tick_once(self) -> None:
        svc = self.service
        pending = len(svc._pending)
        self.metrics.concurrent_schedule.labels().set(pending)
        if pending == 0:
            return
        t0 = time.perf_counter()

        # v1 responses convert to PeerPacket INSIDE the tick thread while
        # service.mu is still held — to_peer_packet reads svc._peer_meta,
        # which dispatch threads mutate, so converting later on the event
        # loop could see a racing leave/GC and emit a packet with an empty
        # task_id (ADVICE r4 low). The membership snapshot is ALSO taken
        # under svc.mu: _dispatch_v1 mutates _v1_peers while holding it,
        # so a pre-lock snapshot could miss a v1 peer that registered
        # between snapshot and tick and hand its connection a raw v2 frame.

        def run():
            with svc.mu:
                with self._v1_mu:
                    v1_peers = set(self._v1_peers)
                out = []
                for response in svc.tick():
                    peer_id = getattr(response, "peer_id", None)
                    if peer_id in v1_peers:
                        response = self.v1.to_peer_packet(response)
                        if response is None:
                            continue
                    out.append(response)
                return out

        # The device call blocks; run it off-loop so streams stay live.
        # (The per-phase histogram is observed by the service's own
        # PhaseRecorder inside tick() — telemetry/flight.py — so the
        # server no longer re-derives it from the ring.)
        with default_tracer().span("scheduler.tick", pending=pending) as tick_span:
            responses = await asyncio.to_thread(run)
        self._m_tick.labels().observe(time.perf_counter() - t0)
        self._m_batch.labels().observe(pending)
        # Responses carry the tick span's context so the client's piece
        # downloads continue the scheduling trace (one trace id from the
        # tick through the daemon's downloads).
        await self._send_responses(
            responses,
            trace_context={
                "trace_id": tick_span.trace_id, "span_id": tick_span.span_id,
            },
        )

    async def _send_responses(self, responses, trace_context=None) -> None:
        # v1 responses arrive here already converted to V1PeerPacket (the
        # conversion runs in the tick thread under service.mu — ADVICE r4
        # low); a packet routes by its src_pid.
        for response in responses:
            peer_id = getattr(response, "peer_id", None) or getattr(
                response, "src_pid", None
            )
            async with self._lock:
                writer = self._peer_conn.get(peer_id)
            if writer is None:
                continue
            try:
                wire.write_frame(writer, response, trace_context=trace_context)
                await writer.drain()
            except (ConnectionError, RuntimeError):
                async with self._lock:
                    self._peer_conn.pop(peer_id, None)


class TrainerRPCServer:
    """Trainer service edge: the Train client-stream as a socket server.

    Capability parity with trainer/rpcserver/trainer_server_v1.go +
    trainer/service/service_v1.go:59-162: a connection streams TrainRequest
    frames ('download' chunks -> the MLP dataset, 'networktopology' -> the
    GNN dataset, per-host files keyed by host_id), EOF kicks training off
    the event loop, errors clear only that host's partial files, and the
    single TrainResponse reports the outcome."""

    def __init__(self, service, host: str = "127.0.0.1", port: int = 0,
                 health_check=None, ssl_context=None):
        self.service = service  # TrainerService (cluster/trainer_service.py)
        self.health_check = health_check
        self.host = host
        self.port = port
        self.ssl_context = ssl_context
        self._server: asyncio.AbstractServer | None = None
        self._writers: set[asyncio.StreamWriter] = set()
        self._tracker = ConnTracker()
        reg = default_registry()
        self.metrics = trainer_series(reg)
        register_version(reg, "trainer")
        self._m_chunks = self.metrics.train_chunks
        self._m_trains = self.metrics.train_runs

    async def start(self) -> tuple[str, int]:
        self._server = await asyncio.start_server(
            self._tracker.tracked(self._serve_conn), self.host, self.port,
            ssl=self.ssl_context,
        )
        addr = self._server.sockets[0].getsockname()
        self.host, self.port = addr[0], addr[1]
        logger.info("trainer rpc listening on %s:%d", self.host, self.port)
        return self.host, self.port

    async def stop(self) -> None:
        if self._server:
            self._server.close()
            # Cancel live Train streams before wait_closed() (3.12 waits on
            # every in-flight handler; utils/conntrack.py).
            await self._tracker.cancel_all()
            await self._server.wait_closed()
        for w in list(self._writers):
            w.close()

    async def _serve_conn(self, reader: asyncio.StreamReader, writer: asyncio.StreamWriter):
        self._writers.add(writer)
        host_id = None
        # trace context from the upload stream's frames (rpc/wire.py): the
        # training run parents on the announcer/scheduler span that sent
        # the datasets — one trace id across the announce->train edge
        remote_ctx = None
        try:
            committed = False
            while True:
                request = await wire.read_frame(reader)
                if request is None:
                    # Bare EOF before the TrainEndRequest commit marker: the
                    # connection tore (read_frame folds ConnectionError into
                    # None) — never train on a possibly-truncated dataset.
                    break
                if remote_ctx is None:
                    remote_ctx = getattr(request, "trace_context", None)
                health = mux.handle_health_request(request, self.health_check)
                if health is not None:
                    wire.write_frame(writer, health)
                    await writer.drain()
                    continue
                if isinstance(request, msg.TrainEndRequest):
                    host_id = request.host_id or host_id
                    committed = True
                    break
                if not isinstance(request, msg.TrainRequest):
                    await self._abort_reply(
                        reader, writer, host_id, "expected TrainRequest"
                    )
                    return
                host_id = request.host_id
                self._m_chunks.labels(request.dataset).inc()
                try:
                    if request.dataset == "download":
                        self.service.train_mlp_chunk(host_id, request.chunk)
                    elif request.dataset == "networktopology":
                        self.service.train_gnn_chunk(host_id, request.chunk)
                    else:
                        raise ValueError(f"unknown dataset {request.dataset!r}")
                except Exception as e:  # noqa: BLE001 - reply, don't kill server
                    await self._abort_reply(reader, writer, host_id, str(e))
                    return
            if not committed:
                if host_id is not None:
                    self.service.train_abort(host_id)
                    self._m_trains.labels("aborted").inc()
                return  # torn connection: nobody is listening for a reply
            if host_id is None:
                wire.write_frame(writer, msg.TrainResponse(ok=False, description="empty stream"))
                await writer.drain()
                return
            # commit -> train both models off-loop (service_v1.go:155 goroutine)
            try:
                with default_tracer().span(
                    "trainer.train_ingest", remote_parent=remote_ctx,
                    host_id=host_id,
                ):
                    outcome = await asyncio.to_thread(self.service.train_finish, host_id)
                self._m_trains.labels("succeeded").inc()
                self.metrics.training.labels().inc()
                parts = []
                if outcome.gnn is not None:
                    parts.append(f"gnn v{outcome.gnn.version}")
                if outcome.mlp is not None:
                    parts.append(f"mlp v{outcome.mlp.version}")
                wire.write_frame(
                    writer, msg.TrainResponse(ok=True, description=", ".join(parts))
                )
            except Exception as e:  # noqa: BLE001
                self.service.train_abort(host_id)
                self._m_trains.labels("failed").inc()
                self.metrics.training_failure.labels().inc()
                wire.write_frame(writer, msg.TrainResponse(ok=False, description=str(e)))
            await writer.drain()
        except Exception:  # noqa: BLE001 - one bad conn must not kill the server
            logger.exception("trainer connection handler failed")
            if host_id is not None:
                self.service.train_abort(host_id)
        finally:
            self._writers.discard(writer)
            writer.close()

    async def _abort_reply(self, reader, writer, host_id, description: str) -> None:
        """Mid-stream error: clear the host's partial files, reply, then
        drain the client's remaining frames so the error response isn't
        lost to a connection reset while the client is still writing."""
        if host_id is not None:
            self.service.train_abort(host_id)
        self._m_trains.labels("aborted").inc()
        wire.write_frame(writer, msg.TrainResponse(ok=False, description=description))
        await writer.drain()
        while await wire.read_frame(reader) is not None:
            pass
