"""Service launchers — the `cmd/{scheduler,trainer,manager,dfdaemon}` tier.

Capability parity with the reference's per-service binaries
(cmd/scheduler, cmd/trainer, cmd/manager, cmd/dfdaemon wired through
cmd/dependency/dependency.go:61 InitCommandAndConfig): one module, one
subcommand per service, YAML config via --config plus flag overrides,
graceful SIGINT/SIGTERM shutdown. Each service prints exactly one
`READY <host> <port>` line once its listener is bound, so a parent
process (or the multi-process e2e) can wait on startup without polling.

    python -m dragonfly2_tpu.cmd scheduler --port 8002 --data-dir /var/df
    python -m dragonfly2_tpu.cmd trainer   --port 8004 --data-dir ... --registry-dir ...
    python -m dragonfly2_tpu.cmd manager   --port 8080 --db manager.db
    python -m dragonfly2_tpu.cmd dfdaemon  --data-dir ... --scheduler host:8002

The file/cache/object CLIs (dfget/dfcache/dfstore) live in client/cli.py.
"""

from __future__ import annotations

import argparse
import asyncio
import os
import contextlib
import signal
import sys


def _parse_addr(value: str) -> tuple[str, int]:
    host, _, port = value.rpartition(":")
    return host or "127.0.0.1", int(port)


async def _run_until_signalled(ready_line: str) -> None:
    stop = asyncio.Event()
    loop = asyncio.get_running_loop()
    for sig in (signal.SIGINT, signal.SIGTERM):
        loop.add_signal_handler(sig, stop.set)
    print(ready_line, flush=True)
    await stop.wait()



@contextlib.asynccontextmanager
async def _monitored(args, ready: str):
    """Start the per-service observability HTTP when --metrics-port is
    set (`/metrics`, `/debug/stacks`, `/debug/profile` — the reference's
    per-service Prometheus server + InitMonitor pprof,
    cmd/dependency/dependency.go:95-138), append its port to the READY
    line, and shut it down on exit."""
    monitor = None
    if getattr(args, "metrics_port", None) is not None:
        from dragonfly2_tpu.telemetry import serve_metrics

        monitor = serve_metrics(port=args.metrics_port)
        ready += f" METRICS {monitor.server_address[1]}"
    try:
        yield ready
    finally:
        if monitor is not None:
            monitor.shutdown()


async def _serve_scheduler(args) -> int:
    from dragonfly2_tpu.cluster.probes import ProbeStore
    from dragonfly2_tpu.cluster.scheduler import SchedulerService
    from dragonfly2_tpu.config.config import Config
    from dragonfly2_tpu.records.storage import TraceStorage
    from dragonfly2_tpu.rpc.server import SchedulerRPCServer

    config = Config.load(args.config) if args.config else Config()
    if args.algorithm:
        config.evaluator.algorithm = args.algorithm
    storage = TraceStorage(args.data_dir) if args.data_dir else None
    probes = ProbeStore(max_hosts=config.scheduler.max_hosts)
    service = SchedulerService(config=config, storage=storage, probes=probes)
    server = SchedulerRPCServer(service, host=args.host, port=args.port)
    host, port = await server.start()
    infer_server = None
    if args.registry_dir:
        # Serve the registry's trained models over the KServe-v2-shaped
        # inference RPC (the reference points its ml evaluator at an
        # external Triton sidecar; here the scheduler process itself is
        # the inference endpoint). Built after start() so the default
        # registry host id uses the *bound* port, not a pre-bind 0.
        from dragonfly2_tpu.cluster.trainer_service import (
            ATTENTION_MODEL_NAME, GNN_MODEL_NAME, MLP_MODEL_NAME,
        )
        from dragonfly2_tpu.registry import ModelRegistry, ModelServer
        from dragonfly2_tpu.registry.registry import (
            MODEL_TYPE_ATTENTION, MODEL_TYPE_GNN, MODEL_TYPE_MLP,
        )
        from dragonfly2_tpu.rpc.inference import InferenceRPCServer

        registry = ModelRegistry(args.registry_dir)
        sched_host_id = args.scheduler_host_id or f"{host}:{port}"
        servers = {
            name: ModelServer(registry, name, sched_host_id, mtype, template_params=None)
            for name, mtype in (
                (GNN_MODEL_NAME, MODEL_TYPE_GNN),
                (MLP_MODEL_NAME, MODEL_TYPE_MLP),
                (ATTENTION_MODEL_NAME, MODEL_TYPE_ATTENTION),
            )
        }
        infer_server = InferenceRPCServer(servers, host=args.host, port=args.infer_port)
        await infer_server.start()
    ready = f"READY {host} {port}"
    if infer_server is not None:
        ready += f" INFER {infer_server.host} {infer_server.port}"
    try:
        async with _monitored(args, ready) as line:
            await _run_until_signalled(line)
    finally:
        if storage is not None:
            storage.close()  # flush buffered trace rows FIRST — an RPC
            # stop() that raises must not take the buffered rows with it
        if infer_server is not None:
            await infer_server.stop()
        await server.stop()
    return 0


async def _serve_trainer(args) -> int:
    from dragonfly2_tpu.cluster.trainer_service import TrainerService
    from dragonfly2_tpu.config.config import Config
    from dragonfly2_tpu.records.storage import HostTraceStorage
    from dragonfly2_tpu.registry import ModelRegistry
    from dragonfly2_tpu.rpc.server import TrainerRPCServer

    config = Config.load(args.config) if args.config else Config()
    if args.epochs:
        config.trainer.epochs = args.epochs
    service = TrainerService(
        HostTraceStorage(args.data_dir),
        ModelRegistry(args.registry_dir),
        config.trainer,
    )
    server = TrainerRPCServer(service, host=args.host, port=args.port)
    host, port = await server.start()
    try:
        async with _monitored(args, f"READY {host} {port}") as line:
            await _run_until_signalled(line)
    finally:
        await server.stop()
    return 0


async def _serve_manager(args) -> int:
    from dragonfly2_tpu.manager.models import Database
    from dragonfly2_tpu.manager.rest import ManagerREST
    from dragonfly2_tpu.manager.service import ManagerService
    from dragonfly2_tpu.registry import ModelRegistry

    registry = ModelRegistry(args.registry_dir) if args.registry_dir else None
    service = ManagerService(db=Database(args.db), registry=registry)
    rest = ManagerREST(service, host=args.host, port=args.port)
    host, port = rest.start()
    try:
        async with _monitored(args, f"READY {host} {port}") as line:
            await _run_until_signalled(line)
    finally:
        rest.stop()
    return 0


def _object_storage_options(args) -> dict | None:
    if not args.object_storage_endpoint:
        return None
    access = os.environ.get("DRAGONFLY_OBJ_ACCESS_KEY", "")
    secret = os.environ.get("DRAGONFLY_OBJ_SECRET_KEY", "")
    if not access or not secret:
        # empty creds would boot cleanly and then fail EVERY request with
        # vendor signature errors — refuse at startup with the real cause
        raise SystemExit(
            "--object-storage-endpoint needs DRAGONFLY_OBJ_ACCESS_KEY and "
            "DRAGONFLY_OBJ_SECRET_KEY in the environment"
        )
    return {
        "endpoint": args.object_storage_endpoint,
        "access_key": access,
        "secret_key": secret,
        "region": args.object_storage_region,
    }


async def _serve_dfdaemon(args) -> int:
    from dragonfly2_tpu.client.daemon import Daemon
    from dragonfly2_tpu.client.transport import ProxyRule

    rules = []
    for spec in args.proxy_rule or []:
        # REGEX[=REDIRECT_HOST]; prefix with 'direct:' to bypass P2P
        direct = spec.startswith("direct:")
        if direct:
            spec = spec[len("direct:"):]
        # '=>' separates regex from redirect host: a bare '=' is common
        # inside URL-query regexes and must stay part of the pattern
        regex, _, redirect = spec.partition("=>")
        if "=" in regex and not redirect:
            print(
                f"warning: --proxy-rule {spec!r} has '=' but no '=>' — the whole "
                "string is treated as the regex (redirect needs '=>HOST')",
                file=sys.stderr,
            )
        rules.append(ProxyRule(regex=regex, direct=direct, redirect=redirect))
    daemon = Daemon(
        data_dir=args.data_dir,
        scheduler_addresses=[_parse_addr(s) for s in args.scheduler],
        ip=args.ip,
        host_type=args.host_type,
        idc=args.idc,
        location=args.location,
        probe_interval=args.probe_interval,
        object_storage=args.object_storage,
        object_storage_backend=args.object_storage_backend,
        object_storage_options=_object_storage_options(args),
        proxy=args.proxy,
        proxy_rules=rules,
        registry_mirror=args.registry_mirror,
        sni_proxy=args.sni_proxy,
        sni_allowed_hosts=args.sni_allow or None,
    )
    await daemon.start()
    ready = f"READY {daemon.ip} {daemon.upload.port}"
    if daemon.proxy is not None:
        ready += f" PROXY {daemon.proxy.port}"
    if daemon.sni_proxy is not None:
        ready += f" SNI {daemon.sni_proxy.port}"
    if daemon.object_storage is not None:
        ready += f" OBJSTORE {daemon.object_storage.port}"
    try:
        async with _monitored(args, ready) as line:
            await _run_until_signalled(line)
    finally:
        await daemon.stop()
    return 0


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(prog="dragonfly2-tpu", description=__doc__)
    sub = p.add_subparsers(dest="cmd", required=True)

    s = sub.add_parser("scheduler", help="peer-scheduling control plane")
    s.add_argument("--host", default="127.0.0.1")
    s.add_argument("--port", type=int, default=0)
    s.add_argument("--config", default=None, help="YAML config path")
    s.add_argument("--data-dir", default=None, help="trace CSV directory")
    s.add_argument("--algorithm", default=None,
                   help="evaluator override: default|nt|ml|plugin")
    s.add_argument("--registry-dir", default=None,
                   help="model registry dir; serves trained models over "
                   "the inference RPC when set")
    s.add_argument("--infer-port", type=int, default=0)
    s.add_argument("--scheduler-host-id", default=None,
                   help="registry host id the trainer published under "
                   "(default host:port)")
    s.add_argument("--metrics-port", type=int, default=None,
                   help="observability HTTP: /metrics /debug/stacks /debug/profile")

    t = sub.add_parser("trainer", help="model training service")
    t.add_argument("--host", default="127.0.0.1")
    t.add_argument("--port", type=int, default=0)
    t.add_argument("--config", default=None)
    t.add_argument("--data-dir", required=True, help="per-host dataset dir")
    t.add_argument("--registry-dir", required=True, help="model registry dir")
    t.add_argument("--epochs", type=int, default=0)
    t.add_argument("--metrics-port", type=int, default=None)

    m = sub.add_parser("manager", help="REST control plane")
    m.add_argument("--host", default="127.0.0.1")
    m.add_argument("--port", type=int, default=0)
    m.add_argument("--db", default=":memory:", help="sqlite path")
    m.add_argument("--registry-dir", default=None)
    m.add_argument("--metrics-port", type=int, default=None)

    d = sub.add_parser("dfdaemon", help="peer data-plane daemon")
    d.add_argument("--data-dir", required=True)
    d.add_argument("--scheduler", action="append", required=True,
                   help="host:port (repeatable)")
    d.add_argument("--ip", default="127.0.0.1")
    d.add_argument("--host-type", default="normal", choices=("normal", "super"))
    d.add_argument("--idc", default="")
    d.add_argument("--location", default="")
    d.add_argument("--probe-interval", type=float, default=0.0)
    d.add_argument("--object-storage", action="store_true")
    d.add_argument("--object-storage-backend", default="fs",
                   choices=("fs", "s3", "oss", "obs"))
    d.add_argument("--object-storage-endpoint", default="",
                   help="vendor endpoint for s3/oss/obs (credentials via "
                   "DRAGONFLY_OBJ_ACCESS_KEY / DRAGONFLY_OBJ_SECRET_KEY env)")
    d.add_argument("--object-storage-region", default="")
    d.add_argument("--proxy", action="store_true",
                   help="serve the HTTP(S) forward proxy listener")
    d.add_argument("--registry-mirror", default="",
                   help="reverse-proxy base URL for relative requests")
    d.add_argument("--sni-proxy", action="store_true",
                   help="serve the raw-TLS SNI passthrough listener "
                   "(refuses every host unless --sni-allow is given)")
    d.add_argument("--sni-allow", action="append", default=[],
                   help="hostname (or suffix) the SNI proxy may dial (repeatable)")
    d.add_argument("--proxy-rule", action="append", default=[],
                   help="P2P hijack rule REGEX[=>REDIRECT_HOST]; prefix "
                   "'direct:' to match-but-bypass (repeatable)")
    d.add_argument("--metrics-port", type=int, default=None)
    return p


def main(argv: list[str] | None = None) -> int:
    args = build_parser().parse_args(argv)
    runner = {
        "scheduler": _serve_scheduler,
        "trainer": _serve_trainer,
        "manager": _serve_manager,
        "dfdaemon": _serve_dfdaemon,
    }[args.cmd]
    return asyncio.run(runner(args))


if __name__ == "__main__":
    sys.exit(main())
