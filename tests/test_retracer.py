"""Runtime half of dfshape (tools/dflint/retracer.py): the retrace
tripwire that fails tier-1 on any serving-jit compile outside the
statically-proven bucket set, and the donation guard that makes
use-after-donate of host staging buffers crash loudly.

The static/runtime agreement test is the acceptance pin: the SAME
deliberate unbucketed call that the static shape pass flags in the
fixture file trips the runtime tripwire when executed."""

import functools
from pathlib import Path

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from tools.dflint import retracer
from tools.dflint.core import run_dflint
from tools.dflint.passes.shape import ShapeDonationPass

ROOT = Path(__file__).resolve().parents[1]
FIXTURES = Path(__file__).parent / "dflint_fixtures"


def _toy_wrapper(name: str):
    """A jitted toy with the serving calling convention (buf, b) wrapped
    in the flight recorder, so the tripwire sees it like a serving jit
    — the REAL serving wrappers stay clean for the session tripwire."""
    from dragonfly2_tpu.telemetry.flight import instrument_jit

    @functools.partial(jax.jit, static_argnames=("b",))
    def toy(buf, b):
        return jnp.reshape(buf.astype(jnp.float32), (b, -1)).sum(axis=1)

    return instrument_jit(toy, name, service="scheduler")


def test_derived_buckets_match_scheduler_constant():
    """The AST-derived bucket set IS the scheduler's _EVAL_BUCKETS: one
    source of truth for the static pass, the tripwire and the tests."""
    from dragonfly2_tpu.cluster.scheduler import _EVAL_BUCKETS

    assert retracer.load_eval_buckets(ROOT) == _EVAL_BUCKETS
    derived = retracer.derive_static_signature_sets(ROOT)
    assert set(derived) == set(retracer.SERVING_B_ARGS)
    for allowed in derived.values():
        assert allowed == frozenset(_EVAL_BUCKETS)


def test_unbucketed_call_trips_static_pass_and_runtime_tripwire():
    """Acceptance pin: a deliberate unbucketed call trips BOTH halves.
    Statically, the bad_shape fixture's runtime-b call site is a
    SHAPE001 finding; dynamically, executing the same mistake compiles a
    signature the tripwire rejects against the same proven set."""
    # static half: the fixture call site is flagged
    report, _ = run_dflint(ROOT, files=[FIXTURES / "bad_shape.py"],
                           passes=[ShapeDonationPass()])
    assert any(f.rule == "SHAPE001" for f in report.findings)

    # runtime half: same mistake, executed
    name = "retracer.toy_unbucketed"
    wrapper = _toy_wrapper(name)
    buckets = frozenset(retracer.load_eval_buckets(ROOT))
    tripwire = retracer.RetraceTripwire(
        root=ROOT,
        allowed={f"scheduler.{name}": buckets},
        b_args={f"scheduler.{name}": 1},
    )
    tripwire.arm()
    np.asarray(wrapper(np.zeros(64 * 4, np.uint8), 64))  # bucketed: fine
    assert tripwire.violations() == []
    b = 100  # the "len(work)" mistake: a runtime batch dim
    np.asarray(wrapper(np.zeros(b * 4, np.uint8), b))
    assert tripwire.new_signatures() == {f"scheduler.{name}": 2}
    violations = tripwire.violations()
    assert len(violations) == 1 and "100" in violations[0], violations


def test_tripwire_reports_unreadable_call_convention():
    name = "retracer.toy_convention"
    wrapper = _toy_wrapper(name)
    np.asarray(wrapper(np.zeros(64, np.uint8), 16))
    tripwire = retracer.RetraceTripwire(
        root=ROOT,
        allowed={f"scheduler.{name}": frozenset({16})},
        b_args={f"scheduler.{name}": 7},  # no arg 7: must fail LOUDLY
    )
    violations = tripwire.violations()
    assert len(violations) == 1 and "no readable batch dim" in violations[0]


def test_donation_guard_mark_mode_reuse_and_write_crash():
    """mark mode (the tier-1 default): a donated buffer passed twice
    raises at the second call; a write to a donated buffer raises; a
    fresh buffer per call stays silent."""
    calls = []

    def fake_jit(buf, b):
        calls.append(b)
        return np.zeros(2, np.float32)

    guard = retracer.DonationGuard(fake_jit, (0,), "test.guard")
    buf = np.zeros(16, np.uint8)
    guard(buf, 64)
    with pytest.raises(ValueError):
        buf[0] = 1  # frozen: a post-donation write crashes loudly
    with pytest.raises(retracer.UseAfterDonateError):
        guard(buf, 64)
    guard(np.zeros(16, np.uint8), 64)  # fresh buffer: fine
    assert calls == [64, 64]
    assert guard.donations == 2 and guard.reuse_trips == 1


def test_donation_guard_poison_mode_makes_stale_reads_loud():
    """poison mode: after the (blocked) call, the donated host buffer is
    filled with the canary byte — a use-after-donate read sees 0xDB
    garbage instead of plausible stale data. The result itself is
    computed BEFORE poisoning (block_until_ready gate), so the guard can
    never corrupt the in-flight computation even under zero-copy H2D."""
    @jax.jit
    def summer(buf):
        return buf.astype(jnp.int32).sum()

    guard = retracer.DonationGuard(summer, (0,), "test.poison", poison=True)
    buf = np.full(32, 7, np.uint8)
    out = int(guard(buf))
    assert out == 7 * 32  # computed from pre-poison bytes
    assert np.all(buf == retracer.POISON_BYTE)


def test_real_serving_jits_are_guarded_this_session():
    """conftest installs the guards session-wide: the module attributes
    the scheduler calls through ARE DonationGuard wrappers, and attribute
    forwarding keeps the flight-recorder surface intact."""
    from dragonfly2_tpu.ops import evaluator as ev
    from dragonfly2_tpu.registry import serving

    assert isinstance(ev.schedule_from_packed, retracer.DonationGuard)
    assert isinstance(serving._ml_schedule_from_packed, retracer.DonationGuard)
    assert ev.schedule_from_packed.donate_argnums == (0,)
    # forwarded JitWrapper surface (stats used by the tripwire + tests)
    assert "signatures" in ev.schedule_from_packed.stats()


def test_guard_install_is_idempotent_and_reversible():
    from dragonfly2_tpu.ops import evaluator as ev

    before = ev.schedule_from_packed
    again = retracer.install_donation_guards()
    assert again == []  # already guarded: left alone
    assert ev.schedule_from_packed is before
