"""Object-storage-backed model registry.

Capability parity with the reference's CreateModel upload path
(manager/rpcserver/manager_server_v1.go:880-952: model bytes -> an
object-storage bucket; metadata keys laid out per
manager/types/model.go:66-75 ``<id>/<version>/model.graphdef`` +
``<id>/config.pbtxt``): a trainer on host A publishes a version, a
scheduler on host B serves it, and the ONLY thing they share is the
bucket — no common filesystem (the round-3 gap: registry/registry.py is
a local directory).

Speaks the backend protocol from objectstorage/backends.py, so the same
registry runs over the local FilesystemBackend or any signed
S3/OSS/OBS-compatible endpoint (objectstorage/remote.py + signing.py).
Params travel as one msgpack object (flax.serialization — a pytree of
numpy arrays), not an orbax directory tree: a bucket stores blobs, and
one PUT/GET per version keeps publish/fetch atomic per object.

Key layout under an optional prefix:
    <model_id>/model.json             active-version pointer (+ name/type)
    <model_id>/<version>/version.json   metadata + evaluation
    <model_id>/<version>/params.msgpack trained params
    <model_id>/<version>/params.sha256  integrity manifest: sha256 + size
                                        of params.msgpack, written by the
                                        publisher and verified by every
                                        load_params — a torn or bit-rotted
                                        blob raises DataLoss instead of
                                        activating into serving

`open_registry` dispatches a plain path to the orbax/fs ModelRegistry and
a ``<vendor>://bucket/prefix?endpoint=...`` URL here, so every
``--registry-dir`` flag accepts either.
"""

from __future__ import annotations

import dataclasses
import json
import time
import urllib.parse
from typing import Any

import jax

from dragonfly2_tpu.objectstorage.backends import new_backend
from dragonfly2_tpu.registry.registry import (
    MODEL_TYPE_ATTENTION,
    MODEL_TYPE_GNN,
    MODEL_TYPE_MLP,
    STATE_ACTIVE,
    STATE_BAD,
    STATE_INACTIVE,
    ModelEvaluation,
    ModelRegistry,
    ModelVersion,
    _version_from_json,
)
from dragonfly2_tpu.utils import dferrors
from dragonfly2_tpu.utils.digest import sha256_from_bytes
from dragonfly2_tpu.utils.idgen import model_id as make_model_id


class BucketModelRegistry:
    """Same public surface as ModelRegistry, stored in an object bucket."""

    def __init__(self, backend, bucket: str, prefix: str = ""):
        self.backend = backend
        self.bucket = bucket
        self.prefix = prefix.strip("/")
        if not backend.is_bucket_exist(bucket):
            backend.create_bucket(bucket)

    def _key(self, *parts: str) -> str:
        parts = tuple(str(p) for p in parts)
        return "/".join((self.prefix,) + parts if self.prefix else parts)

    def _get_json(self, *parts: str) -> dict | None:
        try:
            return json.loads(self.backend.get_object(self.bucket, self._key(*parts)))
        except Exception:  # noqa: BLE001 - missing object == missing entry
            return None

    def _put_json(self, data: dict, *parts: str) -> None:
        self.backend.put_object(
            self.bucket, self._key(*parts), json.dumps(data, indent=2).encode()
        )

    # -------------------------------------------------------------- write

    def create_model_version(
        self,
        name: str,
        model_type: str,
        scheduler_host_id: str,
        params: Any,
        evaluation: ModelEvaluation,
        metadata: dict | None = None,
    ) -> ModelVersion:
        """CreateModel semantics (manager_server_v1.go:880-952): next
        version number, params + evaluation uploaded, version starts
        inactive."""
        from flax import serialization

        if model_type not in (MODEL_TYPE_GNN, MODEL_TYPE_MLP, MODEL_TYPE_ATTENTION):
            raise ValueError(f"unknown model type {model_type!r}")
        mid = make_model_id(name, scheduler_host_id)
        # Version allocation is a conditional create (`If-None-Match: *` /
        # O_EXCL): the version.json RESERVES the number before any params
        # bytes move, so two publishers racing on one bucket get distinct
        # versions instead of silently overwriting each other (ADVICE r4
        # medium; the reference serializes this through the manager DB's
        # auto-increment). A reader can briefly see the reserved INACTIVE
        # version before params.msgpack lands; only activate() makes a
        # version servable, and the publisher activates only after this
        # method returns.
        next_version = max(
            (v.version for v in self.list_versions(mid)), default=0
        ) + 1
        while True:
            mv = ModelVersion(
                model_id=mid,
                name=name,
                type=model_type,
                version=next_version,
                state=STATE_INACTIVE,
                evaluation=evaluation,
                scheduler_host_id=scheduler_host_id,
                created_at=time.time(),
                metadata=metadata or {},
            )
            reserved = self.backend.put_object_if_absent(
                self.bucket,
                self._key(mid, next_version, "version.json"),
                json.dumps(dataclasses.asdict(mv), indent=2).encode(),
            )
            if reserved:
                break
            next_version += 1
        blob = serialization.msgpack_serialize(jax.device_get(params))
        self.backend.put_object(
            self.bucket, self._key(mid, next_version, "params.msgpack"), blob
        )
        # Integrity manifest BESIDE the params (pkg/digest discipline on
        # the model plane): load_params re-hashes the blob against this,
        # so a torn PUT, truncated GET, or bit-rotted object raises
        # DataLoss instead of deserializing garbage into serving.
        self._put_json(
            {"sha256": sha256_from_bytes(blob), "size": len(blob)},
            mid, next_version, "params.sha256",
        )
        self.backend.put_object_if_absent(
            self.bucket,
            self._key(mid, "model.json"),
            json.dumps(
                {"model_id": mid, "name": name, "type": model_type,
                 "active_version": None},
            ).encode(),
        )
        return mv

    def activate(self, model_id: str, version: int) -> None:
        """Flip the active pointer (manager/service/model.go:109-151).

        The manifest's ``active_version`` pointer is the AUTHORITATIVE
        record — active_version() reads only it — and it is flipped first
        in a single PUT, so a crash mid-activate leaves serving consistent
        and only the denormalized per-version ``state`` fields stale (the
        next activate repairs them). Concurrent activates of the SAME
        model_id are last-writer-wins on the pointer: model_id embeds the
        scheduler_host_id, so each model has exactly one natural activator
        (its owning scheduler's trainer) and the reference's DB
        transaction is not re-created here."""
        vdata = self._get_json(model_id, version, "version.json")
        if vdata is None:
            raise FileNotFoundError(f"{model_id} v{version} not found")
        if vdata.get("state") == STATE_BAD:
            raise ValueError(
                f"{model_id} v{version} is marked bad (failed an integrity "
                "or activation gate); publish a new version instead"
            )
        # A publisher that died between reserving version.json and
        # uploading params leaves a permanently-visible params-less
        # version; activating it would make load_params fail at SERVING
        # time, so the gap is checked here instead.
        if not self.backend.is_object_exist(
            self.bucket, self._key(model_id, version, "params.msgpack")
        ):
            raise FileNotFoundError(
                f"{model_id} v{version} has no params uploaded "
                "(publisher died mid-publish?)"
            )
        manifest = self._get_json(model_id, "model.json") or {}
        manifest["active_version"] = version
        self._put_json(manifest, model_id, "model.json")
        for v in self.list_versions(model_id):
            if v.state == STATE_BAD:
                continue  # bad stays bad; never resurrected to inactive
            state = STATE_ACTIVE if v.version == version else STATE_INACTIVE
            if v.state != state:
                data = self._get_json(model_id, v.version, "version.json")
                data["state"] = state
                self._put_json(data, model_id, v.version, "version.json")

    def mark_version_bad(self, model_id: str, version: int, reason: str = "") -> None:
        """ModelRegistry.mark_version_bad over the bucket layout: flag the
        version, and if it was active fall the pointer back to the newest
        remaining good version (serving recovers to last-good)."""
        data = self._get_json(model_id, version, "version.json")
        if data is None:
            return
        data["state"] = STATE_BAD
        data.setdefault("metadata", {})["bad_reason"] = reason
        self._put_json(data, model_id, version, "version.json")
        manifest = self._get_json(model_id, "model.json")
        if not manifest or manifest.get("active_version") != version:
            return
        # fallback must be LOADABLE, not merely not-bad: a publisher that
        # died before uploading params leaves a params-less version that
        # activate() refuses — falling back onto it would wedge every
        # subsequent refresh on a not-found instead of recovering
        good = [
            v for v in self.list_versions(model_id)
            if v.state != STATE_BAD and self.backend.is_object_exist(
                self.bucket, self._key(model_id, v.version, "params.msgpack")
            )
        ]
        fallback = good[-1].version if good else None
        if fallback is not None:
            vdata = self._get_json(model_id, fallback, "version.json")
            vdata["state"] = STATE_ACTIVE
            self._put_json(vdata, model_id, fallback, "version.json")
        manifest["active_version"] = fallback
        self._put_json(manifest, model_id, "model.json")

    def delete_version(self, model_id: str, version: int) -> None:
        if self._get_json(model_id, version, "version.json") is None:
            return
        manifest = self._get_json(model_id, "model.json")
        if manifest and manifest.get("active_version") == version:
            raise ValueError("cannot delete the active version")
        for leaf in ("version.json", "params.msgpack"):
            self.backend.delete_object(self.bucket, self._key(model_id, version, leaf))

    # --------------------------------------------------------------- read

    def list_models(self) -> list[dict]:
        out = []
        for meta in self.backend.get_object_metadatas(self.bucket, prefix=self.prefix):
            if meta.key.endswith("/model.json"):
                out.append(json.loads(self.backend.get_object(self.bucket, meta.key)))
        return sorted(out, key=lambda m: m["model_id"])

    def list_versions(self, model_id: str) -> list[ModelVersion]:
        prefix = self._key(model_id) + "/"
        out = []
        for meta in self.backend.get_object_metadatas(self.bucket, prefix=prefix):
            if meta.key.endswith("/version.json"):
                out.append(
                    _version_from_json(
                        json.loads(self.backend.get_object(self.bucket, meta.key))
                    )
                )
        return sorted(out, key=lambda v: v.version)

    def active_version(self, model_id: str) -> ModelVersion | None:
        manifest = self._get_json(model_id, "model.json")
        if not manifest or manifest.get("active_version") is None:
            return None
        data = self._get_json(model_id, manifest["active_version"], "version.json")
        return _version_from_json(data) if data else None

    def load_params(self, model_id: str, version: int, template: Any = None) -> Any:
        """One GET; numpy leaves (placement happens at the first jit call,
        so a TPU-trained version restores on a CPU scheduler — the same
        topology-portability contract as ModelRegistry.load_params)."""
        from flax import serialization

        blob = self.backend.get_object(
            self.bucket, self._key(model_id, version, "params.msgpack")
        )
        manifest = self._get_json(model_id, version, "params.sha256")
        if manifest:
            # Verify BEFORE deserializing: flax/msgpack would happily
            # restore a truncated blob into a params tree missing leaves,
            # and nothing downstream re-checks byte integrity.
            if len(blob) != manifest.get("size", len(blob)):
                raise dferrors.DataLoss(
                    f"{model_id} v{version}: params.msgpack is {len(blob)} "
                    f"bytes, manifest says {manifest['size']} (torn write?)"
                )
            actual = sha256_from_bytes(blob)
            if actual != manifest.get("sha256"):
                raise dferrors.DataLoss(
                    f"{model_id} v{version}: params sha256 {actual} != "
                    f"manifest {manifest['sha256']} (bit rot or tamper)"
                )
        if template is not None:
            return serialization.from_bytes(template, blob)
        return serialization.msgpack_restore(blob)

    def model_id(self, name: str, scheduler_host_id: str) -> str:
        return make_model_id(name, scheduler_host_id)


def open_registry(spec) -> ModelRegistry | BucketModelRegistry:
    """Dispatch a --registry-dir value: a plain path opens the local
    orbax/fs ModelRegistry; a ``s3://bucket/prefix?endpoint=H:P&
    access_key=AK&secret_key=SK[&region=R][&virtual_hosted=1]`` (or
    oss://, obs://) URL opens the bucket registry over the signed remote
    backend; ``fs://bucket/prefix?base_dir=DIR`` uses the filesystem
    backend through the same blob layout (in-proc tests, NFS buckets)."""
    spec = str(spec)
    if "://" not in spec:
        return ModelRegistry(spec)
    u = urllib.parse.urlsplit(spec)
    q = {k: v[-1] for k, v in urllib.parse.parse_qs(u.query).items()}
    bucket = u.netloc
    prefix = u.path.strip("/")
    if u.scheme == "fs":
        backend = new_backend("fs", base_dir=q.get("base_dir", "."))
    else:
        backend = new_backend(
            u.scheme,
            endpoint=q.get("endpoint", ""),
            access_key=q.get("access_key", ""),
            secret_key=q.get("secret_key", ""),
            region=q.get("region", ""),
            virtual_hosted=q.get("virtual_hosted", "") in ("1", "true"),
        )
    return BucketModelRegistry(backend, bucket, prefix)
