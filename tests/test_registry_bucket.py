"""Object-storage-backed model registry (VERDICT r3 missing #2).

The reference uploads model bytes to a bucket
(manager/rpcserver/manager_server_v1.go:880-952, keys per
manager/types/model.go:66-75); these tests drive the same lifecycle
through BucketModelRegistry over (a) the local FilesystemBackend and
(b) a fake SIGNED S3 endpoint that verifies every SigV4 signature by
recomputing it — so a publish from "trainer host A" reaches a serve on
"scheduler host B" with nothing shared but the bucket."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

# same-directory test module: the fake signature-verifying S3 server
from test_remote_sources import ACCESS, REGION, SECRET, _S3Handler, _serve, _Store

from dragonfly2_tpu.models import ProbeRTTRegressor
from dragonfly2_tpu.objectstorage.backends import FilesystemBackend, new_backend
from dragonfly2_tpu.registry import (
    BucketModelRegistry,
    ModelEvaluation,
    ModelRegistry,
    ModelServer,
    open_registry,
)
from dragonfly2_tpu.registry.registry import (
    MODEL_TYPE_MLP,
    STATE_ACTIVE,
    STATE_INACTIVE,
)


@pytest.fixture
def mlp_setup():
    model = ProbeRTTRegressor(hidden_dim=8)
    x = jnp.ones((2, 8))
    params = model.init(jax.random.key(0), x)
    return model, params, x


@pytest.fixture
def s3_bucket():
    store = _Store()
    handler = type("H", (_S3Handler,), {"store": store})
    srv, addr = _serve(handler)
    yield addr
    srv.shutdown()


def _registries(tmp_path, s3_addr):
    yield "fs-backend", lambda: BucketModelRegistry(
        FilesystemBackend(tmp_path / "bucket-store"), "models"
    )
    url = (
        f"s3://models/team-a?endpoint={s3_addr}"
        f"&access_key={ACCESS}&secret_key={SECRET}&region={REGION}"
    )
    yield "signed-s3", lambda: open_registry(url)


def test_bucket_lifecycle_parity(tmp_path, s3_bucket, mlp_setup):
    """create/version/activate/delete semantics match the fs registry."""
    _, params, _ = mlp_setup
    for label, make in _registries(tmp_path, s3_bucket):
        reg = make()
        v1 = reg.create_model_version(
            "rtt", MODEL_TYPE_MLP, "sched-host", params, ModelEvaluation(mse=0.5)
        )
        v2 = reg.create_model_version(
            "rtt", MODEL_TYPE_MLP, "sched-host", params, ModelEvaluation(mse=0.2)
        )
        assert (v1.version, v2.version) == (1, 2), label
        assert reg.active_version(v1.model_id) is None, label
        assert [v.state for v in reg.list_versions(v1.model_id)] == [
            STATE_INACTIVE, STATE_INACTIVE,
        ], label
        reg.activate(v1.model_id, 1)
        states = {v.version: v.state for v in reg.list_versions(v1.model_id)}
        assert states == {1: STATE_ACTIVE, 2: STATE_INACTIVE}, label
        reg.activate(v1.model_id, 2)
        assert reg.active_version(v1.model_id).version == 2, label
        with pytest.raises(ValueError):
            reg.delete_version(v1.model_id, 2)
        reg.delete_version(v1.model_id, 1)
        assert [v.version for v in reg.list_versions(v1.model_id)] == [2], label
        assert [m["model_id"] for m in reg.list_models()] == [v1.model_id], label


def test_bucket_load_params_roundtrip(tmp_path, s3_bucket, mlp_setup):
    model, params, x = mlp_setup
    want = model.apply(params, x)
    for label, make in _registries(tmp_path, s3_bucket):
        reg = make()
        mv = reg.create_model_version(
            "rtt", MODEL_TYPE_MLP, "h", params, ModelEvaluation()
        )
        # template-less restore -> numpy leaves, placement at first apply
        loaded = reg.load_params(mv.model_id, mv.version)
        got = model.apply(loaded, x)
        assert np.allclose(np.asarray(got), np.asarray(want)), label
        # template restore preserves the pytree structure
        loaded_t = reg.load_params(mv.model_id, mv.version, template=params)
        got_t = model.apply(loaded_t, x)
        assert np.allclose(np.asarray(got_t), np.asarray(want)), label


def test_publish_on_a_serves_on_b_without_shared_fs(s3_bucket, mlp_setup):
    """Trainer-side registry publishes + activates; a COMPLETELY separate
    registry client (fresh backend connection — what a scheduler on
    another host constructs) sees the activation and serves the params.
    The only shared state is the signed HTTP bucket."""
    model, params, x = mlp_setup
    url = (
        f"s3://models?endpoint={s3_bucket}"
        f"&access_key={ACCESS}&secret_key={SECRET}&region={REGION}"
    )
    trainer_reg = open_registry(url)
    mv = trainer_reg.create_model_version(
        "rtt-regressor", MODEL_TYPE_MLP, "sched-1", params,
        ModelEvaluation(mse=0.1), metadata={"hidden_dim": 8},
    )
    trainer_reg.activate(mv.model_id, mv.version)

    scheduler_reg = open_registry(url)  # new client, no local state
    server = ModelServer(
        scheduler_reg, "rtt-regressor", "sched-1", MODEL_TYPE_MLP,
        template_params=None, model=ProbeRTTRegressor(hidden_dim=8),
    )
    assert server.refresh() is True
    assert server.version == mv.version
    out = server.infer_mlp(x)
    assert np.asarray(out).shape == (2,)


def test_bad_credentials_rejected(s3_bucket, mlp_setup):
    _, params, _ = mlp_setup
    url = (
        f"s3://models?endpoint={s3_bucket}"
        f"&access_key={ACCESS}&secret_key=WRONG&region={REGION}"
    )
    with pytest.raises(Exception):
        reg = open_registry(url)
        reg.create_model_version("m", MODEL_TYPE_MLP, "h", params, ModelEvaluation())


def test_concurrent_publishers_get_distinct_versions(tmp_path, s3_bucket, mlp_setup):
    """Two publishers sharing one bucket race create_model_version: the
    conditional version.json create (If-None-Match / O_EXCL) must hand
    them DISTINCT version numbers — the ADVICE r4 list-then-put race
    silently overwrote one publisher's params with the other's."""
    import threading

    _, params, _ = mlp_setup
    for label, make in _registries(tmp_path, s3_bucket):
        reg_a, reg_b = make(), make()
        barrier = threading.Barrier(2)
        out, errs = [], []

        def publish(reg):
            try:
                barrier.wait(timeout=10)
                mv = reg.create_model_version(
                    "raced", MODEL_TYPE_MLP, "h", params, ModelEvaluation()
                )
                out.append(mv.version)
            except Exception as e:  # noqa: BLE001 - surface in the assert
                errs.append(e)

        threads = [threading.Thread(target=publish, args=(r,)) for r in (reg_a, reg_b)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=30)
        assert not errs, (label, errs)
        assert sorted(out) == [1, 2], (label, out)
        # both versions fully landed: distinct params objects exist
        for v in (1, 2):
            assert reg_a.load_params(reg_a.model_id("raced", "h"), v) is not None, label


def test_put_object_if_absent_semantics(tmp_path, s3_bucket):
    """The CAS primitive itself: second create of a key reports False and
    leaves the first writer's bytes intact, on every backend. OSS/OBS do
    NOT honor If-None-Match on PUT — their conditional create is the
    vendor forbid-overwrite header answering 409 — so each vendor
    backend must send ITS header (the fake servers enforce both)."""
    from test_remote_sources import _OSSHandler, _Store, _serve

    vendor_servers = []
    backends = [
        ("fs", FilesystemBackend(tmp_path / "cas-store")),
        ("s3", new_backend(
            "s3", endpoint=s3_bucket, access_key=ACCESS,
            secret_key=SECRET, region=REGION,
        )),
    ]
    for vendor in ("oss", "obs"):
        handler = type("H", (_OSSHandler,), {"store": _Store(), "scheme": vendor.upper()})
        srv, addr = _serve(handler)
        vendor_servers.append(srv)
        backends.append((vendor, new_backend(
            vendor, endpoint=addr, access_key=ACCESS, secret_key=SECRET,
        )))
    try:
        for label, backend in backends:
            backend.create_bucket("cas")
            assert backend.put_object_if_absent("cas", "k", b"first") is True, label
            assert backend.put_object_if_absent("cas", "k", b"second") is False, label
            assert backend.get_object("cas", "k") == b"first", label
    finally:
        for srv in vendor_servers:
            srv.shutdown()


def test_open_registry_dispatch(tmp_path):
    assert isinstance(open_registry(tmp_path / "plain"), ModelRegistry)
    reg = open_registry(f"fs://models/pre?base_dir={tmp_path / 'store'}")
    assert isinstance(reg, BucketModelRegistry)
    assert (reg.bucket, reg.prefix) == ("models", "pre")
