"""Differential tests: the batched evaluator kernel vs a straight-line
Python oracle of the reference semantics (evaluator_base.go:71-188,
evaluator_network_topology.go:96-224, evaluator.go:93-129,
scheduling.go:500-571)."""

import numpy as np
import pytest

from dragonfly2_tpu.config.constants import CONSTANTS
from dragonfly2_tpu.ops import evaluator as ev
from dragonfly2_tpu.records import synth
from dragonfly2_tpu.records.features import downloads_to_eval_batch
from dragonfly2_tpu.state.fsm import BAD_NODE_STATES, HostType, PeerState


# ----------------------------------------------------------------- oracle

def oracle_score(f, i, j, algorithm="default"):
    if algorithm == "nt":
        w = (0.2, 0.2, 0.15, 0.11, 0.11, 0.11, 0.12)
    else:
        w = (0.2, 0.2, 0.15, 0.15, 0.15, 0.15, 0.0)
    w_piece, w_up, w_free, w_type, w_idc, w_loc, w_probe = w

    total = int(f.total_piece_count[i])
    if total > 0:
        piece = int(f.finished_pieces[i, j]) / total
    else:
        piece = float(f.finished_pieces[i, j]) - float(f.child_finished_pieces[i])

    uc, ufc = int(f.upload_count[i, j]), int(f.upload_failed_count[i, j])
    if uc < ufc:
        upload = 0.0
    elif uc == 0 and ufc == 0:
        upload = 1.0
    else:
        upload = (uc - ufc) / uc

    limit, used = int(f.upload_limit[i, j]), int(f.upload_used[i, j])
    free = limit - used
    free_score = free / limit if (limit > 0 and free > 0) else 0.0

    if f.host_type[i, j] != int(HostType.NORMAL):
        active = f.peer_state[i, j] in (int(PeerState.RECEIVED_NORMAL), int(PeerState.RUNNING))
        type_score = 1.0 if active else 0.0
    else:
        type_score = 0.5

    p_idc, c_idc = int(f.parent_idc[i, j]), int(f.child_idc[i])
    idc = 1.0 if (p_idc != 0 and c_idc != 0 and p_idc == c_idc) else 0.0

    p_loc, c_loc = f.parent_location[i, j], f.child_location[i]
    if p_loc[0] == 0 or c_loc[0] == 0:
        loc = 0.0
    elif (p_loc == c_loc).all():
        loc = 1.0
    else:
        depth = 0
        for a, b in zip(p_loc, c_loc):
            if a == 0 or b == 0 or a != b:
                break
            depth += 1
        loc = depth / 5
    score = (
        w_piece * piece + w_up * upload + w_free * free_score
        + w_type * type_score + w_idc * idc + w_loc * loc
    )
    if w_probe:
        probe = (
            (CONSTANTS.PING_TIMEOUT_NS - float(f.avg_rtt_ns[i, j])) / CONSTANTS.PING_TIMEOUT_NS
            if f.has_rtt[i, j]
            else 0.0
        )
        score += w_probe * probe
    return score


def oracle_is_bad(f, i, j):
    if PeerState(int(f.peer_state[i, j])) in BAD_NODE_STATES:
        return True
    n = int(f.piece_cost_count[i, j])
    if n < 2:
        return False
    costs = f.piece_costs[i, j, :n].astype(float)
    last, prev = costs[-1], costs[:-1]
    mean = prev.mean()
    if n < 30:
        return last > mean * 20
    return last > mean + 3 * prev.std()  # population std, like stats.StandardDeviation


# ---------------------------------------------------------------- fixtures

@pytest.fixture(scope="module")
def batch():
    cluster = synth.make_cluster(64, seed=7)
    records = synth.gen_download_records(cluster, 32)
    feats = downloads_to_eval_batch(records, batch_tasks=32, batch_candidates=20)
    rng = np.random.default_rng(1)
    # exercise every branch: scatter states, rtt, zero-limit hosts
    feats.peer_state = rng.integers(0, 10, feats.peer_state.shape).astype(np.int8)
    feats.has_rtt = rng.random(feats.has_rtt.shape) < 0.5
    feats.avg_rtt_ns = (rng.random(feats.avg_rtt_ns.shape) * 2e9).astype(np.float32)
    zero = rng.random(feats.upload_limit.shape) < 0.1
    feats.upload_limit[zero] = 0
    return feats


def test_scores_match_oracle(batch):
    for algorithm in ("default", "nt"):
        got = np.asarray(ev.evaluate(batch.as_dict(), algorithm))
        for i in range(0, batch.valid.shape[0], 5):
            for j in range(batch.valid.shape[1]):
                if not batch.valid[i, j]:
                    continue
                want = oracle_score(batch, i, j, algorithm)
                assert got[i, j] == pytest.approx(want, rel=1e-5), (algorithm, i, j)


def test_is_bad_node_matches_oracle(batch):
    got = np.asarray(ev.is_bad_node(batch.piece_costs, batch.piece_cost_count, batch.peer_state))
    for i in range(batch.valid.shape[0]):
        for j in range(batch.valid.shape[1]):
            assert got[i, j] == oracle_is_bad(batch, i, j), (i, j)


def test_is_bad_node_three_sigma_branch():
    """n >= 30 uses mean+3*sigma; a clear outlier flips it."""
    c = CONSTANTS.PIECE_COST_CAPACITY
    costs = np.zeros((1, 2, c), np.float32)
    count = np.full((1, 2), 30, np.int32)
    state = np.full((1, 2), int(PeerState.RUNNING), np.int8)
    base = 100 + np.arange(29, dtype=np.float32)  # tight spread
    costs[0, 0, :29] = base
    costs[0, 0, 29] = 100.0   # normal last cost
    costs[0, 1, :29] = base
    costs[0, 1, 29] = 1e6     # wild outlier
    got = np.asarray(ev.is_bad_node(costs, count, state))
    assert not got[0, 0]
    assert got[0, 1]


def test_filter_respects_reference_rules(batch):
    feats = batch.as_dict()
    mask = np.asarray(ev.filter_candidates(feats))
    bad = np.asarray(ev.is_bad_node(batch.piece_costs, batch.piece_cost_count, batch.peer_state))
    for i in range(batch.valid.shape[0]):
        for j in range(batch.valid.shape[1]):
            if not batch.valid[i, j]:
                assert not mask[i, j]
                continue
            expect = True
            if batch.parent_host_id[i, j] == batch.child_host_id[i]:
                expect = False
            state = int(batch.peer_state[i, j])
            rooted = state in (int(PeerState.BACK_TO_SOURCE), int(PeerState.SUCCEEDED)) or (
                batch.host_type[i, j] != 0
            )
            if not rooted:
                expect = False
            if bad[i, j]:
                expect = False
            if batch.upload_limit[i, j] - batch.upload_used[i, j] <= 0:
                expect = False
            assert mask[i, j] == expect, (i, j)


def test_schedule_candidate_parents_selects_best(batch):
    out = ev.schedule_candidate_parents(batch.as_dict(), algorithm="default", limit=4)
    scores = np.asarray(out["scores"])
    mask = np.asarray(out["mask"])
    sel = np.asarray(out["selected"])
    sel_valid = np.asarray(out["selected_valid"])
    for i in range(scores.shape[0]):
        eligible = np.nonzero(mask[i])[0]
        want_n = min(len(eligible), 4)
        assert sel_valid[i].sum() == want_n
        if want_n:
            # selected set == top-want_n by score among eligible
            order = eligible[np.argsort(-scores[i, eligible], kind="stable")]
            assert set(sel[i, :want_n].tolist()) == set(order[:want_n].tolist())
            # and in descending score order
            got_scores = scores[i, sel[i, :want_n]]
            assert (np.diff(got_scores) <= 1e-6).all()


def test_find_success_parent(batch):
    """Reference runs the full candidate filter first (scheduling.go:478)
    then keeps Succeeded parents (:484-489)."""
    out = ev.find_success_parent(batch.as_dict())
    scores = np.asarray(ev.evaluate(batch.as_dict()))
    fmask = np.asarray(ev.filter_candidates(batch.as_dict()))
    found = np.asarray(out["found"])
    parent = np.asarray(out["parent"])
    for i in range(scores.shape[0]):
        succeeded = [
            j
            for j in range(batch.valid.shape[1])
            if fmask[i, j] and batch.peer_state[i, j] == int(PeerState.SUCCEEDED)
        ]
        assert found[i] == bool(succeeded)
        if succeeded:
            best = max(succeeded, key=lambda j: (scores[i, j], -j))
            assert scores[i, parent[i]] == pytest.approx(scores[i, best])


def test_packed_matches_full(batch):
    """The serving-path packed variant must agree with the debug dict
    variant bit-for-bit (indices, validity, scores)."""
    for algorithm in ("default", "nt"):
        full = ev.schedule_candidate_parents(batch.as_dict(), algorithm=algorithm, limit=4)
        packed = np.asarray(
            ev.schedule_candidate_parents_packed(batch.as_dict(), algorithm=algorithm, limit=4)
        )
        idx, valid, scores = ev.unpack_selection(packed)
        fv = np.asarray(full["selected_valid"])
        assert (valid == fv).all()
        assert (idx[valid] == np.asarray(full["selected"])[fv]).all()
        assert (scores[valid] == np.asarray(full["selected_scores"])[fv]).all()


def test_packed_transport_roundtrip(batch):
    """pack_eval_batch -> unpack_eval_batch reconstructs every field
    exactly (int64 identity fields travel as int32, matching what the
    x32 dict path already does at device_put time)."""
    import jax
    import jax.numpy as jnp

    fd = batch.as_dict()
    b, k = fd["valid"].shape
    c, l, n = (
        fd["piece_costs"].shape[-1],
        fd["parent_location"].shape[-1],
        fd["numeric"].shape[-1],
    )
    rng = np.random.default_rng(5)
    bl = rng.random((b, k)) < 0.2
    ind = rng.integers(0, 3, (b, k)).astype(np.int32)
    cae = rng.random((b, k)) < 0.8
    buf = ev.pack_eval_batch(fd, blocklist=bl, in_degree=ind, can_add_edge=cae,
                             child_host_slot=np.arange(b, dtype=np.int32),
                             cand_host_slot=np.tile(np.arange(k, dtype=np.int32), (b, 1)))
    unpack = jax.jit(ev.unpack_eval_batch, static_argnames=("b", "k", "c", "l", "n"))
    out = {key: np.asarray(v) for key, v in unpack(jnp.asarray(buf), b=b, k=k, c=c, l=l, n=n).items()}
    for name, want in fd.items():
        want = np.asarray(want)
        if want.dtype == np.int64:
            want = want.astype(np.int32)
        got = out[name]
        assert np.array_equal(got.astype(want.dtype), want), name
    assert np.array_equal(out["blocklist"], bl)
    assert np.array_equal(out["in_degree"], ind)
    assert np.array_equal(out["can_add_edge"], cae)
    assert np.array_equal(out["child_host_slot"], np.arange(b, dtype=np.int32))


def test_schedule_from_packed_matches_dict_transport(batch):
    """The single-buffer transport selects the SAME parents as the dict
    transport (scores may differ by float-fusion ulps, never ordering):
    the serving tick's one-H2D contract cannot drift from the oracle-
    tested dict path. The batch is padded to the smallest _EVAL_BUCKETS
    shape (pad rows valid=False) because the instrumented packed jit is
    under the session retrace tripwire: every signature it routes —
    tests included — must come from the proven bucket set."""
    from dragonfly2_tpu.cluster.scheduler import _EVAL_BUCKETS

    fd = batch.as_dict()
    rows = fd["valid"].shape[0]
    bucket = _EVAL_BUCKETS[0]
    assert rows <= bucket
    fd = {
        name: np.concatenate(
            [v, np.zeros((bucket - rows,) + v.shape[1:], v.dtype)]
        )
        for name, v in fd.items()
    }
    b, k = fd["valid"].shape
    c, l, n = (
        fd["piece_costs"].shape[-1],
        fd["parent_location"].shape[-1],
        fd["numeric"].shape[-1],
    )
    rng = np.random.default_rng(6)
    bl = rng.random((b, k)) < 0.2
    ind = rng.integers(0, 3, (b, k)).astype(np.int32)
    cae = rng.random((b, k)) < 0.8
    for algorithm in ("default", "nt"):
        want = np.asarray(ev.schedule_candidate_parents_packed(
            fd, bl, ind, cae, algorithm=algorithm, limit=4
        ))
        buf = ev.pack_eval_batch(fd, blocklist=bl, in_degree=ind, can_add_edge=cae)
        got = np.asarray(ev.schedule_from_packed(
            buf, b, k, c, l, n, algorithm=algorithm, limit=4
        ))
        assert np.array_equal(want[..., 0], got[..., 0]), algorithm
        valid = want[..., 0] >= 0
        np.testing.assert_allclose(
            got[..., 1][valid], want[..., 1][valid], atol=1e-5
        )


def test_select_with_scores_packed_matches(batch):
    rng = np.random.default_rng(3)
    scores = rng.random(batch.valid.shape).astype(np.float32)
    full = ev.select_with_scores(batch.as_dict(), scores, limit=4)
    packed = np.asarray(ev.select_with_scores_packed(batch.as_dict(), scores, limit=4))
    idx, valid, vals = ev.unpack_selection(packed)
    fv = np.asarray(full["selected_valid"])
    assert (valid == fv).all()
    assert (idx[valid] == np.asarray(full["selected"])[fv]).all()
    assert (vals[valid] == np.asarray(full["selected_scores"])[fv]).all()


def test_masked_top_k_rank_vs_lax():
    """The rank-select fast path must match lax.top_k exactly, including
    lowest-index tie-breaks with duplicate scores and rows with fewer
    valid candidates than k (the -inf*0=NaN trap regression test)."""
    import jax
    import jax.numpy as jnp

    from dragonfly2_tpu.ops.topk import NEG_INF, _masked_top_k_rank

    rng = np.random.default_rng(11)
    scores = rng.random((64, 64)).astype(np.float32)
    scores[:, 10] = scores[:, 5]  # duplicates -> tie-break by index
    scores[:, 20] = scores[:, 5]
    mask = rng.random((64, 64)) < 0.5
    mask[0] = False          # no valid candidates at all
    mask[1] = False
    mask[1, 3] = True        # exactly one valid candidate
    v, i, m = _masked_top_k_rank(jnp.asarray(scores), jnp.asarray(mask), 4)
    ref_masked = jnp.where(jnp.asarray(mask), jnp.asarray(scores), NEG_INF)
    rv, ri = jax.lax.top_k(ref_masked, 4)
    rm = rv > NEG_INF
    assert (np.asarray(m) == np.asarray(rm)).all()
    assert (np.asarray(v)[np.asarray(m)] == np.asarray(rv)[np.asarray(rm)]).all()
    assert (np.asarray(i)[np.asarray(m)] == np.asarray(ri)[np.asarray(rm)]).all()
    # invalid slots keep the -inf contract
    assert np.isneginf(np.asarray(v)[~np.asarray(m)]).all()


def test_masked_top_k_rank_hostile_scores():
    """Externally supplied scores (plugin/ml path) may contain -inf/NaN:
    those candidates must still outrank every masked-out candidate, and
    validity must never surface a blocklisted index (r2 review finding)."""
    import jax.numpy as jnp

    from dragonfly2_tpu.ops.topk import masked_top_k

    scores = np.full((1, 8), 1.0, np.float32)
    scores[0, 0] = -np.inf   # eligible but scored -inf by a plugin
    scores[0, 1] = np.nan    # eligible but NaN
    mask = np.zeros((1, 8), bool)
    mask[0, :4] = True       # 4 eligible candidates; 4..7 are masked out
    v, i, m = masked_top_k(jnp.asarray(scores), jnp.asarray(mask), 6)
    v, i, m = np.asarray(v), np.asarray(i), np.asarray(m)
    assert m[0].sum() == 4                     # exactly the eligible count
    assert set(i[0, :4].tolist()) == {0, 1, 2, 3}  # never a masked index
    assert i[0, :2].tolist() == [2, 3]         # real scores rank first


def test_masked_top_k_wide_path_hostile_scores():
    """The lax.top_k fallback (K > rank-select width) honors the same
    hostile-score contract as the rank path: eligible -inf/NaN candidates
    outrank masked ones and validity comes from the eligible count
    (r2 advisor finding)."""
    import jax.numpy as jnp

    from dragonfly2_tpu.ops.topk import _RANK_SELECT_MAX_WIDTH, masked_top_k

    n = _RANK_SELECT_MAX_WIDTH * 2  # force the wide fallback
    scores = np.full((1, n), 1.0, np.float32)
    scores[0, 0] = -np.inf
    scores[0, 1] = np.nan
    mask = np.zeros((1, n), bool)
    mask[0, :4] = True
    v, i, m = masked_top_k(jnp.asarray(scores), jnp.asarray(mask), 6)
    v, i, m = np.asarray(v), np.asarray(i), np.asarray(m)
    assert m[0].sum() == 4
    assert set(i[0, :4].tolist()) == {0, 1, 2, 3}
    assert i[0, :2].tolist() == [2, 3]
    assert np.isneginf(v[0, 4:]).all()
