#!/usr/bin/env python
"""One-shot static-analysis gate: dflint + waiver audit + typecheck.

``python -m tools.lint_all`` is THE entry point CI and the tier-1 gate
share (tests/test_static_analysis.py invokes the same ``main``), so
"the lint is green" means one thing everywhere:

1. dflint's seven passes over ``dragonfly2_tpu/`` report zero unwaived
   findings and every waiver carries a substantive reason;
2. the waiver audit finds no stale waivers (a ``waive[RULE]`` whose
   rule no longer fires at that site);
3. the mypy strict-core subset passes (or gates with the explicit
   SKIPPED marker on rigs without mypy — tools/typecheck.py);
4. benchwatch validates every checked-in ``BENCH_*.json`` against the
   artifact schema and flags adjacent-round metric regressions beyond
   its threshold (tools/benchwatch.py --check);
5. the dfwire breaking gate (``python -m tools.dflint --breaking``):
   the live wire-schema extraction is compatible with the checked-in
   ``tools/dfwire_schema.json`` snapshot — add-field-with-default is
   the only compatible evolution, everything else needs an intentional
   ``--breaking --write`` regeneration with its schema_version bump.
   Runs in a FRESH interpreter so message types registered by the test
   process (codec tests register throwaway dataclasses) never leak
   into the extraction.

``--json`` forwards dflint's machine-readable findings document.

Exit 0 = all green; 1 = any stage failed.
"""

from __future__ import annotations

import os
import subprocess
import sys
from pathlib import Path

ROOT = Path(__file__).resolve().parents[1]


def main(argv: list[str] | None = None) -> int:
    import argparse

    parser = argparse.ArgumentParser(
        prog="lint_all",
        description="dflint (seven passes, waiver audit) + mypy strict-core "
                    "+ benchwatch + the dfwire breaking gate — the one "
                    "tier-1/CI gate",
    )
    parser.add_argument("--json", action="store_true", dest="as_json",
                        help="emit dflint's machine-readable document with "
                             "the typecheck verdict merged in")
    # no positional targets on purpose: the gate is all-or-nothing; a
    # scoped lint is `python -m tools.dflint <paths>` — accepting paths
    # here while silently linting the whole tree would misreport scope
    args = parser.parse_args(sys.argv[1:] if argv is None else argv)
    as_json = args.as_json

    from tools.dflint.__main__ import main as dflint_main
    from tools.typecheck import SKIP_MARKER

    dflint_args = ["--root", str(ROOT), "--audit-waivers"]
    if as_json:
        import contextlib
        import io
        import json

        captured = io.StringIO()
        with contextlib.redirect_stdout(captured):
            rc_lint = dflint_main(dflint_args + ["--json"])
        doc = json.loads(captured.getvalue())
    else:
        rc_lint = dflint_main(dflint_args)
        print(f"lint_all: dflint+waiver-audit {'OK' if rc_lint == 0 else 'FAILED'}")

    proc = subprocess.run(
        [sys.executable, str(ROOT / "tools" / "typecheck.py")],
        cwd=ROOT, capture_output=True, text=True, timeout=600,
    )

    # bench-artifact registry gate: every BENCH_*.json parses against
    # its schema and no adjacent-round metric regressed past threshold
    import io

    from tools.benchwatch import check as benchwatch_check

    bench_out = io.StringIO()
    rc_bench = benchwatch_check(ROOT, out=bench_out)

    # dfwire breaking gate in a fresh interpreter: the test process has
    # registered throwaway message types (codec tests), and an in-proc
    # extraction would report them as schema adds
    wire_proc = subprocess.run(
        [sys.executable, "-m", "tools.dflint", "--breaking"],
        cwd=ROOT, capture_output=True, text=True, timeout=600,
        env={**os.environ, "JAX_PLATFORMS": "cpu"},
    )

    failed = (
        rc_lint != 0 or proc.returncode != 0 or rc_bench != 0
        or wire_proc.returncode != 0
    )
    if as_json:
        # one merged document: the overall `ok` covers BOTH stages (a
        # dflint-only verdict would let a mypy failure ship green), and
        # the typecheck output rides along so the failure detail is
        # recoverable from the JSON alone
        doc["typecheck"] = {
            "returncode": proc.returncode,
            "skipped": SKIP_MARKER in proc.stdout,
            "output": (proc.stdout + proc.stderr).strip(),
        }
        doc["benchwatch"] = {
            "returncode": rc_bench,
            "output": bench_out.getvalue().strip(),
        }
        doc["wire_breaking"] = {
            "returncode": wire_proc.returncode,
            "output": (wire_proc.stdout + wire_proc.stderr).strip(),
        }
        doc["ok"] = not failed
        print(json.dumps(doc, indent=2))
    else:
        sys.stdout.write(proc.stdout)
        sys.stderr.write(proc.stderr)
        print(f"lint_all: typecheck {'OK' if proc.returncode == 0 else 'FAILED'}")
        sys.stdout.write(bench_out.getvalue())
        print(f"lint_all: benchwatch {'OK' if rc_bench == 0 else 'FAILED'}")
        sys.stdout.write(wire_proc.stdout)
        sys.stderr.write(wire_proc.stderr)
        print(f"lint_all: dfwire-breaking "
              f"{'OK' if wire_proc.returncode == 0 else 'FAILED'}")

    return 1 if failed else 0


if __name__ == "__main__":
    sys.exit(main())
