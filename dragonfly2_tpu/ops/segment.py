"""Segment reductions — the graph-aggregation primitive.

Where the reference walks pointer DAGs (pkg/graph/dag/dag.go), the TPU
build lowers neighborhood aggregation to `jax.ops.segment_sum` over COO
edge arrays (SURVEY.md §2.6/§7): gather node states at edge endpoints,
reduce by segment id. All wrappers take a static `num_segments` so shapes
stay compile-time constant.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp


@functools.partial(jax.jit, static_argnames=("num_segments",))
def segment_sum(data: jax.Array, segment_ids: jax.Array, num_segments: int) -> jax.Array:
    return jax.ops.segment_sum(data, segment_ids, num_segments=num_segments)


@functools.partial(jax.jit, static_argnames=("num_segments",))
def segment_mean(data: jax.Array, segment_ids: jax.Array, num_segments: int) -> jax.Array:
    totals = jax.ops.segment_sum(data, segment_ids, num_segments=num_segments)
    ones = jnp.ones(data.shape[:1], dtype=data.dtype)
    counts = jax.ops.segment_sum(ones, segment_ids, num_segments=num_segments)
    counts = jnp.maximum(counts, 1)
    if data.ndim > 1:
        counts = counts.reshape((-1,) + (1,) * (data.ndim - 1))
    return totals / counts


@functools.partial(jax.jit, static_argnames=("num_segments",))
def segment_max(data: jax.Array, segment_ids: jax.Array, num_segments: int) -> jax.Array:
    return jax.ops.segment_max(data, segment_ids, num_segments=num_segments)


@functools.partial(jax.jit, static_argnames=("num_segments",))
def segment_count(segment_ids: jax.Array, num_segments: int) -> jax.Array:
    ones = jnp.ones(segment_ids.shape, dtype=jnp.int32)
    return jax.ops.segment_sum(ones, segment_ids, num_segments=num_segments)
