"""Ring attention: sequence/context parallelism over the mesh `sp` axis.

The reference has no sequence models (SURVEY.md §5 "long-context:
absent") — this is new TPU-first capability: attention over sequences too
long for one chip's HBM, computed blockwise with the KV shards rotating
around the ICI ring (`lax.ppermute`) while each device keeps only its
query shard — the Ring Attention construction (see PAPERS.md), with
flash-style online-softmax accumulation so nothing materializes the full
[L, L] score matrix.

Layouts: q/k/v are [B, H, L, D] (L = per-device shard inside shard_map),
kv_mask is [B, L] key validity. `dense_attention` is the single-device
reference implementation and the parity oracle in tests.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from dragonfly2_tpu.parallel.mesh import DP_AXIS, SP_AXIS

_NEG = jnp.float32(-1e30)


def dense_attention(q, k, v, kv_mask, causal: bool = False) -> jax.Array:
    """Reference softmax attention. [B,H,L,D] x [B,L] -> [B,H,L,D].

    The q.k matmul keeps the input dtype (bf16 on the MXU) but accumulates
    in float32 — the same contract as the ring path, so the single-chip and
    sp>1 implementations are numerically interchangeable. Also the parity
    oracle and backward-recompute path for the pallas kernel (ops/flash.py),
    which is why the causal option lives here: ONE copy of the masking
    contract."""
    scale = 1.0 / jnp.sqrt(q.shape[-1]).astype(jnp.float32)
    scores = (
        jnp.einsum("bhqd,bhkd->bhqk", q, k, preferred_element_type=jnp.float32) * scale
    )
    valid = jnp.broadcast_to(kv_mask[:, None, None, :], scores.shape)
    if causal:
        ln = q.shape[2]
        q_pos = jax.lax.broadcasted_iota(jnp.int32, (ln, ln), 0)
        k_pos = jax.lax.broadcasted_iota(jnp.int32, (ln, ln), 1)
        valid = valid & (k_pos <= q_pos)[None, None]
    scores = jnp.where(valid, scores, _NEG)
    probs = jax.nn.softmax(scores, axis=-1)
    # rows with no valid key softmax over the -1e30 floor uniformly; zero
    # them so fully-masked rows produce 0 like the ring path
    probs = probs * valid
    return jnp.einsum("bhqk,bhkd->bhqd", probs, v.astype(jnp.float32)).astype(q.dtype)


def ring_attention(
    q, k, v, kv_mask, axis_name: str = SP_AXIS, use_flash: bool = False
) -> jax.Array:
    """Blockwise attention inside shard_map: every step attends the local
    queries to the current KV block, then rotates KV one hop around the
    `axis_name` ring. Online softmax keeps running (max, sum, acc) in
    float32.

    use_flash=True computes each per-device block with the pallas kernel's
    partials mode (ops/flash.py) and merges them with the same combine —
    the [Lq, Lk] block score matrix never materializes, so long local
    shards fit where the einsum path would blow HBM. Forward-only (the
    partials kernel has no VJP); training keeps the einsum path."""
    n = jax.lax.psum(1, axis_name)
    scale = 1.0 / jnp.sqrt(q.shape[-1]).astype(jnp.float32)
    batch, heads, q_len, dim = q.shape

    acc = jnp.zeros((batch, heads, q_len, dim), jnp.float32)
    row_max = jnp.full((batch, heads, q_len), _NEG, jnp.float32)
    row_sum = jnp.zeros((batch, heads, q_len), jnp.float32)
    perm = [(j, (j + 1) % n) for j in range(n)]

    if use_flash:
        from dragonfly2_tpu.ops.flash import flash_attention_partials

        def attend_block(acc, row_max, row_sum, kb, vb, mb):
            acc_b, m_b, l_b = flash_attention_partials(q, kb, vb, mb)
            new_max = jnp.maximum(row_max, m_b)
            c_old = jnp.exp(row_max - new_max)
            c_new = jnp.exp(m_b - new_max)
            acc = acc * c_old[..., None] + acc_b * c_new[..., None]
            row_sum = row_sum * c_old + l_b * c_new
            return acc, new_max, row_sum
    else:
        def attend_block(acc, row_max, row_sum, kb, vb, mb):
            scores = (
                jnp.einsum("bhqd,bhkd->bhqk", q, kb, preferred_element_type=jnp.float32)
                * scale
            )
            key_valid = mb[:, None, None, :]
            scores = jnp.where(key_valid, scores, _NEG)
            block_max = jnp.max(scores, axis=-1)
            new_max = jnp.maximum(row_max, block_max)
            correction = jnp.exp(row_max - new_max)
            probs = jnp.exp(scores - new_max[..., None]) * key_valid
            acc = acc * correction[..., None] + jnp.einsum(
                "bhqk,bhkd->bhqd", probs, vb.astype(jnp.float32)
            )
            row_sum = row_sum * correction + jnp.sum(probs, axis=-1)
            return acc, new_max, row_sum

    def body(_, carry):
        acc, row_max, row_sum, kb, vb, mb = carry
        acc, row_max, row_sum = attend_block(acc, row_max, row_sum, kb, vb, mb)
        kb, vb, mb = jax.lax.ppermute((kb, vb, mb), axis_name, perm)
        return acc, row_max, row_sum, kb, vb, mb

    # n-1 attend+rotate steps, then the final block attends WITHOUT the
    # trailing rotation — its output would be discarded, and each skipped
    # ppermute saves a full K+V+mask shard crossing the ICI ring.
    acc, row_max, row_sum, kb, vb, mb = jax.lax.fori_loop(
        0, n - 1, body, (acc, row_max, row_sum, k, v, kv_mask)
    )
    acc, row_max, row_sum = attend_block(acc, row_max, row_sum, kb, vb, mb)
    out = acc / jnp.maximum(row_sum, 1e-9)[..., None]
    return out.astype(q.dtype)


def sharded_ring_attention(mesh, q, k, v, kv_mask, use_flash: bool = False) -> jax.Array:
    """shard_map wrapper: batch over `dp`, sequence over `sp`. Global
    shapes in, global shapes out; each device holds L/sp of the sequence
    and the KV shards ride the ICI ring. `use_flash` swaps the per-device
    block computation for the pallas partials kernel (forward-only)."""
    qkv_spec = P(DP_AXIS, None, SP_AXIS, None)
    mask_spec = P(DP_AXIS, SP_AXIS)
    fn = jax.shard_map(
        functools.partial(ring_attention, axis_name=SP_AXIS, use_flash=use_flash),
        mesh=mesh,
        in_specs=(qkv_spec, qkv_spec, qkv_spec, mask_spec),
        out_specs=qkv_spec,
        check_vma=False,
    )
    return fn(q, k, v, kv_mask)
