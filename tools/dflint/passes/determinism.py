"""DET001..DET003 — seed-determinism of the simulator/scenario decision
paths.

The paired-seed equivalence oracles (PR-8's vectorized-vs-loop control
plane, PR-9's megascale-vs-per-peer engine) and the scenario A/B matrix
all rest on one property: the same seed + spec produces the same event
stream, bit for bit, run after run. Three things silently break it:

- ``DET001`` unseeded randomness: module-level ``random.*`` /
  ``np.random.*`` calls draw from process-global state any import or
  test can perturb; ``default_rng()`` / ``Random()`` with no seed
  differ per process. Decision paths must draw from an explicitly
  seeded generator threaded through the object.
- ``DET002`` wall-clock reads (``time.time``/``time_ns``/``monotonic``/
  ``datetime.now``): a replay domain has MODEL time (rounds, event
  clocks); wall time makes the fault schedule depend on machine load.
  ``perf_counter`` is exempt — measuring how long a run took is not a
  decision.
- ``DET003`` iteration over a ``set``/``frozenset`` in a decision path:
  Python string hashing is randomized per process (PYTHONHASHSEED), so
  set order differs across runs even with identical seeds — a
  cross-run artifact diff waiting to happen. Wrap in ``sorted(...)``,
  or waive with the argument that the loop body is order-commutative.

Scope is the configured decision modules (simulator, scenario engine and
specs, megascale) — wall clocks are legitimate elsewhere (GC TTLs,
metrics), so a tree-wide DET002 would be noise, not signal. DET003
additionally covers the scheduler: its selection stream is what the
equivalence oracles diff.
"""

from __future__ import annotations

import ast

from tools.dflint.core import FileContext, Finding, attr_chain

DEFAULT_DECISION_SUFFIXES = (
    "cluster/simulator.py",
    "scenarios/engine.py",
    "scenarios/spec.py",
    "megascale/engine.py",
    "megascale/topology.py",
    "megascale/soak.py",
    # the SLO engine's replay evaluation path: megascale feeds it on the
    # event clock and paired-seed runs must produce identical alert
    # timelines — a wall-clock read here would make "did this run page?"
    # depend on machine load (perf_counter stays exempt: live engines
    # use it for window arithmetic, never for replay decisions)
    "telemetry/slo.py",
    # the tail ledger: paired-seed megascale runs pin its digest bit for
    # bit, and every recorded value must derive from the caller's clock
    # (virtual ns on the event plane) and the counter-hashed sampler —
    # a wall-clock read or unseeded rng here breaks the digest pin
    "telemetry/tailtrace.py",
    # the sharded control plane: ring-rebalance handoff sweeps iterate
    # the peer->shard routing map, and the K=1 equivalence oracle plus
    # the paired-seed fleet soaks pin the handoff stream bit for bit —
    # an unsorted dict/set walk here reorders PeerHandoffRequest frames
    # across processes (PYTHONHASHSEED) and breaks both
    # (perf_counter stays exempt: per-shard scheduler-seconds ledgers
    # measure cost, never decide)
    "megascale/fleet.py",
    # the real-process planet's replay-facing half: dfslo re-judges
    # BENCH_proc.json offline, so the timeline synthesized from observed
    # rounds and the sim-vs-real divergence verdict must be pure
    # functions of the recorded observations — a wall-clock read or rng
    # draw here would make the offline replay disagree with the live run
    # (the supervisor itself is NOT in scope: it manages real processes
    # on the real clock by design)
    "procworld/sample.py",
    "procworld/divergence.py",
)
# DET003 also guards the scheduler: the selection/response stream it
# produces is exactly what the paired-seed oracles compare
DEFAULT_SET_ITER_SUFFIXES = DEFAULT_DECISION_SUFFIXES + (
    "cluster/scheduler.py",
)

WALL_CLOCKS = {
    "time.time", "time.time_ns", "time.monotonic", "time.monotonic_ns",
    "datetime.now", "datetime.utcnow", "datetime.datetime.now",
    "datetime.datetime.utcnow",
}

SEEDED_FACTORIES = {"default_rng", "Random", "SeedSequence", "Generator", "key", "PRNGKey"}


class DeterminismPass:
    name = "determinism"
    rules = ("DET001", "DET002", "DET003")

    def __init__(
        self,
        decision_suffixes: tuple[str, ...] = DEFAULT_DECISION_SUFFIXES,
        set_iter_suffixes: tuple[str, ...] = DEFAULT_SET_ITER_SUFFIXES,
    ):
        self.decision_suffixes = decision_suffixes
        self.set_iter_suffixes = set_iter_suffixes

    def run(self, ctx: FileContext) -> list[Finding]:
        in_decision = any(ctx.rel.endswith(s) for s in self.decision_suffixes)
        in_set_scope = any(ctx.rel.endswith(s) for s in self.set_iter_suffixes)
        if not (in_decision or in_set_scope):
            return []
        findings: list[Finding] = []
        set_names = _collect_set_names(ctx.tree) if in_set_scope else set()
        safe_comp_iters = _order_insensitive_comp_iters(ctx.tree)
        for func, symbol in _functions_with_symbols(ctx.tree):
            for node in ast.walk(func):
                if in_decision and isinstance(node, ast.Call):
                    findings.extend(
                        self._check_call(ctx, node, symbol, func.lineno)
                    )
                if in_set_scope and isinstance(node, (ast.For, ast.AsyncFor)):
                    findings.extend(self._check_iteration(
                        ctx, node, node.iter, set_names, symbol, func.lineno
                    ))
                if in_set_scope and isinstance(node, ast.comprehension) \
                        and id(node.iter) not in safe_comp_iters:
                    findings.extend(self._check_iteration(
                        ctx, node.iter, node.iter, set_names, symbol,
                        func.lineno,
                    ))
        return findings

    # ------------------------------------------------------------- calls

    def _check_call(self, ctx, node: ast.Call, symbol, def_line) -> list[Finding]:
        chain = attr_chain(node.func)
        if chain is None:
            return []
        findings = []
        parts = chain.split(".")
        # module-global randomness: random.<fn>(...) / np.random.<fn>(...)
        if parts[0] == "random" and len(parts) == 2 \
                and parts[1] not in SEEDED_FACTORIES:
            findings.append(ctx.make_finding(
                "DET001", node,
                f"'{chain}()' draws from the process-global random state — "
                f"decision paths must use an explicitly seeded "
                f"random.Random/np.random.Generator",
                symbol=symbol, def_line=def_line,
            ))
        elif len(parts) >= 3 and parts[-2] == "random" \
                and parts[0] in ("np", "numpy") \
                and parts[-1] not in SEEDED_FACTORIES:
            findings.append(ctx.make_finding(
                "DET001", node,
                f"'{chain}()' uses numpy's legacy global rng — seed a "
                f"Generator (np.random.default_rng(seed)) instead",
                symbol=symbol, def_line=def_line,
            ))
        elif parts[-1] in ("default_rng", "Random") and not node.args \
                and not node.keywords:
            findings.append(ctx.make_finding(
                "DET001", node,
                f"'{chain}()' without a seed differs per process — thread "
                f"the scenario/sim seed through",
                symbol=symbol, def_line=def_line,
            ))
        elif chain in WALL_CLOCKS:
            findings.append(ctx.make_finding(
                "DET002", node,
                f"'{chain}()' reads the wall clock inside a deterministic "
                f"replay domain — use the model clock (rounds/event time); "
                f"perf_counter is fine for measuring, never for deciding",
                symbol=symbol, def_line=def_line,
            ))
        return findings

    # --------------------------------------------------------- iteration

    def _check_iteration(
        self, ctx, report_node, iter_expr, set_names, symbol, def_line
    ) -> list[Finding]:
        reason = _set_typed(iter_expr, set_names)
        if reason is None:
            return []
        return [ctx.make_finding(
            "DET003", report_node,
            (
                f"iteration over a set ({reason}) in a decision path — "
                f"set order depends on PYTHONHASHSEED across processes; "
                f"wrap in sorted(...) or waive with an order-commutativity "
                f"argument"
            ),
            symbol=symbol, def_line=def_line,
        )]


# ------------------------------------------------------------- helpers

# consumers whose result does not depend on iteration order: a set-fed
# comprehension inside one of these is deterministic by construction
ORDER_INSENSITIVE_CONSUMERS = {
    "sorted", "set", "frozenset", "sum", "min", "max", "any", "all", "len",
    "Counter", "collections.Counter",
}


def _order_insensitive_comp_iters(tree) -> set[int]:
    """ids of comprehension iter-exprs whose comprehension is the direct
    argument of an order-insensitive consumer (``sorted(x for x in s)``)."""
    safe: set[int] = set()
    for node in ast.walk(tree):
        if not isinstance(node, ast.Call):
            continue
        chain = attr_chain(node.func)
        if chain not in ORDER_INSENSITIVE_CONSUMERS:
            continue
        for arg in node.args:
            if isinstance(arg, (ast.GeneratorExp, ast.ListComp, ast.SetComp)):
                for gen in arg.generators:
                    safe.add(id(gen.iter))
    return safe


def _collect_set_names(tree) -> set[str]:
    """Names (locals and ``self.x`` attrs, flattened to their last
    component) assigned from set constructors anywhere in the module —
    a deliberately name-based approximation."""
    names: set[str] = set()
    for node in ast.walk(tree):
        if isinstance(node, ast.Assign) and _is_set_expr(node.value, names):
            for target in node.targets:
                chain = attr_chain(target)
                if chain is not None:
                    names.add(chain.rsplit(".", 1)[-1])
        elif isinstance(node, ast.AnnAssign) and node.value is not None:
            ann = getattr(node.annotation, "id", None) or attr_chain(node.annotation)
            if _is_set_expr(node.value, names) or (
                isinstance(ann, str) and ann.startswith(("set", "frozenset"))
            ):
                chain = attr_chain(node.target)
                if chain is not None:
                    names.add(chain.rsplit(".", 1)[-1])
    return names


def _is_set_expr(node: ast.AST, known: set[str]) -> bool:
    if isinstance(node, (ast.Set, ast.SetComp)):
        return True
    if isinstance(node, ast.Call):
        chain = attr_chain(node.func)
        if chain in ("set", "frozenset"):
            return True
    if isinstance(node, ast.BinOp) and isinstance(
        node.op, (ast.Sub, ast.BitAnd, ast.BitOr, ast.BitXor)
    ):
        return _is_set_expr(node.left, known) or _is_set_expr(node.right, known)
    chain = attr_chain(node)
    if chain is not None and chain.rsplit(".", 1)[-1] in known:
        return True
    return False


def _set_typed(iter_expr: ast.AST, set_names: set[str]) -> str | None:
    """Why the iterated expression is set-ordered, or None. sorted(...)
    and list(...)/tuple(...) wrappers of sets still reach here only when
    the ITERATED expr itself is the set — wrapping in sorted() changes
    the iterated expr to the sorted() call, which is not set-typed."""
    if isinstance(iter_expr, (ast.Set, ast.SetComp)):
        return "set literal/comprehension"
    if isinstance(iter_expr, ast.Call):
        chain = attr_chain(iter_expr.func)
        if chain in ("set", "frozenset"):
            return f"{chain}(...) result"
        # x.active() style known-set-returning calls are out of scope —
        # name-based only, by design
        return None
    if isinstance(iter_expr, ast.BinOp) and isinstance(
        iter_expr.op, (ast.Sub, ast.BitAnd, ast.BitOr, ast.BitXor)
    ):
        left = _set_typed(iter_expr.left, set_names)
        right = _set_typed(iter_expr.right, set_names)
        if left or right:
            return f"set algebra ({left or right})"
        return None
    chain = attr_chain(iter_expr)
    if chain is not None and chain.rsplit(".", 1)[-1] in set_names:
        return f"'{chain}' assigned from a set constructor"
    return None


def _functions_with_symbols(tree):
    """(funcdef, qualified symbol) pairs, class-aware, one level deep
    (nested defs inherit the enclosing symbol via the walk)."""
    for node in tree.body:
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            yield node, node.name
        elif isinstance(node, ast.ClassDef):
            for item in node.body:
                if isinstance(item, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    yield item, f"{node.name}.{item.name}"
