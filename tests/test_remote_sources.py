"""Cloud back-source + remote object-storage backends against in-proc
fake servers.

Mirrors the reference's e2e fixture strategy (SURVEY.md §4: minio +
file-server pods): a threaded mini-S3 that *recomputes* AWS SigV4 with
the shared secret (not just header presence), a mini-OSS/OBS that
recomputes the HMAC-SHA1 header signature, a WebHDFS namenode, and an
OCI registry with a bearer-token challenge."""

from __future__ import annotations

import base64
import hashlib
import hmac
import http.server
import json
import threading
import urllib.parse
import urllib.request

import pytest

from dragonfly2_tpu.client import source
from dragonfly2_tpu.objectstorage import signing
from dragonfly2_tpu.objectstorage.backends import new_backend
from dragonfly2_tpu.utils import dferrors

ACCESS, SECRET, REGION = "AKIDtest", "sekrit123", "us-test-1"


# ------------------------------------------------------------------ fakes


class _Store:
    def __init__(self):
        self.buckets: dict[str, dict[str, bytes]] = {}


class _BaseHandler(http.server.BaseHTTPRequestHandler):
    protocol_version = "HTTP/1.1"
    store: _Store

    def log_message(self, *a):
        pass

    def _body(self) -> bytes:
        n = int(self.headers.get("Content-Length") or 0)
        return self.rfile.read(n) if n else b""

    def _reply(self, code: int, body: bytes = b"", headers: dict | None = None):
        self.send_response(code)
        for k, v in (headers or {}).items():
            self.send_header(k, v)
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        if self.command != "HEAD":
            self.wfile.write(body)

    def _split(self) -> tuple[str, str, str]:
        parsed = urllib.parse.urlsplit(self.path)
        parts = parsed.path.lstrip("/").split("/", 1)
        bucket = parts[0]
        key = urllib.parse.unquote(parts[1]) if len(parts) > 1 else ""
        return bucket, key, parsed.query


class _S3Handler(_BaseHandler):
    """Verifies SigV4 by recomputing it, then serves a dict-backed S3."""

    def _verify(self, body: bytes) -> bool:
        auth = self.headers.get("Authorization", "")
        query = urllib.parse.urlsplit(self.path).query
        q = dict(urllib.parse.parse_qsl(query, keep_blank_values=True))
        if not auth and "X-Amz-Signature" in q:
            return self._verify_presigned(q)
        if not auth.startswith("AWS4-HMAC-SHA256"):
            return False
        fields = dict(
            kv.strip().split("=", 1) for kv in auth.split(" ", 1)[1].split(",")
        )
        signed_names = fields["SignedHeaders"].split(";")
        payload_hash = self.headers.get("x-amz-content-sha256", "")
        if hashlib.sha256(body).hexdigest() != payload_hash:
            return False
        headers = {name: self.headers.get(name, "") for name in signed_names}
        url = f"http://{self.headers.get('Host')}{self.path}"
        amz_date = self.headers.get("x-amz-date", "")
        import datetime

        now = datetime.datetime.strptime(amz_date, "%Y%m%dT%H%M%SZ").replace(
            tzinfo=datetime.timezone.utc
        )
        expect = signing.sign_v4(
            self.command, url, {k: v for k, v in headers.items() if k.lower() not in
                                ("host", "x-amz-date", "x-amz-content-sha256")},
            payload_hash, ACCESS, SECRET, REGION, now=now,
        )["Authorization"]
        return hmac.compare_digest(expect, auth)

    def _verify_presigned(self, q: dict[str, str]) -> bool:
        import datetime

        now = datetime.datetime.strptime(q["X-Amz-Date"], "%Y%m%dT%H%M%SZ").replace(
            tzinfo=datetime.timezone.utc
        )
        path = urllib.parse.urlsplit(self.path).path
        base = f"http://{self.headers.get('Host')}{path}"
        expect = signing.presign_v4(
            self.command, base, ACCESS, SECRET, REGION,
            int(q["X-Amz-Expires"]), now=now,
        )
        got_sig = q["X-Amz-Signature"]
        want_sig = dict(
            urllib.parse.parse_qsl(urllib.parse.urlsplit(expect).query)
        )["X-Amz-Signature"]
        return hmac.compare_digest(want_sig, got_sig)

    def _handle(self):
        body = self._body()
        if not self._verify(body):
            return self._reply(403, b"<Error><Code>SignatureDoesNotMatch</Code></Error>")
        bucket, key, query = self._split()
        q = dict(urllib.parse.parse_qsl(query, keep_blank_values=True))
        store = self.store.buckets
        if self.command == "PUT":
            if not key:
                store.setdefault(bucket, {})
                return self._reply(200)
            if bucket not in store:
                return self._reply(404, b"<Error><Code>NoSuchBucket</Code></Error>")
            src = (
                self.headers.get("x-amz-copy-source")
                or self.headers.get("x-oss-copy-source")
                or self.headers.get("x-obs-copy-source")
            )
            if src:
                sb, _, sk = src.lstrip("/").partition("/")
                data = store.get(sb, {}).get(urllib.parse.unquote(sk))
                if data is None:
                    return self._reply(404, b"<Error/>")
                store[bucket][key] = data
                return self._reply(200, b"<CopyObjectResult/>")
            # conditional create — real S3 answers 412 PreconditionFailed
            # to If-None-Match: *; OSS/OBS answer 409 FileAlreadyExists to
            # their forbid-overwrite headers
            if self.headers.get("If-None-Match") == "*" and key in store[bucket]:
                return self._reply(412, b"<Error><Code>PreconditionFailed</Code></Error>")
            if key in store[bucket] and (
                self.headers.get("x-oss-forbid-overwrite") == "true"
                or self.headers.get("x-obs-forbid-overwrite") == "true"
            ):
                return self._reply(409, b"<Error><Code>FileAlreadyExists</Code></Error>")
            store[bucket][key] = body
            etag = hashlib.md5(body).hexdigest()
            return self._reply(200, headers={"ETag": f'"{etag}"'})
        if self.command in ("GET", "HEAD"):
            if not bucket:
                xml = "<ListAllMyBucketsResult><Buckets>" + "".join(
                    f"<Bucket><Name>{b}</Name>"
                    "<CreationDate>2026-01-01T00:00:00Z</CreationDate></Bucket>"
                    for b in sorted(store)
                ) + "</Buckets></ListAllMyBucketsResult>"
                return self._reply(200, xml.encode())
            if bucket not in store:
                return self._reply(404, b"<Error><Code>NoSuchBucket</Code></Error>")
            if not key:
                if self.command == "HEAD":
                    return self._reply(200)
                prefix = q.get("prefix", "")
                limit = int(q.get("max-keys", "1000"))
                after = q.get("continuation-token", "")
                matching = sorted(k for k in store[bucket] if k.startswith(prefix))
                if after:
                    matching = [k for k in matching if k > after]
                keys, rest = matching[:limit], matching[limit:]
                tail = ""
                if rest:
                    tail = (
                        "<IsTruncated>true</IsTruncated>"
                        f"<NextContinuationToken>{keys[-1]}</NextContinuationToken>"
                    )
                else:
                    tail = "<IsTruncated>false</IsTruncated>"
                xml = "<ListBucketResult>" + "".join(
                    f"<Contents><Key>{k}</Key><Size>{len(store[bucket][k])}</Size>"
                    f'<ETag>"{hashlib.md5(store[bucket][k]).hexdigest()}"</ETag>'
                    "<LastModified>2026-01-02T03:04:05Z</LastModified>"
                    "<StorageClass>STANDARD</StorageClass></Contents>"
                    for k in keys
                ) + tail + "</ListBucketResult>"
                return self._reply(200, xml.encode())
            data = store[bucket].get(key)
            if data is None:
                return self._reply(404, b"<Error><Code>NoSuchKey</Code></Error>")
            headers = {
                "ETag": f'"{hashlib.md5(data).hexdigest()}"',
                "Last-Modified": "Fri, 02 Jan 2026 03:04:05 GMT",
                "Content-Type": "application/octet-stream",
            }
            rng = self.headers.get("Range")
            if rng and self.command == "GET":
                lo, hi = rng.split("=")[1].split("-")
                data = data[int(lo): int(hi) + 1]
                return self._reply(206, data, headers)
            # HEAD: _reply sets Content-Length from the data but skips the body
            return self._reply(200, data, headers)
        if self.command == "DELETE":
            if key:
                store.get(bucket, {}).pop(key, None)
            else:
                store.pop(bucket, None)
            return self._reply(204)
        return self._reply(405)

    do_GET = do_PUT = do_DELETE = do_HEAD = _handle


class _OSSHandler(_S3Handler):
    """Same dict store; verifies the OSS/OBS header signature instead."""

    scheme = "OSS"

    def _verify(self, body: bytes) -> bool:
        auth = self.headers.get("Authorization", "")
        if not auth.startswith(self.scheme + " "):
            return False
        bucket, key, query = self._split()
        md5 = self.headers.get("Content-MD5")
        if body and (not md5 or base64.b64encode(hashlib.md5(body).digest()).decode() != md5):
            return False
        headers = {
            k: v for k, v in self.headers.items()
            if k.lower().startswith(f"x-{self.scheme.lower()}-")
            or k.lower() in ("content-md5", "content-type")
        }
        import datetime
        import email.utils

        date = email.utils.parsedate_to_datetime(self.headers.get("Date", ""))
        expect = signing.sign_headerstyle(
            self.command, bucket, key, headers, ACCESS, SECRET,
            scheme=self.scheme, query=query,
            now=date.astimezone(datetime.timezone.utc),
        )["Authorization"]
        return hmac.compare_digest(expect, auth)


class _WebHDFSHandler(http.server.BaseHTTPRequestHandler):
    protocol_version = "HTTP/1.1"
    tree: dict[str, bytes]  # path -> content; dirs implied by prefixes

    def log_message(self, *a):
        pass

    def do_GET(self):
        parsed = urllib.parse.urlsplit(self.path)
        q = dict(urllib.parse.parse_qsl(parsed.query))
        op = q.get("op", "")
        path = urllib.parse.unquote(parsed.path[len("/webhdfs/v1"):]) or "/"
        body: bytes
        if op == "GETFILESTATUS":
            if path in self.tree:
                st = {"length": len(self.tree[path]), "type": "FILE",
                      "pathSuffix": "", "modificationTime": 1700000000000}
                body = json.dumps({"FileStatus": st}).encode()
            elif any(p.startswith(path.rstrip("/") + "/") for p in self.tree):
                body = json.dumps({"FileStatus": {"length": 0, "type": "DIRECTORY",
                                                  "pathSuffix": ""}}).encode()
            else:
                return self._err(404)
            return self._ok(body)
        if op == "OPEN":
            data = self.tree.get(path)
            if data is None:
                return self._err(404)
            off, ln = int(q.get("offset", 0)), q.get("length")
            data = data[off: off + int(ln)] if ln else data[off:]
            return self._ok(data, ct="application/octet-stream")
        if op == "LISTSTATUS":
            base = path.rstrip("/") + "/"
            children: dict[str, dict] = {}
            for p, content in sorted(self.tree.items()):
                if not p.startswith(base):
                    continue
                rest = p[len(base):]
                name, sep, _ = rest.partition("/")
                if name and name not in children:
                    children[name] = {
                        "pathSuffix": name,
                        "type": "DIRECTORY" if sep else "FILE",
                        "length": 0 if sep else len(content),
                    }
            body = json.dumps({"FileStatuses": {"FileStatus": list(children.values())}}).encode()
            return self._ok(body)
        return self._err(400)

    def _ok(self, body: bytes, ct: str = "application/json"):
        self.send_response(200)
        self.send_header("Content-Type", ct)
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def _err(self, code: int):
        self.send_response(code)
        self.send_header("Content-Length", "0")
        self.end_headers()


class _RegistryHandler(http.server.BaseHTTPRequestHandler):
    """OCI distribution: bearer challenge → /token → manifest → blob.
    Counts manifest hits (per-piece fetches must hit it once, not N times)
    and honors Range on blobs like real registries."""

    protocol_version = "HTTP/1.1"
    blob = b"layer-bytes-" * 1000
    token = "tok-abc123"
    manifest_hits = 0
    honor_range = True

    def log_message(self, *a):
        pass

    @property
    def digest(self):
        return "sha256:" + hashlib.sha256(self.blob).hexdigest()

    def do_GET(self):
        parsed = urllib.parse.urlsplit(self.path)
        host = self.headers.get("Host")
        if parsed.path == "/token":
            q = dict(urllib.parse.parse_qsl(parsed.query))
            assert q.get("service") == "registry.test", q
            assert "repository:proj/artifact:pull" in q.get("scope", "")
            return self._json(200, {"token": self.token})
        if self.headers.get("Authorization") != f"Bearer {self.token}":
            self.send_response(401)
            self.send_header(
                "WWW-Authenticate",
                f'Bearer realm="http://{host}/token",service="registry.test",'
                f'scope="repository:proj/artifact:pull"',
            )
            self.send_header("Content-Length", "0")
            self.end_headers()
            return
        if parsed.path == "/v2/proj/artifact/manifests/v1":
            type(self).manifest_hits += 1
            manifest = {
                "schemaVersion": 2,
                "layers": [
                    {"mediaType": "application/vnd.oci.image.layer.v1.tar",
                     "digest": self.digest, "size": len(self.blob)},
                ],
            }
            return self._json(200, manifest)
        if parsed.path == f"/v2/proj/artifact/blobs/{self.digest}":
            data = self.blob
            rng = self.headers.get("Range")
            code = 200
            if rng and self.honor_range:
                lo, _, hi = rng.split("=")[1].partition("-")
                data = data[int(lo): int(hi) + 1] if hi else data[int(lo):]
                code = 206
            self.send_response(code)
            self.send_header("Content-Length", str(len(data)))
            self.end_headers()
            self.wfile.write(data)
            return
        self._json(404, {"errors": [{"code": "NAME_UNKNOWN"}]})

    def _json(self, code: int, obj: dict):
        body = json.dumps(obj).encode()
        self.send_response(code)
        self.send_header("Content-Type", "application/json")
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)


def _serve(handler_cls) -> tuple[http.server.ThreadingHTTPServer, str]:
    srv = http.server.ThreadingHTTPServer(("127.0.0.1", 0), handler_cls)
    threading.Thread(target=srv.serve_forever, daemon=True).start()
    return srv, f"127.0.0.1:{srv.server_address[1]}"


@pytest.fixture()
def s3_endpoint():
    store = _Store()
    handler = type("H", (_S3Handler,), {"store": store})
    srv, addr = _serve(handler)
    yield addr
    srv.shutdown()


@pytest.fixture(params=["oss", "obs"])
def headerstyle_endpoint(request):
    store = _Store()
    handler = type("H", (_OSSHandler,), {"store": store, "scheme": request.param.upper()})
    srv, addr = _serve(handler)
    yield request.param, addr
    srv.shutdown()


# ---------------------------------------------------------------- backends


def _exercise_backend(backend):
    backend.create_bucket("models")
    assert backend.is_bucket_exist("models")
    assert not backend.is_bucket_exist("nope")

    data = b"weights\x00\x01" * 4096
    meta = backend.put_object("models", "gnn/1/model.msgpack", data)
    assert meta.etag == hashlib.md5(data).hexdigest()
    backend.put_object("models", "gnn/2/model.msgpack", b"v2")
    backend.put_object("models", "mlp/1/model.msgpack", b"m1")

    assert backend.get_object("models", "gnn/1/model.msgpack") == data
    assert backend.get_object("models", "gnn/1/model.msgpack", range_=(8, 15)) == data[8:16]

    got = backend.get_object_metadata("models", "gnn/2/model.msgpack")
    assert got.content_length == 2 and got.last_modified_at > 0

    listed = backend.get_object_metadatas("models", prefix="gnn/")
    assert [m.key for m in listed] == ["gnn/1/model.msgpack", "gnn/2/model.msgpack"]
    assert listed[0].content_length == len(data)

    assert backend.is_object_exist("models", "mlp/1/model.msgpack")
    copied = backend.copy_object("models", "mlp/1/model.msgpack", "mlp/2/model.msgpack")
    assert copied.content_length == 2
    assert backend.get_object("models", "mlp/2/model.msgpack") == b"m1"

    backend.delete_object("models", "mlp/1/model.msgpack")
    assert not backend.is_object_exist("models", "mlp/1/model.msgpack")
    with pytest.raises(dferrors.NotFound):
        backend.get_object("models", "mlp/1/model.msgpack")

    buckets = backend.get_bucket_metadatas()
    assert "models" in [b.name for b in buckets]


def test_s3_backend_roundtrip(s3_endpoint):
    backend = new_backend(
        "s3", endpoint=s3_endpoint, access_key=ACCESS, secret_key=SECRET, region=REGION
    )
    _exercise_backend(backend)


def test_s3_presigned_url(s3_endpoint):
    backend = new_backend(
        "s3", endpoint=s3_endpoint, access_key=ACCESS, secret_key=SECRET, region=REGION
    )
    backend.create_bucket("pub")
    backend.put_object("pub", "file.bin", b"presigned!")
    url = backend.get_sign_url("pub", "file.bin", "GET", 300)
    # A *plain* HTTP client (no signer) can fetch it — that is the point.
    with urllib.request.urlopen(url, timeout=10) as resp:
        assert resp.read() == b"presigned!"


def test_s3_bad_credentials_rejected(s3_endpoint):
    backend = new_backend(
        "s3", endpoint=s3_endpoint, access_key=ACCESS, secret_key="wrong", region=REGION
    )
    with pytest.raises(dferrors.PermissionDenied):
        backend.create_bucket("models")


def test_headerstyle_backend_roundtrip(headerstyle_endpoint):
    vendor, addr = headerstyle_endpoint
    backend = new_backend(
        vendor, endpoint=addr, access_key=ACCESS, secret_key=SECRET
    )
    _exercise_backend(backend)


def test_headerstyle_bad_secret_rejected(headerstyle_endpoint):
    vendor, addr = headerstyle_endpoint
    backend = new_backend(vendor, endpoint=addr, access_key=ACCESS, secret_key="nope")
    with pytest.raises(dferrors.PermissionDenied):
        backend.create_bucket("x")


def test_vendor_requires_endpoint():
    with pytest.raises(dferrors.Unavailable):
        new_backend("s3")
    with pytest.raises(dferrors.InvalidArgument):
        new_backend("gcs", endpoint="x")


# ------------------------------------------------------------ source: s3


def test_s3_source_download_and_range(s3_endpoint):
    backend = new_backend(
        "s3", endpoint=s3_endpoint, access_key=ACCESS, secret_key=SECRET, region=REGION
    )
    backend.create_bucket("data")
    payload = bytes(range(256)) * 64
    backend.put_object("data", "set/train.bin", payload)

    hdrs = {
        "x-df-endpoint": s3_endpoint,
        "x-df-access-key": ACCESS,
        "x-df-secret-key": SECRET,
        "x-df-region": REGION,
    }
    assert source.content_length("s3://data/set/train.bin", hdrs) == len(payload)
    got = b"".join(source.download("s3://data/set/train.bin", hdrs))
    assert got == payload
    part = b"".join(source.download("s3://data/set/train.bin", hdrs, offset=100, length=50))
    assert part == payload[100:150]
    tail = b"".join(source.download("s3://data/set/train.bin", hdrs, offset=len(payload) - 7))
    assert tail == payload[-7:]


def test_s3_source_list_entries(s3_endpoint):
    backend = new_backend(
        "s3", endpoint=s3_endpoint, access_key=ACCESS, secret_key=SECRET, region=REGION
    )
    backend.create_bucket("tree")
    for k in ("root/a.txt", "root/b/x.txt", "root/b/y.txt", "other/z.txt"):
        backend.put_object("tree", k, b"#")
    hdrs = {"x-df-endpoint": s3_endpoint, "x-df-access-key": ACCESS,
            "x-df-secret-key": SECRET, "x-df-region": REGION}
    entries = source.list_entries("s3://tree/root/", hdrs)
    by_name = {e.name: e for e in entries}
    assert set(by_name) == {"a.txt", "b"}
    assert not by_name["a.txt"].is_dir and by_name["b"].is_dir
    assert by_name["b"].url.endswith("/b/")


def test_s3_source_needs_endpoint():
    with pytest.raises(dferrors.Unavailable):
        source.content_length("s3://bucket/key", {})


# ---------------------------------------------------------- source: hdfs


@pytest.fixture()
def hdfs_endpoint():
    tree = {
        "/data/train.csv": b"h1,h2\n1,2\n" * 500,
        "/data/sub/part-0": b"p0",
        "/data/sub/part-1": b"p1",
    }
    handler = type("H", (_WebHDFSHandler,), {"tree": tree})
    srv, addr = _serve(handler)
    yield addr, tree
    srv.shutdown()


def test_hdfs_source(hdfs_endpoint):
    addr, tree = hdfs_endpoint
    url = f"hdfs://{addr}/data/train.csv"
    data = tree["/data/train.csv"]
    assert source.content_length(url) == len(data)
    assert b"".join(source.download(url)) == data
    assert b"".join(source.download(url, offset=3, length=5)) == data[3:8]

    entries = source.list_entries(f"hdfs://{addr}/data")
    by_name = {e.name: e for e in entries}
    assert set(by_name) == {"train.csv", "sub"}
    assert by_name["sub"].is_dir and not by_name["train.csv"].is_dir
    # recursive hop: listing the subdir works off the returned URL
    sub = source.list_entries(by_name["sub"].url)
    assert {e.name for e in sub} == {"part-0", "part-1"}

    with pytest.raises(dferrors.NotFound):
        source.content_length(f"hdfs://{addr}/missing")


# ---------------------------------------------------------- source: oras


@pytest.fixture(params=[True, False], ids=["range-honored", "range-ignored"])
def registry_endpoint(request):
    handler = type(
        "H", (_RegistryHandler,), {"manifest_hits": 0, "honor_range": request.param}
    )
    srv, addr = _serve(handler)
    yield addr, handler
    srv.shutdown()


def test_oras_source(registry_endpoint):
    from dragonfly2_tpu.client.object_sources import OrasSource

    addr, handler = registry_endpoint
    client = OrasSource()  # fresh resolution cache per test
    url = f"oras://{addr}/proj/artifact:v1"
    blob = _RegistryHandler.blob
    assert client.content_length(url) == len(blob)
    assert b"".join(client.download(url)) == blob
    # ranged per-piece reads: correct bytes whether or not the registry
    # honors Range, and the manifest is resolved once, not once per piece
    for off in range(0, 4096, 512):
        assert b"".join(client.download(url, offset=off, length=256)) == blob[off: off + 256]
    assert b"".join(client.download(url, offset=5, length=9)) == blob[5:14]
    assert handler.manifest_hits == 1
    with pytest.raises(dferrors.NotFound):
        client.content_length(f"oras://{addr}/proj/artifact:nope")
    with pytest.raises(dferrors.InvalidArgument):
        client.list_entries(url)


def test_object_sources_imports_standalone():
    """Importing object_sources before source must not crash on the
    half-initialized-module cycle (defaults register lazily)."""
    import subprocess
    import sys

    proc = subprocess.run(
        [sys.executable, "-c",
         "import dragonfly2_tpu.client.object_sources as m; "
         "import dragonfly2_tpu.client.source as s; "
         "assert isinstance(s.client_for('s3://b/k'), m.ObjectStoreSource)"],
        capture_output=True, text=True, timeout=60,
    )
    assert proc.returncode == 0, proc.stderr


def test_s3_list_follows_continuation_tokens(s3_endpoint):
    """>1 page of keys must all be returned (IsTruncated / continuation
    token pagination), or a recursive download silently loses files."""
    backend = new_backend(
        "s3", endpoint=s3_endpoint, access_key=ACCESS, secret_key=SECRET, region=REGION
    )
    backend.create_bucket("big")
    n = 2500  # three 1000-key pages
    for i in range(n):
        backend.put_object("big", f"p/{i:05d}", b"x")
    listed = backend.get_object_metadatas("big", prefix="p/")
    assert len(listed) == n
    assert [m.key for m in listed] == [f"p/{i:05d}" for i in range(n)]
    capped = backend.get_object_metadatas("big", prefix="p/", limit=1500)
    assert len(capped) == 1500


def test_s3_keys_needing_percent_encoding(s3_endpoint):
    """Keys with spaces/'+'/unicode must sign single-encoded (the SigV4
    canonical URI is the path as sent on the wire)."""
    backend = new_backend(
        "s3", endpoint=s3_endpoint, access_key=ACCESS, secret_key=SECRET, region=REGION
    )
    backend.create_bucket("enc")
    for key in ("dir with space/a b.txt", "plus+sign.bin", "uni-köln/日本.txt"):
        backend.put_object("enc", key, key.encode())
        assert backend.get_object("enc", key) == key.encode()
        assert backend.get_object_metadata("enc", key).content_length == len(key.encode())
        # the copy-source header is URL-decoded server-side, so encoded
        # keys must survive copying too
        copied = backend.copy_object("enc", key, key + ".copy")
        assert backend.get_object("enc", key + ".copy") == key.encode()
        assert copied.content_length == len(key.encode())


# ------------------------------------------------------------- signing unit


def test_sigv4_is_deterministic_and_sensitive():
    import datetime

    now = datetime.datetime(2026, 7, 30, 12, 0, 0, tzinfo=datetime.timezone.utc)
    kwargs = dict(payload_hash=signing.EMPTY_SHA256, access_key=ACCESS,
                  secret_key=SECRET, region=REGION, now=now)
    a = signing.sign_v4("GET", "http://h/x/y?b=2&a=1", {}, **kwargs)
    b = signing.sign_v4("GET", "http://h/x/y?a=1&b=2", {}, **kwargs)
    # query canonicalization: param order must not change the signature
    assert a["Authorization"] == b["Authorization"]
    c = signing.sign_v4("PUT", "http://h/x/y?a=1&b=2", {}, **kwargs)
    assert c["Authorization"] != a["Authorization"]


def test_dfget_recursive_s3_with_header_creds(tmp_path, s3_endpoint, capsys):
    """The full CLI edge: `dfget -r s3://bucket/dir/ --header x-df-*`
    walks the object tree via paginated listing and back-sources every
    file through the signed S3 client (reference dfget --header →
    urlMeta.Header reaching the source client)."""
    import asyncio

    from dragonfly2_tpu.client import cli
    from dragonfly2_tpu.cluster.scheduler import SchedulerService
    from dragonfly2_tpu.config.config import Config
    from dragonfly2_tpu.rpc.server import SchedulerRPCServer

    backend = new_backend(
        "s3", endpoint=s3_endpoint, access_key=ACCESS, secret_key=SECRET, region=REGION
    )
    backend.create_bucket("web")
    tree = {
        "site/index.html": b"<html>root</html>",
        "site/assets/app.js": b"console.log(1)" * 100,
        "site/assets/deep/style.css": b"body{}" * 50,
    }
    for k, v in tree.items():
        backend.put_object("web", k, v)

    async def run():
        cfg = Config()
        cfg.scheduler.max_hosts = 16
        cfg.scheduler.max_tasks = 16
        server = SchedulerRPCServer(SchedulerService(config=cfg), tick_interval=0.01)
        host, port = await server.start()
        out = tmp_path / "mirror"
        rc = await cli._dfget(
            cli.build_parser().parse_args(
                [
                    "dfget", "s3://web/site/", "-r",
                    "-o", str(out),
                    "--scheduler", f"{host}:{port}",
                    "--data-dir", str(tmp_path / "dfget-data"),
                    "--piece-length", str(16 * 1024),
                    "-H", f"x-df-endpoint: {s3_endpoint}",
                    "-H", f"x-df-access-key: {ACCESS}",
                    "-H", f"x-df-secret-key: {SECRET}",
                    "-H", f"x-df-region: {REGION}",
                ]
            )
        )
        await server.stop()
        return rc, out

    rc, out = asyncio.run(run())
    assert rc == 0
    assert (out / "index.html").read_bytes() == tree["site/index.html"]
    assert (out / "assets" / "app.js").read_bytes() == tree["site/assets/app.js"]
    assert (out / "assets" / "deep" / "style.css").read_bytes() == tree["site/assets/deep/style.css"]


def test_daemon_object_storage_fronts_signed_s3(tmp_path, s3_endpoint):
    """The daemon's object-storage HTTP API can be backed by a signed S3
    endpoint (pkg/objectstorage vendor dispatch behind the daemon
    listener): objects PUT through the daemon land in the S3 bucket, and
    GETs read back through the signature path."""
    from dragonfly2_tpu.client.storage import StorageManager
    from dragonfly2_tpu.objectstorage.service import (
        DfstoreClient,
        ObjectStorageService,
    )

    s3 = new_backend(
        "s3", endpoint=s3_endpoint, access_key=ACCESS, secret_key=SECRET, region=REGION
    )
    service = ObjectStorageService(
        s3, storage=StorageManager(tmp_path / "pieces"), host="127.0.0.1"
    )
    service.start()
    try:
        client = DfstoreClient(f"http://{service.host}:{service.port}")
        client.create_bucket("artifacts")
        payload = b"tarball-bytes" * 2048
        client.put_object("artifacts", "img/layer.tar", payload)
        # visible directly in the S3 store, not just through the daemon
        assert s3.get_object("artifacts", "img/layer.tar") == payload
        assert client.get_object("artifacts", "img/layer.tar") == payload
        keys = [m.key for m in s3.get_object_metadatas("artifacts")]
        assert keys == ["img/layer.tar"]
    finally:
        service.stop()
