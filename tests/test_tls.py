"""Cluster mTLS: CA issuance, CSR signing, and mutual-auth sockets.

Mirrors the reference's optional security layer (pkg/issuer DragonflyIssuer,
scheduler/scheduler.go:180-219 TLS on every gRPC server/client): the manager
holds the cluster CA and signs CSRs over its RPC; scheduler and daemons
speak mutual TLS; plaintext and wrong-CA clients are rejected.
"""

import asyncio
import hashlib
import ssl

import pytest

from dragonfly2_tpu.client.daemon import Daemon
from dragonfly2_tpu.cluster.scheduler import SchedulerService
from dragonfly2_tpu.config.config import Config
from dragonfly2_tpu.manager import rpc as mrpc
from dragonfly2_tpu.manager.models import Database
from dragonfly2_tpu.manager.service import ManagerService
from dragonfly2_tpu.rpc.server import SchedulerRPCServer
from dragonfly2_tpu.utils import certs

from test_minicluster import _CountingFileServer

# Without the cryptography package every test here dies in
# certs._require_crypto — and worse, the mTLS e2e used to die BEFORE its
# try/finally, leaking its origin listener into the whole session (the
# conftest resource-leak guard flags exactly that). Skip loudly instead.
pytestmark = pytest.mark.skipif(
    not certs._HAVE_CRYPTO,
    reason="TLS tests need the 'cryptography' package",
)


def test_ca_csr_sign_roundtrip(tmp_path):
    ca_cert, ca_key = certs.generate_ca()
    csr, key = certs.generate_csr("scheduler-1", ["127.0.0.1", "localhost"])
    leaf = certs.sign_csr(ca_cert, ca_key, csr)
    mat = certs.TLSMaterial(tmp_path / "tls").write(leaf, key, ca_cert)
    assert mat.ready
    # contexts construct and carry mutual-auth settings
    sctx = mat.server_context()
    assert sctx.verify_mode == ssl.CERT_REQUIRED
    cctx = mat.client_context()
    assert cctx.verify_mode == ssl.CERT_REQUIRED  # TLS_CLIENT default


def test_sign_rejects_bad_csr(tmp_path):
    ca_cert, ca_key = certs.generate_ca()
    with pytest.raises(Exception):
        certs.sign_csr(ca_cert, ca_key, b"-----BEGIN CERTIFICATE REQUEST-----\nnope\n")


def test_manager_issuance_rpc(tmp_path):
    """Full certify flow: service CSR -> manager IssueCertificate RPC ->
    installed chain produces working mTLS contexts."""

    async def run():
        svc = ManagerService(Database(), cert_dir=str(tmp_path / "ca"))
        server = mrpc.ManagerRPCServer(svc)
        host, port = await server.start()
        try:
            mat = await mrpc.obtain_certificate(
                host, port, "scheduler-1", tmp_path / "sched-tls"
            )
            assert mat.ready
            # the leaf verifies against the CA the manager persisted
            ca_pem = (tmp_path / "ca" / "ca.pem").read_bytes()
            assert mat.ca_path.read_bytes() == ca_pem
        finally:
            await server.stop()

    asyncio.new_event_loop().run_until_complete(run())


def test_minicluster_over_mtls(tmp_path):
    """Scheduler RPC serving mutual TLS: a daemon with an issued cert
    downloads end-to-end; a plaintext client and a wrong-CA client are
    both rejected (VERDICT r1 item 4 'done' criterion)."""
    origin = _CountingFileServer(bytes(i % 256 for i in range(120_000)))

    async def run():
        svc = ManagerService(Database(), cert_dir=str(tmp_path / "ca"))
        mserver = mrpc.ManagerRPCServer(svc)
        mhost, mport = await mserver.start()

        sched_mat = await mrpc.obtain_certificate(
            mhost, mport, "scheduler-1", tmp_path / "sched-tls"
        )
        daemon_mat = await mrpc.obtain_certificate(
            mhost, mport, "daemon-1", tmp_path / "daemon-tls"
        )

        cfg = Config()
        cfg.scheduler.max_hosts = 16
        cfg.scheduler.max_tasks = 16
        service = SchedulerService(config=cfg)
        server = SchedulerRPCServer(
            service, tick_interval=0.01,
            ssl_context=sched_mat.server_context(require_client_cert=True),
        )
        host, port = await server.start()
        try:
            d1 = Daemon(
                tmp_path / "d1", [(host, port)], hostname="tls-d1",
                ssl_context=daemon_mat.client_context(),
            )
            await d1.start()
            ts = await d1.download(origin.url(), piece_length=32 * 1024)
            with open(ts.data_path, "rb") as f:
                got = hashlib.sha256(f.read()).hexdigest()
            assert got == hashlib.sha256(origin.payload).hexdigest()
            await d1.stop()

            # plaintext client: the TLS server must refuse the stream
            with pytest.raises((ConnectionError, asyncio.IncompleteReadError, OSError)):
                reader, writer = await asyncio.open_connection(host, port)
                from dragonfly2_tpu.cluster import messages as msg
                from dragonfly2_tpu.rpc import wire

                wire.write_frame(writer, msg.StatTaskRequest(task_id="x"))
                await writer.drain()
                got = await asyncio.wait_for(reader.readexactly(4), timeout=5)
                if not got:
                    raise ConnectionError("closed")

            # wrong-CA client: handshake must fail cert verification
            rogue = certs.self_signed_material(tmp_path / "rogue", "rogue")
            with pytest.raises(ssl.SSLError):
                await asyncio.open_connection(
                    host, port, ssl=rogue.client_context()
                )
        finally:
            await server.stop()
            await mserver.stop()
            origin.stop()

    asyncio.new_event_loop().run_until_complete(run())


def test_issuance_requires_enrollment_token(tmp_path):
    """A manager configured with an enrollment token refuses CSRs that
    don't present it — CA trust must not be granted by mere network
    reachability (r2 advisor finding)."""

    async def run():
        svc = ManagerService(
            Database(), cert_dir=str(tmp_path / "ca"), enrollment_token="sekrit"
        )
        server = mrpc.ManagerRPCServer(svc)
        host, port = await server.start()
        try:
            with pytest.raises(RuntimeError, match="enrollment token"):
                await mrpc.obtain_certificate(host, port, "rogue", tmp_path / "rogue-tls")
            mat = await mrpc.obtain_certificate(
                host, port, "scheduler-1", tmp_path / "sched-tls",
                enrollment_token="sekrit",
            )
            assert mat.ready
        finally:
            await server.stop()

    asyncio.new_event_loop().run_until_complete(run())
