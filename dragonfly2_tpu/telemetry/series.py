"""Per-service metric families — the reference's metrics packages as one
declaration site.

Capability parity with scheduler/metrics/metrics.go:44-454 (per-RPC
totals + failure twins, `traffic` by type/task_type/task_tag/task_app/
host_type, `host_traffic`, download duration histogram, concurrent
schedule gauge, version), client/daemon/metrics/metrics.go (proxy +
peer/piece/file/stream task counters, seed-peer series, cache hits),
manager/metrics/metrics.go (searcher totals) and trainer/metrics/
metrics.go (training totals). Each `*_series` function is idempotent on a
registry (Registry.register returns the existing collector), so servers
and tests can call them freely.
"""

from __future__ import annotations

from dragonfly2_tpu import version as _version

# traffic type label values (scheduler/metrics/metrics.go:24-38)
TRAFFIC_P2P = "p2p"
TRAFFIC_BACK_TO_SOURCE = "back_to_source"
HOST_TRAFFIC_UPLOAD = "upload"
HOST_TRAFFIC_DOWNLOAD = "download"


class _Namespace:
    def __init__(self, **metrics):
        self.__dict__.update(metrics)


def scheduler_series(reg) -> _Namespace:
    c = reg.counter
    return _Namespace(
        announce_peer=c(
            "dragonfly_scheduler_announce_peer_total", "stream messages", ("type",)
        ),
        announce_peer_failure=c(
            "dragonfly_scheduler_announce_peer_failure_total",
            "failed stream messages",
            ("type",),
        ),
        register_peer=c(
            "dragonfly_scheduler_register_peer_total", "peer registrations",
            ("priority", "task_type", "task_tag", "task_app"),
        ),
        register_peer_failure=c(
            "dragonfly_scheduler_register_peer_failure_total",
            "failed peer registrations",
            ("priority", "task_type", "task_tag", "task_app"),
        ),
        download_peer_started=c(
            "dragonfly_scheduler_download_peer_started_total", "downloads started",
            ("priority", "task_type", "task_tag", "task_app"),
        ),
        download_peer_back_to_source_started=c(
            "dragonfly_scheduler_download_peer_back_to_source_started_total",
            "back-to-source downloads started",
            ("priority", "task_type", "task_tag", "task_app"),
        ),
        download_peer_finished=c(
            "dragonfly_scheduler_download_peer_finished_total", "downloads finished",
            ("priority", "task_type", "task_tag", "task_app"),
        ),
        download_peer_finished_failure=c(
            "dragonfly_scheduler_download_peer_finished_failure_total",
            "downloads failed",
            ("priority", "task_type", "task_tag", "task_app"),
        ),
        download_piece_finished=c(
            "dragonfly_scheduler_download_piece_finished_total", "pieces finished",
            ("traffic_type", "task_type", "task_tag", "task_app"),
        ),
        download_piece_finished_failure=c(
            "dragonfly_scheduler_download_piece_finished_failure_total",
            "pieces failed",
            ("traffic_type", "task_type", "task_tag", "task_app"),
        ),
        stat_peer=c("dragonfly_scheduler_stat_peer_total", "StatPeer calls"),
        leave_peer=c("dragonfly_scheduler_leave_peer_total", "LeavePeer calls"),
        stat_task=c("dragonfly_scheduler_stat_task_total", "StatTask calls"),
        announce_host=c("dragonfly_scheduler_announce_host_total", "AnnounceHost calls"),
        leave_host=c("dragonfly_scheduler_leave_host_total", "LeaveHost calls"),
        sync_probes=c("dragonfly_scheduler_sync_probes_total", "SyncProbes calls"),
        traffic=c(
            "dragonfly_scheduler_traffic", "piece bytes moved",
            ("type", "task_type", "task_tag", "task_app", "host_type"),
        ),
        host_traffic=c(
            "dragonfly_scheduler_host_traffic", "piece bytes by host",
            ("type", "host_type", "host_id"),
        ),
        download_peer_duration=reg.histogram(
            "dragonfly_scheduler_download_peer_duration_milliseconds",
            "download duration by size scope",
            ("size_scope",),
            buckets=(100.0, 200.0, 500.0, 1000.0, 1500.0, 2000.0, 3000.0, 5000.0,
                     10000.0, 20000.0, 60000.0, 120000.0, 300000.0),
        ),
        concurrent_schedule=reg.gauge(
            "dragonfly_scheduler_concurrent_schedule_total", "peers pending schedule"
        ),
        schedule_tick=reg.histogram(
            "dragonfly_scheduler_tick_seconds", "batched schedule tick latency"
        ),
        schedule_batch=reg.histogram(
            "dragonfly_scheduler_tick_batch_size", "peers per tick",
            buckets=(1, 8, 64, 512, 4096),
        ),
        # host-vs-device attribution of the tick (the breakdown the loop
        # bench publishes — VERDICT r3 weak #5 — live for operators too)
        schedule_phase=reg.histogram(
            "dragonfly_scheduler_tick_phase_seconds",
            "per-phase tick wall time", ("phase",),
            buckets=(.0005, .002, .01, .05, .2, 1, 5),
        ),
        # trust-boundary integrity: corrupt-parent quarantine
        # (cluster/quarantine.py QuarantineBoard)
        quarantine_total=c(
            "dragonfly_scheduler_quarantine_total",
            "hosts quarantined after integrity failures", ("reason",),
        ),
        quarantine_released=c(
            "dragonfly_scheduler_quarantine_released_total",
            "quarantined hosts released after their penalty decayed",
        ),
        quarantine_active=reg.gauge(
            "dragonfly_scheduler_quarantine_active",
            "hosts currently excluded from candidate scheduling",
        ),
        quarantine_skipped=c(
            "dragonfly_scheduler_quarantine_skipped_candidates_total",
            "candidate slots skipped because their host is quarantined",
        ),
        piece_corruption=c(
            "dragonfly_scheduler_piece_corruption_total",
            "piece failures attributed to digest-verified corruption",
        ),
    )


def decision_series(reg) -> _Namespace:
    """Decision provenance ledger families (telemetry/decisions.py):
    per-arm applied-selection counts, joined outcomes, counterfactual
    shadow-scoring divergence (top-1 disagreement, rank correlation),
    measured per-arm regret on disagreement decisions, ledger occupancy,
    and the decision→outcome join latency."""
    c = reg.counter
    return _Namespace(
        decisions=c(
            "dragonfly_scheduler_decision_total",
            "applied parent selections recorded in the decision ledger",
            ("arm",),
        ),
        outcomes=c(
            "dragonfly_scheduler_decision_outcome_total",
            "terminal peer events joined to a recorded decision",
            ("outcome",),
        ),
        shadow_scored=c(
            "dragonfly_scheduler_decision_shadow_scored_total",
            "decisions re-scored by the inactive (shadow) arm",
        ),
        top1_disagreement=reg.gauge(
            "dragonfly_scheduler_decision_top1_disagreement",
            "last tick's fraction of decisions where the shadow arm's "
            "top-1 pick differed from the active arm's",
        ),
        rank_corr=reg.gauge(
            "dragonfly_scheduler_decision_rank_correlation",
            "last tick's mean rank correlation between the active arm's "
            "ranked selection and the shadow arm's ranking of the same "
            "candidate set",
        ),
        occupancy=reg.gauge(
            "dragonfly_scheduler_decision_ledger_occupancy",
            "decision-ledger ring slots currently holding a decision",
        ),
        regret=reg.gauge(
            "dragonfly_scheduler_decision_regret_ms",
            "measured regret of the active arm on disagreement decisions "
            "(mean joined-outcome TTC delta, active minus shadow pick's "
            "host; positive = the shadow pick's host did better)",
            ("arm",),
        ),
        join_latency=reg.histogram(
            "dragonfly_scheduler_decision_join_latency_seconds",
            "wall time between a recorded decision and its joined "
            "terminal outcome",
            buckets=(.01, .05, .2, 1.0, 5.0, 30.0, 120.0, 600.0),
        ),
    )


def serving_series(reg) -> _Namespace:
    """Guarded model activation (registry/serving.py): every new params
    version is gated — sha256 manifest at load, finite-leaves check, and
    a canary scoring pass — before it can become the serving snapshot."""
    c = reg.counter
    return _Namespace(
        activation_rejected=c(
            "dragonfly_serving_activation_rejected_total",
            "params versions rejected by the activation gate (serving "
            "stays on the last-good snapshot)", ("reason",),
        ),
        activation_accepted=c(
            "dragonfly_serving_activation_accepted_total",
            "params versions that passed the activation gate",
        ),
    )


def megascale_series(reg) -> _Namespace:
    """Megascale scenario lab (dragonfly2_tpu/megascale): the event-batch
    engine's per-step phase breakdown (fault application, arrivals, the
    scheduler tick, the vectorised event batch, retirement) plus event
    throughput — the lab's analogue of the scheduler tick phases, read by
    bench_megascale.py through the same PhaseRecorder ring operators
    scrape."""
    return _Namespace(
        step_phase=reg.histogram(
            "dragonfly_megascale_step_phase_seconds",
            "per-phase engine step wall time", ("phase",),
            buckets=(.001, .005, .02, .1, .5, 2, 10, 60),
        ),
        piece_events=reg.counter(
            "dragonfly_megascale_piece_events_total",
            "piece-transfer events simulated by the event-batch engine",
        ),
    )


def fleet_series(reg) -> _Namespace:
    """Sharded control plane (megascale/fleet.py): K task-sharded
    scheduler replicas behind one consistent hashring. Handoffs count
    the cross-scheduler peer moves a ring rebalance forces (labelled by
    why the owner moved), per-shard piece/restart counters attribute
    load and churn to individual replicas, and the ring-membership gauge
    is the live shard census a fleet dashboard alerts on."""
    return _Namespace(
        handoffs=reg.counter(
            "dragonfly_fleet_peer_handoffs_total",
            "in-flight peers handed off to a new ring-owner scheduler "
            "replica, by cause of the ownership move",
            ("reason",),
        ),
        shard_pieces=reg.counter(
            "dragonfly_fleet_shard_pieces_total",
            "piece-finished reports routed to each scheduler replica",
            ("shard",),
        ),
        shard_restarts=reg.counter(
            "dragonfly_fleet_shard_restarts_total",
            "times each scheduler replica rejoined the ring after a "
            "crash or rolling-upgrade restart",
            ("shard",),
        ),
        shards_in_ring=reg.gauge(
            "dragonfly_fleet_shards_in_ring",
            "scheduler replicas currently serving ring ranges",
        ),
    )


def daemon_series(reg) -> _Namespace:
    c = reg.counter
    return _Namespace(
        proxy_request=c(
            "dragonfly_dfdaemon_proxy_request_total", "proxy requests", ("method",)
        ),
        proxy_request_via=c(
            "dragonfly_dfdaemon_proxy_request_via_dragonfly_total",
            "proxy requests routed through P2P",
        ),
        proxy_request_not_via=c(
            "dragonfly_dfdaemon_proxy_request_not_via_dragonfly_total",
            "proxy requests passed straight through",
        ),
        peer_task=c("dragonfly_dfdaemon_peer_task_total", "peer tasks started"),
        peer_task_failed=c(
            "dragonfly_dfdaemon_peer_task_failed_total", "peer tasks failed", ("type",)
        ),
        piece_task=c("dragonfly_dfdaemon_piece_task_total", "piece downloads"),
        piece_task_failed=c(
            "dragonfly_dfdaemon_piece_task_failed_total", "piece downloads failed"
        ),
        file_task=c("dragonfly_dfdaemon_file_task_total", "file tasks"),
        stream_task=c("dragonfly_dfdaemon_stream_task_total", "stream tasks"),
        seed_peer_download=c(
            "dragonfly_dfdaemon_seed_peer_download_total", "seed downloads"
        ),
        seed_peer_download_failure=c(
            "dragonfly_dfdaemon_seed_peer_download_failure_total",
            "seed downloads failed",
        ),
        seed_peer_download_traffic=c(
            "dragonfly_dfdaemon_seed_peer_download_traffic", "seed bytes", ("type",)
        ),
        peer_task_cache_hit=c(
            "dragonfly_dfdaemon_peer_task_cache_hit_total", "local reuse hits"
        ),
        scheduler_failover=c(
            "dragonfly_dfdaemon_scheduler_failover_total",
            "downloads recovered by failing over to another scheduler "
            "after the announce stream died",
        ),
        seed_task_reannounce=c(
            "dragonfly_dfdaemon_seed_task_reannounce_total",
            "completed tasks re-announced to a scheduler that triggered a "
            "seed download this daemon already holds",
        ),
    )


def manager_series(reg) -> _Namespace:
    c = reg.counter
    return _Namespace(
        search_scheduler_cluster=c(
            "dragonfly_manager_search_scheduler_cluster_total",
            "scheduler-cluster searches",
        ),
        search_scheduler_cluster_failure=c(
            "dragonfly_manager_search_scheduler_cluster_failure_total",
            "failed scheduler-cluster searches",
        ),
        request=c(
            "dragonfly_manager_request_total", "REST requests", ("method", "group")
        ),
        request_failure=c(
            "dragonfly_manager_request_failure_total",
            "REST requests answered >= 400",
            ("method", "group"),
        ),
    )


def trainer_series(reg) -> _Namespace:
    c = reg.counter
    return _Namespace(
        training=c("dragonfly_trainer_training_total", "training runs"),
        training_failure=c(
            "dragonfly_trainer_training_failure_total", "failed training runs"
        ),
        train_chunks=c(
            "dragonfly_trainer_train_chunks_total", "dataset chunks", ("dataset",)
        ),
        train_runs=c("dragonfly_trainer_train_total", "train runs", ("state",)),
    )


def resilience_series(reg, service: str) -> _Namespace:
    """Failure-domain resilience families (rpc/resilience.py): per-target
    circuit-breaker state/transition/fast-fail series for every dial site,
    and the deadline-budget outcome counters — client calls aborted because
    the propagated budget ran out, and server-side work shed on arrival
    because its deadline had already expired. `service` picks the metric
    namespace, so the daemon's pool, the manager's job edge, and the
    scheduler's trainer uploads each report under their own name."""
    return _Namespace(
        breaker_state=reg.gauge(
            f"dragonfly_{service}_rpc_breaker_state",
            "per-target circuit breaker state (0=closed, 1=half_open, 2=open)",
            ("target",),
        ),
        breaker_transitions=reg.counter(
            f"dragonfly_{service}_rpc_breaker_transitions_total",
            "circuit breaker state transitions", ("target", "to"),
        ),
        breaker_fast_fail=reg.counter(
            f"dragonfly_{service}_rpc_breaker_fast_fail_total",
            "calls short-circuited by an open breaker instead of dialing",
            ("target",),
        ),
        deadline_exceeded=reg.counter(
            f"dragonfly_{service}_rpc_deadline_exceeded_total",
            "client calls aborted because the propagated deadline budget "
            "was exhausted",
        ),
        deadline_shed=reg.counter(
            f"dragonfly_{service}_rpc_deadline_shed_total",
            "requests shed on arrival because their propagated deadline "
            "had already expired", ("type",),
        ),
    )


def jit_series(reg, service: str) -> _Namespace:
    """JAX entry-point instrumentation families (telemetry/flight.py
    instrument_jit): per wrapped function, call/retrace totals, the
    compile-cache size, and the host-dispatch vs device-completion time
    split (the call returns at dispatch; block_until_ready bounds the
    device side). `service` picks the metric namespace so the scheduler's
    evaluator and the trainer's epoch step stay in their own families."""
    return _Namespace(
        calls=reg.counter(
            f"dragonfly_{service}_jit_calls_total",
            "calls into wrapped jitted entry points", ("fn",),
        ),
        retraces=reg.counter(
            f"dragonfly_{service}_jit_retraces_total",
            "compiles/retraces: calls whose signature (shapes/dtypes/statics) "
            "was not seen before", ("fn",),
        ),
        cache_entries=reg.gauge(
            f"dragonfly_{service}_jit_cache_entries",
            "live compile-cache entries per wrapped jitted function", ("fn",),
        ),
        dispatch=reg.histogram(
            f"dragonfly_{service}_jit_dispatch_seconds",
            "host time until the jitted call returned (device may still run)",
            ("fn",),
            buckets=(.0001, .0005, .002, .01, .05, .2, 1.0, 5.0, 30.0),
        ),
        device=reg.histogram(
            f"dragonfly_{service}_jit_device_seconds",
            "block_until_ready wait after dispatch (device-side completion)",
            ("fn",),
            buckets=(.0001, .0005, .002, .01, .05, .2, 1.0, 5.0, 30.0),
        ),
    )


def costcard_series(reg) -> _Namespace:
    """XLA cost-card ledger families (telemetry/costcard.py): per
    (entry, signature) compiler-measured cost gauges captured at first
    compile of every registered serving jit and the trainer epoch step —
    the measured basis bench MFU/roofline verdicts are computed against
    (hand-rolled FLOP estimates are demoted to cross-checks)."""
    labels = ("entry", "signature")
    return _Namespace(
        flops=reg.gauge(
            "dragonfly_costcard_flops",
            "XLA cost_analysis FLOPs of one compiled program signature",
            labels,
        ),
        bytes_accessed=reg.gauge(
            "dragonfly_costcard_bytes_accessed",
            "XLA cost_analysis modeled memory traffic (bytes) of one "
            "compiled program signature",
            labels,
        ),
        output_bytes=reg.gauge(
            "dragonfly_costcard_output_bytes",
            "XLA memory_analysis output buffer bytes of one compiled "
            "program signature",
            labels,
        ),
        temp_bytes=reg.gauge(
            "dragonfly_costcard_temp_bytes",
            "XLA memory_analysis peak temporary (scratch HBM) bytes of "
            "one compiled program signature",
            labels,
        ),
        captures=reg.counter(
            "dragonfly_costcard_captures_total",
            "cost cards captured (one per new (entry, signature) pair)",
        ),
    )


def timeline_series(reg) -> _Namespace:
    """Soak-timeline families (telemetry/timeline.py): the latest sample
    of every per-interval series a TimelineRecorder tracks (pieces per
    interval, origin fraction, quarantine population, breaker-open
    count, re-announce backlog, per-region TTC quantiles), labeled by
    recorder source — the live-scrape mirror of the deterministic
    ``timeline`` array in BENCH_mega artifacts."""
    return _Namespace(
        value=reg.gauge(
            "dragonfly_timeline_value",
            "latest per-simulated-interval sample of a timeline series",
            ("source", "metric"),
        ),
        samples=reg.counter(
            "dragonfly_timeline_samples_total",
            "timeline samples recorded", ("source",),
        ),
    )


def slo_series(reg) -> _Namespace:
    """Streaming SLO engine families (telemetry/slo.py): per-objective
    error-budget remaining, multi-window burn rates, alert state and
    fire transitions, SLI event accounting, and the engine's three-state
    health verdict — the live-scrape mirror of the `/debug/health`
    verdict plane and the deterministic alert timelines in megascale
    artifacts."""
    return _Namespace(
        budget_remaining=reg.gauge(
            "dragonfly_slo_budget_remaining",
            "fraction of the SLO's error budget remaining over its "
            "accounting window (1.0 = untouched, below 0 = overspent)",
            ("source", "slo"),
        ),
        burn_rate=reg.gauge(
            "dragonfly_slo_burn_rate",
            "error-budget burn rate over one alert-rule window "
            "(1.0 = consuming exactly the budget)",
            ("source", "slo", "rule", "window"),
        ),
        alert_state=reg.gauge(
            "dragonfly_slo_alert_state",
            "multi-window burn-rate alert state (1 = firing: both the "
            "rule's windows burn at or above its factor)",
            ("source", "slo", "rule", "severity"),
        ),
        alerts_fired=reg.counter(
            "dragonfly_slo_alerts_fired_total",
            "burn-rate alert fire transitions",
            ("source", "slo", "rule", "severity"),
        ),
        verdict_state=reg.gauge(
            "dragonfly_slo_verdict_state",
            "health verdict of one SLO engine "
            "(0=ok, 1=degraded, 2=critical)",
            ("source",),
        ),
        sli_events=reg.counter(
            "dragonfly_slo_sli_events_total",
            "good/bad SLI events accounted by the streaming SLO engine",
            ("source", "sli", "outcome"),
        ),
    )


def tail_series(reg) -> _Namespace:
    """Tail-attribution families (telemetry/tailtrace.py): per-region
    completion/dominant-phase counters bumped on every observed
    download, plus the TTC-quantile, phase-share and exemplar-retention
    gauges refreshed at dump/report time — the live-scrape mirror of the
    deterministic ``tail`` block in megascale artifacts and the
    ``tail`` section of ``/debug/flight``."""
    return _Namespace(
        completions=reg.counter(
            "dragonfly_tail_completions_total",
            "downloads whose TTC was decomposed by the tail plane",
            ("source", "region"),
        ),
        dominant=reg.counter(
            "dragonfly_tail_dominant_total",
            "downloads whose attributed time was dominated by this "
            "lifecycle phase",
            ("source", "region", "phase"),
        ),
        ttc_ms=reg.gauge(
            "dragonfly_tail_ttc_ms",
            "time-to-complete quantile (ms) from the tail plane's "
            "streaming sketch — includes scheduler-wait time, unlike the "
            "transfer-only region percentiles",
            ("source", "region", "quantile"),
        ),
        phase_share=reg.gauge(
            "dragonfly_tail_phase_share",
            "fraction of all attributed download time spent in this "
            "lifecycle phase (shares sum to 1 per region)",
            ("source", "region", "phase"),
        ),
        exemplars_kept=reg.gauge(
            "dragonfly_tail_exemplars_kept",
            "exemplar downloads currently retained by the deterministic "
            "sampler (slowest-K always kept; uniform ring bounded)",
            ("source", "kind"),
        ),
    )


def proc_series(reg) -> _Namespace:
    """Real-process planet families (procworld/supervisor.py): the
    supervision plane over actual OS processes — restarts (rolling
    upgrades + crash recovery), SIGTERM->SIGKILL stop escalations,
    liveness-probe failures, injected process-level chaos ops, the live
    process census, and the sim-vs-real divergence gauges the harness
    publishes after comparing a run against the simulated oracle."""
    return _Namespace(
        processes=reg.gauge(
            "dragonfly_proc_processes",
            "supervised service processes currently running, by role",
            ("role",),
        ),
        restarts=reg.counter(
            "dragonfly_proc_restarts_total",
            "supervised process restarts (rolling-upgrade waves and "
            "post-SIGKILL crash recovery), by role",
            ("role",),
        ),
        stop_escalations=reg.counter(
            "dragonfly_proc_stop_escalations_total",
            "graceful stops that blew the grace window and escalated "
            "to a harder signal",
            ("signal",),
        ),
        liveness_failures=reg.counter(
            "dragonfly_proc_liveness_failures_total",
            "liveness probes that failed against a process the "
            "supervisor believed alive, by role",
            ("role",),
        ),
        chaos_ops=reg.counter(
            "dragonfly_proc_chaos_ops_total",
            "process-level chaos operations injected by the harness "
            "(sigkill / sigstop / sigcont)",
            ("op",),
        ),
        sim_real_divergence=reg.gauge(
            "dragonfly_proc_sim_real_divergence",
            "sim-vs-real divergence value per compared metric "
            "(ratio or delta; each metric's tolerance band travels in "
            "the BENCH_proc artifact, not here)",
            ("metric",),
        ),
    )


def register_version(reg, service: str) -> None:
    _version.register_version_gauge(reg, service)
