"""Record schema flatten/unflatten + rotating CSV storage (reference:
scheduler/storage/storage_test.go, trainer/storage/storage_test.go)."""

import pytest

from dragonfly2_tpu.records import (
    DownloadRecord,
    NetworkTopologyRecord,
    ParentRecord,
    PieceRecord,
    TraceStorage,
)
from dragonfly2_tpu.records.schema import flatten, header, unflatten
from dragonfly2_tpu.records.storage import HostTraceStorage
from dragonfly2_tpu.records import synth


def _sample_records(n=8, hosts=16, seed=3):
    cluster = synth.make_cluster(hosts, seed=seed)
    return cluster, synth.gen_download_records(cluster, n), synth.gen_network_topology_records(cluster, n)


def test_flatten_roundtrip_download():
    _, downloads, _ = _sample_records()
    rec = downloads[0]
    flat = flatten(rec)
    assert set(flat.keys()) == set(header(DownloadRecord))
    back = unflatten(DownloadRecord, {k: str(v) for k, v in flat.items()})
    assert back == rec


def test_flatten_roundtrip_topology():
    _, _, topos = _sample_records()
    rec = topos[0]
    flat = flatten(rec)
    back = unflatten(NetworkTopologyRecord, {k: str(v) for k, v in flat.items()})
    assert back == rec


def test_flatten_fixed_width_and_masks():
    rec = DownloadRecord(parents=[ParentRecord(pieces=[PieceRecord(cost=5)])])
    flat = flatten(rec)
    assert flat["parents.count"] == 1
    assert flat["parents.0.pieces.count"] == 1
    assert flat["parents.0.pieces.0.cost"] == 5
    # padded slots exist and are zero
    assert flat["parents.19.pieces.9.cost"] == 0


def test_flatten_rejects_overflow():
    rec = DownloadRecord(parents=[ParentRecord()] * 21)
    with pytest.raises(ValueError):
        flatten(rec)


def test_storage_roundtrip(tmp_path):
    _, downloads, topos = _sample_records()
    store = TraceStorage(tmp_path)
    for r in downloads:
        store.create_download(r)
    for r in topos:
        store.create_network_topology(r)
    assert store.list_downloads() == downloads
    assert store.list_network_topologies() == topos


def test_storage_rotation_and_backups(tmp_path):
    store = TraceStorage(tmp_path, max_size_mb=1, max_backups=3)
    store.downloads.max_size_bytes = 40_000  # shrink for test speed
    _, downloads, _ = _sample_records(n=40)
    for r in downloads:
        store.create_download(r)
    backups = store.downloads.backup_paths()
    assert backups, "rotation should have produced backups"
    assert len(backups) <= 2  # max_backups(3) - active file
    # every record in unrotated-away files parses
    assert all(isinstance(r, DownloadRecord) for r in store.downloads.iter_records())


def test_storage_clear(tmp_path):
    _, downloads, _ = _sample_records(n=2)
    store = TraceStorage(tmp_path)
    for r in downloads:
        store.create_download(r)
    store.clear()
    assert store.list_downloads() == []


def test_host_trace_storage_concatenated_uploads(tmp_path):
    """Trainer-side store must tolerate repeated headers from chunked
    concatenated uploads (announcer.go:172-235 streams whole files)."""
    _, downloads, _ = _sample_records(n=6)
    sched_store = TraceStorage(tmp_path / "sched")
    for r in downloads:
        sched_store.create_download(r)
    blob = sched_store.open_download()

    trainer_store = HostTraceStorage(tmp_path / "trainer")
    trainer_store.append_download_bytes("hostA", blob)
    trainer_store.append_download_bytes("hostA", blob)  # second upload, repeated header
    got = trainer_store.list_downloads()
    assert len(got) == 2 * len(downloads)
    assert got[: len(downloads)] == downloads

    trainer_store.clear_downloads()
    assert trainer_store.list_downloads() == []


def test_host_trace_storage_clear_host_scoped(tmp_path):
    """Abort of one host's stream must not destroy other hosts' datasets."""
    _, downloads, _ = _sample_records(n=3)
    sched_store = TraceStorage(tmp_path / "s")
    for r in downloads:
        sched_store.create_download(r)
    blob = sched_store.open_download()
    store = HostTraceStorage(tmp_path / "t")
    store.append_download_bytes("hostA", blob)
    store.append_download_bytes("hostB", blob)
    store.clear_host("hostA")
    assert len(store.list_downloads()) == len(downloads)  # hostB intact


def test_iter_records_skips_foreign_rows(tmp_path):
    """A foreign file with the right column count but a renamed column must
    not abort listing — healthy files keep loading (graceful degradation)."""
    from dragonfly2_tpu.records import synth
    from dragonfly2_tpu.records.storage import TraceStorage

    cluster = synth.make_cluster(8, seed=0)
    recs = synth.gen_download_records(cluster, 3, num_tasks=1, max_parents=2)
    store = TraceStorage(tmp_path)
    for r in recs:
        store.create_download(r)

    # inject a backup file whose header renames a column (schema drift)
    good_header = store.downloads.header
    bad_header = ["cost_ns" if h == "cost" else h for h in good_header]
    (tmp_path / "download-1.csv").write_text(",".join(bad_header) + "\n")

    assert store.list_downloads() == recs


def test_to_line_matches_csv_writer_bytes():
    """The compiled direct-to-text codec (schema.to_line) must stay
    byte-identical to csv.writer over to_row — storage.create writes
    through it, so any divergence silently corrupts traces on disk."""
    import io
    import csv

    from dragonfly2_tpu.records import schema
    from dragonfly2_tpu.records.schema import (
        DownloadRecord,
        ErrorRecord,
        HostRecord,
        ParentRecord,
        PieceRecord,
        to_row,
    )

    def via_csv(rec):
        out = io.StringIO()
        csv.writer(out, lineterminator="\n").writerow(to_row(rec))
        return out.getvalue()

    _, downloads, topologies = _sample_records(n=12)
    for rec in downloads + topologies:
        assert schema.to_line(rec) == via_csv(rec)

    # adversarial quoting + shared (memoized) HostRecord sub-records
    shared = HostRecord(id="h-1", hostname='na"me,with\nnasties', ip="10.0.0.1")
    tricky = DownloadRecord(
        id="d,1",
        tag='t"ag',
        error=ErrorRecord(code="E", message='boom "x", y\nz'),
        host=shared,
        parents=[
            ParentRecord(id="p1", host=shared,
                         pieces=[PieceRecord(length=64, cost=7)]),
            ParentRecord(id="p2", host=shared),
        ],
    )
    # twice: second pass serializes through the warm segment memo
    assert schema.to_line(tricky) == via_csv(tricky)
    assert schema.to_line(tricky) == via_csv(tricky)
