"""Lock-order harness unit tests (tools/dflint/lockorder.py).

The harness itself must be trustworthy before the concurrency tests can
lean on it: a red two-lock inversion must produce a cycle, reentrant
RLock acquisition must NOT, and the guarded-attribute subclass must
catch exactly the unlocked writes. The live activations ride in
tests/test_concurrency.py (scheduler storm) and
tests/test_serving_pipeline.py (refresh/serve race)."""

import threading

from tools.dflint.lockorder import (
    LockOrderGraph,
    TrackedLock,
    assert_clean,
    guard_attributes,
    instrument_locks,
)


class _TwoLocks:
    def __init__(self):
        self.a = threading.Lock()
        self.b = threading.Lock()


def test_opposite_order_acquisition_is_a_cycle():
    obj = _TwoLocks()
    graph = instrument_locks(obj, {"a": "lock.a", "b": "lock.b"})

    def ab():
        with obj.a:
            with obj.b:
                pass

    def ba():
        with obj.b:
            with obj.a:
                pass

    # run sequentially on two threads: the ORDER graph records the
    # inversion without risking an actual deadlock in the test
    for fn in (ab, ba):
        t = threading.Thread(target=fn)
        t.start()
        t.join()
    cycles = graph.cycles()
    assert cycles, "A->B->A inversion must be detected as a cycle"
    assert sorted(cycles[0]) == ["lock.a", "lock.b"]
    try:
        assert_clean(graph)
    except AssertionError as e:
        assert "deadlock potential" in str(e)
    else:  # pragma: no cover - the assert above must fire
        raise AssertionError("assert_clean passed on a cyclic graph")


def test_consistent_order_and_reentrant_rlock_are_clean():
    class Obj:
        def __init__(self):
            self.mu = threading.RLock()
            self.inner = threading.Lock()

    obj = Obj()
    graph = instrument_locks(obj, {"mu": "mu", "inner": "inner"})

    def work():
        with obj.mu:
            with obj.mu:  # reentrant: no self-edge
                with obj.inner:
                    pass

    threads = [threading.Thread(target=work) for _ in range(4)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert graph.cycles() == []
    assert ("mu", "mu") not in graph.edges
    assert ("mu", "inner") in graph.edges
    assert_clean(graph)


def test_guarded_attribute_write_without_lock_is_a_violation():
    class Board:
        def __init__(self):
            self._mu = threading.Lock()
            self.score = 0

        def locked_bump(self):
            with self._mu:
                self.score += 1

        def bare_bump(self):
            self.score += 1

    board = Board()
    graph = instrument_locks(board, {"_mu": "board.mu"})
    guard_attributes(board, {"score": "_mu"}, graph)

    board.locked_bump()
    assert graph.violations == []
    board.bare_bump()
    assert len(graph.violations) == 1
    assert "guarded attribute 'score'" in graph.violations[0]
    # the wrapped instance still behaves like the original class
    assert isinstance(board, Board) and board.score == 2


def test_tracked_lock_supports_plain_acquire_release_and_probe():
    graph = LockOrderGraph()
    lock = TrackedLock(threading.Lock(), "x", graph)
    assert not lock.held_by_current_thread()
    assert lock.acquire()
    assert lock.held_by_current_thread() and lock.locked()
    lock.release()
    assert not lock.held_by_current_thread()
    # releasing a lock the thread does not hold is itself a violation
    graph.note_release("x")
    assert any("does not hold" in v for v in graph.violations)
