from dragonfly2_tpu.ops import evaluator, segment, topk, ewma

__all__ = ["evaluator", "segment", "topk", "ewma"]
