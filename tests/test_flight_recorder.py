"""Flight recorder: in-product phase timing, jit compile/retrace counters,
RPC trace propagation, and the operator-facing dump surfaces (ISSUE 1)."""

import json
import re
import time
import urllib.request

import numpy as np
import pytest

from dragonfly2_tpu.cluster import messages as msg
from dragonfly2_tpu.cluster.scheduler import SchedulerService
from dragonfly2_tpu.telemetry import metrics as m
from dragonfly2_tpu.telemetry.flight import PhaseRecorder, instrument_jit
from dragonfly2_tpu.telemetry.series import (
    costcard_series,
    daemon_series,
    decision_series,
    fleet_series,
    jit_series,
    manager_series,
    megascale_series,
    proc_series,
    register_version,
    resilience_series,
    scheduler_series,
    serving_series,
    slo_series,
    tail_series,
    timeline_series,
    trainer_series,
)
from dragonfly2_tpu.telemetry.tracing import Tracer

# The DEFAULT loop is the fused tick (scheduler.fused_tick): feature
# gather, scoring, and selection live inside the single donated device
# program, so the host-visible phases are the fused split — candidate
# sampling, the legality prefilters, staging pack, the async device
# dispatch, the blocking D2H read, and the decode+apply+response emit.
# Multi-chunk ticks additionally record an `overlap` phase (not listed:
# single-chunk ticks legitimately omit it). The legacy packed pipeline's
# phase names (feature_gather/dispatch/apply_selection) are pinned where
# that path is explicitly selected (test_serving_pipeline's
# fused_tick=False overlap test).
TICK_PHASES = (
    "pre_schedule", "candidate_fill", "legality_recheck", "pack",
    "fused_dispatch", "d2h_wait", "emit",
)


def _host(i, seed=False):
    return msg.HostInfo(
        host_id=f"fl-h{i}", hostname=f"fl-n{i}", ip=f"10.9.0.{i}",
        host_type="super" if seed else "normal", idc="idc-a",
        location="na|zone|rack",
    )


def _register(svc, peer_id, h, task_id="fl-task"):
    return svc.register_peer(
        msg.RegisterPeerRequest(
            peer_id=peer_id, task_id=task_id, host=h,
            url="https://e.com/blob", content_length=4 * (4 << 20),
            total_piece_count=4,
        )
    )


def _seeded_service(registry):
    svc = SchedulerService(metrics_registry=registry)
    _register(svc, "fl-seed", _host(0, seed=True))
    svc.peer_finished(msg.DownloadPeerFinishedRequest(peer_id="fl-seed", piece_count=4))
    svc.tick()  # pre_schedule-only tick: no device work, no committed phases
    return svc


def test_tick_phase_histograms_populated_by_normal_loop():
    """Acceptance: after N working ticks each phase histogram reports N
    observations and the flight-recorder dump returns the last-N
    per-phase breakdown — no bench involved, just the service loop."""
    reg = m.Registry()
    svc = _seeded_service(reg)
    n = 6
    for i in range(n):
        _register(svc, f"fl-child-{i}", _host(i + 1))
        svc.tick()
    assert svc.recorder.ticks == n
    text = reg.expose()
    for phase in TICK_PHASES:
        line = (
            f'dragonfly_scheduler_tick_phase_seconds_count{{phase="{phase}"}} {n}'
        )
        assert line in text, f"missing {line}"
    dump = svc.flight_dump(last_n=4)
    assert len(dump["ticks"]["last"]) == 4
    for tick in dump["ticks"]["last"]:
        assert set(TICK_PHASES) <= set(tick)
    assert set(TICK_PHASES) <= set(dump["ticks"]["p50_ms"])
    # the serving entry point is instrumented: its compile counter moved
    ev_stats = dump["jit"]["scheduler.tick.fused_tick_chunk"]
    assert ev_stats["retraces"] >= 1 and ev_stats["calls"] >= n


def test_phase_recorder_overhead_within_one_percent_of_tick():
    """Acceptance micro-check: one full recorder cycle (begin + 6 marks +
    commit, histogram attached) costs <= 1% of the measured tick p50."""
    reg = m.Registry()
    svc = _seeded_service(reg)
    for i in range(8):
        _register(svc, f"fl-ov-{i}", _host(i + 1))
        t0 = time.perf_counter()
        svc.tick()
    tick_p50 = float(np.median([sum(p.values()) for p in svc.recorder.ring]))

    rec = PhaseRecorder(histogram=scheduler_series(m.Registry()).schedule_phase)

    def batch(n):
        t0 = time.perf_counter()
        for _ in range(n):
            rec.begin()
            for phase in TICK_PHASES:
                rec.mark(phase)
            rec.commit()
        return (time.perf_counter() - t0) / n * 1e3

    batch(200)  # warm dict/label caches
    # best-of-batches: a single long average is hostage to scheduler
    # preemption when the whole suite runs in parallel — the minimum is
    # the recorder's actual cost
    cycle_ms = min(batch(300) for _ in range(10))
    assert cycle_ms <= 0.01 * tick_p50, (
        f"recorder cycle {cycle_ms:.4f} ms > 1% of tick p50 {tick_p50:.3f} ms"
    )
    # and a disabled recorder is a no-op that records nothing
    off = PhaseRecorder(enabled=False)
    off.begin()
    off.mark("pre_schedule")
    off.commit()
    assert off.ticks == 0 and not off.ring


def test_retrace_counter_increments_once_per_new_shape():
    """Satellite: a new shape increments the compile counter exactly
    once; a same-shape call does not."""
    import jax

    reg = m.Registry()

    @jax.jit
    def f(x):
        return x * 2

    w = instrument_jit(f, "test.retrace", service="scheduler", registry=reg)
    s = jit_series(reg, "scheduler")
    w(np.zeros((2, 3), np.float32))
    assert s.retraces.value("test.retrace") == 1
    w(np.ones((2, 3), np.float32))  # same signature: no increment
    assert s.retraces.value("test.retrace") == 1
    w(np.zeros((5, 3), np.float32))  # new shape: exactly one increment
    assert s.retraces.value("test.retrace") == 2
    w(np.zeros((5, 3), np.float32))
    assert s.retraces.value("test.retrace") == 2
    w(np.zeros((2, 3), np.float64))  # new dtype is a new signature too
    assert s.retraces.value("test.retrace") == 3
    assert s.calls.value("test.retrace") == 5
    # the gauge prefers jit's OWN cache size; without x64 the float64
    # input downcasts, so jax may fold it into the float32 entry
    assert 2 <= s.cache_entries.value("test.retrace") <= 3
    # dispatch/device time split is populated per call
    text = reg.expose()
    assert 'dragonfly_scheduler_jit_dispatch_seconds_count{fn="test.retrace"} 5' in text
    assert 'dragonfly_scheduler_jit_device_seconds_count{fn="test.retrace"} 5' in text


def test_trace_context_round_trips_through_wire_framing():
    """Satellite: a span opened scheduler-side keeps its trace_id and
    yields the correct parent_id after a wire round trip, including the
    error/record_exception path."""
    from dragonfly2_tpu.rpc import wire

    wire.register_module(msg)
    tracer = Tracer("scheduler")
    spans = tracer.export_to_memory()

    with tracer.span("scheduler.tick") as parent:
        frame = wire.encode(msg.StatPeerRequest(peer_id="p1"))
    decoded = wire.decode(frame[4:])
    assert decoded == msg.StatPeerRequest(peer_id="p1")  # payload untouched
    assert decoded.trace_context == {
        "trace_id": parent.trace_id, "span_id": parent.span_id,
    }

    with pytest.raises(RuntimeError):
        with tracer.span(
            "scheduler.rpc.StatPeerRequest", remote_parent=decoded.trace_context
        ):
            raise RuntimeError("boom")
    child = next(s for s in spans if s.name == "scheduler.rpc.StatPeerRequest")
    assert child.trace_id == parent.trace_id
    assert child.parent_id == parent.span_id
    assert child.status == "ERROR"
    assert child.events[0]["type"] == "RuntimeError"

    # no ambient span -> the envelope carries no context at all
    bare = wire.decode(wire.encode(msg.StatPeerRequest(peer_id="p2"))[4:])
    assert not hasattr(bare, "trace_context")

    # explicit context (the tick->response path) wins over the ambient one
    with tracer.span("other"):
        framed = wire.encode(
            msg.StatPeerRequest(peer_id="p3"),
            trace_context={"trace_id": "a" * 32, "span_id": "b" * 16},
        )
    assert wire.decode(framed[4:]).trace_context["trace_id"] == "a" * 32


def test_metric_naming_convention_registry_walk():
    """Satellite CI sweep: every registered family matches the
    dragonfly_<service>_ naming convention, has HELP text, and
    re-registration is idempotent (returns the existing collector)."""
    reg = m.Registry()
    scheduler_series(reg)
    daemon_series(reg)
    manager_series(reg)
    trainer_series(reg)
    jit_series(reg, "scheduler")
    jit_series(reg, "trainer")
    # perf-observatory + lab families ride the same sweep: cost cards,
    # soak timelines, serving activation gate, megascale engine, and the
    # decision provenance ledger (dragonfly_scheduler_decision_*)
    costcard_series(reg)
    timeline_series(reg)
    serving_series(reg)
    megascale_series(reg)
    decision_series(reg)
    # the SLO verdict plane (dragonfly_slo_*: budget remaining, burn
    # rates, alert state/fire transitions, SLI events, verdict)
    slo_series(reg)
    # the tail-attribution plane (dragonfly_tail_*: completions,
    # dominant-phase counts, TTC quantiles, phase shares, exemplars)
    tail_series(reg)
    # the sharded control plane (dragonfly_fleet_*: cross-scheduler peer
    # handoffs by reason, per-shard pieces, replica restarts, ring size)
    fleet_series(reg)
    # the real-process supervision plane (dragonfly_proc_*: live process
    # census, restarts, stop escalations, liveness failures, chaos ops,
    # and the sim-vs-real divergence gauges)
    proc_series(reg)
    for family in ("dragonfly_proc_processes",
                   "dragonfly_proc_restarts_total",
                   "dragonfly_proc_stop_escalations_total",
                   "dragonfly_proc_liveness_failures_total",
                   "dragonfly_proc_chaos_ops_total",
                   "dragonfly_proc_sim_real_divergence"):
        assert family in reg._metrics, f"{family} missing from the sweep"
    for family in ("dragonfly_fleet_peer_handoffs_total",
                   "dragonfly_fleet_shard_pieces_total",
                   "dragonfly_fleet_shard_restarts_total",
                   "dragonfly_fleet_shards_in_ring"):
        assert family in reg._metrics, f"{family} missing from the sweep"
    for family in ("dragonfly_tail_completions_total",
                   "dragonfly_tail_dominant_total",
                   "dragonfly_tail_ttc_ms",
                   "dragonfly_tail_phase_share",
                   "dragonfly_tail_exemplars_kept"):
        assert family in reg._metrics, f"{family} missing from the sweep"
    assert any(
        name.startswith("dragonfly_scheduler_decision_")
        for name in reg._metrics
    ), "decision ledger families missing from the sweep"
    for family in ("dragonfly_slo_budget_remaining", "dragonfly_slo_burn_rate",
                   "dragonfly_slo_alert_state",
                   "dragonfly_slo_alerts_fired_total",
                   "dragonfly_slo_verdict_state",
                   "dragonfly_slo_sli_events_total"):
        assert family in reg._metrics, f"{family} missing from the sweep"
    for svc in ("scheduler", "dfdaemon", "manager", "trainer"):
        register_version(reg, svc)
        resilience_series(reg, svc)  # breaker-state + deadline families
    # "client" metrics live under the reference's service name, dfdaemon
    pattern = re.compile(
        r"^dragonfly_(scheduler|dfdaemon|manager|trainer|costcard|timeline"
        r"|serving|megascale|slo|tail|fleet|proc)_[a-z0-9_]+$"
    )
    assert reg._metrics, "registry walk found nothing"
    for name, metric in reg._metrics.items():
        assert pattern.match(name), f"{name} violates the naming convention"
        assert metric.help.strip(), f"{name} has no HELP text"
    # idempotent: the factory hands back the SAME collector object
    assert scheduler_series(reg).announce_peer is scheduler_series(reg).announce_peer
    first = reg._metrics["dragonfly_scheduler_announce_peer_total"]
    again = reg.counter(
        "dragonfly_scheduler_announce_peer_total", "stream messages", ("type",)
    )
    assert again is first
    # each family appears exactly once in exposition (registered once)
    text = reg.expose()
    for name in reg._metrics:
        assert text.count(f"# TYPE {name} ") == 1, name


def test_metrics_server_graceful_shutdown():
    """Satellite: shutdown() joins the serving thread and closes the
    listening socket — tests and daemons stop leaking listeners."""
    import threading

    reg = m.Registry()
    reg.counter("dragonfly_manager_flight_smoke_total", "smoke").inc()
    server = m.serve_metrics(reg, port=0)
    port = server.server_address[1]
    body = urllib.request.urlopen(f"http://127.0.0.1:{port}/metrics").read().decode()
    assert "dragonfly_manager_flight_smoke_total" in body
    assert any(t.name == "metrics-http" for t in threading.enumerate())
    server.shutdown()
    assert server.socket.fileno() == -1, "listening socket not closed"
    assert not any(t.name == "metrics-http" for t in threading.enumerate())
    with pytest.raises(OSError):
        urllib.request.urlopen(f"http://127.0.0.1:{port}/metrics", timeout=1)
    server.shutdown()  # idempotent


def test_flight_dump_sections_and_size_cap():
    """Satellite (ISSUE 13): flight.dump has grown costcards + timelines
    + decisions — section selection and a HARD byte cap with a
    truncation marker bound the /debug/flight payload."""
    import json

    from dragonfly2_tpu.telemetry import flight

    reg = m.Registry()
    svc = _seeded_service(reg)
    for i in range(32):
        _register(svc, f"fl-cap-{i}", _host(i + 1))
        svc.tick()
    # section selection: only the asked-for sections ride
    only_ticks = flight.dump(recorder=svc.recorder, sections=("ticks",))
    assert "ticks" in only_ticks and "jit" not in only_ticks
    assert "decisions" not in only_ticks and "costcards" not in only_ticks
    full = flight.dump(recorder=svc.recorder, max_bytes=None)
    assert "decisions" in full, "decision ledger missing from the dump"
    led_dump = full["decisions"].get("scheduler.decisions")
    assert led_dump and led_dump["rows"], "no decision rows in the dump"
    full_size = len(json.dumps(full, separators=(",", ":"), default=str))
    assert full_size > 4096, "fixture dump too small to exercise the cap"
    # the cap is HARD: the body fits and carries the truncation marker
    capped = flight.dump(recorder=svc.recorder, max_bytes=4096)
    capped_size = len(json.dumps(capped, separators=(",", ":"), default=str))
    assert capped_size <= 4096, capped_size
    assert capped["truncated"]["max_bytes"] == 4096
    assert capped["truncated"]["dropped"], "marker records nothing dropped"
    # scalar sections survive truncation; a generous cap truncates nothing
    assert "jit" in capped
    roomy = flight.dump(recorder=svc.recorder, max_bytes=64 << 20)
    assert "truncated" not in roomy
    # last_n=0 is "no entries", not the [-0:] everything-slice
    zero = flight.dump(recorder=svc.recorder, last_n=0, max_bytes=None)
    assert zero["ticks"]["last"] == []
    assert all(led["rows"] == [] for led in zero["decisions"].values())
    # query-param parsing shared by the mux/monitor routes
    kwargs = flight.parse_flight_query("last_n=4&section=ticks,jit&max_bytes=5000")
    assert kwargs == {"last_n": 4, "sections": ("ticks", "jit"),
                      "max_bytes": 5000}
    with pytest.raises(ValueError):
        flight.parse_flight_query("last_n=banana")
    with pytest.raises(ValueError):
        flight.parse_flight_query("section=nope")


def test_mux_flight_route_honours_query_params():
    """/debug/flight?last_n=&section= reaches the default dump source;
    bad input answers 400, explicit zero-arg sources keep working."""
    import asyncio

    from dragonfly2_tpu.rpc.mux import MuxServer

    reg = m.Registry()
    svc = _seeded_service(reg)
    for i in range(4):
        _register(svc, f"fl-mx-{i}", _host(i + 1))
        svc.tick()

    async def run():
        async def rpc_handler(reader, writer):
            writer.close()

        srv = MuxServer(rpc_handler)
        host, port = await srv.start()
        try:
            def get(path):
                return urllib.request.urlopen(
                    f"http://{host}:{port}{path}"
                ).read()

            body = json.loads(await asyncio.to_thread(
                get, "/debug/flight?last_n=2&section=ticks"
            ))
            assert "ticks" in body and "jit" not in body
            assert len(body["ticks"]["last"]) <= 2
            with pytest.raises(urllib.error.HTTPError) as e:
                await asyncio.to_thread(get, "/debug/flight?last_n=x")
            assert e.value.code == 400
        finally:
            await srv.stop()
        # an explicit flight_source without kwargs still serves untouched
        srv2 = MuxServer(rpc_handler, flight_source=lambda: {"ok": True})
        host, port = await srv2.start()
        try:
            body = json.loads(await asyncio.to_thread(
                lambda: urllib.request.urlopen(
                    f"http://{host}:{port}/debug/flight?last_n=1"
                ).read()
            ))
            assert body == {"ok": True}
        finally:
            await srv2.stop()

    asyncio.run(run())


def _slo_engine_with_page(name):
    """A live SLO engine (isolated metrics registry; weak-registered
    under `name`) with one page-severity burn alert firing."""
    from dragonfly2_tpu.telemetry.slo import SLOEngine, SLOSpec

    eng = SLOEngine(
        [SLOSpec("probe", sli="s", objective=0.999)],
        name=name, minutes_per_unit=15.0, registry=m.Registry(),
    )
    for t in range(1, 9):
        eng.observe("s", good=100)
        eng.step(t)
    eng.observe("s", good=10, bad=90)
    eng.step(9)
    assert eng.verdict()["state"] == "critical"
    return eng


def test_flight_dump_slo_section_round_trip():
    """Satellite (ISSUE 14): the `slo` section rides flight.dump behind
    the existing section/max_bytes query machinery — parse_flight_query
    round-trips it, the dump carries live engines' verdicts, and the
    byte cap sheds the alert log with the truncation marker."""
    import gc

    from dragonfly2_tpu.telemetry import flight

    kwargs = flight.parse_flight_query("section=slo&last_n=6")
    assert kwargs == {"last_n": 6, "sections": ("slo",)}
    eng = _slo_engine_with_page("test.flight-slo")
    try:
        body = flight.dump(**kwargs)
        assert "slo" in body and "ticks" not in body and "jit" not in body
        section = body["slo"]["test.flight-slo"]
        assert section["verdict"]["state"] == "critical"
        assert section["pages_fired"] == 1
        assert [e["event"] for e in section["alert_log"]].count("fired") >= 1
        # the slo alert log is ring-backed: the cap sheds it too —
        # alternating bad/clean intervals generates fire/clear pairs
        for i in range(600):
            if i % 2 == 0:
                eng.observe("s", good=10, bad=90)
            else:
                eng.observe("s", good=100)
            eng.step(eng._last_t + 1)
        capped = flight.dump(sections=("slo",), max_bytes=2048, last_n=1024)
        size = len(json.dumps(capped, separators=(",", ":"), default=str))
        assert size <= 2048, size
    finally:
        del eng
        gc.collect()


def _tail_tracer_with_rows(name, rows=48):
    """A registered TailTrace carrying deterministic observations whose
    exemplar ring has real content for the byte cap to shed."""
    from dragonfly2_tpu.telemetry import tailtrace

    tr = tailtrace.TailTrace(
        ("east", "west"), seed=3, name=name,
        sample_rate=1.0, exemplar_capacity=64, registry=m.Registry(),
    )
    for i in range(rows):
        vec = [0.0] * tailtrace.N_PHASES
        vec[tailtrace.PH_PARENT_FETCH] = 4e9 + i * 1e7
        vec[tailtrace.PH_SCHEDULE_WAIT] = 1e9
        tr.observe(i % 2, i, sum(vec), vec, round_idx=i // 8)
    return tr


def test_flight_dump_tail_section_round_trip():
    """Tentpole surface (ISSUE 16): the `tail` section rides flight.dump
    behind the existing section/max_bytes query machinery —
    parse_flight_query round-trips it, the dump carries live tracers'
    per-region decomposition + exemplars, and the byte cap sheds the
    exemplar list with the truncation marker."""
    import gc

    from dragonfly2_tpu.telemetry import flight

    kwargs = flight.parse_flight_query("section=tail&last_n=8")
    assert kwargs == {"last_n": 8, "sections": ("tail",)}
    tr = _tail_tracer_with_rows("test.flight-tail")
    try:
        body = flight.dump(**kwargs)
        assert "tail" in body and "ticks" not in body and "jit" not in body
        section = body["tail"]["test.flight-tail"]
        assert section["completions"] == 48
        assert len(section["exemplars"]) == 8  # last_n bounds the ring
        east = section["regions"]["east"]
        assert east["dominant_phase"] == "parent_fetch"
        assert east["decomp_ratio"] == 1.0
        # the exemplar ring is the section's only unbounded list: the
        # cap sheds it oldest-first and stamps the truncation marker
        capped = flight.dump(sections=("tail",), max_bytes=2048, last_n=64)
        size = len(json.dumps(capped, separators=(",", ":"), default=str))
        assert size <= 2048, size
        assert capped.get("truncated"), "cap under-shed without a marker"
    finally:
        del tr
        gc.collect()
    assert "test.flight-tail" not in flight.dump(sections=("tail",)).get(
        "tail", {}
    ), "weak registry leaked a dead tracer"


def test_mux_and_monitor_serve_debug_flight_tail_section():
    """Satellite (ISSUE 16): /debug/flight?section=tail on BOTH debug
    surfaces — the mux sniffer and the monitor server hand back the
    same tail block, honor max_bytes, and 400 on unknown sections."""
    import asyncio
    import gc

    from dragonfly2_tpu.rpc.mux import MuxServer

    tr = _tail_tracer_with_rows("test.route-tail")

    def check_surface(get):
        body = json.loads(get("/debug/flight?section=tail&last_n=4"))
        section = body["tail"]["test.route-tail"]
        assert section["regions"]["west"]["dominant_phase"] == "parent_fetch"
        assert len(section["exemplars"]) == 4
        with pytest.raises(urllib.error.HTTPError) as e:
            get("/debug/flight?section=nope")
        assert e.value.code == 400
        raw = get("/debug/flight?section=tail&max_bytes=2048&last_n=64")
        assert len(raw) <= 2048

    server = m.serve_metrics(m.Registry(), port=0)
    try:
        port = server.server_address[1]

        def get_monitor(path):
            return urllib.request.urlopen(
                f"http://127.0.0.1:{port}{path}", timeout=5
            ).read()

        check_surface(get_monitor)
    finally:
        server.shutdown()

    async def run():
        async def rpc_handler(reader, writer):
            writer.close()

        srv = MuxServer(rpc_handler)
        host, port = await srv.start()
        try:
            def get_mux(path):
                return urllib.request.urlopen(
                    f"http://{host}:{port}{path}", timeout=5
                ).read()

            await asyncio.to_thread(check_surface, get_mux)
        finally:
            await srv.stop()

    asyncio.run(run())
    del tr
    gc.collect()


def test_mux_and_monitor_serve_debug_health():
    """Satellite (ISSUE 14): /debug/health on BOTH debug surfaces —
    verdict schema, 400 on bad query params, the hard payload cap, and
    503 when a page-severity alert makes the verdict critical."""
    import asyncio
    import gc

    from dragonfly2_tpu.rpc.mux import MuxServer

    eng = _slo_engine_with_page("test.health-slo")

    def check_surface(get):
        # schema: machine-readable verdict with causes and sources
        with pytest.raises(urllib.error.HTTPError) as e:
            get("/debug/health")
        assert e.value.code == 503  # a firing page = critical = 503
        body = json.loads(e.value.read())
        assert body["state"] == "critical" and body["state_code"] == 2
        assert {"state", "state_code", "causes", "slos", "alert_log",
                "sources"} <= set(body)
        assert "test.health-slo" in body["sources"]
        cause = next(
            c for c in body["causes"] if c["source"] == "test.health-slo"
        )
        assert cause["severity"] == "page" and cause["slo"] == "probe"
        assert body["slos"]["test.health-slo"]["pages_fired"] == 1
        # 400 on bad query params (shared parse_health_query contract)
        for bad in ("last_n=banana", "max_bytes=x"):
            with pytest.raises(urllib.error.HTTPError) as e:
                get(f"/debug/health?{bad}")
            assert e.value.code == 400
        # the hard payload cap is the bytes actually shipped
        with pytest.raises(urllib.error.HTTPError) as e:
            get("/debug/health?max_bytes=1200&last_n=512")
        assert e.value.code == 503
        assert len(e.value.read()) <= 1200

    # monitor surface (telemetry/metrics.serve_metrics)
    server = m.serve_metrics(m.Registry(), port=0)
    try:
        port = server.server_address[1]

        def get_monitor(path):
            return urllib.request.urlopen(
                f"http://127.0.0.1:{port}{path}", timeout=5
            ).read()

        check_surface(get_monitor)
    finally:
        server.shutdown()

    # mux surface (rpc/mux.MuxServer HTTP sniffing)
    async def run():
        async def rpc_handler(reader, writer):
            writer.close()

        srv = MuxServer(rpc_handler)
        host, port = await srv.start()
        try:
            def get_mux(path):
                return urllib.request.urlopen(
                    f"http://{host}:{port}{path}", timeout=5
                ).read()

            await asyncio.to_thread(check_surface, get_mux)
        finally:
            await srv.stop()

    asyncio.run(run())
    del eng
    gc.collect()


def test_manager_rest_serves_flight_recorder_dump():
    """The operator route: GET /api/v1/flight-recorder (JWT-authenticated
    — it fans RPCs out to every scheduler, so anonymous callers are 401)
    aggregates the manager's own dump plus every known scheduler's
    (in-proc here; the RemoteScheduler wire edge is covered below)."""
    from dragonfly2_tpu.cluster.jobs import JobManager
    from dragonfly2_tpu.manager.rest import ManagerREST, openapi_spec
    from dragonfly2_tpu.manager.service import ManagerService

    reg = m.Registry()
    svc = _seeded_service(reg)
    _register(svc, "fl-rest-child", _host(1))
    svc.tick()
    mgr = ManagerService(jobs=JobManager({"sched-1": svc}))
    rest = ManagerREST(mgr)
    host, port = rest.start()
    base = f"http://{host}:{port}/api/v1"

    def get(path, token=None):
        req = urllib.request.Request(f"{base}{path}")
        if token:
            req.add_header("Authorization", f"Bearer {token}")
        return json.loads(urllib.request.urlopen(req).read())

    try:
        # anonymous is rejected — this route drives cluster-wide RPCs
        with pytest.raises(urllib.error.HTTPError) as e:
            get("/flight-recorder")
        assert e.value.code == 401
        token = json.loads(
            urllib.request.urlopen(
                urllib.request.Request(
                    f"{base}/users/signin",
                    data=json.dumps(
                        {"name": "root", "password": "dragonfly"}
                    ).encode(),
                    headers={"Content-Type": "application/json"},
                )
            ).read()
        )["token"]
        body = get("/flight-recorder?last_n=8", token)
        assert set(body) == {"manager", "schedulers"}
        sched = body["schedulers"]["sched-1"]
        assert sched["ticks"]["last"], "no tick breakdowns in the dump"
        assert set(TICK_PHASES) <= set(sched["ticks"]["last"][-1])
        assert "scheduler.evaluator.schedule_from_packed" in sched["jit"]
        # the manager's OWN section must not claim the co-located
        # scheduler's ring (that data lives under schedulers.sched-1),
        # and the empty shape stays indexable
        assert body["manager"]["ticks"]["last"] == []
        assert body["manager"]["ticks"]["ticks_total"] == 0
        # bad input is a 400, not a 500
        with pytest.raises(urllib.error.HTTPError) as e:
            get("/flight-recorder?last_n=x", token)
        assert e.value.code == 400
    finally:
        rest.stop()
    assert "/api/v1/flight-recorder" in openapi_spec()["paths"]


def test_mux_serves_flight_recorder_debug_route():
    """/debug/flight on the mux port defaults to the process-global dump
    and honours an explicit flight_source."""
    import asyncio

    from dragonfly2_tpu.rpc.mux import MuxServer

    async def run():
        async def rpc_handler(reader, writer):
            writer.close()

        srv = MuxServer(rpc_handler, flight_source=lambda: {"ok": True})
        host, port = await srv.start()
        try:
            body = await asyncio.to_thread(
                lambda: urllib.request.urlopen(
                    f"http://{host}:{port}/debug/flight"
                ).read()
            )
            assert json.loads(body) == {"ok": True}
        finally:
            await srv.stop()
        default = MuxServer(rpc_handler)
        host, port = await default.start()
        try:
            body = await asyncio.to_thread(
                lambda: urllib.request.urlopen(
                    f"http://{host}:{port}/debug/flight"
                ).read()
            )
            dump = json.loads(body)
            assert {"ticks", "jit", "active_spans"} <= set(dump)
        finally:
            await default.stop()

    asyncio.run(run())


def test_flight_recorder_over_the_wire_and_tick_trace_to_client(tmp_path):
    """Live RPC edge: (1) the scheduler answers FlightRecorderRequest with
    a populated dump; (2) the daemon's piece-download span continues the
    scheduler TICK's trace — same trace_id, parented on the tick span —
    proving context crosses the wire in the response direction."""
    import asyncio

    from test_minicluster import _CountingFileServer, _scheduler_service
    from dragonfly2_tpu.client.daemon import Daemon
    from dragonfly2_tpu.rpc.client import SyncSchedulerClient
    from dragonfly2_tpu.rpc.server import SchedulerRPCServer
    from dragonfly2_tpu.telemetry.tracing import default_tracer

    captured = []
    exporter = captured.append
    tracer = default_tracer()
    tracer.add_exporter(exporter)
    origin = _CountingFileServer(bytes(i % 256 for i in range(120_000)))

    async def run():
        service = _scheduler_service(tmp_path)
        server = SchedulerRPCServer(service, tick_interval=0.01)
        host, port = await server.start()
        try:
            # peer 1 back-sources (empty mesh); peer 2 then downloads FROM
            # peer 1 — the NormalTaskResponse path that carries the tick's
            # trace context down to the piece downloads
            d1 = Daemon(tmp_path / "d1", [(host, port)], hostname="fl-d1")
            await d1.start()
            await d1.download(origin.url(), piece_length=32 * 1024)
            d2 = Daemon(tmp_path / "d2", [(host, port)], hostname="fl-d2")
            await d2.start()
            await d2.download(origin.url(), piece_length=32 * 1024)
            await d2.stop()
            await d1.stop()
            client = SyncSchedulerClient(host, port)
            resp = await asyncio.to_thread(
                client.call, msg.FlightRecorderRequest(last_n=16)
            )
            client.close()
            return resp
        finally:
            await server.stop()
            origin.stop()

    try:
        resp = asyncio.run(run())
    finally:
        tracer.remove_exporter(exporter)

    assert isinstance(resp, msg.FlightRecorderResponse)
    assert resp.dump["ticks"]["last"], "wire dump has no tick breakdowns"
    assert "scheduler.evaluator.schedule_from_packed" in resp.dump["jit"]

    ticks = [s for s in captured if s.name == "scheduler.tick"]
    downloads = [s for s in captured if s.name == "dfdaemon.download_pieces"]
    assert ticks and downloads, {s.name for s in captured}
    tick_ids = {s.span_id for s in ticks}
    linked = [d for d in downloads if d.parent_id in tick_ids]
    assert linked, "no download span parented on a tick span"
    tick_by_id = {s.span_id: s for s in ticks}
    for d in linked:
        assert d.trace_id == tick_by_id[d.parent_id].trace_id
