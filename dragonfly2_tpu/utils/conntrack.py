"""Connection-task tracking for asyncio socket servers.

Python 3.12's `Server.wait_closed()` waits for every in-flight connection
handler, so a server whose client holds a long-lived stream (an announce
connection, a CONNECT/SNI tunnel) hangs shutdown forever unless the
handlers are cancelled first. Every socket server in this codebase wraps
its handler with `ConnTracker.tracked` and calls `cancel_all()` before
`wait_closed()` — one implementation instead of a copy per server."""

from __future__ import annotations

import asyncio


class ConnTracker:
    def __init__(self):
        self._conns: set[asyncio.Task] = set()

    def tracked(self, handler):
        """Wrap an `async (reader, writer)` handler so its task is
        tracked for cancel_all()."""

        async def wrapper(reader, writer):
            task = asyncio.current_task()
            self._conns.add(task)
            try:
                await handler(reader, writer)
            except asyncio.CancelledError:
                writer.close()
            finally:
                self._conns.discard(task)

        return wrapper

    async def cancel_all(self) -> None:
        for task in list(self._conns):
            task.cancel()
        await asyncio.gather(*self._conns, return_exceptions=True)
